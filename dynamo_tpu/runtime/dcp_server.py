"""Control-plane service ("DCP"): the framework's analog of etcd + NATS.

The reference runs two external infra services (docker-compose:
etcd for discovery/config/leases, NATS w/ JetStream for the request plane,
events and work queues — reference deploy/docker-compose.yml:16-31). This
framework provides the same four planes from a single lightweight asyncio
server so a deployment has one infra process (or zero — it can be embedded
in-process for tests):

- **KV store w/ leases + watches** (etcd analog — reference
  lib/runtime/src/transports/etcd.rs): ``kv_put/kv_create/kv_get_prefix/
  kv_delete``, ``lease_grant/keepalive/revoke``; keys attached to a lease are
  deleted when it expires and prefix watchers receive Put/Delete events.
- **Pub/sub** (NATS core analog — reference transports/nats.rs): subjects with
  queue groups; ``publish`` fans out to all plain subscribers and one member
  of each queue group.
- **Request/reply** (NATS request plane analog — reference
  pipeline/network/egress/push.rs): ``request`` routes to one subscriber of
  the subject's queue group and relays the single reply.
- **Work queues** (JetStream pull-queue analog — reference
  examples utils/nats_queue.py): durable-in-memory FIFO with blocking pull,
  used by the disaggregated prefill queue.

Wire protocol: 4-byte big-endian length prefix + msgpack map. Client→server
maps carry ``op`` and ``seq``; server→client maps are either responses
(``seq`` echo + ``ok``) or pushes (``push`` kind).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import signal
import time
from collections import defaultdict, deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

import msgpack

from . import wire
from .tasks import cancel_join, spawn_tracked

log = logging.getLogger("dynamo_tpu.dcp")

MAX_FRAME = 64 * 1024 * 1024


def pack_frame(msg: dict) -> bytes:
    body = msgpack.packb(msg, use_bin_type=True)
    return len(body).to_bytes(4, "big") + body


async def read_frame(reader: asyncio.StreamReader) -> dict:
    # the DCP frame-read primitive (DL011 anchor): callers bound their
    # `await read_frame(...)` or justify an idle server/demux read
    hdr = await reader.readexactly(4)  # dynalint: disable=unbounded-await
    n = int.from_bytes(hdr, "big")
    if n > MAX_FRAME:
        raise ValueError(f"frame too large: {n}")
    body = await reader.readexactly(n)  # dynalint: disable=unbounded-await
    return msgpack.unpackb(body, raw=False)


@dataclass
class _KvEntry:
    value: bytes
    lease: int = 0  # 0 = no lease
    create_rev: int = 0
    mod_rev: int = 0


@dataclass
class _Lease:
    id: int
    ttl: float
    deadline: float
    keys: Set[str] = field(default_factory=set)


@dataclass
class _Sub:
    conn: "_Conn"
    sub_id: int
    subject: str
    group: Optional[str]


@dataclass
class _Watch:
    conn: "_Conn"
    watch_id: int
    prefix: str


def subject_matches(pattern: str, subject: str) -> bool:
    """NATS-style matching: '.'-separated tokens, '*' = one token,
    trailing '>' = one-or-more tokens."""
    if pattern == subject:
        return True
    pt = pattern.split(".")
    st = subject.split(".")
    for i, p in enumerate(pt):
        if p == ">":  # matches one or more remaining tokens
            return len(st) > i
        if i >= len(st):
            return False
        if p != "*" and p != st[i]:
            return False
    return len(pt) == len(st)


class _Conn:
    """One client connection. Outbound frames go through a per-connection
    queue drained by a writer task, so a slow consumer never blocks the
    server's dispatch loop (head-of-line isolation)."""

    MAX_OUTBOUND = 65536

    __slots__ = ("server", "reader", "writer", "id", "alive", "_outq", "_wtask")

    def __init__(self, server: "DcpServer", reader, writer, conn_id: int):
        self.server = server
        self.reader = reader
        self.writer = writer
        self.id = conn_id
        self.alive = True
        self._outq: asyncio.Queue = asyncio.Queue()
        self._wtask = spawn_tracked(self._writer_loop(),
                                    name=f"dcp-conn-{conn_id}-writer")

    async def _writer_loop(self) -> None:
        try:
            while True:
                msg = await self._outq.get()
                self.writer.write(pack_frame(msg))
                # a consumer that stops reading long enough to block the
                # drain past the IO bound is dead: drop the connection
                await asyncio.wait_for(self.writer.drain(), 30.0)
        except (ConnectionError, RuntimeError, asyncio.CancelledError,
                asyncio.TimeoutError):
            self.alive = False

    async def send(self, msg: dict) -> None:
        if not self.alive:
            return
        if self._outq.qsize() > self.MAX_OUTBOUND:
            log.warning("conn %d outbound queue overflow; dropping conn", self.id)
            self.close()
            return
        self._outq.put_nowait(msg)

    def close(self) -> None:
        self.alive = False
        self._wtask.cancel()
        try:
            self.writer.close()
        except Exception:
            pass


class DcpServer:
    """The control-plane server. ``await DcpServer.start(host, port)``;
    ``port=0`` binds an ephemeral port (see ``.port``)."""

    def __init__(self) -> None:
        self._kv: Dict[str, _KvEntry] = {}
        self._rev = 0
        self._leases: Dict[int, _Lease] = {}
        self._lease_ids = itertools.count(0x1000)
        self._conn_ids = itertools.count(1)
        self._sub_ids = itertools.count(1)
        self._subs: Dict[int, _Sub] = {}  # global sub key -> sub
        self._subs_by_conn: Dict[int, Set[int]] = defaultdict(set)
        self._watches: Dict[Tuple[int, int], _Watch] = {}
        self._group_rr: Dict[Tuple[str, str], int] = defaultdict(int)
        # rid -> (requester conn, requester seq, responder conn id)
        self._pending_replies: Dict[int, Tuple[_Conn, int, int]] = {}
        self._reply_ids = itertools.count(1)
        self._conns: Dict[int, _Conn] = {}
        self._queues: Dict[str, deque] = defaultdict(deque)
        self._queue_waiters: Dict[str, deque] = defaultdict(deque)
        self._server: Optional[asyncio.AbstractServer] = None
        self._lease_task: Optional[asyncio.Task] = None
        self._journal = None  # Optional[Journal] — durability (dcp_journal.py)
        self.port: int = 0
        self.host: str = ""

    # ------------------------------------------------------------- lifecycle

    @classmethod
    async def start(cls, host: str = "127.0.0.1", port: int = 0,
                    journal_path: Optional[str] = None) -> "DcpServer":
        self = cls()
        if journal_path:
            from .dcp_journal import Journal

            self._journal = Journal(journal_path)
            rev, kv, queues = self._journal.recover()
            self._rev = rev
            for k, (v, cr, mr) in kv.items():
                self._kv[k] = _KvEntry(value=v, create_rev=cr, mod_rev=mr)
            for name, items in queues.items():
                self._queues[name] = items
            self._journal.open()
        self._server = await asyncio.start_server(self._on_conn, host, port)
        sock = self._server.sockets[0]
        self.host, self.port = sock.getsockname()[:2]
        self._lease_task = spawn_tracked(self._lease_reaper(),
                                         name="dcp-lease-reaper")
        log.info("dcp server listening on %s:%d", self.host, self.port)
        return self

    def _durable_kv(self) -> Dict[str, Tuple[bytes, int, int]]:
        """Unleased entries only — leased keys are ephemeral by design
        (see dcp_journal.py module docstring)."""
        return {k: (e.value, e.create_rev, e.mod_rev)
                for k, e in self._kv.items() if not e.lease}

    def _journal_compact_check(self) -> None:
        # size-gate BEFORE materializing the snapshot dict: _durable_kv()
        # is O(total keys) and this runs on every journaled mutation
        j = self._journal
        if j is not None and j.log_size >= j.max_log_bytes:
            j.snapshot(self._rev, self._durable_kv(), self._queues)

    async def stop(self) -> None:
        await cancel_join(self._lease_task)
        if self._server:
            self._server.close()
        # close live connections so wait_closed() (which waits for all
        # connection handlers on Python 3.12+) cannot hang
        for conn in list(self._conns.values()):
            conn.close()
        if self._server:
            try:
                await asyncio.wait_for(self._server.wait_closed(), 5.0)
            except asyncio.TimeoutError:
                log.warning("dcp server wait_closed timed out")
        if self._journal is not None:
            # graceful exit: compact so restart recovery is snapshot-only
            self._journal.snapshot(self._rev, self._durable_kv(),
                                   self._queues)
            self._journal.close()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    # ------------------------------------------------------------- conn loop

    # ops that may block (waiting) run as tasks so they never stall the
    # connection's dispatch loop; everything else is quick and runs inline
    _BLOCKING_OPS = frozenset({"q_pull"})

    async def _on_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        conn = _Conn(self, reader, writer, next(self._conn_ids))
        self._conns[conn.id] = conn
        try:
            while True:
                # idle server read: a control-plane client is allowed to
                # sit quiet; conn close / lease expiry bound the session
                msg = await read_frame(reader)  # dynalint: disable=unbounded-await
                if msg.get("op") in self._BLOCKING_OPS:
                    spawn_tracked(self._dispatch(conn, msg),
                                  name=f"dcp-op-{msg.get('op')}")
                else:
                    await self._dispatch(conn, msg)
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        except Exception:
            log.exception("dcp conn %d error", conn.id)
        finally:
            conn.close()
            self._conns.pop(conn.id, None)
            await self._cleanup_conn(conn)

    async def _cleanup_conn(self, conn: _Conn) -> None:
        for sid in list(self._subs_by_conn.pop(conn.id, ())):
            self._subs.pop(sid, None)
        for key in [k for k in self._watches if k[0] == conn.id]:
            self._watches.pop(key, None)
        # queue waiters owned by this conn just get dropped; items stay queued
        for q in self._queue_waiters.values():
            for c, fut in list(q):
                if c is conn and not fut.done():
                    fut.cancel()
        # fail in-flight requests this conn was the responder for, and drop
        # entries whose requester is gone
        for rid, (requester, seq, responder_id) in list(self._pending_replies.items()):
            if responder_id == conn.id:
                self._pending_replies.pop(rid, None)
                await requester.send(
                    {"seq": seq, "ok": False, "error": "responder disconnected"})
            elif requester is conn:
                self._pending_replies.pop(rid, None)

    async def _dispatch(self, conn: _Conn, msg: dict) -> None:
        op = msg.get("op")
        seq = msg.get("seq")
        try:
            handler = getattr(self, f"_op_{op}", None)
            if handler is None:
                await conn.send({"seq": seq, "ok": False, "error": f"unknown op {op}"})
                return
            resp = await handler(conn, msg)
            if resp is not None:
                resp["seq"] = seq
                resp.setdefault("ok", True)
                await conn.send(resp)
        except Exception as e:  # noqa: BLE001 — report errors to client
            log.exception("dcp op %s failed", op)
            await conn.send({"seq": seq, "ok": False, "error": repr(e)})

    # ------------------------------------------------------------- KV + lease

    def _notify_watchers(self, event: str, key: str, value: Optional[bytes]) -> None:
        for w in list(self._watches.values()):
            if key.startswith(w.prefix):
                spawn_tracked(
                    w.conn.send(wire.checked(wire.DCP_PUSH_WATCH, {
                        "push": "watch", "watch_id": w.watch_id,
                        "event": event, "key": key, "value": value})),
                    name="dcp-watch-notify")

    async def _op_kv_put(self, conn, msg):
        key, value, lease = msg["key"], msg["value"], msg.get("lease", 0)
        if lease and lease not in self._leases:
            return {"ok": False, "error": f"no such lease {lease}"}
        prev = self._kv.get(key)
        # compare-and-swap (reference etcd.rs txn: mod_revision guard):
        # prev_rev=0 means "must not exist"
        prev_rev = msg.get("prev_rev")
        if prev_rev is not None:
            have = prev.mod_rev if prev is not None else 0
            if have != prev_rev:
                return {"ok": False, "error": "cas conflict",
                        "conflict": True, "mod_rev": have}
        self._rev += 1
        entry = _KvEntry(
            value=value, lease=lease,
            create_rev=prev.create_rev if prev else self._rev, mod_rev=self._rev)
        self._kv[key] = entry
        if lease:
            self._leases[lease].keys.add(key)
        if self._journal is not None:
            if not lease:
                self._journal.record_put(key, value, entry.create_rev,
                                         entry.mod_rev)
            else:
                # leased puts still bump _rev; persist the counter so a
                # recovered server can't re-issue a pre-crash mod_rev
                # (stale CAS tokens must keep failing after restart)
                self._journal.record_rev(self._rev)
                if prev is not None and not prev.lease:
                    # a leased write over a durable key: the durable value
                    # is gone; without this it would resurrect on replay
                    self._journal.record_delete(key)
            self._journal_compact_check()
        self._notify_watchers("put", key, value)
        return {"rev": self._rev}

    async def _op_kv_create(self, conn, msg):
        """Transactional create-if-absent (reference etcd.rs kv_create)."""
        if msg["key"] in self._kv:
            return {"ok": False, "error": "exists", "exists": True}
        return await self._op_kv_put(conn, msg)

    async def _op_kv_get(self, conn, msg):
        e = self._kv.get(msg["key"])
        if e is None:
            return {"found": False}
        return {"found": True, "value": e.value, "lease": e.lease,
                "mod_rev": e.mod_rev}

    async def _op_kv_get_prefix(self, conn, msg):
        p = msg["prefix"]
        items = [
            {"key": k, "value": e.value, "lease": e.lease,
             "mod_rev": e.mod_rev}
            for k, e in sorted(self._kv.items()) if k.startswith(p)
        ]
        return {"items": items}

    async def _op_kv_delete(self, conn, msg):
        key = msg["key"]
        e = self._kv.pop(key, None)
        if e is not None:
            if e.lease in self._leases:
                self._leases[e.lease].keys.discard(key)
            if self._journal is not None and not e.lease:
                self._journal.record_delete(key)
                self._journal_compact_check()
            self._notify_watchers("delete", key, None)
        return {"deleted": e is not None}

    async def _op_kv_delete_prefix(self, conn, msg):
        p = msg["prefix"]
        keys = [k for k in self._kv if k.startswith(p)]
        for k in keys:
            e = self._kv.pop(k)
            if e.lease in self._leases:
                self._leases[e.lease].keys.discard(k)
            if self._journal is not None and not e.lease:
                self._journal.record_delete(k)
            self._notify_watchers("delete", k, None)
        self._journal_compact_check()
        return {"deleted": len(keys)}

    async def _op_watch_prefix(self, conn, msg):
        w = _Watch(conn, msg["watch_id"], msg["prefix"])
        self._watches[(conn.id, w.watch_id)] = w
        items = [
            {"key": k, "value": e.value, "lease": e.lease,
             "mod_rev": e.mod_rev}
            for k, e in sorted(self._kv.items()) if k.startswith(w.prefix)
        ]
        return {"items": items}

    async def _op_unwatch(self, conn, msg):
        self._watches.pop((conn.id, msg["watch_id"]), None)
        return {}

    async def _op_lease_grant(self, conn, msg):
        ttl = float(msg.get("ttl", 10.0))
        lid = next(self._lease_ids)
        self._leases[lid] = _Lease(id=lid, ttl=ttl, deadline=time.monotonic() + ttl)
        return {"lease": lid}

    async def _op_lease_keepalive(self, conn, msg):
        lease = self._leases.get(msg["lease"])
        if lease is None:
            return {"ok": False, "error": "lease expired"}
        lease.deadline = time.monotonic() + lease.ttl
        return {}

    async def _op_lease_revoke(self, conn, msg):
        await self._expire_lease(msg["lease"])
        return {}

    async def _expire_lease(self, lid: int) -> None:
        lease = self._leases.pop(lid, None)
        if lease is None:
            return
        for key in list(lease.keys):
            if key in self._kv and self._kv[key].lease == lid:
                self._kv.pop(key)
                self._notify_watchers("delete", key, None)

    async def _lease_reaper(self) -> None:
        last = time.monotonic()
        while True:
            await asyncio.sleep(0.25)
            now = time.monotonic()
            gap = now - last
            last = now
            if gap > 1.0:
                # the event loop (and so this server) just resumed from a
                # stall: keep-alive renewals may still be queued in socket
                # buffers or mid-reconnect — judging deadlines NOW would
                # expire leases whose owners renewed on time. Skip one
                # tick so pending renewals land first.
                log.info("lease reaper resumed after %.1fs stall; "
                         "deferring one tick", gap)
                continue
            for lid in [l.id for l in self._leases.values() if l.deadline < now]:
                log.info("lease %x expired", lid)
                await self._expire_lease(lid)

    # --------------------------------------------------------------- pub/sub

    async def _op_sub(self, conn, msg):
        sid = next(self._sub_ids)
        sub = _Sub(conn, sid, msg["subject"], msg.get("group"))
        self._subs[sid] = sub
        self._subs_by_conn[conn.id].add(sid)
        return {"sid": sid}

    async def _op_unsub(self, conn, msg):
        # client refers to its own sub_id; resolve via its conn
        for sid in list(self._subs_by_conn.get(conn.id, ())):
            s = self._subs.get(sid)
            if s and s.sub_id == msg["sid"]:
                self._subs.pop(sid, None)
                self._subs_by_conn[conn.id].discard(sid)
        return {}

    def _route(self, subject: str) -> List[_Sub]:
        """All plain subscribers + one per queue group (round-robin)."""
        plain: List[_Sub] = []
        groups: Dict[str, List[_Sub]] = defaultdict(list)
        for s in self._subs.values():
            if not s.conn.alive or not subject_matches(s.subject, subject):
                continue
            if s.group:
                groups[s.group].append(s)
            else:
                plain.append(s)
        out = plain
        for gname, members in groups.items():
            members.sort(key=lambda s: s.sub_id)
            idx = self._group_rr[(subject, gname)] % len(members)
            self._group_rr[(subject, gname)] += 1
            out.append(members[idx])
        return out

    async def _op_pub(self, conn, msg):
        subject, payload = msg["subject"], msg["payload"]
        for s in self._route(subject):
            await s.conn.send(wire.checked(wire.DCP_PUSH_MSG, {
                "push": "msg", "sid": s.sub_id, "subject": subject,
                "payload": payload}))
        return {}

    def _route_request(self, subject: str) -> Optional[_Sub]:
        """Pick exactly one queue-group member for a request (plain
        subscribers observe via pub/sub but never consume requests)."""
        groups: Dict[str, List[_Sub]] = defaultdict(list)
        for s in self._subs.values():
            if s.group and s.conn.alive and subject_matches(s.subject, subject):
                groups[s.group].append(s)
        if not groups:
            return None
        gname = sorted(groups)[0]
        members = sorted(groups[gname], key=lambda s: s.sub_id)
        idx = self._group_rr[(subject, gname)] % len(members)
        self._group_rr[(subject, gname)] += 1
        return members[idx]

    async def _op_req(self, conn, msg):
        """Request plane: route to one queue-group member, relay one reply."""
        subject, payload = msg["subject"], msg["payload"]
        target = self._route_request(subject)
        if target is None:
            return {"ok": False, "error": f"no responders for {subject}"}
        rid = next(self._reply_ids)
        self._pending_replies[rid] = (conn, msg["seq"], target.conn.id)
        await target.conn.send(wire.checked(wire.DCP_PUSH_REQ, {
            "push": "req", "sid": target.sub_id, "subject": subject,
            "payload": payload, "reply": rid}))
        return None  # response sent when the reply comes back

    async def _op_reply(self, conn, msg):
        rid = msg["reply"]
        entry = self._pending_replies.pop(rid, None)
        if entry is not None:
            requester, seq, _responder = entry
            await requester.send(
                {"seq": seq, "ok": msg.get("ok", True), "payload": msg.get("payload"),
                 "error": msg.get("error")})
        return {}

    # ------------------------------------------------------------ work queues

    async def _op_q_put(self, conn, msg):
        qname, payload = msg["queue"], msg["payload"]
        waiters = self._queue_waiters[qname]
        while waiters:
            _c, fut = waiters.popleft()
            if not fut.done():
                # direct handoff to a blocked puller: the item never
                # enters the queue, so there is nothing to journal
                fut.set_result(payload)
                return {"queued": 0}
        self._queues[qname].append(payload)
        if self._journal is not None:
            self._journal.record_qput(qname, payload)
            self._journal_compact_check()
        return {"queued": len(self._queues[qname])}

    async def _op_q_pull(self, conn, msg):
        qname = msg["queue"]
        timeout = msg.get("timeout_ms", 0) / 1000.0
        q = self._queues[qname]
        if q:
            payload = q.popleft()
            if self._journal is not None:
                self._journal.record_qpop(qname)
                self._journal_compact_check()
            return {"found": True, "payload": payload}
        if timeout <= 0:
            return {"found": False}
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._queue_waiters[qname].append((conn, fut))
        try:
            payload = await asyncio.wait_for(fut, timeout)
            return {"found": True, "payload": payload}
        except (asyncio.TimeoutError, asyncio.CancelledError):
            return {"found": False}

    async def _op_q_len(self, conn, msg):
        return {"len": len(self._queues[msg["queue"]])}

    async def _op_ping(self, conn, msg):
        return {"pong": True, "time": time.time()}


async def _amain(host: str, port: int,
                 journal: Optional[str] = None) -> None:
    server = await DcpServer.start(host, port, journal_path=journal)
    print(f"dcp listening on {server.address}", flush=True)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        # graceful shutdown writes the compaction snapshot; SIGKILL is
        # the crash path the journal replay covers
        loop.add_signal_handler(sig, stop.set)
    try:
        await stop.wait()
    finally:
        await server.stop()


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description="dynamo-tpu control-plane service")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=6650)
    ap.add_argument("--journal", default=None, metavar="PATH",
                    help="durability journal path prefix (creates "
                         "PATH.snap + PATH.log); omit for in-memory only")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    try:
        asyncio.run(_amain(args.host, args.port, args.journal))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    main()
