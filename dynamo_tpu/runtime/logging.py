"""Structured logging init (reference lib/runtime/src/logging.rs:16-100):
env-driven level filter (``DYN_LOG``), optional JSONL mode
(``DYN_LOGGING_JSONL``) for machine-ingestible logs. Every record is
stamped with the current request id (dyntrace contextvar) so JSONL logs
are joinable with traces and client-side X-Request-Id records."""

from __future__ import annotations

import json
import logging
import sys
import time

from . import tracing
from .config import env_bool, env_str


class RequestIdFilter(logging.Filter):
    """Stamps ``record.request_id`` from the ambient request context —
    bound by the HTTP frontend, endpoint handlers and the prefill worker
    — independent of trace sampling (log joins work at sample=0)."""

    def filter(self, record: logging.LogRecord) -> bool:
        record.request_id = tracing.current_request_id() or ""
        return True


class JsonlFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "time": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(record.created)),
            "level": record.levelname,
            "target": record.name,
            "message": record.getMessage(),
        }
        rid = getattr(record, "request_id", "")
        if rid:
            out["request_id"] = rid
        if record.exc_info:
            out["exception"] = self.formatException(record.exc_info)
        return json.dumps(out)


class TextFormatter(logging.Formatter):
    """Default human format, with ``[rid]`` appended when a request id is
    bound (kept out of the format string so records without the filter —
    e.g. other libraries' handlers — still render)."""

    def format(self, record: logging.LogRecord) -> str:
        base = super().format(record)
        rid = getattr(record, "request_id", "")
        return f"{base} [{rid}]" if rid else base


_initialized = False


def init(level: str | None = None, jsonl: bool | None = None) -> None:
    global _initialized
    if _initialized:
        return
    _initialized = True
    level = (level or env_str("DYN_LOG")).upper()
    if jsonl is None:
        jsonl = env_bool("DYN_LOGGING_JSONL")
    handler = logging.StreamHandler(sys.stderr)
    handler.addFilter(RequestIdFilter())
    if jsonl:
        handler.setFormatter(JsonlFormatter())
    else:
        handler.setFormatter(TextFormatter(
            "%(asctime)s %(levelname).1s %(name)s: %(message)s", "%H:%M:%S"))
    root = logging.getLogger()
    root.addHandler(handler)
    try:
        root.setLevel(level)
    except ValueError:
        root.setLevel(logging.INFO)
