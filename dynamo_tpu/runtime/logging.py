"""Structured logging init (reference lib/runtime/src/logging.rs:16-100):
env-driven level filter (``DYN_LOG``), optional JSONL mode
(``DYN_LOGGING_JSONL``) for machine-ingestible logs."""

from __future__ import annotations

import json
import logging
import sys
import time

from .config import env_bool, env_str


class JsonlFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "time": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(record.created)),
            "level": record.levelname,
            "target": record.name,
            "message": record.getMessage(),
        }
        if record.exc_info:
            out["exception"] = self.formatException(record.exc_info)
        return json.dumps(out)


_initialized = False


def init(level: str | None = None, jsonl: bool | None = None) -> None:
    global _initialized
    if _initialized:
        return
    _initialized = True
    level = (level or env_str("DYN_LOG")).upper()
    if jsonl is None:
        jsonl = env_bool("DYN_LOGGING_JSONL")
    handler = logging.StreamHandler(sys.stderr)
    if jsonl:
        handler.setFormatter(JsonlFormatter())
    else:
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname).1s %(name)s: %(message)s", "%H:%M:%S"))
    root = logging.getLogger()
    root.addHandler(handler)
    try:
        root.setLevel(level)
    except ValueError:
        root.setLevel(logging.INFO)
