"""Layered runtime configuration + the environment-variable registry.

Reference lib/runtime/src/config.rs: figment-layered settings from env
(``DYN_WORKER_*`` / ``DYN_RUNTIME_*``) + optional TOML. Here: env
(``DYN_*``) + optional YAML/JSON file named by ``DYN_CONFIG_PATH``.

This module is also the single place in the tree allowed to touch
``os.environ`` (enforced by dynalint rule ``untracked-env-read``): every
knob the fleet reads is declared in :data:`ENV_REGISTRY` with a default,
an owning component, and a description, and read through the typed
``env_*`` helpers. ``docs/env_vars.md`` is generated from the registry
(``python -m tools.dynalint --write-env-docs docs/env_vars.md``) and
tier-1 asserts it stays in sync — an undeclared knob fails the build.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, fields
from typing import Dict, Optional


@dataclass(frozen=True)
class EnvVar:
    """One registered environment knob (name, documented default, owning
    component, human description)."""

    name: str
    default: Optional[str]
    component: str
    description: str


ENV_REGISTRY: Dict[str, EnvVar] = {}


def register_env(name: str, default: Optional[str], component: str,
                 description: str) -> str:
    ENV_REGISTRY[name] = EnvVar(name, default, component, description)
    return name


# ------------------------------------------------------------- the registry
# Keep alphabetical within each component block; docs/env_vars.md renders
# straight from this table.

register_env("DYN_BLACKBOX_COOLDOWN_S", "60", "runtime",
             "dynablack incident flight recorder: debounce (seconds) "
             "between persisted captures — a trigger storm (breaker "
             "flapping, repeated stalls) produces one bundle per "
             "cooldown window, not one per event. Manual captures "
             "inside the window answer 409 with Retry-After.")
register_env("DYN_BLACKBOX_DIR", None, "runtime",
             "dynablack: directory incident bundles are persisted into "
             "(one incident-<id>.json per capture). Unset = bundles are "
             "kept in the bounded in-memory incident table only "
             "(GET /debug/incidents).")
register_env("DYN_BLACKBOX_TRIGGERS", "all", "runtime",
             "dynablack: comma-separated trigger allowlist out of "
             "slo_burn_rate,breaker_open,post_warmup_compile,"
             "watchdog_stall,failover_resume,deadline_storm,manual — "
             "'all' (default) arms every trigger; 'manual' keeps only "
             "POST /debug/incidents/capture.")
register_env("DYN_BLACKBOX_WINDOW_S", "30", "runtime",
             "dynablack: how many seconds of shadow-ring telemetry an "
             "incident bundle folds in (trace spans, step-timeline "
             "events and shadow-ring entries older than the window are "
             "dropped at capture time). 0 disables the flight recorder "
             "entirely — no shadow rings, no triggers, no captures "
             "(the hot-path A/B control arm).")
register_env("DYN_BREAKER_PROBE_EVERY", "5", "runtime",
             "Circuit breakers: an OPEN breaker offers a single half-open "
             "probe every Nth denied call (deterministic cadence; works "
             "on stepped virtual time).")
register_env("DYN_BREAKER_RESET_S", "0", "runtime",
             "Circuit breakers: additionally offer the half-open probe "
             "once this many seconds have passed since opening "
             "(0 = count-based cadence only).")
register_env("DYN_BREAKER_THRESHOLD", "3", "runtime",
             "Circuit breakers: consecutive failures that flip an "
             "endpoint's breaker closed→open.")
register_env("DYN_CHAOS", None, "runtime",
             "Chaos-injection scenario for the real transports, e.g. "
             "'seed=42;sever:kv.send@after=1;delay:tcp.send@ms=50,p=0.2' "
             "(grammar in docs/robustness.md). Unset = no chaos.")
register_env("DYN_CONFIG_PATH", None, "runtime",
             "Path to a YAML/JSON RuntimeConfig overlay file.")
register_env("DYN_DRAIN_TIMEOUT_MS", "10000", "runtime",
             "dynarevive graceful drain: bound (ms) on finishing "
             "in-flight sequences after a worker receives SIGTERM or "
             "POST /drain — discovery record deleted first (no new "
             "admissions), KV events flushed, then the lease releases. "
             "On expiry leftover requests are killed.")
register_env("DYN_DCP_ADDRESS", None, "runtime",
             "host:port of the DCP control plane. Unset: workers embed an "
             "in-process server; CLIs fall back to 127.0.0.1:6650.")
register_env("DYN_LEASE_TTL", "10.0", "runtime",
             "Primary-lease TTL in seconds (worker liveness).")
register_env("DYN_IO_TIMEOUT", "30.0", "runtime",
             "Bound (seconds) on single network IO steps: connects, "
             "handshakes, socket-buffer drains. A dead peer fails a hop "
             "in this long instead of wedging it forever.")
register_env("DYN_LOG", "INFO", "runtime",
             "Root log level (DEBUG/INFO/WARNING/...).")
register_env("DYN_LOGGING_JSONL", "0", "runtime",
             "Emit JSONL structured logs instead of text (1/true).")
register_env("DYN_PROF_ATTR_RING", "2048", "runtime",
             "dynaprof: per-request cost-attribution ring capacity "
             "(finished-request attribution dicts kept per process for "
             "/v1/traces/{request_id} and the usage extension block).")
register_env("DYN_PROF_LOOP_INTERVAL_MS", "100", "runtime",
             "dynaprof: event-loop lag-monitor sampling interval in ms "
             "(the sleep whose wakeup drift is measured).")
register_env("DYN_PROF_STACKS", "256", "runtime",
             "dynaprof: max distinct folded stacks the stall watchdog "
             "keeps (new shapes past the cap are counted as dropped).")
register_env("DYN_PROF_STALL_MS", "250", "runtime",
             "dynaprof: loop-callback overrun (ms) past which the stall "
             "watchdog captures the event-loop thread's Python stack "
             "into the flamegraph ring; 0 disables the watchdog thread.")
register_env("DYN_PROTO_VALIDATE", "0", "runtime",
             "Debug mode: validate every proto.step(...) lifecycle "
             "anchor against the runtime/proto.py protocol registry at "
             "transition time (1/true). Default off — the static "
             "dynaproto pass (DL019/DL020) and the model checker are "
             "the production gates.")
register_env("DYN_REQUEST_DEADLINE_MS", "0", "runtime",
             "Default end-to-end request deadline in milliseconds, "
             "applied at the HTTP frontend when the request carries "
             "neither a `timeout` body field nor an X-Request-Deadline-Ms "
             "header. 0 = no implicit deadline.")
register_env("DYN_REQUEST_TIMEOUT", "60.0", "runtime",
             "Default request-plane timeout in seconds.")
register_env("DYN_REVIVE_JOURNAL_TOKENS", "4096", "runtime",
             "dynarevive failover: per-request bound on journaled "
             "emitted tokens (the resume prompt is prompt + journal, so "
             "past this bound the request is marked non-resumable "
             "rather than resumed with a truncated prompt).")
register_env("DYN_REVIVE_MAX", "2", "runtime",
             "dynarevive failover: max mid-stream re-dispatches per "
             "request after an upstream worker dies before its finish "
             "chunk (0 disables failover; the stream errors like "
             "pre-revive).")
register_env("DYN_REVIVE_RING", "2048", "runtime",
             "dynarevive failover: max concurrent journal entries kept "
             "per process (one per in-flight request; eviction only "
             "costs the evicted request its resumability).")
register_env("DYN_RETRY_BASE_MS", "50", "runtime",
             "RetryPolicy: decorrelated-jitter backoff base in ms.")
register_env("DYN_RETRY_CAP_MS", "2000", "runtime",
             "RetryPolicy: backoff ceiling in ms.")
register_env("DYN_RETRY_MAX_ATTEMPTS", "3", "runtime",
             "RetryPolicy: total attempts (first try included) for route "
             "resolution, remote-prefill dispatch, and stats scrapes. "
             "Retries never run past the request deadline.")
register_env("DYN_SHED_KV_FREE_BLOCKS", "0", "runtime",
             "dynarevive admission control: shed (early 503) when the "
             "worst worker's free KV blocks drop to/below this floor. "
             "0 disables the signal.")
register_env("DYN_SHED_LOOP_LAG_MS", "0", "runtime",
             "dynarevive admission control: shed when the worst "
             "worker's event-loop lag p99 exceeds this many ms. "
             "0 disables the signal.")
register_env("DYN_SHED_QUEUE_DEPTH", "0", "runtime",
             "dynarevive admission control: shed when the summed "
             "admission-queue depth exceeds this many waiting requests "
             "PER live worker. 0 disables the signal (the default "
             "frontend sheds on nothing until configured).")
register_env("DYN_SHED_RETRY_CAP_S", "8", "runtime",
             "dynarevive admission control: ceiling (seconds) on the "
             "load-derived, jittered Retry-After answered with shed / "
             "no-capacity 503s.")
register_env("DYN_SLO_BURN_THRESHOLD", "2.0", "runtime",
             "dynaslo: error-budget burn rate BOTH the fast and slow "
             "windows must exceed before an objective's multi-window "
             "alert fires (1.0 = spending exactly the budget).")
register_env("DYN_SLO_FAST_FRACTION", "0.1", "runtime",
             "dynaslo: the fast alert window as a fraction of each "
             "objective's window (SRE multi-window burn-rate pattern: "
             "the fast window catches the spike, the slow window proves "
             "it is sustained).")
register_env("DYN_SLO_FILE", None, "runtime",
             "dynaslo: path to a file of SLO objectives, one per line "
             "('#' comments), same grammar as DYN_SLO_OBJECTIVES. "
             "Ignored when DYN_SLO_OBJECTIVES is set.")
register_env("DYN_SLO_OBJECTIVES", None, "runtime",
             "dynaslo: ';'-separated SLO objectives, grammar "
             "[name=]metric<=threshold_s@target/window_s over metrics "
             "ttft|itl|queue_wait|e2e — e.g. 'ttft<=0.5@0.95/300;"
             "itl<=0.05@0.99/300'. Unset = no objectives (latency "
             "histograms still recorded and rendered).")
register_env("DYN_STATS_TIMEOUT", "2.0", "runtime",
             "Per-instance stats-plane scrape probe timeout in seconds.")
register_env("DYN_STEP_TIMELINE", "512", "runtime",
             "Engine step-timeline ring capacity (events kept per engine "
             "for /v1/traces); 0 disables the timeline.")
register_env("DYN_TRACE_JSONL", None, "runtime",
             "Path to append one JSON line per finished trace span "
             "(dyntrace export; unset = in-memory ring only).")
register_env("DYN_TRACE_RING", "4096", "runtime",
             "dyntrace in-memory ring capacity (finished spans kept per "
             "process for /v1/traces).")
register_env("DYN_TRACE_SAMPLE", "1.0", "runtime",
             "dyntrace sampling rate in [0,1], decided per root span "
             "(children follow their parent). 0 disables all tracing "
             "instrumentation (no spans, no envelope fields).")
register_env("DYN_WIRE_VALIDATE", "0", "runtime",
             "Debug mode: validate every wire frame against the "
             "runtime/wire.py schema registry at encode/decode time "
             "(1/true). Default off — the static dynalint pass (DL009/"
             "DL010) is the production gate.")

register_env("DYN_ADMIN_TOKENS", None, "admin",
             "Inline JSON token map for the admin API (absent = open API).")

register_env("DYN_KV_TRANSFER_CHUNK_PAGES", "4", "llm/disagg",
             "KV pages per streamed transfer chunk frame; 0 = legacy "
             "single bulk frame.")
register_env("DYN_KV_TRANSFER_INT8", "0", "llm/disagg",
             "int8-compress shipped KV pages (~half the DCN bytes; "
             "lossy). 1/true enables.")
register_env("DYN_PREFILL_TIMEOUT", "120.0", "llm/disagg",
             "Decode-side cap (seconds) on one remote-prefill wait "
             "(enqueue to KV commit); the request deadline caps it "
             "further. On expiry the request falls back to local "
             "prefill.")
register_env("DYN_REDISPATCH_MAX", "2", "llm/disagg",
             "Max remote-prefill dispatches per request (first + hedged "
             "re-enqueues after a fast transfer-plane failure, e.g. a "
             "prefill worker dying mid-transfer). 1 disables hedging.")

register_env("DYN_ASYNC_DETOK", "1", "llm",
             "dynaturbo: run Backend detokenization on a dedicated "
             "executor thread instead of the event-loop thread. Chunks "
             "of one request stay ordered (at most one in-flight decode "
             "per request); 0 restores inline decoding for A/B.")

register_env("DYN_CACHE_TOPK", "20", "engine",
             "dynacache: hot prefix chains reported per engine in "
             "GET /debug/cache (top-K cached block hashes by reuse "
             "count; internal tracking stays bounded regardless).")
register_env("DYN_CACHE_WINDOW", "256", "engine",
             "dynacache: admissions in the windowed prefix-hit-rate "
             "window. stats()['gpu_prefix_cache_hit_rate'] (and the "
             "dyn_worker_prefix_cache_hit_rate gauge) reflect the last "
             "N admissions; the lifetime ratio and raw token totals are "
             "exported alongside.")

register_env("DYN_EVICT_POLICY", "cost", "engine",
             "dynaheat: KV eviction policy for both cache tiers "
             "(EngineConfig.evict_policy=None reads this). 'cost' "
             "(default) runs GreedyDual over the dynacache hot-prefix "
             "hit table — a hot shared prefix outlives cold one-shot "
             "churn, O(log n) per eviction; 'lru' restores the original "
             "least-recently-freed order (the A/B control arm).")
register_env("DYN_RESTORE_OVERLAP", "1", "engine",
             "dynaheat: pipeline host-tier restores — a drained batch's "
             "H2D + dequantize dispatch on one drain and its page "
             "inject lands on the next, so the transfer overlaps the "
             "intervening device step instead of stalling it. 0 "
             "restores the serial same-drain inject (the A/B control "
             "arm). EngineConfig.restore_overlap=None reads this.")
register_env("DYN_HOST_TIER_FP16", "0", "engine",
             "dynaheat: keep the host KV tier at pool precision instead "
             "of the int8 default (engine/kv_compress.py). int8 halves "
             "the D2H/H2D bytes and doubles pages-per-GB but pages "
             "round-trip lossily; set 1 for the lossless fallback when "
             "bit-exact restores matter more than tier capacity. "
             "Explicit EngineConfig.host_tier_int8=True/False wins.")

register_env("DYN_LOOP_YIELD", None, "engine",
             "dynaturbo A/B: restore the historical unconditional "
             "asyncio.sleep(0) after each scheduler iteration. The "
             "await run_in_executor(step) already suspends the loop "
             "coroutine once per iteration, so the extra yield only "
             "adds a second event-loop round-trip; set (any value) to "
             "measure the difference with the loop-lag monitor.")

register_env("DYN_JIT_FENCE", None, "engine",
             "Runtime compile fence: reaction to an XLA compile AFTER "
             "JaxEngine.warmup() (the zero-compile serving invariant). "
             "Unset = count only (always exported as "
             "dyn_engine_post_warmup_compiles_total); 'warn' logs each "
             "compile; 'raise' fails the offending jit call with "
             "PostWarmupCompileError (the CI mode).")

register_env("DYN_PROF_SAMPLE", "0", "engine",
             "dynaprof: profile every Nth engine scheduler iteration "
             "with a timed dispatch (host-dispatch vs device-drain "
             "split, per-bucket cost table). The sampled iteration pays "
             "one deliberate device sync; 0 (default) disables sampling "
             "entirely — the hot path stays sync-free.")

register_env("DYN_ROUTER_AUTOTUNE", "1", "llm",
             "dynaheat: self-tune KvScheduler.load_balance_weight from "
             "the dynacache predicted-vs-realized overlap calibration "
             "error. Systematic over-prediction (stale/optimistic index) "
             "shifts weight toward load; under-prediction shifts it "
             "toward overlap. Bounded to [0.1, 0.9] and exported as the "
             "dyn_kv_router_load_balance_weight gauge; 0 pins the "
             "configured weight (the A/B control arm).")
register_env("DYN_ROUTER_AUTOTUNE_GAIN", "0.05", "llm",
             "dynaheat: per-window step size for the load_balance_weight "
             "autotuner (fraction of the bounded range moved per "
             "calibration window at full bias). Small values converge "
             "slowly but never oscillate; 0 observes without adjusting.")

register_env("DYN_PROF_USAGE", "0", "llm",
             "dynaprof: attach the per-request cost-attribution block "
             "to OpenAI usage payloads (stream_options.include_usage) "
             "as a `cost` extension field (1/true).")

register_env("DYN_FLEET_DISCOVERY_TIMEOUT", "10.0", "fleet",
             "Fleet simulator: wall-clock seconds to wait for spawned/"
             "stopped workers to propagate through discovery watches "
             "before a step proceeds.")
register_env("DYN_FLEET_MAX_WORKERS", "64", "fleet",
             "Fleet simulator: hard cap on workers the in-process fleet "
             "controller will run, regardless of planner advisories.")
register_env("DYN_FLEET_REPORT_DIR", None, "fleet",
             "Fleet simulator CLI: also write each run's JSON report "
             "into this directory (unset = stdout only).")

register_env("DYN_DP_REPLICAS", "1", "parallel",
             "dynashard: data-parallel engine replicas per process. Each "
             "replica gets its own submesh of the local device set, its "
             "own DistributedRuntime lease (= worker instance id) and its "
             "own KV-event publisher behind the KV router.")
register_env("DYN_FORCE_HOST_DEVICES", None, "parallel",
             "CPU bring-up: force this many virtual host devices by "
             "appending --xla_force_host_platform_device_count to "
             "XLA_FLAGS. Must be applied BEFORE the jax backend "
             "initializes (parallel.serving.apply_forced_host_devices; "
             "the tier-1 sharded tests run in a subprocess for exactly "
             "this reason).")
register_env("DYN_MESH_SHAPE", None, "parallel",
             "dynashard: per-replica device mesh as 'axis=N' pairs, e.g. "
             "'model=2' or 'data=2,model=4' (axes: data/model/expert/"
             "seq/stage — parallel/mesh.py). Unset = unsharded engines.")

register_env("DYN_DISABLE_PALLAS", None, "models",
             "Any non-empty value forces the XLA gather attention path "
             "everywhere (Pallas kill switch).")
register_env("DYN_MOE_BLOCK", "256", "models",
             "Scanned block height for the sorted MoE dispatch.")
register_env("DYN_PALLAS_INTERPRET", None, "models",
             "CPU test hook: any non-empty value runs Pallas kernels in "
             "interpret mode (never on a real TPU backend).")
register_env("DYN_PREFILL_PALLAS", None, "models",
             "Any non-empty value opts prefill into the flash Pallas "
             "kernel (pages stream through VMEM).")

register_env("DYN_DISABLE_NATIVE", None, "utils",
             "Any non-empty value disables building/loading the native "
             "C++ helper library.")
register_env("DYN_PROFILE_DIR", None, "run",
             "Capture a JAX/XLA profiler trace of the serving session "
             "into this directory.")

register_env("DYN_BENCH_PROBE_TIMEOUT", "240", "bench",
             "bench.py: seconds allowed for the server-readiness probe.")
register_env("DYN_BENCH_REQ_TIMEOUT", "600", "bench",
             "bench.py: per-request timeout in seconds.")
register_env("DYN_BENCH_WALL_BUDGET", "3000", "bench",
             "bench.py: total wall-clock budget in seconds.")

register_env("DYN_TEST_TPU", None, "tests",
             "Set to run the test suite against real TPU hardware instead "
             "of the forced-CPU 8-device virtual mesh.")

register_env("DYNAMO_SERVICE_CONFIG", None, "sdk",
             "Inline JSON ServiceConfig ({service: {key: value}}) "
             "injected into @service workers by `dynamo serve`.")

# Externally-defined variables the tree reads (documented here so the
# full environment surface is one table; defaults are the upstream ones).
register_env("HF_HUB_OFFLINE", "1", "external",
             "Set by dynamo_tpu.llm.tokenizer unless already present: "
             "never hit the HuggingFace hub at serve time.")
register_env("TRANSFORMERS_OFFLINE", "1", "external",
             "Set alongside HF_HUB_OFFLINE for the transformers library.")
register_env("KUBERNETES_SERVICE_HOST", None, "external",
             "In-cluster apiserver host (set by kubelet); required by the "
             "operator's InClusterClient.")
register_env("KUBERNETES_SERVICE_PORT", "443", "external",
             "In-cluster apiserver port.")
register_env("JAX_PLATFORMS", None, "external",
             "JAX backend selector; the SDK/bench pin control-plane "
             "processes to cpu so only TPU workers touch the chip.")
register_env("XLA_FLAGS", None, "external",
             "XLA runtime flags; read (never clobbered) by "
             "parallel.serving.apply_forced_host_devices when appending "
             "the DYN_FORCE_HOST_DEVICES device-count override.")


class UnregisteredEnvVar(KeyError):
    """Reading an env var that is not in ENV_REGISTRY: register it in
    runtime/config.py so it lands in docs/env_vars.md."""


def _lookup(name: str) -> EnvVar:
    var = ENV_REGISTRY.get(name)
    if var is None:
        raise UnregisteredEnvVar(
            f"env var {name!r} is not registered; declare it in "
            f"dynamo_tpu/runtime/config.py (register_env) so it is "
            f"documented in docs/env_vars.md")
    return var


def env_str(name: str, default: Optional[str] = None, *,
            required: bool = False) -> Optional[str]:
    """The registered variable's value, else the explicit ``default``,
    else the registry default. ``required=True`` raises when unset."""
    var = _lookup(name)
    val = os.environ.get(name)
    if val is None:
        val = default if default is not None else var.default
    if val is None and required:
        raise KeyError(f"required env var {name} is not set")
    return val


def env_int(name: str, default: Optional[int] = None) -> Optional[int]:
    val = env_str(name, None if default is None else str(default))
    return None if val is None else int(val)


def env_float(name: str, default: Optional[float] = None) -> Optional[float]:
    val = env_str(name, None if default is None else str(default))
    return None if val is None else float(val)


def env_bool(name: str, default: bool = False) -> bool:
    """Truthy string values: 1/true/yes/on (case-insensitive)."""
    val = env_str(name)
    if val is None or val == "":
        return default
    return val.strip().lower() in ("1", "true", "yes", "on")


def env_flag(name: str) -> bool:
    """Reference semantics for DYN_DISABLE_* style switches: ANY non-empty
    value (even '0') enables the flag."""
    _lookup(name)
    return bool(os.environ.get(name))


def env_set_default(name: str, value: str) -> None:
    """Registered setdefault (import-time offline pins and the like)."""
    _lookup(name)
    os.environ.setdefault(name, value)


def render_env_docs() -> str:
    """docs/env_vars.md content, generated from the registry."""
    lines = [
        "# Environment variables",
        "",
        "Generated from `dynamo_tpu/runtime/config.py` — do not edit by "
        "hand. Regenerate with:",
        "",
        "```",
        "python -m tools.dynalint --write-env-docs docs/env_vars.md",
        "```",
        "",
        "Every env read in the tree goes through this registry's typed "
        "helpers (`env_str`/`env_int`/`env_float`/`env_bool`/`env_flag`); "
        "dynalint rule `untracked-env-read` rejects direct `os.environ` "
        "access anywhere else, so this table is the complete knob surface.",
        "",
        "| Variable | Default | Component | Description |",
        "|---|---|---|---|",
    ]
    for var in sorted(ENV_REGISTRY.values(),
                      key=lambda v: (v.component, v.name)):
        default = "(unset)" if var.default is None else f"`{var.default}`"
        lines.append(f"| `{var.name}` | {default} | {var.component} "
                     f"| {var.description} |")
    return "\n".join(lines) + "\n"


# --------------------------------------------------------------- RuntimeConfig

@dataclass
class RuntimeConfig:
    dcp_address: Optional[str] = None       # DYN_DCP_ADDRESS; None → embedded
    lease_ttl: float = 10.0                 # DYN_LEASE_TTL
    request_timeout: float = 60.0           # DYN_REQUEST_TIMEOUT
    log_level: str = "INFO"                 # DYN_LOG
    log_jsonl: bool = False                 # DYN_LOGGING_JSONL

    @classmethod
    def from_settings(cls) -> "RuntimeConfig":
        cfg = cls()
        path = env_str("DYN_CONFIG_PATH")
        if path and os.path.exists(path):
            with open(path) as f:
                if path.endswith((".yaml", ".yml")):
                    import yaml

                    data = yaml.safe_load(f) or {}
                else:
                    data = json.load(f)
            for f_ in fields(cls):
                if f_.name in data:
                    setattr(cfg, f_.name, data[f_.name])
        env_map = {
            "DYN_DCP_ADDRESS": ("dcp_address", str),
            "DYN_LEASE_TTL": ("lease_ttl", float),
            "DYN_REQUEST_TIMEOUT": ("request_timeout", float),
            "DYN_LOG": ("log_level", str),
            "DYN_LOGGING_JSONL": ("log_jsonl",
                                  lambda v: v.lower() in ("1", "true")),
        }
        for env, (name, conv) in env_map.items():
            if env in os.environ:
                setattr(cfg, name, conv(os.environ[env]))
        return cfg
