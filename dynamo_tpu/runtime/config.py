"""Layered runtime configuration.

Reference lib/runtime/src/config.rs: figment-layered settings from env
(``DYN_WORKER_*`` / ``DYN_RUNTIME_*``) + optional TOML. Here: env
(``DYN_*``) + optional YAML/JSON file named by ``DYN_CONFIG_PATH``.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field, fields
from typing import Optional


@dataclass
class RuntimeConfig:
    dcp_address: Optional[str] = None       # DYN_DCP_ADDRESS; None → embedded
    lease_ttl: float = 10.0                 # DYN_LEASE_TTL
    request_timeout: float = 60.0           # DYN_REQUEST_TIMEOUT
    log_level: str = "INFO"                 # DYN_LOG
    log_jsonl: bool = False                 # DYN_LOGGING_JSONL

    @classmethod
    def from_settings(cls) -> "RuntimeConfig":
        cfg = cls()
        path = os.environ.get("DYN_CONFIG_PATH")
        if path and os.path.exists(path):
            with open(path) as f:
                if path.endswith((".yaml", ".yml")):
                    import yaml

                    data = yaml.safe_load(f) or {}
                else:
                    data = json.load(f)
            for f_ in fields(cls):
                if f_.name in data:
                    setattr(cfg, f_.name, data[f_.name])
        env_map = {
            "DYN_DCP_ADDRESS": ("dcp_address", str),
            "DYN_LEASE_TTL": ("lease_ttl", float),
            "DYN_REQUEST_TIMEOUT": ("request_timeout", float),
            "DYN_LOG": ("log_level", str),
            "DYN_LOGGING_JSONL": ("log_jsonl", lambda v: v.lower() in ("1", "true")),
        }
        for env, (name, conv) in env_map.items():
            if env in os.environ:
                setattr(cfg, name, conv(os.environ[env]))
        return cfg
