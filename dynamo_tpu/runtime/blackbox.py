"""dynablack: the incident flight recorder.

Every telemetry plane in this tree is sampled, windowed, or ring-bounded
(DYN_TRACE_SAMPLE, DYN_PROF_SAMPLE, the bounded stall table) — correct
for steady-state overhead, useless at 3 a.m. when the evidence of *why*
a burn-rate alert fired or a breaker opened has already rotated out.
The standard production answer (Dapper's always-on sampling plus
Canopy-style trigger-driven retroactive capture) is what this module
implements:

- :class:`ShadowRing` — a bounded, lock-free per-worker event ring with
  the dyntrace anchor-pair discipline (``anchor_wall`` +
  ``anchor_monotonic`` stamped once; every event carries a ``mono_ms``
  offset) so rings from different workers align on one timeline.
- :class:`FlightRecorder` — holds the rings, a trigger registry, and a
  bounded incident table. On :meth:`trip` it freezes the rings,
  assembles a JSON **incident bundle** folding the last
  ``DYN_BLACKBOX_WINDOW_S`` seconds of *existing* telemetry (tracer
  spans, step timelines, profiler/cache/memory snapshots, loop lag,
  stall stacks, request attributions, guard counters, breaker and chaos
  state, engine stats), persists it under ``DYN_BLACKBOX_DIR``, and
  debounces with ``DYN_BLACKBOX_COOLDOWN_S``.
- Trigger notifications (:func:`notify_trigger`, :func:`note_deadline`)
  wired from the events that already exist: SLO burn-rate trips
  (slo.py), breaker ``closed→open`` (guard.py), post-warmup compiles
  (jit_fence.py), watchdog stall captures (profiling.py), failover
  resumes (revive.py), and deadline storms (N timeouts in W seconds).
- DCP fan-out (:func:`attach_dcp` / :func:`broadcast_capture`) over the
  optional ``blackbox.capture`` wire frame so sibling workers
  contribute their rings to the same incident id.

Hot-path contract (the A/B acceptance criterion): an armed-but-untripped
recorder costs one global read + a ``None``/bool check per
:func:`note` call and *nothing* anywhere else — every fold of real
telemetry happens at capture time, on the cold path. No host syncs
(DL005), no eager formatting (DL023), every container bounded (DL024).

Trigger sources lazy-import this module inside their cold event paths;
this module lazy-imports tracing/profiling/guard at capture time, so no
import cycle exists at module load.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import weakref
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional

from .config import env_float, env_str
from .tracing import json_safe

log = logging.getLogger("dynamo_tpu.blackbox")

#: every trigger the registry knows; DYN_BLACKBOX_TRIGGERS filters this.
TRIGGERS = ("slo_burn_rate", "breaker_open", "post_warmup_compile",
            "watchdog_stall", "failover_resume", "deadline_storm", "manual")

# deadline storm: this many DeadlineExceeded within this window = trip
STORM_N = 8
STORM_WINDOW_S = 5.0

#: DCP subject the capture fan-out rides on (namespaced by the caller)
BLACKBOX_SUBJECT = "blackbox.capture"


# ------------------------------------------------------------- shadow ring


class ShadowRing:
    """Bounded per-worker event ring, lock-free on the append path.

    ``deque.append`` on a ``maxlen`` deque is a single GIL-atomic
    operation, so writers from any thread never contend and never grow
    the ring (the dynaprof ring idiom). Anchors follow the StepTimeline
    pair discipline: stamped once at construction (and on
    :meth:`restamp` after a restart), events carry only the monotonic
    offset, wall time is derived at export."""

    __slots__ = ("label", "anchor_wall", "anchor_monotonic",
                 "_events", "_clock", "_wall")

    def __init__(self, label: str, maxlen: int = 512,
                 clock: Callable[[], float] = time.monotonic,
                 wall: Callable[[], float] = time.time):
        self.label = label
        self._clock = clock
        self._wall = wall
        self._events: deque = deque(maxlen=maxlen)  # bounded ring
        self.anchor_wall = 0.0
        self.anchor_monotonic = 0.0
        self.restamp()

    def restamp(self) -> None:
        """Re-stamp the anchor pair (worker restart): events recorded
        after a restamp must never alias pre-restart ``mono_ms`` values,
        so the ring is cleared with the anchors."""
        self._events.clear()
        self.anchor_monotonic = self._clock()
        self.anchor_wall = self._wall()

    def note(self, kind: str, **fields: Any) -> None:
        """Append one event. Hot-path safe: no formatting, no locks —
        fields are stored raw and coerced JSON-safe only at capture."""
        fields["kind"] = kind
        fields["mono_ms"] = round(
            (self._clock() - self.anchor_monotonic) * 1000.0, 3)
        self._events.append(fields)

    def __len__(self) -> int:
        return len(self._events)

    def anchors(self) -> dict:
        return {"anchor_wall": round(self.anchor_wall, 6),
                "anchor_monotonic": round(self.anchor_monotonic, 6)}

    def snapshot(self, window_s: Optional[float] = None) -> List[dict]:
        """Events (oldest first), optionally only the last ``window_s``
        seconds, as JSON-safe dicts with derived ``ts_ms`` wall stamps."""
        items = [dict(e) for e in self._events]
        if window_s is not None and window_s > 0:
            cutoff = ((self._clock() - self.anchor_monotonic)
                      - window_s) * 1000.0
            items = [e for e in items if e.get("mono_ms", 0.0) >= cutoff]
        base_ms = self.anchor_wall * 1000.0
        for e in items:
            e["ts_ms"] = round(base_ms + e.get("mono_ms", 0.0), 3)
        return [json_safe(e) for e in items]

    def export(self, window_s: Optional[float] = None) -> dict:
        return {"anchors": self.anchors(),
                "events": self.snapshot(window_s)}


# --------------------------------------------------------- flight recorder


class FlightRecorder:
    """Shadow rings + trigger registry + bounded incident table.

    Everything time-related is injectable (``clock``/``wall``/
    ``id_factory``) so the fleet simulator can run the recorder on its
    virtual clock and produce byte-identical bundles per seed.
    ``include_process_state=False`` skips the live-process telemetry
    fold (tracer/profiler/guard globals) — the sim uses it because those
    globals are not part of the deterministic virtual world."""

    def __init__(self, window_s: Optional[float] = None,
                 out_dir: Optional[str] = None,
                 cooldown_s: Optional[float] = None,
                 triggers: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic,
                 wall: Callable[[], float] = time.time,
                 id_factory: Optional[Callable[[], str]] = None,
                 include_process_state: bool = True,
                 ring_len: int = 512,
                 max_incidents: int = 32):
        if window_s is None:
            window_s = env_float("DYN_BLACKBOX_WINDOW_S") or 0.0
        if cooldown_s is None:
            cooldown_s = env_float("DYN_BLACKBOX_COOLDOWN_S") or 0.0
        if out_dir is None:
            out_dir = env_str("DYN_BLACKBOX_DIR")
        if triggers is None:
            triggers = env_str("DYN_BLACKBOX_TRIGGERS") or "all"
        self.window_s = float(window_s)
        self.cooldown_s = float(cooldown_s)
        self.out_dir = out_dir
        self.triggers = self._parse_triggers(triggers)
        self.include_process_state = include_process_state
        self.ring_len = ring_len
        self._clock = clock
        self._wall = wall
        self._id_factory = id_factory
        self._lock = threading.Lock()
        # ring CREATION is locked; note() appends are lock-free deque pushes
        self.rings: Dict[str, ShadowRing] = {}  # guarded-by: self._lock
        # bounded-by: max_incidents (oldest incident evicted on insert)
        self._incidents: "OrderedDict[str, dict]" = OrderedDict()
        self._max_incidents = max_incidents
        self._sources: "OrderedDict[str, Callable[[], Any]]" = OrderedDict()
        # bounded-by: one weakref per registered engine; dead refs reaped at capture
        self._stats_sources: Dict[str, Any] = {}
        self._listeners: List[Callable[[dict], None]] = []
        self._deadlines: deque = deque(maxlen=STORM_N)  # bounded storm window
        self._last_capture: Optional[float] = None
        self._seq = 0
        self._baseline: dict = {}
        self.captures_total = 0
        self.suppressed_total = 0
        if self.enabled and include_process_state:
            self.refresh_baseline()

    @staticmethod
    def _parse_triggers(spec: str) -> frozenset:
        spec = (spec or "all").strip().lower()
        if spec in ("all", "*", ""):
            return frozenset(TRIGGERS)
        names = {t.strip() for t in spec.split(",") if t.strip()}
        unknown = names - set(TRIGGERS)
        if unknown:
            log.warning("DYN_BLACKBOX_TRIGGERS: unknown trigger(s) %s "
                        "ignored", sorted(unknown))
        return frozenset(names & set(TRIGGERS))

    # --------------------------------------------------------- hot path

    @property
    def enabled(self) -> bool:
        return self.window_s > 0

    def ring(self, worker: str) -> ShadowRing:
        r = self.rings.get(worker)
        if r is None:
            with self._lock:
                r = self.rings.get(worker)
                if r is None:
                    r = ShadowRing(worker, self.ring_len,
                                   self._clock, self._wall)
                    self.rings[worker] = r
        return r

    def note(self, worker: str, kind: str, **fields: Any) -> None:
        """The one per-event call sites pay while armed: a dict lookup
        and a deque append."""
        if not self.enabled:
            return
        self.ring(worker).note(kind, **fields)

    def note_deadline(self) -> None:
        """Deadline-storm detector: STORM_N DeadlineExceeded inside
        STORM_WINDOW_S trips a capture."""
        if not self.enabled or "deadline_storm" not in self.triggers:
            return
        now = self._clock()
        self._deadlines.append(now)
        if (len(self._deadlines) == STORM_N
                and now - self._deadlines[0] <= STORM_WINDOW_S):
            self.trip("deadline_storm", {
                "timeouts": STORM_N,
                "window_s": round(now - self._deadlines[0], 3)})

    # ------------------------------------------------------- registration

    def add_source(self, name: str, fn: Callable[[], Any]) -> None:
        """Extra snapshot provider folded into every bundle under
        ``sources.<name>`` (e.g. the frontend's SLO snapshot, the
        aggregator's last fleet scrape). Bound methods are held weakly
        so a source never pins its owner."""
        if hasattr(fn, "__self__"):
            fn = weakref.WeakMethod(fn)  # type: ignore[assignment]
            self._sources[name] = lambda ref=fn: (ref() or _none)()
        else:
            self._sources[name] = fn

    def register_stats_source(self, label: str, owner: Any) -> None:
        """An engine-shaped object whose ``stats()`` is folded into the
        bundle's ``telemetry.engines.<label>`` (held weakly)."""
        self._stats_sources[label] = weakref.ref(owner)

    def add_capture_listener(self, fn: Callable[[dict], None]) -> None:
        """Called with each freshly assembled bundle (DCP broadcast,
        tests)."""
        self._listeners.append(fn)

    def refresh_baseline(self) -> None:
        """Snapshot the profiler cost table + cache stats as the
        pre-incident baseline the postmortem renderer diffs against.
        Called at construction, from CompileFence.arm() (end of
        warmup), and after every capture."""
        if not self.enabled or not self.include_process_state:
            self._baseline = {}
            return
        from . import profiling
        self._baseline = json_safe({
            "at_wall_ms": round(self._wall() * 1000.0, 3),
            "profiles": profiling.profiles_snapshot(),
            "caches": profiling.caches_snapshot(),
        })

    # ------------------------------------------------------------ capture

    def cooldown_remaining_s(self) -> float:
        if self._last_capture is None or self.cooldown_s <= 0:
            return 0.0
        return max(0.0, self.cooldown_s
                   - (self._clock() - self._last_capture))

    def trip(self, trigger: str, detail: Optional[dict] = None
             ) -> Optional[dict]:
        """Fire a trigger: freeze the rings and assemble a bundle.
        Returns None when disabled, the trigger is filtered out, or the
        cooldown debounce suppresses the capture."""
        if not self.enabled or trigger not in self.triggers:
            return None
        with self._lock:
            if self.cooldown_remaining_s() > 0:
                self.suppressed_total += 1
                return None
            self._last_capture = self._clock()
            bundle = self._assemble(trigger, detail)
            self._remember(bundle)
            self.captures_total += 1
        self._persist(bundle)
        for fn in list(self._listeners):
            try:
                fn(bundle)
            except Exception:
                log.exception("blackbox capture listener failed")
        self.refresh_baseline()
        return bundle

    def _next_id(self) -> str:
        if self._id_factory is not None:
            return self._id_factory()
        self._seq += 1
        return f"incident-{int(self._wall() * 1000.0):x}-{self._seq:02d}"

    def _assemble(self, trigger: str, detail: Optional[dict]) -> dict:
        bundle = {
            "id": self._next_id(),
            "trigger": trigger,
            "detail": json_safe(detail) if detail else {},
            "at_wall_ms": round(self._wall() * 1000.0, 3),
            "at_mono_ms": round(self._clock() * 1000.0, 3),
            "window_s": self.window_s,
            "workers": {label: r.export(self.window_s)
                        for label, r in sorted(self.rings.items())},
            "contributed": [],
            "baseline": self._baseline,
            "sources": self._fold_sources(),
        }
        if self.include_process_state:
            bundle["telemetry"] = self._fold_telemetry()
        return bundle

    def _fold_sources(self) -> dict:
        out = {}
        for name, fn in self._sources.items():
            try:
                out[name] = json_safe(fn())
            except Exception:
                log.exception("blackbox source %s failed", name)
                out[name] = None
        return out

    def _fold_telemetry(self) -> dict:
        """Cold path: fold the last window of every existing telemetry
        plane. Every read here is a snapshot of an already-bounded
        structure — nothing synchronizes with a device."""
        from . import guard, profiling, tracing
        since_ms = (self._wall() - self.window_s) * 1000.0
        tracer = tracing.get_tracer()
        spans = [s.to_dict() for s in tracer.snapshot()
                 if s.wall_start * 1000.0 >= since_ms]
        engines = {}
        for label, ref in list(self._stats_sources.items()):
            owner = ref()
            if owner is None:
                self._stats_sources.pop(label, None)
                continue
            try:
                engines[label] = owner.stats()
            except Exception:
                log.exception("blackbox stats source %s failed", label)
        return json_safe({
            "traces": tracer.traces_summary(limit=200, since_ms=since_ms),
            "spans": spans,
            "timelines": tracing.timelines_snapshot(limit=500,
                                                    since_ms=since_ms),
            "timeline_anchors": tracing.timeline_anchors(),
            "profiles": profiling.profiles_snapshot(),
            "caches": profiling.caches_snapshot(),
            "loop_lag": profiling.loop_lag_snapshot(),
            "stall_stacks": profiling.stall_stacks_folded(limit=50),
            "attributions": [
                {"request_id": rid, "cost": cost}
                for rid, cost in profiling.attributions_snapshot(limit=100)],
            "guard_counters": guard.counters_snapshot(),
            "breakers": guard.boards_snapshot(),
            "chaos": _chaos_snapshot(),
            "engines": engines,
        })

    def _remember(self, bundle: dict) -> None:
        self._incidents[bundle["id"]] = bundle
        while len(self._incidents) > self._max_incidents:
            self._incidents.popitem(last=False)

    def _persist(self, bundle: dict) -> None:
        if not self.out_dir:
            return
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            path = os.path.join(self.out_dir, f"{bundle['id']}.json")
            with open(path, "w", encoding="utf-8") as f:
                f.write(render_bundle_json(bundle))
        except OSError:
            log.exception("blackbox: failed to persist incident %s",
                          bundle["id"])

    # ----------------------------------------------------- incident table

    def incidents_summary(self) -> List[dict]:
        """Newest-first one-row-per-incident summaries for
        GET /debug/incidents."""
        with self._lock:
            rows = [{
                "id": b["id"],
                "trigger": b["trigger"],
                "at_wall_ms": b["at_wall_ms"],
                "workers": sorted(b["workers"].keys()),
                "contributed": list(b.get("contributed", [])),
                "remote": bool(b.get("remote", False)),
            } for b in self._incidents.values()]
        return rows[::-1]

    def get(self, incident_id: str) -> Optional[dict]:
        with self._lock:
            return self._incidents.get(incident_id)

    def rings_export(self, window_s: Optional[float] = None) -> dict:
        """All local rings, for contributing to a sibling's incident."""
        if window_s is None:
            window_s = self.window_s
        return {label: r.export(window_s)
                for label, r in sorted(self.rings.items())}

    def contribute(self, incident_id: str, workers: dict,
                   origin: Optional[str] = None) -> bool:
        """Merge a sibling's rings into an existing incident (first
        writer per worker label wins; re-persists the bundle)."""
        with self._lock:
            bundle = self._incidents.get(incident_id)
            if bundle is None:
                return False
            for label, data in workers.items():
                bundle["workers"].setdefault(label, json_safe(data))
            if origin:
                bundle["contributed"] = sorted(
                    set(bundle.get("contributed", [])) | {origin})
        self._persist(bundle)
        return True

    def observe_remote(self, incident_id: str, trigger: str, origin: str,
                       at_ms: Optional[float] = None) -> dict:
        """A sibling announced a capture: open a local incident stub
        (bypasses cooldown — the debounce belongs to the originator)
        carrying this process's rings."""
        with self._lock:
            bundle = self._incidents.get(incident_id)
            if bundle is not None:
                return bundle
            bundle = {
                "id": incident_id,
                "trigger": trigger,
                "detail": {},
                "origin": origin,
                "remote": True,
                "at_wall_ms": (round(float(at_ms), 3) if at_ms is not None
                               else round(self._wall() * 1000.0, 3)),
                "window_s": self.window_s,
                "workers": {label: r.export(self.window_s)
                            for label, r in sorted(self.rings.items())},
                "contributed": [],
                "baseline": self._baseline,
                "sources": self._fold_sources(),
            }
            self._remember(bundle)
        self._persist(bundle)
        return bundle


def _none() -> None:
    return None


def _chaos_snapshot() -> Optional[dict]:
    from . import guard
    inj = guard.chaos()
    injected = getattr(inj, "injected", None)
    if not injected:
        return None
    return {"injected": {f"{action}:{point}": n
                         for (action, point), n in sorted(injected.items())}}


def render_bundle_json(bundle: dict) -> str:
    """The one canonical bundle serialization: sorted keys, fixed
    indent, the dyntrace JSON-safe coercion — byte-stable given equal
    content (the fleet-sim determinism contract)."""
    return json.dumps(json_safe(bundle), sort_keys=True, indent=2)


# --------------------------------------------------------- module recorder

_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()


def get_recorder() -> FlightRecorder:
    """The process-wide recorder, created lazily from the environment."""
    global _recorder
    rec = _recorder
    if rec is None:
        with _recorder_lock:
            rec = _recorder
            if rec is None:
                rec = _recorder = FlightRecorder()
    return rec


def configure(recorder: Optional[FlightRecorder] = None,
              **kwargs: Any) -> FlightRecorder:
    """Install a specific recorder (tests, sims) or rebuild from kwargs."""
    global _recorder
    with _recorder_lock:
        _recorder = recorder if recorder is not None \
            else FlightRecorder(**kwargs)
    return _recorder


def reset() -> None:
    """Test hook: drop the process recorder (next use re-reads env)."""
    global _recorder
    with _recorder_lock:
        _recorder = None


def notify_trigger(trigger: str, detail: Optional[dict] = None
                   ) -> Optional[dict]:
    """Trigger-source entry point (guard/slo/jit_fence/profiling/revive
    lazy-import and call this on their cold event paths)."""
    return get_recorder().trip(trigger, detail)


def note(worker: str, kind: str, **fields: Any) -> None:
    """Shadow-ring append. A process that never configured or armed a
    recorder pays one global read and a ``None`` check."""
    rec = _recorder
    if rec is None or not rec.enabled:
        return
    rec.note(worker, kind, **fields)


def note_deadline() -> None:
    """Deadline-storm sample (guard.py). Same no-op contract as
    :func:`note` when nothing is armed."""
    rec = _recorder
    if rec is None or not rec.enabled:
        return
    rec.note_deadline()


# ------------------------------------------------------------ DCP fan-out


def capture_header(incident_id: str, trigger: str, worker_label: str,
                   at_ms: Optional[float] = None,
                   rings: Optional[dict] = None) -> dict:
    """Build + validate one ``blackbox.capture`` frame. ``rings`` absent
    = origin announcement; present = a sibling's contribution."""
    from . import wire
    header: Dict[str, Any] = {
        "event": "blackbox.capture",
        "incident_id": incident_id,
        "trigger": trigger,
        "worker_label": worker_label,
    }
    if at_ms is not None:
        header["at_ms"] = float(at_ms)
    if rings is not None:
        header["rings"] = rings
    return wire.checked(wire.BLACKBOX_CAPTURE, header)


async def broadcast_capture(drt: Any, namespace: str, bundle: dict,
                            worker_label: str = "") -> None:
    """Announce a capture to siblings (they reply with their rings via
    the :func:`attach_dcp` handler)."""
    from .dcp_client import pack
    frame = capture_header(bundle["id"], bundle["trigger"], worker_label,
                           at_ms=bundle.get("at_wall_ms"))
    await drt.dcp.publish(f"{namespace}.{BLACKBOX_SUBJECT}", pack(frame))


async def attach_dcp(drt: Any, namespace: str, recorder: FlightRecorder,
                     worker_label: str,
                     rings_fn: Optional[Callable[[], dict]] = None) -> int:
    """Join the capture fan-out: on a sibling's origin announcement,
    record a local incident stub and publish this process's rings back;
    on a ring-carrying frame, merge it into the matching incident.
    Returns the subscription id."""
    from . import wire
    from .dcp_client import pack, unpack

    subject = f"{namespace}.{BLACKBOX_SUBJECT}"

    async def _on_capture(msg: Any) -> None:
        try:
            frame = wire.decoded(wire.BLACKBOX_CAPTURE, unpack(msg.payload))
        except Exception:
            log.debug("blackbox: ignoring undecodable capture frame",
                      exc_info=True)
            return
        if frame.get("event") != BLACKBOX_SUBJECT:
            return  # a foreign frame type sharing the subject
        if frame.get("worker_label") == worker_label:
            return  # own broadcast echoed back
        rings = frame.get("rings")
        if rings is not None:
            recorder.contribute(frame["incident_id"], rings,
                                origin=frame.get("worker_label"))
            return
        recorder.observe_remote(frame["incident_id"],
                                frame.get("trigger", "manual"),
                                frame.get("worker_label", ""),
                                frame.get("at_ms"))
        own = rings_fn() if rings_fn is not None else recorder.rings_export()
        reply = capture_header(frame["incident_id"],
                               frame.get("trigger", "manual"),
                               worker_label, rings=own)
        await drt.dcp.publish(subject, pack(reply))

    return await drt.dcp.subscribe(subject, _on_capture)
