"""dynaproto: declared lifecycle state machines for the failure protocols.

PR 5's ``wire.py`` did for wire frames what this module does for
*lifecycle protocols*: every safety-critical concurrent state machine in
the serving stack — request lifecycle, worker drain, circuit breaker,
revive journal, KV transfer stream, planner P/D shift — is declared ONCE
as a pure literal, and three consumers read the single declaration:

1. **the serving processes** import it normally (``proto.step`` anchors
   validate real transitions under ``DYN_PROTO_VALIDATE``, identity
   no-ops otherwise — zero hot-path cost in production);
2. **the static conformance pass** (``tools/dynalint/dynaproto.py``,
   rules DL019/DL020) parses this file with ``ast.literal_eval`` — no
   runtime import, no jax — and checks every code site that mutates
   protocol state against the declared edges;
3. **the model checker** (``tools/dynalint/modelcheck.py``) explores the
   declared machines exhaustively (TLA-style explicit-state BFS over the
   machine composed with its declared nondeterministic environment:
   client kills, worker deaths, message loss) and fails tier-1 when a
   declared invariant — "drain never nacks before the discovery delete",
   "journal entry closes exactly once", "the breaker never has two
   in-flight probes" — is violated in any reachable state.

Keep every ``register_protocol(...)`` argument a literal. The
declaration grammar:

- ``states`` / ``initial`` / ``terminal`` — the machine's state space.
  No edge may leave a terminal state.
- ``edges`` — dicts ``{"from", "to", "name", "when", "set", "doc"}``.
  ``when`` guards on auxiliary vars (value or tuple = membership);
  ``set`` updates them (``"+1"`` bumps an int-domain var). Every edge
  must be *anchored* by at least one real code site (DL020).
- ``vars`` / ``init`` — auxiliary finite-domain variables (booleans,
  small ints, enum strings) the model checker tracks alongside
  ``state``.
- ``env`` — environment transitions (same shape, no from/to): the
  nondeterminism the protocol must survive. Env transitions may only
  update aux vars — every *state* change is a declared, anchored edge,
  which is what keeps the model and the code from drifting.
- ``owners`` — ``(module-path-suffix, attr)`` pairs naming the
  attribute(s) that hold this machine's state in code. Any store to
  such an attribute outside ``__init__`` must carry an anchor (DL019).
- ``lock`` — the machine's declared serialization discipline:
  ``"loop"`` (event-loop atomicity: no anchored transition may straddle
  an ``await``) or ``"self.<attr>"`` (every anchored transition must
  hold that lock). DL020 enforces it via dynarace's concurrency-root
  inference.
- ``invariants`` — three kinds: ``{"name", "never": {...}}`` (the
  predicate holds in no reachable state); ``{"name", "never_stable":
  {...}}`` (the predicate holds in no *quiescent* state — one with no
  enabled protocol edge — the bounded form of "eventually"); and
  ``{"name", "never_fire": {"edges": (...), "when": {...}}}`` (no
  listed edge is ever *enabled* in a reachable state satisfying the
  predicate — the transition-level form: "no resume is ever dispatched
  after a client kill" is a property of the dispatch edge's guard, not
  of any single state).

Anchor grammar at code sites (docs/static_analysis.md#dynaproto)::

    proto.step("breaker", "open", "half_open")      # call anchor
    self.state = BREAKER_OPEN   # proto: breaker closed|half_open->open

Comment anchors bind to their own line or the line below; ``|``
separates alternative states (the cross product must be declared),
``,`` separates several transitions in one anchor.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Sequence, Tuple, Union

from .config import env_bool


class ProtocolError(RuntimeError):
    """A runtime transition contradicts the declared protocol."""


@dataclass(frozen=True)
class ProtoEdge:
    frm: str
    to: str
    name: str
    when: Tuple[Tuple[str, tuple], ...]   # var -> allowed values
    set: Tuple[Tuple[str, object], ...]   # var -> new value (or "+1")
    doc: str


@dataclass(frozen=True)
class ProtoMachine:
    name: str
    doc: str
    states: Tuple[str, ...]
    initial: str
    terminal: Tuple[str, ...]
    lock: Optional[str]
    owners: Tuple[Tuple[str, str], ...]
    edges: Tuple[ProtoEdge, ...]
    vars: Tuple[Tuple[str, tuple], ...]
    init: Tuple[Tuple[str, object], ...]
    env: Tuple[ProtoEdge, ...]            # frm/to empty on env transitions
    invariants: Tuple[dict, ...]
    depth: int

    @property
    def edge_pairs(self) -> frozenset:
        return frozenset((e.frm, e.to) for e in self.edges)

    def has_edge(self, frm: str, to: str) -> bool:
        return (frm, to) in self.edge_pairs


PROTOCOLS: Dict[str, ProtoMachine] = {}


def _norm_when(when: Optional[dict]) -> Tuple[Tuple[str, tuple], ...]:
    out = []
    for k, v in sorted((when or {}).items()):
        vals = tuple(v) if isinstance(v, (tuple, list)) else (v,)
        out.append((k, vals))
    return tuple(out)


def _norm_edge(e: dict, env: bool = False) -> ProtoEdge:
    return ProtoEdge(
        frm="" if env else e["from"], to="" if env else e["to"],
        name=e.get("name") or (f"{e.get('from')}->{e.get('to')}"),
        when=_norm_when(e.get("when")),
        set=tuple(sorted((e.get("set") or {}).items())),
        doc=e.get("doc", ""))


def register_protocol(name: str, *, doc: str = "",
                      states: Sequence[str] = (), initial: str = "",
                      terminal: Sequence[str] = (),
                      lock: Optional[str] = None,
                      owners: Sequence[tuple] = (),
                      edges: Sequence[dict] = (),
                      vars: Optional[dict] = None,
                      init: Optional[dict] = None,
                      env: Sequence[dict] = (),
                      invariants: Sequence[dict] = (),
                      depth: int = 64) -> str:
    """Declare one lifecycle protocol; returns ``name`` so module
    constants double as registry keys. KEEP EVERY ARGUMENT A LITERAL —
    tools/dynalint parses this file without importing it."""
    sts = tuple(states)
    if initial not in sts:
        raise ValueError(f"protocol {name!r}: initial {initial!r} "
                         f"not in states")
    term = tuple(terminal)
    for t in term:
        if t not in sts:
            raise ValueError(f"protocol {name!r}: terminal {t!r} "
                             f"not in states")
    es = tuple(_norm_edge(e) for e in edges)
    for e in es:
        if e.frm not in sts or e.to not in sts:
            raise ValueError(f"protocol {name!r}: edge {e.name!r} uses "
                             f"undeclared state(s)")
        if e.frm in term:
            raise ValueError(f"protocol {name!r}: edge {e.name!r} leaves "
                             f"terminal state {e.frm!r}")
    PROTOCOLS[name] = ProtoMachine(
        name=name, doc=doc, states=sts, initial=initial, terminal=term,
        lock=lock, owners=tuple((str(m), str(a)) for m, a in owners),
        edges=es,
        vars=tuple(sorted((k, tuple(v)) for k, v in (vars or {}).items())),
        init=tuple(sorted((init or {}).items())),
        env=tuple(_norm_edge(e, env=True) for e in env),
        invariants=tuple(dict(i) for i in invariants),
        depth=int(depth))
    return name


def validation_enabled() -> bool:
    """Debug validation knob (DYN_PROTO_VALIDATE; default off)."""
    return env_bool("DYN_PROTO_VALIDATE")


def step(machine: str, frm: Union[str, Tuple[str, ...]], to: str) -> None:
    """Transition anchor at a protocol-state mutation site. Identity
    no-op unless ``DYN_PROTO_VALIDATE`` is set; the static pass (DL019)
    checks the named transition against the registry either way."""
    if not validation_enabled():
        return
    m = PROTOCOLS.get(machine)
    if m is None:
        raise ProtocolError(f"unknown protocol machine {machine!r}")
    froms = (frm,) if isinstance(frm, str) else tuple(frm)
    for f in froms:
        if f not in m.states or to not in m.states:
            raise ProtocolError(
                f"protocol {machine!r}: unknown state in {f!r}->{to!r}")
        if not m.has_edge(f, to):
            raise ProtocolError(
                f"protocol {machine!r}: transition {f!r}->{to!r} is not "
                f"declared — add the edge in runtime/proto.py or fix the "
                f"call site")


# ------------------------------------------------------------ the registry
#
# KEEP EVERY ARGUMENT A LITERAL — tools/dynalint parses this file with
# ast.literal_eval; computed values would silently drop the machine from
# the static conformance pass (and are rejected by its loader).

REQUEST_LIFECYCLE = register_protocol(
    "request.lifecycle",
    doc="One request through the engine scheduler: admission queue -> "
        "chunked prefill -> decode -> terminal finish/timeout/cancel, "
        "plus dynarevive mid-stream failover (resumed) and KV-pressure "
        "preemption (decode back to the admission queue). The "
        "environment injects client kills and worker deaths.",
    states=("admitted", "prefill", "decode", "resumed",
            "finished", "timeout", "cancelled"),
    initial="admitted",
    terminal=("finished", "timeout", "cancelled"),
    lock="loop",
    owners=(("engine/jax_engine.py", "finished"),),
    vars={"killed": (False, True), "worker_dead": (False, True)},
    init={"killed": False, "worker_dead": False},
    edges=(
        {"from": "admitted", "to": "prefill", "name": "dispatch_prefill",
         "doc": "scheduler admits the sequence (pages reserved)"},
        {"from": "prefill", "to": "decode", "name": "first_token",
         "doc": "prompt KV complete; first token sampled"},
        {"from": "decode", "to": "admitted", "name": "preempt",
         "doc": "KV pool exhausted: pages released, sequence requeued"},
        {"from": "prefill", "to": "finished", "name": "finish_at_prefill",
         "doc": "zero-budget / stop hit on the first sampled token"},
        {"from": "decode", "to": "finished", "name": "finish",
         "doc": "eos / stop / length budget reached"},
        {"from": "admitted", "to": "timeout", "name": "expire_admitted",
         "doc": "deadline spent while queued"},
        {"from": "prefill", "to": "timeout", "name": "expire_prefill"},
        {"from": "decode", "to": "timeout", "name": "expire_decode"},
        {"from": "admitted", "to": "cancelled", "name": "cancel_admitted",
         "when": {"killed": True}},
        {"from": "prefill", "to": "cancelled", "name": "cancel_prefill",
         "when": {"killed": True}},
        {"from": "decode", "to": "cancelled", "name": "cancel_decode",
         "when": {"killed": True}},
        {"from": "admitted", "to": "finished", "name": "reject_admitted",
         "doc": "admission-time reject (over-capacity prompt): the "
                "error finish is emitted without a prefill"},
        {"from": "prefill", "to": "resumed", "name": "revive_prefill",
         "when": {"worker_dead": True, "killed": False},
         "doc": "upstream died mid-prefill; the frontend journal "
                "re-dispatches to a sibling"},
        {"from": "decode", "to": "resumed", "name": "revive_decode",
         "when": {"worker_dead": True, "killed": False}},
        {"from": "resumed", "to": "prefill", "name": "redispatch",
         "when": {"killed": False}, "set": {"worker_dead": False},
         "doc": "resume prompt (prompt + emitted) lands on a sibling"},
        {"from": "resumed", "to": "cancelled", "name": "cancel_resumed",
         "when": {"killed": True},
         "doc": "the client died while the resume was being routed: "
                "should_resume's context.stopped check drops it"},
    ),
    env=(
        {"name": "client_kill", "when": {"killed": False},
         "set": {"killed": True},
         "doc": "SSE client disconnect / ctrl kill frame"},
        {"name": "worker_death", "when": {"worker_dead": False},
         "set": {"worker_dead": True},
         "doc": "serving worker crashes mid-stream"},
    ),
    invariants=(
        {"name": "no-resume-after-kill",
         "never_fire": {"edges": ("revive_prefill", "revive_decode",
                                  "redispatch"),
                        "when": {"killed": True}},
         "doc": "no resume decision or re-dispatch may ever be enabled "
                "for a request whose client is gone — the guards that "
                "revive.should_resume's context.stopped check implements"},
        {"name": "killed-request-terminates",
         "never_stable": {"killed": True,
                          "state": ("admitted", "prefill", "decode",
                                    "resumed")},
         "doc": "after a client kill the protocol always has a cancel "
                "path enabled — no killed request can wedge non-terminal"},
    ),
    depth=64)


SERVE_DRAIN = register_protocol(
    "serve_handle.drain",
    doc="ServeHandle graceful-drain lifecycle (dynarevive): live -> "
        "draining (discovery record deleted FIRST, then new dispatches "
        "nacked while in-flight streams finish and the stats plane keeps "
        "answering with draining=1) -> stopped. worker.kill chaos turns "
        "either live state into the wedged-process `dead` shape.",
    states=("live", "draining", "stopped", "dead"),
    initial="live",
    terminal=("stopped", "dead"),
    lock="loop",
    owners=(("runtime/component.py", "draining"),
            ("runtime/component.py", "_dead")),
    vars={"discovery": ("present", "deleted")},
    init={"discovery": "present"},
    edges=(
        {"from": "live", "to": "live", "name": "withdraw_discovery",
         "set": {"discovery": "deleted"},
         "doc": "begin_drain deletes the discovery record before any "
                "nack can be issued"},
        {"from": "live", "to": "draining", "name": "enter_draining",
         "when": {"discovery": "deleted"},
         "doc": "the nack flag flips only after the discovery delete "
                "completed (delete-before-nack ordering)"},
        {"from": "draining", "to": "draining", "name": "nack_request",
         "doc": "a request that still reaches the subjects gets a typed "
                "accepted=False nack"},
        {"from": "live", "to": "stopped", "name": "stop",
         "set": {"discovery": "deleted"},
         "doc": "fast teardown (SIGINT): unsubscribe + withdraw"},
        {"from": "draining", "to": "stopped", "name": "stop_after_drain"},
        {"from": "live", "to": "dead", "name": "worker_kill",
         "doc": "chaos worker.kill: planes go silent, lease + discovery "
                "record stay behind"},
        {"from": "draining", "to": "dead", "name": "worker_kill_draining"},
    ),
    env=(),
    invariants=(
        {"name": "delete-before-nack",
         "never_fire": {"edges": ("nack_request",),
                        "when": {"discovery": "present"}},
         "doc": "a draining worker must never nack while routers can "
                "still discover it — the nacked client would re-pick the "
                "same instance until its retry budget dies"},
    ),
    depth=32)


BREAKER = register_protocol(
    "breaker",
    doc="CircuitBreaker (dynaguard): closed -> open after N consecutive "
        "failures -> a SINGLE half-open probe (granted every "
        "probe_every-th denial or on clock expiry) -> closed on probe "
        "success / straight back to open on probe failure. The probe "
        "permit is a slot: release_probe() hands it back when the "
        "caller picked a different instance.",
    states=("closed", "open", "half_open"),
    initial="closed",
    terminal=(),
    lock="loop",
    owners=(("runtime/guard.py", "state"),),
    vars={"probe": (0, 1, 2)},
    init={"probe": 0},
    edges=(
        {"from": "closed", "to": "closed", "name": "success",
         "doc": "a success in closed resets the failure count"},
        {"from": "closed", "to": "open", "name": "trip",
         "doc": "threshold consecutive failures"},
        {"from": "open", "to": "open", "name": "deny",
         "doc": "an open breaker answers allow()=False and counts the "
                "denial toward the probe cadence"},
        {"from": "open", "to": "half_open", "name": "grant_probe",
         "when": {"probe": 0}, "set": {"probe": "+1"},
         "doc": "probe cadence due: ONE permit converts to half-open"},
        {"from": "half_open", "to": "half_open", "name": "probe_regrant",
         "when": {"probe": 0}, "set": {"probe": "+1"},
         "doc": "a released permit may be re-granted — never a second "
                "concurrent one"},
        {"from": "half_open", "to": "half_open", "name": "release_probe",
         "when": {"probe": 1}, "set": {"probe": 0},
         "doc": "the caller picked another instance: slot returned"},
        {"from": "half_open", "to": "closed", "name": "probe_success",
         "set": {"probe": 0}},
        {"from": "half_open", "to": "open", "name": "probe_failure",
         "set": {"probe": 0}},
        {"from": "open", "to": "closed", "name": "reset",
         "set": {"probe": 0},
         "doc": "external evidence of recovery (fresh discovery put)"},
    ),
    env=(),
    invariants=(
        {"name": "single-probe", "never": {"probe": 2},
         "doc": "two concurrent half-open probes would double-load a "
                "recovering instance; every grant edge is guarded on "
                "probe==0"},
        {"name": "probe-only-half-open",
         "never": {"state": ("closed", "open"), "probe": (1, 2)},
         "doc": "a probe permit cannot outlive the half-open state"},
    ),
    depth=32)


REVIVE_JOURNAL = register_protocol(
    "revive.journal",
    doc="One ReviveJournal entry (dynarevive): opened at dispatch, "
        "closed EXACTLY ONCE at finish AND on client kill (the "
        "Context.on_kill hook) so the bounded ring holds one entry per "
        "in-flight request — leak-proof under abandonment. Ring "
        "overflow / eviction only clears resumability, never "
        "correctness.",
    states=("open", "closed"),
    initial="open",
    terminal=("closed",),
    lock="loop",
    owners=(("runtime/revive.py", "resumable"),),
    vars={"request": ("streaming", "finished", "killed"),
          "resumable": (True, False), "closes": (0, 1, 2)},
    init={"request": "streaming", "resumable": True, "closes": 0},
    edges=(
        {"from": "open", "to": "open", "name": "overflow",
         "set": {"resumable": False},
         "doc": "journal token bound exceeded: the request loses "
                "resumability, never correctness"},
        {"from": "open", "to": "open", "name": "evict",
         "set": {"resumable": False},
         "doc": "ring capacity eviction (leak-bug backstop)"},
        {"from": "open", "to": "closed", "name": "close_on_finish",
         "when": {"request": "finished"}, "set": {"closes": "+1"},
         "doc": "eager close at the finish chunk (consumers abandon the "
                "stream there; the generator finalizer would leak until "
                "GC)"},
        {"from": "open", "to": "closed", "name": "close_on_kill",
         "when": {"request": "killed"}, "set": {"closes": "+1"},
         "doc": "the processor registers journal close on Context.on_kill"},
        {"from": "open", "to": "closed", "name": "close_final",
         "when": {"request": ("finished", "killed")},
         "set": {"closes": "+1"},
         "doc": "the generate() finally backstop"},
    ),
    env=(
        {"name": "finish", "when": {"request": "streaming"},
         "set": {"request": "finished"}},
        {"name": "client_kill", "when": {"request": "streaming"},
         "set": {"request": "killed"}},
    ),
    invariants=(
        {"name": "close-exactly-once", "never": {"closes": 2},
         "doc": "every close edge leaves `open`, so a second close is "
                "unrepresentable (pop is idempotent in code)"},
        {"name": "closed-after-finish",
         "never_stable": {"request": ("finished", "killed"),
                          "state": "open"},
         "doc": "no terminal request may leave its entry open once the "
                "protocol quiesces — the leak the eager/on_kill/finally "
                "closes exist to prevent"},
    ),
    depth=32)


KV_TRANSFER_STREAM = register_protocol(
    "kv_transfer.stream",
    doc="Receiver-side KV transfer stream (PR 2 chunked plane): chunks "
        "inject in order, the final chunk is the commit that resolves "
        "the decode-side waiter; sender aborts and connection drops "
        "fail the waiter fast, and payloads arriving after terminal "
        "state are dropped by the late-write guard (the pages may "
        "belong to another request by then).",
    states=("streaming", "committed", "aborted", "failed"),
    initial="streaming",
    terminal=("committed", "aborted", "failed"),
    lock="loop",
    owners=(("llm/disagg/transfer.py", "committed"),
            ("llm/disagg/transfer.py", "failed")),
    vars={"conn": ("up", "down"), "resolved": (0, 1, 2)},
    init={"conn": "up", "resolved": 0},
    edges=(
        {"from": "streaming", "to": "committed", "name": "commit",
         "when": {"conn": "up"}, "set": {"resolved": "+1"},
         "doc": "final chunk ingested with all chunks received: waiter "
                "resolves with the first token"},
        {"from": "streaming", "to": "aborted", "name": "abort",
         "set": {"resolved": "+1"},
         "doc": "sender abort frame: drop partial state, fail the "
                "waiter now"},
        {"from": "streaming", "to": "failed", "name": "fail",
         "set": {"resolved": "+1"},
         "doc": "inject error / incomplete stream / unknown-request "
                "late-write guard"},
        {"from": "streaming", "to": "failed", "name": "fail_on_drop",
         "when": {"conn": "down"}, "set": {"resolved": "+1"},
         "doc": "connection dropped mid-stream: the uncommitted stream "
                "fails instead of idling out the prefill timeout"},
    ),
    env=(
        {"name": "conn_drop", "when": {"conn": "up"},
         "set": {"conn": "down"}},
    ),
    invariants=(
        {"name": "resolve-exactly-once", "never": {"resolved": 2},
         "doc": "the decode-side waiter resolves exactly once — every "
                "resolving edge leaves `streaming` (the st.committed "
                "re-checks in code)"},
        {"name": "fail-fast-on-drop",
         "never_stable": {"state": "streaming", "conn": "down"},
         "doc": "a dead connection must never leave a stream parked in "
                "`streaming` (the decode side would idle out its full "
                "prefill timeout)"},
    ),
    depth=32)


PD_SHIFT = register_protocol(
    "planner.pd_shift",
    doc="dynaslo P/D rebalance control loop: the planner publishes at "
        "most one pd_shift advisory per cooldown (TTFT vs ITL burn "
        "pressure), the fleet controller actuates it by flipping ONE "
        "donor worker's role in place, and the cooldown gate readmits "
        "the next decision only after it expires.",
    states=("idle", "advisory", "actuated"),
    initial="idle",
    terminal=(),
    lock="loop",
    owners=(),
    vars={"cooldown": (False, True)},
    init={"cooldown": False},
    edges=(
        {"from": "idle", "to": "advisory", "name": "publish_shift",
         "when": {"cooldown": False}, "set": {"cooldown": True},
         "doc": "decide_pd: one side's SLO budget burns while the other "
                "has slack"},
        {"from": "advisory", "to": "actuated", "name": "actuate_flip",
         "doc": "fleet controller flips the newest donor-role worker"},
        {"from": "advisory", "to": "idle", "name": "no_donor",
         "doc": "no worker holds the donor role: advisory expires "
                "without actuation"},
        {"from": "actuated", "to": "idle", "name": "cooldown_gate",
         "doc": "decide_pd's shift_cooldown_s gate readmits decisions"},
    ),
    env=(
        {"name": "cooldown_expire",
         "when": {"state": "idle", "cooldown": True},
         "set": {"cooldown": False}},
    ),
    invariants=(
        {"name": "one-shift-per-cooldown",
         "never": {"state": ("advisory", "actuated"), "cooldown": False},
         "doc": "a second advisory can never be decided while one is in "
                "flight — the publish edge sets the cooldown atomically"},
    ),
    depth=32)


# ------------------------------------------------------------ doc rendering

def _machine_markdown(m: ProtoMachine) -> list:
    term = ", ".join(f"`{t}`" for t in m.terminal) or "—"
    lines = [f"### `{m.name}` "
             f"({len(m.states)} states, {len(m.edges)} edges)", ""]
    if m.doc:
        lines += [m.doc, ""]
    lines += [f"Initial `{m.initial}`; terminal {term}; "
              f"lock `{m.lock or 'none'}`.", "",
              "| From | To | Edge | Guard | Updates |",
              "|---|---|---|---|---|"]
    for e in m.edges:
        when = "; ".join(
            f"{k} in {list(v)}" if len(v) > 1 else f"{k}={v[0]!r}"
            for k, v in e.when) or "—"
        sets = "; ".join(f"{k}:={v!r}" for k, v in e.set) or "—"
        lines.append(f"| `{e.frm}` | `{e.to}` | `{e.name}` "
                     f"| {when} | {sets} |")
    lines.append("")
    if m.invariants:
        lines.append("Invariants (machine-checked by "
                     "`tools/dynalint/modelcheck.py`):")
        lines.append("")
        for inv in m.invariants:
            if "never" in inv:
                kind, pred = "never", inv["never"]
            elif "never_stable" in inv:
                kind, pred = "never stable", inv["never_stable"]
            else:
                kind, pred = "never fire", inv.get("never_fire")
            lines.append(f"- **{inv['name']}** — {kind} `{pred}`"
                         + (f": {inv['doc']}" if inv.get("doc") else ""))
        lines.append("")
    return lines


def render_proto_tables() -> str:
    """Markdown tables for every declared machine — embedded
    (sync-gated) into docs/static_analysis.md."""
    lines: list = []
    for name in sorted(PROTOCOLS):
        lines += _machine_markdown(PROTOCOLS[name])
    return "\n".join(lines).rstrip() + "\n"
