"""dynaprof: always-on, low-overhead profiling for the serving runtime.

dyntrace (runtime/tracing.py) answers *how long* each stage of a request
took in wall-clock; this module answers *where the time went* — the
measurement gap that kept "scheduler overhead, not FLOPs" an inference.
Three planes, all stdlib-only (the device-side half lives in
``engine/profiler.py`` because it needs jax):

- **Event-loop lag monitor** — an asyncio task sleeps a fixed interval
  and records how late it woke (sampled sleep-drift, the classic
  continuous-profiling signal for a starved event loop). Bounded ring;
  p50/p99 exported as ``dyn_runtime_loop_lag_seconds`` and folded into
  every engine's ``stats()`` → ForwardPassMetrics.
- **Stall watchdog** — a daemon thread watching the monitor's heartbeat.
  When a single loop callback overruns ``DYN_PROF_STALL_MS``, it
  captures the event-loop thread's Python stack via
  ``sys._current_frames()`` and accumulates it into a bounded
  folded-stack table exportable as flamegraph-ready collapsed-stack
  text (``GET /debug/profile/stacks`` → ``flamegraph.pl``). Sampling
  only happens *during* a stall, so the steady-state cost is one
  ``monotonic()`` read per poll.
- **Per-request cost attribution** — a bounded ring of attribution
  dicts (queue wait, occupancy-weighted device-step share, KV bytes,
  prefill/decode split) recorded by the engine at finish and surfaced
  through ``/v1/traces/{request_id}`` and the optional usage extension
  block.

Overhead budget and knobs: docs/profiling.md.
"""

from __future__ import annotations

import asyncio
import logging
import sys
import threading
import time
import weakref
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from .config import env_float, env_int

log = logging.getLogger("dynamo_tpu.profiling")

# --------------------------------------------------------- loop lag monitor


def _pct(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list (0 when empty)."""
    if not sorted_vals:
        return 0.0
    rank = max(int(len(sorted_vals) * q / 100.0), 0)
    return sorted_vals[min(rank, len(sorted_vals) - 1)]


class LoopLagMonitor:
    """Sampled sleep-drift: sleep ``interval``, record how late the wakeup
    was. Lag ≈ the sum of callback overruns during the sleep — exactly
    the stall every other request on this loop also experienced."""

    def __init__(self, interval_s: Optional[float] = None, ring: int = 2048):
        if interval_s is None:
            interval_s = (env_float("DYN_PROF_LOOP_INTERVAL_MS")
                          or 100.0) / 1000.0
        self.interval = max(float(interval_s), 0.001)
        self.samples: deque = deque(maxlen=ring)
        # heartbeat read by the stall watchdog thread (single-word
        # read/write — atomic under the GIL)
        self.last_beat = time.monotonic()
        self.loop_thread_id: Optional[int] = None
        self.beats = 0
        self._task: Optional[asyncio.Task] = None

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        self.loop_thread_id = threading.get_ident()
        while True:
            t0 = loop.time()
            self.last_beat = time.monotonic()
            await asyncio.sleep(self.interval)
            self.beats += 1
            self.samples.append(max(loop.time() - t0 - self.interval, 0.0))

    def start(self) -> None:
        if self._task is None or self._task.done():
            from .tasks import spawn_tracked

            self._task = spawn_tracked(self._run(), name="dynaprof-loop-lag")

    async def stop(self) -> None:
        from .tasks import cancel_join

        task, self._task = self._task, None
        await cancel_join(task)

    def snapshot(self) -> dict:
        vals = sorted(self.samples)
        return {
            "interval_s": self.interval,
            "samples": len(vals),
            "p50_s": round(_pct(vals, 50), 6),
            "p99_s": round(_pct(vals, 99), 6),
            "max_s": round(vals[-1], 6) if vals else 0.0,
        }


# ------------------------------------------------------------ stall watchdog


def fold_stack(frame) -> str:
    """Collapsed-stack line (outermost;...;innermost) for one Python
    frame chain — the flamegraph.pl input format, module.function units."""
    parts: List[str] = []
    f = frame
    while f is not None:
        name = f.f_code.co_name
        mod = f.f_globals.get("__name__", "?")
        parts.append(f"{mod}.{name}")
        f = f.f_back
    return ";".join(reversed(parts))


class StallWatchdog(threading.Thread):
    """Samples the event-loop thread's stack while a callback overruns.

    The monitor task stamps ``last_beat`` before every sleep; if *now*
    exceeds ``last_beat + interval + threshold`` the loop has been stuck
    inside one callback for at least ``threshold`` — capture the stack.
    Repeated captures during one long stall accumulate like a sampling
    profiler: tall bars in the flamegraph = long/frequent stalls."""

    def __init__(self, monitor: LoopLagMonitor,
                 threshold_s: Optional[float] = None,
                 max_stacks: Optional[int] = None,
                 poll_s: Optional[float] = None):
        super().__init__(name="dynaprof-watchdog", daemon=True)
        if threshold_s is None:
            threshold_s = (env_float("DYN_PROF_STALL_MS") or 250.0) / 1000.0
        self.threshold = float(threshold_s)
        self.max_stacks = (max_stacks if max_stacks is not None
                           else (env_int("DYN_PROF_STACKS") or 256))
        self.poll = poll_s if poll_s is not None else max(
            self.threshold / 4.0, 0.01)
        self.monitor = monitor
        self._stacks: "OrderedDict[str, int]" = OrderedDict()
        self._last_seen: Dict[str, float] = {}  # bounded-by: same cap as _stacks (popped together)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.captures = 0
        self.dropped = 0

    def run(self) -> None:
        while not self._stop.wait(self.poll):
            overdue = (time.monotonic() - self.monitor.last_beat
                       - self.monitor.interval)
            if overdue >= self.threshold:
                self.capture()

    def stop(self) -> None:
        self._stop.set()

    def capture(self) -> Optional[str]:
        """Capture the loop thread's current stack into the folded table
        (also callable directly from tests)."""
        tid = self.monitor.loop_thread_id
        if tid is None:
            return None
        frame = sys._current_frames().get(tid)
        if frame is None:
            return None
        folded = fold_stack(frame)
        with self._lock:
            self.captures += 1
            if folded in self._stacks:
                self._stacks[folded] += 1
                self._last_seen[folded] = time.time()
            elif len(self._stacks) < self.max_stacks:
                self._stacks[folded] = 1
                self._last_seen[folded] = time.time()
            else:
                self.dropped += 1  # bounded: new shapes past cap are counted
        # a stall long enough to sample IS an anomaly; already off-loop
        from . import blackbox
        blackbox.notify_trigger("watchdog_stall", {
            "stack": folded, "threshold_ms": self.threshold * 1000.0})
        return folded

    def folded(self, limit: Optional[int] = None,
               since: Optional[float] = None) -> str:
        """Flamegraph-ready collapsed-stack text: ``stack count`` lines.

        ``limit`` keeps only the top-N hottest stacks; ``since`` (wall
        seconds) drops stacks not sampled since that time — both exist so
        /debug/profile/stacks can bound its response at production ring
        sizes (satellite of dynablack)."""
        with self._lock:
            items = sorted(self._stacks.items(),
                           key=lambda kv: (-kv[1], kv[0]))
            if since is not None:
                items = [(s, c) for s, c in items
                         if self._last_seen.get(s, 0.0) >= since]
        if limit is not None and limit >= 0:
            items = items[:limit]
        return "".join(f"{stack} {count}\n" for stack, count in items)

    def snapshot(self) -> dict:
        with self._lock:
            distinct = len(self._stacks)
        return {"captures": self.captures, "distinct_stacks": distinct,
                "dropped": self.dropped,
                "threshold_ms": round(self.threshold * 1000.0, 3)}


# ------------------------------------------------------------- loop profiler


class LoopProfiler:
    """Monitor + watchdog pair for one event loop."""

    def __init__(self, interval_s: Optional[float] = None,
                 stall_threshold_s: Optional[float] = None):
        self.monitor = LoopLagMonitor(interval_s)
        if stall_threshold_s is None:
            stall_threshold_s = (env_float("DYN_PROF_STALL_MS")
                                 or 250.0) / 1000.0
        self.watchdog = (StallWatchdog(self.monitor, stall_threshold_s)
                         if stall_threshold_s > 0 else None)
        self._started = False

    def start(self) -> None:
        self.monitor.start()
        if self.watchdog is not None and not self._started:
            self.watchdog.start()
        self._started = True

    async def stop(self) -> None:
        if self.watchdog is not None:
            self.watchdog.stop()
        await self.monitor.stop()

    def snapshot(self) -> dict:
        out = {"loop_lag": self.monitor.snapshot()}
        if self.watchdog is not None:
            out["stalls"] = self.watchdog.snapshot()
        return out


# one refcounted profiler per event loop: every acquirer (HTTP service,
# engine, bench) shares it; the last release cancels the monitor task so
# no task outlives its loop
_loop_profilers: Dict[int, List] = {}  # id(loop) -> [LoopProfiler, refcount]
_lp_lock = threading.Lock()
_latest: Optional[LoopProfiler] = None  # last started (stats() fallback)


def acquire_loop_profiler() -> LoopProfiler:
    """Start (or join) the running loop's profiler. Must be called from
    the event loop; pair with :func:`release_loop_profiler`."""
    global _latest
    loop = asyncio.get_running_loop()
    key = id(loop)
    with _lp_lock:
        ent = _loop_profilers.get(key)
        if ent is None:
            ent = [LoopProfiler(), 0]
            _loop_profilers[key] = ent
        ent[1] += 1
        prof = ent[0]
    prof.start()
    _latest = prof
    return prof


async def release_loop_profiler() -> None:
    loop = asyncio.get_running_loop()
    key = id(loop)
    with _lp_lock:
        ent = _loop_profilers.get(key)
        if ent is None:
            return
        ent[1] -= 1
        if ent[1] > 0:
            return
        # claim before the await: a concurrent release must not double-stop
        del _loop_profilers[key]
        prof = ent[0]
    await prof.stop()


def current_loop_profiler() -> Optional[LoopProfiler]:
    try:
        loop = asyncio.get_running_loop()
    except RuntimeError:
        loop = None
    if loop is not None:
        with _lp_lock:
            ent = _loop_profilers.get(id(loop))
        if ent is not None:
            return ent[0]
    return _latest


def loop_lag_snapshot() -> dict:
    """The running loop's lag percentiles (zeros when no profiler is up).
    Falls back to the most recently started profiler so engine ``stats()``
    called off-loop (executor thread) still reports the serving loop."""
    prof = current_loop_profiler()
    if prof is None:
        return {"interval_s": 0.0, "samples": 0, "p50_s": 0.0,
                "p99_s": 0.0, "max_s": 0.0}
    return prof.monitor.snapshot()


def stall_stacks_folded(limit: Optional[int] = None,
                        since_ms: Optional[float] = None) -> str:
    prof = current_loop_profiler()
    if prof is None or prof.watchdog is None:
        return ""
    since = since_ms / 1000.0 if since_ms is not None else None
    return prof.watchdog.folded(limit=limit, since=since)


def render_prom_lines() -> List[str]:
    """Loop-lag/stall gauges for the local process's /metrics exposition
    (the aggregator re-exports per-worker figures from ForwardPassMetrics
    instead)."""
    prof = current_loop_profiler()
    if prof is None:
        return []
    snap = prof.monitor.snapshot()
    lines = [
        "# HELP dyn_runtime_loop_lag_seconds event-loop sleep-drift "
        "(sampled callback overrun seen by every task on this loop)",
        "# TYPE dyn_runtime_loop_lag_seconds gauge",
        f'dyn_runtime_loop_lag_seconds{{quantile="p50"}} {snap["p50_s"]}',
        f'dyn_runtime_loop_lag_seconds{{quantile="p99"}} {snap["p99_s"]}',
    ]
    if prof.watchdog is not None:
        w = prof.watchdog.snapshot()
        lines += [
            "# HELP dyn_runtime_loop_stall_captures_total stack samples "
            "taken while a loop callback overran the stall threshold",
            "# TYPE dyn_runtime_loop_stall_captures_total counter",
            f"dyn_runtime_loop_stall_captures_total {w['captures']}",
        ]
    return lines


# -------------------------------------------------- per-request attribution

_attr_lock = threading.Lock()
_attributions: "OrderedDict[str, dict]" = OrderedDict()


def _attr_cap() -> int:
    return max(env_int("DYN_PROF_ATTR_RING") or 2048, 1)


# attribution listeners: called on EVERY record (engine-side finish AND
# the Backend's re-register of a remote cost block) with (request_id,
# cost). Called OUTSIDE the ring lock, and a listener MAY mutate the cost
# dict in place — that is how the KvRouter merges router_overlap_blocks
# into the same dict /v1/traces serves (dynacache calibration).
_attr_listeners: List[Callable[[str, dict], None]] = []


def add_attribution_listener(fn: Callable[[str, dict], None]) -> None:
    if fn not in _attr_listeners:
        _attr_listeners.append(fn)


def remove_attribution_listener(fn: Callable[[str, dict], None]) -> None:
    try:
        _attr_listeners.remove(fn)
    except ValueError:
        pass


def record_attribution(request_id: Optional[str], cost: dict) -> None:
    """Record one finished request's cost-attribution dict (bounded ring,
    newest wins). Called by the engine at finish and by the Backend when
    a remote worker's finish chunk carries a ``cost`` block — so the
    frontend process can serve ``/v1/traces/{rid}`` attribution for
    requests whose engine ran elsewhere."""
    if not request_id:
        return
    cap = _attr_cap()
    with _attr_lock:
        _attributions[request_id] = cost
        _attributions.move_to_end(request_id)
        while len(_attributions) > cap:
            _attributions.popitem(last=False)
    for fn in list(_attr_listeners):
        try:
            fn(request_id, cost)
        except Exception:  # noqa: BLE001 — observability must not break serving
            log.exception("attribution listener failed")


def request_attribution(request_id: str) -> Optional[dict]:
    with _attr_lock:
        return _attributions.get(request_id)


def attributions_snapshot(limit: int = 100) -> List[Tuple[str, dict]]:
    with _attr_lock:
        items = list(_attributions.items())
    return items[-limit:]


# --------------------------------------------------- engine profile registry
# Engine-side profilers (engine/profiler.py) register here so the HTTP
# /debug/profile endpoint can render every live engine's cost table —
# same weakref pattern as tracing.register_timeline.

_profiles: Dict[str, "weakref.ref"] = {}
_profiles_lock = threading.Lock()


def register_profile(name: str, profile: Any) -> None:
    with _profiles_lock:
        _profiles[name] = weakref.ref(profile)


def profiles_snapshot() -> Dict[str, dict]:
    out: Dict[str, dict] = {}
    with _profiles_lock:
        for name, ref in list(_profiles.items()):
            p = ref()
            if p is None:
                del _profiles[name]
            else:
                out[name] = p.summary()
    return out


# ----------------------------------------------------- cache-view registry
# dynacache: anything with a ``cache_snapshot()`` (the JaxEngine's
# pool/host-tier/hot-prefix view) registers here so GET /debug/cache can
# render every live cache in the process — same weakref hygiene as the
# engine-profile registry above.

_caches: Dict[str, "weakref.ref"] = {}
_caches_lock = threading.Lock()


def register_cache(name: str, owner: Any) -> None:
    with _caches_lock:
        _caches[name] = weakref.ref(owner)


def caches_snapshot() -> Dict[str, dict]:
    out: Dict[str, dict] = {}
    with _caches_lock:
        for name, ref in list(_caches.items()):
            c = ref()
            if c is None:
                del _caches[name]
            else:
                try:
                    out[name] = c.cache_snapshot()
                except Exception:  # noqa: BLE001 — a dying engine must not 500 the debug page
                    log.debug("cache snapshot for %s failed", name,
                              exc_info=True)
    return out
