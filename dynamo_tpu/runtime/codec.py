"""Two-part frame codec for the streaming response plane.

Frame layout (reference lib/runtime/src/pipeline/network/codec/two_part.rs:30-70):
a fixed 24-byte prelude — ``header_len`` (u64 LE), ``body_len`` (u64 LE),
``xxh3_64(header || body)`` (u64 LE) — followed by the header bytes (msgpack
control map) and the body bytes (opaque payload). The checksum guards the
response plane against corruption/desync on long-lived raw TCP streams.
"""

from __future__ import annotations

import asyncio
import struct
from dataclasses import dataclass, field
from typing import Optional

import msgpack
import xxhash

from . import wire

PRELUDE = struct.Struct("<QQQ")
PRELUDE_SIZE = PRELUDE.size  # 24
MAX_MESSAGE = 256 * 1024 * 1024


class CodecError(RuntimeError):
    pass


@dataclass
class TwoPartMessage:
    header: dict = field(default_factory=dict)
    body: bytes = b""


def encode(msg: TwoPartMessage) -> bytes:
    if wire.validation_enabled():
        wire.validate_outgoing(msg.header)
    header = msgpack.packb(msg.header, use_bin_type=True)
    body = msg.body or b""
    h = xxhash.xxh3_64()
    h.update(header)
    h.update(body)
    return PRELUDE.pack(len(header), len(body), h.intdigest()) + header + body


def encode_parts(header: dict, body_parts=()) -> list:
    """Zero-copy multi-buffer framing: same wire format as ``encode`` but
    the body is a sequence of buffer-protocol parts (numpy array views,
    bytes) that are hashed and emitted in place — no ``b"".join`` copy of
    a multi-hundred-MB KV payload. Returns the buffer list to hand to
    ``StreamWriter.writelines``; a ``decode`` on the other end sees one
    body of the concatenated parts."""
    if wire.validation_enabled():
        wire.validate_outgoing(header)
    hdr = msgpack.packb(header, use_bin_type=True)
    h = xxhash.xxh3_64()
    h.update(hdr)
    parts = []
    body_len = 0
    for p in body_parts:
        mv = p if isinstance(p, (bytes, memoryview)) else memoryview(p)
        if isinstance(mv, memoryview) and (mv.ndim != 1 or mv.itemsize != 1):
            mv = mv.cast("B")
        h.update(mv)
        body_len += len(mv)
        parts.append(mv)
    return [PRELUDE.pack(len(hdr), body_len, h.intdigest()) + hdr, *parts]


async def decode(reader: asyncio.StreamReader) -> TwoPartMessage:
    # this IS the frame-read primitive dynalint rule DL011 anchors on:
    # callers either bound their `await decode(...)` or justify an idle
    # server read; the reads inside the primitive itself stay naked
    prelude = await reader.readexactly(PRELUDE_SIZE)  # dynalint: disable=unbounded-await
    header_len, body_len, checksum = PRELUDE.unpack(prelude)
    if header_len + body_len > MAX_MESSAGE:
        raise CodecError(f"message too large: {header_len + body_len}")
    header = await reader.readexactly(header_len)  # dynalint: disable=unbounded-await
    body = await reader.readexactly(body_len)  # dynalint: disable=unbounded-await
    h = xxhash.xxh3_64()
    h.update(header)
    h.update(body)
    if h.intdigest() != checksum:
        raise CodecError("two-part frame checksum mismatch")
    return TwoPartMessage(msgpack.unpackb(header, raw=False), body)


def decode_buffer(buf: bytes) -> tuple[Optional[TwoPartMessage], bytes]:
    """Non-async incremental decode: returns (message | None, remaining)."""
    if len(buf) < PRELUDE_SIZE:
        return None, buf
    header_len, body_len, checksum = PRELUDE.unpack(buf[:PRELUDE_SIZE])
    if header_len + body_len > MAX_MESSAGE:
        raise CodecError(f"message too large: {header_len + body_len}")
    total = PRELUDE_SIZE + header_len + body_len
    if len(buf) < total:
        return None, buf
    header = buf[PRELUDE_SIZE:PRELUDE_SIZE + header_len]
    body = buf[PRELUDE_SIZE + header_len:total]
    h = xxhash.xxh3_64()
    h.update(header)
    h.update(body)
    if h.intdigest() != checksum:
        raise CodecError("two-part frame checksum mismatch")
    return TwoPartMessage(msgpack.unpackb(header, raw=False), body), buf[total:]
