"""dynaslo — fleet-wide SLO engine: mergeable latency histograms,
multi-window burn-rate alerts, goodput accounting and pressure signals.

The serving stack exports raw latency signals everywhere (frontend TTFT/
ITL, engine queue wait, per-stage spans) but until dynaslo nothing could
*aggregate* them across workers or judge them against an objective. This
module provides the four layers, all dependency-free and clock-injectable
so the fleet simulator evaluates them on its virtual clock byte-for-byte:

1. :class:`Histogram` — a fixed-bucket, **mergeable** latency histogram.
   Merging is lossless (bucket counts add) because every histogram of a
   metric shares the same bucket bounds, so N workers' histograms fold
   into one fleet-wide distribution; quantiles are nearest-bucket with
   error bounded by one bucket width (property-tested against exact
   nearest-rank in tests/test_slo.py). Rendering follows Prometheus
   cumulative-bucket semantics.

2. :class:`SloObjective` / :class:`SloRegistry` — declared objectives
   ("fraction of observations with metric <= threshold must be >= target
   over a window"), parsed from the ``DYN_SLO_OBJECTIVES`` grammar or a
   file (``DYN_SLO_FILE``).

3. :class:`SloEngine` — continuous evaluation over any cumulative
   histogram source: windowed attainment, error budget, and SRE-style
   **multi-window burn-rate alerts** (fast + slow windows must both burn
   above ``burn_threshold``), plus the ``ttft_pressure``/``itl_pressure``
   signals the planner's P/D rebalance policy consumes.

4. :class:`GoodputTracker` — per-request met-all-objectives accounting
   (DistServe's serving metric: requests that met their latency
   objectives, not raw tok/s).

``nearest_rank`` is the one shared exact-percentile implementation (the
fleet report's former ad-hoc copy now imports it from here).
"""

from __future__ import annotations

import math
import time
from bisect import bisect_left
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Tuple

# ----------------------------------------------------------------- buckets

# Shared bucket bounds (seconds) for every latency metric: log-spaced from
# token cadence (1 ms) through request scale (minutes). One shared grid is
# what makes cross-worker merging lossless — never change bounds without a
# wire-compat plan (merge refuses mismatched grids instead of guessing).
LATENCY_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0)

# The request-latency metric names dynaslo understands, and what they
# measure. Objectives may only name these (the sync-gate test additionally
# pins each one to a rendered /metrics family).
METRICS: Tuple[str, ...] = ("ttft", "itl", "queue_wait", "e2e")

# Worker roles a latency histogram can be labeled with (dynashard/disagg):
ROLES: Tuple[str, ...] = ("prefill", "decode", "unified")


def nearest_rank(values: List[float], q: float) -> Optional[float]:
    """Deterministic nearest-rank percentile (``q`` in [0, 100]).

    The single exact-percentile implementation in the tree — the fleet
    report and bench both use it, and the Histogram quantile is
    property-tested against it."""
    if not values:
        return None
    vs = sorted(values)
    rank = max(int(math.ceil(q / 100.0 * len(vs))), 1)
    return vs[rank - 1]


class Histogram:
    """Fixed-bucket mergeable histogram (Prometheus cumulative semantics).

    ``counts`` holds per-bucket (NON-cumulative) counts plus a trailing
    +Inf bucket; cumulative sums are derived at render time. Two
    histograms with the same bounds merge losslessly by adding counts."""

    __slots__ = ("ubs", "counts", "sum", "count")

    def __init__(self, ubs: Iterable[float] = LATENCY_BUCKETS):
        self.ubs: Tuple[float, ...] = tuple(ubs)
        self.counts: List[int] = [0] * (len(self.ubs) + 1)
        self.sum: float = 0.0
        self.count: int = 0

    def observe(self, value: float, n: int = 1) -> None:
        """Record ``n`` observations of ``value`` seconds."""
        if n <= 0:
            return
        i = bisect_left(self.ubs, value)
        self.counts[i] += n          # i == len(ubs) → +Inf bucket
        self.sum += value * n
        self.count += n

    def merge(self, other: "Histogram") -> None:
        """Lossless in-place merge; bucket grids must match exactly."""
        if other.ubs != self.ubs:
            raise ValueError(
                f"cannot merge histograms with different bucket grids "
                f"({len(self.ubs)} vs {len(other.ubs)} bounds)")
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.sum += other.sum
        self.count += other.count

    def copy(self) -> "Histogram":
        h = Histogram(self.ubs)
        h.counts = list(self.counts)
        h.sum = self.sum
        h.count = self.count
        return h

    def diff(self, earlier: "Histogram") -> "Histogram":
        """Window view between two snapshots of one cumulative histogram
        (``self`` must be the later snapshot of the same series)."""
        if earlier.ubs != self.ubs:
            raise ValueError("diff across different bucket grids")
        h = Histogram(self.ubs)
        h.counts = [max(a - b, 0)
                    for a, b in zip(self.counts, earlier.counts)]
        h.sum = max(self.sum - earlier.sum, 0.0)
        h.count = max(self.count - earlier.count, 0)
        return h

    def cumulative(self) -> List[int]:
        """Cumulative counts per bound (excluding +Inf; total = count)."""
        out, run = [], 0
        for c in self.counts[:-1]:
            run += c
            out.append(run)
        return out

    def quantile(self, q: float) -> Optional[float]:
        """Nearest-bucket quantile (``q`` in [0, 1]): the upper bound of
        the bucket holding the exact nearest-rank observation — error is
        bounded by one bucket width. Observations past the last bound
        report the last bound (the histogram cannot see further)."""
        if self.count <= 0:
            return None
        rank = max(int(math.ceil(q * self.count)), 1)
        run = 0
        for i, c in enumerate(self.counts[:-1]):
            run += c
            if run >= rank:
                return self.ubs[i]
        return self.ubs[-1]

    def fraction_le(self, threshold: float) -> Optional[float]:
        """Fraction of observations <= ``threshold`` (attainment). The
        threshold is resolved to the largest bucket bound <= threshold,
        so snap objective thresholds onto the grid (see
        :func:`snap_threshold`) for exact evaluation."""
        if self.count <= 0:
            return None
        idx = bisect_left(self.ubs, threshold * (1.0 + 1e-9))
        good = sum(self.counts[:idx])
        return good / self.count

    # ------------------------------------------------------------- wire

    def to_wire(self) -> dict:
        """Compact stats-plane form. Bounds ride along so a peer with a
        different grid fails loudly at merge instead of silently skewing
        fleet quantiles."""
        return {"ubs": list(self.ubs), "counts": list(self.counts),
                "sum": round(self.sum, 6), "count": self.count}

    @classmethod
    def from_wire(cls, d: dict) -> "Histogram":
        h = cls(tuple(d.get("ubs") or LATENCY_BUCKETS))
        counts = list(d.get("counts") or [])
        if len(counts) == len(h.counts):
            h.counts = [int(c) for c in counts]
        h.sum = float(d.get("sum", 0.0))
        h.count = int(d.get("count", 0))
        return h

    # ----------------------------------------------------------- render

    def render_prom(self, name: str, labels: str) -> List[str]:
        """Prometheus text lines (cumulative ``_bucket`` + ``_sum`` +
        ``_count``). ``labels`` is the pre-rendered label body without
        braces (may be empty)."""
        sep = "," if labels else ""
        lines = []
        run = 0
        for i, ub in enumerate(self.ubs):
            run += self.counts[i]
            lines.append(f'{name}_bucket{{{labels}{sep}le="{ub}"}} {run}')
        lines.append(f'{name}_bucket{{{labels}{sep}le="+Inf"}} {self.count}')
        lines.append(f'{name}_sum{{{labels}}} {round(self.sum, 6)}')
        lines.append(f'{name}_count{{{labels}}} {self.count}')
        return lines


def snap_threshold(threshold: float,
                   ubs: Tuple[float, ...] = LATENCY_BUCKETS) -> float:
    """Snap an objective threshold onto the nearest bucket bound (log
    distance) so attainment evaluation is exact rather than bounded."""
    if threshold <= 0:
        return ubs[0]
    best = min(ubs, key=lambda ub: abs(math.log(ub) - math.log(threshold)))
    return best


# ------------------------------------------------------- latency recording


class LatencyRecorder:
    """Per-role latency histograms for one worker (engine-side).

    ``observe`` is host-side counter arithmetic only (no device work, no
    syncs) so it is safe on the engine's hot path. The wire form is
    ``{role: {metric: histogram}}`` so a worker that changes role
    mid-lifetime (fleet P/D rebalance) keeps earlier observations
    attributed to the role that produced them."""

    def __init__(self, role: str = "unified"):
        self.role = role
        self.hists: Dict[str, Dict[str, Histogram]] = {}

    def observe(self, metric: str, value: float, n: int = 1) -> None:
        # bounded-by: keyed by role then metric, both fixed vocabularies
        per_role = self.hists.setdefault(self.role, {})
        h = per_role.get(metric)
        if h is None:
            h = per_role[metric] = Histogram()
        h.observe(value, n)

    def to_wire(self) -> dict:
        return {role: {m: h.to_wire() for m, h in sorted(per.items())}
                for role, per in sorted(self.hists.items())}

    @classmethod
    def wire_to_hists(cls, wire: dict) -> Dict[str, Dict[str, Histogram]]:
        out: Dict[str, Dict[str, Histogram]] = {}
        for role, per in (wire or {}).items():
            out[role] = {m: Histogram.from_wire(d) for m, d in per.items()}
        return out


def merge_latency_wire(wires: Iterable[dict]
                       ) -> Dict[str, Dict[str, Histogram]]:
    """Fold many workers' ``latency_hist`` wire dicts into one
    ``{role: {metric: merged histogram}}`` view (the aggregator's
    fleet-wide latency plane)."""
    merged: Dict[str, Dict[str, Histogram]] = {}
    for wire in wires:
        for role, per in (wire or {}).items():
            dst = merged.setdefault(role, {})
            for metric, d in per.items():
                h = Histogram.from_wire(d)
                if metric in dst:
                    dst[metric].merge(h)
                else:
                    dst[metric] = h
    return merged


def collapse_roles(merged: Dict[str, Dict[str, Histogram]]
                   ) -> Dict[str, Histogram]:
    """Merge a role-labeled latency view down to ``{metric: histogram}``
    (the SLO engine evaluates objectives fleet-wide across roles)."""
    out: Dict[str, Histogram] = {}
    for per in merged.values():
        for metric, h in per.items():
            if metric in out:
                out[metric].merge(h)
            else:
                out[metric] = h.copy()
    return out


# ----------------------------------------------------------- SLO registry


@dataclass(frozen=True)
class SloObjective:
    """One objective: P(metric <= threshold_s) >= target over window_s."""

    name: str
    metric: str            # one of METRICS
    threshold_s: float     # snapped onto the histogram bucket grid
    target: float          # required attainment fraction in (0, 1)
    window_s: float        # error-budget (slow) window, seconds

    def to_dict(self) -> dict:
        return {"name": self.name, "metric": self.metric,
                "threshold_s": self.threshold_s, "target": self.target,
                "window_s": self.window_s}


def parse_objective(spec: str) -> SloObjective:
    """Parse one objective from the grammar

        [name=]metric<=threshold_s@target/window_s

    e.g. ``ttft<=0.5@0.95/300`` ("95% of TTFTs under 500 ms over 5 min")
    or ``tail=itl<=0.1@0.99/600``. The threshold is snapped onto the
    histogram bucket grid so windowed attainment is exact."""
    body = spec.strip()
    if not body:
        raise ValueError("empty SLO objective")
    name = None
    if "=" in body.split("<=", 1)[0]:
        name, body = body.split("=", 1)
        name = name.strip()
    try:
        metric, rest = body.split("<=", 1)
        thr, rest = rest.split("@", 1)
        target, window = rest.split("/", 1)
        metric = metric.strip()
        obj = SloObjective(
            name=name or metric, metric=metric,
            threshold_s=snap_threshold(float(thr)),
            target=float(target), window_s=float(window))
    except ValueError as e:
        raise ValueError(
            f"bad SLO objective {spec!r} (grammar: "
            f"[name=]metric<=threshold_s@target/window_s): {e}") from e
    if obj.metric not in METRICS:
        raise ValueError(f"SLO objective {spec!r}: unknown metric "
                         f"{obj.metric!r} (known: {METRICS})")
    if not 0.0 < obj.target < 1.0:
        raise ValueError(f"SLO objective {spec!r}: target must be in "
                         f"(0, 1), got {obj.target}")
    if obj.window_s <= 0:
        raise ValueError(f"SLO objective {spec!r}: window must be > 0")
    return obj


@dataclass
class SloRegistry:
    """The declared objectives plus the burn-rate alert policy."""

    objectives: List[SloObjective] = field(default_factory=list)
    # fast window = fast_fraction * objective window (SRE multi-window
    # pattern: the fast window catches the spike, the slow window proves
    # it is sustained — both must burn above threshold to alert)
    fast_fraction: float = 0.1
    burn_threshold: float = 2.0

    @classmethod
    def parse(cls, spec: str, *, fast_fraction: Optional[float] = None,
              burn_threshold: Optional[float] = None) -> "SloRegistry":
        objectives = [parse_objective(p)
                      for p in spec.split(";") if p.strip()]
        names = [o.name for o in objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO objective names in {spec!r}")
        reg = cls(objectives=objectives)
        if fast_fraction is not None:
            reg.fast_fraction = fast_fraction
        if burn_threshold is not None:
            reg.burn_threshold = burn_threshold
        return reg

    @classmethod
    def from_env(cls) -> "SloRegistry":
        """Build from DYN_SLO_OBJECTIVES (inline grammar) or DYN_SLO_FILE
        (one objective per line, '#' comments). Absent → empty registry
        (no objectives, histograms still recorded/rendered)."""
        from .config import env_float, env_str

        spec = env_str("DYN_SLO_OBJECTIVES") or ""
        path = env_str("DYN_SLO_FILE")
        if not spec and path:
            # one-shot tiny config read at component construction (the
            # registry is parsed once per Metrics/aggregator instance),
            # not on any serving path — same class as the tracer's
            # JSONL sink
            # dynalint: disable=transitive-blocking-in-async
            with open(path) as f:
                lines = [ln.split("#", 1)[0].strip() for ln in f]
            spec = ";".join(ln for ln in lines if ln)
        return cls.parse(
            spec,
            fast_fraction=env_float("DYN_SLO_FAST_FRACTION"),
            burn_threshold=env_float("DYN_SLO_BURN_THRESHOLD"))

    def for_metric(self, metric: str) -> List[SloObjective]:
        return [o for o in self.objectives if o.metric == metric]

    def to_dict(self) -> dict:
        return {"objectives": [o.to_dict() for o in self.objectives],
                "fast_fraction": self.fast_fraction,
                "burn_threshold": self.burn_threshold}


# -------------------------------------------------------------- SLO engine


class SloEngine:
    """Continuous SLO evaluation over a cumulative-histogram source.

    ``source()`` returns the CURRENT cumulative ``{metric: Histogram}``
    view (fleet-merged at the aggregator, process-local at the frontend).
    ``tick()`` snapshots it; windowed attainment/burn rates are computed
    by diffing the newest snapshot against the one nearest the window
    edge. The clock is injectable: wall time in serving, virtual time in
    the fleet simulator (where seeded runs must stay byte-identical)."""

    def __init__(self, registry: SloRegistry,
                 source: Callable[[], Dict[str, Histogram]],
                 clock: Callable[[], float] = time.monotonic,
                 max_snapshots: int = 512):
        self.registry = registry
        self.source = source
        self.clock = clock
        self.max_snapshots = max_snapshots
        # (t, {metric: Histogram}) snapshots, oldest first
        self._snaps: List[Tuple[float, Dict[str, Histogram]]] = []
        self._alerting: Dict[str, bool] = {}
        self.alert_events: List[dict] = []     # fired/cleared transitions

    # ------------------------------------------------------------ intake

    def tick(self) -> List[dict]:
        """Snapshot the source and re-evaluate every objective. Returns
        the alert transitions (fired/cleared) caused by this tick."""
        now = self.clock()
        snap = {m: h.copy() for m, h in self.source().items()}
        if self._snaps and self._snaps[-1][0] >= now:
            self._snaps[-1] = (now, snap)    # same instant: replace
        else:
            self._snaps.append((now, snap))
        if len(self._snaps) > self.max_snapshots:
            del self._snaps[:len(self._snaps) - self.max_snapshots]
        events = []
        for obj in self.registry.objectives:
            ev = self._evaluate_objective(obj, now)
            was = self._alerting.get(obj.name, False)
            if ev["alert"] != was:
                self._alerting[obj.name] = ev["alert"]
                events.append({"at": round(now, 6), "objective": obj.name,
                               "state": "fired" if ev["alert"]
                               else "cleared",
                               "burn_fast": ev["burn_fast"],
                               "burn_slow": ev["burn_slow"]})
        self.alert_events.extend(events)
        for ev in events:
            if ev["state"] == "fired":
                # burn-rate trip: the canonical dynablack trigger (cold
                # path — at most one transition per objective per tick)
                from . import blackbox
                blackbox.notify_trigger("slo_burn_rate", ev)
        return events

    # -------------------------------------------------------- evaluation

    def _window_hist(self, metric: str, window_s: float,
                     now: float) -> Optional[Histogram]:
        """Observations inside ``[now - window_s, now]``: newest snapshot
        minus the snapshot nearest the window edge (older-or-equal when
        one exists, else the oldest available)."""
        if not self._snaps:
            return None
        latest = self._snaps[-1][1].get(metric)
        if latest is None:
            return None
        cutoff = now - window_s
        base = None
        for t, snap in self._snaps:
            if t <= cutoff:
                base = snap.get(metric)
            else:
                break
        if base is None:
            # window predates history: everything ever seen is "inside"
            base = Histogram(latest.ubs)
        return latest.diff(base)

    def _evaluate_objective(self, obj: SloObjective, now: float) -> dict:
        reg = self.registry
        fast_w = max(obj.window_s * reg.fast_fraction, 1e-9)
        slow = self._window_hist(obj.metric, obj.window_s, now)
        fast = self._window_hist(obj.metric, fast_w, now)
        budget = max(1.0 - obj.target, 1e-9)

        def burn(h: Optional[Histogram]) -> Tuple[Optional[float], float]:
            if h is None or h.count == 0:
                return None, 0.0
            att = h.fraction_le(obj.threshold_s)
            return att, (1.0 - att) / budget

        att_slow, burn_slow = burn(slow)
        att_fast, burn_fast = burn(fast)
        alert = (burn_fast >= reg.burn_threshold
                 and burn_slow >= reg.burn_threshold)
        return {
            "objective": obj.name,
            "metric": obj.metric,
            "threshold_s": obj.threshold_s,
            "target": obj.target,
            "attainment": None if att_slow is None else round(att_slow, 6),
            "attainment_fast": (None if att_fast is None
                                else round(att_fast, 6)),
            "window_count": 0 if slow is None else slow.count,
            "burn_slow": round(burn_slow, 6),
            "burn_fast": round(burn_fast, 6),
            "error_budget_remaining": round(1.0 - burn_slow, 6),
            "alert": alert,
        }

    def evaluate(self) -> Dict[str, dict]:
        """Current evaluation of every objective (keyed by name). Uses
        the snapshots laid down by ``tick()``; call ``tick()`` first when
        driving manually."""
        now = self._snaps[-1][0] if self._snaps else self.clock()
        return {o.name: self._evaluate_objective(o, now)
                for o in self.registry.objectives}

    def pressures(self) -> Dict[str, float]:
        """Planner-facing pressure signals: per metric, the max over its
        objectives of ``min(burn_fast, burn_slow)`` — the continuous
        form of the multi-window alert conjunction, so pressure crosses
        a threshold exactly when the same-threshold alert would fire
        (a fast spike alone, or a stale slow window alone, never
        actuates the planner). The P/D rebalance policy compares
        ``ttft_pressure`` (prefill capacity short) against
        ``itl_pressure`` (decode capacity short)."""
        ev = self.evaluate()
        out = {}
        for metric in METRICS:
            vals = [min(e["burn_fast"], e["burn_slow"])
                    for e in ev.values() if e["metric"] == metric]
            out[f"{metric}_pressure"] = round(max(vals), 6) if vals else 0.0
        return out

    def window_quantiles(self, metric: str, window_s: float,
                         qs: Tuple[float, ...] = (0.5, 0.95, 0.99)
                         ) -> Dict[str, Optional[float]]:
        now = self._snaps[-1][0] if self._snaps else self.clock()
        h = self._window_hist(metric, window_s, now)
        if h is None:
            return {f"p{int(q * 100)}": None for q in qs}
        return {f"p{int(q * 100)}": h.quantile(q) for q in qs}

    def snapshot(self) -> dict:
        """The ``/debug/slo`` payload."""
        return {
            "registry": self.registry.to_dict(),
            "evaluation": self.evaluate(),
            "pressures": self.pressures(),
            "alerts": list(self.alert_events),
        }

    # ------------------------------------------------------------ render

    def render_prom_lines(self, labels: str = "") -> List[str]:
        """Objective gauges for a /metrics plane: attainment, error
        budget, fast/slow burn rates, alert state, pressure signals."""
        if not self.registry.objectives:
            return []
        sep = "," if labels else ""
        lines = [
            "# HELP dyn_slo_attainment windowed fraction of observations "
            "meeting the objective threshold",
            "# TYPE dyn_slo_attainment gauge",
        ]
        ev = self.evaluate()
        for name, e in sorted(ev.items()):
            if e["attainment"] is not None:
                lines.append(f'dyn_slo_attainment{{{labels}{sep}'
                             f'objective="{name}"}} {e["attainment"]}')
        lines.append("# HELP dyn_slo_error_budget_remaining remaining "
                     "error-budget fraction over the objective window "
                     "(1 - slow burn; negative = budget overspent)")
        lines.append("# TYPE dyn_slo_error_budget_remaining gauge")
        for name, e in sorted(ev.items()):
            lines.append(f'dyn_slo_error_budget_remaining{{{labels}{sep}'
                         f'objective="{name}"}} '
                         f'{e["error_budget_remaining"]}')
        lines.append("# HELP dyn_slo_burn_rate error-budget burn rate "
                     "(1.0 = spending exactly the budget)")
        lines.append("# TYPE dyn_slo_burn_rate gauge")
        for name, e in sorted(ev.items()):
            lines.append(f'dyn_slo_burn_rate{{{labels}{sep}'
                         f'objective="{name}",window="fast"}} '
                         f'{e["burn_fast"]}')
            lines.append(f'dyn_slo_burn_rate{{{labels}{sep}'
                         f'objective="{name}",window="slow"}} '
                         f'{e["burn_slow"]}')
        lines.append("# HELP dyn_slo_alert_active multi-window burn-rate "
                     "alert state (1 = both windows burning above "
                     "threshold)")
        lines.append("# TYPE dyn_slo_alert_active gauge")
        for name, e in sorted(ev.items()):
            lines.append(f'dyn_slo_alert_active{{{labels}{sep}'
                         f'objective="{name}"}} {int(e["alert"])}')
        lines.append("# HELP dyn_slo_pressure planner-facing pressure "
                     "signals (max fast burn per metric)")
        lines.append("# TYPE dyn_slo_pressure gauge")
        for sig, val in sorted(self.pressures().items()):
            lines.append(f'dyn_slo_pressure{{{labels}{sep}'
                         f'signal="{sig}"}} {val}')
        return lines


# ----------------------------------------------------------------- goodput


class GoodputTracker:
    """Per-request met-all-objectives accounting.

    A request is *good* when every registered objective whose metric the
    request reported is met (objectives on metrics a request cannot
    report — e.g. TTFT for unary — are skipped for that request)."""

    def __init__(self, registry: SloRegistry):
        self.registry = registry
        self.good = 0
        self.total = 0
        self.misses: Dict[str, int] = {
            o.name: 0 for o in registry.objectives}

    def observe_request(self, metrics: Dict[str, float]) -> bool:
        """``metrics`` maps metric name → the request's scalar (seconds);
        for ITL pass the request's mean gap. Returns the verdict."""
        good = True
        for obj in self.registry.objectives:
            val = metrics.get(obj.metric)
            if val is None:
                continue
            if val > obj.threshold_s:
                self.misses[obj.name] = self.misses.get(obj.name, 0) + 1
                good = False
        self.total += 1
        if good:
            self.good += 1
        return good

    def observe_failed(self) -> None:
        """Count a request that never produced latency metrics (failed /
        shed before serving) — it consumed goodput without being good."""
        self.total += 1

    @property
    def rate(self) -> Optional[float]:
        return self.good / self.total if self.total else None

    def snapshot(self) -> dict:
        return {"good": self.good, "total": self.total,
                "rate": None if self.rate is None else round(self.rate, 6),
                "misses_by_objective": dict(sorted(self.misses.items()))}

    def render_prom_lines(self, labels: str = "") -> List[str]:
        if not self.registry.objectives:
            return []
        sep = "," if labels else ""
        lines = [
            "# HELP dyn_slo_goodput_requests_total requests judged "
            "against the registered objectives (goodput = good/total)",
            "# TYPE dyn_slo_goodput_requests_total counter",
            f'dyn_slo_goodput_requests_total{{{labels}{sep}'
            f'verdict="good"}} {self.good}',
            f'dyn_slo_goodput_requests_total{{{labels}{sep}'
            f'verdict="bad"}} {self.total - self.good}',
            "# HELP dyn_slo_objective_miss_total requests that missed "
            "each objective",
            "# TYPE dyn_slo_objective_miss_total counter",
        ]
        for name, n in sorted(self.misses.items()):
            lines.append(f'dyn_slo_objective_miss_total{{{labels}{sep}'
                         f'objective="{name}"}} {n}')
        return lines


# ------------------------------------------------------------ render helper


def render_role_histograms(merged: Dict[str, Dict[str, Histogram]],
                           prefix: str = "dyn_slo",
                           labels: str = "") -> List[str]:
    """Prometheus text for a role-labeled latency view: one histogram
    family per metric (``<prefix>_<metric>_seconds{role=...}``) plus
    nearest-bucket quantile gauges."""
    lines: List[str] = []
    sep = "," if labels else ""
    metrics = sorted({m for per in merged.values() for m in per})
    for metric in metrics:
        name = f"{prefix}_{metric}_seconds"
        lines.append(f"# HELP {name} fleet-merged {metric} latency "
                     f"(mergeable fixed-bucket histogram, per worker "
                     f"role)")
        lines.append(f"# TYPE {name} histogram")
        for role in sorted(merged):
            h = merged[role].get(metric)
            if h is not None:
                lines.extend(h.render_prom(
                    name, f'{labels}{sep}role="{role}"'))
    if metrics:
        qname = f"{prefix}_latency_quantile_seconds"
        lines.append(f"# HELP {qname} nearest-bucket quantiles of the "
                     f"merged per-role latency histograms (error <= one "
                     f"bucket)")
        lines.append(f"# TYPE {qname} gauge")
        for metric in metrics:
            for role in sorted(merged):
                h = merged[role].get(metric)
                if h is None or h.count == 0:
                    continue
                for q, tag in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
                    lines.append(
                        f'{qname}{{{labels}{sep}metric="{metric}",'
                        f'role="{role}",quantile="{tag}"}} '
                        f'{h.quantile(q)}')
    return lines
