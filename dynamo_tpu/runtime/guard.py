"""dynaguard: end-to-end request deadlines, retry policy, circuit breakers,
and chaos injection for the real transports.

The reference treats failure handling as a serving property, not a
per-call afterthought: a routed request must survive worker churn, and
the disagg path must degrade to local prefill rather than hang (SURVEY
§2.2, §3.3). This module is the one place those policies live:

- :class:`Deadline` — a monotonic budget that travels WITH the request
  (Orca-style per-request SLO; "The Tail at Scale"): accepted at the
  HTTP frontend, stamped into the DCP request envelope and the remote
  prefill queue as ``deadline_ms`` (remaining budget at send time, so
  each hop naturally decrements it), enforced wherever time is actually
  spent. :func:`bound` is the standard await wrapper — every bounded
  wait in the tree goes through it or ``asyncio.wait_for`` (dynalint
  rule DL011 ``unbounded-await`` rejects naked network awaits).
- :class:`RetryPolicy` — bounded attempts with decorrelated-jitter
  backoff, budget-aware: it never sleeps (or retries) past the
  request's deadline. Used by route resolution (``Client.generate``),
  remote-prefill dispatch, and stats scrapes.
- :class:`CircuitBreaker` / :class:`BreakerBoard` — per-endpoint
  closed→open→half-open breakers with deterministic (count-based)
  and/or clock-based probe cadence; the one shared implementation
  behind what used to be the Client's stats-plane quarantine (PR 6)
  and the prefill worker's stale-client eviction (PR 2). State is
  exported as ``dyn_client_breaker_state`` gauges.
- :class:`ChaosInjector` — seeded fault injection on the REAL
  transports (TCP call-home, KV transfer plane): drop, delay, or sever
  frames and kill connections at deterministic points, driven by the
  ``DYN_CHAOS`` scenario string, so ``tests/test_chaos.py`` can run the
  full stack on CPU and assert fail-fast instead of hang.

Chaos spec grammar (documented in docs/robustness.md)::

    DYN_CHAOS = "seed=42;sever:kv.send@after=1;delay:tcp.send@ms=50,p=0.25"

    spec  := [seed=N ';'] rule (';' rule)*
    rule  := action ':' point ['@' param (',' param)*]
    action:= drop | delay | sever
    param := nth=N    fire on exactly the Nth hit of the point (1-based)
           | after=N  fire on every hit >= N
           | p=F      fire with probability F (seeded rng)
           | ms=F     delay duration (delay action)
           | times=N  stop after N fires

Injection points: ``tcp.connect``, ``tcp.send`` (call-home response
plane), ``kv.connect``, ``kv.send``, ``kv.recv`` (KV transfer plane),
plus the worker-scoped points (dynarevive):

- ``worker.kill`` — consulted once per response frame a served endpoint
  streams. A ``sever``/``drop`` fire turns the serving handle into a
  wedged process: every stream on it dies with a raw connection drop (no
  error frame), the request/stats planes go silent, and the lease +
  discovery record stay behind — the exact crash shape mid-stream
  failover and breaker eviction must absorb.
  ``seed=1;sever:worker.kill@nth=4`` kills the worker under the 4th
  streamed frame.
- ``engine.stall`` — consulted once per engine scheduler iteration
  (only when chaos is active; the hot path never pays for it). A
  ``delay`` rule (``delay:engine.stall@ms=250,times=3``) stalls the
  decode loop — the loop-lag monitor, ITL histograms and resume-stall
  measurements all see it.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
import weakref
from dataclasses import dataclass, field
from typing import (Any, AsyncIterator, Awaitable, Callable, Dict, List,
                    Optional, Tuple)

from . import proto
from .config import env_float, env_int, env_str

log = logging.getLogger("dynamo_tpu.guard")


class DeadlineExceeded(asyncio.TimeoutError):
    """The request's end-to-end budget is spent. Subclasses TimeoutError
    so existing ``except asyncio.TimeoutError`` waits handle it; the HTTP
    frontend maps it to 504 with a structured body, streams finish with
    ``finish_reason: "timeout"``."""


class NoCapacity(RuntimeError):
    """No instance can take the request right now (none discovered, or
    every breaker is open). Maps to HTTP 503 + Retry-After — the client
    should back off and retry, unlike a 500."""


# ------------------------------------------------------------------ deadline


class Deadline:
    """Absolute monotonic deadline with an injectable clock.

    The wire representation is the REMAINING budget in ms at encode time
    (:meth:`to_wire_ms`); the receiving hop rebuilds an absolute deadline
    against its own clock (:meth:`from_wire_ms`), so clocks never need to
    agree across hosts and each hop naturally inherits the decremented
    budget.
    """

    __slots__ = ("t_end", "clock")

    def __init__(self, t_end: float,
                 clock: Callable[[], float] = time.monotonic):
        self.t_end = t_end
        self.clock = clock

    @classmethod
    def after_ms(cls, ms: float,
                 clock: Callable[[], float] = time.monotonic) -> "Deadline":
        return cls(clock() + ms / 1000.0, clock)

    @classmethod
    def after_s(cls, seconds: float,
                clock: Callable[[], float] = time.monotonic) -> "Deadline":
        return cls(clock() + seconds, clock)

    @classmethod
    def from_wire_ms(cls, ms: Optional[float],
                     clock: Callable[[], float] = time.monotonic
                     ) -> Optional["Deadline"]:
        """Absent/None/<=0 on the wire = no deadline (legacy peer)."""
        if ms is None or ms <= 0:
            return None
        return cls.after_ms(ms, clock)

    @property
    def expired(self) -> bool:
        return self.clock() >= self.t_end

    def remaining_s(self) -> float:
        return max(0.0, self.t_end - self.clock())

    def remaining_ms(self) -> float:
        return self.remaining_s() * 1000.0

    def to_wire_ms(self) -> int:
        """Remaining budget for the next hop, floored at 1ms so a
        just-about-to-expire request still carries *a* deadline rather
        than silently becoming unbounded."""
        return max(1, int(self.remaining_ms()))

    def cap(self, timeout: Optional[float]) -> float:
        """Bound a per-hop timeout by the remaining budget."""
        rem = self.remaining_s()
        return rem if timeout is None else min(timeout, rem)

    def check(self, what: str = "request") -> None:
        if self.expired:
            counter_inc("dyn_guard_deadline_exceeded_total")
            from . import blackbox
            blackbox.note_deadline()
            raise DeadlineExceeded(f"deadline exceeded before {what}")

    def __repr__(self) -> str:
        return f"Deadline(remaining={self.remaining_s():.3f}s)"


def default_deadline(clock: Callable[[], float] = time.monotonic
                     ) -> Optional[Deadline]:
    """Process-default request deadline from DYN_REQUEST_DEADLINE_MS
    (0/unset = no implicit deadline)."""
    ms = env_float("DYN_REQUEST_DEADLINE_MS", 0.0) or 0.0
    return Deadline.after_ms(ms, clock) if ms > 0 else None


async def bound(awaitable: Awaitable, *, timeout: Optional[float] = None,
                deadline: Optional[Deadline] = None,
                what: str = "wait") -> Any:
    """The standard bounded await: ``min(timeout, deadline remaining)``.

    Raises :class:`DeadlineExceeded` when the deadline (not the plain
    timeout) is what ran out, so callers and the HTTP layer can
    distinguish budget exhaustion (504/"timeout") from a slow hop
    (retryable). This wrapper is one of the guards dynalint rule DL011
    recognizes on network awaits.
    """
    if deadline is not None:
        if deadline.expired:
            # never awaited: close the coroutine so it doesn't warn
            close = getattr(awaitable, "close", None)
            if close is not None:
                close()
            deadline.check(what)
        eff = deadline.cap(timeout)
    else:
        eff = timeout
    if eff is None:
        return await awaitable
    try:
        return await asyncio.wait_for(awaitable, eff)
    except asyncio.TimeoutError:
        if deadline is not None and deadline.expired:
            counter_inc("dyn_guard_deadline_exceeded_total")
            from . import blackbox
            blackbox.note_deadline()
            raise DeadlineExceeded(f"deadline exceeded during {what}") \
                from None
        raise


# --------------------------------------------------------------- retry policy


@dataclass
class RetryPolicy:
    """Bounded retries with decorrelated-jitter backoff, budget-aware.

    ``attempts(deadline)`` is an async generator yielding attempt indices
    (0-based); it sleeps the backoff BETWEEN attempts and stops early
    when the remaining deadline budget cannot cover the next backoff —
    a retry that must overrun the deadline is never issued.
    """

    max_attempts: int = 3
    base_s: float = 0.05
    cap_s: float = 2.0
    rng: random.Random = field(default_factory=random.Random)
    sleep: Callable[[float], Awaitable[None]] = asyncio.sleep

    @classmethod
    def from_env(cls, rng: Optional[random.Random] = None) -> "RetryPolicy":
        return cls(
            max_attempts=env_int("DYN_RETRY_MAX_ATTEMPTS", 3) or 1,
            base_s=(env_float("DYN_RETRY_BASE_MS", 50.0) or 50.0) / 1000.0,
            cap_s=(env_float("DYN_RETRY_CAP_MS", 2000.0) or 2000.0) / 1000.0,
            rng=rng if rng is not None else random.Random())

    def next_backoff(self, prev: Optional[float]) -> float:
        """Decorrelated jitter (AWS architecture-blog variant):
        ``min(cap, uniform(base, prev * 3))``."""
        hi = self.base_s if prev is None else prev * 3.0
        return min(self.cap_s, self.rng.uniform(self.base_s, max(hi, self.base_s)))

    async def attempts(self, deadline: Optional[Deadline] = None
                       ) -> AsyncIterator[int]:
        backoff: Optional[float] = None
        for i in range(max(1, self.max_attempts)):
            if deadline is not None and deadline.expired:
                if i == 0:
                    deadline.check("first attempt")
                return  # budget spent mid-retry: stop, caller raises last error
            yield i
            if i + 1 >= max(1, self.max_attempts):
                return
            backoff = self.next_backoff(backoff)
            if deadline is not None and deadline.remaining_s() <= backoff:
                return  # never retry past the deadline
            counter_inc("dyn_guard_retries_total")
            await self.sleep(backoff)

    async def run(self, fn: Callable[[], Awaitable[Any]], *,
                  deadline: Optional[Deadline] = None,
                  retry_on: Tuple[type, ...] = (Exception,),
                  what: str = "operation") -> Any:
        """Call ``fn`` under the policy; re-raises the last error when
        attempts (or budget) run out. CancelledError and
        DeadlineExceeded always propagate immediately."""
        last: Optional[BaseException] = None
        async for attempt in self.attempts(deadline):
            try:
                return await fn()
            except asyncio.CancelledError:
                raise
            except DeadlineExceeded:
                raise
            except retry_on as exc:  # noqa: PERF203 — retry loop
                last = exc
                log.debug("%s attempt %d failed: %r", what, attempt, exc)
        if last is None:
            raise DeadlineExceeded(f"no budget left for {what}")
        raise last


# ------------------------------------------------------------ circuit breaker

BREAKER_CLOSED = 0
BREAKER_OPEN = 1
BREAKER_HALF_OPEN = 2

_STATE_NAMES = {BREAKER_CLOSED: "closed", BREAKER_OPEN: "open",
                BREAKER_HALF_OPEN: "half_open"}


@dataclass(frozen=True)
class BreakerConfig:
    """``threshold`` consecutive failures open the breaker; an open
    breaker offers a single half-open probe every ``probe_every``-th
    denied call (deterministic, works on stepped/virtual time) and/or
    once ``reset_after_s`` has elapsed (0 = count-based only)."""

    threshold: int = 3
    probe_every: int = 5
    reset_after_s: float = 0.0

    @classmethod
    def from_env(cls) -> "BreakerConfig":
        return cls(threshold=env_int("DYN_BREAKER_THRESHOLD", 3) or 3,
                   probe_every=env_int("DYN_BREAKER_PROBE_EVERY", 5) or 5,
                   reset_after_s=env_float("DYN_BREAKER_RESET_S", 0.0) or 0.0)


class CircuitBreaker:
    """closed → open after N consecutive failures → half-open single
    probe → closed on success / open on failure. Clock injectable for
    deterministic tests."""

    __slots__ = ("cfg", "clock", "state", "failures", "opened_at",
                 "denied_since_open", "opened_total", "_probe_inflight")

    def __init__(self, cfg: Optional[BreakerConfig] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.cfg = cfg or BreakerConfig()
        self.clock = clock
        self.state = BREAKER_CLOSED
        self.failures = 0
        self.opened_at = 0.0
        self.denied_since_open = 0
        self.opened_total = 0
        self._probe_inflight = False

    def allow(self) -> bool:
        """May a call go through now? In OPEN, denials are counted and
        every ``probe_every``-th one (or clock expiry) converts to the
        single half-open probe permit."""
        if self.state == BREAKER_CLOSED:
            return True
        if self.state == BREAKER_HALF_OPEN:
            if not self._probe_inflight:
                self._probe_inflight = True  # proto: breaker half_open->half_open
                return True
            return False
        # OPEN
        self.denied_since_open += 1  # proto: breaker open->open
        due = (self.cfg.probe_every > 0
               and self.denied_since_open % self.cfg.probe_every == 0)
        if self.cfg.reset_after_s > 0 and \
                self.clock() - self.opened_at >= self.cfg.reset_after_s:
            due = True
        if due:
            proto.step("breaker", "open", "half_open")
            self.state = BREAKER_HALF_OPEN
            self._probe_inflight = True
            return True
        return False

    def release_probe(self) -> None:
        """A half-open permit was granted but the caller chose a
        different instance: hand the single probe slot back."""
        if self.state == BREAKER_HALF_OPEN:
            self._probe_inflight = False  # proto: breaker half_open->half_open

    def record_success(self) -> None:
        # proto: breaker closed|open|half_open->closed
        self.state = BREAKER_CLOSED
        self.failures = 0
        self.denied_since_open = 0
        self._probe_inflight = False

    def record_failure(self) -> None:
        if self.state == BREAKER_HALF_OPEN:
            self._open()  # failed probe: straight back to open
            return
        self.failures += 1
        if self.state == BREAKER_CLOSED and \
                self.failures >= self.cfg.threshold:
            self._open()

    def _open(self) -> None:
        self.state = BREAKER_OPEN  # proto: breaker closed|half_open->open
        self.opened_at = self.clock()
        self.opened_total += 1
        self.denied_since_open = 0
        self._probe_inflight = False
        # a breaker opening IS the incident; cold path by definition
        from . import blackbox
        blackbox.notify_trigger("breaker_open", {
            "failures": self.failures,
            "opened_total": self.opened_total,
        })

    def reset(self) -> None:
        """External evidence of recovery (fresh discovery put): close."""
        self.record_success()

    @property
    def state_name(self) -> str:
        return _STATE_NAMES[self.state]


# every live board, for the dyn_client_breaker_state exposition
_BOARDS: "weakref.WeakSet[BreakerBoard]" = weakref.WeakSet()


class BreakerBoard:
    """Keyed breaker collection for one client (key = (plane, id))."""

    def __init__(self, name: str, cfg: Optional[BreakerConfig] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self.cfg = cfg or BreakerConfig.from_env()
        self.clock = clock
        # shared by every task routing/scraping through one client; all
        # board methods are sync (atomic under the event loop), and
        # dynarace rejects any future access that straddles an await
        self.breakers: Dict[Tuple[str, Any], CircuitBreaker] = {}  # guarded-by: loop
        _BOARDS.add(self)

    def get(self, plane: str, key: Any) -> CircuitBreaker:
        br = self.breakers.get((plane, key))
        if br is None:
            br = CircuitBreaker(self.cfg, self.clock)
            self.breakers[(plane, key)] = br
        return br

    def drop(self, plane: str, key: Any) -> None:
        self.breakers.pop((plane, key), None)

    def reset(self, plane: str, key: Any) -> None:
        br = self.breakers.get((plane, key))
        if br is not None:
            br.reset()

    def not_closed(self, plane: str) -> List[Any]:
        return sorted(
            (k for (p, k), br in self.breakers.items()
             if p == plane and br.state != BREAKER_CLOSED),
            key=repr)

    def opened_total(self, plane: Optional[str] = None) -> int:
        return sum(br.opened_total for (p, _k), br in self.breakers.items()
                   if plane is None or p == plane)

    def states(self) -> Dict[Tuple[str, Any], int]:
        return {k: br.state for k, br in self.breakers.items()}


# ------------------------------------------------------------------- metrics
# Minimal process-wide counters for the guard plane (route fallbacks,
# hedged re-dispatches, chaos fires, deadline exhaustions). Rendered into
# both the HTTP-service /metrics and the aggregator exposition.

_COUNTERS: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}


def counter_inc(name: str, value: float = 1.0, **labels: str) -> None:
    key = (name, tuple(sorted(labels.items())))
    _COUNTERS[key] = _COUNTERS.get(key, 0.0) + value


def counter_value(name: str, **labels: str) -> float:
    return _COUNTERS.get((name, tuple(sorted(labels.items()))), 0.0)


def reset_counters() -> None:
    """Test hook."""
    _COUNTERS.clear()


def counters_snapshot() -> Dict[str, float]:
    """Guard-plane counters as one flat JSON-safe dict (dynablack incident
    bundles). Label sets fold into the key: ``name{k="v"}``."""
    out: Dict[str, float] = {}
    for (name, labels), val in sorted(_COUNTERS.items()):
        if labels:
            lbl = ",".join(f'{k}="{v}"' for k, v in labels)
            out[f"{name}{{{lbl}}}"] = val
        else:
            out[name] = val
    return out


def boards_snapshot() -> Dict[str, Dict[str, Any]]:
    """Per-board breaker state for dynablack incident bundles: state name,
    consecutive failures and lifetime opens per (plane, instance)."""
    out: Dict[str, Dict[str, Any]] = {}
    for board in sorted(_BOARDS, key=lambda b: b.name):
        rows: Dict[str, Any] = {}
        for (plane, key), br in sorted(board.breakers.items(),
                                       key=lambda kv: repr(kv[0])):
            ident = f"{key:x}" if isinstance(key, int) else str(key)
            rows[f"{plane}/{ident}"] = {
                "state": br.state_name,
                "failures": br.failures,
                "opened_total": br.opened_total,
            }
        out[board.name] = rows
    return out


def render_prom_lines() -> List[str]:
    """Guard-plane exposition: the named counters plus one
    ``dyn_client_breaker_state`` gauge per (board, plane, instance)."""
    lines: List[str] = []
    by_name: Dict[str, List[str]] = {}
    for (name, labels), val in sorted(_COUNTERS.items()):
        lbl = ",".join(f'{k}="{v}"' for k, v in labels)
        v = int(val) if float(val).is_integer() else val
        by_name.setdefault(name, []).append(
            f"{name}{{{lbl}}} {v}" if lbl else f"{name} {v}")
    for name in sorted(by_name):
        lines.append(f"# HELP {name} dynaguard counter")
        lines.append(f"# TYPE {name} counter")
        lines.extend(by_name[name])
    rows = []
    for board in sorted(_BOARDS, key=lambda b: b.name):
        for (plane, key), state in sorted(board.states().items(),
                                          key=lambda kv: repr(kv[0])):
            ident = f"{key:x}" if isinstance(key, int) else str(key)
            rows.append(
                f'dyn_client_breaker_state{{board="{board.name}",'
                f'plane="{plane}",instance="{ident}"}} {state}')
    if rows:
        lines.append("# HELP dyn_client_breaker_state per-endpoint circuit "
                     "breaker state (0=closed, 1=open, 2=half_open)")
        lines.append("# TYPE dyn_client_breaker_state gauge")
        lines.extend(rows)
    return lines


# ------------------------------------------------------------------- chaos


class ChaosError(ConnectionError):
    """Raised by a ``drop`` rule: the transport pretends the peer died."""


@dataclass
class ChaosRule:
    action: str                      # drop | delay | sever
    point: str                       # e.g. kv.send
    nth: Optional[int] = None        # fire on exactly the Nth hit
    after: Optional[int] = None      # fire on every hit >= N
    p: Optional[float] = None        # fire probability (seeded rng)
    ms: float = 0.0                  # delay duration
    times: Optional[int] = None      # max fires
    hits: int = 0
    fired: int = 0

    def should_fire(self, rng: random.Random) -> bool:
        self.hits += 1
        if self.times is not None and self.fired >= self.times:
            return False
        if self.nth is not None and self.hits != self.nth:
            return False
        if self.after is not None and self.hits < self.after:
            return False
        if self.p is not None and rng.random() >= self.p:
            return False
        self.fired += 1
        return True


_ACTIONS = ("drop", "delay", "sever")


def parse_chaos(spec: str) -> Tuple[int, List[ChaosRule]]:
    """Parse a ``DYN_CHAOS`` scenario string (grammar in the module
    docstring); raises ValueError on malformed specs so a typo fails the
    process loudly instead of silently running without chaos."""
    seed = 0
    rules: List[ChaosRule] = []
    for part in (p.strip() for p in spec.split(";") if p.strip()):
        if part.startswith("seed="):
            seed = int(part[len("seed="):])
            continue
        head, _, params = part.partition("@")
        action, _, point = head.partition(":")
        if action not in _ACTIONS or not point:
            raise ValueError(
                f"bad chaos rule {part!r}: want action:point[@params] "
                f"with action in {_ACTIONS}")
        rule = ChaosRule(action=action, point=point)
        for kv in (p.strip() for p in params.split(",") if p.strip()):
            k, _, v = kv.partition("=")
            if k == "nth":
                rule.nth = int(v)
            elif k == "after":
                rule.after = int(v)
            elif k == "p":
                rule.p = float(v)
            elif k == "ms":
                rule.ms = float(v)
            elif k == "times":
                rule.times = int(v)
            else:
                raise ValueError(f"bad chaos param {kv!r} in {part!r}")
        rules.append(rule)
    return seed, rules


class ChaosInjector:
    """Seeded fault injector the transport layers consult at their
    named points (see :func:`chaos_point`)."""

    def __init__(self, spec: str):
        self.spec = spec
        seed, self.rules = parse_chaos(spec)
        self.rng = random.Random(seed)
        self.injected: Dict[Tuple[str, str], int] = {}

    async def point(self, name: str, writer=None) -> None:
        for rule in self.rules:
            if rule.point != name:
                continue
            if not rule.should_fire(self.rng):
                continue
            self.injected[(name, rule.action)] = \
                self.injected.get((name, rule.action), 0) + 1
            counter_inc("dyn_guard_chaos_injections_total",
                        point=name, action=rule.action)
            log.warning("chaos: %s at %s (hit %d)", rule.action, name,
                        rule.hits)
            if rule.action == "delay":
                await asyncio.sleep(rule.ms / 1000.0)
            elif rule.action == "drop":
                raise ChaosError(f"chaos: dropped at {name}")
            elif rule.action == "sever":
                if writer is not None:
                    try:
                        writer.close()
                    except Exception:  # noqa: BLE001 — already dead is fine
                        log.debug("chaos sever: close failed", exc_info=True)
                raise ConnectionResetError(f"chaos: severed at {name}")


# module-level injector, parsed lazily from DYN_CHAOS; tests swap it via
# set_chaos(). ``False`` = not yet resolved (None is a valid resolution).
_CHAOS: Any = False


def chaos() -> Optional[ChaosInjector]:
    global _CHAOS
    if _CHAOS is False:
        spec = env_str("DYN_CHAOS")
        _CHAOS = ChaosInjector(spec) if spec else None
    return _CHAOS


def set_chaos(spec: Optional[str]) -> Optional[ChaosInjector]:
    """Install (or clear, with None) the process chaos injector — the
    test hook; production resolves DYN_CHAOS on first use."""
    global _CHAOS
    _CHAOS = ChaosInjector(spec) if spec else None
    return _CHAOS


async def chaos_point(name: str, writer=None) -> None:
    """Transport-layer hook: no-op unless a chaos rule targets ``name``.
    ``writer`` (if given) is the connection a ``sever`` rule kills."""
    c = chaos()
    if c is not None:
        await c.point(name, writer)
