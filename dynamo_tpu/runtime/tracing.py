"""dyntrace: dependency-free distributed request tracing.

The reference stack threads Rust ``tracing`` spans through every hop
(frontend → router → worker → transfer). This module is the TPU port's
equivalent: a Dapper-style propagated-context tracer (Sigelman et al.,
2010) with

- **Spans** — ``trace_id``/``span_id``/``parent_id``, monotonic
  start/end, free-form attributes. Finished spans land in a bounded
  in-memory ring; nothing here allocates device memory or imports
  anything beyond the stdlib.
- **Propagation** — a contextvar carries the current span along the
  asyncio task tree; process hops carry a tiny ``{"trace_id", "span_id"}``
  dict (``current_trace_ctx()``) inside the existing request envelopes
  (DCP request plane, prefill queue, KV transfer frames) and W3C
  ``traceparent`` headers on the HTTP edge. Absent field = no parent, so
  old wire peers interoperate unchanged.
- **Sampling** — ``DYN_TRACE_SAMPLE`` (0..1) decides per ROOT span;
  children always follow their parent so a sampled trace is complete.
  At 0 every ``start_span`` returns a no-op span: no ring writes, no
  envelope growth, no JSONL IO.
- **Export** — ``DYN_TRACE_JSONL=<path>`` appends one JSON object per
  finished span (schema in docs/observability.md), joinable across
  processes on ``trace_id``.

Retrieval: the HTTP frontend serves ``/v1/traces`` and
``/v1/traces/{request_id}`` straight from this ring (plus the engine
step timelines registered here).
"""

from __future__ import annotations

import contextvars
import json
import random
import threading
import time
import uuid
import weakref
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional

from .config import env_float, env_int, env_str

_JSON_SCALARS = (str, int, float, bool, type(None))


def json_safe(value: Any) -> Any:
    """Coerce ``value`` to JSON-serializable types (the dyntrace export
    and dynablack incident-bundle serializer). Scalars pass through,
    containers recurse, bytes decode (hex on failure), everything else
    becomes its ``repr`` string — so ``json.dumps`` of the result never
    raises and ``json.loads`` round-trips what jq/ingest pipelines see."""
    if isinstance(value, _JSON_SCALARS):
        return value
    if isinstance(value, dict):
        return {str(k): json_safe(v) for k, v in value.items()}
    if isinstance(value, (list, tuple, set, frozenset)):
        return [json_safe(v) for v in value]
    if isinstance(value, (bytes, bytearray, memoryview)):
        raw = bytes(value)
        try:
            return raw.decode("utf-8")
        except UnicodeDecodeError:
            return raw.hex()
    return repr(value)


_current: contextvars.ContextVar = contextvars.ContextVar(
    "dyn_trace_span", default=None)
_request_id: contextvars.ContextVar = contextvars.ContextVar(
    "dyn_request_id", default=None)

# sentinel: "no explicit parent given — use the ambient contextvar"
_AMBIENT = object()


def bind_request_id(request_id: Optional[str]) -> None:
    """Bind the current request id for log correlation (independent of
    sampling: logs carry the id even when the trace is not recorded)."""
    _request_id.set(request_id)


def current_request_id() -> Optional[str]:
    return _request_id.get()


def current_span():
    """The ambient (recording) span, or None — lets instrumented code
    attach attributes to whatever span encloses it without threading span
    objects through every call signature (dynashard stamps the serving
    replica/mesh this way)."""
    cur = _current.get()
    return cur if cur is not None and cur.recording else None


class NoopSpan:
    """Returned when a span is not sampled. Absorbs the full Span API at
    near-zero cost and suppresses descendant sampling decisions by
    becoming the ambient span inside its ``with`` block."""

    __slots__ = ("_token",)

    recording = False
    trace_id: Optional[str] = None
    span_id: Optional[str] = None
    parent_id: Optional[str] = None
    name = ""
    attributes: Dict[str, Any] = {}

    def set_attribute(self, key: str, value: Any) -> None:
        pass

    def end(self) -> None:
        pass

    def __enter__(self) -> "NoopSpan":
        self._token = _current.set(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        try:
            _current.reset(self._token)
        except ValueError:
            pass  # closed from a different context (asyncgen finalizer)


class Span:
    """One recorded operation. Use as a context manager (becomes the
    ambient parent for spans started inside the block) or call ``end()``
    explicitly — dynalint rule ``span-not-closed`` enforces one of the
    two."""

    __slots__ = ("_tracer", "trace_id", "span_id", "parent_id", "name",
                 "start", "wall_start", "end_time", "attributes", "_token")

    recording = True

    def __init__(self, tracer: "Tracer", trace_id: str, span_id: str,
                 parent_id: Optional[str], name: str,
                 attributes: Optional[dict] = None):
        self._tracer = tracer
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start = time.monotonic()
        self.wall_start = time.time()
        self.end_time: Optional[float] = None
        # attrs are coerced JSON-safe at RECORD time (not export): a span
        # carrying a jax array / dataclass / bytes must never leak a
        # Python repr into the JSONL export or an incident bundle
        self.attributes: Dict[str, Any] = (
            {k: json_safe(v) for k, v in attributes.items()}
            if attributes else {})
        self._token = None

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = json_safe(value)

    @property
    def duration_s(self) -> Optional[float]:
        return None if self.end_time is None else self.end_time - self.start

    def end(self) -> None:
        if self.end_time is not None:
            return  # idempotent
        self.end_time = time.monotonic()
        self._tracer._finish(self)

    def __enter__(self) -> "Span":
        self._token = _current.set(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None and "error" not in self.attributes:
            self.attributes["error"] = repr(exc)
        if self._token is not None:
            try:
                _current.reset(self._token)
            except ValueError:
                pass  # closed from a different context (asyncgen finalizer)
            self._token = None
        self.end()

    def to_dict(self) -> dict:
        d = {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_ms": round(self.wall_start * 1000.0, 3),
            "duration_ms": (round(self.duration_s * 1000.0, 3)
                            if self.end_time is not None else None),
            "attributes": self.attributes,
        }
        return d


class Tracer:
    """Process-wide span recorder: bounded ring of finished spans, a
    request-id → trace-id join table, optional JSONL export, and span-end
    listeners (the metrics plane hooks per-stage histograms here)."""

    def __init__(self, sample: Optional[float] = None,
                 ring: Optional[int] = None,
                 jsonl: Optional[str] = None):
        if sample is None:
            sample = env_float("DYN_TRACE_SAMPLE")
        if ring is None:
            ring = env_int("DYN_TRACE_RING")
        if jsonl is None:
            jsonl = env_str("DYN_TRACE_JSONL")
        self.sample = float(sample)
        self.ring_size = max(int(ring), 1)
        self._spans: deque = deque(maxlen=self.ring_size)
        self._by_request: "OrderedDict[str, str]" = OrderedDict()
        self._lock = threading.Lock()
        self._listeners: List[Callable[[Span], None]] = []
        # one-shot, knob-gated export-file open at (lazy) tracer
        # construction; all later writes are buffered appends. Opening
        # eagerly at import would charge every process the handle even
        # with export off.
        # dynalint: disable=transitive-blocking-in-async
        self._fh = open(jsonl, "a", encoding="utf-8") if jsonl else None
        self.spans_recorded = 0

    # ------------------------------------------------------------- creation

    def start_span(self, name: str, *, parent: Any = _AMBIENT,
                   attributes: Optional[dict] = None,
                   request_id: Optional[str] = None):
        """Start a span. ``parent`` is, in order of precedence: an explicit
        Span, a wire ctx dict (``{"trace_id", "span_id"}``), ``None``
        (force a new root), or — by default — the ambient span set by an
        enclosing ``with``. Returns a NoopSpan when the trace is not
        sampled."""
        if parent is _AMBIENT:
            parent = _current.get()
        if isinstance(parent, dict):
            trace_id = parent.get("trace_id")
            parent_id = parent.get("span_id")
            if not trace_id:
                parent = None
            else:
                return self._make(name, trace_id, parent_id, attributes,
                                  request_id)
        if isinstance(parent, Span):
            return self._make(name, parent.trace_id, parent.span_id,
                              attributes, request_id)
        if isinstance(parent, NoopSpan):
            return NoopSpan()
        # root: the sampling decision happens exactly here
        if self.sample <= 0.0 or (self.sample < 1.0
                                  and random.random() >= self.sample):
            return NoopSpan()
        return self._make(name, uuid.uuid4().hex, None, attributes,
                          request_id)

    def _make(self, name, trace_id, parent_id, attributes, request_id):
        span = Span(self, trace_id, uuid.uuid4().hex[:16], parent_id, name,
                    attributes)
        if request_id is not None:
            span.attributes["request_id"] = request_id
            with self._lock:
                self._by_request[request_id] = trace_id
                while len(self._by_request) > self.ring_size:
                    self._by_request.popitem(last=False)
        return span

    def record_span(self, name: str, seconds: float, *,
                    parent: Any = _AMBIENT,
                    attributes: Optional[dict] = None) -> None:
        """Synthesize an already-finished span of the given duration ending
        now — how measured stage accumulators (TransferStats deltas) are
        adopted as child spans without wrapping their interleaved code."""
        span = self.start_span(name, parent=parent, attributes=attributes)
        if not span.recording:
            return
        span.start = time.monotonic() - seconds
        span.wall_start = time.time() - seconds
        span.end()

    def current_trace_ctx(self) -> Optional[dict]:
        """Wire form of the ambient span, or None when nothing is being
        recorded — callers must then OMIT the field entirely (no envelope
        growth with sampling off)."""
        cur = _current.get()
        if cur is None or not cur.recording:
            return None
        return {"trace_id": cur.trace_id, "span_id": cur.span_id}

    # ------------------------------------------------------------ recording

    def add_listener(self, fn: Callable[[Span], None]) -> None:
        """Bound methods are held weakly so a dead owner (e.g. a stopped
        HttpService) silently drops off the fan-out list."""
        if hasattr(fn, "__self__"):
            self._listeners.append(weakref.WeakMethod(fn))
        else:
            self._listeners.append(fn)

    def _finish(self, span: Span) -> None:
        line = None
        if self._fh is not None:
            # attrs were coerced at record time; json_safe as the dumps
            # fallback covers direct attribute-dict mutation so the
            # export stays parseable JSON no matter what (never repr)
            line = json.dumps(span.to_dict(), default=json_safe) + "\n"
        with self._lock:
            self._spans.append(span)
            self.spans_recorded += 1
            if line is not None:
                try:
                    self._fh.write(line)
                    self._fh.flush()
                except (OSError, ValueError):
                    self._fh = None  # export is best-effort; never raise
        for entry in list(self._listeners):
            fn = entry() if isinstance(entry, weakref.ref) else entry
            if fn is None:
                try:
                    self._listeners.remove(entry)
                except ValueError:
                    pass
                continue
            try:
                fn(span)
            # a log call here could recurse through the logging filter back
            # into the tracer, so listener errors are dropped outright
            # dynalint: disable=swallowed-loop-error
            except Exception:  # noqa: BLE001 — listeners must not break spans
                pass

    # ------------------------------------------------------------ retrieval

    def snapshot(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def trace_id_for_request(self, request_id: str) -> Optional[str]:
        with self._lock:
            return self._by_request.get(request_id)

    def get_trace(self, trace_id: str) -> List[dict]:
        """All finished spans of one trace, oldest-first."""
        spans = [s for s in self.snapshot() if s.trace_id == trace_id]
        spans.sort(key=lambda s: s.start)
        return [s.to_dict() for s in spans]

    def get_request_trace(self, request_id: str) -> Optional[dict]:
        """The /v1/traces/{request_id} payload: flat spans (parent links
        intact) plus a per-stage duration rollup."""
        trace_id = self.trace_id_for_request(request_id)
        if trace_id is None:
            return None
        spans = self.get_trace(trace_id)
        stages: Dict[str, float] = {}
        for s in spans:
            if s["duration_ms"] is not None:
                stages[s["name"]] = (stages.get(s["name"], 0.0)
                                     + s["duration_ms"])
        return {"request_id": request_id, "trace_id": trace_id,
                "spans": spans,
                "stages": {k: round(v, 3) for k, v in stages.items()}}

    def traces_summary(self, limit: int = 100,
                       since_ms: Optional[float] = None) -> List[dict]:
        """Newest-first one-line-per-trace summaries for /v1/traces.
        ``since_ms`` (wall-clock epoch ms) drops spans that started
        earlier — the incremental-poll / incident-window filter."""
        by_trace: "OrderedDict[str, dict]" = OrderedDict()
        earliest: Dict[str, Span] = {}
        for s in self.snapshot():
            if since_ms is not None and s.wall_start * 1000.0 < since_ms:
                continue
            e = by_trace.setdefault(s.trace_id, {
                "trace_id": s.trace_id, "request_id": None, "root": None,
                "spans": 0, "duration_ms": 0.0, "start_ms": None})
            e["spans"] += 1
            rid = s.attributes.get("request_id")
            if rid is not None:
                e["request_id"] = rid
            # representative span: a true root wins; otherwise the
            # earliest local span (the trace may have been rooted in
            # another process via traceparent/envelope ctx)
            cur = earliest.get(s.trace_id)
            if cur is None or (cur.parent_id is not None
                               and (s.parent_id is None
                                    or s.start < cur.start)):
                earliest[s.trace_id] = s
        for tid, s in earliest.items():
            e = by_trace[tid]
            e["root"] = s.name
            e["duration_ms"] = s.to_dict()["duration_ms"]
            e["start_ms"] = round(s.wall_start * 1000.0, 3)
        return list(by_trace.values())[-limit:][::-1]


# ------------------------------------------------------------ global tracer

_tracer: Optional[Tracer] = None
_tracer_lock = threading.Lock()


def get_tracer() -> Tracer:
    global _tracer
    if _tracer is None:
        with _tracer_lock:
            if _tracer is None:
                _tracer = Tracer()
    return _tracer


def configure(sample: Optional[float] = None, ring: Optional[int] = None,
              jsonl: Optional[str] = None) -> Tracer:
    """Replace the process tracer (tests, CLI flags). Returns it."""
    global _tracer
    with _tracer_lock:
        _tracer = Tracer(sample=sample, ring=ring, jsonl=jsonl)
    return _tracer


# --------------------------------------------------------- traceparent edge

def parse_traceparent(value: Optional[str]) -> Optional[dict]:
    """W3C ``traceparent`` (``00-<32hex>-<16hex>-<2hex>``) → wire ctx dict,
    or None for absent/malformed/unsampled headers."""
    if not value:
        return None
    parts = value.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        sampled = int(flags, 16) & 1
        int(trace_id, 16)
        int(span_id, 16)
    except ValueError:
        return None
    if not sampled or set(trace_id) == {"0"}:
        return None
    return {"trace_id": trace_id, "span_id": span_id}


def format_traceparent(span) -> Optional[str]:
    if span is None or not span.recording:
        return None
    return f"00-{span.trace_id}-{span.span_id}-01"


# ------------------------------------------------------ engine step timeline

class StepTimeline:
    """Bounded ring of engine scheduler events (per-step queue-wait, batch
    occupancy, tokens/step, spec accepts). Appends are cheap dict pushes —
    safe from the engine's executor thread; ``capacity=0`` disables.

    Each record stores a MONOTONIC offset (``mono_ms``) from one
    wall/monotonic anchor pair stamped once at ring construction; the
    wall ``ts_ms`` is derived at export (``anchor_wall + mono_ms``).
    Per-record ``time.time()`` stamps (the old scheme) drift under NTP
    slew and carry no monotonic companion, so timelines from different
    workers could not be ordered against each other in /v1/traces
    rollups — the anchor pair makes cross-worker alignment a single
    per-ring offset subtraction."""

    def __init__(self, capacity: int):
        self._q: Optional[deque] = (deque(maxlen=capacity)
                                    if capacity > 0 else None)
        # the per-ring anchor pair: monotonic for intervals, wall for
        # cross-worker alignment (stamped together, once)
        self.anchor_monotonic = time.monotonic()
        self.anchor_wall = time.time()

    @property
    def enabled(self) -> bool:
        return self._q is not None

    def add(self, kind: str, **fields: Any) -> None:
        if self._q is not None:
            fields["mono_ms"] = round(
                (time.monotonic() - self.anchor_monotonic) * 1000.0, 3)
            fields["kind"] = kind
            self._q.append(fields)

    def snapshot(self, limit: Optional[int] = None,
                 since_ms: Optional[float] = None) -> List[dict]:
        """Newest ``limit`` events with derived wall ``ts_ms``;
        ``since_ms`` (wall epoch ms) drops older events first."""
        if self._q is None:
            return []
        items = list(self._q)
        base = self.anchor_wall * 1000.0
        out = [{**e, "ts_ms": round(base + e["mono_ms"], 3)}
               for e in items]
        if since_ms is not None:
            out = [e for e in out if e["ts_ms"] >= since_ms]
        if limit:
            out = out[-limit:]
        return out

    def anchors(self) -> dict:
        return {"anchor_wall_ms": round(self.anchor_wall * 1000.0, 3),
                "anchor_monotonic_ms": round(
                    self.anchor_monotonic * 1000.0, 3)}


_timelines: Dict[str, "weakref.ref[StepTimeline]"] = {}
_timelines_lock = threading.Lock()


def register_timeline(name: str, timeline: StepTimeline) -> None:
    """Expose an engine's step timeline under /v1/traces. Held by weakref
    so a stopped engine disappears with its last strong reference."""
    with _timelines_lock:
        _timelines[name] = weakref.ref(timeline)


def timelines_snapshot(limit: int = 200,
                       since_ms: Optional[float] = None
                       ) -> Dict[str, List[dict]]:
    out: Dict[str, List[dict]] = {}
    with _timelines_lock:
        for name, ref in list(_timelines.items()):
            tl = ref()
            if tl is None:
                del _timelines[name]
            elif tl.enabled:
                out[name] = tl.snapshot(limit, since_ms=since_ms)
    return out


def timeline_anchors() -> Dict[str, dict]:
    """Each registered ring's wall/monotonic anchor pair — what a
    cross-worker rollup subtracts to put every timeline on one axis."""
    out: Dict[str, dict] = {}
    with _timelines_lock:
        for name, ref in list(_timelines.items()):
            tl = ref()
            if tl is not None and tl.enabled:
                out[name] = tl.anchors()
    return out
