"""Runtime + DistributedRuntime + Worker bootstrap.

Reference lib/runtime/src/{lib.rs,runtime.rs,distributed.rs,worker.rs}:
``Runtime`` owns the execution context and root cancellation;
``DistributedRuntime`` adds the control-plane client (etcd+NATS analog: DCP),
the primary lease (worker identity + liveness), and the lazily-created TCP
response-plane server; ``Worker.execute`` is the process entrypoint running a
user async fn with SIGINT-triggered graceful shutdown.
"""

from __future__ import annotations

import asyncio
import logging
import signal
from typing import Awaitable, Callable, Optional

from .component import Namespace
from .config import RuntimeConfig, env_str
from .dcp_client import DcpClient, KeepaliveThread
from .dcp_server import DcpServer
from .tasks import spawn_tracked
from .tcp import TcpStreamServer

log = logging.getLogger("dynamo_tpu.runtime")

DEFAULT_DCP = env_str("DYN_DCP_ADDRESS", "127.0.0.1:6650")


class Runtime:
    """Process-local execution context + hierarchical cancellation."""

    def __init__(self, config: Optional[RuntimeConfig] = None):
        self.config = config or RuntimeConfig.from_settings()
        self._shutdown = asyncio.Event()

    @property
    def shutdown_event(self) -> asyncio.Event:
        return self._shutdown

    def shutdown(self) -> None:
        self._shutdown.set()

    @property
    def is_shutdown(self) -> bool:
        return self._shutdown.is_set()

    def child_event(self) -> asyncio.Event:
        """A cancellation event that fires when the root shuts down."""
        ev = asyncio.Event()

        async def _link():
            await self._shutdown.wait()
            ev.set()

        spawn_tracked(_link(), name="runtime-shutdown-link")
        return ev


class DistributedRuntime:
    """Runtime + control-plane connectivity + worker identity.

    ``lease_id`` (primary lease) doubles as the worker/instance id, exactly
    as the reference uses the etcd lease id (distributed.rs:31-66).
    """

    def __init__(self, runtime: Runtime, dcp: DcpClient, lease: int):
        self.runtime = runtime
        self.dcp = dcp
        self.primary_lease = lease
        self._tcp_server: Optional[TcpStreamServer] = None
        self._tcp_lock = asyncio.Lock()
        self._keepalive_task: Optional[KeepaliveThread] = None
        self._embedded_server: Optional[DcpServer] = None

    @classmethod
    async def attach(
        cls,
        dcp_address: Optional[str] = None,
        runtime: Optional[Runtime] = None,
        lease_ttl: Optional[float] = None,
    ) -> "DistributedRuntime":
        """Connect to the control plane and acquire the primary lease."""
        # Runtime() reads the DYN_CONFIG_PATH overlay file — off-loop
        runtime = runtime or await asyncio.to_thread(Runtime)
        address = dcp_address or runtime.config.dcp_address or DEFAULT_DCP
        lease_ttl = lease_ttl if lease_ttl is not None else runtime.config.lease_ttl
        dcp = await DcpClient.connect(address)
        lease = await dcp.lease_grant(lease_ttl)
        self = cls(runtime, dcp, lease)
        # dedicated-thread keepalive: the serving process blocks its event
        # loop for multiples of the TTL (engine warmup, host-staged KV
        # transfers), and a loop-resident keepalive would let the primary
        # lease expire mid-stall, deleting every instance/endpoint record
        # under it (see KeepaliveThread)
        self._keepalive_task = KeepaliveThread(address, lease, lease_ttl)
        return self

    @classmethod
    async def detached(cls, runtime: Optional[Runtime] = None) -> "DistributedRuntime":
        """Single-process mode: embed a DCP server in-process (reference
        ``Runtime::single_threaded`` standalone mode). Used by tests and
        ``run`` when no control plane is configured."""
        server = await DcpServer.start("127.0.0.1", 0)
        drt = await cls.attach(server.address, runtime)
        drt._embedded_server = server
        return drt

    @property
    def instance_id(self) -> int:
        return self.primary_lease

    def namespace(self, name: str) -> Namespace:
        return Namespace(self, name)

    async def tcp_server(self) -> TcpStreamServer:
        """Lazily-created response-plane listener (distributed.rs:110-120)."""
        async with self._tcp_lock:
            if self._tcp_server is None:
                self._tcp_server = await TcpStreamServer.start()
            return self._tcp_server

    async def shutdown(self) -> None:
        self.runtime.shutdown()
        if self._keepalive_task:
            # cancel() joins the keepalive thread, which may sit in an
            # in-flight renewal RPC for up to its timeout — run it in the
            # default executor so this loop keeps serving meanwhile
            await asyncio.get_running_loop().run_in_executor(
                None, self._keepalive_task.cancel)
        try:
            await self.dcp.lease_revoke(self.primary_lease)
        except Exception:
            pass
        if self._tcp_server:
            await self._tcp_server.stop()
        await self.dcp.close()
        if self._embedded_server is not None:
            await self._embedded_server.stop()


class Worker:
    """Process entrypoint (reference worker.rs:60-133): builds the runtime,
    runs the user's async main, handles SIGINT/SIGTERM gracefully."""

    def __init__(self, config: Optional[RuntimeConfig] = None):
        self.config = config or RuntimeConfig.from_settings()

    def execute(self, main: Callable[[DistributedRuntime], Awaitable[None]]) -> None:
        asyncio.run(self._run(main))

    async def _run(self, main) -> None:
        # config is already resolved here, but Runtime's default path can
        # read the overlay file — keep construction off the fresh loop
        runtime = await asyncio.to_thread(Runtime, self.config)
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, runtime.shutdown)
            except NotImplementedError:
                pass
        if self.config.dcp_address:
            drt = await DistributedRuntime.attach(
                self.config.dcp_address, runtime)
        else:
            drt = await DistributedRuntime.detached(runtime)
        try:
            await main(drt)
        finally:
            await drt.shutdown()


def dynamo_worker(config: Optional[RuntimeConfig] = None):
    """Decorator: ``@dynamo_worker()`` turns an async fn taking a
    DistributedRuntime into a blocking main() (reference Python bindings
    ``@dynamo_worker()``)."""

    def deco(fn: Callable[[DistributedRuntime], Awaitable[None]]):
        def main() -> None:
            Worker(config).execute(fn)

        main.__wrapped__ = fn
        return main

    return deco
