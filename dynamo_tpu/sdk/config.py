"""Per-service configuration (reference sdk lib/config.py ServiceConfig:
YAML file keyed by service name + ``DYNAMO_SERVICE_CONFIG`` env override,
exploded into the service instance)."""

from __future__ import annotations

import json
from typing import Any, Dict, Optional

from ..runtime.config import env_str

ENV_KEY = "DYNAMO_SERVICE_CONFIG"


class ServiceConfig:
    """Singleton mapping ``{service_name: {key: value}}``."""

    _instance: Optional["ServiceConfig"] = None

    def __init__(self, data: Optional[Dict[str, Dict[str, Any]]] = None):
        self.data: Dict[str, Dict[str, Any]] = data or {}

    # ------------------------------------------------------------ loading

    @classmethod
    def get_instance(cls) -> "ServiceConfig":
        if cls._instance is None:
            cls._instance = cls.from_env()
        return cls._instance

    @classmethod
    def set_instance(cls, cfg: "ServiceConfig") -> None:
        cls._instance = cfg

    @classmethod
    def from_env(cls) -> "ServiceConfig":
        raw = env_str(ENV_KEY)
        return cls(json.loads(raw)) if raw else cls()

    @classmethod
    def from_yaml(cls, path: str) -> "ServiceConfig":
        import yaml

        with open(path) as f:
            data = yaml.safe_load(f) or {}
        if not isinstance(data, dict):
            raise ValueError(f"service config must be a mapping: {path}")
        return cls(data)

    def to_env_value(self) -> str:
        return json.dumps(self.data)

    # ------------------------------------------------------------- access

    def for_service(self, name: str) -> Dict[str, Any]:
        return dict(self.data.get(name, {}))

    def get(self, service: str, key: str, default: Any = None) -> Any:
        return self.data.get(service, {}).get(key, default)
