"""Service SDK: declarative distributed graphs over the runtime.

Reference deploy/dynamo/sdk (BentoML-derived, SURVEY §2.7):
``@service(dynamo={...}, resources={...}, workers=N)`` wraps a class into a
:class:`DynamoService` (reference lib/service.py:67-241); ``@dynamo_endpoint``
marks streaming endpoint methods (lib/decorators.py:26-101); ``depends(Svc)``
declares runtime client edges (lib/dependency.py); ``A.link(B)`` activates
deployment edges for a graph file (lib/service.py:173-177, used by
examples/llm/graphs/*.py); ``@async_on_start`` hooks run before serving
(cli/serve_dynamo.py:110-189).

TPU-first re-design notes: services are plain asyncio classes served by the
in-process runtime (no BentoML runner layer); one service worker = one
process = (potentially) one SPMD program over its own mesh; resources
declare ``tpu`` chips instead of ``gpu``.
"""

from __future__ import annotations

import inspect
import logging
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set

log = logging.getLogger("dynamo_tpu.sdk")

_ENDPOINT_ATTR = "__dynamo_endpoint__"
_ON_START_ATTR = "__dynamo_on_start__"


@dataclass
class EndpointDef:
    name: str
    method: str          # attribute name on the class
    is_default: bool = False  # first declared endpoint = the service's API


def dynamo_endpoint(name: Optional[str] = None, **_kw):
    """Mark an async-generator method as a served endpoint
    (reference sdk lib/decorators.py ``@dynamo_endpoint``). Accepts and
    ignores legacy typing kwargs for signature compatibility."""

    def deco(fn):
        setattr(fn, _ENDPOINT_ATTR, name or fn.__name__)
        return fn

    # bare usage: @dynamo_endpoint
    if callable(name):
        fn, name = name, None
        return deco(fn)
    return deco


# reference sdk also exposes `api` as the bento-style alias
api = dynamo_endpoint


def async_on_start(fn):
    """Mark an async method to run after runtime wiring, before serving
    (reference ``@async_on_start``, cli/serve_dynamo.py:139)."""
    setattr(fn, _ON_START_ATTR, True)
    return fn


class Depends:
    """Declared dependency edge: resolves to a live client at runtime
    (reference sdk lib/dependency.py). Use as a class attribute:

        class Processor:
            worker = depends(Worker)

    Inside methods, ``self.worker`` is a :class:`DependencyHandle`.
    """

    def __init__(self, target: "DynamoService"):
        if not isinstance(target, DynamoService):
            raise TypeError("depends() takes a @service-decorated class")
        self.target = target
        self.attr: Optional[str] = None

    def __set_name__(self, owner, name):
        self.attr = name

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        handle = obj.__dict__.get(f"__dep_{self.attr}")
        if handle is None:
            raise RuntimeError(
                f"dependency {self.attr!r} not wired (service not started "
                f"through the SDK runner)")
        return handle


def depends(target: "DynamoService") -> Depends:
    return Depends(target)


class DynamoService:
    """A deployable service: user class + deployment metadata + edges."""

    def __init__(self, cls: type, *, name: str, namespace: str,
                 workers: int, resources: Dict[str, Any],
                 dynamo_enabled: bool):
        self.cls = cls
        self.name = name
        self.namespace = namespace
        self.workers = workers
        self.resources = resources or {}
        self.dynamo_enabled = dynamo_enabled
        self.links: List["DynamoService"] = []
        self.endpoints: List[EndpointDef] = []
        for attr, fn in inspect.getmembers(cls, inspect.isfunction):
            ep = getattr(fn, _ENDPOINT_ATTR, None)
            if ep:
                self.endpoints.append(EndpointDef(name=ep, method=attr))
        # declaration order, not alphabetic: re-sort by source line
        self.endpoints.sort(
            key=lambda e: getattr(getattr(cls, e.method), "__code__",
                                  None).co_firstlineno
            if hasattr(getattr(cls, e.method), "__code__") else 0)
        if self.endpoints:
            self.endpoints[0].is_default = True
        self.on_start_methods = [
            attr for attr, fn in inspect.getmembers(cls, inspect.isfunction)
            if getattr(fn, _ON_START_ATTR, False)]
        self.depends_attrs: Dict[str, DynamoService] = {
            a: d.target for a, d in vars(cls).items()
            if isinstance(d, Depends)}

    # ------------------------------------------------------------- graph

    def link(self, other: "DynamoService") -> "DynamoService":
        """Activate a deployment edge self→other; returns ``other`` so
        graphs chain: ``Frontend.link(Processor).link(Worker)``
        (reference lib/service.py:173-177)."""
        if other not in self.links:
            self.links.append(other)
        return other

    def graph(self) -> List["DynamoService"]:
        """All services reachable from this one via link + depends edges,
        dependency-first order (reference LinkedServices semantics)."""
        seen: Set[int] = set()
        out: List[DynamoService] = []

        def visit(svc: "DynamoService"):
            if id(svc) in seen:
                return
            seen.add(id(svc))
            for dep in svc.depends_attrs.values():
                visit(dep)
            for l in svc.links:
                visit(l)
            out.append(svc)

        visit(self)
        return out

    # ---------------------------------------------------------- addressing

    @property
    def component_name(self) -> str:
        return self.name

    def endpoint_address(self, endpoint: Optional[str] = None) -> str:
        ep = endpoint or (self.endpoints[0].name if self.endpoints
                          else "generate")
        return f"dyn://{self.namespace}.{self.name}.{ep}"

    def __repr__(self) -> str:
        return (f"<DynamoService {self.namespace}.{self.name} "
                f"endpoints={[e.name for e in self.endpoints]}>")


def service(dynamo: Optional[Dict[str, Any]] = None,
            resources: Optional[Dict[str, Any]] = None,
            workers: int = 1, name: Optional[str] = None, **_kw):
    """Class decorator: ``@service(dynamo={"namespace": "ns"},
    resources={"tpu": 1}, workers=2)`` (reference sdk lib/service.py
    ``@service``)."""
    dynamo = dynamo or {}

    def deco(cls: type) -> DynamoService:
        return DynamoService(
            cls, name=name or cls.__name__,
            namespace=dynamo.get("namespace", "dynamo"),
            workers=workers, resources=resources or {},
            dynamo_enabled=dynamo.get("enabled", True))

    return deco
