"""SDK runtime: wire and serve DynamoService instances.

Per-worker path (reference cli/serve_dynamo.py:62-189): connect the
DistributedRuntime, create the component, resolve ``depends()`` edges into
live clients, run ``@async_on_start`` hooks, then serve every declared
endpoint. ``deploy_inline`` runs a whole graph in one process/event loop
(the reference sdk tests' local pipelines, sdk/tests/{pipeline,e2e}.py) —
also the fast path for single-host serving without process isolation.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Dict, List, Optional

from ..runtime.component import AsyncResponseStream, Client
from ..runtime.engine import Context
from ..runtime.runtime import DistributedRuntime
from .config import ServiceConfig
from .service import DynamoService

log = logging.getLogger("dynamo_tpu.sdk")


class DependencyHandle:
    """Live client edge injected for each ``depends()`` attribute."""

    def __init__(self, target: DynamoService, client: Client):
        self.target = target
        self.client = client

    async def generate(self, request: Any, **kw) -> AsyncResponseStream:
        return await self.client.generate(request, **kw)

    async def round_robin(self, request: Any, **kw) -> AsyncResponseStream:
        return await self.client.round_robin(request, **kw)

    async def random(self, request: Any, **kw) -> AsyncResponseStream:
        return await self.client.random(request, **kw)

    async def direct(self, request: Any, instance_id: int,
                     **kw) -> AsyncResponseStream:
        return await self.client.direct(request, instance_id, **kw)

    def instance_ids(self) -> List[int]:
        return self.client.instance_ids()

    async def wait_for_instances(self, timeout: float = 30.0) -> List[int]:
        return await self.client.wait_for_instances(timeout)

    async def collect_stats(self, timeout: float = 2.0) -> Dict[int, dict]:
        return await self.client.collect_stats(timeout)


class ServiceWorker:
    """One running worker of a service (one process in `serve`, one task
    group in `deploy_inline`)."""

    def __init__(self, svc: DynamoService, drt: DistributedRuntime,
                 config: Optional[ServiceConfig] = None):
        self.svc = svc
        self.drt = drt
        self.config = config or ServiceConfig.get_instance()
        self.instance: Any = None
        self._handles: list = []
        self._clients: List[Client] = []

    async def start(self) -> None:
        svc = self.svc
        inst = object.__new__(svc.cls)  # construct without running __init__
        # inject service config BEFORE __init__ so it can read overrides
        inst.service_config = self.config.for_service(svc.name)
        inst.dynamo_service = svc
        inst.runtime = self.drt
        init = getattr(svc.cls, "__init__", None)
        if init and init is not object.__init__:
            init(inst)
        # resolve dependency edges
        for attr, target in svc.depends_attrs.items():
            ep = target.endpoints[0].name if target.endpoints else "generate"
            address = f"{target.namespace}.{target.name}.{ep}"
            client = await self.drt.namespace(target.namespace).component(
                target.name).endpoint(ep).client()
            self._clients.append(client)
            inst.__dict__[f"__dep_{attr}"] = DependencyHandle(target, client)
        self.instance = inst
        component = self.drt.namespace(svc.namespace).component(svc.name)
        await component.create_service()
        for m in svc.on_start_methods:
            await getattr(inst, m)()
        for ep in svc.endpoints:
            method = getattr(inst, ep.method)
            handler = _adapt_handler(method)
            stats = getattr(inst, "stats_handler", None)
            h = await component.endpoint(ep.name).serve(
                handler, stats_handler=stats)
            self._handles.append(h)
        log.info("service %s.%s serving %d endpoint(s)", svc.namespace,
                 svc.name, len(self._handles))

    async def stop(self) -> None:
        for h in self._handles:
            await h.stop()
        for c in self._clients:
            await c.close()
        stop = getattr(self.instance, "on_stop", None)
        if stop is not None:
            res = stop()
            if asyncio.iscoroutine(res):
                await res


def _adapt_handler(method):
    """Endpoint methods may be ``async def m(self, request)`` or
    ``async def m(self, request, context)``; the runtime always calls
    handler(request, context)."""
    import inspect

    sig = inspect.signature(method)
    takes_ctx = len(sig.parameters) >= 2

    if takes_ctx:
        return method

    def handler(request, context: Context):
        return method(request)

    return handler


class InlineDeployment:
    """A whole service graph running in one process (tests / single host)."""

    def __init__(self, drt: DistributedRuntime,
                 workers: List[ServiceWorker]):
        self.drt = drt
        self.workers = workers

    async def client(self, svc: DynamoService,
                     endpoint: Optional[str] = None) -> Client:
        ep = endpoint or (svc.endpoints[0].name if svc.endpoints
                          else "generate")
        return await self.drt.namespace(svc.namespace).component(
            svc.name).endpoint(ep).client()

    async def stop(self) -> None:
        for w in self.workers:
            await w.stop()


async def deploy_inline(entry: DynamoService,
                        drt: Optional[DistributedRuntime] = None,
                        config: Optional[ServiceConfig] = None
                        ) -> InlineDeployment:
    """Deploy ``entry.graph()`` into one event loop. Services are started
    dependency-first so ``wait_for_instances`` in on_start hooks resolves."""
    drt = drt or await DistributedRuntime.detached()
    workers: List[ServiceWorker] = []
    for svc in entry.graph():
        w = ServiceWorker(svc, drt, config)
        await w.start()
        workers.append(w)
    return InlineDeployment(drt, workers)
