"""Serving SDK (reference deploy/dynamo/sdk, SURVEY §2.7): declarative
service graphs — ``@service``, ``@dynamo_endpoint``, ``depends()``,
``.link()``, ``@async_on_start`` — deployed via ``python -m
dynamo_tpu.sdk.cli serve module:Entry`` or in-process with
``deploy_inline``."""

from .config import ServiceConfig
from .runner import (DependencyHandle, InlineDeployment, ServiceWorker,
                     deploy_inline)
from .service import (DynamoService, api, async_on_start, depends,
                      dynamo_endpoint, service)

__all__ = [
    "ServiceConfig", "DependencyHandle", "InlineDeployment", "ServiceWorker",
    "deploy_inline", "DynamoService", "api", "async_on_start", "depends",
    "dynamo_endpoint", "service",
]
