"""`dynamo serve` — deploy a service graph as supervised processes.

Reference deploy/dynamo/sdk/cli (SURVEY §2.7): ``serve`` loads the graph
module, computes the linked-service set, and spawns one process per service
worker (the reference uses circus watchers; here a plain asyncio
supervisor). ``serve-worker`` is the per-process entrypoint (reference
cli/serve_dynamo.py). The GPU allocator (cli/allocator.py slicing
CUDA_VISIBLE_DEVICES) becomes TPU-chip gating: services that declare no
``resources={"tpu": N}`` are pinned to CPU JAX so they never grab the chip.

Usage:
    python -m dynamo_tpu.sdk.cli serve examples.llm.graphs.agg:Frontend \
        -f configs/agg.yaml [--dcp HOST:PORT]
"""

from __future__ import annotations

import argparse
import asyncio
import importlib
import logging
import os
import signal
import socket
import subprocess
import sys
from typing import Dict, List, Optional, Tuple

from ..runtime.config import env_str
from .config import ENV_KEY, ServiceConfig
from .service import DynamoService

log = logging.getLogger("dynamo_tpu.sdk.cli")


def load_target(target: str) -> DynamoService:
    """Resolve ``pkg.module:ServiceName`` to the entry DynamoService."""
    if ":" not in target:
        raise SystemExit(f"target must be module:Service, got {target!r}")
    mod_name, attr = target.split(":", 1)
    mod = importlib.import_module(mod_name)
    svc = getattr(mod, attr)
    if not isinstance(svc, DynamoService):
        raise SystemExit(f"{target} is not a @service (got {type(svc)})")
    return svc


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _worker_env(svc: DynamoService, dcp: str, cfg: ServiceConfig) -> dict:
    env = dict(os.environ)
    env["DYN_DCP_ADDRESS"] = dcp
    env[ENV_KEY] = cfg.to_env_value()
    if not svc.resources.get("tpu"):
        # CPU-pin control-plane services so only TPU workers touch the chip
        env.setdefault("JAX_PLATFORMS", "cpu")
    return env


async def cmd_serve(args) -> int:
    entry = load_target(args.target)
    cfg = (await asyncio.to_thread(ServiceConfig.from_yaml, args.config)
           if args.config else ServiceConfig.from_env())
    graph = entry.graph()
    log.info("graph: %s", " -> ".join(s.name for s in graph))

    dcp_proc: Optional[subprocess.Popen] = None
    dcp = args.dcp
    if not dcp:
        port = _free_port()
        dcp = f"127.0.0.1:{port}"
        dcp_proc = subprocess.Popen(
            [sys.executable, "-m", "dynamo_tpu.runtime.dcp_server",
             "--host", "127.0.0.1", "--port", str(port)],
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        log.info("embedded control plane at %s (pid %d)", dcp, dcp_proc.pid)
        await asyncio.sleep(0.3)

    procs: List[Tuple[DynamoService, subprocess.Popen]] = []
    restarts: Dict[int, int] = {}

    for svc in graph:
        for _ in range(max(svc.workers, 1)):
            p = subprocess.Popen(
                [sys.executable, "-m", "dynamo_tpu.sdk.cli", "serve-worker",
                 "--target", args.target, "--service", svc.name],
                env=_worker_env(svc, dcp, cfg))
            procs.append((svc, p))
            log.info("spawned %s worker pid %d", svc.name, p.pid)

    loop = asyncio.get_running_loop()
    stop_ev = asyncio.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop_ev.set)
        except NotImplementedError:
            pass

    async def supervise():
        nonlocal procs
        while not stop_ev.is_set():
            await asyncio.sleep(0.5)
            for i, (svc, p) in enumerate(list(procs)):
                rc = p.poll()
                if rc is None or stop_ev.is_set():
                    continue
                n = restarts.get(i, 0)
                if n >= args.max_restarts:
                    log.error("%s worker died rc=%s; restart budget spent",
                              svc.name, rc)
                    stop_ev.set()
                    return
                restarts[i] = n + 1
                log.warning("%s worker died rc=%s; restarting (%d/%d)",
                            svc.name, rc, n + 1, args.max_restarts)
                procs[i] = (svc, subprocess.Popen(
                    [sys.executable, "-m", "dynamo_tpu.sdk.cli",
                     "serve-worker", "--target", args.target,
                     "--service", svc.name],
                    env=_worker_env(svc, dcp, cfg)))

    try:
        await supervise()
        await stop_ev.wait()
    finally:
        for _, p in procs:
            if p.poll() is None:
                p.terminate()
        for _, p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        if dcp_proc is not None:
            dcp_proc.terminate()
            try:
                dcp_proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                dcp_proc.kill()
    return 0


async def cmd_serve_worker(args) -> int:
    from ..runtime.runtime import DistributedRuntime, Runtime
    from .runner import ServiceWorker

    entry = load_target(args.target)
    svc = next((s for s in entry.graph() if s.name == args.service), None)
    if svc is None:
        raise SystemExit(f"service {args.service!r} not in graph of "
                         f"{args.target}")
    cfg = ServiceConfig.from_env()
    runtime = await asyncio.to_thread(Runtime)
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, runtime.shutdown)
        except NotImplementedError:
            pass
    drt = await DistributedRuntime.attach(
        env_str("DYN_DCP_ADDRESS"), runtime)
    worker = ServiceWorker(svc, drt, cfg)
    try:
        await worker.start()
        await runtime.shutdown_event.wait()
    finally:
        await worker.stop()
        await drt.shutdown()
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    logging.basicConfig(level=env_str("DYN_LOG"))
    ap = argparse.ArgumentParser(prog="dynamo")
    sub = ap.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("serve", help="deploy a service graph")
    s.add_argument("target", help="module.path:EntryService")
    s.add_argument("-f", "--config", help="service config YAML")
    s.add_argument("--dcp", help="external control-plane address")
    s.add_argument("--max-restarts", type=int, default=3)

    w = sub.add_parser("serve-worker", help="(internal) one service worker")
    w.add_argument("--target", required=True)
    w.add_argument("--service", required=True)

    args = ap.parse_args(argv)
    if args.cmd == "serve":
        return asyncio.run(cmd_serve(args))
    if args.cmd == "serve-worker":
        return asyncio.run(cmd_serve_worker(args))
    return 2


if __name__ == "__main__":
    sys.exit(main())
