"""Kubernetes deployment plane: CRD rendering + reconcile controller.

The reference ships a kubebuilder operator (deploy/dynamo/operator, Go:
internal/controller/dynamodeployment_controller.go) that converges
DynamoDeployment CRs into per-service Deployments/Services. This package is
the same control loop in Python: `render` (the pure CR→manifests mapping),
`KubeClient` (pluggable API transport: in-cluster REST or a test fake), and
`Reconciler` (diff + create/patch/delete + status)."""

from .controller import Reconciler
from .render import render

__all__ = ["Reconciler", "render"]
