"""Render a DynamoDeployment spec into Kubernetes manifests.

The reference ships a kubebuilder operator (deploy/dynamo/operator, Go)
whose controllers expand a DynamoDeployment CR into per-service
Deployments/Services. This renderer is that expansion as a pure,
cluster-free function — usable as `kubectl apply -f <(python render.py
deployment.yaml)`, as the core of a future in-cluster controller, and as a
unit-testable spec of the mapping. TPU scheduling uses GKE's
`google.com/tpu` resources + node selectors instead of the reference's
GPU allocator env slicing.
"""

from __future__ import annotations

import sys
from typing import Any, Dict, List

import yaml

DCP_PORT = 6650


def render(spec: Dict[str, Any]) -> List[Dict[str, Any]]:
    """DynamoDeployment dict → list of k8s manifests."""
    meta = spec.get("metadata", {})
    name = meta.get("name", "dynamo")
    ns = meta.get("namespace", "default")
    s = spec["spec"]
    image = s.get("image", "dynamo-tpu:latest")
    graph = s["graph"]
    config_yaml = s.get("configYaml", "")
    out: List[Dict[str, Any]] = []

    labels = {"app.kubernetes.io/part-of": name}

    # control plane: one DCP server Deployment + Service
    out.append({
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": f"{name}-dcp", "namespace": ns,
                     "labels": labels},
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": {"app": f"{name}-dcp"}},
            "template": {
                "metadata": {"labels": {"app": f"{name}-dcp", **labels}},
                "spec": {"containers": [{
                    "name": "dcp", "image": image,
                    "command": ["python", "-m", "dynamo_tpu", "dcp-server",
                                "--host", "0.0.0.0", "--port",
                                str(DCP_PORT)],
                    "ports": [{"containerPort": DCP_PORT}],
                    "env": [{"name": "JAX_PLATFORMS", "value": "cpu"}],
                }]}}}})
    out.append({
        "apiVersion": "v1", "kind": "Service",
        "metadata": {"name": f"{name}-dcp", "namespace": ns,
                     "labels": labels},
        "spec": {"selector": {"app": f"{name}-dcp"},
                 "ports": [{"port": DCP_PORT}]}})

    cfgmap_name = f"{name}-service-config"
    out.append({
        "apiVersion": "v1", "kind": "ConfigMap",
        "metadata": {"name": cfgmap_name, "namespace": ns, "labels": labels},
        "data": {"config.yaml": config_yaml}})

    services = s.get("services") or {}
    frontends = [n for n, v in services.items() if v.get("frontend")]
    spec_ing = s.get("ingress")
    if (spec_ing and spec_ing.get("enabled", True)
            and len(frontends) > 1 and not spec_ing.get("service")):
        # two Ingresses claiming the same host+path would route
        # arbitrarily — refuse loudly instead (per-service `ingress`
        # blocks or an explicit `ingress.service` disambiguate)
        raise ValueError(
            f"spec.ingress is ambiguous with {len(frontends)} frontend "
            f"services ({', '.join(frontends)}): set ingress.service or "
            "move ingress under one service")
    # dangling references render "successfully" with no route and
    # nothing in status — validate them loudly instead
    if (spec_ing and spec_ing.get("enabled", True)
            and spec_ing.get("service")
            and spec_ing["service"] not in frontends):
        raise ValueError(
            f"ingress.service {spec_ing['service']!r} is not a frontend "
            f"service (frontends: {', '.join(frontends) or 'none'})")
    for n, v in services.items():
        if v.get("ingress") and not v.get("frontend"):
            raise ValueError(
                f"service {n!r} carries an ingress block but is not "
                "frontend: true — the block would be silently ignored")
    # debug-split targets need a backing Service even when they are not
    # frontends (the canary Ingress / Istio debug route points at them)
    debug_targets = set()
    for ing in [spec_ing] + [v.get("ingress") for v in services.values()]:
        if ing and ing.get("enabled", True) and ing.get("debugService"):
            if ing["debugService"] not in services:
                raise ValueError(
                    f"ingress.debugService {ing['debugService']!r} names "
                    "no defined service")
            debug_targets.add(ing["debugService"])

    for svc_name, svc in services.items():
        slug = svc_name.lower()
        tpu = svc.get("tpuAccelerator")
        pod: Dict[str, Any] = {
            "containers": [{
                "name": slug, "image": image,
                "command": ["python", "-m", "dynamo_tpu", "serve-worker",
                            "--target", graph, "--service", svc_name],
                "env": [
                    {"name": "DYN_DCP_ADDRESS",
                     "value": f"{name}-dcp.{ns}.svc:{DCP_PORT}"},
                    {"name": "DYNAMO_SERVICE_CONFIG_FILE",
                     "value": "/etc/dynamo/config.yaml"},
                ],
                "volumeMounts": [{"name": "svc-config",
                                  "mountPath": "/etc/dynamo"}],
                "resources": {"limits": dict(svc.get("resources") or {})},
            }],
            "volumes": [{"name": "svc-config",
                         "configMap": {"name": cfgmap_name}}],
        }
        if tpu:
            # GKE TPU scheduling: node selectors + google.com/tpu resource
            pod["nodeSelector"] = {
                "cloud.google.com/gke-tpu-accelerator": tpu,
                "cloud.google.com/gke-tpu-topology":
                    svc.get("tpuTopology", "1x1"),
            }
            pod["containers"][0]["resources"].setdefault(
                "limits", {})
            pod["containers"][0]["resources"]["limits"][
                "google.com/tpu"] = svc.get("tpuChips", "1")
        else:
            pod["containers"][0]["env"].append(
                {"name": "JAX_PLATFORMS", "value": "cpu"})
        out.append({
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": f"{name}-{slug}", "namespace": ns,
                         "labels": labels},
            "spec": {
                "replicas": svc.get("replicas", 1),
                "selector": {"matchLabels": {"app": f"{name}-{slug}"}},
                "template": {
                    "metadata": {"labels": {"app": f"{name}-{slug}",
                                            **labels}},
                    "spec": pod}}})
        if svc.get("frontend") or svc_name in debug_targets:
            out.append({
                "apiVersion": "v1", "kind": "Service",
                "metadata": {"name": f"{name}-{slug}", "namespace": ns,
                             "labels": labels},
                "spec": {"selector": {"app": f"{name}-{slug}"},
                         "ports": [{"port": svc.get("port", 8080)}],
                         "type": svc.get("serviceType", "ClusterIP")}})
        if svc.get("frontend"):
            ing = svc.get("ingress")
            if ing is None and spec_ing is not None:
                target = spec_ing.get("service")
                if target is None or target == svc_name:
                    ing = spec_ing
            if ing:
                out.extend(_render_networking(name, ns, slug, svc, ing,
                                              labels, services))
    return out


def _render_networking(name: str, ns: str, slug: str,
                       svc: Dict[str, Any], ing: Dict[str, Any],
                       labels: Dict[str, str],
                       services: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Cluster networking for a frontend service — the reference
    operator's ingress plane (deploy/dynamo/operator pkg/dynamo/system/
    ingress.go: networking/v1 Ingress from a network config;
    internal/controller dynamonimdeployment_controller.go:1133: Istio
    VirtualService; internal/envoy/envoy.go: header-routed
    debug/production split), expressed K8s-natively:

    - ``spec.ingress`` → networking/v1 Ingress (class, host, path,
      annotations, TLS);
    - ``ingress.istio: true`` → an Istio VirtualService instead;
    - ``ingress.debugService`` → a second CANARY Ingress routing
      requests carrying the debug header to that service
      (ingress-controller canary-by-header — the K8s-native form of the
      reference's Envoy header split; no sidecar proxy to manage).
    """
    if not ing or not ing.get("enabled", True):
        return []
    port = svc.get("port", 8080)
    # the debug route targets the DEBUG service's own port (its backing
    # Service exposes that, not the frontend's)
    dbg = ing.get("debugService")
    dbg_port = (services.get(dbg, {}).get("port", 8080) if dbg else None)
    backend_svc = f"{name}-{slug}"
    host = ing.get("host") or (
        f"{name}.{ing['hostSuffix']}" if ing.get("hostSuffix") else None)
    path = ing.get("path", "/")
    path_type = ing.get("pathType", "Prefix")
    out: List[Dict[str, Any]] = []

    if ing.get("istio"):
        vs: Dict[str, Any] = {
            "apiVersion": "networking.istio.io/v1beta1",
            "kind": "VirtualService",
            "metadata": {"name": backend_svc, "namespace": ns,
                         "labels": labels},
            "spec": {
                "hosts": [host or backend_svc],
                "gateways": [ing.get("istioGateway", "istio-system/"
                                     "ingress-gateway")],
                "http": [{
                    "match": [{"uri": {"prefix": path}}],
                    "route": [{"destination": {
                        "host": f"{backend_svc}.{ns}.svc.cluster.local",
                        "port": {"number": port}}}],
                }],
            }}
        if ing.get("debugService"):
            # header-matched route first (Istio evaluates in order)
            vs["spec"]["http"].insert(0, {
                "match": [{
                    "uri": {"prefix": path},
                    "headers": {ing.get("debugHeader", "x-dynamo-debug"):
                                {"exact": ing.get("debugHeaderValue",
                                                  "1")}},
                }],
                "route": [{"destination": {
                    "host": (f"{name}-{ing['debugService'].lower()}"
                             f".{ns}.svc.cluster.local"),
                    "port": {"number": dbg_port}}}],
            })
        return [vs]

    def rule(svc_name: str, svc_port: int) -> Dict[str, Any]:
        r: Dict[str, Any] = {"http": {"paths": [{
            "path": path, "pathType": path_type,
            "backend": {"service": {"name": svc_name,
                                    "port": {"number": svc_port}}}}]}}
        if host:
            r["host"] = host
        return r

    ingress: Dict[str, Any] = {
        "apiVersion": "networking.k8s.io/v1", "kind": "Ingress",
        "metadata": {"name": backend_svc, "namespace": ns,
                     "labels": labels,
                     "annotations": dict(ing.get("annotations") or {})},
        "spec": {"rules": [rule(backend_svc, port)]},
    }
    if ing.get("className"):
        ingress["spec"]["ingressClassName"] = ing["className"]
    if ing.get("tlsSecret"):
        ingress["spec"]["tls"] = [{"hosts": [host] if host else [],
                                   "secretName": ing["tlsSecret"]}]
    out.append(ingress)

    if ing.get("debugService"):
        canary = {
            "apiVersion": "networking.k8s.io/v1", "kind": "Ingress",
            "metadata": {
                "name": f"{backend_svc}-debug", "namespace": ns,
                "labels": labels,
                "annotations": {
                    **dict(ing.get("annotations") or {}),
                    "nginx.ingress.kubernetes.io/canary": "true",
                    "nginx.ingress.kubernetes.io/canary-by-header":
                        ing.get("debugHeader", "x-dynamo-debug"),
                    "nginx.ingress.kubernetes.io/canary-by-header-value":
                        ing.get("debugHeaderValue", "1"),
                }},
            "spec": {"rules": [rule(
                f"{name}-{ing['debugService'].lower()}", dbg_port)]},
        }
        if ing.get("className"):
            canary["spec"]["ingressClassName"] = ing["className"]
        out.append(canary)
    return out


def render_model_request(spec: Dict[str, Any]) -> List[Dict[str, Any]]:
    """DynamoModelRequest → PVC + model-seeding Job.

    The reference's third CRD, DynamoNimRequest, stages the serving
    ARTIFACT before a deployment can run: it seeds models and bakes a
    per-model container image via builder Jobs
    (operator api/v1alpha1/dynamoinimrequest_types.go conditions
    ModelsSeeding/ImageBuilding; internal/controller/
    dynamonimrequest_controller.go:476-532 generateImageBuilderJob).
    On TPU the serving image is generic — the artifact that must be
    staged is the CHECKPOINT — so the TPU-native plane is: a
    PersistentVolumeClaim for the model store plus a batch Job running
    ``python -m dynamo_tpu fetch-model`` into it. DynamoDeployment
    services then mount the claim at /models.
    """
    meta = spec.get("metadata", {})
    name = meta.get("name", "model")
    ns = meta.get("namespace", "default")
    s = spec["spec"]
    model_id = s["modelId"]
    image = s.get("image", "dynamo-tpu:latest")
    claim = s.get("existingClaim") or f"{name}-models"
    dest = s.get("destPath", f"/models/{name}")
    labels = {"app.kubernetes.io/part-of": name}
    out: List[Dict[str, Any]] = []

    if not s.get("existingClaim"):
        out.append({
            "apiVersion": "v1", "kind": "PersistentVolumeClaim",
            "metadata": {"name": claim, "namespace": ns, "labels": labels},
            "spec": {
                "accessModes": [s.get("accessMode", "ReadWriteOnce")],
                "resources": {"requests": {
                    "storage": s.get("storage", "50Gi")}},
                **({"storageClassName": s["storageClassName"]}
                   if s.get("storageClassName") else {}),
            }})

    cmd = ["python", "-m", "dynamo_tpu", "fetch-model",
           "--model-id", model_id, "--dest", dest]
    if s.get("revision"):
        cmd += ["--revision", s["revision"]]
    container: Dict[str, Any] = {
        "name": "seed", "image": image, "command": cmd,
        "volumeMounts": [{"name": "models", "mountPath": "/models"}],
    }
    if s.get("hfTokenSecret"):
        # only set env when non-empty: the apiserver drops an empty env
        # list on read-back (omitempty), which the drift diff would read
        # as a change and hot-loop Job recreation
        container["env"] = [{"name": "HF_TOKEN", "valueFrom": {
            "secretKeyRef": {"name": s["hfTokenSecret"],
                             "key": s.get("hfTokenKey", "token")}}}]
    out.append({
        "apiVersion": "batch/v1", "kind": "Job",
        "metadata": {"name": f"{name}-seed", "namespace": ns,
                     "labels": labels},
        "spec": {
            "backoffLimit": s.get("backoffLimit", 4),
            "template": {
                "metadata": {"labels": {"app": f"{name}-seed", **labels}},
                "spec": {
                    "restartPolicy": "OnFailure",
                    "containers": [container],
                    "volumes": [{"name": "models",
                                 "persistentVolumeClaim":
                                     {"claimName": claim}}],
                }}}})
    return out


def main() -> int:
    if len(sys.argv) != 2:
        print("usage: render.py <dynamodeployment.yaml>", file=sys.stderr)
        return 2
    with open(sys.argv[1]) as f:
        spec = yaml.safe_load(f)
    print(yaml.safe_dump_all(render(spec), sort_keys=False))
    return 0


if __name__ == "__main__":
    sys.exit(main())
