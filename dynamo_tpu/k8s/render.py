"""Render a DynamoDeployment spec into Kubernetes manifests.

The reference ships a kubebuilder operator (deploy/dynamo/operator, Go)
whose controllers expand a DynamoDeployment CR into per-service
Deployments/Services. This renderer is that expansion as a pure,
cluster-free function — usable as `kubectl apply -f <(python render.py
deployment.yaml)`, as the core of a future in-cluster controller, and as a
unit-testable spec of the mapping. TPU scheduling uses GKE's
`google.com/tpu` resources + node selectors instead of the reference's
GPU allocator env slicing.
"""

from __future__ import annotations

import sys
from typing import Any, Dict, List

import yaml

DCP_PORT = 6650


def render(spec: Dict[str, Any]) -> List[Dict[str, Any]]:
    """DynamoDeployment dict → list of k8s manifests."""
    meta = spec.get("metadata", {})
    name = meta.get("name", "dynamo")
    ns = meta.get("namespace", "default")
    s = spec["spec"]
    image = s.get("image", "dynamo-tpu:latest")
    graph = s["graph"]
    config_yaml = s.get("configYaml", "")
    out: List[Dict[str, Any]] = []

    labels = {"app.kubernetes.io/part-of": name}

    # control plane: one DCP server Deployment + Service
    out.append({
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": f"{name}-dcp", "namespace": ns,
                     "labels": labels},
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": {"app": f"{name}-dcp"}},
            "template": {
                "metadata": {"labels": {"app": f"{name}-dcp", **labels}},
                "spec": {"containers": [{
                    "name": "dcp", "image": image,
                    "command": ["python", "-m", "dynamo_tpu", "dcp-server",
                                "--host", "0.0.0.0", "--port",
                                str(DCP_PORT)],
                    "ports": [{"containerPort": DCP_PORT}],
                    "env": [{"name": "JAX_PLATFORMS", "value": "cpu"}],
                }]}}}})
    out.append({
        "apiVersion": "v1", "kind": "Service",
        "metadata": {"name": f"{name}-dcp", "namespace": ns,
                     "labels": labels},
        "spec": {"selector": {"app": f"{name}-dcp"},
                 "ports": [{"port": DCP_PORT}]}})

    cfgmap_name = f"{name}-service-config"
    out.append({
        "apiVersion": "v1", "kind": "ConfigMap",
        "metadata": {"name": cfgmap_name, "namespace": ns, "labels": labels},
        "data": {"config.yaml": config_yaml}})

    for svc_name, svc in (s.get("services") or {}).items():
        slug = svc_name.lower()
        tpu = svc.get("tpuAccelerator")
        pod: Dict[str, Any] = {
            "containers": [{
                "name": slug, "image": image,
                "command": ["python", "-m", "dynamo_tpu", "serve-worker",
                            "--target", graph, "--service", svc_name],
                "env": [
                    {"name": "DYN_DCP_ADDRESS",
                     "value": f"{name}-dcp.{ns}.svc:{DCP_PORT}"},
                    {"name": "DYNAMO_SERVICE_CONFIG_FILE",
                     "value": "/etc/dynamo/config.yaml"},
                ],
                "volumeMounts": [{"name": "svc-config",
                                  "mountPath": "/etc/dynamo"}],
                "resources": {"limits": dict(svc.get("resources") or {})},
            }],
            "volumes": [{"name": "svc-config",
                         "configMap": {"name": cfgmap_name}}],
        }
        if tpu:
            # GKE TPU scheduling: node selectors + google.com/tpu resource
            pod["nodeSelector"] = {
                "cloud.google.com/gke-tpu-accelerator": tpu,
                "cloud.google.com/gke-tpu-topology":
                    svc.get("tpuTopology", "1x1"),
            }
            pod["containers"][0]["resources"].setdefault(
                "limits", {})
            pod["containers"][0]["resources"]["limits"][
                "google.com/tpu"] = svc.get("tpuChips", "1")
        else:
            pod["containers"][0]["env"].append(
                {"name": "JAX_PLATFORMS", "value": "cpu"})
        out.append({
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": f"{name}-{slug}", "namespace": ns,
                         "labels": labels},
            "spec": {
                "replicas": svc.get("replicas", 1),
                "selector": {"matchLabels": {"app": f"{name}-{slug}"}},
                "template": {
                    "metadata": {"labels": {"app": f"{name}-{slug}",
                                            **labels}},
                    "spec": pod}}})
        if svc.get("frontend"):
            out.append({
                "apiVersion": "v1", "kind": "Service",
                "metadata": {"name": f"{name}-{slug}", "namespace": ns,
                             "labels": labels},
                "spec": {"selector": {"app": f"{name}-{slug}"},
                         "ports": [{"port": svc.get("port", 8080)}],
                         "type": svc.get("serviceType", "ClusterIP")}})
    return out


def main() -> int:
    if len(sys.argv) != 2:
        print("usage: render.py <dynamodeployment.yaml>", file=sys.stderr)
        return 2
    with open(sys.argv[1]) as f:
        spec = yaml.safe_load(f)
    print(yaml.safe_dump_all(render(spec), sort_keys=False))
    return 0


if __name__ == "__main__":
    sys.exit(main())
