"""Kubernetes API client: a minimal pluggable transport.

The reconcile controller (controller.py) talks to the cluster through the
four verbs below; tests inject an in-memory fake, production uses
``InClusterClient`` — a dependency-free REST client over the pod's service
account (the environment bakes no kubernetes client package, and the
controller needs only a tiny slice of the API).

Reference parity: the Go operator uses controller-runtime's cached client
(deploy/dynamo/operator internal/controller); the verbs here are the same
ones its Reconcile() bodies issue.
"""

from __future__ import annotations

import json
import os
import ssl
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Protocol

from ..runtime.config import env_str

# group/version/plural routing for the kinds the controller manages
_ROUTES = {
    "DynamoDeployment": ("apis/dynamo-tpu.dev/v1alpha1", "dynamodeployments"),
    "DynamoModelRequest": ("apis/dynamo-tpu.dev/v1alpha1",
                           "dynamomodelrequests"),
    "Deployment": ("apis/apps/v1", "deployments"),
    "Service": ("api/v1", "services"),
    "ConfigMap": ("api/v1", "configmaps"),
    "Job": ("apis/batch/v1", "jobs"),
    "PersistentVolumeClaim": ("api/v1", "persistentvolumeclaims"),
    "Ingress": ("apis/networking.k8s.io/v1", "ingresses"),
    # optional Istio plane (reference operator's VirtualService path,
    # dynamonimdeployment_controller.go:1133) — only touched when a CR
    # asks for it, so clusters without Istio never see the route
    "VirtualService": ("apis/networking.istio.io/v1beta1",
                       "virtualservices"),
}


class KubeClient(Protocol):
    def list(self, kind: str, namespace: str,
             label_selector: Optional[str] = None) -> List[Dict[str, Any]]:
        ...

    def get(self, kind: str, namespace: str,
            name: str) -> Optional[Dict[str, Any]]:
        ...

    def create(self, kind: str, namespace: str,
               obj: Dict[str, Any]) -> Dict[str, Any]:
        ...

    def replace(self, kind: str, namespace: str, name: str,
                obj: Dict[str, Any]) -> Dict[str, Any]:
        ...

    def delete(self, kind: str, namespace: str, name: str) -> None:
        ...

    def update_status(self, kind: str, namespace: str, name: str,
                      status: Dict[str, Any]) -> None:
        ...


SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class InClusterClient:
    """Service-account REST client (stdlib only).

    Speaks to https://$KUBERNETES_SERVICE_HOST with the mounted token +
    cluster CA — the standard in-cluster path the Go operator's rest
    config resolves to.
    """

    def __init__(self, host: Optional[str] = None,
                 token: Optional[str] = None,
                 ca_path: Optional[str] = None):
        self.base = host or (
            f"https://{env_str('KUBERNETES_SERVICE_HOST', required=True)}:"
            f"{env_str('KUBERNETES_SERVICE_PORT')}")
        # bound service-account tokens rotate on disk (~hourly); keep the
        # PATH and re-read per request so the operator survives rotation
        self._token = token
        self._token_path = (None if token is not None
                            else os.path.join(SA_DIR, "token"))
        ctx = ssl.create_default_context(
            cafile=ca_path or os.path.join(SA_DIR, "ca.crt"))
        self._ctx = ctx

    def _bearer(self) -> str:
        if self._token_path is not None:
            with open(self._token_path) as f:
                return f.read().strip()
        return self._token

    def _req(self, method: str, path: str,
             body: Optional[Dict[str, Any]] = None) -> Any:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self.base + path, data=data, method=method,
            headers={"Authorization": f"Bearer {self._bearer()}",
                     "Content-Type": "application/json",
                     "Accept": "application/json"})
        try:
            with urllib.request.urlopen(req, context=self._ctx) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as exc:
            # absent-object 404s are an expected answer only for reads and
            # deletes; a 404 on POST/PUT (missing CRD, missing namespace,
            # RBAC misroute) is a real failure that must surface
            if exc.code == 404 and method in ("GET", "DELETE"):
                return None
            raise

    def _path(self, kind: str, namespace: str, name: str = "") -> str:
        api, plural = _ROUTES[kind]
        p = f"/{api}/namespaces/{namespace}/{plural}"
        return f"{p}/{name}" if name else p

    def list(self, kind, namespace, label_selector=None):
        path = self._path(kind, namespace)
        if label_selector:
            path += f"?labelSelector={urllib.request.quote(label_selector)}"
        res = self._req("GET", path)
        return (res or {}).get("items", [])

    def get(self, kind, namespace, name):
        return self._req("GET", self._path(kind, namespace, name))

    def create(self, kind, namespace, obj):
        return self._req("POST", self._path(kind, namespace), obj)

    def replace(self, kind, namespace, name, obj):
        return self._req("PUT", self._path(kind, namespace, name), obj)

    def delete(self, kind, namespace, name):
        # explicit Background propagation: batch/v1 Jobs default to
        # ORPHAN over the raw REST API (unlike kubectl) — a bare DELETE
        # would leave the old seed pod running and writing to the PVC
        # beside its replacement
        self._req("DELETE", self._path(kind, namespace, name),
                  body={"kind": "DeleteOptions", "apiVersion": "v1",
                        "propagationPolicy": "Background"})

    def update_status(self, kind, namespace, name, status):
        cur = self.get(kind, namespace, name)
        if cur is None:
            return
        cur["status"] = status
        self._req("PUT", self._path(kind, namespace, name) + "/status", cur)
