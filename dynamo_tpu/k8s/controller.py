"""DynamoDeployment reconcile controller.

The convergence loop the reference implements in Go
(deploy/dynamo/operator internal/controller/
dynamodeployment_controller.go): observe DynamoDeployment CRs, expand each
through the pure renderer (render.py), and drive the cluster toward that
desired state — create missing children, replace drifted ones, delete
orphans, stamp ownerReferences for garbage collection, and publish
phase/readyServices on the CR status subresource.

Level-triggered: ``reconcile_all`` is safe to call from a watch event, a
poll tick, or a test — it recomputes everything from observed state. The
controller owns only objects it labels ``app.kubernetes.io/managed-by:
dynamo-tpu-operator``; it never touches anything else.
"""

from __future__ import annotations

import asyncio
import copy
import json
import hashlib
import logging
from typing import Any, Dict, List, Optional, Tuple

from .client import KubeClient
from .render import render, render_model_request

log = logging.getLogger("dynamo_tpu.k8s")

MANAGED_BY = "dynamo-tpu-operator"
OWNER_KIND_LABEL = "dynamo-tpu.dev/owner-kind"
# kinds the controller owns; VirtualService only exists on Istio clusters
MANAGED_KINDS = ("Deployment", "Service", "ConfigMap", "Ingress",
                 "VirtualService", "Job", "PersistentVolumeClaim")
OPTIONAL_KINDS = frozenset({"VirtualService"})
# PVC spec is immutable (and holds model data): create once, never
# replace on drift; Jobs' pod templates are immutable too — a changed
# render is applied by DELETE + recreate, not PUT
CREATE_ONLY = frozenset({"PersistentVolumeClaim"})
RECREATE_ON_DRIFT = frozenset({"Job"})
SPEC_HASH_ANN = "dynamo-tpu.dev/spec-hash"


def _spec_hash(obj: Dict[str, Any]) -> str:
    return hashlib.sha256(
        json.dumps(obj, sort_keys=True).encode()).hexdigest()[:16]


def _key(obj: Dict[str, Any]) -> Tuple[str, str]:
    return obj["kind"], obj["metadata"]["name"]


def _owned_fields_drifted(want: Any, have: Any) -> bool:
    """True when any field the controller OWNS (present in the rendered
    object) differs in the observed one. Server-added fields (defaults,
    status, timestamps) are ignored — they're not in `want`. This is what
    catches `kubectl scale`-style edits that leave the spec-hash
    annotation untouched."""
    if isinstance(want, dict):
        if not isinstance(have, dict):
            return True
        return any(_owned_fields_drifted(v, have.get(k))
                   for k, v in want.items())
    if isinstance(want, list):
        # element-wise, not atomic: the apiserver defaults fields INSIDE
        # list items too (containers[].imagePullPolicy, ports[].protocol)
        # and those additions must not read as drift. Extra elements the
        # cluster added (admission-webhook sidecars) are tolerated for
        # the same reason server-added dict keys are; missing ones are
        # drift.
        if not isinstance(have, list):
            return True
        if want and all(isinstance(w, dict) for w in want):
            # object lists (containers, env, ports): match by name like
            # server-side-apply, so a webhook PRE/APPENDING an element
            # (injected sidecar) doesn't misalign the comparison or read
            # as drift. "name" is OPTIONAL on some of these (single-port
            # Services) — unnamed wanted elements match in order against
            # the observed unnamed elements, so a server-appended named
            # element never re-reads as drift on every reconcile tick
            # (which would hot-loop replaces against the apiserver)
            by_name = {h.get("name"): h for h in have
                       if isinstance(h, dict)}
            unnamed_have = [h for h in have
                            if not (isinstance(h, dict) and "name" in h)]
            ui = 0
            for w in want:
                if "name" in w:
                    if (w["name"] not in by_name
                            or _owned_fields_drifted(w, by_name[w["name"]])):
                        return True
                else:
                    if (ui >= len(unnamed_have)
                            or _owned_fields_drifted(w, unnamed_have[ui])):
                        return True
                    ui += 1
            return False
        # scalar/unnamed lists (args, command): the server never appends
        # to these, so any length change — including a kubectl-edit that
        # appends a flag — is drift to heal
        return (len(want) != len(have)
                or any(_owned_fields_drifted(w, h)
                       for w, h in zip(want, have)))
    return want != have


class Reconciler:
    def __init__(self, client: KubeClient):
        self.client = client

    # ------------------------------------------------------------ converge

    def reconcile_all(self, namespace: str) -> None:
        # list each managed kind ONCE per pass and partition by instance
        # label — per-CR listing would cost 3N+1 apiserver calls per tick
        # partition by (owning CR kind, instance): a DynamoDeployment and
        # a DynamoModelRequest sharing one name (the natural pairing) must
        # never see — and orphan-delete — each other's children
        observed_by_cr: Dict[Tuple[str, str],
                             Dict[Tuple[str, str], Dict[str, Any]]] = {}
        for kind in MANAGED_KINDS:
            sel = f"app.kubernetes.io/managed-by={MANAGED_BY}"
            for obj in self._list_tolerant(kind, namespace, sel):
                labels = obj.get("metadata", {}).get("labels", {})
                inst = labels.get("app.kubernetes.io/instance")
                # children stamped before the owner-kind label existed
                # default to DynamoDeployment (the only CR kind then)
                okind = labels.get(OWNER_KIND_LABEL, "DynamoDeployment")
                if inst is not None:
                    observed_by_cr.setdefault(
                        (okind, inst), {})[_key(obj)] = obj
        for cr_kind in ("DynamoDeployment", "DynamoModelRequest"):
            for cr in self.client.list(cr_kind, namespace):
                cr.setdefault("kind", cr_kind)
                name = cr.get("metadata", {}).get("name")
                try:
                    self.reconcile(
                        cr, observed=observed_by_cr.get((cr_kind, name)))
                except Exception:  # noqa: BLE001 — one bad CR must not
                    log.exception("reconcile failed for %s", name)  # wedge

    def _observe(self, ns: str, name: str, cr_kind: str
                 ) -> Dict[Tuple[str, str], Dict[str, Any]]:
        selector = (f"app.kubernetes.io/managed-by={MANAGED_BY},"
                    f"app.kubernetes.io/instance={name},"
                    f"{OWNER_KIND_LABEL}={cr_kind}")
        observed: Dict[Tuple[str, str], Dict[str, Any]] = {}
        for kind in MANAGED_KINDS:
            for obj in self._list_tolerant(kind, ns, selector):
                observed[_key(obj)] = obj
        return observed

    def _list_tolerant(self, kind: str, ns: str, selector: str):
        """List a managed kind, tolerating clusters without the optional
        networking CRDs (Istio VirtualService): a NOT-FOUND on the route
        means "none exist", not a reconcile failure — a CR that never
        asks for Istio must reconcile cleanly on a vanilla cluster.
        ONLY not-found qualifies: a 403/timeout/500 on an optional kind
        must still surface (demoting it would make a transient apiserver
        error indistinguishable from "Istio not installed" and hot-loop
        create→409 against existing objects)."""
        try:
            out = []
            for obj in self.client.list(kind, ns, label_selector=selector):
                obj.setdefault("kind", kind)
                out.append(obj)
            return out
        except Exception as e:  # noqa: BLE001
            msg = str(e).lower()
            if kind in OPTIONAL_KINDS and (
                    "404" in msg or "not found" in msg
                    or "could not find" in msg):
                log.debug("optional kind %s unavailable: %s", kind, e)
                return []
            raise

    def reconcile(self, cr: Dict[str, Any],
                  observed: Optional[Dict[Tuple[str, str],
                                          Dict[str, Any]]] = None) -> None:
        """Converge one DynamoDeployment toward its rendered manifests."""
        meta = cr["metadata"]
        name, ns = meta["name"], meta.get("namespace", "default")
        cr_kind = cr.get("kind", "DynamoDeployment")
        renderer = (render_model_request
                    if cr_kind == "DynamoModelRequest" else render)
        owner_ref = {
            "apiVersion": cr.get("apiVersion", "dynamo-tpu.dev/v1alpha1"),
            "kind": cr_kind,
            "name": name,
            "uid": meta.get("uid", ""),
            "controller": True,
            "blockOwnerDeletion": True,
        }
        desired: Dict[Tuple[str, str], Dict[str, Any]] = {}
        for obj in renderer(cr):
            obj = copy.deepcopy(obj)
            m = obj.setdefault("metadata", {})
            m.setdefault("labels", {})[
                "app.kubernetes.io/managed-by"] = MANAGED_BY
            m["labels"]["app.kubernetes.io/instance"] = name
            m["labels"][OWNER_KIND_LABEL] = cr_kind
            m["ownerReferences"] = [owner_ref]
            m.setdefault("annotations", {})[SPEC_HASH_ANN] = _spec_hash(obj)
            desired[_key(obj)] = obj

        if observed is None:
            observed = self._observe(ns, name, cr_kind)
        else:
            observed = dict(observed)

        for key, want in desired.items():
            kind, oname = key
            have = observed.get(key)
            if have is None:
                log.info("create %s/%s", kind, oname)
                observed[key] = self.client.create(kind, ns, want) or want
                continue
            hash_drift = (have.get("metadata", {}).get("annotations", {})
                          .get(SPEC_HASH_ANN)
                          != want["metadata"]["annotations"][SPEC_HASH_ANN])
            # spec-hash catches render changes; the field diff catches
            # kubectl-scale-style edits that leave annotations untouched
            field_drift = any(
                _owned_fields_drifted(want.get(sect), have.get(sect))
                for sect in ("spec", "data"))
            if (hash_drift or field_drift) and kind in CREATE_ONLY:
                # immutable spec (PVC): the object exists, leave it be —
                # resize/class changes need operator intervention anyway
                log.debug("skip drift on create-only %s/%s", kind, oname)
                continue
            if (hash_drift or field_drift) and kind in RECREATE_ON_DRIFT:
                # immutable pod template (Job): apply by delete+create
                log.info("recreate %s/%s", kind, oname)
                self.client.delete(kind, ns, oname)
                observed[key] = self.client.create(kind, ns, want) or want
                continue
            if hash_drift or field_drift:
                # replace with the rendered truth, keeping resourceVersion
                # so the API server's optimistic concurrency applies
                rv = have.get("metadata", {}).get("resourceVersion")
                if rv is not None:
                    want["metadata"]["resourceVersion"] = rv
                if kind == "Service":
                    # carry over the server-allocated immutable fields: a
                    # PUT without spec.clusterIP is rejected with 422
                    # "field is immutable" by a real apiserver
                    for f in ("clusterIP", "clusterIPs", "ipFamilies",
                              "ipFamilyPolicy"):
                        v = (have.get("spec") or {}).get(f)
                        if v is not None and f not in want["spec"]:
                            want["spec"][f] = v
                log.info("replace %s/%s", kind, oname)
                observed[key] = (self.client.replace(kind, ns, oname, want)
                                 or want)

        for key, have in list(observed.items()):
            if key not in desired:
                log.info("delete orphan %s/%s", *key)
                self.client.delete(key[0], ns, key[1])

        if cr_kind == "DynamoModelRequest":
            self._update_model_request_status(cr, ns, name, observed)
        else:
            self._update_status(cr, ns, name, desired, observed)

    def _update_model_request_status(self, cr, ns, name,
                                     observed) -> None:
        """Seeding/Ready/Failed from the seeding Job's CONDITIONS — the
        reference's ModelsSeeding / ModelsExists conditions
        (dynamoinimrequest_types.go:28-33), collapsed to a phase.
        Conditions, not the failed/succeeded counters: under
        restartPolicy OnFailure retries are in-pod container restarts
        that never increment status.failed, so a crash-looping seed
        would read as "Seeding" forever from counters alone."""
        job = observed.get(("Job", f"{name}-seed")) or {}
        st = job.get("status") or {}
        conds = {c.get("type"): c.get("status")
                 for c in st.get("conditions") or []}
        # `or 0`, not a .get default: the API server can report an
        # explicit `"succeeded": null`, which .get passes through
        if conds.get("Complete") == "True" or (st.get("succeeded") or 0) >= 1:
            phase = "Ready"
        elif conds.get("Failed") == "True":
            phase = "Failed"
        else:
            phase = "Seeding"
        # same claim resolution as the renderer — an existingClaim CR
        # renders no PVC but its claim is still the one seeded into
        spec = cr.get("spec") or {}
        claim = spec.get("existingClaim") or f"{name}-models"
        self.client.update_status(
            "DynamoModelRequest", ns, name,
            {"phase": phase, "claim": claim})

    def _update_status(self, cr, ns, name, desired, observed) -> None:
        """phase + readyServices from the Deployment readiness already in
        hand this tick (reference controller's status conditions,
        simplified; one-tick-stale is fine under level triggering)."""
        want_deps = [k for k in desired if k[0] == "Deployment"]
        ready = 0
        for key in want_deps:
            d = observed.get(key) or {}
            spec_replicas = (d.get("spec") or {}).get("replicas", 1)
            if (d.get("status") or {}).get("readyReplicas", 0) >= \
                    spec_replicas:
                ready += 1
        phase = "Ready" if ready == len(want_deps) else "Progressing"
        self.client.update_status(
            "DynamoDeployment", ns, name,
            {"phase": phase, "readyServices": ready})

    # ---------------------------------------------------------------- loop

    async def run_async(self, namespace: str,
                        interval: float = 10.0) -> None:
        """Poll-based level-triggered loop (a watch is an optimization the
        fake-client tests don't need; the reconcile itself is identical).
        Transient API failures (token rotation races, apiserver restarts)
        back off and retry — the operator pod must not crash-loop on
        them. The reconcile pass itself is synchronous HTTP against the
        apiserver, so it runs in a worker thread: anything else sharing
        this event loop (health endpoints, future watches) keeps serving
        during a slow pass, and the retry sleep never blocks the loop."""
        log.info("dynamo-tpu operator reconciling namespace %s", namespace)
        backoff = interval
        while True:
            try:
                await asyncio.to_thread(self.reconcile_all, namespace)
                backoff = interval
            except Exception:  # noqa: BLE001
                log.exception("reconcile pass failed; backing off %.0fs",
                              backoff)
                backoff = min(backoff * 2, 300.0)
            await asyncio.sleep(backoff)

    def run(self, namespace: str, interval: float = 10.0) -> None:
        """Blocking entrypoint for the operator main()."""
        asyncio.run(self.run_async(namespace, interval))


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="dynamo-tpu-operator")
    ap.add_argument("--namespace", default="default")
    ap.add_argument("--interval", type=float, default=10.0)
    ap.add_argument("--once", action="store_true",
                    help="single reconcile pass (CI / cron mode)")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    from .client import InClusterClient

    rec = Reconciler(InClusterClient())
    if args.once:
        rec.reconcile_all(args.namespace)
        return 0
    rec.run(args.namespace, args.interval)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
