"""Virtual clock for the fleet simulator.

Every time-dependent component in a simulated fleet — the planner's
cooldown hysteresis, advisory ``at`` stamps, request latency records —
reads the same :class:`VirtualClock` instead of wall time. The clock only
advances when the step loop says so, which is what makes a run
deterministic: two runs with the same seed perform the same operations at
the same virtual instants regardless of host speed.

Real async I/O (DCP round trips, HTTP, watch fanout) still happens on the
wall clock *between* virtual instants; the harness quiesces each step
before advancing, so wall latency never leaks into a report.
"""

from __future__ import annotations

from typing import Optional


class VirtualClock:
    """A manually-advanced clock. ``now()`` is a drop-in for both
    ``time.monotonic`` and ``time.time`` hooks (the simulated epoch starts
    at 0.0)."""

    def __init__(self, step_seconds: float = 1.0):
        self.step_seconds = step_seconds
        self._now = 0.0

    def now(self) -> float:
        return self._now

    def advance(self, dt: Optional[float] = None) -> float:
        """Advance by ``dt`` virtual seconds (default: one step)."""
        self._now += self.step_seconds if dt is None else dt
        return self._now

    @property
    def step(self) -> int:
        """The current step index (``now / step_seconds``)."""
        return int(round(self._now / self.step_seconds))
