"""In-process fleet controller: the actuator that closes the planner loop.

The planner publishes every :class:`ScaleAdvisory` on
``<ns>.planner.advisory`` and (with ``--apply``) edits the stored
deployment spec — but nothing in-process ever *acted* on an advisory
before. This controller subscribes to the advisory subject and actually
converges the worker pool: scale-up spawns fresh :class:`SimWorker`
instances (each on its own runtime/lease), scale-down drains the
newest workers first and retires them once idle.

Safety mirrors of the planner's own rules:

- an advisory with ``current_replicas == 0`` is **ignored** — zero
  observed is ambiguous between "scaled to zero" and "scrape blackout",
  and acting on it would tear down a live-but-unobservable pool
  (planner/policy.py documents the same never-apply rule);
- the pool is hard-capped by ``DYN_FLEET_MAX_WORKERS`` no matter what
  the advisory asks for.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Awaitable, Callable, Dict, List, Optional

from ..planner.policy import PLANNER_ADVISORY_SUBJECT
from ..runtime.config import env_int
from ..runtime.dcp_client import unpack
from ..runtime.runtime import DistributedRuntime
from .worker import SimWorker

log = logging.getLogger("dynamo_tpu.fleet.controller")

# worker_factory(name) -> started SimWorker
WorkerFactory = Callable[[str], Awaitable[SimWorker]]


class FleetController:
    """Subscribes to planner advisories and spawns/retires SimWorkers."""

    def __init__(self, drt: DistributedRuntime, namespace: str,
                 component: str, worker_factory: WorkerFactory,
                 max_workers: Optional[int] = None):
        self.drt = drt
        self.namespace = namespace
        self.component = component
        self.worker_factory = worker_factory
        self.max_workers = max_workers if max_workers is not None \
            else (env_int("DYN_FLEET_MAX_WORKERS") or 64)
        self.workers: Dict[str, SimWorker] = {}     # name -> live worker
        self.retired: List[SimWorker] = []          # kept for teardown
        self.advisories_seen: List[dict] = []       # raw bus payloads
        self._acted = 0                             # advisories consumed
        self._spawned = 0                           # name counter
        self._sid: Optional[int] = None

    async def start(self) -> None:
        self._sid = await self.drt.dcp.subscribe(
            f"{self.namespace}.{PLANNER_ADVISORY_SUBJECT}", self._on_adv)

    async def stop(self) -> None:
        # claim the subscription before the await: a concurrent stop()
        # interleaving at the unsubscribe must not double-unsubscribe
        sid, self._sid = self._sid, None
        if sid is not None:
            try:
                await self.drt.dcp.unsubscribe(sid)
            except Exception:
                log.debug("unsubscribe failed during stop", exc_info=True)

    async def _on_adv(self, msg) -> None:
        try:
            self.advisories_seen.append(unpack(msg.payload))
        except Exception:
            log.exception("bad advisory payload")

    # ---------------------------------------------------------- actuation

    @property
    def live(self) -> List[SimWorker]:
        """Healthy, non-draining workers, in spawn order."""
        return [w for w in self.workers.values()
                if not w.model.crashed and not w.draining]

    async def wait_advisories(self, expected: int,
                              timeout: float = 5.0) -> None:
        """Wait (wall-bounded) for the pub/sub fanout to deliver
        ``expected`` advisories to this subscriber."""
        deadline = asyncio.get_running_loop().time() + timeout
        while (len(self.advisories_seen) < expected
               and asyncio.get_running_loop().time() < deadline):
            await asyncio.sleep(0.005)

    async def reconcile(self) -> List[dict]:
        """Act on advisories received since the last call. Returns a list
        of action dicts (for the scorer's actuation timeline)."""
        actions: List[dict] = []
        while self._acted < len(self.advisories_seen):
            adv = self.advisories_seen[self._acted]
            self._acted += 1
            if adv.get("component") != self.component:
                continue
            if int(adv.get("current_replicas", 0)) <= 0:
                # zero-observed: never actuate (scrape blackout vs real
                # scale-to-zero is indistinguishable here)
                log.info("ignoring zero-observed advisory for %s",
                         self.component)
                actions.append({"action": "ignored-zero-observed",
                                "desired": int(adv["desired_replicas"]),
                                "workers": []})
                continue
            if adv.get("kind") == "pd_shift":
                # dynaslo P/D rebalance: flip ONE worker's role in place
                # (newest of the donor role first — mirrors newest-first
                # scale-down); the scheduler honors the flip on its next
                # scrape, total replica count unchanged
                frm, to = adv.get("shift_from"), adv.get("shift_to")
                donors = [w for w in self.live if w.model.role == frm]
                if donors:
                    w = donors[-1]
                    # proto: planner.pd_shift advisory->actuated
                    w.set_role(to)
                    log.info("fleet controller pd-shift: %s %s->%s",
                             w.name, frm, to)
                    actions.append({"action": f"pd-shift:{frm}->{to}",
                                    "desired":
                                        int(adv["desired_replicas"]),
                                    "workers": [w.name]})
                else:
                    # proto: planner.pd_shift advisory->idle
                    actions.append({"action": "pd-shift-no-donor",
                                    "desired":
                                        int(adv["desired_replicas"]),
                                    "workers": []})
                continue
            desired = min(int(adv["desired_replicas"]), self.max_workers)
            live = self.live
            if desired > len(live):
                names = [await self._spawn()
                         for _ in range(desired - len(live))]
                actions.append({"action": "scale-up", "desired": desired,
                                "workers": names})
            elif desired < len(live):
                names = []
                for w in reversed(live):        # newest-first
                    if len(self.live) <= desired:
                        break
                    await self._drain(w)
                    names.append(w.name)
                actions.append({"action": "scale-down", "desired": desired,
                                "workers": names})
        return actions

    async def _spawn(self) -> str:
        name = f"w{self._spawned:03d}"
        self._spawned += 1
        worker = await self.worker_factory(name)
        self.workers[name] = worker
        log.info("fleet controller spawned %s (instance %x)", name,
                 worker.instance_id)
        return name

    async def spawn_initial(self, n: int) -> List[str]:
        return [await self._spawn() for _ in range(n)]

    async def _drain(self, worker: SimWorker) -> None:
        # SimWorker.drain is a sim-model state flip, not a socket drain
        await worker.drain()  # dynalint: disable=unbounded-await
        log.info("fleet controller draining %s", worker.name)

    async def retire_idle_drained(self) -> List[str]:
        """Shut down drained workers whose in-flight work has finished."""
        out = []
        for name, w in list(self.workers.items()):
            if w.draining and w.model.idle:
                await w.stop()
                self.retired.append(w)
                del self.workers[name]
                out.append(name)
        return out

    async def teardown(self) -> None:
        await self.stop()
        for w in list(self.workers.values()):
            try:
                await w.stop()
            except Exception:
                log.debug("worker %s teardown failed", w.name,
                          exc_info=True)
        self.workers.clear()
