"""Scripted fleet workers: a real serving endpoint over a virtual-time
service model.

Each :class:`SimWorker` owns its own :class:`DistributedRuntime`
attachment (own lease → own instance id, exactly like a separate worker
process) and serves the token-level ``generate_tokens`` endpoint the real
processor/KV-router path calls into. What it does *not* do is run a model:
service is simulated by :class:`SimEngineModel`, a discrete queueing model
advanced one virtual step at a time by the harness —

- arrivals enter a FIFO queue (``num_requests_waiting``),
- up to ``slots`` requests are in service; each consumes
  ``prefill_steps`` steps of prefill, then releases
  ``tokens_per_step`` output tokens per step until its budget is spent,
- every lifecycle stamp (arrival, admission, first token, done) is a
  virtual-clock value written synchronously inside ``step()``,

so latency percentiles are exact functions of the trace + fleet size, not
of host speed. The endpoint handler bridges the model to the real wire:
it parks on the request's event queue and yields ``EngineOutput`` frames
as the model releases tokens.

Fault hooks (scenario-scripted):

- ``crash()``   — drop the request-plane subscriptions *without*
  deregistering discovery (the lease keepalive is still running, exactly
  like a wedged process), and error every in-flight stream. The stale
  instance record is what the Client eviction path must clean up.
- ``blackout(on)`` — the stats handler raises, simulating a scrape
  blackout while serving continues.
- ``drain()``  — graceful scale-down: deregister from discovery, finish
  what's in flight, then shut down.
"""

from __future__ import annotations

import asyncio
import logging
from collections import deque
from dataclasses import dataclass
from typing import Callable, Deque, List, Optional, Tuple

from ..engine.kv_manager import chain_hashes
from ..llm.kv_router.protocols import (KV_EVENT_SUBJECT, ForwardPassMetrics,
                                       KvCacheEventWire)
from ..llm.protocols.common import EngineOutput, PreprocessedRequest
from ..runtime.dcp_client import pack
from ..runtime.engine import Context
from ..runtime.runtime import DistributedRuntime
from ..runtime.slo import LatencyRecorder

log = logging.getLogger("dynamo_tpu.fleet.worker")

_CRASH = object()   # sentinel pushed into request event queues on crash


@dataclass(frozen=True)
class WorkerProfile:
    """Scripted service capacity of one worker."""

    slots: int = 4                  # concurrent in-service requests
    total_slots: int = 32           # advertised slot capacity (saturation)
    prefill_steps: int = 1          # virtual steps of prefill per request
    tokens_per_step: int = 8        # decode tokens released per step
    kv_total_blocks: int = 4096
    publish_kv_events: bool = True  # feed the router's radix index
    # dynaslo P/D modeling (all default-off: the legacy scenarios'
    # behavior is bit-identical when unset):
    # remote_prefill — admitted requests enqueue their prompt into the
    # harness's shared PrefillPool instead of counting local
    # prefill_steps; the first token releases once the pool has
    # processed the prompt (disagg: prefill capacity is fleet-shared).
    remote_prefill: bool = False
    # tokens of shared prefill capacity ONE prefill-role worker
    # contributes to the pool per virtual step
    prefill_tokens_per_step: int = 0
    # shared decode-token budget per worker per step, split evenly over
    # in-decode requests (each still capped by tokens_per_step) — decode
    # contention now shows up as ITL, not just queue wait. 0 = legacy
    # fixed tokens_per_step per request.
    decode_budget_per_step: int = 0


class _SimRequest:
    """One request inside the model."""

    __slots__ = ("rid", "token_ids", "max_tokens", "prompt_tokens",
                 "prefill_left", "tokens_left", "events", "finished",
                 "arrival_vt", "pool_left", "pool_done", "last_tok_vt")

    def __init__(self, rid: str, token_ids: List[int], max_tokens: int,
                 prefill_steps: int):
        self.rid = rid
        self.token_ids = token_ids
        self.prompt_tokens = len(token_ids)
        self.max_tokens = max_tokens
        self.prefill_left = max(prefill_steps, 1)
        self.tokens_left = max(max_tokens, 1)
        self.events: asyncio.Queue = asyncio.Queue()
        self.finished = False
        # dynaslo: virtual-time stamps for the worker-side latency
        # histograms + shared-prefill-pool state (remote_prefill mode)
        self.arrival_vt: float = 0.0
        self.pool_left: int = 0
        self.pool_done = False
        self.last_tok_vt: Optional[float] = None


class PrefillPool:
    """Shared prefill capacity (dynaslo P/D modeling): prefill-role
    workers pool their ``prefill_tokens_per_step`` and prompts drain
    FIFO — exactly the disagg shared-queue shape, so shifting a worker
    decode→prefill raises fleet prefill throughput one step later."""

    def __init__(self) -> None:
        self.jobs: Deque[_SimRequest] = deque()
        self.enqueued_total = 0
        self.completed_total = 0

    def enqueue(self, req: _SimRequest) -> None:
        req.pool_left = max(req.prompt_tokens, 1)
        self.jobs.append(req)
        self.enqueued_total += 1

    @property
    def depth(self) -> int:
        return len(self.jobs)

    def backlog_tokens(self) -> int:
        return sum(r.pool_left for r in self.jobs)

    def step(self, capacity: int) -> None:
        """Drain up to ``capacity`` prompt tokens FIFO; jobs whose
        request finished meanwhile (crash/abandon) are skipped free."""
        while self.jobs and capacity > 0:
            job = self.jobs[0]
            if job.finished:
                self.jobs.popleft()
                continue
            take = min(capacity, job.pool_left)
            job.pool_left -= take
            capacity -= take
            if job.pool_left <= 0:
                job.pool_done = True
                self.jobs.popleft()
                self.completed_total += 1


class SimEngineModel:
    """Discrete-time queueing model behind one worker endpoint."""

    def __init__(self, name: str, profile: WorkerProfile, block_size: int,
                 clock: Callable[[], float],
                 on_lifecycle: Callable[[str, str, float], None],
                 role: str = "unified",
                 pool: Optional[PrefillPool] = None):
        """``clock`` is the shared virtual clock; ``on_lifecycle(rid,
        event, vt)`` with events ``enqueued|admitted|first_token|done|
        crashed`` feeds the scorer."""
        self.name = name
        self.profile = profile
        self.block_size = block_size
        self.clock = clock
        self.on_lifecycle = on_lifecycle
        # dynashard: stable per-replica identity + modeled submesh size,
        # riding the same ForwardPassMetrics fields the real sharded
        # engine exports (the aggregator's `replica` gauge label)
        self.worker_label = name
        self.mesh_devices = 1
        # dynaslo: serving role + per-role latency histograms in virtual
        # time (deterministic), riding the same FPM fields as the real
        # engine; the shared PrefillPool models disagg prefill capacity
        self.role = role
        self.pool = pool
        self.latency = LatencyRecorder(role)
        self.queue: Deque[_SimRequest] = deque()
        self.active: List[_SimRequest] = []
        self.crashed = False
        self.blackout = False
        self.served_total = 0
        self._stored_blocks: int = 0   # modeled resident cache blocks
        # dynacache: modeled engine-side prefix cache — the set of block
        # hashes this worker has stored; a new prompt's REALIZED hit is
        # its longest leading chain already present. Virtual-state only,
        # so seeded reports stay byte-identical.
        self._stored_hashes: set = set()
        self.realized_hit_blocks: int = 0
        self.prompt_blocks_total: int = 0

    # ------------------------------------------------------------ intake

    def set_role(self, role: str) -> None:
        """dynaslo P/D rebalance: flip this worker's serving role live.
        The KV scheduler stops/starts offering it decode work from the
        next scrape; in-flight requests run to completion; latency
        observations before the flip stay attributed to the old role."""
        self.role = role
        self.latency.role = role

    def submit(self, rid: str, token_ids: List[int],
               max_tokens: int) -> _SimRequest:
        if self.crashed:
            raise RuntimeError(f"worker {self.name} crashed")
        req = _SimRequest(rid, token_ids, max_tokens,
                          self.profile.prefill_steps)
        req.arrival_vt = self.clock()
        self.queue.append(req)
        self.on_lifecycle(rid, "enqueued", self.clock())
        return req

    def abandon(self, req: _SimRequest) -> None:
        """Client went away mid-stream: free the slot/queue entry."""
        if req in self.active:
            self.active.remove(req)
        elif req in self.queue:
            self.queue.remove(req)

    # ------------------------------------------------------------- step

    def step(self) -> List[Tuple[List[int], Optional[int]]]:
        """Advance one virtual step at the clock's current time. Returns
        the KV 'stored' events (block-hash chains) for prompts admitted
        this step, for the harness to publish on the bus."""
        vt = self.clock()
        if self.crashed:
            return []
        kv_events: List[Tuple[List[int], Optional[int]]] = []
        # admit from the FIFO into free slots
        while self.queue and len(self.active) < self.profile.slots:
            req = self.queue.popleft()
            self.active.append(req)
            self.on_lifecycle(req.rid, "admitted", vt)
            self.latency.observe("queue_wait", vt - req.arrival_vt)
            if self.profile.remote_prefill and self.pool is not None:
                # disagg shape: the prompt's prefill is fleet-shared —
                # this request decodes once the pool has chewed through
                # its prompt tokens (FIFO across all decode workers)
                self.pool.enqueue(req)
            if self.profile.publish_kv_events and req.token_ids:
                hashes = chain_hashes(req.token_ids, self.block_size)
                if hashes:
                    # realized engine-side hit: the longest leading chain
                    # already stored on THIS worker (the router's overlap
                    # prediction is scored against this in the report's
                    # cache block)
                    hit = 0
                    for h in hashes:
                        if h not in self._stored_hashes:
                            break
                        hit += 1
                    self.realized_hit_blocks += hit
                    self.prompt_blocks_total += len(hashes)
                    self._stored_hashes.update(hashes)
                    kv_events.append((hashes, None))
                    self._stored_blocks = min(
                        self._stored_blocks + len(hashes),
                        self.profile.kv_total_blocks)
        # advance in-service requests: pass 1 resolves prefill (local
        # countdown, or the shared pool's verdict in remote mode) and
        # collects the decode-ready set
        in_decode: List[_SimRequest] = []
        for req in list(self.active):
            if self.profile.remote_prefill and self.pool is not None:
                if not req.pool_done:
                    continue          # prompt still in the shared pool
                if req.prefill_left > 0:
                    # pool finished since last step → first-token boundary
                    req.prefill_left = 0
                    self.on_lifecycle(req.rid, "first_token", vt)
                    self.latency.observe("ttft", vt - req.arrival_vt)
            else:
                if req.prefill_left > 0:
                    req.prefill_left -= 1
                    if req.prefill_left > 0:
                        continue
                    # prefill completed this step → first token batch
                    self.on_lifecycle(req.rid, "first_token", vt)
                    self.latency.observe("ttft", vt - req.arrival_vt)
            in_decode.append(req)
        # pass 2 releases decode tokens. Legacy (budget 0): every request
        # gets its full tokens_per_step. Budget mode: the worker's shared
        # decode throughput splits evenly (deterministic remainder order),
        # still per-request capped — contention degrades ITL, the signal
        # the P/D rebalance loop must NOT regress.
        budget = self.profile.decode_budget_per_step
        if budget > 0 and in_decode:
            base, rem = divmod(budget, len(in_decode))
            grants = [base + (1 if i < rem else 0)
                      for i in range(len(in_decode))]
        else:
            grants = [self.profile.tokens_per_step] * len(in_decode)
        for req, grant in zip(in_decode, grants):
            n = min(self.profile.tokens_per_step, grant, req.tokens_left)
            if n <= 0:
                continue              # budget-starved this step
            if req.last_tok_vt is not None:
                # n per-token gaps of (gap / n): window size never skews
                # the per-token ITL distribution
                self.latency.observe(
                    "itl", (vt - req.last_tok_vt) / n, n)
            req.last_tok_vt = vt
            req.tokens_left -= n
            done = req.tokens_left <= 0
            req.events.put_nowait((n, "length" if done else None))
            if done:
                req.finished = True
                self.active.remove(req)
                self.served_total += 1
                self.on_lifecycle(req.rid, "done", vt)
                self.latency.observe("e2e", vt - req.arrival_vt)
        return kv_events

    # ------------------------------------------------------------ faults

    def crash(self) -> None:
        vt = self.clock()
        self.crashed = True
        for req in list(self.active) + list(self.queue):
            req.events.put_nowait(_CRASH)
            self.on_lifecycle(req.rid, "crashed", vt)
        self.active.clear()
        self.queue.clear()

    # ------------------------------------------------------------- stats

    @property
    def idle(self) -> bool:
        return not self.active and not self.queue

    def stats(self) -> dict:
        if self.blackout:
            raise RuntimeError(f"scrape blackout on {self.name}")
        p = self.profile
        inflight_blocks = sum(
            (r.prompt_tokens + self.block_size - 1) // self.block_size
            for r in self.active)
        blocks = min(inflight_blocks + self._stored_blocks,
                     p.kv_total_blocks)
        return ForwardPassMetrics(
            worker_label=self.worker_label,
            mesh_devices=self.mesh_devices,
            # dynaslo: role gates the KV scheduler (prefill-role workers
            # take no routed decode work) and labels the merged latency
            # histograms in the aggregator
            role=self.role,
            latency_hist=self.latency.to_wire(),
            request_active_slots=len(self.active),
            request_total_slots=p.total_slots,
            kv_active_blocks=blocks,
            kv_total_blocks=p.kv_total_blocks,
            num_requests_waiting=len(self.queue),
            gpu_cache_usage_perc=blocks / max(p.kv_total_blocks, 1),
            # dynacache: realized (engine-side) hit rate from the modeled
            # stored-chain set — reported next to the router's predicted
            # avg_hit_rate in the fleet report's cache block
            gpu_prefix_cache_hit_rate=(
                self.realized_hit_blocks
                / max(self.prompt_blocks_total, 1)),
            gpu_prefix_cache_hit_rate_lifetime=(
                self.realized_hit_blocks
                / max(self.prompt_blocks_total, 1)),
            prefix_hit_tokens_total=(self.realized_hit_blocks
                                     * self.block_size),
            prompt_tokens_total=(self.prompt_blocks_total
                                 * self.block_size),
            cache_device_hit_blocks_total=self.realized_hit_blocks,
            # dynaprof gauges, modeled from virtual state only (so seeded
            # reports stay byte-identical): slot utilization stands in
            # for the sampled device fraction; free pages from the block
            # model
            kv_free_blocks=p.kv_total_blocks - blocks,
            device_time_fraction=round(
                len(self.active) / max(p.slots, 1), 4),
        ).to_dict()


class SimWorker:
    """A scripted worker: real endpoint + runtime, simulated service."""

    def __init__(self, drt: DistributedRuntime, namespace: str,
                 component: str, name: str, profile: WorkerProfile,
                 block_size: int, clock: Callable[[], float],
                 on_lifecycle: Callable[[str, str, float], None],
                 endpoint: str = "generate_tokens",
                 submesh: Optional[List[int]] = None,
                 role: str = "unified",
                 prefill_pool: Optional[PrefillPool] = None):
        self.drt = drt
        self.namespace = namespace
        self.component = component
        self.endpoint = endpoint
        self.name = name
        # dynashard scenario: the modeled device ids this replica's
        # submesh occupies (assigned by the harness's DevicePool; None =
        # the unsharded fleet scenarios)
        self.submesh = list(submesh) if submesh else None
        self.model = SimEngineModel(name, profile, block_size, clock,
                                    on_lifecycle, role=role,
                                    pool=prefill_pool)
        if self.submesh:
            self.model.mesh_devices = len(self.submesh)
        self.kv_subject = f"{namespace}.{component}.{KV_EVENT_SUBJECT}"
        self.draining = False
        self._handle = None

    @property
    def instance_id(self) -> int:
        return self.drt.instance_id

    def set_role(self, role: str) -> None:
        self.model.set_role(role)

    async def start(self) -> None:
        comp = self.drt.namespace(self.namespace).component(self.component)
        await comp.create_service()
        self._handle = await comp.endpoint(self.endpoint).serve(
            self._handler, stats_handler=self.model.stats)
        log.info("fleet worker %s serving as instance %x",
                 self.name, self.instance_id)

    async def _handler(self, request: dict, context: Context):
        pre = PreprocessedRequest.from_dict(request)
        req = self.model.submit(context.id,
                                list(pre.token_ids),
                                pre.stop.max_tokens or 16)
        try:
            sent = 0
            while True:
                ev = await req.events.get()
                if ev is _CRASH:
                    raise RuntimeError(
                        f"worker {self.name} crashed mid-stream")
                if context.killed:
                    return
                n, finish = ev
                ids = [pre.token_ids[(sent + i) % max(len(pre.token_ids), 1)]
                       if pre.token_ids else 32 for i in range(n)]
                sent += n
                if n:
                    yield EngineOutput(
                        token_ids=ids,
                        prompt_tokens=pre_prompt_tokens(pre)).to_dict()
                if finish:
                    yield EngineOutput(
                        token_ids=[], finish_reason=finish,
                        prompt_tokens=pre_prompt_tokens(pre)).to_dict()
                    return
        finally:
            if not req.finished:
                self.model.abandon(req)

    async def publish_kv_events(
            self, events: List[Tuple[List[int], Optional[int]]]) -> None:
        """Publish this step's stored-block chains on the router's event
        subject (called by the harness, in deterministic worker order)."""
        if not events:
            return
        payload = pack([KvCacheEventWire(
            worker_id=self.instance_id, kind="stored",
            block_hashes=hashes, parent_hash=parent).to_dict()
            for hashes, parent in events])
        await self.drt.dcp.publish(self.kv_subject, payload)

    # ------------------------------------------------------------ faults

    async def crash(self) -> None:
        """Wedge, don't deregister: subscriptions die but the discovery
        record stays (keepalive thread still renews the lease) — the
        stale-endpoint case the Client eviction path handles."""
        self.model.crash()
        if self._handle:
            for sid in self._handle._sids:
                try:
                    await self.drt.dcp.unsubscribe(sid)
                except Exception:
                    log.debug("unsubscribe during crash failed",
                              exc_info=True)
            self._handle._sids.clear()

    def set_blackout(self, on: bool) -> None:
        self.model.blackout = on

    async def drain(self) -> None:
        """dynarevive graceful drain: leave discovery (no new
        admissions; the handle nacks stragglers) while in-flight
        requests keep stepping to done and their streams finish clean.
        The handle stays owned so ``stop()`` (via retire_idle_drained,
        once the model is idle) completes the state machine."""
        self.draining = True
        handle = self._handle
        if handle:
            await handle.begin_drain()

    async def stop(self) -> None:
        handle, self._handle = self._handle, None
        if handle:
            await handle.stop()
        await self.drt.shutdown()


def pre_prompt_tokens(pre: PreprocessedRequest) -> int:
    return len(pre.token_ids)
