"""Scenario registry for the fleet simulator.

A :class:`Scenario` is the complete, seed-independent *shape* of a run:
fleet sizing, worker service profile, traffic curve factory, planner
thresholds, scripted faults, and SLO targets. ``seed`` is supplied at run
time (`python -m dynamo_tpu.fleet --scenario burst --seed 0`) and only
affects the materialized trace + router tie-breaking — same seed, same
report, byte for byte.

Adding a scenario: build a :class:`Scenario` and register it in
:data:`SCENARIOS` (docs/fleet_sim.md walks through an example).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..planner.policy import PdConfig, PlannerConfig
from .report import SloTargets
from .traffic import TrafficTrace, burst, constant, diurnal, hot_tenant, phased
from .worker import WorkerProfile


@dataclass(frozen=True)
class FaultEvent:
    """One scripted fault, applied at the start of ``step``.

    kinds: ``crash`` (crash the ``arg``-th live worker, mid-stream),
    ``drain`` (gracefully drain the ``arg``-th live worker — discovery
    out first, in-flight finishes; the dynarevive rolling-restart wave),
    ``join`` (spawn one extra worker outside the planner loop — delayed
    join), ``blackout_start`` / ``blackout_end`` (all live workers stop /
    resume answering stats scrapes), ``flap_start`` / ``flap_end``
    (ONE worker — the ``arg``-th — stops/resumes answering: the
    circuit-breaker scenario's flapping instance)."""

    step: int
    kind: str
    arg: int = 0


@dataclass
class Scenario:
    name: str
    steps: int
    traffic: Callable[[int], TrafficTrace]   # seed -> trace
    initial_workers: int = 2
    step_seconds: float = 1.0
    profile: WorkerProfile = field(default_factory=WorkerProfile)
    planner: PlannerConfig = field(default_factory=lambda: PlannerConfig(
        min_replicas=1, max_replicas=6,
        waiting_per_worker_high=2.0,
        scale_up_cooldown_s=8.0, scale_down_cooldown_s=30.0))
    slo: SloTargets = field(default_factory=SloTargets)
    faults: List[FaultEvent] = field(default_factory=list)
    block_size: int = 16
    # step index the "disturbance" (burst / crash window) ends at, for
    # time-to-recover scoring; None = no disturbance
    disturb_end_step: Optional[int] = None
    # close the loop through the k8s reconcile controller in dry-run too
    k8s_dry_run: bool = False
    # extra virtual steps granted after the last arrival to drain queues
    drain_steps: int = 40
    # dynashard: model each replica as a submesh of this many devices
    # drawn from a pool of device_pool_size — the planner then scales
    # SHARDED replicas, and every join/drain re-partitions the submesh
    # assignment through the shared DevicePool (parallel/serving.py).
    # 0/0 = the unsharded scenarios. The pool hard-caps the fleet:
    # device_pool_size // devices_per_replica replicas fit.
    devices_per_replica: int = 0
    device_pool_size: int = 0
    # dynarevive: SLO-aware admission control — shed (early 503 +
    # seeded jittered Retry-After) once the fleet-wide admission queue
    # exceeds this many waiting requests PER live worker. 0 = off.
    shed_queue_depth: int = 0
    # dynaslo: how many of the FIRST spawned workers take the prefill
    # role (only meaningful with profile.remote_prefill — the shared-
    # prefill-pool P/D scenarios); later spawns land decode-side
    initial_prefill_workers: int = 0
    # dynaslo: SLO objectives for the run (DYN_SLO_OBJECTIVES grammar,
    # windows in VIRTUAL seconds), evaluated by the aggregator's
    # SloEngine on the virtual clock; None = no objectives
    slo_objectives: Optional[str] = None
    slo_fast_fraction: float = 0.1
    slo_burn_threshold: float = 2.0
    # dynablack: run a deterministic FlightRecorder on the virtual clock,
    # feed it every lifecycle stamp, trip it on the first fired burn-rate
    # alert, fan the capture out over DCP (every SimWorker contributes
    # its shadow ring) and attach the merged bundle to the report
    capture_incident: bool = False


def _smoke() -> Scenario:
    """Tier-1 smoke: a small burst that must trigger a scale-up and
    recover — the closed-loop regression gate."""
    steps = 26
    return Scenario(
        name="smoke", steps=steps,
        traffic=lambda seed: burst(seed, steps=steps, base_rate=1.0,
                                   burst_rate=6.0, burst_start=6,
                                   burst_end=12, max_tokens=12),
        initial_workers=2,
        profile=WorkerProfile(slots=3, tokens_per_step=6),
        planner=PlannerConfig(min_replicas=2, max_replicas=4,
                              waiting_per_worker_high=2.0,
                              scale_up_cooldown_s=6.0,
                              scale_down_cooldown_s=60.0),
        slo=SloTargets(ttft_p95=4.0, queue_wait_p95=3.0),
        disturb_end_step=12,
        k8s_dry_run=True,
    )


def _burst() -> Scenario:
    steps = 48
    return Scenario(
        name="burst", steps=steps,
        traffic=lambda seed: burst(seed, steps=steps, base_rate=2.0,
                                   burst_rate=8.0, burst_start=10,
                                   burst_end=22, max_tokens=16),
        initial_workers=2,
        planner=PlannerConfig(min_replicas=2, max_replicas=6,
                              waiting_per_worker_high=2.0,
                              scale_up_cooldown_s=8.0,
                              scale_down_cooldown_s=20.0,
                              cache_low_water=0.95),
        slo=SloTargets(ttft_p95=4.0, queue_wait_p95=3.0),
        disturb_end_step=22,
        k8s_dry_run=True,
    )


def _diurnal() -> Scenario:
    steps = 72
    return Scenario(
        name="diurnal", steps=steps,
        traffic=lambda seed: diurnal(seed, steps=steps, low_rate=1.0,
                                     peak_rate=7.0, max_tokens=16),
        initial_workers=2,
        planner=PlannerConfig(min_replicas=2, max_replicas=8,
                              waiting_per_worker_high=2.0,
                              scale_up_cooldown_s=8.0,
                              scale_down_cooldown_s=16.0,
                              cache_low_water=0.95),
        slo=SloTargets(ttft_p95=5.0, queue_wait_p95=4.0),
    )


def _hot_tenant() -> Scenario:
    steps = 40
    return Scenario(
        name="hot-tenant", steps=steps,
        traffic=lambda seed: hot_tenant(seed, steps=steps, rate=3.0,
                                        hot_share=0.75, prefix_words=64,
                                        max_tokens=12),
        initial_workers=3,
        slo=SloTargets(ttft_p95=4.0, queue_wait_p95=3.0),
    )


def _crash() -> Scenario:
    """Worker crash mid-stream under steady load: streams fail fast, the
    stale endpoint is evicted, the planner re-scales, SLO recovers."""
    steps = 36
    return Scenario(
        name="crash", steps=steps,
        traffic=lambda seed: constant(seed, steps=steps, rate=5.0,
                                      max_tokens=12),
        initial_workers=3,
        planner=PlannerConfig(min_replicas=2, max_replicas=6,
                              waiting_per_worker_high=2.0,
                              scale_up_cooldown_s=6.0,
                              scale_down_cooldown_s=60.0),
        faults=[FaultEvent(step=10, kind="crash", arg=0)],
        slo=SloTargets(ttft_p95=4.0, queue_wait_p95=3.0),
        disturb_end_step=10,
    )


def _blackout() -> Scenario:
    """Scrape blackout: every worker stops answering stats for a window.
    The planner's zero-observed guard must hold the fleet steady (no
    scale-down applied, no controller action) and advisories must resume
    after the blackout."""
    steps = 30
    return Scenario(
        name="blackout", steps=steps,
        traffic=lambda seed: constant(seed, steps=steps, rate=2.0,
                                      max_tokens=12),
        initial_workers=3,
        faults=[FaultEvent(step=8, kind="blackout_start"),
                FaultEvent(step=14, kind="blackout_end")],
        slo=SloTargets(ttft_p95=4.0, queue_wait_p95=3.0),
    )


def _breaker() -> Scenario:
    """A flapping worker (stats plane up/down/up/down) must be circuit-
    broken by every collector — open after DYN_BREAKER_THRESHOLD
    consecutive failed rounds, half-open re-probe cadence, close on the
    final recovery — while traffic keeps flowing on the healthy pool."""
    steps = 34
    return Scenario(
        name="breaker", steps=steps,
        traffic=lambda seed: constant(seed, steps=steps, rate=3.0,
                                      max_tokens=12),
        initial_workers=3,
        planner=PlannerConfig(min_replicas=3, max_replicas=4,
                              waiting_per_worker_high=3.0,
                              scale_up_cooldown_s=8.0,
                              scale_down_cooldown_s=120.0),
        faults=[FaultEvent(step=6, kind="flap_start", arg=0),
                FaultEvent(step=11, kind="flap_end", arg=0),
                FaultEvent(step=13, kind="flap_start", arg=0),
                FaultEvent(step=18, kind="flap_end", arg=0)],
        slo=SloTargets(ttft_p95=5.0, queue_wait_p95=4.0),
        disturb_end_step=18,
    )


def _join() -> Scenario:
    """Delayed join: an out-of-band worker joins mid-run and must start
    taking routed traffic."""
    steps = 30
    return Scenario(
        name="join", steps=steps,
        traffic=lambda seed: constant(seed, steps=steps, rate=3.0,
                                      max_tokens=12),
        initial_workers=2,
        faults=[FaultEvent(step=8, kind="join")],
        slo=SloTargets(ttft_p95=4.0, queue_wait_p95=3.0),
    )


def _sharded() -> Scenario:
    """dynashard closed loop: the planner scales SHARDED replicas (each a
    2-device submesh of an 8-device pool). A burst forces a scale-up
    (joins partition fresh submeshes), the post-burst scale-down drains
    newest-first (their devices return to the pool), and a late join
    fault re-partitions onto the freed devices — the report's `sharding`
    block records the assignment timeline and the SLO verdict shows
    recovery."""
    steps = 44
    return Scenario(
        name="sharded", steps=steps,
        traffic=lambda seed: burst(seed, steps=steps, base_rate=1.5,
                                   burst_rate=7.0, burst_start=8,
                                   burst_end=18, max_tokens=12),
        initial_workers=2,
        profile=WorkerProfile(slots=3, tokens_per_step=6),
        planner=PlannerConfig(min_replicas=2, max_replicas=4,
                              waiting_per_worker_high=2.0,
                              scale_up_cooldown_s=6.0,
                              scale_down_cooldown_s=10.0),
        slo=SloTargets(ttft_p95=5.0, queue_wait_p95=4.0),
        faults=[FaultEvent(step=34, kind="join")],
        disturb_end_step=18,
        devices_per_replica=2,
        device_pool_size=8,
    )


def _failover() -> Scenario:
    """dynarevive end-to-end: a loaded worker is killed mid-burst and a
    rolling-drain wave follows. Mid-stream failover must resume every
    crashed stream on a sibling (zero failed requests, nonzero resumed
    count), drains must finish their in-flight work without the router
    ever routing to them, admission control sheds the overflow with
    jittered Retry-After instead of letting the queue melt, and the SLO
    must recover after the wave — byte-identical per seed like every
    other scenario."""
    steps = 44
    return Scenario(
        name="failover", steps=steps,
        traffic=lambda seed: burst(seed, steps=steps, base_rate=2.0,
                                   burst_rate=7.0, burst_start=8,
                                   burst_end=22, max_tokens=12),
        initial_workers=3,
        profile=WorkerProfile(slots=3, tokens_per_step=6),
        planner=PlannerConfig(min_replicas=3, max_replicas=6,
                              waiting_per_worker_high=2.0,
                              scale_up_cooldown_s=6.0,
                              scale_down_cooldown_s=60.0),
        faults=[FaultEvent(step=12, kind="crash", arg=0),
                # rolling-drain wave through the survivors
                FaultEvent(step=18, kind="drain", arg=0),
                FaultEvent(step=24, kind="drain", arg=0)],
        slo=SloTargets(ttft_p95=5.0, queue_wait_p95=4.0),
        disturb_end_step=24,
        shed_queue_depth=4,
    )


def _pd_rebalance() -> Scenario:
    """dynaslo closed loop (ROADMAP item 4): a fleet of 2 prefill + 4
    decode workers shares a prefill pool. Mid-run the trace turns
    prefill-heavy (same request rate, much longer prompts), the pool
    backlogs, TTFT burns its error budget and the multi-window alert
    fires; the planner's pd policy answers with a decode→prefill role
    shift (total replicas unchanged), the scheduler stops routing to the
    flipped worker, pool capacity rises and TTFT p95 recovers to SLO —
    with decode headroom sized so ITL p99 never regresses past its own
    objective. Byte-identical per seed like every scenario."""
    phases = [
        {"name": "balanced", "steps": 10, "rate": 2.0, "prompt_words": 15},
        {"name": "prefill-heavy", "steps": 20, "rate": 2.0,
         "prompt_words": 40},
        {"name": "rebalanced", "steps": 18, "rate": 2.0,
         "prompt_words": 40},
    ]
    steps = sum(p["steps"] for p in phases)
    return Scenario(
        name="pd_rebalance", steps=steps,
        traffic=lambda seed: phased(seed, phases=phases, max_tokens=12),
        initial_workers=6,
        initial_prefill_workers=2,
        profile=WorkerProfile(slots=6, total_slots=32,
                              tokens_per_step=4,
                              remote_prefill=True,
                              prefill_tokens_per_step=200,
                              decode_budget_per_step=24),
        # replica scaling disabled (0-thresholds) — this scenario isolates
        # the role-shift loop; the pd policy is the only actuator
        planner=PlannerConfig(min_replicas=6, max_replicas=6,
                              cache_high_water=0.0,
                              cache_low_water=-1.0,
                              waiting_per_worker_high=0.0,
                              queue_depth_per_worker_high=0.0,
                              pd=PdConfig(enabled=True,
                                          ttft_burn_high=1.5,
                                          itl_burn_high=1.5,
                                          min_prefill=1, min_decode=2,
                                          shift_cooldown_s=8.0)),
        slo_objectives="ttft<=2.5@0.95/16;itl<=0.25@0.95/16",
        slo_fast_fraction=0.25,
        slo_burn_threshold=1.5,
        slo=SloTargets(ttft_p95=4.0, queue_wait_p95=3.0),
        disturb_end_step=30,
    )


def _incident() -> Scenario:
    """dynablack end-to-end: steady load, a mid-run crash shrinks the
    fleet (the planner is pinned, so no relief arrives), TTFT burns its
    error budget and the multi-window alert fires — the first ``fired``
    transition trips the flight recorder. The capture fans out over the
    ``blackbox.capture`` DCP frame; every live worker answers with its
    shadow ring, so the bundle holds ≥ 2 rings aligned by timeline
    anchors, names the tripping trigger, and — the acceptance bar — is
    byte-identical across runs at the same seed."""
    steps = 36
    return Scenario(
        name="incident", steps=steps,
        # rate sized so the 3-worker fleet holds the objective (demand 8
        # slot-steps vs 9 capacity) and the 2-worker post-crash fleet
        # cannot (8 vs 6): the burn is crash-caused, not baked in
        traffic=lambda seed: constant(seed, steps=steps, rate=4.0,
                                      max_tokens=12),
        initial_workers=3,
        profile=WorkerProfile(slots=3, tokens_per_step=6),
        # scaling disabled (0-thresholds) and min below the post-crash
        # count: the crashed worker is never replaced, so the capacity
        # loss sustains the burn until the alert fires
        planner=PlannerConfig(min_replicas=2, max_replicas=3,
                              waiting_per_worker_high=0.0,
                              queue_depth_per_worker_high=0.0,
                              cache_high_water=0.0,
                              cache_low_water=-1.0),
        faults=[FaultEvent(step=9, kind="crash", arg=0)],
        slo=SloTargets(ttft_p95=4.0, queue_wait_p95=3.0),
        slo_objectives="ttft<=2.0@0.95/10",
        slo_fast_fraction=0.25,
        slo_burn_threshold=1.5,
        disturb_end_step=9,
        capture_incident=True,
    )


SCENARIOS: Dict[str, Callable[[], Scenario]] = {
    "smoke": _smoke,
    "burst": _burst,
    "diurnal": _diurnal,
    "hot-tenant": _hot_tenant,
    "crash": _crash,
    "blackout": _blackout,
    "breaker": _breaker,
    "join": _join,
    "sharded": _sharded,
    "failover": _failover,
    "pd_rebalance": _pd_rebalance,
    "incident": _incident,
}


def get_scenario(name: str) -> Scenario:
    try:
        return SCENARIOS[name]()
    except KeyError:
        raise ValueError(
            f"unknown scenario {name!r}; available: {sorted(SCENARIOS)}")
