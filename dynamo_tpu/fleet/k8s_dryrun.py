"""Dry-run Kubernetes actuation for fleet scenarios.

The planner's ``--apply`` path edits the stored deployment spec
(``deployments/<name>`` in the control-plane KV); in a real cluster the
operator's reconcile loop (k8s/controller.py) converges Deployments to
that spec. A fleet scenario with ``k8s_dry_run`` closes that half of the
loop too, against an in-memory cluster: after each actuation the harness
reads the stored spec back, presents it as a DynamoDeployment CR, and
runs the *real* :class:`~dynamo_tpu.k8s.controller.Reconciler` over a
:class:`DryRunKube`. The report then shows the replica count a real
cluster would have converged to — decided by the planner, rendered by
render.py, actuated by the reconcile controller, all without an
apiserver.
"""

from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional, Tuple

from ..k8s.controller import Reconciler


class DryRunKube:
    """In-memory KubeClient: (kind, ns, name) → object, with label
    selectors — enough surface for the reconcile controller."""

    def __init__(self) -> None:
        self.store: Dict[Tuple[str, str, str], Dict[str, Any]] = {}
        self.actions: List[Tuple[str, str]] = []   # (verb, kind/name)

    @staticmethod
    def _sel_match(obj: Dict[str, Any], sel: Optional[str]) -> bool:
        if not sel:
            return True
        labels = obj.get("metadata", {}).get("labels", {})
        for part in sel.split(","):
            k, v = part.split("=", 1)
            if labels.get(k) != v:
                return False
        return True

    def list(self, kind: str, namespace: str,
             label_selector: Optional[str] = None) -> List[Dict[str, Any]]:
        return [copy.deepcopy(o) for (k, ns, _), o in self.store.items()
                if k == kind and ns == namespace
                and self._sel_match(o, label_selector)]

    def get(self, kind: str, namespace: str,
            name: str) -> Optional[Dict[str, Any]]:
        o = self.store.get((kind, namespace, name))
        return copy.deepcopy(o) if o else None

    def create(self, kind: str, namespace: str,
               obj: Dict[str, Any]) -> Dict[str, Any]:
        obj = copy.deepcopy(obj)
        obj.setdefault("metadata", {})["resourceVersion"] = "1"
        name = obj["metadata"]["name"]
        self.store[(kind, namespace, name)] = obj
        self.actions.append(("create", f"{kind}/{name}"))
        return obj

    def replace(self, kind: str, namespace: str, name: str,
                obj: Dict[str, Any]) -> Dict[str, Any]:
        cur = self.store[(kind, namespace, name)]
        obj = copy.deepcopy(obj)
        obj["metadata"]["resourceVersion"] = str(
            int(cur["metadata"].get("resourceVersion", "0")) + 1)
        self.store[(kind, namespace, name)] = obj
        self.actions.append(("replace", f"{kind}/{name}"))
        return obj

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self.store.pop((kind, namespace, name), None)
        self.actions.append(("delete", f"{kind}/{name}"))

    def update_status(self, kind: str, namespace: str, name: str,
                      status: Dict[str, Any]) -> None:
        if (kind, namespace, name) in self.store:
            self.store[(kind, namespace, name)]["status"] = status


class K8sDryRun:
    """Reconciles the planner-edited stored spec into the fake cluster."""

    def __init__(self, deployment_name: str, service: str,
                 k8s_namespace: str = "fleet-sim"):
        self.deployment_name = deployment_name
        self.service = service
        self.k8s_namespace = k8s_namespace
        self.kube = DryRunKube()
        self.reconciler = Reconciler(self.kube)

    def make_cr(self, replicas: int) -> dict:
        """The CR seeded into the control-plane KV at scenario start."""
        return {
            "apiVersion": "dynamo-tpu.dev/v1alpha1",
            "kind": "DynamoDeployment",
            "metadata": {"name": self.deployment_name,
                         "namespace": self.k8s_namespace,
                         "uid": "fleet-sim-uid"},
            "spec": {"graph": "examples.llm.graphs.agg:Frontend",
                     "services": {self.service: {"replicas": replicas}}},
        }

    def reconcile(self, stored_spec: dict) -> Optional[int]:
        """Run the real reconcile controller over the (planner-edited)
        stored CR; returns the converged Deployment replica count."""
        cr = copy.deepcopy(stored_spec)
        cr.setdefault("kind", "DynamoDeployment")
        cr.setdefault("metadata", {}).setdefault(
            "namespace", self.k8s_namespace)
        key = ("DynamoDeployment", self.k8s_namespace,
               cr["metadata"]["name"])
        if key in self.kube.store:
            self.kube.store[key]["spec"] = copy.deepcopy(cr["spec"])
        else:
            self.kube.create("DynamoDeployment", self.k8s_namespace, cr)
        self.reconciler.reconcile_all(self.k8s_namespace)
        dep = self.kube.get(
            "Deployment", self.k8s_namespace,
            f"{self.deployment_name}-{self.service}")
        if dep is None:
            return None
        return (dep.get("spec") or {}).get("replicas")
