"""Fleet simulator CLI.

    python -m dynamo_tpu.fleet --scenario burst --seed 0

Prints the run's JSON report (sorted keys) to stdout; identical seeds
render identical reports. ``DYN_FLEET_REPORT_DIR`` additionally writes
``<scenario>-seed<seed>.json`` into that directory.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import sys

from ..runtime.config import env_str
from .harness import run_scenario
from .scenarios import SCENARIOS, get_scenario


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="dynamo-fleet",
        description="deterministic fleet-scale serving simulator")
    ap.add_argument("--scenario", default="burst",
                    choices=sorted(SCENARIOS))
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--report", default=None,
                    help="also write the JSON report to this path")
    ap.add_argument("--log-level", default="WARNING")
    args = ap.parse_args(argv)

    logging.basicConfig(level=args.log_level.upper())
    scenario = get_scenario(args.scenario)
    report = asyncio.run(run_scenario(scenario, args.seed))
    text = json.dumps(report, sort_keys=True, indent=2)
    print(text)

    paths = []
    if args.report:
        paths.append(args.report)
    report_dir = env_str("DYN_FLEET_REPORT_DIR")
    if report_dir:
        paths.append(os.path.join(
            report_dir, f"{args.scenario}-seed{args.seed}.json"))
    for path in paths:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            f.write(text + "\n")
        print(f"report written to {path}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
