"""The fleet simulator harness: real stack, scripted workers, stepped time.

One :class:`FleetSim` run assembles the **production** serving plane
in-process —

  ``aiohttp client → HttpService → Processor (byte tokenizer) → KvRouter
  → SimWorker endpoints`` over an embedded DCP control plane, with the
  real :class:`MetricsAggregator` scraping stats and the real
  :class:`Planner` deciding scale — and drives it step by step on a
  :class:`VirtualClock`:

  1. apply scripted faults due this step (crash / join / blackout),
  2. inject this step's trace arrivals through the HTTP frontend
     (sequentially: each request is awaited until it is enqueued at a
     worker, so router state evolves in a fixed order),
  3. advance every worker's service model one step (admissions, prefill,
     token releases — all lifecycle stamps in virtual time),
  4. scrape: aggregator then router (manual ``scrape_once``),
  5. tick the planner (virtual clock; advisories stamped in virtual
     time),
  6. actuate: wait for the advisory fanout, let the fleet controller
     spawn/drain workers, sync discovery, optionally reconcile the
     k8s dry-run cluster,
  7. sample fleet state for the scorer and advance the clock.

After the last trace step the loop keeps stepping (no new arrivals)
until every request has drained, then joins the HTTP client tasks and
renders the report. Wall-clock time never enters the report, so a seeded
run is byte-identical across hosts.
"""

from __future__ import annotations

import asyncio
import logging
import random
from typing import Dict, List, Optional, Set

from ..llm.http.service import HttpService
from ..llm.kv_router.router import KvRouter
from ..llm.model_card import ModelDeploymentCard
from ..llm.processor import Processor
from ..metrics.component import MetricsAggregator
from ..parallel.serving import DevicePool, NoFreeDevices
from ..planner.planner import Planner, WatchTarget
from ..planner.policy import PLANNER_KV_PREFIX
from ..runtime import blackbox, revive
from ..runtime.component import Client
from ..runtime.config import env_float
from ..runtime.dcp_client import pack, unpack
from ..runtime.runtime import DistributedRuntime
from ..runtime.slo import GoodputTracker, SloRegistry, collapse_roles
from ..runtime.tasks import spawn_tracked
from .clock import VirtualClock
from .controller import FleetController
from .k8s_dryrun import K8sDryRun
from .report import SloScorer
from .scenarios import Scenario
from .worker import PrefillPool, SimWorker

log = logging.getLogger("dynamo_tpu.fleet")

NAMESPACE = "fleetsim"
COMPONENT = "sim"
MODEL = "sim"
DEPLOYMENT = "fleet-sim"


class FleetSim:
    """One deterministic scenario run. Use :func:`run_scenario`."""

    def __init__(self, scenario: Scenario, seed: int):
        self.scenario = scenario
        self.seed = seed
        self.clock = VirtualClock(scenario.step_seconds)
        self.trace = scenario.traffic(seed)
        self.scorer = SloScorer(self.trace, scenario.slo,
                                scenario.step_seconds)
        self._max_tokens = {r.rid: r.max_tokens for r in self.trace.requests}
        self._enqueued: Dict[str, asyncio.Event] = {
            r.rid: asyncio.Event() for r in self.trace.requests}
        self._client_tasks: List[asyncio.Task] = []
        # dynacache: run-long per-worker (hit_tokens, prompt_tokens) view
        # folded from every scrape (survives drained workers)
        self._cache_seen: Dict[int, tuple] = {}
        # dynashard: the modeled accelerator pool replicas draw their
        # submeshes from (None in unsharded scenarios) + the assignment
        # timeline for the report's `sharding` block
        self.device_pool: Optional[DevicePool] = None
        if scenario.devices_per_replica > 0:
            self.device_pool = DevicePool(
                range(scenario.device_pool_size))
        self._sharding_events: List[dict] = []
        self._max_devices_in_use = 0
        # dynaslo: shared prefill capacity pool (remote_prefill
        # profiles), explicit SLO registry (objectives evaluated on the
        # virtual clock inside the aggregator's SloEngine), worker role
        # assignment sequence, and per-step merged latency snapshots for
        # the report's per-phase per-role quantiles
        self.prefill_pool: Optional[PrefillPool] = (
            PrefillPool() if scenario.profile.remote_prefill else None)
        self.slo_registry = (
            SloRegistry.parse(scenario.slo_objectives,
                              fast_fraction=scenario.slo_fast_fraction,
                              burn_threshold=scenario.slo_burn_threshold)
            if scenario.slo_objectives else SloRegistry())
        self._role_seq = 0
        self._slo_step_hists: Dict[int, dict] = {}
        # dynablack: a deterministic flight recorder on the virtual
        # clock. The harness owns one ShadowRing per worker (fed by the
        # lifecycle callback); on the first fired burn-rate alert the
        # recorder trips, the capture fans out over the blackbox.capture
        # DCP frame, every worker contributes its ring, and the merged
        # bundle lands in the report's `incident` block — byte-identical
        # per seed (virtual time only, canonical sorted serialization)
        self.recorder: Optional[blackbox.FlightRecorder] = None
        self._worker_rings: Dict[str, blackbox.ShadowRing] = {}
        self._bb_workers: Set[str] = set()
        self._incident_bundle: Optional[dict] = None
        if scenario.capture_incident:
            horizon = float(scenario.steps + scenario.drain_steps + 1) \
                * scenario.step_seconds
            self.recorder = blackbox.FlightRecorder(
                window_s=horizon, cooldown_s=0.0, out_dir=None,
                triggers="all", clock=self.clock.now, wall=self.clock.now,
                id_factory=lambda: f"incident-{scenario.name}-{seed}",
                include_process_state=False)
        # dynarevive: SLO-aware shed controller (wired in setup() when
        # the scenario sets shed_queue_depth)
        self.admission: Optional[revive.AdmissionController] = None
        self._discovery_timeout = env_float(
            "DYN_FLEET_DISCOVERY_TIMEOUT") or 10.0
        # wired in setup()
        self.drt: Optional[DistributedRuntime] = None
        self.controller: Optional[FleetController] = None
        self.router: Optional[KvRouter] = None
        self.agg: Optional[MetricsAggregator] = None
        self.planner: Optional[Planner] = None
        self.service: Optional[HttpService] = None
        self.token_client: Optional[Client] = None
        self._http = None
        self._base_url = ""
        self.k8s: Optional[K8sDryRun] = None
        self._k8s_replicas: Optional[int] = None

    # ------------------------------------------------------------- setup

    async def setup(self) -> None:
        sc = self.scenario
        self.drt = await DistributedRuntime.detached()

        self.controller = FleetController(
            self.drt, NAMESPACE, COMPONENT, self._worker_factory)
        await self.controller.start()
        names = await self.controller.spawn_initial(sc.initial_workers)
        for name in names:
            self.scorer.worker_event(self.clock.now(), "spawn", name)

        self.router = KvRouter(self.drt, NAMESPACE, COMPONENT,
                               block_size=sc.block_size,
                               scrape_interval=1.0, seed=self.seed)
        await self.router.start(run_loop=False)

        self.agg = MetricsAggregator(self.drt, NAMESPACE, COMPONENT,
                                     slo_registry=self.slo_registry,
                                     slo_clock=self.clock.now)
        await self.agg.start(run_loop=False)
        if self.recorder is not None:
            # the ISSUE-mandated "last fleet-aggregator scrape" evidence
            self.recorder.add_source("fleet_scrape", self.agg.last_scrape)

        self.planner = Planner(
            self.drt, NAMESPACE,
            [WatchTarget(component=COMPONENT,
                         endpoint="generate_tokens",
                         deployment=DEPLOYMENT if sc.k8s_dry_run else None,
                         service=COMPONENT,
                         config=sc.planner)],
            apply=sc.k8s_dry_run,
            clock=self.clock.now, wall_clock=self.clock.now,
            # dynaslo advisory input: the aggregator's SLO engine burn
            # rates (virtual clock) feed the P/D rebalance policy
            pressure_source=self.agg.slo.pressures)
        await self.planner.start(run_loop=False)

        if sc.k8s_dry_run:
            self.k8s = K8sDryRun(DEPLOYMENT, COMPONENT)
            cr = self.k8s.make_cr(sc.initial_workers)
            await self.drt.dcp.kv_put(f"deployments/{DEPLOYMENT}", pack(cr))

        mdc = ModelDeploymentCard(name=MODEL, tokenizer_kind="byte",
                                  kv_block_size=sc.block_size,
                                  model_type="completions")
        self.token_client = await self.drt.namespace(NAMESPACE) \
            .component(COMPONENT).endpoint("generate_tokens").client()
        processor = Processor(mdc, self.token_client, self.router)

        if sc.shed_queue_depth > 0:
            # dynarevive admission control over the aggregator's view,
            # with a seeded rng so the jittered Retry-After (and thus the
            # report) stays byte-identical per seed
            # window=4: signals refresh once per virtual step (scrape),
            # so a long peak-hold would keep shedding for many steps
            # after a burst clears; the sim never runs the wall-clock
            # sampler task
            self.admission = revive.AdmissionController(
                lambda: revive.signals_from_metrics(
                    self.agg.worker_metrics),
                cfg=revive.ShedConfig(queue_depth=sc.shed_queue_depth),
                rng=random.Random(self.seed ^ 0x5EED),
                window=4)
        self.service = HttpService(admission=self.admission)
        self.service.manager.add_completions_model(MODEL,
                                                   processor.completion)
        await self.service.start(host="127.0.0.1", port=0)
        self._base_url = f"http://127.0.0.1:{self.service.port}"

        import aiohttp

        self._http = aiohttp.ClientSession()

        await self._sync_discovery()
        # warm the scheduler/aggregator view before the first arrivals
        await self._scrape()

    async def _worker_factory(self, name: str) -> SimWorker:
        submesh = None
        if self.device_pool is not None:
            # partition a submesh for the new replica BEFORE any await:
            # an exhausted pool must fail the spawn, not serve unsharded
            submesh = self.device_pool.acquire(
                name, self.scenario.devices_per_replica)
            idx = self.device_pool.assignment()[name]
            self._sharding_events.append(
                {"at": self.clock.now(), "event": "assign",
                 "worker": name, "devices": idx})
            in_use = sum(len(d) for d in
                         self.device_pool.assigned.values())
            self._max_devices_in_use = max(self._max_devices_in_use,
                                           in_use)
            submesh = idx
        drt = await DistributedRuntime.attach(self.drt.dcp.address)
        # dynaslo P/D roles: in remote-prefill scenarios the first
        # initial_prefill_workers spawned are the prefill side, every
        # later spawn (scale-up, join) lands decode-side; the planner's
        # pd policy then re-ratios by flipping roles
        role = "unified"
        if self.prefill_pool is not None:
            role = ("prefill"
                    if self._role_seq < self.scenario.initial_prefill_workers
                    else "decode")
        self._role_seq += 1
        worker = SimWorker(
            drt, NAMESPACE, COMPONENT, name, self.scenario.profile,
            self.scenario.block_size, self.clock.now,
            lambda rid, ev, vt, n=name: self._lifecycle(n, rid, ev, vt),
            submesh=submesh, role=role, prefill_pool=self.prefill_pool)
        await worker.start()
        if self.recorder is not None:
            # one shadow ring per worker, anchored at its (virtual) spawn
            # time; the worker joins the capture fan-out and answers an
            # origin announcement with exactly its own ring
            ring = blackbox.ShadowRing(name, maxlen=2048,
                                       clock=self.clock.now,
                                       wall=self.clock.now)
            self._worker_rings[name] = ring
            await blackbox.attach_dcp(
                worker.drt, NAMESPACE, self.recorder, name,
                rings_fn=lambda n=name: {
                    n: self._worker_rings[n].export()})
            self._bb_workers.add(name)
        return worker

    # --------------------------------------------------------- lifecycle

    def _lifecycle(self, worker: str, rid: str, event: str,
                   vt: float) -> None:
        ring = self._worker_rings.get(worker)
        if ring is not None:
            ring.note(event, rid=rid, vt=vt)
        rec = self.scorer.record(rid)
        if rec is None:
            return
        # first-stamp-wins on arrival/admission/first-token: a resumed
        # request (dynarevive failover re-submits the same rid on a
        # sibling worker) keeps its ORIGINAL latency stamps — TTFT is
        # what the client saw, not what the resume saw
        if event == "enqueued":
            rec.worker = worker
            if rec.arrival_vt is None:
                rec.arrival_vt = vt
            ev = self._enqueued.get(rid)
            if ev is not None:
                ev.set()
        elif event == "admitted":
            if rec.admitted_vt is None:
                rec.admitted_vt = vt
        elif event == "first_token":
            if rec.first_token_vt is None:
                rec.first_token_vt = vt
        elif event == "done":
            rec.done_vt = vt
            rec.tokens_out = self._max_tokens.get(rid, 0)
        elif event == "crashed":
            rec.status = "crashed"

    # ------------------------------------------------------------ inject

    async def _do_request(self, spec) -> None:
        rec = self.scorer.record(spec.rid)
        try:
            body = {"model": MODEL, "prompt": spec.prompt,
                    "stream": True, "max_tokens": spec.max_tokens}
            async with self._http.post(
                    f"{self._base_url}/v1/completions", json=body,
                    headers={"X-Request-Id": spec.rid}) as resp:
                rec.http_status = resp.status
                if resp.status == 503:
                    # admission control answered an early 503 with
                    # Retry-After: shed, not failed — the client was
                    # told when to come back
                    rec.status = "shed"
                    return
                if resp.status != 200:
                    rec.status = "failed"
                    return
                errored = False
                async for raw in resp.content:
                    line = raw.strip()
                    if line.startswith(b"event: error"):
                        errored = True
                    elif line == b"data: [DONE]":
                        break
                if rec.status in ("pending", "crashed"):
                    if errored:
                        rec.status = "failed"
                    else:
                        # a "crashed" record whose stream still finished
                        # clean is a dynarevive mid-stream failover: the
                        # worker died, the resume completed the stream
                        rec.resumed = rec.status == "crashed"
                        rec.status = "ok"
        except Exception:
            log.debug("client request %s failed", spec.rid, exc_info=True)
            if rec.status in ("pending", "crashed"):
                rec.status = "failed"

    async def _inject(self, step: int) -> None:
        for spec in self.trace.at(step):
            task = spawn_tracked(self._do_request(spec),
                                 name=f"fleet-req-{spec.rid}")
            self._client_tasks.append(task)
            # sequential admission: wait until the request is enqueued at
            # a worker (or failed fast) before injecting the next one, so
            # router decisions replay in a fixed order
            ev = self._enqueued[spec.rid]
            waiter = spawn_tracked(ev.wait(),
                                   name=f"fleet-enq-{spec.rid}")
            done, _pending = await asyncio.wait(
                {task, waiter}, timeout=self._discovery_timeout,
                return_when=asyncio.FIRST_COMPLETED)
            waiter.cancel()
            if not done:
                raise RuntimeError(
                    f"request {spec.rid} neither enqueued nor failed "
                    f"within {self._discovery_timeout}s — sim wedged")

    # ----------------------------------------------------------- helpers

    def _workers_in_order(self) -> List[SimWorker]:
        return list(self.controller.workers.values())

    async def _advance_workers(self) -> None:
        if self.prefill_pool is not None:
            # shared prefill capacity this step = the prefill-role side
            # of the fleet (role flips change this one step later — the
            # actuation latency the rebalance loop pays)
            capacity = sum(
                self.scenario.profile.prefill_tokens_per_step
                for w in self.controller.live
                if w.model.role == "prefill")
            self.prefill_pool.step(capacity)
        for worker in self._workers_in_order():
            events = worker.model.step()
            if events and not worker.draining:
                await worker.publish_kv_events(events)
        retired = await self.controller.retire_idle_drained()
        for name in retired:
            self.scorer.worker_event(self.clock.now(), "removed", name)
            if self.device_pool is not None:
                # a retired replica's submesh returns to the pool — the
                # next join re-partitions onto these devices
                devs = self.device_pool.assignment().get(name, [])
                self.device_pool.release(name)
                self._sharding_events.append(
                    {"at": self.clock.now(), "event": "release",
                     "worker": name, "devices": devs})
        # let woken handlers push their token frames down the wire
        await asyncio.sleep(0)

    async def _scrape(self) -> None:
        try:
            await self.agg.scrape_once()
        except Exception:
            log.exception("aggregator scrape failed")
        # dynacache: fold each scrape's per-worker hit/prompt totals into
        # a run-long view — a drained worker's counters leave the
        # aggregator with it, but its realized hits still happened (the
        # hot-tenant worker is often the one newest-first scale-down
        # retires). Counters are per-worker monotonic, so overwrite.
        for wid, m in self.agg.worker_metrics.items():
            self._cache_seen[wid] = (m.prefix_hit_tokens_total,
                                     m.prompt_tokens_total)
        try:
            await self.router.scrape_once()
        except Exception:
            log.exception("router scrape failed")

    async def _actuate(self) -> None:
        await self.controller.wait_advisories(len(self.planner.advisories))
        actions = await self.controller.reconcile()
        vt = self.clock.now()
        for act in actions:
            self.scorer.actuation(vt, act["action"], act["desired"],
                                  act["workers"])
            for name in act["workers"]:
                if act["action"] == "scale-up":
                    self.scorer.worker_event(vt, "spawn", name)
                elif act["action"] == "scale-down":
                    self.scorer.worker_event(vt, "drain", name)
                elif act["action"].startswith("pd-shift"):
                    # dynaslo role flip: record it on the worker
                    # timeline (no discovery churn — the flip is a
                    # stats-plane label the scheduler honors next scrape)
                    self.scorer.worker_event(vt, act["action"], name)
        if actions:
            await self._sync_discovery()
        if self.k8s is not None:
            raw = await self.drt.dcp.kv_get(f"deployments/{DEPLOYMENT}")
            if raw is not None:
                replicas = self.k8s.reconcile(unpack(raw))
                if replicas is not None:
                    self._k8s_replicas = replicas

    def _observers(self) -> List[Client]:
        obs = [self.token_client, self.router.client, self.agg._client]
        obs.extend(self.planner._clients.values())
        return [c for c in obs if c is not None]

    async def _sync_discovery(self) -> None:
        """Block (wall-bounded) until every client's discovery view shows
        the live workers and has dropped the drained ones."""
        present: Set[int] = {w.instance_id for w in self.controller.live}
        absent: Set[int] = {
            w.instance_id for w in self.controller.workers.values()
            if w.draining}
        absent |= {w.instance_id for w in self.controller.retired}
        deadline = asyncio.get_running_loop().time() \
            + self._discovery_timeout
        while asyncio.get_running_loop().time() < deadline:
            views = [set(c.instances) for c in self._observers()]
            if all(present <= v and not (absent & v) for v in views):
                return
            await asyncio.sleep(0.005)
        raise RuntimeError("discovery views did not converge "
                           f"(want +{present} -{absent})")

    async def _apply_faults(self, step: int) -> None:
        for fault in [f for f in self.scenario.faults if f.step == step]:
            vt = self.clock.now()
            if fault.kind == "crash":
                live = self.controller.live
                if live:
                    worker = live[min(fault.arg, len(live) - 1)]
                    await worker.crash()
                    self.scorer.worker_event(vt, "crash", worker.name)
                    if self.recorder is not None:
                        self.recorder.note("sim-harness", "fault",
                                           fault="crash", step=step,
                                           name=worker.name, vt=vt)
            elif fault.kind == "drain":
                # rolling-restart wave: graceful drain of one live
                # worker — discovery out, in-flight finishes, the
                # router must never route to it again (dynarevive)
                live = self.controller.live
                if live:
                    worker = live[min(fault.arg, len(live) - 1)]
                    # sim-model lifecycle drain, not a socket drain
                    await worker.drain()  # dynalint: disable=unbounded-await
                    self.scorer.worker_event(vt, "drain", worker.name)
                    await self._sync_discovery()
            elif fault.kind == "join":
                try:
                    name = await self.controller._spawn()
                except NoFreeDevices:
                    # the modeled accelerator pool is the hard capacity
                    # limit: a join with no free submesh is DENIED, not
                    # served unsharded (recorded for the report)
                    self._sharding_events.append(
                        {"at": vt, "event": "join_denied_no_devices",
                         "worker": None, "devices": []})
                    self.scorer.worker_event(vt, "join_denied", "*")
                    continue
                self.scorer.worker_event(vt, "join", name)
                await self._sync_discovery()
            elif fault.kind == "blackout_start":
                for worker in self.controller.live:
                    worker.set_blackout(True)
                self.scorer.worker_event(vt, "blackout_start", "*")
            elif fault.kind == "blackout_end":
                for worker in self.controller.live:
                    worker.set_blackout(False)
                self.scorer.worker_event(vt, "blackout_end", "*")
            elif fault.kind in ("flap_start", "flap_end"):
                live = self.controller.live
                if live:
                    worker = live[min(fault.arg, len(live) - 1)]
                    worker.set_blackout(fault.kind == "flap_start")
                    self.scorer.worker_event(vt, fault.kind, worker.name)

    async def _capture_incident(self, alert: dict, step: int) -> None:
        """First fired burn-rate alert: trip the recorder, broadcast the
        capture over DCP, and wall-bounded-wait until every subscribed
        worker's ring has merged into the bundle (the wait is for
        determinism: the bundle must hold the same ring set every run)."""
        rec = self.recorder
        rec.note("sim-harness", "alert", step=step, **alert)
        bundle = rec.trip("slo_burn_rate", alert)
        if bundle is None:
            return
        await blackbox.broadcast_capture(self.drt, NAMESPACE, bundle,
                                         worker_label="sim-harness")
        want = set(self._bb_workers)
        deadline = asyncio.get_running_loop().time() \
            + self._discovery_timeout
        while not want <= set(bundle["workers"]):
            if asyncio.get_running_loop().time() >= deadline:
                raise RuntimeError(
                    "incident contributions did not converge "
                    f"(have {sorted(bundle['workers'])}, want "
                    f"{sorted(want)})")
            await asyncio.sleep(0.005)
        self._incident_bundle = bundle

    def _fleet_sample(self) -> None:
        waiting = sum(len(w.model.queue)
                      for w in self._workers_in_order())
        active = sum(len(w.model.active)
                     for w in self._workers_in_order())
        self.scorer.sample_step(self.clock.now(), waiting, active,
                                len(self.controller.live))

    # -------------------------------------------------------------- run

    async def _step(self, step: int, *, inject: bool = True) -> None:
        await self._apply_faults(step)
        if inject:
            await self._inject(step)
        await self._advance_workers()
        await self._scrape()
        # dynaslo: per-step fleet-merged latency snapshot (fresh
        # Histogram objects each call) — the report diffs these at phase
        # boundaries into per-phase per-role quantiles
        self._slo_step_hists[step] = self.agg.merged_latency()
        if self.recorder is not None and self._incident_bundle is None:
            fired = [e for e in self.agg.slo.alert_events
                     if e["state"] == "fired"]
            if fired:
                await self._capture_incident(fired[0], step)
        await self.planner.tick()
        await self._actuate()
        self._fleet_sample()
        self.clock.advance()

    async def run(self) -> dict:
        sc = self.scenario
        await self.setup()
        try:
            for step in range(sc.steps):
                await self._step(step)
            # drain: no arrivals, keep stepping until all requests settle
            for extra in range(sc.drain_steps):
                if self._drained():
                    break
                await self._step(sc.steps + extra, inject=False)
            await self._join_clients()
            return await self._report()
        finally:
            await self.teardown()

    def _drained(self) -> bool:
        return all(r.status != "pending" or r.done_vt is not None
                   for r in self.scorer.records.values())

    async def _join_clients(self) -> None:
        if self._client_tasks:
            await asyncio.wait(self._client_tasks, timeout=30.0)

    async def _report(self) -> dict:
        advisories = [a.to_dict() for a in self.planner.advisories]
        stored = await self.drt.dcp.kv_get_prefix(PLANNER_KV_PREFIX)
        extra = {
            "router": self.router.stats(),
            "stats_evictions": {
                "aggregator": self.agg._client.evicted_ids(),
                "router": self.router.client.evicted_ids(),
            },
            # circuit-breaker evidence for the breaker scenario: how many
            # times each collector's stats-plane breakers opened over the
            # run, and which instances are open at the end
            "breakers": {
                "aggregator": {
                    "opened_total":
                        self.agg._client.breakers.opened_total("stats"),
                    "open_now": self.agg._client.evicted_ids(),
                },
                "router": {
                    "opened_total":
                        self.router.client.breakers.opened_total("stats"),
                    "open_now": self.router.client.evicted_ids(),
                },
            },
            "advisories_in_kv": len(stored),
            # dynaprof plane: the new dyn_engine_*/dyn_runtime_* gauges
            # as scraped from worker ForwardPassMetrics at run end, so
            # fleet scenarios regression-gate scheduler overhead next to
            # the SLO verdicts (virtual-state values only: deterministic)
            "engine_gauges": self._engine_gauges(),
            # dynacache plane: the router's PREDICTED overlap hit rate
            # next to the workers' REALIZED (engine-side) hit rate, so
            # scenarios like hot-tenant can assert both views agree
            "cache": self._cache_block(),
        }
        if self.admission is not None or any(
                f.kind in ("crash", "drain") for f in self.scenario.faults):
            # dynarevive plane: mid-stream failover + drain + shed story
            # of the run (scorer-derived counts only — process-global
            # revive counters never enter the report, keeping seeded
            # runs byte-identical across processes)
            recs = self.scorer.records.values()
            extra["failover"] = {
                "resumed_requests": len([r for r in recs if r.resumed]),
                "still_crashed": len([r for r in recs
                                      if r.status == "crashed"]),
                "shed_requests": len([r for r in recs
                                      if r.status == "shed"]),
                "shed_by_signal": (dict(sorted(
                    self.admission.shed_by_signal.items()))
                    if self.admission else {}),
                "drains": [e for e in self.scorer.worker_events
                           if e["event"] == "drain"],
            }
        if self.slo_registry.objectives or self.prefill_pool is not None:
            extra["dynaslo"] = self._dynaslo_block()
        if self.recorder is not None:
            # dynablack plane: the merged incident bundle (or the armed-
            # but-untripped recorder state) — virtual-time values only
            extra["incident"] = (
                self._incident_bundle if self._incident_bundle is not None
                else {"captured": False,
                      "captures_total": self.recorder.captures_total})
        if self.device_pool is not None:
            # dynashard plane: the submesh-assignment story of the run —
            # every partition/release with its virtual timestamp, the
            # final assignment, and the peak device usage (all modeled
            # state: byte-identical per seed)
            extra["sharding"] = {
                "device_pool_size": self.scenario.device_pool_size,
                "devices_per_replica": self.scenario.devices_per_replica,
                "assignment": self.device_pool.assignment(),
                "timeline": self._sharding_events,
                "max_devices_in_use": self._max_devices_in_use,
            }
        if self.k8s is not None:
            extra["k8s_dry_run"] = {
                "deployment_replicas": self._k8s_replicas,
                "objects": sorted(f"{k}/{n}" for (k, _ns, n)
                                  in self.k8s.kube.store),
            }
        return self.scorer.report(
            scenario=self.scenario.name, seed=self.seed,
            steps=self.scenario.steps, advisories=advisories,
            disturb_end_step=self.scenario.disturb_end_step, extra=extra)

    def _engine_gauges(self) -> dict:
        """Fleet-level rollup of the dynaprof ForwardPassMetrics gauges
        from the final aggregator scrape (sorted per-worker rows keep the
        JSON byte-stable across runs)."""
        wm = [m for _, m in sorted(self.agg.worker_metrics.items())]
        n = max(len(wm), 1)
        return {
            "workers_scraped": len(wm),
            "inflight_sequences": sum(m.request_active_slots for m in wm),
            "admission_queue_depth": sum(m.num_requests_waiting
                                         for m in wm),
            "kv_free_blocks_min": min((m.kv_free_blocks for m in wm),
                                      default=0),
            "device_time_fraction_avg": round(
                sum(m.device_time_fraction for m in wm) / n, 6),
            "loop_lag_p99_seconds_max": max(
                (m.loop_lag_p99_seconds for m in wm), default=0.0),
            "queue_wait_seconds_total": round(
                sum(m.queue_wait_seconds_total for m in wm), 6),
        }

    def _phase_role_quantiles(self) -> Dict[str, dict]:
        """Per-phase, per-role latency quantiles from the mergeable
        histograms: phase window = snapshot at the phase's last step
        minus the snapshot before its first (the FINAL phase extends
        through the drain tail so late observations land somewhere).
        Counters are monotonic, so diffs are exact."""
        steps_rec = sorted(self._slo_step_hists)
        if not steps_rec:
            return {}
        last = steps_rec[-1]
        empty: Dict[str, dict] = {}
        out: Dict[str, dict] = {}
        phases = self.trace.phases
        for i, phase in enumerate(phases):
            top_step = last if i == len(phases) - 1 \
                else min(phase.end - 1, last)
            top = self._slo_step_hists.get(top_step, empty)
            base = self._slo_step_hists.get(phase.start - 1, empty)
            rows: Dict[str, dict] = {}
            for role in sorted(top):
                per = {}
                for metric, h in sorted(top[role].items()):
                    b = base.get(role, {}).get(metric)
                    d = h.diff(b) if b is not None else h
                    if d.count == 0:
                        continue
                    per[metric] = {"p50": d.quantile(0.5),
                                   "p95": d.quantile(0.95),
                                   "p99": d.quantile(0.99),
                                   "count": d.count}
                if per:
                    rows[role] = per
            out[phase.name] = rows
        return out

    def _dynaslo_block(self) -> dict:
        """The dynaslo story of the run: objective evaluation + alert
        timeline off the aggregator's SLO engine (virtual clock),
        goodput over the request records, per-phase per-role quantiles,
        the prefill pool's totals, and the post-rebalance verdict the
        pd_rebalance scenario regression-gates (final-phase TTFT p95 and
        ITL p99 vs their objective thresholds)."""
        gp = GoodputTracker(self.slo_registry)
        for rec in self.scorer.records.values():
            if rec.status != "ok":
                gp.observe_failed()
                continue
            metrics: Dict[str, float] = {}
            if rec.ttft is not None:
                metrics["ttft"] = rec.ttft
            if rec.queue_wait is not None:
                metrics["queue_wait"] = rec.queue_wait
            if rec.done_vt is not None and rec.arrival_vt is not None:
                metrics["e2e"] = rec.done_vt - rec.arrival_vt
            gp.observe_request(metrics)
        phase_q = self._phase_role_quantiles()
        block = {
            "registry": self.slo_registry.to_dict(),
            "evaluation": self.agg.slo.evaluate(),
            "alerts": list(self.agg.slo.alert_events),
            "pressures": self.agg.slo.pressures(),
            "goodput": gp.snapshot(),
            "phase_role_quantiles": phase_q,
        }
        if self.prefill_pool is not None:
            block["prefill_pool"] = {
                "enqueued": self.prefill_pool.enqueued_total,
                "completed": self.prefill_pool.completed_total,
                "final_depth": self.prefill_pool.depth,
            }
            block["roles_final"] = {
                name: w.model.role
                for name, w in sorted(self.controller.workers.items())}
        # post-rebalance verdict: final-phase quantiles (role-collapsed)
        # against the ttft/itl objective thresholds
        if self.trace.phases and phase_q:
            final = self.trace.phases[-1].name
            rows = phase_q.get(final, {})
            hists: Dict[str, dict] = {}
            for role, per in rows.items():
                hr = {}
                for m in per:
                    h = self._hist_for(final, role, m)
                    if h is not None:
                        hr[m] = h
                hists[role] = hr
            merged = collapse_roles(hists)
            verdict: Dict[str, object] = {"phase": final}
            for metric, q, tag in (("ttft", 0.95, "ttft_p95_s"),
                                   ("itl", 0.99, "itl_p99_s")):
                h = merged.get(metric)
                val = h.quantile(q) if h is not None and h.count else None
                verdict[tag] = val
                objs = self.slo_registry.for_metric(metric)
                if objs:
                    verdict[f"{metric}_met"] = (
                        val is not None and val <= objs[0].threshold_s)
            block["post_rebalance"] = verdict
        return block

    def _hist_for(self, phase_name: str, role: str, metric: str):
        """The final-phase window histogram for (role, metric) — same
        diff _phase_role_quantiles renders quantiles from."""
        steps_rec = sorted(self._slo_step_hists)
        last = steps_rec[-1]
        phase = next(p for p in self.trace.phases if p.name == phase_name)
        top = self._slo_step_hists[last].get(role, {}).get(metric)
        base = self._slo_step_hists.get(
            phase.start - 1, {}).get(role, {}).get(metric)
        return top.diff(base) if (top is not None and base is not None) \
            else top

    def _cache_block(self) -> dict:
        """Predicted (router overlap scoring) vs realized (worker-side
        stored-chain replay) hit rates, folded over every scrape of the
        run so drained workers' totals still count — sorted per-worker
        rows keep the JSON byte-stable across runs."""
        rows = sorted(self._cache_seen.items())
        hits = sum(h for _, (h, _p) in rows)
        prompts = sum(p for _, (_h, p) in rows)
        rstats = self.router.stats()
        return {
            "router_predicted_hit_rate": rstats["avg_hit_rate"],
            "engine_realized_hit_rate": hits / max(prompts, 1),
            "per_worker_realized": [h / max(p, 1)
                                    for _, (h, p) in rows],
        }

    async def teardown(self) -> None:
        if self._http is not None:
            await self._http.close()
        for task in self._client_tasks:
            task.cancel()
        if self.service is not None:
            await self.service.stop()
        if self.planner is not None:
            await self.planner.stop()
        if self.agg is not None:
            await self.agg.stop()
        if self.router is not None:
            await self.router.stop()
        if self.token_client is not None:
            await self.token_client.close()
        if self.controller is not None:
            await self.controller.teardown()
            for w in self.controller.retired:
                # runtimes of drained workers were already shut down in
                # retire_idle_drained; nothing further
                pass
        if self.drt is not None:
            await self.drt.shutdown()


async def run_scenario(scenario: Scenario, seed: int) -> dict:
    """Run one scenario to completion and return its report dict."""
    return await FleetSim(scenario, seed).run()
