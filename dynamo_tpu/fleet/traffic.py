"""Traffic-trace layer: replayable request schedules.

A :class:`TrafficTrace` is the full, pre-materialized request schedule of
a scenario — every request with its arrival step, prompt, token budget and
tenant — generated from a seed so the same seed always replays the same
trace. The harness drives each request through the *real* HTTP frontend →
processor → KV router path; nothing here knows how requests are served.

Shapes (SURVEY §3.5 load patterns the planner control loop must absorb):

- ``constant``   — steady arrivals, the warmup/steady-state baseline.
- ``burst``      — constant base rate with a rectangular burst window;
                   the canonical scale-up-then-recover scenario.
- ``diurnal``    — a half-sine ramp up and back down across the run
                   (compressed day/night cycle).
- ``hot_tenant`` — a skewed tenant mix where one tenant's requests all
                   share a long common prompt prefix (system prompt /
                   RAG context), exercising KV-overlap routing.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

_WORDS = ("alpha bravo charlie delta echo foxtrot golf hotel india juliet "
          "kilo lima mike november oscar papa quebec romeo sierra tango "
          "uniform victor whiskey xray yankee zulu").split()


@dataclass(frozen=True)
class RequestSpec:
    """One scheduled request."""

    rid: str
    step: int                 # arrival step index
    prompt: str
    max_tokens: int
    tenant: str = "default"


@dataclass(frozen=True)
class PhaseSpec:
    """A named half-open step window ``[start, end)`` for reporting."""

    name: str
    start: int
    end: int

    def contains(self, step: int) -> bool:
        return self.start <= step < self.end


@dataclass
class TrafficTrace:
    """The materialized schedule: requests sorted by (step, rid)."""

    requests: List[RequestSpec]
    phases: List[PhaseSpec]
    seed: int

    def at(self, step: int) -> List[RequestSpec]:
        return [r for r in self.requests if r.step == step]

    def phase_of(self, step: int) -> str:
        for p in self.phases:
            if p.contains(step):
                return p.name
        return "other"

    @property
    def total(self) -> int:
        return len(self.requests)


def _arrivals(rng: random.Random, rate: float) -> int:
    """Integer arrivals for one step at fractional ``rate``: the integer
    part always arrives, the remainder arrives Bernoulli(frac)."""
    base = int(rate)
    frac = rate - base
    return base + (1 if frac > 0 and rng.random() < frac else 0)


def _prompt(rng: random.Random, words: int, prefix: str = "") -> str:
    body = " ".join(rng.choice(_WORDS) for _ in range(max(words, 1)))
    return (prefix + " " + body) if prefix else body


def _materialize(seed: int, rates: Sequence[float], phases: List[PhaseSpec],
                 *, prompt_words: int = 12, max_tokens: int = 16,
                 tenants: Optional[Dict[str, float]] = None,
                 tenant_prefixes: Optional[Dict[str, str]] = None
                 ) -> TrafficTrace:
    """Turn a per-step rate curve into a concrete trace."""
    rng = random.Random(seed)
    tenants = tenants or {"default": 1.0}
    names = sorted(tenants)
    weights = [tenants[n] for n in names]
    reqs: List[RequestSpec] = []
    n = 0
    for step, rate in enumerate(rates):
        for _ in range(_arrivals(rng, rate)):
            tenant = rng.choices(names, weights=weights)[0]
            prefix = (tenant_prefixes or {}).get(tenant, "")
            reqs.append(RequestSpec(
                rid=f"r{n:05d}", step=step,
                prompt=_prompt(rng, prompt_words, prefix),
                max_tokens=max_tokens, tenant=tenant))
            n += 1
    return TrafficTrace(requests=reqs, phases=phases, seed=seed)


# ------------------------------------------------------------ trace shapes


def constant(seed: int, *, steps: int, rate: float,
             max_tokens: int = 16) -> TrafficTrace:
    return _materialize(seed, [rate] * steps,
                        [PhaseSpec("steady", 0, steps)],
                        max_tokens=max_tokens)


def burst(seed: int, *, steps: int, base_rate: float, burst_rate: float,
          burst_start: int, burst_end: int,
          max_tokens: int = 16) -> TrafficTrace:
    rates = [burst_rate if burst_start <= s < burst_end else base_rate
             for s in range(steps)]
    phases = [PhaseSpec("warmup", 0, burst_start),
              PhaseSpec("burst", burst_start, burst_end),
              PhaseSpec("recovery", burst_end, steps)]
    return _materialize(seed, rates, phases, max_tokens=max_tokens)


def diurnal(seed: int, *, steps: int, low_rate: float, peak_rate: float,
            max_tokens: int = 16) -> TrafficTrace:
    """Half-sine ramp: low → peak → low across the run."""
    rates = [low_rate + (peak_rate - low_rate) *
             math.sin(math.pi * s / max(steps - 1, 1))
             for s in range(steps)]
    third = steps // 3
    phases = [PhaseSpec("ramp-up", 0, third),
              PhaseSpec("peak", third, 2 * third),
              PhaseSpec("ramp-down", 2 * third, steps)]
    return _materialize(seed, rates, phases, max_tokens=max_tokens)


def phased(seed: int, *, phases: List[dict],
           max_tokens: int = 16) -> TrafficTrace:
    """Piecewise trace where each named phase sets its own arrival rate
    AND prompt length — the dynaslo P/D-rebalance shape: a window whose
    prompts grow long turns the workload prefill-heavy at constant
    request rate (TTFT pressure without ITL pressure).

    ``phases``: ``[{"name", "steps", "rate", "prompt_words",
    "max_tokens"?}, ...]`` applied back to back."""
    rng = random.Random(seed)
    reqs: List[RequestSpec] = []
    phase_specs: List[PhaseSpec] = []
    n = 0
    step0 = 0
    for ph in phases:
        end = step0 + int(ph["steps"])
        phase_specs.append(PhaseSpec(ph["name"], step0, end))
        for step in range(step0, end):
            for _ in range(_arrivals(rng, float(ph["rate"]))):
                reqs.append(RequestSpec(
                    rid=f"r{n:05d}", step=step,
                    prompt=_prompt(rng, int(ph["prompt_words"])),
                    max_tokens=int(ph.get("max_tokens", max_tokens))))
                n += 1
        step0 = end
    return TrafficTrace(requests=reqs, phases=phase_specs, seed=seed)


def hot_tenant(seed: int, *, steps: int, rate: float,
               hot_share: float = 0.7, prefix_words: int = 48,
               max_tokens: int = 16) -> TrafficTrace:
    """One hot tenant dominates arrivals and all its requests share a long
    deterministic prompt prefix — the KV-overlap routing workload."""
    prefix_rng = random.Random(seed ^ 0x5EED)
    shared = " ".join(prefix_rng.choice(_WORDS)
                      for _ in range(prefix_words))
    return _materialize(
        seed, [rate] * steps, [PhaseSpec("steady", 0, steps)],
        max_tokens=max_tokens,
        tenants={"hot": hot_share, "cold": 1.0 - hot_share},
        tenant_prefixes={"hot": shared})
