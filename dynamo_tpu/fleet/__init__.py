"""dynafleet — deterministic fleet-scale serving simulator.

Runs the real distributed serving stack (HTTP frontend, KV router,
metrics aggregator, planner) against scripted workers on a virtual
clock, closes the planner's advisory loop with an in-process fleet
controller, injects faults, and scores SLOs into a reproducible JSON
report. See docs/fleet_sim.md.
"""

from .clock import VirtualClock
from .controller import FleetController
from .harness import FleetSim, run_scenario
from .report import RequestRecord, SloScorer, SloTargets, percentile
from .scenarios import SCENARIOS, FaultEvent, Scenario, get_scenario
from .traffic import (PhaseSpec, RequestSpec, TrafficTrace, burst, constant,
                      diurnal, hot_tenant, phased)
from .worker import PrefillPool, SimEngineModel, SimWorker, WorkerProfile

__all__ = [
    "VirtualClock", "FleetController", "FleetSim", "run_scenario",
    "RequestRecord", "SloScorer", "SloTargets", "percentile",
    "SCENARIOS", "FaultEvent", "Scenario", "get_scenario",
    "PhaseSpec", "RequestSpec", "TrafficTrace", "burst", "constant",
    "diurnal", "hot_tenant", "phased",
    "PrefillPool", "SimEngineModel", "SimWorker", "WorkerProfile",
]
