"""SLO scorer + JSON report for fleet-simulator runs.

All scored quantities are **virtual-time** values (request lifecycle
stamps written by the worker model at step boundaries) or counters —
never wall-clock measurements — so a seeded run renders byte-identical
JSON on any host. The report carries:

- per-phase latency percentiles (TTFT, queue wait) + throughput,
- the advisory timeline (planner decisions) and the actuation timeline
  (what the fleet controller actually did about them),
- the worker timeline (spawn / drain / remove / crash / join),
- SLO verdicts: post-recovery percentile targets and time-to-recover
  after the burst/fault window.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

from ..runtime.slo import nearest_rank
from .traffic import TrafficTrace


@dataclass
class RequestRecord:
    """Lifecycle of one simulated request (virtual-time stamps)."""

    rid: str
    step: int                       # scheduled arrival step
    tenant: str = "default"
    worker: Optional[str] = None    # serving worker name
    arrival_vt: Optional[float] = None   # enqueued at the worker
    admitted_vt: Optional[float] = None  # entered a service slot
    first_token_vt: Optional[float] = None
    done_vt: Optional[float] = None
    tokens_out: int = 0
    # pending | ok | failed | crashed | shed. "crashed" is transient
    # under dynarevive: a mid-stream failover that completes flips it to
    # "ok" with resumed=True; "shed" = admission control answered an
    # early 503 (not a failure — the client was told to come back)
    status: str = "pending"
    http_status: Optional[int] = None
    resumed: bool = False           # completed via mid-stream failover

    @property
    def queue_wait(self) -> Optional[float]:
        if self.arrival_vt is None or self.admitted_vt is None:
            return None
        return self.admitted_vt - self.arrival_vt

    @property
    def ttft(self) -> Optional[float]:
        if self.arrival_vt is None or self.first_token_vt is None:
            return None
        return self.first_token_vt - self.arrival_vt


# One property-tested percentile implementation everywhere (dynaslo):
# the former ad-hoc copy here moved to runtime/slo.py, where the
# mergeable histogram's bucket quantiles are tested against it.
percentile = nearest_rank


@dataclass
class SloTargets:
    """Per-scenario service-level objectives, in virtual seconds."""

    ttft_p95: float = 3.0
    queue_wait_p95: float = 2.0
    # queue must stay drained this many consecutive steps to count as
    # recovered after the disturbance window
    recovery_settle_steps: int = 2

    def to_dict(self) -> dict:
        return {"ttft_p95_s": self.ttft_p95,
                "queue_wait_p95_s": self.queue_wait_p95,
                "recovery_settle_steps": self.recovery_settle_steps}


class SloScorer:
    """Accumulates per-step fleet samples + request records and renders
    the final report dict."""

    def __init__(self, trace: TrafficTrace, slo: SloTargets,
                 step_seconds: float):
        self.trace = trace
        self.slo = slo
        self.step_seconds = step_seconds
        self.records: Dict[str, RequestRecord] = {
            r.rid: RequestRecord(rid=r.rid, step=r.step, tenant=r.tenant)
            for r in trace.requests}
        # per-step samples: (vt, waiting_total, active_total, workers_live)
        self.step_samples: List[dict] = []
        self.worker_events: List[dict] = []     # spawn/drain/remove/crash
        self.actuations: List[dict] = []        # controller actions

    # ------------------------------------------------------------ intake

    def record(self, rid: str) -> Optional[RequestRecord]:
        return self.records.get(rid)

    def sample_step(self, vt: float, waiting: int, active: int,
                    live_workers: int) -> None:
        self.step_samples.append({"vt": vt, "waiting": waiting,
                                  "active": active,
                                  "workers": live_workers})

    def worker_event(self, vt: float, event: str, worker: str) -> None:
        self.worker_events.append({"vt": vt, "event": event,
                                   "worker": worker})

    def actuation(self, vt: float, action: str, desired: int,
                  workers: List[str]) -> None:
        self.actuations.append({"vt": vt, "action": action,
                                "desired": desired, "workers": workers})

    # ----------------------------------------------------------- scoring

    def _phase_rows(self) -> Dict[str, dict]:
        rows: Dict[str, dict] = {}
        for phase in self.trace.phases:
            recs = [r for r in self.records.values()
                    if phase.contains(r.step)]
            ttfts = [r.ttft for r in recs if r.ttft is not None]
            waits = [r.queue_wait for r in recs
                     if r.queue_wait is not None]
            done = [r for r in recs if r.status == "ok"]
            toks = sum(r.tokens_out for r in recs)
            span_s = max((phase.end - phase.start) * self.step_seconds,
                         self.step_seconds)
            rows[phase.name] = {
                "requests": len(recs),
                "completed": len(done),
                "failed": len([r for r in recs
                               if r.status in ("failed", "crashed")]),
                "ttft_p50_s": percentile(ttfts, 50),
                "ttft_p95_s": percentile(ttfts, 95),
                "queue_wait_p50_s": percentile(waits, 50),
                "queue_wait_p95_s": percentile(waits, 95),
                "tokens_out": toks,
                "throughput_tok_per_s": round(toks / span_s, 4),
            }
        return rows

    def _recovery(self, disturb_end_step: Optional[int]) -> dict:
        """Time from the end of the disturbance window (burst end / crash)
        to the first sustained drained-queue sample."""
        if disturb_end_step is None:
            return {"time_to_recover_s": None, "recovered_at_s": None}
        settle = self.slo.recovery_settle_steps
        end_vt = disturb_end_step * self.step_seconds
        streak = 0
        for s in self.step_samples:
            if s["vt"] < end_vt:
                continue
            streak = streak + 1 if s["waiting"] == 0 else 0
            if streak >= settle:
                recovered = s["vt"] - (settle - 1) * self.step_seconds
                return {"time_to_recover_s": round(recovered - end_vt, 6),
                        "recovered_at_s": recovered}
        return {"time_to_recover_s": None, "recovered_at_s": None}

    def report(self, *, scenario: str, seed: int, steps: int,
               advisories: List[dict],
               disturb_end_step: Optional[int] = None,
               extra: Optional[dict] = None) -> dict:
        phases = self._phase_rows()
        recovery = self._recovery(disturb_end_step)
        # SLO verdict on the phase AFTER the disturbance (or the last
        # phase for steady scenarios)
        final_phase = self.trace.phases[-1].name
        fin = phases.get(final_phase, {})
        slo_met = (
            fin.get("ttft_p95_s") is not None
            and fin["ttft_p95_s"] <= self.slo.ttft_p95
            and (fin.get("queue_wait_p95_s") or 0.0)
            <= self.slo.queue_wait_p95)
        recs = self.records.values()
        report = {
            "scenario": scenario,
            "seed": seed,
            "steps": steps,
            "step_seconds": self.step_seconds,
            "requests": {
                "total": len(self.records),
                "completed": len([r for r in recs if r.status == "ok"]),
                "failed": len([r for r in recs
                               if r.status in ("failed", "crashed")]),
                "shed": len([r for r in recs if r.status == "shed"]),
                "resumed": len([r for r in recs if r.resumed]),
                "tokens_out": sum(r.tokens_out for r in recs),
            },
            "phases": phases,
            "advisories": advisories,
            "actuations": self.actuations,
            "workers": {
                "timeline": self.worker_events,
                "peak_live": max((s["workers"] for s in self.step_samples),
                                 default=0),
            },
            "slo": {
                "targets": self.slo.to_dict(),
                "final_phase": final_phase,
                "met": bool(slo_met),
                **recovery,
            },
        }
        if extra:
            report.update(extra)
        return report
