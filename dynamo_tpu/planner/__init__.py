"""Planner — demand-driven scale advisories (reference
docs/architecture.md:47 roadmap component, realized)."""

from .planner import Planner, WatchTarget, read_advisories
from .policy import (PLANNER_ADVISORY_SUBJECT, PLANNER_KV_PREFIX,
                     ComponentSnapshot, PlannerConfig, ScaleAdvisory,
                     decide)

__all__ = ["Planner", "WatchTarget", "read_advisories",
           "ComponentSnapshot", "PlannerConfig", "ScaleAdvisory", "decide",
           "PLANNER_ADVISORY_SUBJECT", "PLANNER_KV_PREFIX"]
