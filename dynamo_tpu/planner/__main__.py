from .planner import main

raise SystemExit(main())
