"""Scale-decision policy — the pure core of the planner.

Reference: the "Planner" box in docs/architecture.md:47 ("scales up and
down [workers] based on demand") is a roadmap component there; this is
our v0 realization.  The policy is deliberately a pure function of an
observed snapshot + config + clock so it can be unit-tested exhaustively
and reused by any driver (the async Planner component, a CLI dry-run, or
a K8s controller hook).

Signals (per watched component):
  - ForwardPassMetrics scraped from each live worker (cache usage,
    waiting requests) — the same snapshot the KV router costs on.
  - Shared prefill-queue depth (disagg xPyD elasticity: the queue is the
    natural backpressure signal for prefill workers,
    docs/disagg_serving.md:93-100).

Rules (classic utilization band + hysteresis):
  - UP   when mean cache usage > high-water, or waiting/worker > cap,
         or queue depth/worker > cap.  Step is proportional to overload.
  - DOWN one replica at a time when everything is comfortably under the
         low-water mark — and only after a (longer) cooldown.
  - Cooldowns gate both directions so advisories cannot flap.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..llm.kv_router.protocols import ForwardPassMetrics

PLANNER_ADVISORY_SUBJECT = "planner.advisory"   # published under <ns>.
PLANNER_KV_PREFIX = "planner/advisories/"


@dataclass
class PlannerConfig:
    min_replicas: int = 1
    max_replicas: int = 8
    # utilization band on mean KV-cache usage
    cache_high_water: float = 0.85
    cache_low_water: float = 0.30
    # request-pressure caps
    waiting_per_worker_high: float = 2.0
    queue_depth_per_worker_high: float = 4.0
    # hysteresis
    scale_up_cooldown_s: float = 30.0
    scale_down_cooldown_s: float = 180.0

    def clamp(self, n: int) -> int:
        return max(self.min_replicas, min(self.max_replicas, n))


@dataclass
class ComponentSnapshot:
    """What the planner observed for one component this tick."""

    component: str
    metrics: Dict[int, ForwardPassMetrics] = field(default_factory=dict)
    queue_depth: int = 0          # shared work queue feeding this pool

    @property
    def replicas(self) -> int:
        return len(self.metrics)

    @property
    def mean_cache_usage(self) -> float:
        if not self.metrics:
            return 0.0
        return (sum(m.gpu_cache_usage_perc for m in self.metrics.values())
                / len(self.metrics))

    @property
    def total_waiting(self) -> int:
        return sum(m.num_requests_waiting for m in self.metrics.values())


@dataclass
class ScaleAdvisory:
    """One scale decision, published on the event plane and stored in KV
    for the admin API.  ``at`` is injected by the caller (wall time)."""

    component: str
    current_replicas: int
    desired_replicas: int
    reason: str
    at: float = 0.0

    @property
    def direction(self) -> str:
        if self.desired_replicas > self.current_replicas:
            return "up"
        if self.desired_replicas < self.current_replicas:
            return "down"
        return "hold"

    def to_dict(self) -> dict:
        return {"component": self.component,
                "current_replicas": self.current_replicas,
                "desired_replicas": self.desired_replicas,
                "reason": self.reason, "at": self.at,
                "direction": self.direction}

    @classmethod
    def from_dict(cls, d: dict) -> "ScaleAdvisory":
        return cls(component=d["component"],
                   current_replicas=int(d["current_replicas"]),
                   desired_replicas=int(d["desired_replicas"]),
                   reason=d["reason"], at=float(d.get("at", 0.0)))


def decide(snap: ComponentSnapshot, cfg: PlannerConfig, *, now: float,
           last_up_at: float = float("-inf"),
           last_down_at: float = float("-inf")
           ) -> Optional[ScaleAdvisory]:
    """Return a scale advisory, or None when no change is warranted.

    Pure: all state (snapshot, clock, last-action timestamps) is passed
    in.  A component with zero live replicas yields an UP advisory to
    ``min_replicas`` immediately (cold start / total failure beats
    cooldown).
    """
    n = snap.replicas
    if n == 0:
        # cold start / total outage: advise min_replicas, but rate-limit
        # by the up-cooldown so an unobservable pool doesn't republish
        # every tick. NOTE: n==0 can also mean "pool briefly unreachable"
        # (rolling restart, scrape timeout) — Planner._emit therefore
        # never --applies this advisory, it only publishes it.
        if cfg.min_replicas <= 0 or now - last_up_at < cfg.scale_up_cooldown_s:
            return None
        return ScaleAdvisory(snap.component, 0, cfg.min_replicas,
                             "no live replicas", at=now)

    usage = snap.mean_cache_usage
    waiting_pw = snap.total_waiting / n
    queue_pw = snap.queue_depth / n

    # ---- scale up: any pressure signal over its cap -----------------
    pressure = max(
        usage / cfg.cache_high_water if cfg.cache_high_water > 0 else 0.0,
        waiting_pw / cfg.waiting_per_worker_high
        if cfg.waiting_per_worker_high > 0 else 0.0,
        queue_pw / cfg.queue_depth_per_worker_high
        if cfg.queue_depth_per_worker_high > 0 else 0.0,
    )
    if pressure > 1.0:
        if now - last_up_at < cfg.scale_up_cooldown_s:
            return None
        # proportional: enough replicas to bring the worst signal back
        # under its cap, never more than double per step
        desired = cfg.clamp(min(2 * n, math.ceil(n * pressure)))
        if desired > n:
            reasons = []
            if usage > cfg.cache_high_water:
                reasons.append(f"cache usage {usage:.2f} > "
                               f"{cfg.cache_high_water:.2f}")
            if waiting_pw > cfg.waiting_per_worker_high:
                reasons.append(f"waiting/worker {waiting_pw:.1f} > "
                               f"{cfg.waiting_per_worker_high:.1f}")
            if queue_pw > cfg.queue_depth_per_worker_high:
                reasons.append(f"queue/worker {queue_pw:.1f} > "
                               f"{cfg.queue_depth_per_worker_high:.1f}")
            return ScaleAdvisory(snap.component, n, desired,
                                 "; ".join(reasons), at=now)
        return None

    # ---- scale down: everything under the low-water mark ------------
    if (usage < cfg.cache_low_water and snap.total_waiting == 0
            and snap.queue_depth == 0 and n > cfg.min_replicas):
        if now - last_down_at < cfg.scale_down_cooldown_s:
            return None
        # also respect the up-cooldown: don't shed a replica we just added
        if now - last_up_at < cfg.scale_down_cooldown_s:
            return None
        return ScaleAdvisory(
            snap.component, n, cfg.clamp(n - 1),
            f"cache usage {usage:.2f} < {cfg.cache_low_water:.2f}, "
            f"idle queue", at=now)

    return None
