"""Scale-decision policy — the pure core of the planner.

Reference: the "Planner" box in docs/architecture.md:47 ("scales up and
down [workers] based on demand") is a roadmap component there; this is
our v0 realization.  The policy is deliberately a pure function of an
observed snapshot + config + clock so it can be unit-tested exhaustively
and reused by any driver (the async Planner component, a CLI dry-run, or
a K8s controller hook).

Signals (per watched component):
  - ForwardPassMetrics scraped from each live worker (cache usage,
    waiting requests) — the same snapshot the KV router costs on.
  - Shared prefill-queue depth (disagg xPyD elasticity: the queue is the
    natural backpressure signal for prefill workers,
    docs/disagg_serving.md:93-100).

Rules (classic utilization band + hysteresis):
  - UP   when mean cache usage > high-water, or waiting/worker > cap,
         or queue depth/worker > cap.  Step is proportional to overload.
  - DOWN one replica at a time when everything is comfortably under the
         low-water mark — and only after a (longer) cooldown.
  - Cooldowns gate both directions so advisories cannot flap.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..llm.kv_router.protocols import ForwardPassMetrics

PLANNER_ADVISORY_SUBJECT = "planner.advisory"   # published under <ns>.
PLANNER_KV_PREFIX = "planner/advisories/"


@dataclass
class PdConfig:
    """P/D rebalance policy knobs (dynaslo → ROADMAP item 4).

    The planner shifts one worker between the prefill and decode roles
    (total replicas unchanged) when ONE side's SLO error budget is
    burning (pressure = the dynaslo fast-window burn rate of that
    metric's objective) while the other side has slack. TTFT pressure =
    prefill capacity short; ITL pressure = decode capacity short."""

    enabled: bool = False
    # pressure (fast burn rate) above which a shift toward that side is
    # warranted; 1.0 = burning exactly the error budget
    ttft_burn_high: float = 1.0
    itl_burn_high: float = 1.0
    # never shift a side below these floors
    min_prefill: int = 1
    min_decode: int = 1
    # hysteresis between shifts (role flips churn in-flight work less
    # than spawns, but flapping still wastes warm capacity)
    shift_cooldown_s: float = 20.0


@dataclass
class PlannerConfig:
    min_replicas: int = 1
    max_replicas: int = 8
    # utilization band on mean KV-cache usage
    cache_high_water: float = 0.85
    cache_low_water: float = 0.30
    # request-pressure caps
    waiting_per_worker_high: float = 2.0
    queue_depth_per_worker_high: float = 4.0
    # hysteresis
    scale_up_cooldown_s: float = 30.0
    scale_down_cooldown_s: float = 180.0
    # dynaslo P/D rebalance (None/disabled = replica scaling only)
    pd: Optional[PdConfig] = None

    def clamp(self, n: int) -> int:
        return max(self.min_replicas, min(self.max_replicas, n))


@dataclass
class ComponentSnapshot:
    """What the planner observed for one component this tick."""

    component: str
    metrics: Dict[int, ForwardPassMetrics] = field(default_factory=dict)
    queue_depth: int = 0          # shared work queue feeding this pool

    @property
    def replicas(self) -> int:
        return len(self.metrics)

    @property
    def mean_cache_usage(self) -> float:
        if not self.metrics:
            return 0.0
        return (sum(m.gpu_cache_usage_perc for m in self.metrics.values())
                / len(self.metrics))

    @property
    def total_waiting(self) -> int:
        return sum(m.num_requests_waiting for m in self.metrics.values())

    def role_counts(self) -> Dict[str, int]:
        """Workers per serving role (dynaslo P/D rebalance input; a
        legacy worker without the role field counts as unified)."""
        out: Dict[str, int] = {}
        for m in self.metrics.values():
            role = getattr(m, "role", "") or "unified"
            out[role] = out.get(role, 0) + 1
        return out

    @property
    def prefill_replicas(self) -> int:
        return self.role_counts().get("prefill", 0)

    @property
    def decode_replicas(self) -> int:
        """Decode-capable workers (decode + unified)."""
        rc = self.role_counts()
        return rc.get("decode", 0) + rc.get("unified", 0)


@dataclass
class ScaleAdvisory:
    """One scale decision, published on the event plane and stored in KV
    for the admin API.  ``at`` is injected by the caller (wall time)."""

    component: str
    current_replicas: int
    desired_replicas: int
    reason: str
    at: float = 0.0
    # dynaslo P/D rebalance: kind="pd_shift" advisories keep the replica
    # count but move one worker shift_from → shift_to ("prefill"/
    # "decode"). kind="scale" (the default) is the classic replica
    # advisory; absent fields on the wire = legacy scale advisory.
    kind: str = "scale"
    shift_from: str = ""
    shift_to: str = ""

    @property
    def direction(self) -> str:
        if self.desired_replicas > self.current_replicas:
            return "up"
        if self.desired_replicas < self.current_replicas:
            return "down"
        return "hold"

    def to_dict(self) -> dict:
        d = {"component": self.component,
             "current_replicas": self.current_replicas,
             "desired_replicas": self.desired_replicas,
             "reason": self.reason, "at": self.at,
             "direction": self.direction, "kind": self.kind}
        if self.kind == "pd_shift":
            d["shift_from"] = self.shift_from
            d["shift_to"] = self.shift_to
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "ScaleAdvisory":
        return cls(component=d["component"],
                   current_replicas=int(d["current_replicas"]),
                   desired_replicas=int(d["desired_replicas"]),
                   reason=d["reason"], at=float(d.get("at", 0.0)),
                   kind=d.get("kind", "scale"),
                   shift_from=d.get("shift_from", ""),
                   shift_to=d.get("shift_to", ""))


def decide(snap: ComponentSnapshot, cfg: PlannerConfig, *, now: float,
           last_up_at: float = float("-inf"),
           last_down_at: float = float("-inf")
           ) -> Optional[ScaleAdvisory]:
    """Return a scale advisory, or None when no change is warranted.

    Pure: all state (snapshot, clock, last-action timestamps) is passed
    in.  A component with zero live replicas yields an UP advisory to
    ``min_replicas`` immediately (cold start / total failure beats
    cooldown).
    """
    n = snap.replicas
    if n == 0:
        # cold start / total outage: advise min_replicas, but rate-limit
        # by the up-cooldown so an unobservable pool doesn't republish
        # every tick. NOTE: n==0 can also mean "pool briefly unreachable"
        # (rolling restart, scrape timeout) — Planner._emit therefore
        # never --applies this advisory, it only publishes it.
        if cfg.min_replicas <= 0 or now - last_up_at < cfg.scale_up_cooldown_s:
            return None
        return ScaleAdvisory(snap.component, 0, cfg.min_replicas,
                             "no live replicas", at=now)

    usage = snap.mean_cache_usage
    waiting_pw = snap.total_waiting / n
    queue_pw = snap.queue_depth / n

    # ---- scale up: any pressure signal over its cap -----------------
    pressure = max(
        usage / cfg.cache_high_water if cfg.cache_high_water > 0 else 0.0,
        waiting_pw / cfg.waiting_per_worker_high
        if cfg.waiting_per_worker_high > 0 else 0.0,
        queue_pw / cfg.queue_depth_per_worker_high
        if cfg.queue_depth_per_worker_high > 0 else 0.0,
    )
    if pressure > 1.0:
        if now - last_up_at < cfg.scale_up_cooldown_s:
            return None
        # proportional: enough replicas to bring the worst signal back
        # under its cap, never more than double per step
        desired = cfg.clamp(min(2 * n, math.ceil(n * pressure)))
        if desired > n:
            reasons = []
            if usage > cfg.cache_high_water:
                reasons.append(f"cache usage {usage:.2f} > "
                               f"{cfg.cache_high_water:.2f}")
            if waiting_pw > cfg.waiting_per_worker_high:
                reasons.append(f"waiting/worker {waiting_pw:.1f} > "
                               f"{cfg.waiting_per_worker_high:.1f}")
            if queue_pw > cfg.queue_depth_per_worker_high:
                reasons.append(f"queue/worker {queue_pw:.1f} > "
                               f"{cfg.queue_depth_per_worker_high:.1f}")
            return ScaleAdvisory(snap.component, n, desired,
                                 "; ".join(reasons), at=now)
        return None

    # ---- scale down: everything under the low-water mark ------------
    if (usage < cfg.cache_low_water and snap.total_waiting == 0
            and snap.queue_depth == 0 and n > cfg.min_replicas):
        if now - last_down_at < cfg.scale_down_cooldown_s:
            return None
        # also respect the up-cooldown: don't shed a replica we just added
        if now - last_up_at < cfg.scale_down_cooldown_s:
            return None
        return ScaleAdvisory(
            snap.component, n, cfg.clamp(n - 1),
            f"cache usage {usage:.2f} < {cfg.cache_low_water:.2f}, "
            f"idle queue", at=now)

    return None


def decide_pd(snap: ComponentSnapshot, pd: PdConfig,
              pressures: Dict[str, float], *, now: float,
              last_shift_at: float = float("-inf")
              ) -> Optional[ScaleAdvisory]:
    """P/D rebalance decision (pure, like :func:`decide`).

    ``pressures`` is the dynaslo pressure dict
    ({"ttft_pressure": fast burn, "itl_pressure": fast burn, ...}): TTFT
    burning while ITL has slack → convert one decode worker to prefill;
    the mirror image converts one back. One shift per cooldown, floors
    respected, and the DOMINANT pressure wins a tie so the loop cannot
    oscillate inside a single evaluation."""
    if not pd.enabled or snap.replicas == 0:
        return None
    # the cooldown gate readmits the next shift decision
    # proto: planner.pd_shift actuated->idle
    if now - last_shift_at < pd.shift_cooldown_s:
        return None
    ttft_p = pressures.get("ttft_pressure", 0.0)
    itl_p = pressures.get("itl_pressure", 0.0)
    n = snap.replicas
    if (ttft_p > pd.ttft_burn_high and ttft_p >= itl_p
            and snap.decode_replicas > pd.min_decode):
        # proto: planner.pd_shift idle->advisory
        return ScaleAdvisory(
            snap.component, n, n,
            f"ttft burn {ttft_p:.2f} > {pd.ttft_burn_high:.2f} "
            f"(itl burn {itl_p:.2f}): shift decode->prefill",
            at=now, kind="pd_shift",
            shift_from="decode", shift_to="prefill")
    if (itl_p > pd.itl_burn_high and itl_p > ttft_p
            and snap.prefill_replicas > pd.min_prefill):
        # proto: planner.pd_shift idle->advisory
        return ScaleAdvisory(
            snap.component, n, n,
            f"itl burn {itl_p:.2f} > {pd.itl_burn_high:.2f} "
            f"(ttft burn {ttft_p:.2f}): shift prefill->decode",
            at=now, kind="pd_shift",
            shift_from="prefill", shift_to="decode")
    return None
