"""Planner v0 — demand-driven scale advisories.

Reference docs/architecture.md:47 describes the Planner as the component
that "scales up and down [workers] based on demand"; the reference ships
it as a roadmap box.  Here it is a real component: it scrapes the same
ForwardPassMetrics plane the KV router costs on, reads the shared
prefill-queue depth, runs the pure policy (policy.py), and

  1. publishes every advisory on the event plane
     (``<ns>.planner.advisory``) for anything to consume,
  2. stores the latest advisory per component in KV
     (``planner/advisories/<component>``) so the admin API can surface
     it, and
  3. (``apply=True``) edits the stored deployment spec's replica count
     (``deployments/<name>``) — the K8s renderer/controller then
     converge the cluster, closing the elastic loop end-to-end.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..llm.kv_router.protocols import ForwardPassMetrics
from ..runtime import wire
from ..runtime.component import Client
from ..runtime.config import env_str
from ..runtime.dcp_client import pack, unpack
from ..runtime.runtime import DistributedRuntime
from ..runtime.tasks import cancel_join, spawn_tracked
from .policy import (PLANNER_ADVISORY_SUBJECT, PLANNER_KV_PREFIX,
                     ComponentSnapshot, PlannerConfig, ScaleAdvisory, decide,
                     decide_pd)

from ..admin.store import DEPLOYMENT_PREFIX

log = logging.getLogger("dynamo_tpu.planner")


@dataclass
class WatchTarget:
    """One scaled pool the planner observes."""

    component: str
    endpoint: str = "generate_tokens"
    queue: Optional[str] = None       # DCP work queue feeding this pool
    deployment: Optional[str] = None  # stored deployment spec to edit
    service: Optional[str] = None     # service key inside that spec
    config: PlannerConfig = field(default_factory=PlannerConfig)


class Planner:
    def __init__(self, drt: DistributedRuntime, namespace: str = "dynamo",
                 targets: Optional[List[WatchTarget]] = None,
                 interval: float = 5.0, apply: bool = False,
                 clock=time.monotonic, wall_clock=time.time,
                 pressure_source=None):
        self.drt = drt
        self.namespace = namespace
        self.targets = targets or []
        self.interval = interval
        self.apply = apply
        self.clock = clock
        # ``at`` on the wire: injectable so simulated runs (fleet sim) get
        # advisory timestamps on the same virtual clock as everything else
        self.wall_clock = wall_clock
        # dynaslo advisory input: a zero-arg callable returning the SLO
        # engine's pressure dict ({"ttft_pressure": burn, ...}) — the
        # P/D rebalance policy (PlannerConfig.pd) consumes it. None
        # disables P/D decisions regardless of config.
        self.pressure_source = pressure_source
        self._clients: Dict[str, Client] = {}
        self._last_up: Dict[str, float] = {}
        self._last_down: Dict[str, float] = {}
        self._last_shift: Dict[str, float] = {}
        self._task: Optional[asyncio.Task] = None
        self.advisories: List[ScaleAdvisory] = []   # emitted this lifetime

    # ------------------------------------------------------------ lifecycle

    async def start(self, *, run_loop: bool = True) -> None:
        """Create the stats clients and (unless ``run_loop=False``) spawn
        the periodic tick task. Drivers that tick manually — tests and the
        fleet simulator's step loop — pass ``run_loop=False``."""
        for t in self.targets:
            self._clients[t.component] = await self.drt.namespace(
                self.namespace).component(t.component).endpoint(
                t.endpoint).client()
            # startup hysteresis, down-direction only: a fresh planner has
            # no load history, and its first tick of a momentarily-idle
            # pool must not shed a replica — wait out a full down-cooldown
            # from start. Scale-UP stays immediate (cold start / outage
            # response beats conservatism).
            self._last_down.setdefault(t.component, self.clock())
        if run_loop:
            self._task = spawn_tracked(self._loop(), name="planner-tick")

    async def stop(self) -> None:
        # claim the task before the await (concurrent stops must not
        # double-cancel), then wait the cancellation out before closing
        # the clients the in-flight tick may still be using
        task, self._task = self._task, None
        await cancel_join(task)
        for c in self._clients.values():
            await c.close()
        self._clients.clear()

    async def _loop(self) -> None:
        while True:
            try:
                await self.tick()
            except Exception:
                log.exception("planner tick failed")
            await asyncio.sleep(self.interval)

    # ----------------------------------------------------------------- tick

    async def observe(self, t: WatchTarget) -> ComponentSnapshot:
        stats = await self._clients[t.component].collect_stats()
        metrics = {}
        for wid, payload in stats.items():
            payload = wire.decoded(wire.DCP_STATS_REPLY, payload)
            metrics[wid] = ForwardPassMetrics.from_dict(
                payload.get("data") or {})
        depth = 0
        if t.queue:
            depth = await self.drt.dcp.queue_len(
                f"{self.namespace}.{t.queue}")
        return ComponentSnapshot(component=t.component, metrics=metrics,
                                 queue_depth=depth)

    async def tick(self) -> List[ScaleAdvisory]:
        """One observe→decide→emit pass over all targets. Returns the
        advisories emitted this tick (also accumulated on
        ``self.advisories``)."""
        now = self.clock()
        out: List[ScaleAdvisory] = []
        for t in self.targets:
            snap = await self.observe(t)
            adv = decide(
                snap, t.config, now=now,
                last_up_at=self._last_up.get(t.component, float("-inf")),
                last_down_at=self._last_down.get(
                    t.component, float("-inf")))
            if adv is not None:
                adv.at = self.wall_clock()   # wall time on the wire
                if adv.direction == "up":
                    self._last_up[t.component] = now
                elif adv.direction == "down":
                    self._last_down[t.component] = now
                await self._emit(t, adv)
                out.append(adv)
                self.advisories.append(adv)
            # dynaslo P/D rebalance: a second, independent decision per
            # tick — shift one worker between prefill and decode roles
            # when one side's SLO error budget burns while the other has
            # slack (pressures come from the SLO engine's fast windows)
            if (t.config.pd is not None and t.config.pd.enabled
                    and self.pressure_source is not None):
                shift = decide_pd(
                    snap, t.config.pd, self.pressure_source(), now=now,
                    last_shift_at=self._last_shift.get(
                        t.component, float("-inf")))
                if shift is not None:
                    shift.at = self.wall_clock()
                    self._last_shift[t.component] = now
                    await self._emit(t, shift)
                    out.append(shift)
                    self.advisories.append(shift)
        return out

    async def _emit(self, t: WatchTarget, adv: ScaleAdvisory) -> None:
        log.info("scale advisory %s: %d -> %d (%s)", adv.component,
                 adv.current_replicas, adv.desired_replicas, adv.reason)
        await self.drt.dcp.publish(
            f"{self.namespace}.{PLANNER_ADVISORY_SUBJECT}",
            pack(adv.to_dict()))
        await self.drt.dcp.kv_put(
            f"{PLANNER_KV_PREFIX}{adv.component}", pack(adv.to_dict()))
        # never auto-apply a zero-observed advisory: n==0 is ambiguous
        # between "scaled to zero" and "briefly unobservable" (rolling
        # restart / scrape timeout), and shrinking a live deployment to
        # min_replicas on a scrape blip would be destructive
        # pd_shift advisories keep the replica count — nothing to apply
        # to the deployment spec; the fleet controller actuates the flip
        if (self.apply and t.deployment and adv.kind == "scale"
                and adv.current_replicas > 0):
            await self._apply(t, adv)

    async def _apply(self, t: WatchTarget, adv: ScaleAdvisory,
                     retries: int = 3) -> None:
        """Edit the stored deployment spec so the K8s reconcile loop
        (k8s/controller.py) converges replicas — planner decides,
        controller actuates.  CAS on mod_rev so a concurrent admin-API
        spec update (new image, config) is never silently reverted."""
        key = f"{DEPLOYMENT_PREFIX}{t.deployment}"
        for _ in range(retries):
            item = await self.drt.dcp.kv_get_item(key)
            if item is None:
                log.warning("apply: stored deployment %r not found",
                            t.deployment)
                return
            spec = unpack(item.value)
            services = (spec.get("spec") or {}).get("services") or {}
            svc_key = t.service or t.component
            if svc_key not in services:
                log.warning("apply: service %r not in deployment %r",
                            svc_key, t.deployment)
                return
            services[svc_key]["replicas"] = adv.desired_replicas
            if await self.drt.dcp.kv_cas(key, pack(spec), item.mod_rev):
                log.info("applied: %s/%s replicas=%d", t.deployment,
                         svc_key, adv.desired_replicas)
                return
        log.warning("apply: CAS conflict persisted for %r after %d tries",
                    t.deployment, retries)


async def read_advisories(dcp, limit: int = 64) -> List[dict]:
    """Latest advisory per component, for the admin API."""
    items = await dcp.kv_get_prefix(PLANNER_KV_PREFIX)
    out = [unpack(i.value) for i in items]
    out.sort(key=lambda d: -float(d.get("at", 0.0)))
    return out[:limit]


def main(argv=None) -> int:
    """Standalone planner process.

        python -m dynamo_tpu.planner --component decode \\
            --queue prefill_queue --apply --deployment my-graph
    """
    import argparse

    ap = argparse.ArgumentParser(prog="dynamo-planner")
    ap.add_argument("--namespace", default="dynamo")
    ap.add_argument("--component", action="append", required=True,
                    help="component pool to watch (repeatable)")
    ap.add_argument("--endpoint", default="generate_tokens")
    ap.add_argument("--queue", default=None,
                    help="DCP work queue feeding the pool")
    ap.add_argument("--deployment", default=None,
                    help="stored deployment spec to edit with --apply")
    ap.add_argument("--apply", action="store_true")
    ap.add_argument("--interval", type=float, default=5.0)
    ap.add_argument("--min-replicas", type=int, default=1)
    ap.add_argument("--max-replicas", type=int, default=8)
    ap.add_argument("--dcp", default=None)
    args = ap.parse_args(argv)

    cfg = PlannerConfig(min_replicas=args.min_replicas,
                        max_replicas=args.max_replicas)
    targets = [WatchTarget(component=c, endpoint=args.endpoint,
                           queue=args.queue, deployment=args.deployment,
                           config=cfg)
               for c in args.component]

    async def amain():
        drt = await DistributedRuntime.attach(
            args.dcp or env_str("DYN_DCP_ADDRESS"))
        planner = Planner(drt, args.namespace, targets,
                          interval=args.interval, apply=args.apply)
        await planner.start()
        try:
            await asyncio.Event().wait()
        finally:
            await planner.stop()
            await drt.shutdown()

    logging.basicConfig(level="INFO")
    asyncio.run(amain())
    return 0


if __name__ == "__main__":
    main()
