"""Namespace-wide metrics aggregator component.

Reference components/metrics (src/main.rs:24-46 + lib.rs, ~1,000 LoC):
scrapes worker ForwardPassMetrics over the service-stats plane, subscribes
``kv-hit-rate`` events from the router, and exposes everything as
Prometheus text for Grafana (deploy/metrics/grafana.json).

Gauges mirror the reference's aggregator: per-worker slots/blocks/waiting/
cache-usage plus namespace aggregates (avg/min/max), and hit-rate counters
(isl blocks vs overlap blocks per routed request).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Callable, Dict, Optional

from ..llm.kv_router.protocols import KV_HIT_RATE_SUBJECT, ForwardPassMetrics
from ..runtime.component import Client, EndpointAddress
from ..runtime.config import env_str
from ..runtime import blackbox, wire
from ..runtime.dcp_client import unpack
from ..runtime.runtime import DistributedRuntime
from ..runtime.slo import (Histogram, SloEngine, SloRegistry, collapse_roles,
                           merge_latency_wire, render_role_histograms)
from ..runtime.tasks import backoff_interval, cancel_join, spawn_tracked

log = logging.getLogger("dynamo_tpu.metrics")


class MetricsAggregator:
    """Scrape + subscribe + render (one per namespace)."""

    def __init__(self, drt: DistributedRuntime, namespace: str,
                 component: str, endpoint: str = "generate_tokens",
                 interval: float = 2.0,
                 slo_registry: Optional[SloRegistry] = None,
                 slo_clock: Callable[[], float] = time.monotonic):
        self.drt = drt
        self.namespace = namespace
        self.address = EndpointAddress(namespace, component, endpoint)
        self.interval = interval
        # written by the scrape loop, read by every /metrics render;
        # single-statement accesses only (atomic under the event loop)
        self.worker_metrics: Dict[int, ForwardPassMetrics] = {}  # guarded-by: loop
        self.hit_rate_isl_blocks = 0
        self.hit_rate_overlap_blocks = 0
        self.hit_rate_events = 0
        # failed scrape attempts (the PR 3 backoff path, now visible in
        # the exposition instead of only the logs)
        self.scrape_failures_total = 0
        self.consecutive_scrape_failures = 0
        # dynaslo: fold each scraped worker's per-role latency histograms
        # into a run-long per-worker view (a drained worker's histogram
        # leaves worker_metrics with it, but its observations happened)
        # and evaluate the SLO registry over the fleet-merged result on
        # every scrape. The clock is injectable: wall time in serving,
        # virtual time in the fleet simulator.
        self._latency_seen: Dict[int, dict] = {}  # guarded-by: loop
        self.slo = SloEngine(
            slo_registry if slo_registry is not None
            else SloRegistry.from_env(),
            source=self.merged_latency_all_roles, clock=slo_clock)
        self._client: Optional[Client] = None
        self._task: Optional[asyncio.Task] = None
        self._sid: Optional[int] = None
        self._bb_sid: Optional[int] = None

    def last_scrape(self) -> dict:
        """The most recent fleet scrape as a JSON-safe dict — folded into
        dynablack incident bundles as the 'what did the aggregator see
        last' evidence."""
        return {
            "workers": {str(wid): m.to_dict()
                        for wid, m in sorted(self.worker_metrics.items())},
            "hit_rate_events": self.hit_rate_events,
            "scrape_failures_total": self.scrape_failures_total,
            "alerts": list(self.slo.alert_events[-20:]),
        }

    async def start(self, *, run_loop: bool = True) -> None:
        """``run_loop=False`` skips the periodic scrape task; drivers that
        step time themselves (the fleet simulator) call ``scrape_once``
        directly."""
        self._client = await self.drt.namespace(
            self.address.namespace).component(
            self.address.component).endpoint(self.address.endpoint).client()
        self._sid = await self.drt.dcp.subscribe(
            f"{self.namespace}.{KV_HIT_RATE_SUBJECT}", self._on_hit_rate)
        # dynablack: join the incident capture fan-out — the aggregator
        # contributes its last fleet scrape and receives sibling captures
        rec = blackbox.get_recorder()
        if rec.enabled:
            rec.add_source("fleet_scrape", self.last_scrape)
            self._bb_sid = await blackbox.attach_dcp(
                self.drt, self.namespace, rec,
                f"aggregator-{self.address.component}")
        if run_loop:
            self._task = spawn_tracked(self._loop(), name="metrics-scrape")

    async def stop(self) -> None:
        await cancel_join(self._task)
        for sid in (self._sid, self._bb_sid):
            if sid is None:
                continue
            try:
                await self.drt.dcp.unsubscribe(sid)
            except Exception:
                log.debug("unsubscribe failed during stop", exc_info=True)
        if self._client:
            await self._client.close()

    async def _on_hit_rate(self, msg) -> None:
        ev = unpack(msg.payload)
        self.hit_rate_events += 1
        self.hit_rate_isl_blocks += int(ev.get("isl_blocks", 0))
        self.hit_rate_overlap_blocks += int(ev.get("overlap_blocks", 0))

    async def _loop(self) -> None:
        failures = 0
        while True:
            try:
                await self.scrape_once()
                failures = 0
            except Exception:
                # bounded backoff: a persistently-down stats plane gets
                # polled gently instead of hammered every interval forever
                failures += 1
                self.scrape_failures_total += 1
                log.exception("metrics scrape failed "
                              "(%d consecutive failures)", failures)
            self.consecutive_scrape_failures = failures
            await asyncio.sleep(backoff_interval(self.interval, failures))

    async def scrape_once(self) -> None:
        stats = await self._client.collect_stats()
        live = set()
        for instance_id, payload in stats.items():
            payload = wire.decoded(wire.DCP_STATS_REPLY, payload)
            data = payload.get("data") or {}
            self.worker_metrics[instance_id] = ForwardPassMetrics.from_dict(
                data)
            live.add(instance_id)
        # drop metrics of departed workers (lease expiry) and of workers
        # quarantined off the stats plane (a crashed-but-leased worker
        # must not keep contributing its last-known load forever)
        evicted = set(self._client.evicted_ids())
        for wid in list(self.worker_metrics):
            if wid not in live and (wid not in self._client.instances
                                    or wid in evicted):
                del self.worker_metrics[wid]
        # dynaslo: per-worker histograms are monotonic counters, so the
        # newest scrape simply overwrites; departed workers keep their
        # last-seen contribution (fleet totals never regress on a drain)
        for wid, m in self.worker_metrics.items():
            if m.latency_hist:
                self._latency_seen[wid] = m.latency_hist
        self.slo.tick()

    # ----------------------------------------------------- dynaslo merging

    def merged_latency(self) -> Dict[str, Dict[str, Histogram]]:
        """Fleet-wide ``{role: {metric: Histogram}}`` — every worker's
        latency histograms losslessly merged (the first cross-worker
        latency view; per-worker gauges could never aggregate)."""
        return merge_latency_wire(self._latency_seen.values())

    def merged_latency_all_roles(self) -> Dict[str, Histogram]:
        """Role-collapsed merge — the SLO engine's evaluation source."""
        return collapse_roles(self.merged_latency())

    def slo_snapshot(self) -> dict:
        """The aggregator-side /debug/slo payload: registry, evaluation,
        pressures, alert timeline, plus merged per-role quantiles."""
        snap = self.slo.snapshot()
        snap["quantiles"] = {
            role: {metric: {"p50": h.quantile(0.5), "p95": h.quantile(0.95),
                            "p99": h.quantile(0.99), "count": h.count}
                   for metric, h in sorted(per.items())}
            for role, per in sorted(self.merged_latency().items())}
        return snap

    # ------------------------------------------------------------- render

    def render_prometheus(self) -> str:
        """Prometheus text exposition (reference lib.rs gauges +
        deploy/metrics Grafana dashboard feed)."""
        ns = self.namespace
        lines = []

        def gauge(name, help_, rows):
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} gauge")
            lines.extend(rows)

        def wlabels(wid, m) -> str:
            """Per-worker label set. The `replica` label (the engine's
            stable worker_label, dynashard) disambiguates N replicas in
            one process and survives restarts — the `worker` lease hex
            does neither."""
            extra = ""
            if getattr(m, "worker_label", ""):
                extra = f',replica="{m.worker_label}"'
            return f'namespace="{ns}",worker="{wid:x}"{extra}'

        per_worker = [
            ("dyn_engine_mesh_devices",
             "devices in this worker's submesh (1 = unsharded; dynashard)",
             lambda m: m.mesh_devices),
            ("dyn_worker_draining",
             "1 while the worker drains (discovery withdrawn, in-flight "
             "finishing; dynarevive — draining is not dead)",
             lambda m: m.draining),
            ("dyn_worker_request_active_slots", "active request slots",
             lambda m: m.request_active_slots),
            ("dyn_worker_request_total_slots", "total request slots",
             lambda m: m.request_total_slots),
            ("dyn_worker_kv_active_blocks", "active KV blocks",
             lambda m: m.kv_active_blocks),
            ("dyn_worker_kv_total_blocks", "total KV blocks",
             lambda m: m.kv_total_blocks),
            ("dyn_worker_requests_waiting", "queued requests",
             lambda m: m.num_requests_waiting),
            ("dyn_worker_cache_usage_perc", "KV cache usage fraction",
             lambda m: m.gpu_cache_usage_perc),
            ("dyn_worker_prefix_cache_hit_rate",
             "engine prefix hit rate (windowed over recent admissions)",
             lambda m: m.gpu_prefix_cache_hit_rate),
            # dynacache: cache-lifecycle plane (allocation prefix split,
            # eviction fates + block age, restore queue) — every counter
            # the engine's PageManager keeps, per worker
            ("dyn_engine_cache_hit_rate_lifetime",
             "engine prefix hit rate since start (cumulative)",
             lambda m: m.gpu_prefix_cache_hit_rate_lifetime),
            ("dyn_engine_cache_prefix_hit_tokens_total",
             "prompt tokens served from the prefix cache",
             lambda m: m.prefix_hit_tokens_total),
            ("dyn_engine_cache_prompt_tokens_total",
             "prompt tokens admitted", lambda m: m.prompt_tokens_total),
            ("dyn_engine_cache_device_hit_blocks_total",
             "allocated blocks reused directly from the HBM pool",
             lambda m: m.cache_device_hit_blocks_total),
            ("dyn_engine_cache_host_restored_blocks_total",
             "allocated blocks restored from the host-DRAM tier",
             lambda m: m.cache_host_restored_blocks_total),
            ("dyn_engine_cache_fresh_blocks_total",
             "allocated blocks computed fresh (no cache source)",
             lambda m: m.cache_fresh_blocks_total),
            ("dyn_engine_cache_evict_offloaded_total",
             "HBM evictions that spilled to the host tier",
             lambda m: m.cache_evict_offloaded_total),
            ("dyn_engine_cache_evict_dropped_total",
             "HBM evictions dropped entirely (no host slot)",
             lambda m: m.cache_evict_dropped_total),
            ("dyn_engine_cache_evict_age_seconds_total",
             "summed block age (commit to eviction) of evicted blocks",
             lambda m: m.cache_evict_age_seconds_total),
            ("dyn_engine_cache_host_evictions_total",
             "host-tier blocks evicted to make room",
             lambda m: m.cache_host_evictions_total),
            ("dyn_engine_cache_restore_queue_depth",
             "host->HBM restores queued but not yet dispatched",
             lambda m: m.cache_restore_queue_depth),
            ("dyn_engine_cache_restores_drained_total",
             "host->HBM restores dispatched",
             lambda m: m.cache_restores_drained_total),
            ("dyn_engine_cache_restore_wait_seconds_total",
             "summed queue wait of dispatched restores",
             lambda m: m.cache_restore_wait_seconds_total),
            ("dyn_engine_cache_restore_batches_total",
             "host->HBM restore batches dispatched (dynaheat batching)",
             lambda m: m.cache_restore_batches_total),
            ("dyn_engine_cache_restore_batch_pages_total",
             "pages across dispatched restore batches (mean batch size "
             "= pages / batches)",
             lambda m: m.cache_restore_batch_pages_total),
            ("dyn_engine_batch_dispatches_total",
             "dispatches that distributed a per-request step share "
             "(dynaprof attribution conservation denominator)",
             lambda m: m.batch_dispatches_total),
            ("dyn_worker_spec_decode_acceptance_rate",
             "speculative-draft tokens accepted / drafted",
             lambda m: m.spec_decode_acceptance_rate),
            ("dyn_worker_spec_decode_mean_accepted_len",
             "mean accepted draft length per verify step",
             lambda m: m.spec_decode_mean_accepted_len),
            ("dyn_engine_post_warmup_compiles_total",
             "XLA compiles after warmup (compile-fence counter; nonzero "
             "= a mid-serving compile stalled this worker)",
             lambda m: m.post_warmup_compiles_total),
            ("dyn_worker_kv_transfer_bytes_total",
             "disagg KV bytes ingested over the transfer plane",
             lambda m: m.kv_transfer_bytes_total),
            ("dyn_worker_kv_transfer_chunks_total",
             "disagg KV chunk frames ingested",
             lambda m: m.kv_transfer_chunks_total),
            ("dyn_worker_kv_transfer_inject_seconds_total",
             "seconds spent injecting transferred KV into the pool",
             lambda m: m.kv_transfer_inject_seconds_total),
            ("dyn_worker_kv_transfer_streams_failed_total",
             "KV transfer streams torn down before commit",
             lambda m: m.kv_transfer_streams_failed_total),
            ("dyn_worker_remote_prefill_wait_seconds_total",
             "decode-side wait for remote prefill (enqueue to KV commit)",
             lambda m: m.remote_prefill_wait_seconds_total),
            # dynaprof: engine internals that previously never left
            # stats() + the sampled device/host split
            ("dyn_engine_inflight_sequences",
             "sequences holding engine batch slots (prefilling+running)",
             lambda m: m.request_active_slots),
            ("dyn_engine_admission_queue_depth",
             "requests waiting for engine admission",
             lambda m: m.num_requests_waiting),
            ("dyn_engine_queue_wait_seconds_total",
             "cumulative seconds requests spent waiting for admission",
             lambda m: m.queue_wait_seconds_total),
            ("dyn_engine_kv_free_blocks",
             "free HBM KV pages", lambda m: m.kv_free_blocks),
            ("dyn_engine_kv_cached_blocks",
             "reusable prefix-cache HBM KV pages",
             lambda m: m.kv_cached_blocks),
            ("dyn_engine_host_free_blocks",
             "free host-tier KV pages", lambda m: m.host_free_blocks),
            ("dyn_engine_host_cache_usage_perc",
             "host offload-tier usage fraction",
             lambda m: m.host_cache_usage_perc),
            ("dyn_engine_host_offload_pages_total",
             "pages evicted HBM->host tier",
             lambda m: m.host_offload_pages_total),
            ("dyn_engine_host_restore_pages_total",
             "pages restored host tier->HBM",
             lambda m: m.host_restore_pages_total),
            ("dyn_engine_long_prefills_total",
             "sequence-parallel ring prefills served",
             lambda m: m.long_prefills_total),
            ("dyn_engine_device_time_fraction",
             "sampled device-drain fraction of (device + host dispatch) "
             "time (dynaprof; 0 until DYN_PROF_SAMPLE>0 samples a step)",
             lambda m: m.device_time_fraction),
            ("dyn_engine_profiled_steps_total",
             "scheduler iterations sampled by the dynaprof timed "
             "dispatch", lambda m: m.profiled_steps_total),
        ]
        for name, help_, get in per_worker:
            rows = [
                f'{name}{{{wlabels(wid, m)}}} {get(m)}'
                for wid, m in sorted(self.worker_metrics.items())]
            gauge(name, help_, rows)
        # dynaprof labeled families: loop lag quantiles + per-bucket
        # program cost (one row per compiled (kind, bucket) program —
        # the ROADMAP item-3 regression surface)
        gauge("dyn_runtime_loop_lag_seconds",
              "per-worker event-loop sleep-drift percentiles (dynaprof)",
              [f'dyn_runtime_loop_lag_seconds{{{wlabels(wid, m)},'
               f'quantile="{q}"}} {val}'
               for wid, m in sorted(self.worker_metrics.items())
               for q, val in (("p50", m.loop_lag_p50_seconds),
                              ("p99", m.loop_lag_p99_seconds))])
        gauge("dyn_engine_bucket_cost_us",
              "mean sampled device-drain microseconds per dispatch, per "
              "compiled (kind, bucket) program (dynaprof cost table)",
              [f'dyn_engine_bucket_cost_us{{{wlabels(wid, m)},'
               f'bucket="{bucket}"}} '
               f'{row.get("device_us", 0.0)}'
               for wid, m in sorted(self.worker_metrics.items())
               for bucket, row in sorted(
                   (m.bucket_cost or {}).items())])
        usages = [m.gpu_cache_usage_perc
                  for m in self.worker_metrics.values()]
        if usages:
            gauge("dyn_namespace_cache_usage_avg", "mean cache usage",
                  [f'dyn_namespace_cache_usage_avg{{namespace="{ns}"}} '
                   f'{sum(usages)/len(usages)}'])
        lines.append("# HELP dyn_kv_hit_rate_isl_blocks routed prompt "
                     "blocks total")
        lines.append("# TYPE dyn_kv_hit_rate_isl_blocks counter")
        lines.append(f'dyn_kv_hit_rate_isl_blocks{{namespace="{ns}"}} '
                     f'{self.hit_rate_isl_blocks}')
        lines.append("# HELP dyn_kv_hit_rate_overlap_blocks routed prompt "
                     "blocks served from cache")
        lines.append("# TYPE dyn_kv_hit_rate_overlap_blocks counter")
        lines.append(f'dyn_kv_hit_rate_overlap_blocks{{namespace="{ns}"}} '
                     f'{self.hit_rate_overlap_blocks}')
        lines.append("# HELP dyn_kv_hit_rate_events routing decisions seen")
        lines.append("# TYPE dyn_kv_hit_rate_events counter")
        lines.append(f'dyn_kv_hit_rate_events{{namespace="{ns}"}} '
                     f'{self.hit_rate_events}')
        lines.append("# HELP dyn_metrics_scrape_failures_total failed "
                     "stats-plane scrape attempts (backoff path)")
        lines.append("# TYPE dyn_metrics_scrape_failures_total counter")
        lines.append(f'dyn_metrics_scrape_failures_total{{namespace="{ns}"}} '
                     f'{self.scrape_failures_total}')
        lines.append("# HELP dyn_metrics_consecutive_scrape_failures "
                     "current failure streak driving the scrape backoff")
        lines.append("# TYPE dyn_metrics_consecutive_scrape_failures gauge")
        lines.append(
            f'dyn_metrics_consecutive_scrape_failures{{namespace="{ns}"}} '
            f'{self.consecutive_scrape_failures}')
        evicted = len(self._client.evicted_ids()) if self._client else 0
        lines.append("# HELP dyn_metrics_evicted_instances instances "
                     "whose stats-plane circuit breaker is open after "
                     "consecutive probe failures (stale-endpoint hygiene)")
        lines.append("# TYPE dyn_metrics_evicted_instances gauge")
        lines.append(f'dyn_metrics_evicted_instances{{namespace="{ns}"}} '
                     f'{evicted}')
        # dynaslo plane: fleet-merged per-role latency histograms (the
        # first cross-worker TTFT/ITL/queue-wait/e2e quantiles) plus the
        # SLO registry's attainment / error-budget / burn-rate / alert /
        # pressure gauges
        if getattr(self, "_latency_seen", None) is not None:
            nslabel = f'namespace="{ns}"'
            lines.extend(render_role_histograms(self.merged_latency(),
                                                labels=nslabel))
            lines.extend(self.slo.render_prom_lines(labels=nslabel))
        # dynaguard plane: per-endpoint breaker state gauges + counters
        from ..runtime import guard

        lines.extend(guard.render_prom_lines())
        return "\n".join(lines) + "\n"


async def serve_metrics(drt: DistributedRuntime, namespace: str,
                        component: str, *, endpoint: str = "generate_tokens",
                        host: str = "0.0.0.0", port: int = 9091,
                        interval: float = 2.0):
    """Run the aggregator + a /metrics HTTP endpoint. Returns
    (aggregator, site_runner) — call ``runner.cleanup()`` +
    ``agg.stop()`` to shut down."""
    from aiohttp import web

    agg = MetricsAggregator(drt, namespace, component, endpoint, interval)
    await agg.start()

    async def metrics_handler(_request):
        return web.Response(text=agg.render_prometheus(),
                            content_type="text/plain")

    app = web.Application()
    app.router.add_get("/metrics", metrics_handler)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    log.info("metrics aggregator on %s:%d/metrics", host, port)
    return agg, runner


def main(argv=None) -> int:
    """Standalone aggregator process (reference components/metrics
    src/main.rs)."""
    import argparse

    ap = argparse.ArgumentParser(prog="dynamo-metrics")
    ap.add_argument("--namespace", default="dynamo")
    ap.add_argument("--component", required=True)
    ap.add_argument("--endpoint", default="generate_tokens")
    ap.add_argument("--port", type=int, default=9091)
    ap.add_argument("--dcp", default=None)
    args = ap.parse_args(argv)

    async def amain():
        drt = await DistributedRuntime.attach(
            args.dcp or env_str("DYN_DCP_ADDRESS"))
        agg, runner = await serve_metrics(
            drt, args.namespace, args.component,
            endpoint=args.endpoint, port=args.port)
        try:
            await asyncio.Event().wait()
        finally:
            await agg.stop()
            await runner.cleanup()
            await drt.shutdown()

    import logging as _logging

    _logging.basicConfig(level="INFO")
    asyncio.run(amain())
    return 0


if __name__ == "__main__":
    main()
