"""Mock worker for the metrics plane (reference
components/metrics/src/bin/mock_worker.rs: publishes fake
ForwardPassMetrics stats + KVHitRateEvents so the aggregator is testable
with no engine)."""

from __future__ import annotations

import asyncio
import logging
import random
from typing import Callable, Optional, Sequence, Union

LoadProfile = Union[Callable[[int], Union["ForwardPassMetrics", dict]],
                    Sequence[Union["ForwardPassMetrics", dict]]]

from ..llm.kv_router.protocols import (KV_HIT_RATE_SUBJECT,
                                       ForwardPassMetrics)
from ..runtime.config import env_str
from ..runtime.dcp_client import pack
from ..runtime.runtime import DistributedRuntime
from ..runtime.tasks import cancel_join, spawn_tracked

log = logging.getLogger("dynamo_tpu.metrics.mock")


class MockWorker:
    """Serves a stats-only endpoint with synthetic ForwardPassMetrics and
    emits synthetic hit-rate events.

    ``profile`` scripts the load shape instead of random draws: either a
    callable ``tick -> ForwardPassMetrics | dict`` (tick counts stats
    scrapes served, starting at 0) or a sequence of snapshots cycled per
    scrape. Fleet scenarios use this for reproducible per-worker load;
    the default (``profile=None``) keeps the original seeded-random
    behavior."""

    def __init__(self, drt: DistributedRuntime, namespace: str = "dynamo",
                 component: str = "mock", endpoint: str = "generate_tokens",
                 seed: int = 0, hit_rate_interval: float = 0.5,
                 profile: Optional[LoadProfile] = None):
        self.drt = drt
        self.namespace = namespace
        self.component = component
        self.endpoint = endpoint
        self.rng = random.Random(seed)
        self.hit_rate_interval = hit_rate_interval
        self.profile = profile
        self._tick = 0
        self._handle = None
        self._task: Optional[asyncio.Task] = None

    def _stats(self) -> dict:
        tick, self._tick = self._tick, self._tick + 1
        if self.profile is not None:
            snap = (self.profile(tick) if callable(self.profile)
                    else self.profile[tick % len(self.profile)])
            return snap.to_dict() if isinstance(snap, ForwardPassMetrics) \
                else dict(snap)
        return ForwardPassMetrics(
            request_active_slots=self.rng.randint(0, 16),
            request_total_slots=16,
            kv_active_blocks=self.rng.randint(0, 512),
            kv_total_blocks=512,
            num_requests_waiting=self.rng.randint(0, 4),
            gpu_cache_usage_perc=self.rng.random(),
            gpu_prefix_cache_hit_rate=self.rng.random(),
        ).to_dict()

    async def start(self) -> None:
        async def handler(request, context):
            yield {"echo": request}

        comp = self.drt.namespace(self.namespace).component(self.component)
        await comp.create_service()
        self._handle = await comp.endpoint(self.endpoint).serve(
            handler, stats_handler=self._stats)
        self._task = spawn_tracked(self._hit_rate_loop(),
                                   name="mock-hit-rate")

    async def stop(self) -> None:
        await cancel_join(self._task)
        if self._handle:
            await self._handle.stop()

    async def _hit_rate_loop(self) -> None:
        while True:
            isl = self.rng.randint(8, 64)
            await self.drt.dcp.publish(
                f"{self.namespace}.{KV_HIT_RATE_SUBJECT}",
                pack({"worker_id": self.drt.instance_id, "isl_blocks": isl,
                      "overlap_blocks": self.rng.randint(0, isl)}))
            await asyncio.sleep(self.hit_rate_interval)


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="dynamo-mock-worker")
    ap.add_argument("--namespace", default="dynamo")
    ap.add_argument("--component", default="mock")
    ap.add_argument("--dcp", default=None)
    args = ap.parse_args(argv)

    async def amain():
        drt = await DistributedRuntime.attach(
            args.dcp or env_str("DYN_DCP_ADDRESS"))
        w = MockWorker(drt, args.namespace, args.component)
        await w.start()
        try:
            await asyncio.Event().wait()
        finally:
            await w.stop()
            await drt.shutdown()

    logging.basicConfig(level="INFO")
    asyncio.run(amain())
    return 0


if __name__ == "__main__":
    main()
