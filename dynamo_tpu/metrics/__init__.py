"""Metrics plane (reference components/metrics): namespace-wide
aggregator scraping ForwardPassMetrics + kv-hit-rate events into
Prometheus text, plus a mock worker for engine-less testing."""

from .component import MetricsAggregator, serve_metrics
from .mock_worker import MockWorker

__all__ = ["MetricsAggregator", "serve_metrics", "MockWorker"]
