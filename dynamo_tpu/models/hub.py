"""Model acquisition by id: the serving front door.

Reference: ``dynamo-run`` resolves positional model arguments against the
HuggingFace hub with a local-cache-first download
(launch/dynamo-run/src/hub.rs). Same contract here: a local directory
passes through untouched; anything else resolves through the HF cache
(offline-friendly) and only then the network. Zero-egress deployments
pre-populate the cache (or set HF_HUB_OFFLINE=1) and everything keeps
working.
"""

from __future__ import annotations

import logging
import os

log = logging.getLogger("dynamo_tpu.hub")

# weights + configs + tokenizer assets; skips .bin duplicates when
# safetensors exist (the loader is safetensors-only)
_PATTERNS = ["*.safetensors", "*.safetensors.index.json", "*.json",
             "*.model", "tokenizer*", "*.tiktoken"]


def resolve_model(model_id: str, revision: str | None = None) -> str:
    """Resolve a model id or path to a local checkpoint directory.

    Local directories are returned as-is. Hub ids resolve via
    huggingface_hub's snapshot cache: cache-only first (works with zero
    egress when the cache is pre-populated), then a network download.
    """
    if os.path.isdir(model_id):
        return model_id
    from huggingface_hub import snapshot_download

    try:
        path = snapshot_download(model_id, revision=revision,
                                 allow_patterns=_PATTERNS,
                                 local_files_only=True)
        log.info("resolved %s from local HF cache: %s", model_id, path)
        return path
    except Exception:  # noqa: BLE001 — cache miss falls through to network
        pass
    try:
        path = snapshot_download(model_id, revision=revision,
                                 allow_patterns=_PATTERNS)
        log.info("downloaded %s: %s", model_id, path)
        return path
    except Exception as exc:  # noqa: BLE001
        raise RuntimeError(
            f"cannot resolve model {model_id!r}: not a local directory, "
            f"not in the HF cache, and download failed ({exc}). Pass "
            f"--model-path, or pre-populate the HuggingFace cache on "
            f"zero-egress hosts.") from exc


def fetch_model_cli(argv) -> int:
    """``python -m dynamo_tpu fetch-model --model-id M --dest DIR``.

    The model-seeding Job body the K8s DynamoModelRequest plane runs
    (k8s/render.py render_model_request — the TPU-native analog of the
    reference's DynamoNimRequest image/model seeding,
    operator internal/controller/dynamonimrequest_controller.go):
    resolve the checkpoint (cache → network), then materialize it at a
    stable destination (the mounted PVC). Idempotent: a complete
    destination (config.json present and no partial marker) returns
    immediately, so Job retries and re-runs are free."""
    import argparse
    import json
    import shutil

    ap = argparse.ArgumentParser(prog="dynamo_tpu fetch-model")
    ap.add_argument("--model-id", required=True,
                    help="HF hub id, local dir, or anything resolve_model "
                         "accepts")
    ap.add_argument("--revision", default=None)
    ap.add_argument("--dest", required=True,
                    help="destination directory (PVC mount)")
    args = ap.parse_args(argv)

    marker = os.path.join(args.dest, ".seeding")
    stamp = os.path.join(args.dest, ".seeded.json")
    want = {"model_id": args.model_id, "revision": args.revision}
    # done = stamped with the SAME model+revision and no partial marker:
    # a changed spec.modelId recreates the Job, and that Job must
    # actually replace the checkpoint, not short-circuit on the old one
    try:
        with open(stamp) as f:
            done = json.load(f) == want and not os.path.exists(marker)
    except (FileNotFoundError, json.JSONDecodeError):
        done = False
    if done:
        log.info("model already seeded at %s", args.dest)
        print(args.dest)
        return 0
    src = resolve_model(args.model_id, revision=args.revision)
    os.makedirs(args.dest, exist_ok=True)
    open(marker, "w").close()
    if os.path.realpath(src) != os.path.realpath(args.dest):
        # a changed modelId/revision re-seeds over a destination that may
        # still hold the OLD checkpoint's shards — copytree(dirs_exist_ok)
        # alone would leave stale files (e.g. extra safetensors shards)
        # mixed into the new one. Clear everything but the in-progress
        # marker first; the stamp only lands after a complete copy, so an
        # interrupted clear+copy stays "not done" and re-runs.
        for entry in os.listdir(args.dest):
            if entry == os.path.basename(marker):
                continue
            path = os.path.join(args.dest, entry)
            if os.path.isdir(path) and not os.path.islink(path):
                shutil.rmtree(path)
            else:
                os.unlink(path)
        shutil.copytree(src, args.dest, dirs_exist_ok=True)
    with open(stamp, "w") as f:
        json.dump(want, f)
    os.unlink(marker)
    log.info("seeded %s -> %s", args.model_id, args.dest)
    print(args.dest)
    return 0
