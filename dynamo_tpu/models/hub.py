"""Model acquisition by id: the serving front door.

Reference: ``dynamo-run`` resolves positional model arguments against the
HuggingFace hub with a local-cache-first download
(launch/dynamo-run/src/hub.rs). Same contract here: a local directory
passes through untouched; anything else resolves through the HF cache
(offline-friendly) and only then the network. Zero-egress deployments
pre-populate the cache (or set HF_HUB_OFFLINE=1) and everything keeps
working.
"""

from __future__ import annotations

import logging
import os

log = logging.getLogger("dynamo_tpu.hub")

# weights + configs + tokenizer assets; skips .bin duplicates when
# safetensors exist (the loader is safetensors-only)
_PATTERNS = ["*.safetensors", "*.safetensors.index.json", "*.json",
             "*.model", "tokenizer*", "*.tiktoken"]


def resolve_model(model_id: str, revision: str | None = None) -> str:
    """Resolve a model id or path to a local checkpoint directory.

    Local directories are returned as-is. Hub ids resolve via
    huggingface_hub's snapshot cache: cache-only first (works with zero
    egress when the cache is pre-populated), then a network download.
    """
    if os.path.isdir(model_id):
        return model_id
    from huggingface_hub import snapshot_download

    try:
        path = snapshot_download(model_id, revision=revision,
                                 allow_patterns=_PATTERNS,
                                 local_files_only=True)
        log.info("resolved %s from local HF cache: %s", model_id, path)
        return path
    except Exception:  # noqa: BLE001 — cache miss falls through to network
        pass
    try:
        path = snapshot_download(model_id, revision=revision,
                                 allow_patterns=_PATTERNS)
        log.info("downloaded %s: %s", model_id, path)
        return path
    except Exception as exc:  # noqa: BLE001
        raise RuntimeError(
            f"cannot resolve model {model_id!r}: not a local directory, "
            f"not in the HF cache, and download failed ({exc}). Pass "
            f"--model-path, or pre-populate the HuggingFace cache on "
            f"zero-egress hosts.") from exc
