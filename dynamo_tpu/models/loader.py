"""Weight loading from local HF-style checkpoints (safetensors).

Maps HuggingFace Llama/Mixtral parameter names onto this framework's
stacked-layer layout (models/llama.py). HF ``nn.Linear`` stores ``[out, in]``
weights; our matmuls are ``x @ W`` so every projection is transposed once at
load time (cheaper than transposing per step).
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .config import ModelConfig


def _index(path: str) -> Dict[str, str]:
    """tensor name → shard file, from the safetensors index (or single file)."""
    idx_path = os.path.join(path, "model.safetensors.index.json")
    if os.path.exists(idx_path):
        with open(idx_path) as f:
            return json.load(f)["weight_map"]
    single = os.path.join(path, "model.safetensors")
    if os.path.exists(single):
        from safetensors import safe_open

        with safe_open(single, framework="np") as f:
            return {k: "model.safetensors" for k in f.keys()}
    raise FileNotFoundError(f"no safetensors checkpoint under {path}")


def load_params(path: str, cfg: Optional[ModelConfig] = None,
                dtype=None, quant: Optional[str] = None
                ) -> Dict[str, jax.Array]:
    """Load and restack a local HF checkpoint; returns the params pytree.

    ``quant="int8"`` quantizes the projection weights on the host
    (models/quant.py) so only int8 + scales ever reach the device."""
    from safetensors import safe_open

    cfg = cfg or ModelConfig.from_local_path(path)
    dtype = dtype or cfg.jax_dtype
    wmap = _index(path)
    handles: Dict[str, "safe_open"] = {}

    def get(name: str) -> np.ndarray:
        fname = wmap[name]
        if fname not in handles:
            handles[fname] = safe_open(os.path.join(path, fname),
                                       framework="np")
        return handles[fname].get_tensor(name)

    def linear(name: str) -> np.ndarray:
        return np.ascontiguousarray(get(name).T)  # [out,in] → [in,out]

    L = cfg.num_layers
    p: Dict[str, np.ndarray] = {
        "embed": get("model.embed_tokens.weight"),
        "ln_final": get("model.norm.weight"),
    }
    if not cfg.tie_word_embeddings:
        p["lm_head"] = linear("lm_head.weight")

    def stack(fmt: str, fn=linear) -> np.ndarray:
        return np.stack([fn(fmt.format(i)) for i in range(L)])

    p["ln_attn"] = stack("model.layers.{}.input_layernorm.weight", get)
    if cfg.sandwich_norms:
        # Gemma-2: post_attention_layernorm normalizes the ATTENTION
        # OUTPUT (before its residual add); the pre-MLP norm is
        # pre_feedforward_layernorm
        p["ln_mlp"] = stack(
            "model.layers.{}.pre_feedforward_layernorm.weight", get)
        p["ln_attn_post"] = stack(
            "model.layers.{}.post_attention_layernorm.weight", get)
        p["ln_mlp_post"] = stack(
            "model.layers.{}.post_feedforward_layernorm.weight", get)
    else:
        p["ln_mlp"] = stack(
            "model.layers.{}.post_attention_layernorm.weight", get)
    if cfg.is_mla:
        _load_mla_attention(cfg, p, stack, linear, get)
    else:
        p["wq"] = stack("model.layers.{}.self_attn.q_proj.weight")
        p["wk"] = stack("model.layers.{}.self_attn.k_proj.weight")
        p["wv"] = stack("model.layers.{}.self_attn.v_proj.weight")
        p["wo"] = stack("model.layers.{}.self_attn.o_proj.weight")
        if cfg.attn_bias:  # Qwen2-style qkv bias
            p["bq"] = stack("model.layers.{}.self_attn.q_proj.bias", get)
            p["bk"] = stack("model.layers.{}.self_attn.k_proj.bias", get)
            p["bv"] = stack("model.layers.{}.self_attn.v_proj.bias", get)
        if cfg.qk_norm:  # Qwen3 per-head q/k norms
            p["q_norm"] = stack("model.layers.{}.self_attn.q_norm.weight",
                                get)
            p["k_norm"] = stack("model.layers.{}.self_attn.k_norm.weight",
                                get)
    if cfg.num_experts > 0 and cfg.is_mla:
        _load_deepseek_moe(cfg, p, linear, get)
    elif cfg.num_experts > 0:
        E = cfg.num_experts
        # HF names the MoE block differently per family: Mixtral uses
        # block_sparse_moe with w1/w3/w2, Qwen3-MoE uses mlp with
        # gate/up/down_proj
        if cfg.model_type == "qwen3":
            moe, w1, w3, w2 = "mlp", "gate_proj", "up_proj", "down_proj"
        else:
            moe, w1, w3, w2 = "block_sparse_moe", "w1", "w3", "w2"
        p["w_router"] = stack(
            "model.layers.{}.%s.gate.weight" % moe)

        def experts(proj: str) -> np.ndarray:
            return np.stack([
                np.stack([linear(
                    f"model.layers.{i}.{moe}.experts.{e}.{proj}.weight")
                    for e in range(E)])
                for i in range(L)])

        p["w_gate"] = experts(w1)
        p["w_up"] = experts(w3)
        p["w_down"] = experts(w2)
    else:
        p["w_gate"] = stack("model.layers.{}.mlp.gate_proj.weight")
        p["w_up"] = stack("model.layers.{}.mlp.up_proj.weight")
        p["w_down"] = stack("model.layers.{}.mlp.down_proj.weight")

    if quant == "int8":
        from .quant import QuantInt8, quantize_params

        p = quantize_params(p)
        return {k: (QuantInt8(jnp.asarray(v.q), jnp.asarray(v.s))
                    if isinstance(v, QuantInt8) else jnp.asarray(v, dtype))
                for k, v in p.items()}
    if quant is not None:
        raise ValueError(f"unknown quant mode {quant!r} (expected 'int8')")
    return jax.tree.map(lambda a: jnp.asarray(a, dtype), p)


def _rope_perm(dr: int) -> np.ndarray:
    """Interleaved → split-half rope column permutation: DeepSeek
    checkpoints store rope dims as (pair0_re, pair0_im, pair1_re, ...);
    our apply_rope expects all real parts first. Applying the SAME
    permutation to the q and k rope columns leaves q·k scores exactly
    invariant (HF's apply_rotary_pos_emb_interleave is this permutation
    followed by split-half rope)."""
    return np.concatenate([np.arange(0, dr, 2), np.arange(1, dr, 2)])


def _load_deepseek_moe(cfg: ModelConfig, p: Dict[str, np.ndarray],
                       linear, get) -> None:
    """DeepSeek-V2/V3 MoE weights → models/mla.py segmented layout:
    dense first-k layers (mlp.{gate,up,down}_proj → w_*_d), then routed
    experts (mlp.experts.N.* → w_*_e [Lm, E, D, Im], router mlp.gate →
    w_router, V3 e_score_correction_bias → router_bias) plus the
    always-on shared experts (mlp.shared_experts.* → w_*_s)."""
    L, E, kd = cfg.num_layers, cfg.num_experts, cfg.first_k_dense_replace

    def seg(fmt, rng, fn=linear):
        return np.stack([fn(fmt.format(i)) for i in rng])

    if kd > 0:
        p["w_gate_d"] = seg("model.layers.{}.mlp.gate_proj.weight",
                            range(kd))
        p["w_up_d"] = seg("model.layers.{}.mlp.up_proj.weight", range(kd))
        p["w_down_d"] = seg("model.layers.{}.mlp.down_proj.weight",
                            range(kd))
    moe_rng = range(kd, L)
    p["w_router"] = seg("model.layers.{}.mlp.gate.weight", moe_rng)
    if cfg.moe_router == "deepseek_v3":
        p["router_bias"] = seg(
            "model.layers.{}.mlp.gate.e_score_correction_bias", moe_rng,
            get)

    def experts(proj):
        return np.stack([
            np.stack([linear(
                f"model.layers.{i}.mlp.experts.{e}.{proj}.weight")
                for e in range(E)])
            for i in moe_rng])

    p["w_gate_e"] = experts("gate_proj")
    p["w_up_e"] = experts("up_proj")
    p["w_down_e"] = experts("down_proj")
    if cfg.n_shared_experts > 0:
        p["w_gate_s"] = seg(
            "model.layers.{}.mlp.shared_experts.gate_proj.weight", moe_rng)
        p["w_up_s"] = seg(
            "model.layers.{}.mlp.shared_experts.up_proj.weight", moe_rng)
        p["w_down_s"] = seg(
            "model.layers.{}.mlp.shared_experts.down_proj.weight", moe_rng)


def _load_mla_attention(cfg: ModelConfig, p: Dict[str, np.ndarray],
                        stack, linear, get) -> None:
    """DeepSeek-V2/V3 MLA attention weights → models/mla.py layout:
    kv_a_proj_with_mqa → w_dkv ([D, r+dr]); kv_a_layernorm → kv_norm;
    kv_b_proj ([H*(dn+dv), r] in HF) splits into w_uk [r, H*dn] and
    w_uv [r, H*dv]; q path full-rank or LoRA (q_a/q_b + q_a_layernorm)."""
    H = cfg.num_heads
    r, dn, dv = cfg.kv_lora_rank, cfg.qk_nope_head_dim, cfg.v_head_dim
    L = cfg.num_layers

    p["w_dkv"] = stack("model.layers.{}.self_attn.kv_a_proj_with_mqa.weight")
    p["kv_norm"] = stack("model.layers.{}.self_attn.kv_a_layernorm.weight",
                         get)
    dr = cfg.qk_rope_head_dim
    if cfg.rope_interleave:
        perm = _rope_perm(dr)
        p["w_dkv"] = np.concatenate(
            [p["w_dkv"][..., :r], p["w_dkv"][..., r:][..., perm]], axis=-1)
    uk, uv = [], []
    for i in range(L):
        b = linear(f"model.layers.{i}.self_attn.kv_b_proj.weight")
        b = b.reshape(r, H, dn + dv)
        uk.append(np.ascontiguousarray(b[:, :, :dn]).reshape(r, H * dn))
        uv.append(np.ascontiguousarray(b[:, :, dn:]).reshape(r, H * dv))
    p["w_uk"] = np.stack(uk)
    p["w_uv"] = np.stack(uv)
    p["w_o"] = stack("model.layers.{}.self_attn.o_proj.weight")
    if cfg.q_lora_rank > 0:
        p["w_dq"] = stack("model.layers.{}.self_attn.q_a_proj.weight")
        p["q_norm"] = stack("model.layers.{}.self_attn.q_a_layernorm.weight",
                            get)
        p["w_uq"] = stack("model.layers.{}.self_attn.q_b_proj.weight")
        qk = "w_uq"
    else:
        p["w_q"] = stack("model.layers.{}.self_attn.q_proj.weight")
        qk = "w_q"
    if cfg.rope_interleave:
        # per-head layout [dn | dr]: permute each head's rope block
        w = p[qk]
        shp = w.shape
        w = w.reshape(*shp[:-1], H, dn + cfg.qk_rope_head_dim)
        w = np.concatenate([w[..., :dn], w[..., dn:][..., perm]], axis=-1)
        p[qk] = np.ascontiguousarray(w.reshape(shp))
