"""Model-family registry: ModelConfig → model module.

The engine resolves init_params / init_kv_cache / make_step_fns through
this table, so adding a family (reference: each engine adapter brings its
own model zoo, lib/llm/src/engines/) is one module with the shared paged
step-fn contract."""

from __future__ import annotations

from .config import ModelConfig


def get_model_module(cfg: ModelConfig):
    if cfg.is_mla:
        from . import mla

        return mla
    from . import llama

    return llama
