"""Weight-only int8 quantization (per-output-channel symmetric).

Decode is HBM-bound: at batch sizes a single chip serves, every decode
step streams the full weight set from HBM, so int8 storage halves
bytes/token (and is the only way ~8B parameters fit beside a KV pool in
a 16 GB v5e). Activations stay bf16 — the MXU matmul runs exactly as in
the bf16 path; only the weight operand is stored quantized and widened
in VMEM (XLA fuses the convert+scale into the consumer dot, so the bf16
weights are never materialized in HBM).

Scheme: for a weight ``w[..., in, out]``, ``q = round(w / s)`` in int8
with per-output-channel scales ``s[..., 1, out] = amax(|w|, in) / 127``.
``x @ w`` is computed as ``(x @ q) * s`` — exactly equal to dequantizing
first (the scale is constant along the contraction), and slightly more
accurate since int8 values are exact in bf16.

``QuantInt8`` is a registered pytree whose leaves (q, s) both carry the
stacked-layer leading axis, so ``lax.scan`` over layers, pipeline-stage
sharding (P("stage") applies to both leaves via spec-prefixing), and
jit argument passing all work unchanged. It duck-types the few array
operations the model code applies to weights (``x @ w``, ``.astype``,
``.reshape``, ``.shape``) so models/llama.py and models/mla.py need no
int8 branches.

Reference parity: the reference's flagship configs serve FP8 engines
(docs/architecture.md:57-61, examples/llm/configs/disagg_router.yaml);
int8 weight-only is the TPU-native analog (v5e has no FP8 MXU mode).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import tree_util

# Params quantized under --dtype int8: every large projection matrix.
# Excluded: embed (gather table), routers + router_bias (tiny,
# routing-precision-critical), norms and biases (1-D).
QUANT_KEYS = frozenset({
    # llama/qwen/gemma stack
    "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head",
    # MLA (DeepSeek) stack: q path, latent projections, output
    "w_q", "w_dq", "w_uq", "w_dkv", "w_uk", "w_uv", "w_o",
    # DeepSeek MoE segments: dense first-k, routed experts, shared
    "w_gate_d", "w_up_d", "w_down_d",
    "w_gate_e", "w_up_e", "w_down_e",
    "w_gate_s", "w_up_s", "w_down_s",
})


class QuantInt8:
    """int8 weight + per-output-channel scale; see module docstring."""

    __slots__ = ("q", "s")

    def __init__(self, q, s):
        self.q, self.s = q, s

    # ---- duck-typed array surface (only what model code uses on weights)

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    def dequant(self, dtype=None):
        w = self.q.astype(self.s.dtype) * self.s
        return w.astype(dtype) if dtype is not None else w

    def astype(self, dtype):
        return self.dequant(dtype)

    def reshape(self, *shape):
        return self.dequant().reshape(*shape)

    def __getitem__(self, idx):
        # leading-(layer-)axis indexing only — q and s share that axis
        # (scale reduces axis -2, never axis 0, for every quantized key)
        return QuantInt8(self.q[idx], self.s[idx])

    def __rmatmul__(self, x):
        # (x @ q) * s — exact (scale constant along the contraction).
        # jax.Array.__matmul__ defers to unrecognized right operands.
        y = x @ self.q.astype(x.dtype)
        return y * jnp.squeeze(self.s, -2).astype(x.dtype)

    def __repr__(self):
        return f"QuantInt8(shape={tuple(self.q.shape)}, s={self.s.shape})"


tree_util.register_pytree_node(
    QuantInt8,
    lambda t: ((t.q, t.s), None),
    lambda aux, children: QuantInt8(*children),
)


def quantize_int8_np(w: np.ndarray) -> QuantInt8:
    """Host-side (numpy) quantization — used at checkpoint load so bf16
    weights never hit the device."""
    w32 = np.asarray(w, np.float32)
    amax = np.max(np.abs(w32), axis=-2, keepdims=True)
    s = np.maximum(amax / 127.0, 1e-12).astype(np.float32)
    q = np.clip(np.rint(w32 / s), -127, 127).astype(np.int8)
    return QuantInt8(q, s)


def quantize_int8(w: jax.Array) -> QuantInt8:
    w32 = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=-2, keepdims=True)
    s = jnp.maximum(amax / 127.0, 1e-12)
    q = jnp.clip(jnp.rint(w32 / s), -127, 127).astype(jnp.int8)
    return QuantInt8(q, s)


def quantize_params(params: Dict, keys=QUANT_KEYS) -> Dict:
    """Quantize the standard projection weights of a loaded params tree
    (leaves already on device or host; non-listed keys untouched)."""
    out = {}
    for k, v in params.items():
        if k in keys and not isinstance(v, QuantInt8):
            out[k] = (quantize_int8_np(v) if isinstance(v, np.ndarray)
                      else quantize_int8(v))
        else:
            out[k] = v
    return out


def synthetic_int8_params(model, cfg,
                          device: Optional[jax.Device] = None) -> Dict:
    """Shape-faithful int8 params with MEANINGLESS values, built in
    milliseconds — for throughput benchmarking only.

    ``host_init_quantized`` draws a full Gaussian tree on the host; at
    8B on a single-core bench host that costs minutes of the chip
    session's budget for values the throughput measurement never looks
    at. Here: ``jax.eval_shape`` gives the exact tree without computing
    it, quantized keys get UNINITIALIZED int8 (always finite) with
    fan-in scales, norms get ones and everything else zeros (finite
    activations throughout — XLA does no value-dependent shortcuts, so
    the timing is identical to real weights)."""
    shapes = jax.eval_shape(lambda key: model.init_params(cfg, key),
                            jax.random.PRNGKey(0))
    out = {}
    for k, sd in shapes.items():
        if k in QUANT_KEYS:
            q = np.empty(sd.shape, np.int8)
            s = np.full(sd.shape[:-2] + (1,) + sd.shape[-1:],
                        1.0 / np.sqrt(sd.shape[-2]) / 127.0, np.float32)
            out[k] = QuantInt8(q, s)
        elif k.startswith(("ln_", "q_norm", "k_norm", "kv_norm")):
            out[k] = np.ones(sd.shape, np.float32)
        else:
            out[k] = np.zeros(sd.shape,
                              np.float32 if sd.dtype == jnp.float32
                              else jnp.bfloat16)
    dev = device or jax.devices()[0]
    return jax.device_put(out, dev)


def host_init_quantized(model, cfg, seed: int = 0,
                        device: Optional[jax.Device] = None) -> Dict:
    """Random-init on the host CPU backend, quantize there, then ship
    int8 to the accelerator — the bf16 tree never exists in HBM, which
    is what lets an 8B-shaped model start up on a 16 GB chip."""
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        params = model.init_params(cfg, jax.random.PRNGKey(seed))
        params = quantize_params(params)
    dev = device or jax.devices()[0]
    return jax.device_put(params, dev)
