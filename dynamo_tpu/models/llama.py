"""Llama-family model in pure-functional JAX with a paged KV cache.

This is the worker data plane the reference delegates to patched vLLM
(container/deps/vllm/*-dynamo-kv-disagg-patch.patch) — re-designed TPU-first
instead of ported:

- layers are stacked on a leading axis and driven by ``lax.scan`` (one
  layer trace → fast XLA compiles at any depth);
- the KV cache is a preallocated page pool ``[L, num_pages, kv_heads,
  page_size, head_dim]`` living in HBM; sequences own pages via page tables
  (the vLLM paged-KV idea, expressed as JAX gather/scatter so XLA can fuse
  and shard it);
- prefill and decode share ONE attention path: write the new K/V into pages
  (scatter), gather the sequence's pages, masked GQA attention — so chunked
  prefill, prefix-cache continuation, and decode are the same program at
  different query lengths;
- shardings: heads over the "model" mesh axis, batch over "data"
  (tensor-parallel decode per SURVEY §2.4), applied via NamedSharding on
  params + cache (see dynamo_tpu/parallel/mesh.py).

All shapes are static under jit; batches/chunks are bucketed and padded by
the scheduler (dynamo_tpu/engine/scheduler.py).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.paged_attention import (effective_window,
                                   paged_attention_decode,
                                   paged_attention_decode_sharded,
                                   paged_attention_prefill,
                                   paged_attention_prefill_sharded)
from ..runtime.config import env_flag, env_int
from .config import ModelConfig

Params = Dict[str, jax.Array]

# scatter sentinel for padded rows: guaranteed out-of-range so mode="drop"
# discards the write (negative indices would WRAP per numpy semantics)
DROP_SLOT = 1 << 30


# ---------------------------------------------------------------- KV cache


@dataclass
class KVCacheSpec:
    num_pages: int
    page_size: int

    def shape(self, cfg: ModelConfig) -> Tuple[int, ...]:
        # kv-head-major page layout [L, pages, KV, ps, hd]: the Pallas decode
        # kernel then consumes pages with NO in-kernel transpose (batched
        # MXU dots over the leading KV axis) and (ps, hd) is lane-aligned.
        # The reference models this as KvLayout::{KvFirst,BlockFirst}
        # (lib/llm/src/kv/layer.rs:100-106) — layout chosen for the device.
        return (cfg.num_layers, self.num_pages, cfg.num_kv_heads,
                self.page_size, cfg.head_dim_)


def init_kv_cache(cfg: ModelConfig, spec: KVCacheSpec,
                  dtype=None) -> Tuple[jax.Array, jax.Array]:
    shape = spec.shape(cfg)
    dtype = dtype or cfg.jax_dtype
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


# ------------------------------------------------------------------ params


def init_params(cfg: ModelConfig, key: jax.Array, dtype=None) -> Params:
    """Random-init params (stacked layers on axis 0)."""
    dtype = dtype or cfg.jax_dtype
    D, I, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    H, KV, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim_
    V = cfg.vocab_size
    ks = jax.random.split(key, 10)

    def norm_init(k, *shape):
        return jnp.ones(shape, dtype)

    def w_init(k, *shape):
        scale = 1.0 / math.sqrt(shape[-2]) if len(shape) > 1 else 0.02
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

    p: Params = {
        "embed": w_init(ks[0], V, D),
        "wq": w_init(ks[1], L, D, H * hd),
        "wk": w_init(ks[2], L, D, KV * hd),
        "wv": w_init(ks[3], L, D, KV * hd),
        "wo": w_init(ks[4], L, H * hd, D),
        "w_gate": w_init(ks[5], L, D, I),
        "w_up": w_init(ks[6], L, D, I),
        "w_down": w_init(ks[7], L, I, D),
        "ln_attn": norm_init(ks[8], L, D),
        "ln_mlp": norm_init(ks[8], L, D),
        "ln_final": norm_init(ks[8], D),
    }
    if cfg.attn_bias:  # Qwen2-style q/k/v projection bias
        p["bq"] = jnp.zeros((L, H * hd), dtype)
        p["bk"] = jnp.zeros((L, KV * hd), dtype)
        p["bv"] = jnp.zeros((L, KV * hd), dtype)
    if cfg.sandwich_norms:  # Gemma-2 post-attention/feedforward norms
        p["ln_attn_post"] = norm_init(ks[8], L, D)
        p["ln_mlp_post"] = norm_init(ks[8], L, D)
    if cfg.qk_norm:  # Qwen3 per-head q/k norms
        p["q_norm"] = norm_init(ks[8], L, hd)
        p["k_norm"] = norm_init(ks[8], L, hd)
    if not cfg.tie_word_embeddings:
        p["lm_head"] = w_init(ks[9], D, V)
    if cfg.num_experts > 0:
        E = cfg.num_experts
        p["w_router"] = w_init(ks[5], L, D, E)
        p["w_gate"] = w_init(ks[5], L, E, D, I)
        p["w_up"] = w_init(ks[6], L, E, D, I)
        p["w_down"] = w_init(ks[7], L, E, I, D)
    return p


# -------------------------------------------------------------- primitives


def rms_norm(x: jax.Array, w: jax.Array, eps: float,
             unit_offset: bool = False) -> jax.Array:
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    normed = x32 * lax.rsqrt(var + eps)
    if unit_offset:
        # Gemma: w is a delta around 1, applied in float32 before the
        # cast (matches HF GemmaRMSNorm exactly)
        return (normed * (1.0 + w.astype(jnp.float32))).astype(x.dtype)
    # Llama: cast first, then scale (matches HF LlamaRMSNorm)
    return normed.astype(x.dtype) * w


def embed_tokens(params: Params, cfg: ModelConfig,
                 tokens: jax.Array) -> jax.Array:
    """Token embedding lookup; Gemma scales by sqrt(hidden)."""
    h = params["embed"][tokens]
    if cfg.embed_scale:
        h = h * jnp.asarray(math.sqrt(cfg.hidden_size), h.dtype)
    return h


def project_logits(params: Params, cfg: ModelConfig,
                   h: jax.Array) -> jax.Array:
    """LM head (tied to the embedding when absent) + the optional
    Gemma-2-style final-logit softcap — the single logit-path exit used
    by every forward variant."""
    head = params.get("lm_head")
    if head is None:
        head = params["embed"].T
    logits = (h @ head).astype(jnp.float32)
    if cfg.final_logit_softcap:
        c = cfg.final_logit_softcap
        logits = c * jnp.tanh(logits / c)
    return logits


def _act(cfg: ModelConfig):
    if cfg.hidden_act == "gelu_tanh":
        return lambda x: jax.nn.gelu(x, approximate=True)
    return jax.nn.silu


def rope_freqs(cfg: ModelConfig, dim: Optional[int] = None) -> jax.Array:
    hd = dim or cfg.head_dim_
    inv = 1.0 / (cfg.rope_theta ** (jnp.arange(0, hd, 2, jnp.float32) / hd))
    scaling = cfg.rope_scaling or {}
    if scaling.get("rope_type") == "llama3" or scaling.get("type") == "llama3":
        # Llama-3.1-style NTK-by-parts frequency rescaling: low frequencies
        # are divided by `factor`, high frequencies kept, mid smoothly mixed
        factor = scaling.get("factor", 8.0)
        low = scaling.get("low_freq_factor", 1.0)
        high = scaling.get("high_freq_factor", 4.0)
        orig = scaling.get("original_max_position_embeddings", 8192)
        wavelen = 2 * jnp.pi / inv
        smooth = jnp.clip((orig / wavelen - low) / (high - low), 0.0, 1.0)
        inv = jnp.where(wavelen > orig / low, inv / factor,
                        jnp.where(wavelen < orig / high, inv,
                                  (1 - smooth) * inv / factor + smooth * inv))
    return inv


def apply_rope(x: jax.Array, positions: jax.Array,
               inv_freq: jax.Array) -> jax.Array:
    """x: [..., T, heads, head_dim]; positions: [..., T]."""
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [...,T,hd/2]
    cos = jnp.cos(angles)[..., None, :]  # [..., T, 1, hd/2]
    sin = jnp.sin(angles)[..., None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _scatter_pages_paged(cache_layer: jax.Array, new: jax.Array,
                         page_slots: jax.Array) -> jax.Array:
    """Page-granular prefill commit: write WHOLE pages instead of
    scattering T individual rows (the row scatter costs ~110 ms per
    8x1024 prefill dispatch on v5e; this path is a reshape + block
    write). Requires chunk starts page-aligned (the engine guarantees it:
    prefix-cache hits are whole pages and chunk sizes are multiples of
    the page size). The tail page may carry junk K/V beyond the chunk —
    safe, because a position's K/V is always written before any query
    attends to it (causal masks exclude unwritten positions, and decode
    overwrites its slot before reading it).

    cache_layer: [num_pages, KV, ps, hd]; new: [B, T, KV, hd] (T % ps
    == 0); page_slots: [B, T // ps] destination page ids (>= num_pages →
    dropped padding).
    """
    np_, kv, ps, hd = cache_layer.shape
    B, T = new.shape[:2]
    blocks = new.reshape(B, T // ps, ps, kv, hd).transpose(0, 1, 3, 2, 4)
    blocks = blocks.reshape(B * (T // ps), kv, ps, hd)
    idx = page_slots.reshape(-1)
    return cache_layer.at[idx].set(blocks.astype(cache_layer.dtype),
                                   mode="drop")


def _scatter_pages(cache_layer: jax.Array, new: jax.Array,
                   flat_slots: jax.Array) -> jax.Array:
    """Write new K/V rows into the page pool.

    cache_layer: [num_pages, KV, page_size, hd]; new: [B, T, KV, hd];
    flat_slots: [B, T] flattened (page*page_size + slot) indices; indices
    >= num_pages*page_size (use DROP_SLOT) are dropped (negative indices
    would wrap, so padding must use the out-of-range sentinel).
    (TPU-native replacement for the reference's block_copy.cu CUDA kernel —
    an XLA scatter the compiler lays out on the VPU.)
    """
    np_, kv, ps, hd = cache_layer.shape
    idx = flat_slots.reshape(-1)
    pages = idx // ps   # DROP_SLOT → page >= num_pages → dropped
    offs = idx % ps
    rows = new.reshape(-1, kv, hd).astype(cache_layer.dtype)
    # advanced indices (pages, offs) separated by the KV slice put the
    # scatter axis first: target shape [B*T, KV, hd]
    return cache_layer.at[pages, :, offs].set(rows, mode="drop")


def _use_pallas() -> bool:
    """Route decode attention through the Pallas kernel on TPU backends
    (DYN_DISABLE_PALLAS=1 forces the XLA gather path everywhere)."""
    if env_flag("DYN_DISABLE_PALLAS"):
        return False
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:
        return False


def _softcap_mask(scores: jax.Array, visible: jax.Array,
                  softcap: Optional[float]) -> jax.Array:
    """Gemma-2 attention-score postprocess: tanh softcap (BEFORE masking —
    -1e30 through tanh would collapse to -softcap and unmask), then the
    visibility mask. ``visible`` broadcasts against ``scores``."""
    if softcap:
        scores = softcap * jnp.tanh(scores / softcap)
    return jnp.where(visible, scores, -1e30)


def _visible(kv_pos: jax.Array, q_pos: jax.Array,
             window: Optional[int], is_sliding) -> jax.Array:
    """Causal visibility of kv position j to query position t, with the
    optional Gemma-2 sliding window: on sliding layers only the last
    ``window`` positions (j > t - window) are visible. ``is_sliding`` is
    a traced bool scalar (layer parity under lax.scan)."""
    vis = kv_pos <= q_pos
    if window is not None:
        in_win = kv_pos > q_pos - window
        vis = jnp.logical_and(vis, jnp.logical_or(
            jnp.logical_not(is_sliding), in_win))
    return vis


def _attention(q: jax.Array, k_pages: jax.Array, v_pages: jax.Array,
               page_table: jax.Array, q_positions: jax.Array,
               scale: float, allow_pallas: bool = True,
               mesh=None, softcap: Optional[float] = None,
               window: Optional[int] = None,
               is_sliding=False) -> jax.Array:
    """Dispatch: decode (T==1) on TPU → Pallas flash kernel over pages;
    otherwise the XLA gather path. With a >1-device ``mesh`` the kernel
    runs per model-shard via shard_map (heads follow their kv heads —
    ops/paged_attention.py *_sharded wrappers), so TP no longer forces
    the XLA gather for prefill or K=1 decode (VERDICT r3 weak #3).
    ``allow_pallas=False`` still forces the XLA path outright."""
    # CPU test hook: DYN_PALLAS_INTERPRET drives the kernel-in-engine
    # path in interpret mode — but NEVER on a real TPU backend (a
    # lingering env var must not silently interpret-mode a hardware
    # bench), and never past the DYN_DISABLE_PALLAS kill switch
    interp = (env_flag("DYN_PALLAS_INTERPRET")
              and not env_flag("DYN_DISABLE_PALLAS")
              and not _use_pallas())
    B, T, H, hd = q.shape
    KV = k_pages.shape[1]
    sharded = mesh is not None and mesh.size > 1
    pallas_ok = allow_pallas and (_use_pallas() or interp)
    if sharded:
        # shard_map needs whole GQA groups and whole batch rows per shard;
        # shapes are static at trace time so this is a compile-time choice
        tp = mesh.shape.get("model", 1)
        dp = mesh.shape.get("data", 1)
        pallas_ok = pallas_ok and KV % tp == 0 and B % dp == 0
    # Gemma-2 knobs for the kernels: per-row effective window (huge on
    # global layers — is_sliding is traced layer parity) and the static
    # score softcap
    eff = None
    if window is not None:
        eff = effective_window(window, is_sliding, B)
    if T == 1 and pallas_ok:
        lengths = q_positions[:, 0] + 1  # padding rows: -1 → 0 → zeros out
        lower = None
        if eff is not None:
            # first visible position; clamped so at least one position of
            # a live row stays in view (the index map indexes pt[lo//ps])
            lower = jnp.clip(lengths - eff, 0, jnp.maximum(lengths - 1, 0))
        if sharded:
            out = paged_attention_decode_sharded(
                q[:, 0], k_pages[None], v_pages[None], 0, page_table,
                lengths, mesh=mesh, scale=scale, interpret=interp,
                return_stats=False, softcap=softcap, lower=lower)
            return out[:, None]
        if _use_pallas():  # unsharded K=1: hardware kernel only (no
            return paged_attention_decode(  # interpret hook needed here)
                q[:, 0], k_pages, v_pages, page_table,
                lengths, scale=scale, softcap=softcap,
                lower=lower)[:, None]
    if (T > 1 and pallas_ok and env_flag("DYN_PREFILL_PALLAS")):
        # opt-in flash prefill (any non-empty value, like the sibling
        # DYN_DISABLE_PALLAS flag): pages stream through VMEM instead of
        # the XLA path's dense [B, P*ps, KV, hd] gather per layer
        if sharded:
            return paged_attention_prefill_sharded(
                q, k_pages, v_pages, page_table, q_positions, mesh=mesh,
                scale=scale, interpret=interp, softcap=softcap,
                eff_win=eff)
        return paged_attention_prefill(q, k_pages, v_pages, page_table,
                                       q_positions, scale=scale,
                                       interpret=interp, softcap=softcap,
                                       eff_win=eff)
    return _paged_attention(q, k_pages, v_pages, page_table, q_positions,
                            scale, softcap=softcap, window=window,
                            is_sliding=is_sliding)


def _paged_attention(q: jax.Array, k_pages: jax.Array, v_pages: jax.Array,
                     page_table: jax.Array, q_positions: jax.Array,
                     scale: float, softcap: Optional[float] = None,
                     window: Optional[int] = None,
                     is_sliding=False) -> jax.Array:
    """Gather-based paged GQA attention (XLA path; the Pallas kernel in
    dynamo_tpu/ops/paged_attention.py replaces this on TPU hot paths).

    q: [B, T, H, hd]; k_pages/v_pages: [num_pages, KV, ps, hd];
    page_table: [B, P]; q_positions: [B, T] (absolute, -1 for padding).
    Attends to logical positions j <= q_position (causal over the whole
    cached sequence, which includes the just-written chunk).
    """
    B, T, H, hd = q.shape
    _, KV, ps, _ = k_pages.shape
    P = page_table.shape[1]
    S = P * ps
    group = H // KV

    k = k_pages[page_table]  # [B, P, KV, ps, hd]
    v = v_pages[page_table]
    k = k.transpose(0, 1, 3, 2, 4).reshape(B, S, KV, hd)
    v = v.transpose(0, 1, 3, 2, 4).reshape(B, S, KV, hd)

    qg = q.reshape(B, T, KV, group, hd)
    # native-dtype operands + f32 accumulation: upcasting q/k to f32
    # BEFORE the matmul forces the MXU onto its f32 path (~8x slower than
    # bf16 x bf16 -> f32); preferred_element_type keeps the accumulator
    # exact. CPU test configs run f32 models, so this is identical there.
    scores = jnp.einsum("btkgh,bskh->bkgts", qg, k,
                        preferred_element_type=jnp.float32) * scale
    # mask [B, T, S]: slot j (logical position) visible iff j <= query pos
    # (and within the sliding window on Gemma-2 sliding layers)
    mask = _visible(jnp.arange(S)[None, None, :], q_positions[:, :, None],
                    window, is_sliding)
    scores = _softcap_mask(scores, mask[:, None, None, :, :], softcap)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgts,bskh->btkgh", probs.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.reshape(B, T, H, hd).astype(q.dtype)


# ------------------------------------------------------------ forward pass


def _mlp(h: jax.Array, w_gate, w_up, w_down, act=jax.nn.silu) -> jax.Array:
    return (act(h @ w_gate) * (h @ w_up)) @ w_down


def _layer_keys(cfg: ModelConfig) -> list:
    """Per-layer param names scanned over the stacked-layer axis — the
    single source for every forward variant (paged, fused window, full)."""
    keys = ["wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
            "ln_attn", "ln_mlp"]
    if cfg.num_experts > 0:
        keys.append("w_router")
    if cfg.attn_bias:
        keys += ["bq", "bk", "bv"]
    if cfg.sandwich_norms:
        keys += ["ln_attn_post", "ln_mlp_post"]
    if cfg.qk_norm:
        keys += ["q_norm", "k_norm"]
    return keys


def _residual_add(h: jax.Array, out: jax.Array, lp, post_key: str,
                  cfg: ModelConfig) -> jax.Array:
    """Residual add, with the Gemma-2 sandwich norm on the branch output
    (post_attention_layernorm / post_feedforward_layernorm) when the
    config uses them."""
    if cfg.sandwich_norms:
        out = rms_norm(out, lp[post_key], cfg.rms_norm_eps,
                       cfg.norm_unit_offset)
    return h + out


def _qk_headnorm(q, k, lp, cfg: ModelConfig):
    """Qwen3 per-head RMSNorm on q/k before RoPE: weights [hd] broadcast
    over [..., H|KV, hd]. No-op unless cfg.qk_norm."""
    if not cfg.qk_norm:
        return q, k
    return (rms_norm(q, lp["q_norm"], cfg.rms_norm_eps),
            rms_norm(k, lp["k_norm"], cfg.rms_norm_eps))


def _sliding_flag(cfg: ModelConfig, l_idx):
    """Traced per-layer sliding-window flag: Gemma-2 applies the window on
    even-indexed layers only (HF Gemma2DecoderLayer
    ``is_sliding = not bool(layer_idx % 2)``)."""
    if cfg.sliding_window is None:
        return False
    return (l_idx % 2) == 0


def _dyn_expert(w, e):
    """One expert's weight from the stacked [E, ...] tensor by traced
    index — dequantizing after the slice when quantized, so the scan body
    only reads the ACTIVE expert's int8 bytes from HBM."""
    from .quant import QuantInt8

    if isinstance(w, QuantInt8):
        return QuantInt8(lax.dynamic_index_in_dim(w.q, e, 0, False),
                         lax.dynamic_index_in_dim(w.s, e, 0, False)
                         ).dequant(jnp.float32)
    return lax.dynamic_index_in_dim(w, e, 0, False).astype(jnp.float32)


def moe_experts_blocked(x: jax.Array, weights: jax.Array, idx: jax.Array,
                        w_gate, w_up, w_down, block: int = 256,
                        act=jax.nn.silu) -> jax.Array:
    """Sparse top-k expert dispatch with static shapes and NO token drops.

    x: [N, D] (f32) flattened tokens; weights/idx: [N, k] routing output.
    Sort the N*k (token, expert) pairs by expert, pad each expert's group
    to a multiple of ``block``, and scan fixed-size blocks — each block
    belongs to ONE expert, fetched by traced index (a dynamic-slice, so
    HBM only streams the active experts' weights). Cost ≈ (k/E +
    padding) of the dense-over-experts einsum; exact same math (the
    per-expert MLP is linear in which rows are present — padded rows are
    zero and are never scattered back).

    TPU-first shape rationale: argsort/cumsum/gather are bandwidth-bound
    O(N·k·D); each scanned block is a [block, D]×[D, I] MXU matmul.
    Reference analog: vLLM's fused_moe dispatch (the reference serves
    Mixtral through vLLM); this is the XLA-native equivalent.
    """
    N, D = x.shape
    k = idx.shape[-1]
    E = w_gate.shape[0]
    NK = N * k
    nb = (NK + block - 1) // block + E  # static worst-case block count

    pair_e = idx.reshape(-1)                          # [NK]
    pair_t = jnp.repeat(jnp.arange(N, dtype=jnp.int32), k)
    pair_w = weights.reshape(-1).astype(jnp.float32)
    order = jnp.argsort(pair_e, stable=True)
    se, st, sw = pair_e[order], pair_t[order], pair_w[order]

    counts = jnp.sum(jax.nn.one_hot(pair_e, E, dtype=jnp.int32), axis=0)
    start = jnp.cumsum(counts) - counts               # exclusive, [E]
    padded = ((counts + block - 1) // block) * block
    pend = jnp.cumsum(padded)                         # padded group ends
    pstart = pend - padded
    pos = jnp.arange(NK, dtype=jnp.int32) - start[se]
    dest = pstart[se] + pos                           # [NK], < nb*block

    buf = jnp.zeros((nb * block, D), jnp.float32).at[dest].set(x[st])
    # block j covers rows [j*block, (j+1)*block) of exactly one padded
    # group; slack blocks past the last group stay all-zero (clamped
    # expert index — their output is discarded by the scatter-back)
    bstart = jnp.arange(nb, dtype=jnp.int32) * block
    block_e = jnp.minimum(
        jnp.sum(bstart[:, None] >= pend[None, :], axis=1), E - 1)

    def body(_, inp):
        xb, be = inp
        wg, wu, wd = (_dyn_expert(w, be) for w in (w_gate, w_up, w_down))
        return None, (act(xb @ wg) * (xb @ wu)) @ wd

    _, yb = lax.scan(body, None, (buf.reshape(nb, block, D), block_e))
    contrib = yb.reshape(nb * block, D)[dest] * sw[:, None]
    return jnp.zeros((N, D), jnp.float32).at[st].add(contrib)


# scanned block height for the sorted dispatch (MXU-friendly; also the
# per-expert padding quantum, so it enters the cost model below)
_MOE_BLOCK = env_int("DYN_MOE_BLOCK")


def _moe_use_blocked(mesh, n_tokens: int, n_experts: int,
                     top_k: int, block: int) -> bool:
    """Blocked dispatch only where its cost model actually wins, and
    only on UNSHARDED execution.

    Cost in row-MLPs: blocked pays worst-case ``N*k + E*block`` (every
    pair once, plus up to one padded block per expert — slack blocks are
    scanned too); dense-over-experts pays ``N*E``. Require blocked to be
    at least 2x cheaper so the argsort/one-hot/scatter overhead can't
    eat the margin — a flat token threshold would mis-fire near the
    boundary (e.g. Mixtral E=8, k=2 at N=256: blocked is ~1.25x DENSE).

    Under any >1-device mesh the tokens/experts are GSPMD-sharded and
    the sort/scatter would turn into cross-device gathers — there the
    dense einsum (whose E axis shards cleanly over the "expert" mesh
    axis) stays the right program."""
    return (n_experts > 1
            and n_tokens * top_k + n_experts * block
            <= (n_tokens * n_experts) // 2
            and (mesh is None or mesh.size == 1))


def _moe_mlp(h: jax.Array, w_router, w_gate, w_up, w_down,
             top_k: int, mesh=None) -> jax.Array:
    """Mixtral-style MoE MLP: token-choice top-k routing.

    Two execution strategies, chosen at trace time (shapes are static
    under jit):
    - ``moe_experts_blocked`` sorted dispatch — ~top_k/E of the dense
      FLOPs; default for big dispatches on an unsharded expert axis.
    - dense einsum over ALL experts weighted by the routing mask —
      decode-sized dispatches (sort overhead dominates) and
      expert-parallel meshes (GSPMD shards the E axis of the einsum;
      the blocked scan's dynamic expert indexing would all-gather).
    """
    B, T, D = h.shape
    E = w_gate.shape[0]
    logits = (h @ w_router).astype(jnp.float32)  # [B, T, E]
    weights, idx = lax.top_k(logits, top_k)  # [B, T, k]
    weights = jax.nn.softmax(weights, axis=-1)
    if _moe_use_blocked(mesh, B * T, E, top_k, _MOE_BLOCK):
        out = moe_experts_blocked(
            h.reshape(B * T, D).astype(jnp.float32),
            weights.reshape(B * T, top_k), idx.reshape(B * T, top_k),
            w_gate, w_up, w_down, block=_MOE_BLOCK)
        return out.reshape(B, T, D).astype(h.dtype)
    full_gate = jnp.sum(
        jax.nn.one_hot(idx, E, dtype=jnp.float32) * weights[..., None], axis=2)
    # dense-over-experts: out = sum_e gate[...,e] * mlp_e(h)
    ge = jnp.einsum("btd,edi->btei", h.astype(jnp.float32),
                    w_gate.astype(jnp.float32))
    up = jnp.einsum("btd,edi->btei", h.astype(jnp.float32),
                    w_up.astype(jnp.float32))
    act = jax.nn.silu(ge) * up
    down = jnp.einsum("btei,eid->bted", act, w_down.astype(jnp.float32))
    out = jnp.einsum("bted,bte->btd", down, full_gate)
    return out.astype(h.dtype)


def forward(params: Params, cfg: ModelConfig, tokens: jax.Array,
            positions: jax.Array, kv_k: jax.Array, kv_v: jax.Array,
            page_table: jax.Array, flat_slots: jax.Array,
            allow_pallas: bool = True, page_slots: Optional[jax.Array] = None,
            mesh=None) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Shared prefill/decode forward.

    tokens: [B, T] (T=1 for decode); positions: [B, T] absolute positions
    (-1 for padding rows); page_table: [B, P]; flat_slots: [B, T] cache
    write slots (page*page_size + offset, -1 to drop padding);
    page_slots: optional [B, T // ps] page-granular write path for
    aligned prefill chunks (see _scatter_pages_paged).

    Returns (hidden [B, T, D], new_kv_k, new_kv_v).
    """
    inv_freq = rope_freqs(cfg)
    scale = cfg.attn_scale
    H, KV, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim_
    B, T = tokens.shape

    h = embed_tokens(params, cfg, tokens)  # [B, T, D]
    act = _act(cfg)
    safe_pos = jnp.maximum(positions, 0)

    layer_params = {k: params[k] for k in _layer_keys(cfg)}

    def layer(h, xs):
        lp, l_idx, k_layer, v_layer = xs
        x = rms_norm(h, lp["ln_attn"], cfg.rms_norm_eps, cfg.norm_unit_offset)
        xq, xk, xv = x @ lp["wq"], x @ lp["wk"], x @ lp["wv"]
        if cfg.attn_bias:
            xq, xk, xv = xq + lp["bq"], xk + lp["bk"], xv + lp["bv"]
        q = xq.reshape(B, T, H, hd)
        k = xk.reshape(B, T, KV, hd)
        v = xv.reshape(B, T, KV, hd)
        q, k = _qk_headnorm(q, k, lp, cfg)
        q = apply_rope(q, safe_pos, inv_freq)
        k = apply_rope(k, safe_pos, inv_freq)
        if page_slots is not None:
            k_layer = _scatter_pages_paged(k_layer, k, page_slots)
            v_layer = _scatter_pages_paged(v_layer, v, page_slots)
        else:
            k_layer = _scatter_pages(k_layer, k, flat_slots)
            v_layer = _scatter_pages(v_layer, v, flat_slots)
        attn = _attention(q, k_layer, v_layer, page_table, positions, scale,
                          allow_pallas=allow_pallas, mesh=mesh,
                          softcap=cfg.attn_logit_softcap,
                          window=cfg.sliding_window,
                          is_sliding=_sliding_flag(cfg, l_idx))
        h = _residual_add(h, attn.reshape(B, T, H * hd) @ lp["wo"], lp,
                          "ln_attn_post", cfg)
        x = rms_norm(h, lp["ln_mlp"], cfg.rms_norm_eps, cfg.norm_unit_offset)
        if cfg.num_experts > 0:
            mlp_out = _moe_mlp(x, lp["w_router"], lp["w_gate"], lp["w_up"],
                               lp["w_down"], cfg.num_experts_per_tok,
                               mesh=mesh)
        else:
            mlp_out = _mlp(x, lp["w_gate"], lp["w_up"], lp["w_down"], act)
        h = _residual_add(h, mlp_out, lp, "ln_mlp_post", cfg)
        return h, (k_layer, v_layer)

    h, (new_k, new_v) = lax.scan(
        layer, h, (layer_params, jnp.arange(cfg.num_layers), kv_k, kv_v))
    h = rms_norm(h, params["ln_final"], cfg.rms_norm_eps, cfg.norm_unit_offset)
    return h, new_k, new_v


def logits_at(params: Params, cfg: ModelConfig, hidden: jax.Array,
              gather_idx: jax.Array) -> jax.Array:
    """LM head at selected positions. hidden: [B, T, D];
    gather_idx: [B] position per row → logits [B, V] (float32)."""
    B = hidden.shape[0]
    h_last = hidden[jnp.arange(B), gather_idx]  # [B, D]
    return project_logits(params, cfg, h_last)


# ----------------------------------------------------- jitted entry points


def make_step_fns(cfg: ModelConfig, allow_pallas: bool = True, mesh=None):
    """Build the jitted (prefill_step, decode_step) pair for one config.

    Closures instead of static args because ModelConfig holds dicts
    (rope_scaling). KV buffers are donated so XLA updates pages in place.
    With a >1-device ``mesh`` the Pallas attention kernels run per
    model-shard via shard_map (see _attention); ``allow_pallas=False``
    forces the XLA gather path everywhere.
    """

    @partial(jax.jit, donate_argnames=("kv_k", "kv_v"))
    def prefill_step(params: Params, tokens: jax.Array, positions: jax.Array,
                     kv_k: jax.Array, kv_v: jax.Array, page_table: jax.Array,
                     flat_slots: jax.Array, last_idx: jax.Array,
                     page_slots: Optional[jax.Array] = None):
        """Process prompt chunks [B, T]; returns (logits [B, V], kv_k, kv_v)."""
        h, kv_k2, kv_v2 = forward(params, cfg, tokens, positions, kv_k, kv_v,
                                  page_table, flat_slots,
                                  allow_pallas=allow_pallas,
                                  page_slots=page_slots, mesh=mesh)
        return logits_at(params, cfg, h, last_idx), kv_k2, kv_v2

    @partial(jax.jit, donate_argnames=("kv_k", "kv_v"))
    def decode_step(params: Params, tokens: jax.Array, positions: jax.Array,
                    kv_k: jax.Array, kv_v: jax.Array, page_table: jax.Array,
                    flat_slots: jax.Array):
        """One decode step: tokens [B], positions [B] →
        (logits [B, V], kv_k, kv_v)."""
        h, kv_k2, kv_v2 = forward(params, cfg, tokens[:, None],
                                  positions[:, None], kv_k, kv_v,
                                  page_table, flat_slots[:, None], mesh=mesh,
                                  allow_pallas=allow_pallas)
        return (logits_at(params, cfg, h,
                          jnp.zeros(tokens.shape[0], jnp.int32)),
                kv_k2, kv_v2)

    return prefill_step, decode_step


def make_verify_fn(cfg: ModelConfig, allow_pallas: bool = True, mesh=None):
    """Speculative-verify forward: ONE [B, K+1] multi-token decode step
    against the paged pool, returning logits at EVERY position (unlike
    prefill_step's last-position gather — the accept mask needs the
    greedy target after each draft token).

    Reuses the chunked-prefill program shape exactly: the K+1 input
    tokens' K/V scatter into their page slots before attention, and the
    causal position mask lets draft token j attend to drafts 0..j-1 plus
    the whole cached sequence. K is static (one compile per batch/page
    bucket), so the verify grid stays as bounded as the decode grid.

    Rejected drafts leave their K/V in slots PAST the row's accepted
    extent — harmless by the same invariant that protects prefill tail
    pages: a position's K/V is always rewritten when its real token is
    the decode input, before any query can see it (causal masking hides
    positions beyond the current query, and pages only publish to the
    prefix cache once every slot holds accepted content)."""

    @partial(jax.jit, donate_argnames=("kv_k", "kv_v"))
    def verify_step(params: Params, tokens: jax.Array, positions: jax.Array,
                    kv_k: jax.Array, kv_v: jax.Array, page_table: jax.Array,
                    flat_slots: jax.Array):
        """tokens/positions/flat_slots: [B, K+1] (-1 / DROP_SLOT padding)
        → (logits [B, K+1, V] float32, kv_k, kv_v)."""
        h, kv_k2, kv_v2 = forward(params, cfg, tokens, positions, kv_k,
                                  kv_v, page_table, flat_slots,
                                  allow_pallas=allow_pallas, mesh=mesh)
        return project_logits(params, cfg, h), kv_k2, kv_v2

    return verify_step


# ------------------------------------------------- fused decode window


def carry_active(done: jax.Array, pos: jax.Array) -> jax.Array:
    """Rows still generating: not stopped, not padding (pos < 0)."""
    return jnp.logical_and(jnp.logical_not(done), pos >= 0)


def carry_step_update(nxt, tok, pos, done, steps, remaining, eos_table):
    """Shared on-device sequence-carry update for one fused decode step:
    freeze rows that sample a stop token or exhaust their budget. Both
    fused-window implementations (llama window form and the engine's
    generic full-forward fallback) MUST use this — the host bookkeeping in
    _process_window assumes identical stop semantics on every path."""
    active = carry_active(done, pos)
    hit_stop = jnp.any(nxt[:, None] == eos_table, axis=1)
    remaining = jnp.where(active, remaining - 1, remaining)
    tok = jnp.where(active, nxt, tok)
    pos = jnp.where(active, pos + 1, pos)
    steps = jnp.where(active, steps + 1, steps)
    done = jnp.logical_or(
        done, jnp.logical_and(active, jnp.logical_or(
            hit_stop, remaining <= 0)))
    return tok, pos, done, steps, remaining


def make_decode_window_fn(cfg: ModelConfig, allow_pallas: bool = True,
                          max_top_k: int = 64, mesh=None,
                          pallas_interpret: bool = False):
    """Fused K-step decode with a READ-ONLY pool and a fully on-device
    sequence carry. The pool is gathered but never written inside the
    window; the K new tokens' K/V accumulate in a small per-layer window
    buffer that attention reads alongside the pool, and ONE scatter at the
    end commits the window into the pool. This keeps peak HBM at ~one pool
    copy — an unrolled chain of full forward() steps makes XLA hold
    several pool instances (each step's scatter output is a new buffer)
    and OOMs large pools.

    The carry (tok, pos, done, steps, remaining) lives on device so the
    engine can dispatch window N+1 *before* reading back window N's tokens
    (async pipelining — the host never sits on the critical path between
    windows). Stop conditions run on device: a row freezes (no position
    advance, no KV writes) as soon as it samples an EOS/stop token or
    exhausts its token budget, so K can grow without dead compute past the
    stop and without stray writes into released pages. The reference keeps
    streaming off the sync path with its TCP response plane
    (lib/runtime/src/pipeline/network/tcp/server.rs); here the analogous
    move is keeping the sampling feedback loop on device.

    Signature matches engine._make_decode_multi's generic fallback."""
    from ..engine.sampling import (logprob_aux, sample_tokens,
                                   update_penalty_state)

    inv_freq = rope_freqs(cfg)
    scale = cfg.attn_scale
    H, KV, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim_
    # pool attention: Pallas flash kernel on TPU (streams only each row's
    # live pages HBM→VMEM, returns online-softmax stats merged with the
    # in-flight window buffer) — the XLA gather fallback re-materializes
    # the gathered pool EVERY unrolled step (the gather fuses into its
    # per-step consumer instead of hoisting), ~4.3 GB of HBM traffic per
    # step at B=32/P=32: measured 54 ms/step vs ~2 ms for the kernel.
    # Under a mesh the kernel runs per model-shard via shard_map (heads
    # follow their kv heads — ops/paged_attention.py
    # paged_attention_decode_sharded); pallas_interpret forces the kernel
    # path in interpret mode for CPU parity tests.
    tp = mesh.shape.get("model", 1) if mesh is not None else 1
    sharded = mesh is not None and mesh.size > 1
    # the same CPU test hook _attention honors: engine-level window tests
    # drive the kernel path in interpret mode (never on a real TPU)
    pallas_interpret = pallas_interpret or (
        env_flag("DYN_PALLAS_INTERPRET")
        and not env_flag("DYN_DISABLE_PALLAS")
        and not _use_pallas())
    use_pallas = (allow_pallas and (_use_pallas() or pallas_interpret)
                  and cfg.num_kv_heads % max(tp, 1) == 0)

    @partial(jax.jit, static_argnames=("k_steps", "logprobs_topn"),
             donate_argnames=("kv_k", "kv_v"))
    def decode_window(params, tokens, positions, done, steps, remaining,
                      kv_k, kv_v, page_table, temperature, top_k, top_p,
                      seeds, eos_table, penalties=None, *, k_steps: int,
                      logprobs_topn: int = 0):
        B = tokens.shape[0]
        L = cfg.num_layers
        ps = kv_k.shape[3]
        start = positions  # [B] position of the first window token (-1 pad)
        wdt = kv_k.dtype
        wk = jnp.zeros((L, B, k_steps, KV, hd), wdt)
        wv = jnp.zeros((L, B, k_steps, KV, hd), wdt)
        layer_params = {k: params[k] for k in _layer_keys(cfg)}

        act = _act(cfg)

        def one_step(tok, pos, wk, wv, i):
            h = embed_tokens(params, cfg, tok)[:, None]  # [B, 1, D]
            safe_pos = jnp.maximum(pos, 0)[:, None]

            def layer(h, xs):
                # NOTE: the pools are closure-captured, NOT scanned xs —
                # scanning them makes XLA materialize a fresh per-layer
                # slice copy for each unrolled step's pallas operand
                # (≈6.4 GB/step of copy traffic at serving sizes)
                lp, l_idx, wk_l, wv_l = xs
                x = rms_norm(h, lp["ln_attn"], cfg.rms_norm_eps, cfg.norm_unit_offset)
                xq, xk, xv = x @ lp["wq"], x @ lp["wk"], x @ lp["wv"]
                if cfg.attn_bias:
                    xq, xk, xv = (xq + lp["bq"], xk + lp["bk"],
                                  xv + lp["bv"])
                q, k = _qk_headnorm(xq.reshape(B, 1, H, hd),
                                    xk.reshape(B, 1, KV, hd), lp, cfg)
                q = apply_rope(q, safe_pos, inv_freq)
                k = apply_rope(k, safe_pos, inv_freq)
                v = xv.reshape(B, 1, KV, hd)
                wk_l = wk_l.at[:, i].set(k[:, 0].astype(wdt))
                wv_l = wv_l.at[:, i].set(v[:, 0].astype(wdt))
                if use_pallas:
                    attn = _pool_window_attention_pallas(
                        q, kv_k, kv_v, l_idx, page_table, start, wk_l,
                        wv_l, i, scale,
                        interpret=pallas_interpret,
                        mesh=mesh if sharded else None,
                        softcap=cfg.attn_logit_softcap,
                        window=cfg.sliding_window,
                        is_sliding=_sliding_flag(cfg, l_idx),
                        q_pos=safe_pos[:, 0])
                else:
                    attn = _pool_window_attention(
                        q, kv_k[l_idx], kv_v[l_idx], page_table, start,
                        wk_l, wv_l, i, scale,
                        softcap=cfg.attn_logit_softcap,
                        window=cfg.sliding_window,
                        is_sliding=_sliding_flag(cfg, l_idx),
                        q_pos=safe_pos[:, 0])
                h = _residual_add(h, attn.reshape(B, 1, H * hd) @ lp["wo"],
                                  lp, "ln_attn_post", cfg)
                x = rms_norm(h, lp["ln_mlp"], cfg.rms_norm_eps, cfg.norm_unit_offset)
                if cfg.num_experts > 0:
                    mlp_out = _moe_mlp(x, lp["w_router"], lp["w_gate"],
                                       lp["w_up"], lp["w_down"],
                                       cfg.num_experts_per_tok, mesh=mesh)
                else:
                    mlp_out = _mlp(x, lp["w_gate"], lp["w_up"],
                                   lp["w_down"], act)
                h = _residual_add(h, mlp_out, lp, "ln_mlp_post", cfg)
                return h, (wk_l, wv_l)

            h, (wk, wv) = lax.scan(
                layer, h,
                (layer_params, jnp.arange(L, dtype=jnp.int32), wk, wv))
            h = rms_norm(h, params["ln_final"], cfg.rms_norm_eps, cfg.norm_unit_offset)
            logits = logits_at(params, cfg, h, jnp.zeros(B, jnp.int32))
            return logits, wk, wv

        tok, pos = tokens, positions
        toks = []
        lps, tvs, tis = [], [], []
        # per-row count of tokens this window actually produced: a row
        # that freezes (stop token / budget) mid-window stops counting, so
        # the host can slice toks[i, :emitted[i]] without a per-step scan
        emitted = jnp.zeros((B,), jnp.int32)
        for i in range(k_steps):
            # frozen (done/pad) rows still flow through the matmuls — their
            # outputs are discarded and their KV never commits (commit mask
            # below), so correctness needs no per-row control flow
            logits, wk, wv = one_step(tok, pos, wk, wv, i)
            nxt = sample_tokens(logits, temperature, top_k, top_p, seeds,
                                steps, max_top_k=max_top_k,
                                penalties=penalties)
            if logprobs_topn:
                lp, tv, ti = logprob_aux(logits, nxt, logprobs_topn)
                lps.append(lp); tvs.append(tv); tis.append(ti)
            penalties = update_penalty_state(penalties, nxt, done)
            emitted = emitted + carry_active(done, pos).astype(jnp.int32)
            tok, pos, done, steps, remaining = carry_step_update(
                nxt, tok, pos, done, steps, remaining, eos_table)
            toks.append(tok)

        # commit the window into the pool: one scatter per layer; entry i
        # holds the K/V of position start+i, valid only if the row was
        # still active at step i (start+i < final pos)
        wpos = start[:, None] + jnp.arange(k_steps)[None, :]  # [B, K]
        page = page_table[jnp.arange(B)[:, None],
                          jnp.clip(wpos // ps, 0, page_table.shape[1] - 1)]
        valid = jnp.logical_and(start[:, None] >= 0, wpos < pos[:, None])
        flat = jnp.where(valid, page * ps + wpos % ps, DROP_SLOT)
        kv_k = jax.vmap(_scatter_pages)(kv_k, wk, jnp.broadcast_to(
            flat, (cfg.num_layers,) + flat.shape))
        kv_v = jax.vmap(_scatter_pages)(kv_v, wv, jnp.broadcast_to(
            flat, (cfg.num_layers,) + flat.shape))
        out_toks = jnp.stack(toks, axis=1)
        carry = (tok, pos, done, steps, remaining)
        if logprobs_topn:
            aux = (jnp.stack(lps, axis=1), jnp.stack(tvs, axis=1),
                   jnp.stack(tis, axis=1))
            return out_toks, emitted, aux, carry, kv_k, kv_v
        return out_toks, emitted, carry, kv_k, kv_v

    return decode_window


def _pool_window_attention_pallas(q, k_pools, v_pools, l_idx, page_table,
                                  start, wk_l, wv_l, i: int, scale,
                                  interpret: bool = False, mesh=None,
                                  softcap=None, window=None,
                                  is_sliding=False, q_pos=None):
    """Decode attention for one fused-window step: the (frozen) paged pool
    via the Pallas flash kernel (stats returned, layer selected by index
    map — no layer-slice materialization), merged with the in-flight
    window buffer by online-softmax combination. Positions < start live in
    the pool; positions start..start+i in the buffer.

    q: [B, 1, H, hd]; *_pools: [L, pages, KV, ps, hd]; l_idx: scalar;
    wk_l/wv_l: [B, K, KV, hd]; start: [B]; i: static step index. The
    Gemma-2 knobs (score softcap; sliding window on is_sliding layers
    with ``q_pos`` [B] the current query position) apply to BOTH sides:
    the kernel takes a per-row lower bound — and skips pages the window
    already slid past — while the buffer side masks in XLA."""
    from ..ops.paged_attention import (NEG_INF,
                                       paged_attention_decode_layered,
                                       paged_attention_decode_sharded)

    B, _, H, hd = q.shape
    KV = wk_l.shape[2]
    G = H // KV
    K = wk_l.shape[1]
    lengths = jnp.maximum(start, 0)  # pool extent; padding rows (-1) → 0
    lower = None
    eff = None
    if window is not None:
        eff = effective_window(window, is_sliding, B)
        # pool side sees [lower, start); a window that slid past the whole
        # pool (q_pos + 1 - eff >= start) leaves an empty view, which the
        # kernel's valid-masking returns as (m=NEG_INF, l=0) — the merge
        # below weights that side by l_p = 0
        lower = jnp.clip(q_pos + 1 - eff, 0, lengths)
    if mesh is not None:
        out_p, m_p, l_p = paged_attention_decode_sharded(
            q[:, 0], k_pools, v_pools, l_idx, page_table, lengths,
            mesh=mesh, scale=scale, interpret=interpret,
            softcap=softcap, lower=lower)
    else:
        out_p, m_p, l_p = paged_attention_decode_layered(
            q[:, 0], k_pools, v_pools, l_idx, page_table, lengths,
            scale=scale, return_stats=True, interpret=interpret,
            softcap=softcap, lower=lower)
    qg = q.reshape(B, KV, G, hd).astype(jnp.float32)
    sw = jnp.einsum("bkgh,bwkh->bkgw", qg,
                    wk_l.astype(jnp.float32)) * scale  # [B, KV, G, K]
    if softcap:
        sw = softcap * jnp.tanh(sw / softcap)
    mask_w = (jnp.arange(K)[None, :] <= i) & (start[:, None] >= 0)
    if eff is not None:
        # buffer slot w holds position start + w; slot i (the current
        # token) always stays visible since eff >= 1
        mask_w &= (start[:, None] + jnp.arange(K)[None, :]
                   > (q_pos - eff)[:, None])
    sw = jnp.where(mask_w[:, None, None, :], sw, NEG_INF)
    m_w = jnp.max(sw, axis=-1)                         # [B, KV, G]
    p_w = jnp.exp(sw - m_w[..., None])
    l_w = jnp.sum(p_w, axis=-1)
    out_w = jnp.einsum("bkgw,bwkh->bkgh", p_w, wv_l.astype(jnp.float32))
    # merge: rescale each side to the joint max, renormalize once
    m_p = m_p.reshape(B, KV, G)
    l_p = l_p.reshape(B, KV, G)
    m_t = jnp.maximum(m_p, m_w)
    a_p = jnp.exp(m_p - m_t) * l_p   # pool side un-normalized weight
    a_w = jnp.exp(m_w - m_t)
    l_t = jnp.maximum(a_p + a_w * l_w, 1e-9)
    out = (out_p.reshape(B, KV, G, hd).astype(jnp.float32) * a_p[..., None]
           + out_w * a_w[..., None]) / l_t[..., None]
    return out.reshape(B, 1, H, hd).astype(q.dtype)


def _pool_window_attention(q, k_pool_l, v_pool_l, page_table, start,
                           wk_l, wv_l, i: int, scale,
                           softcap=None, window=None, is_sliding=False,
                           q_pos=None):
    """Decode attention reading the (frozen) paged pool for positions
    < start plus the in-flight window for positions start..start+i.

    q: [B, 1, H, hd]; *_pool_l: [pages, KV, ps, hd]; wk_l/wv_l:
    [B, K, KV, hd]; start: [B]; i: static step index. The Gemma-2 knobs
    (score softcap, sliding window on is_sliding layers, with ``q_pos``
    [B] the current query position) ride this XLA path — the Pallas
    window kernel doesn't implement them."""
    B, _, H, hd = q.shape
    _, KV, ps, _ = k_pool_l.shape
    K = wk_l.shape[1]
    P = page_table.shape[1]
    S = P * ps
    G = H // KV

    kp = k_pool_l[page_table].transpose(0, 1, 3, 2, 4).reshape(B, S, KV, hd)
    vp = v_pool_l[page_table].transpose(0, 1, 3, 2, 4).reshape(B, S, KV, hd)
    qg = q.reshape(B, 1, KV, G, hd).astype(jnp.float32)
    sp = jnp.einsum("btkgh,bskh->bkgts", qg,
                    kp.astype(jnp.float32)) * scale  # [B,KV,G,1,S]
    sw = jnp.einsum("btkgh,bwkh->bkgtw", qg,
                    wk_l.astype(jnp.float32)) * scale  # [B,KV,G,1,K]
    mask_p = (jnp.arange(S)[None, :] < start[:, None])  # start<0 → all off
    mask_w = (jnp.arange(K)[None, :] <= i) & (start[:, None] >= 0)
    if window is not None:
        # sliding layers see only kv positions > q_pos - window; pool
        # slot j holds logical position j, window slot w holds start + w
        keep = jnp.logical_not(is_sliding)
        mask_p &= keep | (jnp.arange(S)[None, :]
                          > (q_pos - window)[:, None])
        mask_w &= keep | ((start[:, None] + jnp.arange(K)[None, :])
                          > (q_pos - window)[:, None])
    sp = _softcap_mask(sp, mask_p[:, None, None, None, :], softcap)
    sw = _softcap_mask(sw, mask_w[:, None, None, None, :], softcap)
    s = jnp.concatenate([sp, sw], axis=-1)
    p = jax.nn.softmax(s, axis=-1)
    pp, pw = p[..., :S], p[..., S:]
    out = (jnp.einsum("bkgts,bskh->btkgh", pp, vp.astype(jnp.float32))
           + jnp.einsum("bkgtw,bwkh->btkgh", pw,
                        wv_l.astype(jnp.float32)))
    return out.reshape(B, 1, H, hd).astype(q.dtype)


# -------------------------------------------------- full-attention reference


def full_attention_layer(cfg: ModelConfig, h: jax.Array, lp: Params,
                         pos: jax.Array, inv_freq: jax.Array,
                         scale: float, is_sliding=False,
                         mesh=None) -> jax.Array:
    """One transformer layer with plain causal full attention (no paged
    cache). The single source of the layer math for every non-paged
    consumer: ``reference_forward`` (test oracle) and the
    pipeline-parallel stage body (parallel/pipeline_parallel.py) —
    inside the latter's shard_map all values are device-local, so the
    default mesh=None (which may pick the blocked MoE dispatch) is
    correct there too.
    ``is_sliding`` is the traced Gemma-2 per-layer window flag (the
    caller owns the layer-parity bookkeeping — see _sliding_flag)."""
    B, T = h.shape[:2]
    H, KV, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim_
    x = rms_norm(h, lp["ln_attn"], cfg.rms_norm_eps, cfg.norm_unit_offset)
    xq, xk, xv = x @ lp["wq"], x @ lp["wk"], x @ lp["wv"]
    if cfg.attn_bias:
        xq, xk, xv = xq + lp["bq"], xk + lp["bk"], xv + lp["bv"]
    q, k = _qk_headnorm(xq.reshape(B, T, H, hd),
                        xk.reshape(B, T, KV, hd), lp, cfg)
    q = apply_rope(q, pos, inv_freq)
    k = apply_rope(k, pos, inv_freq)
    v = xv.reshape(B, T, KV, hd)
    qg = q.reshape(B, T, KV, H // KV, hd)
    scores = jnp.einsum("btkgh,bskh->bkgts", qg.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    mask = _visible(jnp.arange(T)[None, None, :],
                    jnp.arange(T)[None, :, None],
                    cfg.sliding_window, is_sliding)  # [1, T, T]
    scores = _softcap_mask(scores, mask[:, None, None],
                           cfg.attn_logit_softcap)
    probs = jax.nn.softmax(scores, axis=-1)
    attn = jnp.einsum("bkgts,bskh->btkgh", probs, v.astype(jnp.float32))
    attn = attn.reshape(B, T, H * hd).astype(h.dtype)
    h = _residual_add(h, attn @ lp["wo"], lp, "ln_attn_post", cfg)
    x = rms_norm(h, lp["ln_mlp"], cfg.rms_norm_eps, cfg.norm_unit_offset)
    if cfg.num_experts > 0:
        mlp_out = _moe_mlp(x, lp["w_router"], lp["w_gate"], lp["w_up"],
                           lp["w_down"], cfg.num_experts_per_tok, mesh=mesh)
    else:
        mlp_out = _mlp(x, lp["w_gate"], lp["w_up"], lp["w_down"], _act(cfg))
    return _residual_add(h, mlp_out, lp, "ln_mlp_post", cfg)


def reference_forward(params: Params, cfg: ModelConfig,
                      tokens: jax.Array) -> jax.Array:
    """Plain full-attention forward (no paging) used to validate the paged
    path in tests; returns logits for every position [B, T, V]."""
    B, T = tokens.shape
    inv_freq = rope_freqs(cfg)
    scale = cfg.attn_scale
    pos = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
    h = embed_tokens(params, cfg, tokens)

    layer_params = {k: params[k] for k in _layer_keys(cfg)}

    def layer(h, xs):
        lp, l_idx = xs
        return full_attention_layer(cfg, h, lp, pos, inv_freq, scale,
                                    is_sliding=_sliding_flag(cfg, l_idx)), \
            None

    h, _ = lax.scan(layer, h,
                    (layer_params, jnp.arange(cfg.num_layers)))
    h = rms_norm(h, params["ln_final"], cfg.rms_norm_eps, cfg.norm_unit_offset)
    return project_logits(params, cfg, h)
