"""DeepSeek-style MLA (multi-head latent attention) with a paged latent
KV cache — the second model family (BASELINE scale-out config: MLA
workers; the reference serves DeepSeek models through its engines).

TPU-first design points:

- the KV cache stores ONLY the rank-r latent ``c_kv`` plus the shared
  rope key ``k_rope`` per token — cache bytes/token shrink by ~an order
  of magnitude vs GQA, so the same HBM pool holds proportionally more
  context (paged pools [L, pages, 1, ps, r] and [L, pages, 1, ps, dr],
  shape-compatible with the engine's generic page machinery);
- decode uses the absorbed form: W_UK is folded into the query
  (q_lat = q_nope · W_UK) and W_UV into the output, so attention runs
  entirely in latent space — two big MXU einsums per layer instead of
  materializing per-head K/V;
- prefill/decode share one program exactly like models/llama.py (scatter
  new latents into pages, gather the page table, masked attention).

Weight layout follows the DeepSeek-V2 architecture (q LoRA optional,
kv LoRA + decoupled rope head); MoE layers reuse the Mixtral-style
dense-over-experts MLP from models/llama.py.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .config import ModelConfig
from .llama import (DROP_SLOT, KVCacheSpec, _mlp, apply_rope, logits_at,
                    rms_norm, rope_freqs)

Params = Dict[str, jax.Array]


# ---------------------------------------------------------------- KV cache


def cache_shapes(cfg: ModelConfig, spec: KVCacheSpec):
    """(latent pool shape, rope pool shape): KV-head axis fixed at 1 so
    the engine's page gather/scatter/transfer stay shape-agnostic."""
    latent = (cfg.num_layers, spec.num_pages, 1, spec.page_size,
              cfg.kv_lora_rank)
    rope = (cfg.num_layers, spec.num_pages, 1, spec.page_size,
            cfg.qk_rope_head_dim)
    return latent, rope


def init_kv_cache(cfg: ModelConfig, spec: KVCacheSpec,
                  dtype=None) -> Tuple[jax.Array, jax.Array]:
    dtype = dtype or cfg.jax_dtype
    lat, rope = cache_shapes(cfg, spec)
    return jnp.zeros(lat, dtype), jnp.zeros(rope, dtype)


# ------------------------------------------------------------------ params


def init_params(cfg: ModelConfig, key: jax.Array, dtype=None) -> Params:
    dtype = dtype or cfg.jax_dtype
    D, I, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    H = cfg.num_heads
    r, dr = cfg.kv_lora_rank, cfg.qk_rope_head_dim
    dn, dv = cfg.qk_nope_head_dim, cfg.v_head_dim
    V = cfg.vocab_size
    ks = jax.random.split(key, 14)

    def w_init(k, *shape):
        scale = 1.0 / math.sqrt(shape[-2]) if len(shape) > 1 else 0.02
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

    p: Params = {
        "embed": w_init(ks[0], V, D),
        # kv path: x → [c_kv (r) | k_rope (dr)]; c_kv normed before up-proj
        "w_dkv": w_init(ks[1], L, D, r + dr),
        "kv_norm": jnp.ones((L, r), dtype),
        "w_uk": w_init(ks[2], L, r, H * dn),
        "w_uv": w_init(ks[3], L, r, H * dv),
        "w_o": w_init(ks[4], L, H * dv, D),
        "w_gate": w_init(ks[5], L, D, I),
        "w_up": w_init(ks[6], L, D, I),
        "w_down": w_init(ks[7], L, I, D),
        "ln_attn": jnp.ones((L, D), dtype),
        "ln_mlp": jnp.ones((L, D), dtype),
        "ln_final": jnp.ones((D,), dtype),
    }
    if cfg.q_lora_rank > 0:
        rq = cfg.q_lora_rank
        p["w_dq"] = w_init(ks[8], L, D, rq)
        p["q_norm"] = jnp.ones((L, rq), dtype)
        p["w_uq"] = w_init(ks[9], L, rq, H * (dn + dr))
    else:
        p["w_q"] = w_init(ks[9], L, D, H * (dn + dr))
    if not cfg.tie_word_embeddings:
        p["lm_head"] = w_init(ks[10], D, V)
    if cfg.num_experts > 0:
        # DeepSeek-MoE layout: dense first-k layers keep w_*_d; MoE
        # layers carry routed experts (+ optional shared experts/bias)
        E, kd = cfg.num_experts, cfg.first_k_dense_replace
        Lm = L - kd
        Im = cfg.moe_intermediate_size or I
        del p["w_gate"], p["w_up"], p["w_down"]
        if kd > 0:
            p["w_gate_d"] = w_init(ks[5], kd, D, I)
            p["w_up_d"] = w_init(ks[6], kd, D, I)
            p["w_down_d"] = w_init(ks[7], kd, I, D)
        p["w_router"] = w_init(ks[11], Lm, D, E)
        p["w_gate_e"] = w_init(ks[5], Lm, E, D, Im)
        p["w_up_e"] = w_init(ks[6], Lm, E, D, Im)
        p["w_down_e"] = w_init(ks[7], Lm, E, Im, D)
        if cfg.moe_router == "deepseek_v3":
            p["router_bias"] = jnp.zeros((Lm, E), dtype)
        if cfg.n_shared_experts > 0:
            Is = Im * cfg.n_shared_experts
            p["w_gate_s"] = w_init(ks[12], Lm, D, Is)
            p["w_up_s"] = w_init(ks[13], Lm, D, Is)
            p["w_down_s"] = w_init(ks[12], Lm, Is, D)
    return p


# ----------------------------------------------------------------- forward


def _mla_attn_keys(cfg: ModelConfig) -> list:
    """Attention-side per-layer param names (stacked over ALL layers,
    sliced per dense/MoE segment)."""
    keys = ["w_dkv", "kv_norm", "w_uk", "w_uv", "w_o", "ln_attn",
            "ln_mlp"]
    keys += (["w_dq", "q_norm", "w_uq"] if cfg.q_lora_rank > 0
             else ["w_q"])
    return keys


def _mla_layer_keys(cfg: ModelConfig) -> list:
    """Per-layer param names scanned over the stacked-layer axis — shared
    by forward, reference_forward, and the MLA ring long-prefill
    (parallel/ring_attention.make_mla_long_prefill_fn). DENSE configs
    only; DeepSeek-MoE configs segment their params (see forward)."""
    return _mla_attn_keys(cfg) + ["w_gate", "w_up", "w_down"]


def _moe_layer_params(cfg: ModelConfig, params: Params) -> dict:
    """The MoE segment's per-layer params (stacked over layers
    [first_k_dense_replace, L))."""
    lp = {"w_router": params["w_router"], "w_gate_e": params["w_gate_e"],
          "w_up_e": params["w_up_e"], "w_down_e": params["w_down_e"]}
    if cfg.moe_router == "deepseek_v3":
        lp["router_bias"] = params["router_bias"]
    if cfg.n_shared_experts > 0:
        lp.update({k: params[k] for k in ("w_gate_s", "w_up_s",
                                          "w_down_s")})
    return lp


def _deepseek_gate(x32, w_router, bias, cfg: ModelConfig):
    """DeepSeek router → (weights [B, T, k], expert indices [B, T, k]).

    v2 (HF DeepseekV2MoEGate): softmax scores; optional group limiting by
    the MAX score per group; top-k; weights scaled (NOT renormalized).
    v3 (HF DeepseekV3TopkRouter): sigmoid scores; selection by scores +
    e_score_correction_bias with groups ranked by their top-2 SUM; the
    applied weights are the ORIGINAL sigmoid scores of the selected
    experts, optionally renormalized, then scaled."""
    E = w_router.shape[-1]
    k = cfg.num_experts_per_tok
    logits = x32 @ w_router.astype(jnp.float32)
    if cfg.moe_router == "deepseek_v3":
        scores = jax.nn.sigmoid(logits)
        choice = scores + bias.astype(jnp.float32)
    else:
        scores = jax.nn.softmax(logits, axis=-1)
        choice = scores
    if cfg.n_group > 0 and cfg.topk_group > 0:
        G = cfg.n_group
        cg = choice.reshape(*choice.shape[:-1], G, E // G)
        if cfg.moe_router == "deepseek_v3":
            g_scores = jnp.sum(lax.top_k(cg, 2)[0], axis=-1)
        else:
            g_scores = jnp.max(cg, axis=-1)
        _, g_idx = lax.top_k(g_scores, cfg.topk_group)
        g_mask = jnp.sum(jax.nn.one_hot(g_idx, G, dtype=jnp.float32),
                         axis=-2)
        choice = jnp.where(g_mask[..., :, None] > 0, cg,
                           0.0).reshape(choice.shape)
    _, topi = lax.top_k(choice, k)
    w = jnp.take_along_axis(scores, topi, axis=-1)
    # v3 (HF DeepseekV3TopkRouter): optional renorm, then ALWAYS scaled.
    # v2: transformers' DeepseekV2MoEGate ignores norm_topk_prob (always
    # scales); configs setting it are rejected at ModelConfig load.
    if cfg.moe_router == "deepseek_v3" and cfg.norm_topk_prob:
        w = w / (jnp.sum(w, axis=-1, keepdims=True) + 1e-20)
    w = w * cfg.routed_scaling_factor
    return w, topi


def _dense_gate(w, topi, E):
    """(weights, indices) → dense [B, T, E] mask for the
    dense-over-experts einsum path."""
    return jnp.sum(jax.nn.one_hot(topi, E, dtype=jnp.float32)
                   * w[..., None], axis=-2)


def _deepseek_moe_mlp(x: jax.Array, lp, cfg: ModelConfig,
                      mesh=None) -> jax.Array:
    """Routed experts plus the always-on shared experts. Large
    dispatches on an unsharded expert axis use the sorted blocked
    dispatch (~top_k/E of the dense FLOPs — with E up to 256 on
    DeepSeek-V3 the dense-over-experts einsum is ~32x waste); decode-
    sized dispatches and expert-parallel meshes keep the dense einsum
    (see llama._moe_mlp for the strategy rationale)."""
    from .llama import _MOE_BLOCK, _moe_use_blocked, moe_experts_blocked

    B, T, D = x.shape
    E = lp["w_gate_e"].shape[0]
    x32 = x.astype(jnp.float32)
    w, topi = _deepseek_gate(x32, lp["w_router"],
                             lp.get("router_bias"), cfg)
    if _moe_use_blocked(mesh, B * T, E, cfg.num_experts_per_tok,
                        _MOE_BLOCK):
        out = moe_experts_blocked(
            x32.reshape(B * T, D), w.reshape(B * T, -1),
            topi.reshape(B * T, -1), lp["w_gate_e"], lp["w_up_e"],
            lp["w_down_e"], block=_MOE_BLOCK).reshape(B, T, D)
    else:
        gate = _dense_gate(w, topi, E)
        ge = jnp.einsum("btd,edi->btei", x32,
                        lp["w_gate_e"].astype(jnp.float32))
        up = jnp.einsum("btd,edi->btei", x32,
                        lp["w_up_e"].astype(jnp.float32))
        act = jax.nn.silu(ge) * up
        down = jnp.einsum("btei,eid->bted", act,
                          lp["w_down_e"].astype(jnp.float32))
        out = jnp.einsum("bted,bte->btd", down, gate)
    if cfg.n_shared_experts > 0:
        out = out + _mlp(x32, lp["w_gate_s"].astype(jnp.float32),
                         lp["w_up_s"].astype(jnp.float32),
                         lp["w_down_s"].astype(jnp.float32))
    return out.astype(x.dtype)


def _scatter_rows(cache_layer: jax.Array, new: jax.Array,
                  flat_slots: jax.Array) -> jax.Array:
    """cache_layer: [pages, 1, ps, d]; new: [B, T, d]; flat_slots [B, T]
    (page*ps + off; DROP_SLOT pads)."""
    _, _, ps, d = cache_layer.shape
    idx = flat_slots.reshape(-1)
    pages, offs = idx // ps, idx % ps
    rows = new.reshape(-1, d).astype(cache_layer.dtype)
    return cache_layer.at[pages, 0, offs].set(rows, mode="drop")


def _mla_attention(q_lat, q_rope, c_pages, r_pages, page_table,
                   q_positions, scale):
    """Latent-space paged attention.

    q_lat: [B, T, H, r] (absorbed queries); q_rope: [B, T, H, dr];
    c_pages: [pages, 1, ps, r]; r_pages: [pages, 1, ps, dr];
    page_table: [B, P]; q_positions: [B, T]. Returns [B, T, H, r]
    (latent-space context, to be up-projected by W_UV)."""
    B, T, H, r = q_lat.shape
    _, _, ps, dr = r_pages.shape
    P = page_table.shape[1]
    S = P * ps

    c = c_pages[page_table].reshape(B, S, r)  # [B, P, 1, ps, r] → [B, S, r]
    kr = r_pages[page_table].reshape(B, S, dr)
    scores = (jnp.einsum("bthr,bsr->bhts", q_lat.astype(jnp.float32),
                         c.astype(jnp.float32))
              + jnp.einsum("bthd,bsd->bhts", q_rope.astype(jnp.float32),
                           kr.astype(jnp.float32))) * scale
    mask = (jnp.arange(S)[None, None, :] <= q_positions[:, :, None])
    scores = jnp.where(mask[:, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhts,bsr->bthr", probs, c.astype(jnp.float32))
    return out


def forward(params: Params, cfg: ModelConfig, tokens: jax.Array,
            positions: jax.Array, kv_lat: jax.Array, kv_rope: jax.Array,
            page_table: jax.Array, flat_slots: jax.Array,
            allow_pallas: bool = True, mesh=None,
            ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Same signature/contract as llama.forward; (kv_k, kv_v) ≡
    (latent pool, rope pool)."""
    del allow_pallas  # latent attention is XLA-einsum throughout;
    # mesh is only consulted to pick the MoE dispatch strategy
    inv_freq = rope_freqs(cfg, dim=cfg.qk_rope_head_dim)
    H = cfg.num_heads
    r, dr = cfg.kv_lora_rank, cfg.qk_rope_head_dim
    dn, dv = cfg.qk_nope_head_dim, cfg.v_head_dim
    scale = 1.0 / math.sqrt(dn + dr)
    B, T = tokens.shape

    h = params["embed"][tokens]
    safe_pos = jnp.maximum(positions, 0)

    def layer_with(mlp_apply):
        def layer(h, xs):
            lp, c_layer, r_layer = xs
            x = rms_norm(h, lp["ln_attn"], cfg.rms_norm_eps)
            # queries
            if cfg.q_lora_rank > 0:
                q_all = rms_norm(x @ lp["w_dq"], lp["q_norm"],
                                 cfg.rms_norm_eps) @ lp["w_uq"]
            else:
                q_all = x @ lp["w_q"]
            q_all = q_all.reshape(B, T, H, dn + dr)
            q_nope, q_rope = q_all[..., :dn], q_all[..., dn:]
            q_rope = apply_rope(q_rope, safe_pos, inv_freq)
            # kv latent + shared rope key
            ckr = x @ lp["w_dkv"]  # [B, T, r + dr]
            c_kv = rms_norm(ckr[..., :r], lp["kv_norm"], cfg.rms_norm_eps)
            k_rope = apply_rope(ckr[..., None, r:], safe_pos,
                                inv_freq)[..., 0, :]  # one shared rope head
            c_layer = _scatter_rows(c_layer, c_kv, flat_slots)
            r_layer = _scatter_rows(r_layer, k_rope, flat_slots)
            # absorbed attention: q_lat = q_nope · W_UK (per head)
            w_uk = lp["w_uk"].reshape(r, H, dn)
            q_lat = jnp.einsum("bthd,rhd->bthr",
                               q_nope.astype(jnp.float32),
                               w_uk.astype(jnp.float32))
            out_lat = _mla_attention(q_lat, q_rope, c_layer, r_layer,
                                     page_table, positions, scale)
            # up-project latent context per head: out = out_lat · W_UV
            w_uv = lp["w_uv"].reshape(r, H, dv)
            out = jnp.einsum("bthr,rhd->bthd", out_lat,
                             w_uv.astype(jnp.float32))
            h2 = h + out.reshape(B, T, H * dv).astype(h.dtype) @ lp["w_o"]
            x = rms_norm(h2, lp["ln_mlp"], cfg.rms_norm_eps)
            return h2 + mlp_apply(x, lp), (c_layer, r_layer)

        return layer

    if cfg.num_experts == 0:
        layer_params = {k: params[k] for k in _mla_layer_keys(cfg)}
        dense = layer_with(
            lambda x, lp: _mlp(x, lp["w_gate"], lp["w_up"], lp["w_down"]))
        h, (new_c, new_r) = lax.scan(dense, h,
                                     (layer_params, kv_lat, kv_rope))
    else:
        # DeepSeek-MoE: dense first-k layers, then MoE layers — two scans
        # over layer segments (per-segment param stacks; the pools are
        # sliced/concatenated, an extra copy the small latent cache
        # affords)
        kd = cfg.first_k_dense_replace
        attn = {k: params[k] for k in _mla_attn_keys(cfg)}
        seg_a = jax.tree.map(lambda a: a[:kd], attn)
        seg_b = jax.tree.map(lambda a: a[kd:], attn)
        new_c_parts, new_r_parts = [], []
        if kd > 0:
            seg_a.update({k: params[f"{k}_d"]
                          for k in ("w_gate", "w_up", "w_down")})
            dense = layer_with(lambda x, lp: _mlp(
                x, lp["w_gate"], lp["w_up"], lp["w_down"]))
            h, (c_a, r_a) = lax.scan(dense, h,
                                     (seg_a, kv_lat[:kd], kv_rope[:kd]))
            new_c_parts.append(c_a)
            new_r_parts.append(r_a)
        seg_b.update(_moe_layer_params(cfg, params))
        moe = layer_with(
            lambda x, lp: _deepseek_moe_mlp(x, lp, cfg, mesh=mesh))
        h, (c_b, r_b) = lax.scan(moe, h,
                                 (seg_b, kv_lat[kd:], kv_rope[kd:]))
        new_c_parts.append(c_b)
        new_r_parts.append(r_b)
        new_c = jnp.concatenate(new_c_parts, axis=0)
        new_r = jnp.concatenate(new_r_parts, axis=0)
    h = rms_norm(h, params["ln_final"], cfg.rms_norm_eps)
    return h, new_c, new_r


def make_step_fns(cfg: ModelConfig, allow_pallas: bool = True, mesh=None):
    """Jitted (prefill_step, decode_step); same contract as llama.
    Latent attention is XLA-einsum based throughout, so the pallas
    kernel knob is accepted for interface parity and ignored (GSPMD
    shards the einsums directly); mesh only picks the MoE dispatch
    strategy (expert-sharded meshes keep the dense einsum)."""
    del allow_pallas

    @partial(jax.jit, donate_argnames=("kv_k", "kv_v"))
    def prefill_step(params, tokens, positions, kv_k, kv_v, page_table,
                     flat_slots, last_idx, page_slots=None):
        # page_slots accepted for engine-contract parity with llama; the
        # MLA latent cache keeps the row-scatter commit (its pages hold
        # compressed latents, not per-head K/V blocks)
        del page_slots
        h, k2, v2 = forward(params, cfg, tokens, positions, kv_k, kv_v,
                            page_table, flat_slots, mesh=mesh)
        return logits_at(params, cfg, h, last_idx), k2, v2

    @partial(jax.jit, donate_argnames=("kv_k", "kv_v"))
    def decode_step(params, tokens, positions, kv_k, kv_v, page_table,
                    flat_slots):
        h, k2, v2 = forward(params, cfg, tokens[:, None], positions[:, None],
                            kv_k, kv_v, page_table, flat_slots[:, None],
                            mesh=mesh)
        return (logits_at(params, cfg, h,
                          jnp.zeros(tokens.shape[0], jnp.int32)), k2, v2)

    return prefill_step, decode_step


# -------------------------------------------------- full-attention reference


def reference_forward(params: Params, cfg: ModelConfig,
                      tokens: jax.Array) -> jax.Array:
    """Non-paged, non-absorbed MLA forward (materializes per-head K/V) —
    the independent oracle for the paged/absorbed path."""
    B, T = tokens.shape
    inv_freq = rope_freqs(cfg, dim=cfg.qk_rope_head_dim)
    H = cfg.num_heads
    r, dr = cfg.kv_lora_rank, cfg.qk_rope_head_dim
    dn, dv = cfg.qk_nope_head_dim, cfg.v_head_dim
    scale = 1.0 / math.sqrt(dn + dr)
    pos = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
    h = params["embed"][tokens]

    def layer(h, lp, mlp_apply):
        x = rms_norm(h, lp["ln_attn"], cfg.rms_norm_eps)
        if cfg.q_lora_rank > 0:
            q_all = rms_norm(x @ lp["w_dq"], lp["q_norm"],
                             cfg.rms_norm_eps) @ lp["w_uq"]
        else:
            q_all = x @ lp["w_q"]
        q_all = q_all.reshape(B, T, H, dn + dr)
        q_nope, q_rope = q_all[..., :dn], q_all[..., dn:]
        q_rope = apply_rope(q_rope, pos, inv_freq)
        ckr = x @ lp["w_dkv"]
        c_kv = rms_norm(ckr[..., :r], lp["kv_norm"], cfg.rms_norm_eps)
        k_rope = apply_rope(ckr[..., None, r:], pos, inv_freq)[..., 0, :]
        # materialized per-head keys/values (the non-absorbed form)
        k_nope = jnp.einsum("btr,rhd->bthd", c_kv.astype(jnp.float32),
                            lp["w_uk"].reshape(r, H, dn).astype(jnp.float32))
        v = jnp.einsum("btr,rhd->bthd", c_kv.astype(jnp.float32),
                       lp["w_uv"].reshape(r, H, dv).astype(jnp.float32))
        scores = (jnp.einsum("bthd,bshd->bhts", q_nope.astype(jnp.float32),
                             k_nope)
                  + jnp.einsum("bthd,bsd->bhts",
                               q_rope.astype(jnp.float32),
                               k_rope.astype(jnp.float32))) * scale
        causal = jnp.tril(jnp.ones((T, T), bool))
        scores = jnp.where(causal[None, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bhts,bshd->bthd", probs, v)
        h = h + out.reshape(B, T, H * dv).astype(h.dtype) @ lp["w_o"]
        x = rms_norm(h, lp["ln_mlp"], cfg.rms_norm_eps)
        return h + mlp_apply(x, lp)

    # oracle path: plain per-layer Python loop (unrolled trace; test-sized)
    dense_mlp = lambda x, lp: _mlp(x, lp["w_gate"], lp["w_up"],
                                   lp["w_down"])
    for li in range(cfg.num_layers):
        if cfg.num_experts == 0:
            lp = {k: params[k][li] for k in _mla_layer_keys(cfg)}
            h = layer(h, lp, dense_mlp)
        elif li < cfg.first_k_dense_replace:
            lp = {k: params[k][li] for k in _mla_attn_keys(cfg)}
            lp.update({k: params[f"{k}_d"][li]
                       for k in ("w_gate", "w_up", "w_down")})
            h = layer(h, lp, dense_mlp)
        else:
            mi = li - cfg.first_k_dense_replace
            lp = {k: params[k][li] for k in _mla_attn_keys(cfg)}
            lp.update({k: v[mi]
                       for k, v in _moe_layer_params(cfg, params).items()})
            h = layer(h, lp, lambda x, lp: _deepseek_moe_mlp(x, lp, cfg))
    h = rms_norm(h, params["ln_final"], cfg.rms_norm_eps)
    head = params.get("lm_head")
    if head is None:
        head = params["embed"].T
    return (h @ head).astype(jnp.float32)
