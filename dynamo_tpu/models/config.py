"""Model configuration.

Covers the Llama family (incl. DeepSeek-R1-Distill-Llama — the reference's
flagship example model, examples/llm/configs/agg.yaml) and Mixtral-style MoE.
``from_hf_config`` maps a HuggingFace ``config.json`` dict.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Optional

import jax.numpy as jnp


@dataclass
class ModelConfig:
    model_type: str = "llama"
    vocab_size: int = 128256
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: Optional[int] = None
    rope_theta: float = 500000.0
    rope_scaling: Optional[dict] = None
    rms_norm_eps: float = 1e-5
    tie_word_embeddings: bool = False
    # MoE (Mixtral-style); num_experts=0 → dense
    num_experts: int = 0
    num_experts_per_tok: int = 2
    # MLA (DeepSeek-V2/V3 multi-head latent attention); kv_lora_rank>0
    # switches the attention/KV-cache design (models/mla.py)
    q_lora_rank: int = 0           # 0 = full-rank q projection
    kv_lora_rank: int = 0
    qk_nope_head_dim: int = 128
    qk_rope_head_dim: int = 64
    v_head_dim: int = 128
    attn_bias: bool = False        # qkv projection bias (Qwen2-style)
    qk_norm: bool = False          # per-head RMSNorm on q/k pre-RoPE (Qwen3)
    # DeepSeek-MoE (V2/V3): dense first-k layers, shared experts riding
    # beside the routed ones, and family-specific routing — "deepseek_v2"
    # (softmax scores, optional max-per-group limiting, scale) or
    # "deepseek_v3" (sigmoid scores + selection bias, top-2-sum groups,
    # optional renorm, scale). moe_intermediate_size is the EXPERT width;
    # intermediate_size stays the dense-layer width.
    moe_router: str = "mixtral"
    n_shared_experts: int = 0
    first_k_dense_replace: int = 0
    moe_intermediate_size: Optional[int] = None
    routed_scaling_factor: float = 1.0
    n_group: int = 0               # 0 = no group-limited routing
    topk_group: int = 0
    norm_topk_prob: bool = False
    # real DeepSeek checkpoints store rope dims INTERLEAVED (pairs
    # (2i, 2i+1)); the loader permutes those weight columns to our
    # split-half rope convention (scores are permutation-invariant)
    rope_interleave: bool = False
    # Gemma-family knobs (model_type "gemma"/"gemma2"): scaled embeddings,
    # (1 + w) RMSNorm, GeGLU activation, explicit attention scale, and the
    # Gemma-2 final-logit softcap
    embed_scale: bool = False      # multiply embeddings by sqrt(hidden)
    norm_unit_offset: bool = False  # rms_norm weight is (1 + w)
    hidden_act: str = "silu"       # "silu" | "gelu_tanh"
    query_pre_attn_scalar: Optional[float] = None  # attn scale override
    final_logit_softcap: Optional[float] = None
    # Gemma-2 only: sandwich norms (post-attention + pre/post-feedforward
    # norms around each residual add), tanh softcap on attention logits,
    # and sliding-window attention on even-indexed layers
    sandwich_norms: bool = False
    attn_logit_softcap: Optional[float] = None
    sliding_window: Optional[int] = None
    dtype: str = "bfloat16"

    @property
    def is_mla(self) -> bool:
        return self.kv_lora_rank > 0

    @property
    def head_dim_(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    @property
    def attn_scale(self) -> float:
        """Attention logit scale: 1/sqrt(head_dim) unless the config pins
        a different denominator (Gemma-2's query_pre_attn_scalar)."""
        denom = self.query_pre_attn_scalar or self.head_dim_
        return 1.0 / (denom ** 0.5)

    @property
    def jax_dtype(self):
        return {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
                "float16": jnp.float16}[self.dtype]

    @classmethod
    def from_hf_config(cls, cfg: dict) -> "ModelConfig":
        mt = cfg.get("model_type", "llama")
        c = cls(
            model_type="mixtral" if mt == "mixtral" else "llama",
            vocab_size=cfg["vocab_size"],
            hidden_size=cfg["hidden_size"],
            intermediate_size=cfg["intermediate_size"],
            num_layers=cfg["num_hidden_layers"],
            num_heads=cfg["num_attention_heads"],
            num_kv_heads=cfg.get("num_key_value_heads",
                                 cfg["num_attention_heads"]),
            head_dim=cfg.get("head_dim"),
            rope_theta=cfg.get("rope_theta", 10000.0),
            rope_scaling=cfg.get("rope_scaling"),
            rms_norm_eps=cfg.get("rms_norm_eps", 1e-5),
            tie_word_embeddings=cfg.get("tie_word_embeddings", False),
        )
        if mt == "mixtral":
            c.num_experts = cfg.get("num_local_experts", 8)
            c.num_experts_per_tok = cfg.get("num_experts_per_tok", 2)
        if mt in ("deepseek_v2", "deepseek_v3"):
            c.model_type = mt
            c.q_lora_rank = cfg.get("q_lora_rank") or 0
            c.kv_lora_rank = cfg.get("kv_lora_rank", 512)
            c.qk_nope_head_dim = cfg.get("qk_nope_head_dim", 128)
            c.qk_rope_head_dim = cfg.get("qk_rope_head_dim", 64)
            c.v_head_dim = cfg.get("v_head_dim", 128)
            c.num_experts = cfg.get("n_routed_experts") or 0
            c.num_experts_per_tok = cfg.get("num_experts_per_tok", 2)
            c.rope_interleave = cfg.get("rope_interleave", True)
            if c.num_experts > 0:
                c.moe_router = mt
                c.n_shared_experts = cfg.get("n_shared_experts") or 0
                c.first_k_dense_replace = cfg.get("first_k_dense_replace",
                                                  0)
                c.moe_intermediate_size = cfg.get("moe_intermediate_size")
                c.routed_scaling_factor = cfg.get("routed_scaling_factor",
                                                  1.0)
                c.norm_topk_prob = cfg.get("norm_topk_prob", False)
                if mt == "deepseek_v2" and c.norm_topk_prob:
                    # The installed transformers DeepseekV2MoEGate ignores
                    # this flag (always scales, never renormalizes) while
                    # DeepSeek's remote-code gate renormalizes instead of
                    # scaling — two conflicting oracles, and no published
                    # V2 checkpoint sets it. Reject loudly rather than
                    # silently diverging from either.
                    raise NotImplementedError(
                        "deepseek_v2 with norm_topk_prob=true is not "
                        "supported (conflicting reference semantics)")
                if mt == "deepseek_v3" or cfg.get(
                        "topk_method", "greedy") != "greedy":
                    # v2 "greedy" routes without group limiting; v3 is
                    # always group-limited (noaux_tc)
                    c.n_group = cfg.get("n_group") or 0
                    c.topk_group = cfg.get("topk_group") or 0
        if mt == "qwen2":
            c.model_type = "llama"  # same decoder shape (GQA + SwiGLU)
            c.attn_bias = True      # qwen2 keeps bias on q/k/v projections
        if mt in ("qwen3", "qwen3_moe"):
            # Qwen3 = Llama GQA + per-head q/k RMSNorm (no qkv bias);
            # the MoE variant routes Mixtral-style (softmax-then-top-k ==
            # top-k-then-softmax after renorm) with its own expert width
            c.model_type = "qwen3"
            c.qk_norm = True
            if mt == "qwen3_moe":
                if not cfg.get("norm_topk_prob", False):
                    # our dense-over-experts MoE normalizes the top-k
                    # weights (softmax over the selected logits); the
                    # un-renormalized variant would silently diverge
                    raise NotImplementedError(
                        "qwen3_moe with norm_topk_prob=false is not "
                        "supported (router weights are renormalized)")
                if (cfg.get("decoder_sparse_step", 1) != 1
                        or cfg.get("mlp_only_layers")):
                    # every layer is treated as MoE; interleaved dense
                    # layers would need per-layer MLP selection
                    raise NotImplementedError(
                        "qwen3_moe with dense layers interleaved "
                        "(decoder_sparse_step != 1 or mlp_only_layers) "
                        "is not supported")
                c.num_experts = cfg.get("num_experts", 128)
                c.num_experts_per_tok = cfg.get("num_experts_per_tok", 8)
                c.intermediate_size = cfg["moe_intermediate_size"]
        if mt in ("gemma", "gemma2"):
            # Gemma rides the Llama GQA stack with four semantic switches
            c.model_type = "gemma"
            c.embed_scale = True
            c.norm_unit_offset = True
            c.hidden_act = "gelu_tanh"
            c.tie_word_embeddings = cfg.get("tie_word_embeddings", True)
            if mt == "gemma2":
                # Gemma-2 adds sandwich norms (post-attention norm on the
                # attention output, pre/post-feedforward norms), sliding-
                # window attention on even layers, logit softcaps, and an
                # explicit attention-scale denominator
                c.model_type = "gemma2"
                c.sandwich_norms = True
                c.sliding_window = cfg.get("sliding_window", 4096)
                c.attn_logit_softcap = cfg.get("attn_logit_softcapping")
                c.final_logit_softcap = cfg.get("final_logit_softcapping")
                c.query_pre_attn_scalar = cfg.get("query_pre_attn_scalar")
        return c

    @classmethod
    def from_local_path(cls, path: str) -> "ModelConfig":
        with open(os.path.join(path, "config.json")) as f:
            return cls.from_hf_config(json.load(f))

    @classmethod
    def tiny(cls, **overrides) -> "ModelConfig":
        """A CPU-testable configuration (vocab matches ByteTokenizer)."""
        base = dict(vocab_size=512, hidden_size=64, intermediate_size=128,
                    num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
                    rope_theta=10000.0, dtype="float32")
        base.update(overrides)
        return cls(**base)

    @classmethod
    def llama3_8b(cls) -> "ModelConfig":
        return cls()  # defaults above are Llama-3-8B

    @classmethod
    def llama3_70b(cls) -> "ModelConfig":
        return cls(hidden_size=8192, intermediate_size=28672, num_layers=80,
                   num_heads=64, num_kv_heads=8)

    @classmethod
    def mixtral_8x7b(cls) -> "ModelConfig":
        return cls(model_type="mixtral", vocab_size=32000, hidden_size=4096,
                   intermediate_size=14336, num_layers=32, num_heads=32,
                   num_kv_heads=8, rope_theta=1e6, num_experts=8,
                   num_experts_per_tok=2)
