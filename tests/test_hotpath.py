"""dynaturbo hot-path tests (ISSUE 16): token identity across every
scheduler arm with the hot-path optimizations on vs off, zero post-warmup
compiles under default AND exotic warmed_grid configs, async-detok
ordering/cancellation, the cost_diff evidence tool, and the CPU hotpath
bench smoke so the evidence pipeline itself can't silently rot."""

import asyncio
import json

import numpy as np
import pytest

from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
from dynamo_tpu.llm.backend import Backend
from dynamo_tpu.llm.protocols.common import (EngineOutput,
                                             PreprocessedRequest,
                                             SamplingOptions,
                                             StopConditions)
from dynamo_tpu.llm.tokenizer import ByteTokenizer
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.runtime import Context

LEGACY = dict(overlap_idle_prefill=False, coalesce_window_emissions=False,
              cache_sampler_params=False, admit_in_step=False)


def _ecfg(**kw):
    base = dict(page_size=4, num_pages=64, max_batch=4, prefill_chunk=32,
                prefill_buckets=(32,), batch_buckets=(4,),
                page_buckets=(16,))
    base.update(kw)
    return EngineConfig(**base)


def _req(tokens, mt=10, eos=(), **sampling):
    return PreprocessedRequest(
        token_ids=list(tokens), sampling=SamplingOptions(**sampling),
        stop=StopConditions(max_tokens=mt, ignore_eos=not eos),
        eos_token_ids=list(eos))


async def _collect(engine, req):
    toks, fin = [], None
    async for out in engine.generate(req, Context()):
        toks.extend(out.token_ids)
        if out.finish_reason:
            fin = out.finish_reason
            break
    return toks, fin


def _mixed_requests():
    """greedy, penalties, logit_bias, and a seeded sampled row — the
    pinned token-identity surface (unseeded sampling is exempt by
    design: the sampler-param cache freezes its build-time reseeds)."""
    rng = np.random.RandomState(11)
    p = [rng.randint(1, 400, n).tolist() for n in (8, 15, 22, 30)]
    return [
        _req(p[0]),
        _req(p[1], repetition_penalty=1.3, frequency_penalty=0.4),
        _req(p[2], logit_bias={7: -100.0, 19: 4.0}),
        _req(p[3], temperature=0.8, top_k=16, seed=123),
    ]


@pytest.mark.parametrize("arm", ["single", "windowed", "pipelined"])
def test_token_identity_optimizations_on_off(run_async, arm):
    """Every scheduler arm must emit bit-identical tokens with the
    dynaturbo optimizations on (defaults) and off (legacy)."""
    arm_kw = {"single": dict(decode_steps=1),
              "windowed": dict(decode_steps=4, pipeline_decode=False),
              "pipelined": dict(decode_steps=4, pipeline_decode=True)}[arm]
    cfg = ModelConfig.tiny()

    async def gen_all(engine):
        outs = await asyncio.gather(
            *(_collect(engine, r) for r in _mixed_requests()))
        await engine.stop()
        return outs

    results = {}
    for name, toggles in (("legacy", LEGACY), ("new", {})):
        eng = JaxEngine(cfg, _ecfg(**arm_kw, **toggles), seed=0)
        results[name] = run_async(gen_all(eng))
    assert results["legacy"] == results["new"]
    assert all(len(t) == 10 and f == "length"
               for t, f in results["new"])


def test_token_identity_spec_arm(run_async):
    """Spec-decode arm: same identity contract (admission moved into the
    step; the spec step itself is untouched)."""
    cfg = ModelConfig.tiny()
    prompt = [5, 6, 7, 5, 6, 7, 5, 6] * 3  # spec-friendly motif

    async def gen(engine):
        out = await _collect(engine, _req(prompt, mt=12))
        await engine.stop()
        return out

    results = {}
    for name, toggles in (("legacy", LEGACY), ("new", {})):
        eng = JaxEngine(cfg, _ecfg(page_size=8, spec_decode=True,
                                   spec_tokens=2, decode_steps=2,
                                   **toggles), seed=0)
        results[name] = run_async(gen(eng))
    assert results["legacy"] == results["new"]
    assert len(results["new"][0]) == 12


def test_stop_string_identity_through_backend(run_async):
    """e2e stop-string arm: Backend + real engine. A stop string cut from
    the free-running text must truncate identically (text and finish
    reason) with the optimizations on and off."""
    cfg = ModelConfig.tiny()
    tok = ByteTokenizer()

    async def gen(toggles, stop):
        eng = JaxEngine(cfg, _ecfg(decode_steps=4, **toggles), seed=0)
        be = Backend(eng, tok)
        req = PreprocessedRequest(
            token_ids=list(range(60, 80)), sampling=SamplingOptions(),
            stop=StopConditions(max_tokens=16, ignore_eos=True,
                                stop=stop),
            eos_token_ids=[])
        text, fin = "", None
        async for out in be.generate(req, Context()):
            text += out.text or ""
            if out.finish_reason:
                fin = out.finish_reason
                break
        await eng.stop()
        return text, fin

    free, fin = run_async(gen({}, None))
    assert fin == "length" and len(free) > 4
    needle = free[2:5]
    a = run_async(gen(LEGACY, [needle]))
    b = run_async(gen({}, [needle]))
    assert a == b
    assert b[1] == "stop" and needle not in b[0]


def _run_fence_grid(run_async, name, ecfg):
    """post_warmup_compiles must stay 0 while serving a mixed workload on
    the given warmed grid — the warmed_grid() enumeration must cover the
    coalesced window's emitted-counts output too."""
    cfg = ModelConfig.tiny()
    eng = JaxEngine(cfg, ecfg, seed=0)
    eng.warmup()
    assert eng.fence.armed

    async def main():
        # no penalty rows here: the penalized window variant is
        # deliberately NOT warmed (a first penalty request pays one
        # compile per bucket, by documented contract)
        reqs = [_req(list(range(1, 20)), mt=9),
                _req(list(range(30, 64)), mt=7),
                _req([9, 9, 9, 9, 9, 9], mt=6,
                     temperature=0.9, seed=3)]
        outs = await asyncio.gather(*(_collect(eng, r) for r in reqs))
        await eng.stop()
        return outs

    outs = run_async(main())
    assert all(len(t) >= 6 for t, _ in outs)
    assert eng.stats()["post_warmup_compiles_total"] == 0, (
        f"{name} grid compiled mid-serving")
    eng.fence.disarm()


def test_fence_zero_default_grid(run_async):
    _run_fence_grid(run_async, "default", _ecfg(decode_steps=4))


@pytest.mark.slow
def test_fence_zero_exotic_grid(run_async):
    """Exotic grid: prefill_chunk above the largest prefill bucket,
    max_batch off the bucket list, odd window length."""
    _run_fence_grid(run_async, "exotic", EngineConfig(
        page_size=4, num_pages=64, max_batch=3, prefill_chunk=48,
        prefill_buckets=(16, 32), batch_buckets=(1, 2),
        page_buckets=(8, 16), max_prefill_batch=2, decode_steps=5))


class _ChunkEngine:
    """Fake engine: yields pre-cut token chunks with tiny await points, so
    Backend chunk handling interleaves across concurrent streams."""

    def __init__(self, chunks):
        self.chunks = chunks

    async def generate(self, request, context):
        for c in self.chunks:
            await asyncio.sleep(0)
            yield EngineOutput(token_ids=list(c))
        yield EngineOutput(token_ids=[], finish_reason="length")


def test_async_detok_ordering_under_concurrency(run_async):
    """DYN_ASYNC_DETOK (default on): per-request chunk texts must come
    back in chunk order and concatenate to exactly the inline decode of
    the same ids, across many concurrent streams."""
    tok = ByteTokenizer()
    texts = [f"stream-{i}: héllo wörld →🌍 {'x' * i}" for i in range(6)]

    async def one(text):
        ids = tok.encode(text, add_special_tokens=False)
        chunks = [ids[j:j + 3] for j in range(0, len(ids), 3)]
        be = Backend(_ChunkEngine(chunks), tok)
        req = PreprocessedRequest(
            token_ids=[1], sampling=SamplingOptions(),
            stop=StopConditions(max_tokens=len(ids) + 1, ignore_eos=True),
            eos_token_ids=[])
        parts = []
        async for out in be.generate(req, Context()):
            if out.text:
                parts.append(out.text)
            if out.finish_reason:
                break
        return parts

    async def main():
        return await asyncio.gather(*(one(t) for t in texts))

    all_parts = run_async(main())
    for text, parts in zip(texts, all_parts):
        assert "".join(parts) == text
        assert "�" not in "".join(parts)


def test_async_detok_cancellation_isolated(run_async):
    """Cancelling one stream mid-decode must not corrupt or stall a
    concurrent stream sharing the detok executor."""
    tok = ByteTokenizer()
    text = "the quick brown fox jumps over the lazy dog " * 4

    async def victim():
        ids = tok.encode(text, add_special_tokens=False)
        be = Backend(_ChunkEngine([ids[j:j + 2]
                                   for j in range(0, len(ids), 2)]), tok)
        req = PreprocessedRequest(
            token_ids=[1], sampling=SamplingOptions(),
            stop=StopConditions(max_tokens=len(ids) + 1, ignore_eos=True),
            eos_token_ids=[])
        got = ""
        async for out in be.generate(req, Context()):
            got += out.text or ""
            await asyncio.sleep(0)  # cancellation window
            if out.finish_reason:
                break
        return got

    async def main():
        t1 = asyncio.ensure_future(victim())
        t2 = asyncio.ensure_future(victim())
        await asyncio.sleep(0.01)
        t1.cancel()
        survivor = await t2
        with pytest.raises(asyncio.CancelledError):
            await t1
        return survivor

    assert run_async(main()) == text


def _bench_record(disp, dev, extra_bucket=None, **headline):
    buckets = {"decode_window:4x16x4": {
        "samples": 10, "dispatch_us": disp, "device_us": dev,
        "tokens_per_s": 1000.0}}
    if extra_bucket:
        buckets[extra_bucket] = {"samples": 2, "dispatch_us": 5.0,
                                 "device_us": 1.0, "tokens_per_s": 0.0}
    detail = {"bucket_cost": buckets, "itl_raw_chunk_p99_ms": 10.0,
              "loop_lag_p99_ms": 2.0, "post_warmup_compiles": 0}
    detail.update(headline)
    return {"metric": "m", "value": 1.0, "unit": "ms", "detail": detail}


def test_cost_diff_tool(tmp_path, capsys):
    from tools import cost_diff

    before = _bench_record(100.0, 50.0, itl_raw_chunk_p99_ms=12.0)
    after = _bench_record(60.0, 50.0, extra_bucket="admit:host",
                          itl_raw_chunk_p99_ms=9.0)
    diff = cost_diff.diff_reports(before, after)
    by_bucket = {r["bucket"]: r for r in diff["buckets"]}
    assert by_bucket["decode_window:4x16x4"]["dispatch_us_delta"] == -40.0
    assert by_bucket["decode_window:4x16x4"]["device_us_delta"] == 0.0
    # one-sided bucket: missing side stays None, no crash
    assert by_bucket["admit:host"]["dispatch_us_before"] is None
    assert by_bucket["admit:host"]["dispatch_us_delta"] is None
    assert diff["headline"]["itl_raw_chunk_p99_ms"]["delta"] == -3.0

    bf, af = tmp_path / "b.json", tmp_path / "a.json"
    bf.write_text(json.dumps(before))
    af.write_text(json.dumps(after))
    assert cost_diff.main([str(bf), str(af)]) == 0
    out = capsys.readouterr().out
    assert "decode_window:4x16x4" in out and "-40.0" in out
    assert cost_diff.main(["--json", str(bf), str(af)]) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["headline"]["itl_raw_chunk_p99_ms"]["after"] == 9.0
    # reports without a cost table are a hard error, not an empty diff
    nf = tmp_path / "n.json"
    nf.write_text(json.dumps({"metric": "m", "detail": {}}))
    assert cost_diff.main([str(nf), str(nf)]) == 1


def test_hotpath_scenario_cpu_smoke():
    """CI smoke for the evidence pipeline: the CPU hotpath scenario must
    produce ONE record with a non-empty per-bucket cost table,
    post_warmup_compiles == 0, and itl_raw_chunk_p99_ms present."""
    import sys

    import bench

    argv = sys.argv
    sys.argv = ["bench.py", "--cpu", "--model", "tiny",
                "--scenario", "hotpath", "--requests", "4",
                "--concurrency", "2", "--isl", "48", "--osl", "24",
                "--decode-steps", "4"]
    try:
        args = bench.parse_args()
    finally:
        sys.argv = argv
    record = bench._run_scenario(args)
    detail = record["detail"]
    assert record["unit"] == "ms"
    assert isinstance(record["value"], (int, float))
    assert detail["bucket_cost"], "cost table empty — --prof-sample rot"
    assert any(k.startswith("decode_window:")
               for k in detail["bucket_cost"])
    assert detail["post_warmup_compiles"] == 0
    assert "itl_raw_chunk_p99_ms" in detail
    assert "loop_lag_p99_ms" in detail


# ------------------------- dynahot DL022 fix regressions (ISSUE 18)


def test_sequence_stop_set_cached_once():
    """The per-token stop check reads ONE cached frozenset (built on
    first access) instead of rebuilding `x or []` defaults per token —
    later mutation of the request's lists must not change it (proves
    the cache is actually hit, not rebuilt)."""
    from dynamo_tpu.engine.jax_engine import Sequence

    req = _req([1, 2, 3], mt=10, eos=(7,))
    req.stop.stop_token_ids = [9]
    seq = Sequence(req=req, context=Context(), out=asyncio.Queue(),
                   tokens=[1, 2, 3], num_prompt=3)
    first = seq.stop_set
    assert first == frozenset({7, 9})
    assert seq.dev_stop_count == 2
    req.stop.stop_token_ids.append(11)   # post-hoc mutation: ignored
    assert seq.stop_set is first
    assert seq.dev_stop_count == 2


def test_sequence_stop_set_respects_ignore_eos():
    from dynamo_tpu.engine.jax_engine import Sequence

    req = _req([1], mt=10, eos=(7,))
    req.stop.ignore_eos = True
    req.stop.stop_token_ids = [9]
    seq = Sequence(req=req, context=Context(), out=asyncio.Queue(),
                   tokens=[1], num_prompt=1)
    assert seq.stop_set == frozenset({9})
    assert seq.dev_stop_count == 1


def test_emit_routes_by_thread_id_without_exception_probe():
    """_emit's on/off-loop routing is one thread-id compare: on the
    captured thread it puts directly; off it goes through
    call_soon_threadsafe; with no captured tid (engine not started) it
    puts directly — and no asyncio loop probe is involved at all."""
    import threading
    import types

    from dynamo_tpu.engine.jax_engine import JaxEngine, Sequence

    calls = []
    fake_loop = types.SimpleNamespace(
        call_soon_threadsafe=lambda fn, *a: calls.append(a))
    q = asyncio.Queue()
    seq = Sequence(req=_req([1]), context=Context(), out=q,
                   tokens=[1], num_prompt=1)
    eng = types.SimpleNamespace(
        latency=types.SimpleNamespace(observe=lambda *a, **k: None),
        _aio_loop=fake_loop, _aio_loop_tid=threading.get_ident())
    out = EngineOutput(token_ids=[5], prompt_tokens=1)
    JaxEngine._emit(eng, seq, out)          # on-thread: direct put
    assert q.qsize() == 1 and not calls
    eng._aio_loop_tid = threading.get_ident() + 1
    JaxEngine._emit(eng, seq, out)          # off-thread: via the loop
    assert q.qsize() == 1 and len(calls) == 1
    eng._aio_loop_tid = None
    JaxEngine._emit(eng, seq, out)          # pre-start: direct put
    assert q.qsize() == 2 and len(calls) == 1


def test_router_decision_overlap_consistent():
    """KvScheduler.schedule reads the chosen worker's capped overlap
    once: the decision record, the optimistic accounting, and the
    hit-rate event must all carry the SAME value."""
    from dynamo_tpu.llm.kv_router.indexer import OverlapScores
    from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics
    from dynamo_tpu.llm.kv_router.scheduler import KvScheduler

    events = []
    sched = KvScheduler(block_size=16, on_hit_rate_event=events.append)
    sched.update_metrics({1: ForwardPassMetrics(
        request_active_slots=0, request_total_slots=8,
        kv_active_blocks=0, kv_total_blocks=100)})
    chosen = sched.schedule(64, OverlapScores({1: 2}), request_id="r1")
    assert chosen == 1
    dec = sched.decisions[-1]
    expect = min(2, (64 + 15) // 16)
    assert dec["overlap_blocks"] == expect
    assert events[-1].overlap_blocks == expect
    assert sched.workers[1].extra_blocks == (64 + 15) // 16 - expect
