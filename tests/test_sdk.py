"""Serving SDK (reference deploy/dynamo/sdk tests: pipeline.py, link.py,
e2e.py — graph-link semantics + end-to-end pipelines)."""

import pytest

from dynamo_tpu.sdk import (ServiceConfig, deploy_inline, depends,
                            dynamo_endpoint, service)
from dynamo_tpu.sdk.service import DynamoService


def make_graph():
    @service(dynamo={"namespace": "t"})
    class Backend:
        def __init__(self):
            self.prefix = self.service_config.get("prefix", "b")

        @dynamo_endpoint()
        async def generate(self, req):
            for i in range(3):
                yield f"{self.prefix}{i}-{req}"

    @service(dynamo={"namespace": "t"}, workers=1)
    class Middle:
        backend = depends(Backend)

        @dynamo_endpoint()
        async def generate(self, req):
            stream = await self.backend.round_robin(req)
            async for env in stream:
                yield f"m:{env.data}"

    @service(dynamo={"namespace": "t"})
    class Frontend:
        middle = depends(Middle)

        @dynamo_endpoint()
        async def generate(self, req):
            stream = await self.middle.round_robin(req)
            async for env in stream:
                yield f"f:{env.data}"

    return Backend, Middle, Frontend


def test_service_decorator_introspection():
    Backend, Middle, Frontend = make_graph()
    assert isinstance(Frontend, DynamoService)
    assert [e.name for e in Backend.endpoints] == ["generate"]
    assert Backend.endpoints[0].is_default
    assert Middle.depends_attrs == {"backend": Backend}
    assert Frontend.endpoint_address() == "dyn://t.Frontend.generate"


def test_graph_discovery_depends_and_link():
    Backend, Middle, Frontend = make_graph()
    graph = Frontend.graph()
    # dependency-first order
    names = [s.name for s in graph]
    assert names.index("Backend") < names.index("Middle") < names.index(
        "Frontend")

    @service(dynamo={"namespace": "t"})
    class Extra:
        @dynamo_endpoint()
        async def generate(self, req):
            yield req

    # link() activates an edge not present via depends and chains
    assert Frontend.link(Extra) is Extra
    assert Extra in [s for s in Frontend.graph()]


def test_sdk_pipeline_e2e(run_async):
    """Whole 3-stage pipeline served + called through the runtime
    (reference sdk/tests/e2e.py)."""
    Backend, Middle, Frontend = make_graph()
    cfg = ServiceConfig({"Backend": {"prefix": "X"}})

    async def scenario():
        dep = await deploy_inline(Frontend, config=cfg)
        client = await dep.client(Frontend)
        await client.wait_for_instances()
        stream = await client.round_robin("q")
        out = [env.data async for env in stream]
        await client.close()
        await dep.stop()
        await dep.drt.shutdown()
        return out

    out = run_async(scenario())
    # config injection reached Backend (prefix X), both hops wrapped
    assert out == [f"f:m:X{i}-q" for i in range(3)]


def test_unwired_dependency_raises():
    Backend, Middle, _ = make_graph()
    inst = object.__new__(Middle.cls)
    with pytest.raises(RuntimeError, match="not wired"):
        _ = inst.backend
