"""MLA (DeepSeek-style multi-head latent attention) model family: the
absorbed/paged path must match the materialized full-attention oracle, and
the engine must serve it end-to-end through the registry."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.models import mla
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.llama import DROP_SLOT, KVCacheSpec


def tiny_mla(**over):
    base = dict(model_type="deepseek_v2", vocab_size=512, hidden_size=64,
                intermediate_size=128, num_layers=2, num_heads=4,
                num_kv_heads=4, kv_lora_rank=16, qk_nope_head_dim=16,
                qk_rope_head_dim=8, v_head_dim=16, q_lora_rank=0,
                rope_theta=10000.0, dtype="float32")
    base.update(over)
    return ModelConfig(**base)


@pytest.mark.parametrize("q_lora", [0, 24])
def test_mla_paged_prefill_matches_reference(q_lora):
    cfg = tiny_mla(q_lora_rank=q_lora)
    params = mla.init_params(cfg, jax.random.PRNGKey(0))
    B, T, ps = 2, 16, 8
    tokens = jnp.asarray(np.random.RandomState(0).randint(1, 500, (B, T)),
                         jnp.int32)
    ref = mla.reference_forward(params, cfg, tokens)

    kv_c, kv_r = mla.init_kv_cache(cfg, KVCacheSpec(num_pages=8,
                                                    page_size=ps))
    prefill, _ = mla.make_step_fns(cfg)
    table = np.zeros((B, 4), np.int32)
    slots = np.zeros((B, T), np.int32)
    for b in range(B):
        table[b, :2] = [1 + 2 * b, 2 + 2 * b]
        for t in range(T):
            slots[b, t] = table[b, t // ps] * ps + t % ps
    positions = np.broadcast_to(np.arange(T, dtype=np.int32), (B, T))
    logits, kv_c, kv_r = prefill(params, tokens, jnp.asarray(positions),
                                 kv_c, kv_r, jnp.asarray(table),
                                 jnp.asarray(slots),
                                 jnp.full((B,), T - 1, np.int32))
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref[:, -1]),
                               rtol=2e-4, atol=2e-4)


def test_mla_decode_matches_reference_continuation():
    cfg = tiny_mla()
    params = mla.init_params(cfg, jax.random.PRNGKey(1))
    B, T, ps = 1, 8, 8
    rng = np.random.RandomState(1)
    tokens = rng.randint(1, 500, (B, T + 4)).astype(np.int32)
    prefill, decode = mla.make_step_fns(cfg)
    kv_c, kv_r = mla.init_kv_cache(cfg, KVCacheSpec(num_pages=8,
                                                    page_size=ps))
    table = np.asarray([[1, 2]], np.int32)
    slots = np.asarray([[ps + t for t in range(T)]], np.int32)
    positions = np.arange(T, dtype=np.int32)[None]
    logits, kv_c, kv_r = prefill(
        params, jnp.asarray(tokens[:, :T]), jnp.asarray(positions),
        kv_c, kv_r, jnp.asarray(table), jnp.asarray(slots),
        jnp.asarray([T - 1], np.int32))
    # decode the next 4 (teacher-forced) tokens one at a time
    for i in range(4):
        pos = T + i
        slot = np.asarray([table[0, pos // ps] * ps + pos % ps], np.int32)
        logits, kv_c, kv_r = decode(
            params, jnp.asarray(tokens[:, pos]),
            jnp.asarray([pos], np.int32), kv_c, kv_r,
            jnp.asarray(table), jnp.asarray(slot))
    ref = mla.reference_forward(params, cfg, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref[:, -1]),
                               rtol=3e-4, atol=3e-4)


def test_mla_cache_is_compact():
    """The latent cache must be far smaller than an equivalent GQA cache
    (the point of MLA on HBM-bound decode)."""
    cfg = tiny_mla()
    spec = KVCacheSpec(num_pages=8, page_size=8)
    lat, rope = mla.cache_shapes(cfg, spec)
    mla_bytes = np.prod(lat) + np.prod(rope)
    gqa_bytes = 2 * np.prod((cfg.num_layers, 8, cfg.num_kv_heads, 8,
                             cfg.qk_nope_head_dim))
    assert mla_bytes < gqa_bytes


def test_engine_serves_mla(run_async):
    from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
    from dynamo_tpu.llm.protocols.common import (PreprocessedRequest,
                                                 SamplingOptions,
                                                 StopConditions)
    from dynamo_tpu.runtime.engine import Context

    cfg = tiny_mla()
    ecfg = EngineConfig(page_size=8, num_pages=64, max_batch=4,
                        prefill_chunk=32, prefill_buckets=(32,),
                        batch_buckets=(4,), page_buckets=(8,))
    engine = JaxEngine(cfg, ecfg, seed=0)

    async def scenario():
        req = PreprocessedRequest(
            token_ids=list(range(1, 20)), sampling=SamplingOptions(),
            stop=StopConditions(max_tokens=8, ignore_eos=True),
            eos_token_ids=[])
        toks = []
        async for out in engine.generate(req, Context()):
            toks.extend(out.token_ids)
            if out.finish_reason:
                break
        # determinism under greedy: same prompt, same continuation
        toks2 = []
        async for out in engine.generate(req, Context()):
            toks2.extend(out.token_ids)
            if out.finish_reason:
                break
        await engine.stop()
        return toks, toks2

    toks, toks2 = run_async(scenario())
    assert len(toks) == 8 and toks == toks2


def test_mla_tp_sharding_compiles():
    """MLA params shard over the model axis and one prefill step executes
    on an 8-device mesh."""
    from dynamo_tpu.parallel.mesh import MeshSpec, shard_params

    cfg = tiny_mla()
    mesh = MeshSpec(model=2, data=4).build()
    params = shard_params(mla.init_params(cfg, jax.random.PRNGKey(0)),
                          cfg, mesh)
    prefill, _ = mla.make_step_fns(cfg)
    B, T, ps = 4, 8, 8
    kv_c, kv_r = mla.init_kv_cache(cfg, KVCacheSpec(num_pages=16,
                                                    page_size=ps))
    from dynamo_tpu.parallel.mesh import shard_kv_cache

    kv_c, kv_r = shard_kv_cache(kv_c, kv_r, cfg, mesh)
    tokens = np.random.RandomState(0).randint(1, 500, (B, T)).astype(np.int32)
    table = np.zeros((B, 2), np.int32)
    slots = np.full((B, T), DROP_SLOT, np.int32)
    for b in range(B):
        table[b, 0] = 1 + b
        slots[b] = [(1 + b) * ps + t for t in range(T)]
    positions = np.broadcast_to(np.arange(T, dtype=np.int32), (B, T))
    logits, kv_c, kv_r = prefill(
        params, jnp.asarray(tokens), jnp.asarray(positions), kv_c, kv_r,
        jnp.asarray(table), jnp.asarray(slots),
        jnp.full((B,), T - 1, np.int32))
    assert np.isfinite(np.asarray(logits)).all()


def test_mla_ring_long_prefill_matches_reference():
    """Latent-only ring exchange (VERDICT r3 task 7): the MLA
    sequence-parallel prefill on a seq=4 mesh matches the materialized
    full-attention oracle's last-position logits, and its c/r streams
    match the paged prefill pools."""
    from dynamo_tpu.parallel.mesh import MeshSpec
    from dynamo_tpu.parallel.ring_attention import make_mla_long_prefill_fn

    cfg = tiny_mla()
    params = mla.init_params(cfg, jax.random.PRNGKey(3))
    B, T = 1, 32
    tokens = np.random.RandomState(3).randint(1, 500, (B, T)).astype(np.int32)
    pos = np.broadcast_to(np.arange(T, dtype=np.int32), (B, T))
    ref = mla.reference_forward(params, cfg, jnp.asarray(tokens))

    mesh = MeshSpec(seq=4).build()
    fn = make_mla_long_prefill_fn(cfg, mesh)
    with jax.set_mesh(mesh):
        logits, c_all, r_all = fn(params, jnp.asarray(tokens),
                                  jnp.asarray(pos))
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref[:, -1]),
                               rtol=2e-4, atol=2e-4)
    assert c_all.shape == (cfg.num_layers, B, T, 1, cfg.kv_lora_rank)
    assert r_all.shape == (cfg.num_layers, B, T, 1, cfg.qk_rope_head_dim)

    # the ring-produced streams equal what the paged prefill writes
    ps = 8
    kv_c, kv_r = mla.init_kv_cache(cfg, KVCacheSpec(num_pages=8,
                                                    page_size=ps))
    prefill, _ = mla.make_step_fns(cfg)
    table = np.zeros((B, 4), np.int32)
    slots = np.zeros((B, T), np.int32)
    for b in range(B):
        table[b] = np.arange(1 + 4 * b, 5 + 4 * b)
        for t in range(T):
            slots[b, t] = table[b, t // ps] * ps + t % ps
    _, kv_c, kv_r = prefill(params, jnp.asarray(tokens), jnp.asarray(pos),
                            kv_c, kv_r, jnp.asarray(table),
                            jnp.asarray(slots),
                            jnp.full((B,), T - 1, np.int32))
    for t in range(T):
        page, off = table[0][t // ps], t % ps
        np.testing.assert_allclose(np.asarray(c_all[:, 0, t, 0]),
                                   np.asarray(kv_c[:, page, 0, off]),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(np.asarray(r_all[:, 0, t, 0]),
                                   np.asarray(kv_r[:, page, 0, off]),
                                   rtol=2e-5, atol=2e-5)


def test_mla_long_prompt_takes_ring_path(run_async):
    """MLA engine on a seq mesh routes long prompts through the latent
    ring prefill and the continuation is token-identical to the ordinary
    chunked-prefill MLA engine."""
    from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
    from dynamo_tpu.llm.protocols.common import (PreprocessedRequest,
                                                 SamplingOptions,
                                                 StopConditions)
    from dynamo_tpu.parallel.mesh import MeshSpec
    from dynamo_tpu.runtime.engine import Context

    cfg = tiny_mla()
    params = mla.init_params(cfg, jax.random.PRNGKey(4))
    prompt = [(i * 13) % 200 + 1 for i in range(40)]

    async def gen(engine):
        req = PreprocessedRequest(
            token_ids=list(prompt), sampling=SamplingOptions(),
            stop=StopConditions(max_tokens=6, ignore_eos=True),
            eos_token_ids=[])
        toks = []
        async for out in engine.generate(req, Context()):
            toks.extend(out.token_ids)
            if out.finish_reason:
                break
        await engine.stop()
        return toks

    base_ecfg = dict(page_size=4, num_pages=64, max_batch=4,
                     prefill_chunk=32, prefill_buckets=(32,),
                     batch_buckets=(4,), page_buckets=(16,))
    want = run_async(gen(JaxEngine(cfg, EngineConfig(**base_ecfg),
                                   params=params)))

    mesh = MeshSpec(seq=4).build()
    engine = JaxEngine(cfg, EngineConfig(long_prefill_threshold=16,
                                         **base_ecfg),
                       params=params, mesh=mesh)
    got = run_async(gen(engine))
    assert engine.long_prefills_total == 1, "ring path not taken"
    assert got == want
