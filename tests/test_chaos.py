"""dynaguard: deadlines, retry policy, circuit breakers, chaos injection.

The acceptance contract (ISSUE 7): under injected prefill crash, transfer
sever, and worker blackout, every request either completes within its
deadline or fails fast with a TYPED error (HTTP 504/503, finish_reason
"timeout") — zero hangs, zero waits that outlive the request budget; all
breaker transitions deterministic under an injected clock; everything on
CPU against the REAL transports (DCP + TCP call-home + KV transfer).
"""

import asyncio
import time

import pytest

from dynamo_tpu.runtime import guard
from dynamo_tpu.runtime.engine import Context


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture(autouse=True)
def _no_ambient_chaos():
    """Each test opts into chaos explicitly; none leaks between tests."""
    guard.set_chaos(None)
    yield
    guard.set_chaos(None)


# ------------------------------------------------------------------ deadline


def test_deadline_decrements_and_expires():
    clk = FakeClock()
    d = guard.Deadline.after_ms(1000, clock=clk)
    assert not d.expired and d.remaining_ms() == 1000
    clk.advance(0.4)
    assert 599 <= d.to_wire_ms() <= 600       # hop re-stamps what is left
    clk.advance(0.7)
    assert d.expired and d.remaining_s() == 0.0
    assert d.to_wire_ms() == 1                # floor: never "no deadline"
    with pytest.raises(guard.DeadlineExceeded):
        d.check("test")


def test_deadline_wire_roundtrip_and_absent():
    clk = FakeClock()
    assert guard.Deadline.from_wire_ms(None, clock=clk) is None
    assert guard.Deadline.from_wire_ms(0, clock=clk) is None
    d = guard.Deadline.from_wire_ms(250, clock=clk)
    assert d.cap(10.0) == pytest.approx(0.25)
    assert d.cap(0.1) == pytest.approx(0.1)


def test_deadline_is_timeout_error():
    """except asyncio.TimeoutError must catch budget exhaustion."""
    assert issubclass(guard.DeadlineExceeded, asyncio.TimeoutError)


def test_bound_raises_deadline_not_plain_timeout(run_async):
    async def main():
        d = guard.Deadline.after_ms(30)
        with pytest.raises(guard.DeadlineExceeded):
            await guard.bound(asyncio.sleep(5), deadline=d)
        # plain timeout (no deadline) keeps the plain TimeoutError type
        with pytest.raises(asyncio.TimeoutError) as ei:
            await guard.bound(asyncio.sleep(5), timeout=0.01)
        assert not isinstance(ei.value, guard.DeadlineExceeded)

    run_async(main())


def test_context_stopped_includes_expiry():
    clk = FakeClock()
    ctx = Context("r", deadline=guard.Deadline.after_ms(100, clock=clk))
    assert not ctx.stopped and ctx.cancel_reason() == "cancelled"
    clk.advance(0.2)
    assert ctx.stopped and ctx.expired
    assert ctx.cancel_reason() == "timeout"


# -------------------------------------------------------------- retry policy


def test_retry_policy_budget_aware(run_async):
    """Backoffs are decorrelated-jitter bounded, and the policy never
    sleeps (or retries) past the deadline."""
    import random

    slept = []

    async def fake_sleep(s):
        slept.append(s)
        clk.advance(s)

    clk = FakeClock()
    pol = guard.RetryPolicy(max_attempts=5, base_s=0.1, cap_s=0.5,
                            rng=random.Random(0), sleep=fake_sleep)

    async def main():
        # plenty of budget: all attempts run
        attempts = [i async for i in pol.attempts(None)]
        assert attempts == [0, 1, 2, 3, 4]
        assert len(slept) == 4
        assert all(0.1 <= s <= 0.5 for s in slept)
        # tiny budget: first attempt always runs, no retry can be afforded
        slept.clear()
        d = guard.Deadline.after_ms(50, clock=clk)
        attempts = [i async for i in pol.attempts(d)]
        assert attempts == [0] and slept == []

    run_async(main())


def test_retry_run_reraises_last_and_propagates_deadline(run_async):
    async def main():
        pol = guard.RetryPolicy(max_attempts=3, base_s=0.001, cap_s=0.002)
        calls = []

        async def flaky():
            calls.append(1)
            raise ValueError(f"boom {len(calls)}")

        with pytest.raises(ValueError, match="boom 3"):
            await pol.run(flaky, what="flaky")
        assert len(calls) == 3

        async def too_slow():
            raise guard.DeadlineExceeded("spent")

        calls.clear()

        async def once():
            calls.append(1)
            raise guard.DeadlineExceeded("spent")

        with pytest.raises(guard.DeadlineExceeded):
            await pol.run(once)
        assert len(calls) == 1  # deadline errors are never retried

    run_async(main())


# ----------------------------------------------------------- circuit breaker


def test_breaker_transitions_deterministic_under_injected_clock():
    clk = FakeClock()
    br = guard.CircuitBreaker(
        guard.BreakerConfig(threshold=3, probe_every=4, reset_after_s=0.0),
        clock=clk)
    # closed: failures below threshold keep admitting
    assert br.allow()
    br.record_failure()
    br.record_failure()
    assert br.state == guard.BREAKER_CLOSED and br.allow()
    br.record_failure()                      # third consecutive → open
    assert br.state == guard.BREAKER_OPEN and br.opened_total == 1
    # open: denies, then the probe_every-th denial converts to the single
    # half-open probe
    assert [br.allow() for _ in range(3)] == [False, False, False]
    assert br.allow() is True                # 4th call: half-open probe
    assert br.state == guard.BREAKER_HALF_OPEN
    assert br.allow() is False               # single probe: no second admit
    br.record_failure()                      # failed probe → straight open
    assert br.state == guard.BREAKER_OPEN and br.opened_total == 2
    assert [br.allow() for _ in range(3)] == [False] * 3
    assert br.allow() is True                # next probe window
    br.record_success()                      # probe succeeded → closed
    assert br.state == guard.BREAKER_CLOSED
    assert br.failures == 0 and br.allow()


def test_breaker_clock_based_probe():
    clk = FakeClock()
    br = guard.CircuitBreaker(
        guard.BreakerConfig(threshold=1, probe_every=0, reset_after_s=5.0),
        clock=clk)
    br.record_failure()
    assert br.state == guard.BREAKER_OPEN
    assert not br.allow()
    clk.advance(4.9)
    assert not br.allow()
    clk.advance(0.2)                         # reset_after elapsed
    assert br.allow() and br.state == guard.BREAKER_HALF_OPEN
    br.record_success()
    assert br.state == guard.BREAKER_CLOSED


def test_breaker_release_probe_hands_back_the_slot():
    clk = FakeClock()
    br = guard.CircuitBreaker(
        guard.BreakerConfig(threshold=1, probe_every=1), clock=clk)
    br.record_failure()
    assert br.allow() and br.state == guard.BREAKER_HALF_OPEN
    assert not br.allow()
    br.release_probe()                       # picked another instance
    assert br.allow()                        # slot available again


# -------------------------------------------------------------- chaos parser


def test_chaos_spec_parse():
    seed, rules = guard.parse_chaos(
        "seed=42;sever:kv.send@after=1;delay:tcp.send@ms=50,p=0.25;"
        "drop:kv.recv@nth=3,times=1")
    assert seed == 42 and len(rules) == 3
    sever, delay, drop = rules
    assert (sever.action, sever.point, sever.after) == ("sever", "kv.send", 1)
    assert (delay.ms, delay.p) == (50.0, 0.25)
    assert (drop.nth, drop.times) == (3, 1)


def test_chaos_spec_rejects_malformed():
    with pytest.raises(ValueError):
        guard.parse_chaos("explode:kv.send")
    with pytest.raises(ValueError):
        guard.parse_chaos("drop:kv.send@wat=1")


def test_chaos_rules_fire_deterministically(run_async):
    async def main():
        inj = guard.set_chaos("seed=1;drop:x.point@nth=2,times=1")
        await guard.chaos_point("x.point")           # hit 1: no fire
        with pytest.raises(guard.ChaosError):
            await guard.chaos_point("x.point")       # hit 2: drop
        await guard.chaos_point("x.point")           # times=1: spent
        assert inj.injected[("x.point", "drop")] == 1

    run_async(main())


# ---------------------------------------------- engine: deadline frees pages


def _tiny_engine(params=None, seed=2):
    import jax

    from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.models.llama import init_params

    cfg = ModelConfig.tiny(num_heads=4, num_kv_heads=2, head_dim=8,
                           hidden_size=32, vocab_size=128)
    ecfg = EngineConfig(page_size=8, num_pages=64, max_batch=4,
                        prefill_chunk=32, batch_buckets=(1, 2, 4),
                        prefill_buckets=(8, 32), page_buckets=(8,),
                        watermark_pages=2)
    if params is None:
        params = init_params(cfg, jax.random.PRNGKey(seed))
    return JaxEngine(cfg, ecfg, params=params), params


def _req(tokens, max_tokens=6):
    from dynamo_tpu.llm.protocols.common import (PreprocessedRequest,
                                                 SamplingOptions,
                                                 StopConditions)

    return PreprocessedRequest(token_ids=tokens,
                               sampling=SamplingOptions(),
                               stop=StopConditions(max_tokens=max_tokens))


async def _collect(engine, req, ctx):
    toks = []
    async for out in engine.generate(req, ctx):
        toks.extend(out.token_ids)
        if out.finish_reason is not None:
            return toks, out.finish_reason
    return toks, None


def test_engine_expired_at_admission_cancels_and_frees_pages(run_async):
    async def main():
        engine, _ = _tiny_engine()
        clk = FakeClock()
        ctx = Context("exp", deadline=guard.Deadline.after_ms(5, clock=clk))
        clk.advance(1.0)                       # expired before admission
        baseline = engine.pm.active
        toks, fin = await _collect(engine, _req(list(range(1, 20))), ctx)
        assert fin == "timeout" and toks == []
        assert engine.pm.active == baseline    # nothing leaked
        await engine.stop()

    run_async(main())


def test_engine_mid_decode_expiry_finishes_timeout_and_frees(run_async):
    async def main():
        engine, _ = _tiny_engine()
        clk = FakeClock()
        ctx = Context("mid", deadline=guard.Deadline.after_ms(1000,
                                                              clock=clk))
        baseline = engine.pm.active
        toks = []
        fin = None
        async for out in engine.generate(_req(list(range(1, 20)),
                                              max_tokens=64), ctx):
            toks.extend(out.token_ids)
            if out.finish_reason is not None:
                fin = out.finish_reason
                break
            if len(toks) >= 2:
                clk.advance(2.0)               # budget dies mid-decode
        assert fin == "timeout"
        assert 0 < len(toks) < 64
        # pages free on the cancel path (give the loop a tick to settle)
        for _ in range(50):
            if engine.pm.active == baseline:
                break
            await asyncio.sleep(0.05)
        assert engine.pm.active == baseline
        await engine.stop()

    run_async(main())


# -------------------------------- disagg: chaos on the real transfer plane


def test_transfer_sever_mid_stream_hedge_recovers(run_async):
    """kv.send severed on the SECOND chunk (a prefill worker dying
    mid-transfer): the conn drop fails the decode waiter fast, the job is
    hedged onto the queue, the second dispatch commits — the request
    completes remotely with the exact local output, well inside the
    prefill timeout."""

    async def main():
        from dynamo_tpu.llm.disagg import DisaggRouter, PrefillWorker
        from dynamo_tpu.llm.disagg.decode import build_disagg_decode
        from dynamo_tpu.runtime.runtime import DistributedRuntime

        engine, params = _tiny_engine(seed=4)
        prompt = [(i * 7) % 100 + 1 for i in range(20)]
        want, want_fin = await _collect(engine, _req(prompt), Context())
        await engine.stop()

        drt = await DistributedRuntime.detached()
        try:
            decode_eng, _ = _tiny_engine(params=params)
            prefill_eng, _ = _tiny_engine(params=params)
            router = DisaggRouter(max_local_prefill_length=4)
            disagg = await build_disagg_decode(drt, decode_eng,
                                               namespace="chaos",
                                               router=router,
                                               watch_config=False)
            disagg.prefill_timeout = 30.0      # the hedge must beat this
            pw = PrefillWorker(drt, prefill_eng, namespace="chaos",
                               chunk_pages=1)
            # one attempt per dispatch: the recovery under test is the
            # decode-side hedge, not the worker's own send retry
            pw.retry = guard.RetryPolicy(max_attempts=1)
            pw.start()

            guard.set_chaos("seed=7;sever:kv.send@nth=2")
            t0 = time.monotonic()
            got, fin = await asyncio.wait_for(
                _collect(disagg, _req(prompt), Context()), timeout=25.0)
            elapsed = time.monotonic() - t0
            assert got == want and fin == want_fin
            assert disagg.redispatches == 1            # hedged once
            assert disagg.remote_fallbacks == 0        # …and it worked
            assert pw.failed == 1 and pw.completed == 1
            # fail-fast + hedge, not a prefill_timeout burn
            assert elapsed < 15.0, f"hedge took {elapsed:.1f}s"

            await pw.stop()
            await disagg.transfer.stop()
            await prefill_eng.stop()
            await decode_eng.stop()
        finally:
            guard.set_chaos(None)
            await drt.shutdown()

    run_async(main())


def test_transfer_dead_plane_respects_deadline(run_async):
    """EVERY kv.send severed before the first frame: the decode side can
    never hear a fail-fast (nothing reached the server), so the request
    budget is what bounds the wait — the request finishes with
    finish_reason "timeout" in ~deadline, never prefill_timeout."""

    async def main():
        from dynamo_tpu.llm.disagg import DisaggRouter, PrefillWorker
        from dynamo_tpu.llm.disagg.decode import build_disagg_decode
        from dynamo_tpu.runtime.runtime import DistributedRuntime

        drt = await DistributedRuntime.detached()
        try:
            decode_eng, params = _tiny_engine(seed=4)
            prefill_eng, _ = _tiny_engine(params=params)
            router = DisaggRouter(max_local_prefill_length=4)
            disagg = await build_disagg_decode(drt, decode_eng,
                                               namespace="dead",
                                               router=router,
                                               watch_config=False)
            disagg.prefill_timeout = 30.0     # deliberately way past budget
            pw = PrefillWorker(drt, prefill_eng, namespace="dead",
                               chunk_pages=1)
            pw.start()

            guard.set_chaos("seed=13;sever:kv.send@after=1")
            prompt = [(i * 7) % 100 + 1 for i in range(20)]
            ctx = Context("dead-req",
                          deadline=guard.Deadline.after_s(2.5))
            t0 = time.monotonic()
            toks, fin = await asyncio.wait_for(
                _collect(disagg, _req(prompt), ctx), timeout=20.0)
            elapsed = time.monotonic() - t0
            assert fin == "timeout"
            assert elapsed < 8.0, f"request outlived its budget ({elapsed:.1f}s)"
            assert disagg.remote_fallbacks == 1

            await pw.stop()
            await disagg.transfer.stop()
            await prefill_eng.stop()
            await decode_eng.stop()
        finally:
            guard.set_chaos(None)
            await drt.shutdown()

    run_async(main())


def test_decode_hedged_redispatch_then_local_fallback(run_async):
    """A fast transfer-plane failure re-enqueues the job (hedge) before
    falling back: with no worker to serve either dispatch, two queue
    entries appear and the request still completes locally."""

    async def main():
        from dynamo_tpu.llm.disagg import DisaggRouter
        from dynamo_tpu.llm.disagg.decode import build_disagg_decode
        from dynamo_tpu.runtime.runtime import DistributedRuntime

        drt = await DistributedRuntime.detached()
        try:
            decode_eng, params = _tiny_engine(seed=5)
            local_ref, _ = _tiny_engine(params=params)
            prompt = [(i * 3) % 50 + 1 for i in range(20)]
            want, _ = await _collect(local_ref, _req(prompt), Context())
            await local_ref.stop()

            router = DisaggRouter(max_local_prefill_length=4)
            disagg = await build_disagg_decode(drt, decode_eng,
                                               namespace="hedge",
                                               router=router,
                                               watch_config=False)
            assert disagg.max_dispatches == 2   # DYN_REDISPATCH_MAX default

            async def fail_waiters_fast():
                # the "prefill worker died mid-transfer" signal, delivered
                # through the real waiter plumbing for each dispatch
                for _ in range(2):
                    while not disagg.transfer._waiters:
                        await asyncio.sleep(0.01)
                    rid = next(iter(disagg.transfer._waiters))
                    disagg.transfer._fail_waiter(
                        rid, ConnectionError("worker died mid-transfer"))
                    await asyncio.sleep(0.05)

            failer = asyncio.ensure_future(fail_waiters_fast())
            got, fin = await asyncio.wait_for(
                _collect(disagg, _req(prompt), Context()), timeout=20.0)
            await failer
            assert got == want
            assert disagg.redispatches == 1     # hedged exactly once
            assert disagg.remote_fallbacks == 1
            assert await disagg.queue.depth() == 2  # both dispatches queued

            await disagg.transfer.stop()
            await decode_eng.stop()
        finally:
            await drt.shutdown()

    run_async(main())


def test_expired_job_dropped_by_prefill_worker(run_async):
    """A job whose 1ms budget cannot survive the prefill compute is
    dropped by the worker (counted as expired, not failed) instead of
    racing a doomed transfer the decode side already abandoned."""

    async def main():
        from dynamo_tpu.llm.disagg import PrefillWorker, RemotePrefillRequest
        from dynamo_tpu.runtime.runtime import DistributedRuntime

        drt = await DistributedRuntime.detached()
        try:
            prefill_eng, _ = _tiny_engine(seed=6)
            pw = PrefillWorker(drt, prefill_eng, namespace="expired")
            await pw.queue.put(RemotePrefillRequest(
                request_id="dead", token_ids=list(range(1, 20)),
                page_ids=[1, 2, 3], engine_id=1, deadline_ms=1))
            pw.start()
            for _ in range(200):
                if pw.expired:
                    break
                await asyncio.sleep(0.05)
            assert pw.expired == 1 and pw.failed == 0
            await pw.stop()
            await prefill_eng.stop()
        finally:
            await drt.shutdown()

    run_async(main())


# ------------------------------ request plane: severed call-home, breakers


def test_severed_callhome_is_typed_fail_fast(run_async):
    """Chaos severs the worker's TCP call-home mid-stream: the client's
    stream read raises a typed error promptly — never hangs."""

    async def main():
        from dynamo_tpu.runtime.runtime import DistributedRuntime

        drt = await DistributedRuntime.detached()
        try:
            async def handler(request, ctx):
                for i in range(50):
                    yield {"i": i}
                    await asyncio.sleep(0.01)

            ep = drt.namespace("sever").component("w").endpoint("gen")
            handle = await ep.serve(handler)
            client = await ep.client()

            guard.set_chaos("seed=3;sever:tcp.send@nth=4")
            stream = await client.round_robin({"x": 1})
            t0 = time.monotonic()
            with pytest.raises(RuntimeError):
                async for _env in stream:
                    pass
            assert time.monotonic() - t0 < 10.0
            await handle.stop()
            await client.close()
        finally:
            guard.set_chaos(None)
            await drt.shutdown()

    run_async(main())


def test_client_route_retry_waits_out_late_instance(run_async):
    """Route resolution under the RetryPolicy: no instance at dispatch
    time, one registers during the backoff window → the request succeeds
    instead of 500ing."""

    async def main():
        from dynamo_tpu.runtime.runtime import DistributedRuntime

        drt = await DistributedRuntime.detached()
        try:
            ep = drt.namespace("late").component("w").endpoint("gen")
            client = await ep.client()
            client.retry = guard.RetryPolicy(max_attempts=8, base_s=0.05,
                                             cap_s=0.2)

            async def handler(request, ctx):
                yield {"ok": True}

            async def register_later():
                await asyncio.sleep(0.3)
                return await ep.serve(handler)

            reg = asyncio.ensure_future(register_later())
            stream = await client.round_robin({"x": 1})
            out = [env.data async for env in stream]
            assert out == [{"ok": True}]
            handle = await reg
            await handle.stop()
            await client.close()
        finally:
            await drt.shutdown()

    run_async(main())


def test_request_breaker_opens_and_recovers_via_discovery_put(run_async):
    """Request-plane breaker: a dead-but-discovered instance stops being
    picked after threshold failures (typed NoCapacity when it is the only
    one), and a fresh discovery put closes the breaker."""

    async def main():
        from dynamo_tpu.runtime.runtime import DistributedRuntime

        drt = await DistributedRuntime.detached()
        try:
            async def handler(request, ctx):
                yield {"ok": True}

            ep = drt.namespace("brk").component("w").endpoint("gen")
            handle = await ep.serve(handler)
            client = await ep.client()
            await client.wait_for_instances(timeout=5)
            wid = client.instance_ids()[0]

            # crash without deregistering: unsubscribe the handlers but
            # keep the discovery record (crashed-but-leased worker)
            for sid in handle._sids:
                await drt.dcp.unsubscribe(sid)
            handle._sids.clear()

            client.retry = guard.RetryPolicy(max_attempts=1)
            failures = 0
            for _ in range(client.breakers.cfg.threshold):
                with pytest.raises(Exception):
                    await client.round_robin({"x": 1}, timeout=0.5)
                failures += 1
            br = client.breakers.get("request", wid)
            assert br.state == guard.BREAKER_OPEN
            # every instance circuit-broken → typed NoCapacity (503)
            with pytest.raises((guard.NoCapacity, Exception)) as ei:
                await client.round_robin({"x": 1}, timeout=0.5)
            # re-register: discovery put must close the breaker
            handle2 = await ep.serve(handler)
            for _ in range(100):
                if br.state == guard.BREAKER_CLOSED:
                    break
                await asyncio.sleep(0.02)
            assert br.state == guard.BREAKER_CLOSED
            stream = await client.round_robin({"x": 1})
            assert [env.data async for env in stream] == [{"ok": True}]
            await handle2.stop()
            await client.close()
        finally:
            await drt.shutdown()

    run_async(main())


# --------------------------------------------------- HTTP: 504 / 503 / SSE


def _service_with(engine_fn, model="m"):
    from dynamo_tpu.llm.http.service import HttpService

    service = HttpService()
    service.manager.add_completions_model(model, engine_fn)
    return service


def test_http_unary_deadline_maps_to_504(run_async):
    async def main():
        import aiohttp

        async def stuck_engine(req, ctx):
            await asyncio.sleep(60)
            yield {}

        service = _service_with(stuck_engine)
        await service.start(host="127.0.0.1", port=0)
        try:
            async with aiohttp.ClientSession() as http:
                t0 = time.monotonic()
                async with http.post(
                        f"http://127.0.0.1:{service.port}/v1/completions",
                        json={"model": "m", "prompt": "hi",
                              "timeout": 0.3}) as resp:
                    body = await resp.json()
                assert resp.status == 504
                assert body["error"]["type"] == "timeout_error"
                assert body["error"]["code"] == 504
                assert "X-Request-Id" in resp.headers
                assert time.monotonic() - t0 < 5.0
        finally:
            await service.stop()

    run_async(main())


def test_http_header_deadline_and_504(run_async):
    async def main():
        import aiohttp

        async def stuck_engine(req, ctx):
            # engine honors nothing: the service-level bound must fire
            await asyncio.sleep(60)
            yield {}

        service = _service_with(stuck_engine)
        await service.start(host="127.0.0.1", port=0)
        try:
            async with aiohttp.ClientSession() as http:
                async with http.post(
                        f"http://127.0.0.1:{service.port}/v1/completions",
                        json={"model": "m", "prompt": "hi"},
                        headers={"X-Request-Deadline-Ms": "300"}) as resp:
                    assert resp.status == 504
        finally:
            await service.stop()

    run_async(main())


def test_http_streaming_deadline_emits_timeout_finish(run_async):
    """SSE: deadline dies mid-stream → final chunk finish_reason
    "timeout" + [DONE]; the stream ends cleanly instead of hanging."""

    async def main():
        import json as _json

        import aiohttp

        async def slow_engine(req, ctx):
            yield {"id": "cmpl-1", "object": "text_completion", "created": 1,
                   "model": "m", "choices": [{"index": 0, "text": "tok",
                                              "finish_reason": None}]}
            await asyncio.sleep(60)

        service = _service_with(slow_engine)
        await service.start(host="127.0.0.1", port=0)
        try:
            async with aiohttp.ClientSession() as http:
                finishes = []
                done = False
                async with http.post(
                        f"http://127.0.0.1:{service.port}/v1/completions",
                        json={"model": "m", "prompt": "hi", "stream": True,
                              "timeout": 0.4}) as resp:
                    assert resp.status == 200
                    async for raw in resp.content:
                        line = raw.strip()
                        if not line.startswith(b"data: "):
                            continue
                        if line == b"data: [DONE]":
                            done = True
                            break
                        chunk = _json.loads(line[len(b"data: "):])
                        finishes.extend(
                            c.get("finish_reason")
                            for c in chunk.get("choices", []))
                assert done
                assert finishes[-1] == "timeout"
        finally:
            await service.stop()

    run_async(main())


def test_http_no_capacity_maps_to_503_with_retry_after(run_async):
    async def main():
        import aiohttp

        from dynamo_tpu.runtime.dcp_client import NoRespondersError

        async def no_cap_engine(req, ctx):
            raise guard.NoCapacity("all instances circuit-broken")
            yield {}

        async def no_resp_engine(req, ctx):
            raise NoRespondersError("no live instances")
            yield {}

        for engine, name in ((no_cap_engine, "m"), (no_resp_engine, "m2")):
            service = _service_with(engine, model=name)
            await service.start(host="127.0.0.1", port=0)
            try:
                async with aiohttp.ClientSession() as http:
                    async with http.post(
                            f"http://127.0.0.1:{service.port}"
                            f"/v1/completions",
                            json={"model": name, "prompt": "x"}) as resp:
                        body = await resp.json()
                    assert resp.status == 503
                    # dynarevive: Retry-After is load-derived + jittered
                    # (a constant "1" re-stampeded recovering fleets);
                    # still a valid HTTP delta-seconds integer >= 1
                    ra = int(resp.headers.get("Retry-After"))
                    assert 1 <= ra <= 8
                    assert body["error"]["type"] == "overloaded_error"
            finally:
                await service.stop()

    run_async(main())


def test_guard_metrics_exposed(run_async):
    async def main():
        import aiohttp

        guard.counter_inc("dyn_llm_route_fallback_total",
                          reason="NoRespondersError")

        async def ok_engine(req, ctx):
            yield {"id": "cmpl-1", "object": "text_completion", "created": 1,
                   "model": "m", "choices": [{"index": 0, "text": "x",
                                              "finish_reason": "stop"}]}

        service = _service_with(ok_engine)
        await service.start(host="127.0.0.1", port=0)
        try:
            async with aiohttp.ClientSession() as http:
                async with http.get(
                        f"http://127.0.0.1:{service.port}/metrics") as resp:
                    text = await resp.text()
            assert "dyn_llm_route_fallback_total" in text
        finally:
            await service.stop()

    run_async(main())


# ------------------- the full stack under chaos: complete-or-fail, no hang


@pytest.mark.slow  # heavyweight e2e: tier-1 wall budget (cheaper siblings stay in the gate)
def test_full_stack_chaos_completes_or_fails_typed_within_deadline(run_async):
    """HTTP → processor → router → disagg decode → engine on CPU with the
    transfer plane severed under every send and a per-request deadline:
    every request completes (local-prefill fallback) or fails typed —
    none outlives its budget, none hangs."""

    async def main():
        import json as _json

        import aiohttp

        from dynamo_tpu.llm.disagg import DisaggRouter, PrefillWorker
        from dynamo_tpu.llm.disagg.decode import build_disagg_decode
        from dynamo_tpu.llm.http.service import HttpService
        from dynamo_tpu.llm.kv_router.router import KvRouter
        from dynamo_tpu.llm.model_card import ModelDeploymentCard
        from dynamo_tpu.llm.processor import Processor
        from dynamo_tpu.llm.worker import serve_token_model
        from dynamo_tpu.runtime.runtime import DistributedRuntime

        drt = await DistributedRuntime.detached()
        service = None
        try:
            decode_eng, params = _tiny_engine(seed=9)
            prefill_eng, _ = _tiny_engine(params=params)
            decode_eng.warmup()
            prefill_eng.warmup(decode=False)
            router = DisaggRouter(max_local_prefill_length=4)
            disagg = await build_disagg_decode(drt, decode_eng,
                                               namespace="stack",
                                               router=router,
                                               watch_config=False)
            disagg.prefill_timeout = 20.0
            pw = PrefillWorker(drt, prefill_eng, namespace="stack",
                               chunk_pages=1)
            pw.start()

            mdc = ModelDeploymentCard(name="m", tokenizer_kind="byte",
                                      kv_block_size=16,
                                      model_type="completions")
            await serve_token_model(drt, mdc, disagg, namespace="stack",
                                    component="w",
                                    publish_kv_events=False)
            kvr = KvRouter(drt, "stack", "w", block_size=16,
                           scrape_interval=1.0, seed=0)
            await kvr.start(run_loop=False)
            await kvr.scrape_once()
            token_client = await drt.namespace("stack").component("w") \
                .endpoint("generate_tokens").client()
            processor = Processor(mdc, token_client, kvr)
            service = HttpService()
            service.manager.add_completions_model("m", processor.completion)
            await service.start(host="127.0.0.1", port=0)

            # sever every transfer send: remote prefill can never commit;
            # every request must degrade to local prefill inside its budget
            guard.set_chaos("seed=11;sever:kv.send@after=1")

            deadline_s = 15.0
            async with aiohttp.ClientSession() as http:
                async def one(i):
                    prompt = "chaos " * (3 + i % 3)
                    t0 = time.monotonic()
                    async with http.post(
                            f"http://127.0.0.1:{service.port}"
                            f"/v1/completions",
                            json={"model": "m", "prompt": prompt,
                                  "stream": True, "max_tokens": 6,
                                  "timeout": deadline_s}) as resp:
                        assert resp.status in (200, 503, 504), resp.status
                        finishes = []
                        if resp.status == 200:
                            async for raw in resp.content:
                                line = raw.strip()
                                if line == b"data: [DONE]":
                                    break
                                if line.startswith(b"data: "):
                                    chunk = _json.loads(
                                        line[len(b"data: "):])
                                    finishes.extend(
                                        c.get("finish_reason")
                                        for c in chunk.get("choices", []))
                        elapsed = time.monotonic() - t0
                        assert elapsed < deadline_s + 5.0, \
                            f"request {i} outlived its budget ({elapsed:.1f}s)"
                        if finishes:
                            assert finishes[-1] in ("stop", "length",
                                                    "timeout"), finishes

                await asyncio.wait_for(
                    asyncio.gather(*(one(i) for i in range(4))),
                    timeout=120.0)

            assert disagg.remote_fallbacks >= 1, \
                "chaos never exercised the fallback path"

            await pw.stop()
            await kvr.stop()
            await token_client.close()
            await disagg.transfer.stop()
            await prefill_eng.stop()
            await decode_eng.stop()
        finally:
            guard.set_chaos(None)
            if service is not None:
                await service.stop()
            await drt.shutdown()

    run_async(main())


# --------------------------------------------------- fleet breaker scenario


def test_fleet_breaker_scenario_circuit_breaks_and_recovers(run_async):
    """--scenario breaker: the flapping worker's stats breaker opens in
    every collector (once per flap), closes again by run end, and traffic
    keeps meeting the SLO on the healthy pool."""
    from dynamo_tpu.fleet.harness import run_scenario
    from dynamo_tpu.fleet.scenarios import get_scenario

    report = run_async(run_scenario(get_scenario("breaker"), seed=0))
    flaps = [e for e in report["workers"]["timeline"]
             if e["event"] == "flap_start"]
    assert len(flaps) == 2
    for collector in ("aggregator", "router"):
        b = report["breakers"][collector]
        assert b["opened_total"] >= 1, (collector, b)
        assert b["open_now"] == [], (collector, b)
    assert report["requests"]["failed"] == 0
    assert report["slo"]["met"], report["phases"]
