"""dynarevive: mid-stream failover, graceful drain, SLO-aware admission.

The acceptance contract (ISSUE 13): no single worker failure ever turns
into a client-visible error — a `worker.kill` chaos rule fired mid-decode
on a 2-replica set leaves the client's greedy SSE stream token-identical
to an uninterrupted control with zero compile-fence trips and prefix
reuse on the resume; SIGTERM/drain finishes in-flight work and admits
nothing new; overload answers early 503s with load-derived jittered
Retry-After. All on CPU against the real transports.
"""

import asyncio
import json
import random
import time

import pytest

from dynamo_tpu.runtime import guard, profiling, revive
from dynamo_tpu.runtime.engine import Context


@pytest.fixture(autouse=True)
def _fresh_revive():
    """Chaos and the failover journal never leak between tests."""
    guard.set_chaos(None)
    revive.reset_journal()
    yield
    guard.set_chaos(None)
    revive.reset_journal()


# ------------------------------------------------------------------ journal


def test_journal_open_record_close_and_bound():
    ring = revive.ReviveJournal(capacity=4, max_tokens=6)
    e = ring.open("r1", prompt_tokens=10)
    e.record([1, 2, 3])
    e.record([4, 5])
    assert e.tokens == [1, 2, 3, 4, 5] and e.resumable
    # overflowing the bound marks non-resumable instead of truncating
    e.record([6, 7])
    assert e.tokens == [1, 2, 3, 4, 5] and not e.resumable
    assert len(ring) == 1
    ring.close("r1")
    assert len(ring) == 0 and ring.get("r1") is None


def test_journal_ring_eviction_costs_resumability_only():
    ring = revive.ReviveJournal(capacity=2, max_tokens=100)
    a = ring.open("a", 1)
    ring.open("b", 1)
    ring.open("c", 1)  # evicts a
    assert len(ring) == 2 and ring.get("a") is None
    assert not a.resumable
    assert ring.evicted_total == 1
    snap = ring.snapshot()
    assert snap["inflight"] == 2 and snap["opened_total"] == 3


# ------------------------------------------------------------------ session


def _pre(tokens, max_tokens=8, min_tokens=None, echo=False):
    from dynamo_tpu.llm.protocols.common import (OutputOptions,
                                                 PreprocessedRequest,
                                                 SamplingOptions,
                                                 StopConditions)

    return PreprocessedRequest(
        token_ids=list(tokens), sampling=SamplingOptions(),
        stop=StopConditions(max_tokens=max_tokens, min_tokens=min_tokens),
        output=OutputOptions(echo_prompt=echo))


def _out(ids, finish=None):
    from dynamo_tpu.llm.protocols.common import EngineOutput

    return EngineOutput(token_ids=list(ids), finish_reason=finish)


def test_session_resume_request_dedupes_overlap():
    """The resume prompt is prompt + emitted with the stop budget
    decremented and echo force-cleared — the overlap dedupe that makes
    greedy resumes token-identical."""
    ctx = Context("rid-1")
    s = revive.ReviveSession(_pre([1, 2, 3], max_tokens=8, min_tokens=4,
                                  echo=True), ctx, limit=2)
    s.observe(_out([10, 11]))
    s.observe(_out([12]))
    r = s.resume_request()
    assert r.token_ids == [1, 2, 3, 10, 11, 12]
    assert r.stop.max_tokens == 5            # 8 - 3 emitted
    assert r.stop.min_tokens == 1            # 4 - 3 emitted
    assert r.output.echo_prompt is False     # echo already streamed once
    # the base request is untouched
    assert s.base.token_ids == [1, 2, 3]
    assert s.base.stop.max_tokens == 8 and s.base.output.echo_prompt
    s.close()


def test_session_should_resume_matrix():
    ctx = Context("rid-2")
    s = revive.ReviveSession(_pre([1], max_tokens=8), ctx, limit=1)
    assert s.should_resume(RuntimeError("worker died"))
    assert s.should_resume(ConnectionResetError("severed"))
    # typed budget/capacity/client errors never resume
    assert not s.should_resume(guard.DeadlineExceeded("spent"))
    assert not s.should_resume(guard.NoCapacity("all broken"))
    assert not s.should_resume(ValueError("bad request"))
    # a finished stream never resumes
    s.observe(_out([5], finish="stop"))
    assert not s.should_resume(RuntimeError("late failure"))
    s.close()

    ctx2 = Context("rid-3")
    s2 = revive.ReviveSession(_pre([1], max_tokens=8), ctx2, limit=1)
    s2.mark_resume()
    assert not s2.should_resume(RuntimeError("x"))  # limit spent
    s2.close()

    ctx3 = Context("rid-4")
    s3 = revive.ReviveSession(_pre([1], max_tokens=8), ctx3, limit=2)
    ctx3.kill()  # client gone: nothing to save
    assert not s3.should_resume(RuntimeError("x"))
    s3.close()


def test_session_budget_spent_synthesizes_length_finish():
    """Worker died between the last budgeted token and its finish chunk:
    the session synthesizes the lost finish instead of dispatching a
    zero-token resume."""
    ctx = Context("rid-5")
    s = revive.ReviveSession(_pre([1, 2], max_tokens=3), ctx, limit=2)
    s.observe(_out([7, 8, 9]))
    assert s.budget_spent()
    fin = s.synthetic_finish()
    assert fin.finish_reason == "length"
    assert fin.completion_tokens == 3 and fin.prompt_tokens == 2
    s.close()


# -------------------------------------------------------------- retry-after


def test_retry_after_jittered_deterministic_and_capped():
    r1 = [revive.retry_after_s(p, rng=random.Random(7), cap_s=8.0)
          for p in (1.0, 2.0, 4.0, 50.0)]
    r2 = [revive.retry_after_s(p, rng=random.Random(7), cap_s=8.0)
          for p in (1.0, 2.0, 4.0, 50.0)]
    assert r1 == r2                         # injectable rng → deterministic
    assert all(1 <= v <= 8 for v in r1)     # pressure beyond cap clamps
    # jitter actually varies across draws (not the old constant 1)
    rng = random.Random(3)
    draws = {revive.retry_after_s(3.0, rng=rng, cap_s=8.0)
             for _ in range(32)}
    assert len(draws) > 1


# -------------------------------------------------------- admission control


def test_admission_disabled_by_default_admits_everything():
    calls = []

    def signals():
        calls.append(1)
        return revive.LoadSignals(queue_depth=10 ** 6)

    ctrl = revive.AdmissionController(signals, cfg=revive.ShedConfig())
    assert not ctrl.cfg.enabled
    assert ctrl.admit() is None and not calls  # signals never even read


def test_admission_sheds_on_queue_depth_with_peak_hold():
    sig = revive.LoadSignals(queue_depth=0, workers=2)

    def signals():
        return sig

    ctrl = revive.AdmissionController(
        signals, cfg=revive.ShedConfig(queue_depth=3),
        rng=random.Random(0), window=8)
    assert ctrl.admit() is None              # 0 < 3*2
    sig = revive.LoadSignals(queue_depth=7, workers=2)
    ra = ctrl.admit()                        # 7 >= 6: shed
    assert isinstance(ra, int) and ra >= 1
    assert ctrl.shed_total == 1
    assert ctrl.shed_by_signal == {"queue_depth": 1}
    # peak-hold: the queue drained at this instant, but the recent peak
    # still sheds (batched engines complete in lockstep — instantaneous
    # reads anti-correlate with load)
    sig = revive.LoadSignals(queue_depth=0, workers=2)
    assert ctrl.admit() is not None
    # once the peak leaves the window, admission resumes
    for _ in range(10):
        ctrl.observe()
    assert ctrl.admit() is None
    snap = ctrl.snapshot()
    assert snap["enabled"] and snap["shed_total"] == 2


def test_admission_loop_lag_and_kv_signals():
    sig = {"s": revive.LoadSignals(loop_lag_p99_ms=120.0)}
    ctrl = revive.AdmissionController(
        lambda: sig["s"], cfg=revive.ShedConfig(loop_lag_ms=100.0),
        rng=random.Random(0), window=2)
    name, pressure = ctrl.evaluate()
    assert name == "loop_lag" and pressure == pytest.approx(1.2)
    sig["s"] = revive.LoadSignals(loop_lag_p99_ms=0.0, kv_free_blocks=2)
    ctrl2 = revive.AdmissionController(
        lambda: sig["s"], cfg=revive.ShedConfig(kv_free_blocks=8),
        rng=random.Random(0), window=2)
    name, pressure = ctrl2.evaluate()
    assert name == "kv_free_blocks" and pressure == pytest.approx(4.0)
    # a broken signal source admits (never a shed storm)
    ctrl3 = revive.AdmissionController(
        lambda: 1 / 0, cfg=revive.ShedConfig(queue_depth=1))
    assert ctrl3.admit() is None


def test_signals_adapters():
    stats = {"num_requests_waiting": 5, "loop_lag_p99_seconds": 0.25,
             "kv_free_blocks": 17}
    sig = revive.signals_from_stats(stats)
    assert (sig.queue_depth, sig.workers, sig.kv_free_blocks) == (5, 1, 17)
    assert sig.loop_lag_p99_ms == pytest.approx(250.0)

    from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics

    metrics = {
        1: ForwardPassMetrics(num_requests_waiting=3, kv_free_blocks=9,
                              loop_lag_p99_seconds=0.1),
        2: ForwardPassMetrics(num_requests_waiting=4, kv_free_blocks=2,
                              loop_lag_p99_seconds=0.3),
        3: ForwardPassMetrics(num_requests_waiting=50, draining=1),
    }
    sig = revive.signals_from_metrics(metrics)
    # the draining worker is leaving: its queue is not admissible load
    assert sig.queue_depth == 7 and sig.workers == 2
    assert sig.kv_free_blocks == 2
    assert sig.loop_lag_p99_ms == pytest.approx(300.0)


# ------------------------------------------------------------ chaos grammar


def test_chaos_grammar_worker_points_parse_and_reject():
    seed, rules = guard.parse_chaos(
        "seed=5;sever:worker.kill@nth=4;delay:engine.stall@ms=80,times=2")
    assert seed == 5 and len(rules) == 2
    kill, stall = rules
    assert (kill.action, kill.point, kill.nth) == ("sever", "worker.kill", 4)
    assert (stall.action, stall.point, stall.ms, stall.times) == \
        ("delay", "engine.stall", 80.0, 2)
    # malformed specs still fail loudly
    with pytest.raises(ValueError):
        guard.parse_chaos("explode:worker.kill")
    with pytest.raises(ValueError):
        guard.parse_chaos("sever:worker.kill@bogus=1")


def test_chaos_worker_kill_fires_deterministically(run_async):
    async def main():
        inj = guard.set_chaos("seed=1;sever:worker.kill@nth=2,times=1")
        await guard.chaos_point("worker.kill")          # hit 1: no fire
        with pytest.raises(ConnectionResetError):
            await guard.chaos_point("worker.kill")      # hit 2: sever
        await guard.chaos_point("worker.kill")          # times=1: spent
        assert inj.injected[("worker.kill", "sever")] == 1

    run_async(main())


# --------------------------------------------------- tiny engine scaffolding


def _tiny_engine(params=None, seed=2, decode_steps=None):
    import jax

    from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.models.llama import init_params

    cfg = ModelConfig.tiny(num_heads=4, num_kv_heads=2, head_dim=8,
                           hidden_size=32, vocab_size=300)
    kw = {}
    if decode_steps is not None:
        kw["decode_steps"] = decode_steps
    ecfg = EngineConfig(page_size=8, num_pages=64, max_batch=4,
                        prefill_chunk=32, batch_buckets=(1, 2, 4),
                        prefill_buckets=(8, 32), page_buckets=(8,),
                        watermark_pages=2, **kw)
    if params is None:
        params = init_params(cfg, jax.random.PRNGKey(seed))
    return JaxEngine(cfg, ecfg, params=params, seed=seed), params


def _req(tokens, max_tokens=6):
    from dynamo_tpu.llm.protocols.common import (PreprocessedRequest,
                                                 SamplingOptions,
                                                 StopConditions)

    return PreprocessedRequest(token_ids=tokens,
                               sampling=SamplingOptions(),
                               stop=StopConditions(max_tokens=max_tokens))


async def _collect(engine, req, ctx):
    toks = []
    async for out in engine.generate(req, ctx):
        toks.extend(out.token_ids)
        if out.finish_reason is not None:
            return toks, out.finish_reason
    return toks, None


def test_engine_stall_chaos_delays_but_completes(run_async):
    async def main():
        engine, _ = _tiny_engine()
        inj = guard.set_chaos("seed=2;delay:engine.stall@ms=40,times=2")
        toks, fin = await _collect(engine, _req(list(range(1, 12))), Context())
        assert fin is not None and toks
        assert inj.injected.get(("engine.stall", "delay")) == 2
        await engine.stop()

    run_async(main())


# --------------------------------------- worker.kill on a served endpoint


def test_worker_kill_makes_handle_a_wedged_process(run_async):
    """A fired worker.kill rule: the client sees a raw conn drop (typed
    fail-fast), the discovery record and lease stay behind, the stats
    plane answers errors — the exact crashed-but-leased shape."""

    async def main():
        from dynamo_tpu.runtime.component import instance_key
        from dynamo_tpu.runtime.runtime import DistributedRuntime

        drt = await DistributedRuntime.detached()
        try:
            async def handler(request, ctx):
                for i in range(50):
                    yield {"i": i}
                    await asyncio.sleep(0.005)

            ep = drt.namespace("kill").component("w").endpoint("gen")
            handle = await ep.serve(handler)
            client = await ep.client()
            guard.set_chaos("seed=9;sever:worker.kill@nth=3")

            stream = await client.round_robin({"x": 1})
            got = []
            t0 = time.monotonic()
            with pytest.raises(RuntimeError, match="disconnected"):
                async for env in stream:
                    got.append(env.data)
            assert time.monotonic() - t0 < 10.0
            assert len(got) == 2                  # died under frame 3
            assert handle._dead
            # wedged process: lease + discovery record stay behind
            key = instance_key("kill", "w", "gen",
                               handle.instance.instance_id)
            assert await drt.dcp.kv_get(key) is not None
            # the stats plane errors instead of answering
            with pytest.raises(Exception):
                await drt.dcp.request(
                    f"stats.{handle.instance.subject}", b"", timeout=2.0)
            await handle.stop()
            await client.close()
        finally:
            await drt.shutdown()

    run_async(main())


# --------------------------------------------- the failover e2e (tentpole)


@pytest.mark.slow  # heavyweight e2e: tier-1 wall budget (cheaper siblings stay in the gate)
def test_worker_kill_mid_decode_resumes_token_identical(run_async):
    """THE acceptance e2e: `worker.kill` chaos mid-decode on a 2-replica
    set → the client's greedy SSE stream completes token-identical to an
    unfaulted control, no error chunk, zero post-warmup compiles on the
    surviving replica, and the resumed request's cost block shows prefix
    reuse (device_hit > 0) because overlap routing landed the resume on
    the replica with the warmest prefix."""

    async def main():
        import aiohttp

        from dynamo_tpu.llm.http.service import HttpService
        from dynamo_tpu.llm.kv_router.router import KvRouter
        from dynamo_tpu.llm.model_card import ModelDeploymentCard
        from dynamo_tpu.llm.processor import Processor
        from dynamo_tpu.llm.worker import serve_token_model
        from dynamo_tpu.runtime.runtime import DistributedRuntime

        drt = await DistributedRuntime.detached()
        drt2 = await DistributedRuntime.attach(drt.dcp.address)
        service = None
        try:
            # identical weights on both replicas (sibling equivalence is
            # what makes the greedy resume token-identical); small decode
            # windows so the stream has several chunks to die between
            eng_a, params = _tiny_engine(seed=11, decode_steps=2)
            eng_b, _ = _tiny_engine(params=params, decode_steps=2)
            eng_a.warmup()
            # the fence is process-global: sibling warmup is an
            # intentional compile phase (the dynashard join idiom)
            eng_a.fence.disarm()
            try:
                eng_b.warmup()
            finally:
                eng_a.fence.arm()

            mdc = ModelDeploymentCard(name="m", tokenizer_kind="byte",
                                      kv_block_size=8,
                                      model_type="completions")
            h_a, pub_a = await serve_token_model(
                drt, mdc, eng_a, namespace="rev", component="w")
            h_b, pub_b = await serve_token_model(
                drt2, mdc, eng_b, namespace="rev", component="w")
            kvr = KvRouter(drt, "rev", "w", block_size=8, seed=0)
            await kvr.start(run_loop=False)
            await kvr.scrape_once()
            token_client = await drt.namespace("rev").component("w") \
                .endpoint("generate_tokens").client()
            processor = Processor(mdc, token_client, kvr)
            service = HttpService()
            service.manager.add_completions_model("m",
                                                  processor.completion)
            await service.start(host="127.0.0.1", port=0)

            from dynamo_tpu.llm.tokenizer import ByteTokenizer

            prompt = "resume me please now!!!"   # BOS + 23 bytes = 3 pages
            tokens = ByteTokenizer().encode(prompt)  # the HTTP lowering
            # CONTROL + cache warm: run the identical greedy request
            # in-process on BOTH engines — the uninterrupted reference
            # output, and both replicas now hold the prompt (and
            # continuation) pages, so whichever survives has the warm
            # prefix the resume should hit. The control carries the same
            # eos semantics the HTTP preprocessor lowers.
            def ctrl_req():
                r = _req(tokens, 12)
                r.eos_token_ids = [ByteTokenizer.EOS]
                return r

            want, want_fin = await _collect(eng_a, ctrl_req(),
                                            Context("warm-a"))
            want_b, _ = await _collect(eng_b, ctrl_req(),
                                       Context("warm-b"))
            assert want == want_b, "sibling equivalence broken"
            control_text = ByteTokenizer().decode(want)
            await pub_a.flush()
            await pub_b.flush()
            await asyncio.sleep(0.05)
            await kvr.scrape_once()

            # the kill: the serving replica dies under its 3rd streamed
            # frame — mid-decode, after the client saw real tokens
            guard.set_chaos("seed=3;sever:worker.kill@nth=3")

            rid = "revive-e2e-1"
            text = []
            finishes = []
            saw_error = False
            async with aiohttp.ClientSession() as http:
                async with http.post(
                        f"http://127.0.0.1:{service.port}/v1/completions",
                        json={"model": "m", "prompt": prompt,
                              "stream": True, "max_tokens": 12},
                        headers={"X-Request-Id": rid}) as resp:
                    assert resp.status == 200
                    async for raw in resp.content:
                        line = raw.strip()
                        if line == b"data: [DONE]":
                            break
                        if line.startswith(b"event: error"):
                            saw_error = True
                        if not line.startswith(b"data: "):
                            continue
                        chunk = json.loads(line[len(b"data: "):])
                        for c in chunk.get("choices", []):
                            text.append(c.get("text") or "")
                            if c.get("finish_reason"):
                                finishes.append(c["finish_reason"])

                # exactly one replica died under the chaos rule
                dead = [h for h in (h_a, h_b) if h._dead]
                assert len(dead) == 1, "chaos should kill exactly one"
                survivor = eng_b if dead[0] is h_a else eng_a

                # the contract: no error chunk, token-identical output
                assert not saw_error
                assert "".join(text) == control_text
                assert finishes and finishes[-1] in ("length", "stop")
                # one mid-stream failover happened
                assert revive.journal().resumed_total == 1
                # no journal entry leaked
                assert len(revive.journal()) == 0
                # zero compile-fence trips on the surviving replica: the
                # resume prompt stayed on the warmed grid
                assert survivor.fence.post_warmup_compiles == 0

                # the resumed request's cost block: names the resume and
                # shows prefix reuse on the survivor (warmest-prefix
                # routing made the resume one cached prefill)
                cost = profiling.request_attribution(rid)
                assert cost is not None
                assert cost.get("resumed_attempts") == 1
                assert (cost.get("device_hit_blocks", 0)
                        + cost.get("host_restored_blocks", 0)) > 0
                # /v1/traces/{rid} serves the same block to operators
                async with http.get(
                        f"http://127.0.0.1:{service.port}"
                        f"/v1/traces/{rid}") as tresp:
                    tdata = await tresp.json()
                assert tdata["cost"]["resumed_attempts"] == 1

            await kvr.stop()
            await token_client.close()
            for pub in (pub_a, pub_b):
                await pub.stop()
            for h in (h_a, h_b):
                await h.stop()
            await eng_a.stop()
            await eng_b.stop()
        finally:
            guard.set_chaos(None)
            if service is not None:
                await service.stop()
            await drt2.shutdown()
            await drt.shutdown()

    run_async(main())


# ------------------------------------------------------------ drain e2e


@pytest.mark.slow  # heavyweight e2e: tier-1 wall budget (cheaper siblings stay in the gate)
def test_drain_finishes_inflight_refuses_new_and_router_avoids(run_async):
    """SIGTERM-shaped drain during active decode: the in-flight stream
    completes its full budget, new requests are refused with a typed
    nack, the discovery record disappears (the router prunes the
    worker), and the drain reports clean."""

    async def main():
        from dynamo_tpu.llm.model_card import ModelDeploymentCard
        from dynamo_tpu.llm.worker import serve_token_model
        from dynamo_tpu.runtime.component import instance_key
        from dynamo_tpu.runtime.runtime import DistributedRuntime

        drt = await DistributedRuntime.detached()
        try:
            engine, _ = _tiny_engine(seed=6, decode_steps=2)
            engine.warmup()
            mdc = ModelDeploymentCard(name="m", tokenizer_kind="byte",
                                      kv_block_size=8,
                                      model_type="completions")
            handle, pub = await serve_token_model(
                drt, mdc, engine, namespace="drain", component="w")
            client = await drt.namespace("drain").component("w") \
                .endpoint("generate_tokens").client()
            await client.wait_for_instances(timeout=5)

            # start a long-ish stream and consume it concurrently
            stream = await client.round_robin(
                _req(list(range(1, 20)), max_tokens=24).to_dict())
            got = []
            fins = []

            async def consume():
                async for env in stream:
                    if env.data is not None:
                        got.extend(env.data.get("token_ids", []))
                        if env.data.get("finish_reason"):
                            fins.append(env.data["finish_reason"])

            consumer = asyncio.ensure_future(consume())
            while not got:           # the stream is mid-decode
                await asyncio.sleep(0.01)

            drained = await revive.drain_worker(
                handle, engine=engine, publisher=pub, timeout_s=15.0)
            await consumer

            # the in-flight stream finished its FULL budget, cleanly
            assert drained is True
            assert fins == ["length"] and len(got) == 24
            # discovery record gone: routers stop picking this worker
            key = instance_key("drain", "w", "generate_tokens",
                               handle.instance.instance_id)
            assert await drt.dcp.kv_get(key) is None
            # engine refuses new admissions with the typed 503 shape
            with pytest.raises(guard.NoCapacity):
                async for _ in engine.generate(_req([1, 2, 3]), Context()):
                    pass

            await client.close()
            await engine.stop()
        finally:
            await drt.shutdown()

    run_async(main())


def test_drain_nacks_new_requests_typed(run_async):
    """A draining handle answers new dispatches with accepted=False (the
    Client maps it to a retryable rejection, never a hang)."""

    async def main():
        from dynamo_tpu.runtime.runtime import DistributedRuntime

        drt = await DistributedRuntime.detached()
        try:
            async def handler(request, ctx):
                yield {"ok": True}

            ep = drt.namespace("nack").component("w").endpoint("gen")
            handle = await ep.serve(handler)
            client = await ep.client()
            await client.wait_for_instances(timeout=5)
            wid = client.instance_ids()[0]

            await handle.begin_drain()
            client.retry = guard.RetryPolicy(max_attempts=2, base_s=0.01,
                                             cap_s=0.02)
            # the watch delete may not have landed yet: a direct dispatch
            # hits the draining nack, typed
            with pytest.raises(Exception) as ei:
                await client.direct({"x": 1}, wid, timeout=2.0)
            assert "rejected" in str(ei.value) or "not found" in \
                str(ei.value) or "circuit-broken" in str(ei.value)
            # draining ≠ dead: the stats plane still answers, flagged
            from dynamo_tpu.runtime import wire
            from dynamo_tpu.runtime.dcp_client import unpack

            reply = wire.decoded(wire.DCP_STATS_REPLY, unpack(
                await drt.dcp.request(f"stats.{handle.instance.subject}",
                                      b"", timeout=2.0)))
            assert reply["data"]["draining"] == 1
            assert await handle.wait_idle(2.0)
            await handle.stop()
            await client.close()
        finally:
            await drt.shutdown()

    run_async(main())


def test_scheduler_skips_draining_workers():
    from dynamo_tpu.llm.kv_router.indexer import OverlapScores
    from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics
    from dynamo_tpu.llm.kv_router.scheduler import KvScheduler

    sched = KvScheduler(block_size=8, rng=random.Random(0))
    sched.update_metrics({
        1: ForwardPassMetrics(request_total_slots=8, kv_total_blocks=64),
        2: ForwardPassMetrics(request_total_slots=8, kv_total_blocks=64,
                              draining=1),
    })
    for _ in range(8):
        assert sched.schedule(16, OverlapScores()) == 1
    # exclusion (the dynarevive resume path) composes with it
    sched.update_metrics({
        1: ForwardPassMetrics(request_total_slots=8, kv_total_blocks=64),
        2: ForwardPassMetrics(request_total_slots=8, kv_total_blocks=64),
        3: ForwardPassMetrics(request_total_slots=8, kv_total_blocks=64,
                              draining=1),
    })
    for _ in range(4):
        assert sched.schedule(16, OverlapScores(), exclude={1}) == 2
    with pytest.raises(RuntimeError):
        sched.schedule(16, OverlapScores(), exclude={1, 2})


# ------------------------------------------------- client disconnect e2e


def test_client_disconnect_cancels_upstream_promptly(run_async):
    """An SSE client dropping mid-stream must cancel the upstream
    generation promptly: engine pages return to the pool, the
    attribution records finish_reason "cancelled", and no failover
    journal entry leaks."""

    async def main():
        import aiohttp

        from dynamo_tpu.llm.http.service import HttpService
        from dynamo_tpu.llm.model_card import ModelDeploymentCard
        from dynamo_tpu.llm.processor import Processor
        from dynamo_tpu.llm.worker import serve_token_model
        from dynamo_tpu.runtime.runtime import DistributedRuntime

        drt = await DistributedRuntime.detached()
        service = None
        try:
            engine, _ = _tiny_engine(seed=8, decode_steps=2)
            engine.warmup()
            mdc = ModelDeploymentCard(name="m", tokenizer_kind="byte",
                                      kv_block_size=8,
                                      model_type="completions")
            handle, pub = await serve_token_model(
                drt, mdc, engine, namespace="disc", component="w")
            token_client = await drt.namespace("disc").component("w") \
                .endpoint("generate_tokens").client()
            processor = Processor(mdc, token_client, None)
            service = HttpService()
            service.manager.add_completions_model("m",
                                                  processor.completion)
            await service.start(host="127.0.0.1", port=0)

            baseline = engine.pm.active
            rid = "disconnect-1"
            session = aiohttp.ClientSession()
            resp = await session.post(
                f"http://127.0.0.1:{service.port}/v1/completions",
                json={"model": "m", "prompt": "disconnect me now please",
                      "stream": True, "max_tokens": 40},
                headers={"X-Request-Id": rid})
            assert resp.status == 200
            chunks = 0
            async for raw in resp.content:
                if raw.strip().startswith(b"data: "):
                    chunks += 1
                if chunks >= 2:
                    break                       # drop mid-stream
            # abort the connection outright (no graceful close)
            resp.close()
            await session.close()

            # the upstream must cancel PROMPTLY: pages back to baseline
            # long before the 40-token budget could finish on its own
            for _ in range(200):
                cost = profiling.request_attribution(rid)
                if engine.pm.active == baseline and cost is not None:
                    break
                await asyncio.sleep(0.02)
            assert engine.pm.active == baseline, "pages leaked"
            cost = profiling.request_attribution(rid)
            assert cost is not None
            assert cost["finish_reason"] == "cancelled"
            assert cost["decode_tokens"] < 40
            # no journal entry leaked
            assert len(revive.journal()) == 0

            await token_client.close()
            await pub.stop()
            await handle.stop()
            await engine.stop()
        finally:
            if service is not None:
                await service.stop()
            await drt.shutdown()

    run_async(main())


# ------------------------------------------------ HTTP shed + POST /drain


def _service_with(engine_fn, model="m", admission=None):
    from dynamo_tpu.llm.http.service import HttpService

    service = HttpService(admission=admission)
    service.manager.add_completions_model(model, engine_fn)
    return service


def test_http_shed_answers_503_with_derived_retry_after(run_async):
    async def main():
        import aiohttp

        async def ok_engine(req, ctx):
            yield {"id": "cmpl-1", "object": "text_completion",
                   "created": 1, "model": "m",
                   "choices": [{"index": 0, "text": "x",
                                "finish_reason": "stop"}]}

        sig = {"s": revive.LoadSignals(queue_depth=0, workers=1)}
        ctrl = revive.AdmissionController(
            lambda: sig["s"], cfg=revive.ShedConfig(queue_depth=2),
            rng=random.Random(1), window=1)
        service = _service_with(ok_engine, admission=ctrl)
        await service.start(host="127.0.0.1", port=0)
        try:
            async with aiohttp.ClientSession() as http:
                url = f"http://127.0.0.1:{service.port}/v1/completions"
                async with http.post(url, json={"model": "m",
                                                "prompt": "x"}) as resp:
                    assert resp.status == 200
                sig["s"] = revive.LoadSignals(queue_depth=9, workers=1)
                async with http.post(url, json={"model": "m",
                                                "prompt": "x"}) as resp:
                    body = await resp.json()
                    assert resp.status == 503
                    assert body["error"]["type"] == "overloaded_error"
                    ra = int(resp.headers["Retry-After"])
                    assert 1 <= ra <= 8
                assert ctrl.shed_total == 1
                # the shed shows up on the metrics plane
                async with http.get(
                        f"http://127.0.0.1:{service.port}/metrics") as r:
                    text = await r.text()
                assert "dyn_shed_requests_total" in text
        finally:
            await service.stop()

    run_async(main())


def test_http_post_drain_stops_admitting_and_runs_callbacks(run_async):
    async def main():
        import aiohttp

        async def ok_engine(req, ctx):
            yield {"id": "cmpl-1", "object": "text_completion",
                   "created": 1, "model": "m",
                   "choices": [{"index": 0, "text": "x",
                                "finish_reason": "stop"}]}

        drained = []

        async def on_drain():
            drained.append(True)
            return True

        service = _service_with(ok_engine)
        service.on_drain(on_drain)
        await service.start(host="127.0.0.1", port=0)
        try:
            async with aiohttp.ClientSession() as http:
                base = f"http://127.0.0.1:{service.port}"
                async with http.post(f"{base}/drain") as resp:
                    body = await resp.json()
                assert resp.status == 200 and body["draining"]
                assert drained == [True]
                # new work is refused with Retry-After
                async with http.post(f"{base}/v1/completions",
                                     json={"model": "m",
                                           "prompt": "x"}) as resp:
                    assert resp.status == 503
                    assert int(resp.headers["Retry-After"]) >= 1
                # health reports draining; a second drain 409s
                async with http.get(f"{base}/health") as resp:
                    assert (await resp.json())["status"] == "draining"
                async with http.post(f"{base}/drain") as resp:
                    assert resp.status == 409
        finally:
            await service.stop()

    run_async(main())


# --------------------------------------------------- fleet failover gate


def test_fleet_failover_scenario(run_async):
    """`python -m dynamo_tpu.fleet --scenario failover`: a loaded worker
    killed mid-burst + a rolling-drain wave → zero failed requests,
    nonzero resumed count, some shed (reported, not failed), recovery
    SLO met."""
    from dynamo_tpu.fleet.harness import run_scenario
    from dynamo_tpu.fleet.scenarios import get_scenario

    report = run_async(run_scenario(get_scenario("failover"), seed=0))
    assert report["requests"]["failed"] == 0
    assert report["requests"]["resumed"] >= 1
    fo = report["failover"]
    assert fo["resumed_requests"] == report["requests"]["resumed"]
    assert fo["still_crashed"] == 0
    assert len(fo["drains"]) == 2
    # drained workers retired cleanly (never counted dead)
    removed = [e for e in report["workers"]["timeline"]
               if e["event"] == "removed"]
    assert len(removed) >= 2
    assert report["slo"]["met"], report["phases"]
    assert report["slo"]["time_to_recover_s"] is not None
    # shed requests are reported as shed, never as failures
    assert report["requests"]["shed"] == fo["shed_requests"]
