"""Streaming chunked KV transfer plane (llm/disagg/transfer.py).

Protocol-level tests against a fake engine: interleaved multi-request
chunk streams on one connection, per-chunk late-write guards, mid-stream
failure/abort/connection-drop teardown (waiter fails fast → decode-side
fallback), int8 chunk round-trips matching the bulk path, and the
zero-copy multi-buffer codec framing. Everything runs on plain asyncio —
no JAX engine — so this is the fast tier-1 smoke for the wire protocol.
"""

import asyncio
import time

import numpy as np
import pytest

from dynamo_tpu.llm.disagg.transfer import (KvTransferClient,
                                            KvTransferServer, TransferStats)
from dynamo_tpu.runtime import codec, wire

SHAPE = (2, 1, 2, 4, 8)  # [L, n=1 page per unit, KV, ps, hd]


class FakeEngine:
    """Page-keyed sink standing in for JaxEngine.inject_pages."""

    def __init__(self, inject_delay=0.0, fail_on_page=None):
        self.pages = {}
        self.inject_delay = inject_delay
        self.fail_on_page = fail_on_page
        self.inject_calls = 0

    async def inject_pages(self, page_ids, k, v):
        self.inject_calls += 1
        if self.fail_on_page is not None and self.fail_on_page in page_ids:
            raise RuntimeError(f"boom on page {self.fail_on_page}")
        if self.inject_delay:
            await asyncio.sleep(self.inject_delay)
        for i, p in enumerate(page_ids):
            self.pages[int(p)] = (np.asarray(k)[:, i].copy(),
                                  np.asarray(v)[:, i].copy())


def _pages(n, seed=0, dtype=np.float32):
    rng = np.random.RandomState(seed)
    shape = (SHAPE[0], n) + SHAPE[2:]
    return (rng.randn(*shape).astype(dtype) * 0.3,
            rng.randn(*shape).astype(dtype) * 0.3)


async def _frames(page_ids, k, v, chunk_pages, compress=False):
    """Chunk producer mirroring PrefillWorker._frames, fed from arrays."""
    for off in range(0, len(page_ids), chunk_pages):
        kc = np.ascontiguousarray(k[:, off:off + chunk_pages])
        vc = np.ascontiguousarray(v[:, off:off + chunk_pages])
        dst = page_ids[off:off + chunk_pages]
        extra = {"shape": list(kc.shape), "dtype": str(kc.dtype),
                 "k_len": kc.nbytes}
        if compress:
            from dynamo_tpu.engine.kv_compress import quantize_pages_np

            kq, ks = quantize_pages_np(kc)
            vq, vs = quantize_pages_np(vc)
            extra.update(quant="int8", k_len=kq.nbytes)
            yield dst, extra, [kq, vq, ks, vs], (kq.nbytes + vq.nbytes
                                                 + ks.nbytes + vs.nbytes)
        else:
            yield dst, extra, [kc, vc], kc.nbytes + vc.nbytes


def n_chunks(n_pages, cp):
    return -(-n_pages // cp)


async def _server(engine):
    server = KvTransferServer(engine)
    await server.start(host="127.0.0.1")
    return server


def test_encode_parts_matches_encode():
    """Multi-buffer zero-copy framing is byte-identical on the wire to the
    concatenating encoder, and decodable by both decoders."""
    k = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    v = np.ones((2, 3, 4), np.float32)
    # a real registered frame, so this test also passes under
    # DYN_WIRE_VALIDATE=1 (ad-hoc headers are rejected there by design)
    header = {"request_id": "r", "page_ids": [1, 2, 3],
              "shape": list(k.shape), "dtype": str(k.dtype),
              "k_len": k.nbytes, "first_token": 7}
    whole = codec.encode(codec.TwoPartMessage(
        header=header, body=k.tobytes() + v.tobytes()))
    parts = codec.encode_parts(header, [k, v])
    assert b"".join(bytes(p) for p in parts) == whole
    msg, rest = codec.decode_buffer(whole)
    assert rest == b""
    assert msg.header == header
    np.testing.assert_array_equal(
        np.frombuffer(msg.body[:k.nbytes], np.float32).reshape(k.shape), k)


def test_chunked_stream_roundtrip(run_async):
    """A multi-chunk stream lands every page exactly and resolves the
    waiter only on the final commit chunk."""

    async def main():
        eng = FakeEngine()
        server = await _server(eng)
        k, v = _pages(5, seed=1)
        dst = [10, 11, 12, 13, 14]
        client = KvTransferClient("127.0.0.1", server.port)
        fut = server.expect("r1")
        await client.send_kv_chunked(
            "r1", n_chunks(5, 2), _frames(dst, k, v, 2), first_token=99)
        tok = await asyncio.wait_for(fut, 5)
        assert tok == 99
        assert server.chunks_ingested == 3
        assert server.pages_ingested == 5
        assert not server._ingests  # state torn down on commit
        for i, p in enumerate(dst):
            np.testing.assert_array_equal(eng.pages[p][0], k[:, i])
            np.testing.assert_array_equal(eng.pages[p][1], v[:, i])
        client.close()
        await server.stop()

    run_async(main())


def test_interleaved_streams_one_connection_concurrent_progress(run_async):
    """Two requests stream concurrently over ONE client/connection; a slow
    inject for request A must not block request B's commit (the seed held
    a per-client lock across the whole ack wait, serializing them — this
    is the no-head-of-line-blocking regression test)."""

    async def main():
        eng = FakeEngine()
        server = await _server(eng)

        slow_real = eng.inject_pages

        async def slow_inject(page_ids, k, v):
            if 0 in page_ids:  # request A's pages
                await asyncio.sleep(0.5)
            await slow_real(page_ids, k, v)

        eng.inject_pages = slow_inject
        client = KvTransferClient("127.0.0.1", server.port)
        ka, va = _pages(4, seed=2)
        kb, vb = _pages(4, seed=3)
        fut_a = server.expect("a")
        fut_b = server.expect("b")
        t0 = time.monotonic()
        done_at = {}

        async def send(rid, dst, k, v):
            await client.send_kv_chunked(
                rid, n_chunks(4, 2), _frames(dst, k, v, 2), first_token=1)
            done_at[rid] = time.monotonic() - t0

        await asyncio.gather(send("a", [0, 1, 2, 3], ka, va),
                             send("b", [20, 21, 22, 23], kb, vb))
        assert await fut_a == 1 and await fut_b == 1
        # B finished while A's first inject was still sleeping (0.5s)
        assert done_at["b"] < 0.45, done_at
        assert done_at["a"] >= 0.45, done_at
        for p in (0, 1, 2, 3, 20, 21, 22, 23):
            assert p in eng.pages
        client.close()
        await server.stop()

    run_async(main())


def test_concurrent_bulk_sends_share_connection(run_async):
    """Bulk-mode sends also demux acks by request_id — no client-side lock
    across the remote ack wait."""

    async def main():
        eng = FakeEngine()
        server = await _server(eng)

        real = eng.inject_pages

        async def slow_inject(page_ids, k, v):
            if 0 in page_ids:
                await asyncio.sleep(0.5)
            await real(page_ids, k, v)

        eng.inject_pages = slow_inject
        client = KvTransferClient("127.0.0.1", server.port)
        k, v = _pages(2, seed=4)
        fa, fb = server.expect("a"), server.expect("b")
        t0 = time.monotonic()
        done = {}

        async def send(rid, dst):
            await client.send_kv(rid, dst, k, v, first_token=5)
            done[rid] = time.monotonic() - t0

        await asyncio.gather(send("a", [0, 1]), send("b", [30, 31]))
        assert await fa == 5 and await fb == 5
        assert done["b"] < 0.45, done
        client.close()
        await server.stop()

    run_async(main())


def test_chunked_int8_matches_bulk_dequant(run_async):
    """int8-compressed chunks restore byte-identically to what the bulk
    int8 path restores (same quantize → dequantize per page row)."""

    async def main():
        k, v = _pages(4, seed=5)
        dst = [1, 2, 3, 4]

        eng_bulk = FakeEngine()
        server_b = await _server(eng_bulk)
        cb = KvTransferClient("127.0.0.1", server_b.port)
        fut = server_b.expect("r")
        await cb.send_kv("r", dst, k, v, first_token=0, compress=True)
        await asyncio.wait_for(fut, 5)
        cb.close()
        await server_b.stop()

        eng_ch = FakeEngine()
        server_c = await _server(eng_ch)
        cc = KvTransferClient("127.0.0.1", server_c.port)
        fut = server_c.expect("r")
        await cc.send_kv_chunked(
            "r", n_chunks(4, 3), _frames(dst, k, v, 3, compress=True),
            first_token=0)
        await asyncio.wait_for(fut, 5)
        cc.close()
        await server_c.stop()

        for p in dst:
            np.testing.assert_array_equal(eng_bulk.pages[p][0],
                                          eng_ch.pages[p][0])
            np.testing.assert_array_equal(eng_bulk.pages[p][1],
                                          eng_ch.pages[p][1])

    run_async(main())


def test_ingest_failure_fails_waiter_immediately(run_async):
    """A decode-side inject error must fail the waiter NOW (satellite: the
    seed only nacked the sender while the waiter idled out the full
    prefill timeout) and nack the sender."""

    async def main():
        eng = FakeEngine(fail_on_page=12)
        server = await _server(eng)
        client = KvTransferClient("127.0.0.1", server.port)
        k, v = _pages(4, seed=6)
        fut = server.expect("r")
        t0 = time.monotonic()
        with pytest.raises(RuntimeError, match="ingest failed"):
            await client.send_kv_chunked(
                "r", n_chunks(4, 2), _frames([10, 11, 12, 13], k, v, 2),
                first_token=0, timeout=30.0)
        with pytest.raises(RuntimeError, match="boom"):
            await asyncio.wait_for(fut, 1)
        assert time.monotonic() - t0 < 5  # nowhere near any timeout
        assert server.streams_failed >= 1
        client.close()
        await server.stop()

    run_async(main())


def test_bulk_ingest_failure_fails_waiter(run_async):
    """Same fast-fail contract on the legacy bulk frame."""

    async def main():
        eng = FakeEngine(fail_on_page=11)
        server = await _server(eng)
        client = KvTransferClient("127.0.0.1", server.port)
        k, v = _pages(2, seed=7)
        fut = server.expect("r")
        with pytest.raises(RuntimeError, match="ingest failed"):
            await client.send_kv("r", [10, 11], k, v, first_token=0)
        with pytest.raises(RuntimeError, match="boom"):
            await asyncio.wait_for(fut, 1)
        client.close()
        await server.stop()

    run_async(main())


def test_connection_drop_mid_stream_releases_state(run_async):
    """Killing the connection between chunks fails the waiter immediately
    (decode falls back, releasing/quarantining the partially-injected
    pages it owns) and tears down the server's partial ingest state."""

    async def main():
        eng = FakeEngine()
        server = await _server(eng)
        client = KvTransferClient("127.0.0.1", server.port)
        k, v = _pages(6, seed=8)
        dst = [1, 2, 3, 4, 5, 6]
        fut = server.expect("r")

        async def two_chunks_then_die():
            agen = _frames(dst, k, v, 2)
            i = 0
            async for item in agen:
                yield item
                i += 1
                if i == 2:
                    client._writer.close()  # simulate sender crash
                    await asyncio.sleep(0.05)

        with pytest.raises(Exception):
            await client.send_kv_chunked("r", 3, two_chunks_then_die(),
                                         first_token=0, timeout=5.0)
        # the waiter fails the moment the server notices the drop (ack
        # write fails or the reader EOFs) — never idles out a timeout
        with pytest.raises(ConnectionError):
            await asyncio.wait_for(fut, 2)
        await asyncio.sleep(0.05)
        assert not server._ingests  # partial state torn down
        assert "r" not in server._waiters
        assert server.streams_failed >= 1
        client.close()
        await server.stop()

    run_async(main())


def test_abort_frame_tears_down_stream(run_async):
    """A producer error aborts the stream: the server drops partial state
    and fails the waiter without the connection dying (other requests on
    the connection keep working)."""

    async def main():
        eng = FakeEngine()
        server = await _server(eng)
        client = KvTransferClient("127.0.0.1", server.port)
        k, v = _pages(4, seed=9)
        fut = server.expect("r")

        async def broken_producer():
            agen = _frames([1, 2, 3, 4], k, v, 2)
            yield await agen.__anext__()
            raise RuntimeError("extract exploded")

        with pytest.raises(RuntimeError, match="extract exploded"):
            await client.send_kv_chunked("r", 2, broken_producer(),
                                         first_token=0)
        with pytest.raises(RuntimeError, match="aborted"):
            await asyncio.wait_for(fut, 2)
        await asyncio.sleep(0.05)
        assert not server._ingests

        # connection still usable for the next request
        k2, v2 = _pages(2, seed=10)
        fut2 = server.expect("r2")
        await client.send_kv_chunked(
            "r2", 1, _frames([7, 8], k2, v2, 2), first_token=3)
        assert await asyncio.wait_for(fut2, 2) == 3
        client.close()
        await server.stop()

    run_async(main())


def test_late_chunk_after_cancel_never_writes(run_async):
    """Per-chunk late-write guard: once the decode side cancels (timeout →
    pages may be reassigned), arriving chunks are dropped, not injected."""

    async def main():
        eng = FakeEngine()
        server = await _server(eng)
        client = KvTransferClient("127.0.0.1", server.port)
        k, v = _pages(4, seed=11)
        fut = server.expect("r")

        async def cancel_after_first():
            agen = _frames([1, 2, 3, 4], k, v, 2)
            yield await agen.__anext__()
            # wait until the server has injected chunk 0, THEN simulate the
            # decode-side timeout before chunk 1 goes out
            while 2 not in eng.pages:
                await asyncio.sleep(0.005)
            server.cancel("r")
            yield await agen.__anext__()

        with pytest.raises(RuntimeError, match="unknown/cancelled"):
            await client.send_kv_chunked("r", 2, cancel_after_first(),
                                         first_token=0)
        assert fut.cancelled()
        # chunk 1 landed (waiter was live), chunk 2 must have been dropped
        assert 1 in eng.pages and 2 in eng.pages
        assert 3 not in eng.pages and 4 not in eng.pages
        client.close()
        await server.stop()

    run_async(main())


# ------------------------------------------------- wire-schema conformance


# one representative on-the-wire header per registered frame; the test
# below asserts this map covers EVERY frame, so adding a frame without an
# example here fails loudly
FRAME_EXAMPLES = {
    "dcp.request_envelope": {"req_id": "r1", "conn": {"address": "h:1",
                                                      "subject": "s"},
                             "payload": b"x", "trace": {"trace_id": "t",
                                                        "span_id": "s"},
                             "deadline_ms": 1500},
    "dcp.request_ack": {"accepted": True, "instance_id": 7},
    "dcp.stats_reply": {"instance_id": 7, "subject": "ns.c.e-7",
                        "inflight": 0, "data": {"kv_active_blocks": 1}},
    "dcp.push_watch": {"push": "watch", "watch_id": 1, "event": "put",
                       "key": "k", "value": b"v"},
    "dcp.push_msg": {"push": "msg", "sid": 1, "subject": "s",
                     "payload": b"x"},
    "dcp.push_req": {"push": "req", "sid": 1, "subject": "s",
                     "payload": b"x", "reply": 9},
    "prefill.remote_request": {"request_id": "r", "token_ids": [1, 2],
                               "sampling": {}, "eos_token_ids": [0],
                               "page_ids": [3], "skip_pages": 0,
                               "engine_id": 1,
                               "trace_ctx": {"trace_id": "t",
                                             "span_id": "s"},
                               "deadline_ms": 1500},
    "kv_transfer.bulk": {"request_id": "r", "page_ids": [1], "shape":
                         [2, 1, 2, 4, 8], "dtype": "float32", "k_len": 512,
                         "first_token": 5, "quant": "int8", "v": 2},
    "kv_transfer.chunk": {"kind": "chunk", "request_id": "r",
                          "chunk_idx": 0, "n_chunks": 1, "page_ids": [1],
                          "shape": [2, 1, 2, 4, 8], "dtype": "float32",
                          "k_len": 512, "first_token": 5, "v": 2},
    "kv_transfer.abort": {"kind": "abort", "request_id": "r", "v": 2},
    "kv_transfer.ack": {"ok": True, "request_id": "r", "chunk_idx": 0,
                        "committed": True, "v": 2},
    "tcp.hello": {"t": "hello", "subject": "abc"},
    "tcp.data": {"t": "data"},
    "tcp.complete": {"t": "complete"},
    "tcp.err": {"t": "err", "message": "boom", "kind": "ValueError"},
    "tcp.ctrl": {"t": "ctrl", "kind": "stop"},
    "blackbox.capture": {"event": "blackbox.capture",
                         "incident_id": "incident-1", "trigger": "manual",
                         "worker_label": "w0", "at_ms": 1000.0,
                         "rings": {"w0": {"anchors": {}, "events": []}}},
}


def test_every_registered_frame_roundtrips_validated(monkeypatch):
    """DYN_WIRE_VALIDATE=1: every frame in the registry encodes through
    the codec hook (frame inference + schema check) and decodes back
    byte-identically through both decoders."""
    monkeypatch.setenv("DYN_WIRE_VALIDATE", "1")
    assert set(FRAME_EXAMPLES) == set(wire.FRAMES), (
        "add an example header for every registered frame")
    for name, header in FRAME_EXAMPLES.items():
        inferred = wire.infer_frame(header)
        assert inferred.name == name, (name, inferred.name)
        blob = codec.encode(codec.TwoPartMessage(header=header, body=b"b"))
        msg, rest = codec.decode_buffer(blob)
        assert rest == b"" and msg.header == header
        # the multi-part encoder runs the same hook
        parts = codec.encode_parts(header, [b"b"])
        assert b"".join(bytes(p) for p in parts) == blob
        # anchors are identity + validation
        assert wire.checked(name, header) is header
        assert wire.decoded(name, header) is header


def test_validation_rejects_drift_and_unknown(monkeypatch):
    monkeypatch.setenv("DYN_WIRE_VALIDATE", "1")
    with pytest.raises(wire.UnknownWireFrame):
        codec.encode(codec.TwoPartMessage(header={"zzz": 1}))
    with pytest.raises(wire.WireValidationError, match="sneaky"):
        wire.checked(wire.KV_TRANSFER_ABORT,
                     {"kind": "abort", "request_id": "r", "sneaky": 1})
    with pytest.raises(wire.WireValidationError, match="request_id"):
        wire.checked(wire.KV_TRANSFER_ABORT, {"kind": "abort"})
    with pytest.raises(wire.WireValidationError, match="expects int"):
        wire.checked(wire.KV_TRANSFER_ACK,
                     {"ok": True, "request_id": "r", "chunk_idx": "zero"})
    # decode side: absent fields = legacy peer, accepted; unknown = drift
    assert wire.decoded(wire.KV_TRANSFER_ACK, {"ok": True}) == {"ok": True}
    with pytest.raises(wire.WireValidationError, match="made_up"):
        wire.decoded(wire.KV_TRANSFER_ACK, {"ok": True, "made_up": 1})


def test_validation_off_is_identity():
    """Default (DYN_WIRE_VALIDATE unset): anchors never inspect frames."""
    junk = {"totally": "unregistered"}
    assert wire.checked(wire.KV_TRANSFER_ABORT, junk) is junk
    assert wire.decoded(wire.KV_TRANSFER_ABORT, junk) is junk


def test_chunked_roundtrip_under_validation(run_async, monkeypatch):
    """The real streaming pipeline end-to-end with the debug validation
    hot: every chunk, ack and commit frame passes the registry check."""
    monkeypatch.setenv("DYN_WIRE_VALIDATE", "1")

    async def main():
        eng = FakeEngine()
        server = await _server(eng)
        k, v = _pages(4, seed=21)
        client = KvTransferClient("127.0.0.1", server.port)
        fut = server.expect("rv")
        await client.send_kv_chunked(
            "rv", n_chunks(4, 2), _frames([5, 6, 7, 8], k, v, 2),
            first_token=11)
        assert await asyncio.wait_for(fut, 5) == 11
        client.close()
        await server.stop()

    run_async(main())


def test_unknown_frame_kind_rejected_typed(run_async):
    """Satellite: a frame with an unknown kind is refused with a logged,
    typed error — the waiter fails fast and the sender gets a nack — not
    a KeyError three frames deep in the ingest worker."""

    async def main():
        eng = FakeEngine()
        server = await _server(eng)
        client = KvTransferClient("127.0.0.1", server.port)
        fut = server.expect("rx")
        await client._ensure()
        q = client._register("rx")
        client._writer.writelines(codec.encode_parts(
            {"kind": "zstd-delta", "request_id": "rx", "page_ids": [1]}))
        await client._writer.drain()
        ack = await asyncio.wait_for(q.get(), 5)
        assert ack["ok"] is False and "unsupported" in ack["error"]
        with pytest.raises(wire.WireVersionMismatch):
            await asyncio.wait_for(fut, 1)
        assert server.streams_failed >= 1
        assert not eng.pages  # nothing was injected
        # the connection survives: a well-formed stream still lands
        k, v = _pages(2, seed=22)
        fut2 = server.expect("ry")
        await client.send_kv_chunked(
            "ry", 1, _frames([7, 8], k, v, 2), first_token=3)
        assert await asyncio.wait_for(fut2, 5) == 3
        client.close()
        await server.stop()

    run_async(main())


def test_newer_schema_version_rejected_typed(run_async):
    """A chunk frame stamped v=99 (a future schema) is rejected up front;
    absent v = legacy and keeps working (covered by every other test)."""

    async def main():
        eng = FakeEngine()
        server = await _server(eng)
        client = KvTransferClient("127.0.0.1", server.port)
        fut = server.expect("rz")
        await client._ensure()
        q = client._register("rz")
        client._writer.writelines(codec.encode_parts(
            {"kind": "chunk", "request_id": "rz", "chunk_idx": 0,
             "n_chunks": 1, "page_ids": [], "shape": [], "dtype": "float32",
             "k_len": 0, "first_token": 0, "v": 99}))
        await client._writer.drain()
        ack = await asyncio.wait_for(q.get(), 5)
        assert ack["ok"] is False and "v=99" in ack["error"]
        with pytest.raises(wire.WireVersionMismatch):
            await asyncio.wait_for(fut, 1)
        client.close()
        await server.stop()

    run_async(main())


def test_sender_stage_stats_accumulate(run_async):
    """The sender's per-stage breakdown counts every chunk and byte."""

    async def main():
        eng = FakeEngine()
        server = await _server(eng)
        stats = TransferStats()
        client = KvTransferClient("127.0.0.1", server.port, stats=stats)
        k, v = _pages(4, seed=12)
        fut = server.expect("r")
        await client.send_kv_chunked(
            "r", n_chunks(4, 1), _frames([1, 2, 3, 4], k, v, 1),
            first_token=0)
        await asyncio.wait_for(fut, 2)
        assert stats.chunks_sent == 4
        assert stats.bytes_sent == k.nbytes + v.nbytes
        assert stats.sends == 1
        assert stats.wall_seconds > 0
        assert server.bytes_ingested == stats.bytes_sent
        client.close()
        await server.stop()

    run_async(main())
