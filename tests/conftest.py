"""Test configuration: force an 8-device virtual CPU mesh so sharding
semantics are testable without TPU hardware (SURVEY.md §4 TPU test plan)."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import asyncio  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture
def run_async():
    """Run an async fn to completion on a fresh event loop."""

    def _run(coro):
        return asyncio.run(coro)

    return _run
