"""Test configuration: force an 8-device virtual CPU mesh so sharding
semantics are testable without TPU hardware (SURVEY.md §4 TPU test plan)."""

import os

# force CPU even when the ambient environment points at a TPU (JAX_PLATFORMS
# is pre-set to the TPU platform in the serving image); set DYN_TEST_TPU=1 to
# run the suite against real hardware instead
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
if not os.environ.get("DYN_TEST_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    # the TPU platform plugin overrides JAX_PLATFORMS in jax.config; force
    # it back before the backend initializes
    jax.config.update("jax_platforms", "cpu")

import asyncio  # noqa: E402
import sys  # noqa: E402

import pytest  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def run_async():
    """Run an async fn to completion on a fresh event loop."""

    def _run(coro):
        return asyncio.run(coro)

    return _run


@pytest.fixture
def device_subprocess(tmp_path):
    """Write a worker script and run it in a subprocess whose XLA_FLAGS
    force exactly N virtual CPU devices BEFORE jax imports (the flag is
    read once at backend init, so in-process monkeypatching cannot do
    this). Shared by test_tp_serving and test_sharded_serving — see
    tests/device_harness.py."""
    from device_harness import run_device_subprocess

    def _run(source: str, *args, devices: int = 8, timeout: float = 600,
             env: dict = None):
        script = tmp_path / "device_worker.py"
        script.write_text(source)
        return run_device_subprocess(script, args, devices=devices,
                                     timeout=timeout, env_extra=env)

    return _run
