"""Test configuration: force an 8-device virtual CPU mesh so sharding
semantics are testable without TPU hardware (SURVEY.md §4 TPU test plan)."""

import os

# force CPU even when the ambient environment points at a TPU (JAX_PLATFORMS
# is pre-set to the TPU platform in the serving image); set DYN_TEST_TPU=1 to
# run the suite against real hardware instead
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
if not os.environ.get("DYN_TEST_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    # the TPU platform plugin overrides JAX_PLATFORMS in jax.config; force
    # it back before the backend initializes
    jax.config.update("jax_platforms", "cpu")

import asyncio  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture
def run_async():
    """Run an async fn to completion on a fresh event loop."""

    def _run(coro):
        return asyncio.run(coro)

    return _run
