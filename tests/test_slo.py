"""dynaslo: fleet-wide SLO engine (ISSUE 14).

Covers the four layers the tentpole wires together:

- the mergeable fixed-bucket histogram: PROPERTY tests for merge
  order-invariance, quantile error bounded by one bucket vs the exact
  nearest-rank implementation it shares a module with, and
  cumulative-bucket monotonicity of the rendered Prometheus lines;
- the SLO registry (objective grammar), the multi-window burn-rate
  engine on an injected clock, goodput accounting, and the pressure
  signals (min of fast/slow burn = the alert conjunction, continuous);
- the planner's P/D rebalance policy (decide_pd) and the metric→plane
  SYNC GATE: every metric an objective may name renders a histogram
  family on the aggregator /metrics plane (PR 11 gate pattern);
- the aggregator's fleet-wide merge (merged quantiles == single-worker
  computation, role labels rendered), the engine's stats-plane export,
  the frontend /debug/slo endpoint, and THE pd_rebalance fleet gate:
  TTFT burn alert fires under the prefill-heavy phase, the planner's
  pd advisory actuates a decode→prefill role shift, post-rebalance TTFT
  p95 and ITL p99 both meet SLO, byte-identical per seed.
"""

import asyncio
import json
import os
import random
import sys
from bisect import bisect_left

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from dynamo_tpu.runtime import slo  # noqa: E402


# ------------------------------------------------ histogram property tests


def test_merge_is_lossless_and_order_invariant():
    """Any partition of an observation stream across N histograms,
    merged in any order, equals the single-histogram result exactly."""
    rng = random.Random(7)
    vals = [rng.uniform(0.0005, 700.0) for _ in range(2000)]
    single = slo.Histogram()
    for v in vals:
        single.observe(v)
    for trial in range(3):
        r = random.Random(trial)
        parts = [slo.Histogram() for _ in range(5)]
        for v in vals:
            r.choice(parts).observe(v)
        r.shuffle(parts)
        merged = slo.Histogram()
        for p in parts:
            merged.merge(p)
        assert merged.counts == single.counts
        assert merged.count == single.count == len(vals)
        assert abs(merged.sum - single.sum) < 1e-6


def test_quantile_error_bounded_by_one_bucket_vs_nearest_rank():
    """The histogram quantile is the upper bound of the bucket holding
    the EXACT nearest-rank observation — error <= one bucket width."""
    rng = random.Random(3)
    for trial in range(6):
        n = rng.randint(1, 400)
        vals = [rng.uniform(0.0005, 500.0) for _ in range(n)]
        h = slo.Histogram()
        for v in vals:
            h.observe(v)
        for q in (0.5, 0.9, 0.95, 0.99):
            exact = slo.nearest_rank(vals, q * 100.0)
            expected_ub = h.ubs[bisect_left(h.ubs, exact)]
            assert h.quantile(q) == expected_ub


def test_rendered_prometheus_buckets_are_cumulative_monotonic():
    rng = random.Random(11)
    h = slo.Histogram()
    for _ in range(500):
        h.observe(rng.uniform(0.0005, 2000.0))  # incl. +Inf observations
    lines = h.render_prom("dyn_slo_ttft_seconds", 'role="decode"')
    bucket_vals = [float(ln.rsplit(" ", 1)[1])
                   for ln in lines if "_bucket{" in ln]
    assert len(bucket_vals) == len(h.ubs) + 1  # every le + +Inf
    assert all(b >= a for a, b in zip(bucket_vals, bucket_vals[1:]))
    assert bucket_vals[-1] == h.count  # +Inf == count
    count_line = [ln for ln in lines if "_count{" in ln][0]
    assert float(count_line.rsplit(" ", 1)[1]) == h.count


def test_wire_roundtrip_and_grid_mismatch_refused():
    h = slo.Histogram()
    for v in (0.002, 0.3, 45.0, 10_000.0):
        h.observe(v)
    rt = slo.Histogram.from_wire(h.to_wire())
    assert rt.counts == h.counts and rt.count == h.count
    assert rt.ubs == h.ubs
    other = slo.Histogram((1.0, 2.0))
    with pytest.raises(ValueError):
        h.merge(other)


def test_quantile_edges_and_attainment():
    h = slo.Histogram()
    assert h.quantile(0.5) is None and h.fraction_le(1.0) is None
    h.observe(10_000.0)  # beyond the last bound
    assert h.quantile(0.99) == h.ubs[-1]  # clamped to the last bound
    assert h.fraction_le(600.0) == 0.0
    h2 = slo.Histogram()
    for v in (0.1, 0.1, 0.1, 5.0):
        h2.observe(v)
    assert h2.fraction_le(0.1) == 0.75  # threshold ON a bound is inclusive
    # weighted observe: n gaps of gap/n
    h3 = slo.Histogram()
    h3.observe(0.05, n=4)
    assert h3.count == 4 and h3.fraction_le(0.05) == 1.0


# ------------------------------------------------- registry / objectives


def test_objective_grammar():
    obj = slo.parse_objective("ttft<=2.5@0.95/16")
    assert (obj.name, obj.metric, obj.threshold_s, obj.target,
            obj.window_s) == ("ttft", "ttft", 2.5, 0.95, 16.0)
    named = slo.parse_objective("tail=itl<=0.25@0.99/300")
    assert named.name == "tail" and named.metric == "itl"
    # threshold snaps onto the bucket grid (log-nearest)
    assert slo.parse_objective("ttft<=0.3@0.9/60").threshold_s == 0.25
    for bad in ("nope<=1@0.9/60", "ttft<=1@1.5/60", "ttft<=1@0.9/0",
                "ttft<1@0.9/60", ""):
        with pytest.raises(ValueError):
            slo.parse_objective(bad)
    with pytest.raises(ValueError):  # duplicate names
        slo.SloRegistry.parse("ttft<=1@0.9/60;ttft<=2@0.9/60")
    reg = slo.SloRegistry.parse("ttft<=1@0.9/60;itl<=0.05@0.99/60",
                                fast_fraction=0.25, burn_threshold=1.5)
    assert [o.name for o in reg.objectives] == ["ttft", "itl"]
    assert reg.fast_fraction == 0.25 and reg.burn_threshold == 1.5
    assert slo.SloRegistry.parse("").objectives == []


def test_latency_recorder_keeps_role_split_across_flips():
    rec = slo.LatencyRecorder("decode")
    rec.observe("ttft", 0.1)
    rec.role = "prefill"
    rec.observe("queue_wait", 0.2)
    wire = rec.to_wire()
    assert set(wire) == {"decode", "prefill"}
    merged = slo.merge_latency_wire([wire])
    assert merged["decode"]["ttft"].count == 1
    assert merged["prefill"]["queue_wait"].count == 1
    flat = slo.collapse_roles(merged)
    assert flat["ttft"].count == 1 and flat["queue_wait"].count == 1


# ------------------------------------------- burn-rate engine (fake clock)


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _engine_with_source():
    clock = _Clock()
    hist = slo.Histogram()
    reg = slo.SloRegistry.parse("ttft<=0.25@0.9/10",
                                fast_fraction=0.2, burn_threshold=2.0)
    eng = slo.SloEngine(reg, source=lambda: {"ttft": hist}, clock=clock)
    return eng, hist, clock


def test_multiwindow_burn_alert_fires_and_clears():
    eng, hist, clock = _engine_with_source()
    # 10 ticks of healthy traffic: no alert
    for _ in range(10):
        hist.observe(0.1, n=5)
        clock.t += 1.0
        assert eng.tick() == []
    ev = eng.evaluate()["ttft"]
    assert ev["attainment"] == 1.0 and not ev["alert"]
    assert ev["error_budget_remaining"] == 1.0
    # sustained badness: both windows burn past threshold -> one fired
    events = []
    for _ in range(6):
        hist.observe(5.0, n=5)
        clock.t += 1.0
        events += eng.tick()
    assert [e["state"] for e in events] == ["fired"]
    fired = events[0]
    assert fired["burn_fast"] >= 2.0 and fired["burn_slow"] >= 2.0
    ev = eng.evaluate()["ttft"]
    assert ev["alert"] and ev["error_budget_remaining"] < 0
    # recovery: healthy traffic until both windows drain -> cleared
    for _ in range(12):
        hist.observe(0.1, n=20)
        clock.t += 1.0
        events += eng.tick()
    assert [e["state"] for e in events] == ["fired", "cleared"]
    assert not eng.evaluate()["ttft"]["alert"]
    # the transition log is what the fleet report archives
    assert [e["state"] for e in eng.alert_events] == ["fired", "cleared"]


def test_pressure_is_min_of_fast_and_slow_burn():
    """A fresh spike burns the fast window before the slow one; pressure
    (the planner input) must track the LAGGING window so a blip alone
    never actuates a rebalance."""
    eng, hist, clock = _engine_with_source()
    for _ in range(10):
        hist.observe(0.1, n=10)
        clock.t += 1.0
        eng.tick()
    hist.observe(5.0, n=5)  # one bad burst
    clock.t += 1.0
    eng.tick()
    ev = eng.evaluate()["ttft"]
    assert ev["burn_fast"] > ev["burn_slow"] > 0.0
    assert eng.pressures()["ttft_pressure"] == round(
        min(ev["burn_fast"], ev["burn_slow"]), 6)
    assert eng.pressures()["itl_pressure"] == 0.0  # no objective -> 0


def test_window_quantiles_are_windowed():
    eng, hist, clock = _engine_with_source()
    hist.observe(0.1, n=100)
    clock.t += 1.0
    eng.tick()
    for _ in range(5):
        hist.observe(5.0, n=10)
        clock.t += 1.0
        eng.tick()
    # a 5s window sees only the bad tail; the lifetime view would not
    assert eng.window_quantiles("ttft", 5.0)["p50"] == 5.0
    assert eng.window_quantiles("ttft", 1e9)["p50"] == 0.1


def test_goodput_tracker():
    reg = slo.SloRegistry.parse("ttft<=1@0.9/60;e2e<=10@0.9/60")
    gp = slo.GoodputTracker(reg)
    assert gp.observe_request({"ttft": 0.5, "e2e": 5.0})
    assert not gp.observe_request({"ttft": 2.0, "e2e": 5.0})
    assert gp.observe_request({"e2e": 5.0})  # absent metric is skipped
    gp.observe_failed()
    snap = gp.snapshot()
    assert (snap["good"], snap["total"]) == (2, 4)
    assert snap["rate"] == 0.5
    assert snap["misses_by_objective"] == {"e2e": 0, "ttft": 1}
    lines = gp.render_prom_lines()
    assert any('verdict="good"} 2' in ln for ln in lines)
    assert any('verdict="bad"} 2' in ln for ln in lines)


# ------------------------------------------------------ planner pd policy


def _pd_snapshot(prefill=1, decode=3):
    from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics
    from dynamo_tpu.planner.policy import ComponentSnapshot

    metrics = {}
    for i in range(prefill):
        metrics[i] = ForwardPassMetrics(role="prefill")
    for i in range(decode):
        metrics[100 + i] = ForwardPassMetrics(role="decode")
    return ComponentSnapshot(component="pool", metrics=metrics)


def test_decide_pd_shifts_toward_burning_side():
    from dynamo_tpu.planner.policy import PdConfig, decide_pd

    pd = PdConfig(enabled=True, ttft_burn_high=1.5, itl_burn_high=1.5,
                  min_prefill=1, min_decode=2, shift_cooldown_s=8.0)
    snap = _pd_snapshot(prefill=1, decode=3)
    # ttft burning, itl quiet -> decode->prefill
    adv = decide_pd(snap, pd, {"ttft_pressure": 3.0, "itl_pressure": 0.1},
                    now=100.0)
    assert adv is not None and adv.kind == "pd_shift"
    assert (adv.shift_from, adv.shift_to) == ("decode", "prefill")
    assert adv.current_replicas == adv.desired_replicas == 4
    assert adv.direction == "hold"
    d = adv.to_dict()
    assert d["kind"] == "pd_shift" and d["shift_to"] == "prefill"
    # wire round-trip keeps the shift fields
    from dynamo_tpu.planner.policy import ScaleAdvisory
    assert ScaleAdvisory.from_dict(d).shift_from == "decode"
    # itl burning -> prefill->decode (needs prefill above the floor)
    adv = decide_pd(_pd_snapshot(prefill=2, decode=2), pd,
                    {"ttft_pressure": 0.0, "itl_pressure": 3.0}, now=100.0)
    assert (adv.shift_from, adv.shift_to) == ("prefill", "decode")


def test_decide_pd_respects_floors_cooldown_and_quiet():
    from dynamo_tpu.planner.policy import PdConfig, decide_pd

    pd = PdConfig(enabled=True, ttft_burn_high=1.5, itl_burn_high=1.5,
                  min_prefill=1, min_decode=2, shift_cooldown_s=8.0)
    hot = {"ttft_pressure": 3.0, "itl_pressure": 0.0}
    # decode floor blocks the donor side
    assert decide_pd(_pd_snapshot(prefill=2, decode=2), pd, hot,
                     now=100.0) is None
    # cooldown
    assert decide_pd(_pd_snapshot(), pd, hot, now=100.0,
                     last_shift_at=95.0) is None
    # quiet pressures / disabled policy
    assert decide_pd(_pd_snapshot(), pd,
                     {"ttft_pressure": 0.5, "itl_pressure": 0.5},
                     now=100.0) is None
    pd_off = PdConfig(enabled=False)
    assert decide_pd(_pd_snapshot(), pd_off, hot, now=100.0) is None
    # prefill floor blocks the reverse shift
    assert decide_pd(_pd_snapshot(prefill=1, decode=3), pd,
                     {"ttft_pressure": 0.0, "itl_pressure": 9.0},
                     now=100.0) is None


# ---------------------------------------------- metric -> plane sync gate


def _offline_aggregator(worker_metrics, registry=None):
    """A render-ready MetricsAggregator without a runtime (the PR 11
    sentinel-render pattern)."""
    from dynamo_tpu.metrics.component import MetricsAggregator
    from dynamo_tpu.runtime.slo import SloEngine, SloRegistry

    agg = MetricsAggregator.__new__(MetricsAggregator)
    agg.namespace = "gate"
    agg.worker_metrics = dict(worker_metrics)
    agg.hit_rate_isl_blocks = agg.hit_rate_overlap_blocks = 0
    agg.hit_rate_events = 0
    agg.scrape_failures_total = agg.consecutive_scrape_failures = 0
    agg._client = None
    agg._latency_seen = {wid: m.latency_hist
                         for wid, m in worker_metrics.items()
                         if m.latency_hist}
    agg.slo = SloEngine(registry or SloRegistry(),
                        source=agg.merged_latency_all_roles,
                        clock=lambda: 0.0)
    return agg


def test_every_objective_metric_renders_on_the_metrics_plane():
    """SYNC GATE: every metric the objective grammar accepts must render
    a histogram family on the aggregator /metrics plane once a worker
    has observed it — an objective can never name a metric the plane
    cannot show (PR 11 gate pattern)."""
    from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics

    rec = slo.LatencyRecorder("decode")
    for metric in slo.METRICS:
        rec.observe(metric, 0.1)
    fpm = ForwardPassMetrics(role="decode", latency_hist=rec.to_wire())
    reg = slo.SloRegistry.parse(";".join(
        f"{m}<=0.5@0.9/60" for m in slo.METRICS))
    agg = _offline_aggregator({7: fpm}, registry=reg)
    agg.slo.tick()
    text = agg.render_prometheus()
    for obj in reg.objectives:
        family = f"dyn_slo_{obj.metric}_seconds_bucket"
        assert family in text, (
            f"objective {obj.name!r} names metric {obj.metric!r} but "
            f"{family} is not on the rendered /metrics plane")
        assert f'dyn_slo_attainment{{namespace="gate",' \
               f'objective="{obj.name}"}}' in text
    # pressure + alert gauges present for the planner/pager to scrape
    assert 'dyn_slo_pressure{namespace="gate",signal="ttft_pressure"}' \
        in text
    assert 'dyn_slo_alert_active' in text
    # the frontend plane renders its own families for the metrics it can
    # source (ttft histogram is the promoted satellite)
    from dynamo_tpu.llm.http.metrics import Metrics
    m = Metrics()
    m.observe_ttft("m", 0.1)
    m.observe_itl("m", 0.01)
    m.observe_duration("m", 1.0)
    front = m.render()
    assert "dyn_llm_http_service_time_to_first_token_seconds_bucket" \
        in front
    src = m._slo_source()
    assert set(src) == {"ttft", "itl", "e2e"}
    assert src["ttft"].count == 1


def test_frontend_ttft_histogram_keeps_sum_count_lines():
    """Satellite: TTFT promoted summary->histogram keeps the legacy
    _sum/_count lines (existing scrapers keep working) and gains
    scrapeable buckets."""
    from dynamo_tpu.llm.http.metrics import Metrics

    m = Metrics()
    m.observe_ttft("llama", 0.3)
    m.observe_ttft("llama", 7.0)
    text = m.render()
    pfx = "dyn_llm_http_service_time_to_first_token_seconds"
    assert f'{pfx}_sum{{model="llama"}} 7.3' in text
    assert f'{pfx}_count{{model="llama"}} 2' in text
    assert f'{pfx}_bucket{{model="llama",le="0.5"}} 1' in text
    assert f'{pfx}_bucket{{model="llama",le="+Inf"}} 2' in text
    assert f"# TYPE {pfx} histogram" in text


# ------------------------------------- aggregator fleet-wide merge + roles


def test_aggregator_merged_quantiles_match_single_worker_exact():
    """Acceptance: fleet-merged quantiles == the single-histogram result
    over the union stream (lossless merge), within one bucket of the
    exact nearest-rank value, with role labels rendered."""
    from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics

    rng = random.Random(5)
    vals = [rng.uniform(0.001, 80.0) for _ in range(900)]
    union = slo.Histogram()
    workers = {}
    recs = [slo.LatencyRecorder("decode") for _ in range(3)]
    for i, v in enumerate(vals):
        union.observe(v)
        recs[i % 3].observe("ttft", v)
    for wid, rec in enumerate(recs):
        workers[wid] = ForwardPassMetrics(role="decode",
                                          latency_hist=rec.to_wire())
    # plus a prefill-role worker whose histogram must NOT pollute decode
    prec = slo.LatencyRecorder("prefill")
    prec.observe("queue_wait", 0.5)
    workers[99] = ForwardPassMetrics(role="prefill",
                                     latency_hist=prec.to_wire())
    agg = _offline_aggregator(workers)
    merged = agg.merged_latency()
    h = merged["decode"]["ttft"]
    assert h.counts == union.counts
    for q in (0.5, 0.95, 0.99):
        assert h.quantile(q) == union.quantile(q)
        exact = slo.nearest_rank(vals, q * 100.0)
        assert h.quantile(q) == h.ubs[bisect_left(h.ubs, exact)]
    text = agg.render_prometheus()
    assert 'dyn_slo_ttft_seconds_bucket{namespace="gate",role="decode"' \
        in text
    assert 'dyn_slo_queue_wait_seconds_bucket{namespace="gate",' \
           'role="prefill"' in text
    assert 'metric="ttft",role="decode",quantile="p95"' in text


# --------------------------------------------- engine stats-plane export


def test_engine_exports_role_and_latency_histograms(run_async):
    from tests.test_cache_obs import _gen, _tiny_engine

    async def scenario():
        engine = _tiny_engine()
        assert engine.role == "unified"
        await _gen(engine, list(range(1, 13)), n=6)
        st = engine.stats()
        await engine.stop()
        return st

    st = run_async(scenario())
    assert st["role"] == "unified"
    hists = slo.merge_latency_wire([st["latency_hist"]])["unified"]
    # one request: 1 queue-wait, 1 ttft, 1 e2e, >=1 per-token itl gap
    for metric in ("queue_wait", "ttft", "e2e"):
        assert hists[metric].count == 1, metric
    assert hists["itl"].count >= 1
    assert hists["e2e"].sum >= hists["ttft"].sum


def test_disagg_wrappers_label_roles():
    class _Eng:
        def __init__(self):
            self.role = "unified"

        def set_role(self, r):
            self.role = r

    from dynamo_tpu.llm.disagg.decode import DisaggDecodeEngine
    from dynamo_tpu.llm.disagg.router import DisaggRouter

    eng = _Eng()
    DisaggDecodeEngine(eng, queue=None, transfer=None,
                       router=DisaggRouter(), engine_id=1)
    assert eng.role == "decode"


# ------------------------------------------------- /debug/slo endpoint


def test_debug_slo_endpoint(run_async):
    async def main():
        import aiohttp

        from dynamo_tpu.llm.http.metrics import Metrics
        from dynamo_tpu.llm.http.service import HttpService

        metrics = Metrics()
        reg = slo.SloRegistry.parse("ttft<=0.5@0.9/60;e2e<=10@0.9/60")
        metrics.slo_registry = reg
        metrics.goodput = slo.GoodputTracker(reg)
        metrics.slo = slo.SloEngine(reg, source=metrics._slo_source)
        service = HttpService(metrics=metrics)
        await service.start(host="127.0.0.1", port=0)
        try:
            metrics.observe_ttft("m", 0.1)
            metrics.observe_request_slo({"ttft": 0.1, "e2e": 1.0})
            metrics.observe_request_slo({"ttft": 2.0, "e2e": 1.0})
            async with aiohttp.ClientSession() as http:
                async with http.get(
                        f"http://127.0.0.1:{service.port}"
                        f"/debug/slo") as resp:
                    assert resp.status == 200
                    return await resp.json()
        finally:
            await service.stop()

    snap = run_async(main())
    assert [o["name"] for o in snap["registry"]["objectives"]] \
        == ["ttft", "e2e"]
    assert snap["goodput"] == {"good": 1, "total": 2, "rate": 0.5,
                               "misses_by_objective": {"e2e": 0,
                                                       "ttft": 1}}
    assert "ttft" in snap["evaluation"]
    assert "ttft_pressure" in snap["pressures"]


# ----------------------------------------------- THE pd_rebalance gate


def test_pd_rebalance_closes_the_loop_and_is_byte_identical(run_async):
    """Tier-1 acceptance gate (burst-scenario pattern, doubled): the
    prefill-heavy phase fires the TTFT burn-rate alert, the planner's
    pd advisory actuates a decode→prefill role shift, post-rebalance
    TTFT p95 AND ITL p99 meet their objectives, and the report is
    byte-identical across independent runs of the same seed."""
    from dynamo_tpu.fleet import get_scenario, run_scenario

    r1 = run_async(run_scenario(get_scenario("pd_rebalance"), seed=0))
    r2 = run_async(run_scenario(get_scenario("pd_rebalance"), seed=0))
    assert json.dumps(r1, sort_keys=True) == json.dumps(r2, sort_keys=True)

    d = r1["dynaslo"]
    sc = get_scenario("pd_rebalance")
    heavy = next(p for p in sc.traffic(0).phases
                 if p.name == "prefill-heavy")
    # the multi-window TTFT burn alert fired during the prefill-heavy
    # phase (virtual time == step here: step_seconds=1)
    fired = [a for a in d["alerts"]
             if a["objective"] == "ttft" and a["state"] == "fired"]
    assert fired and heavy.start <= fired[0]["at"] < heavy.end, d["alerts"]
    # the planner emitted a pd advisory and the controller actuated it
    shifts = [a for a in r1["advisories"] if a.get("kind") == "pd_shift"]
    assert shifts and shifts[0]["shift_from"] == "decode" \
        and shifts[0]["shift_to"] == "prefill"
    acted = [a for a in r1["actuations"]
             if a["action"].startswith("pd-shift") and a["workers"]]
    assert acted, r1["actuations"]
    assert sum(1 for role in d["roles_final"].values()
               if role == "prefill") > sc.initial_prefill_workers
    # post-rebalance: TTFT p95 recovered to SLO without ITL p99 regressing
    post = d["post_rebalance"]
    assert post["phase"] == "rebalanced"
    assert post["ttft_met"] and post["itl_met"], post
    # scenario-level SLO + request accounting stay clean
    assert r1["slo"]["met"], r1["phases"]
    assert r1["requests"]["failed"] == 0
    assert d["goodput"]["rate"] is not None \
        and d["goodput"]["rate"] > 0.8
    assert d["prefill_pool"]["completed"] == d["prefill_pool"]["enqueued"]
    # per-phase per-role quantiles came from the mergeable histograms
    assert "decode" in d["phase_role_quantiles"]["rebalanced"]


# ------------------------------------------------------------ fleet units


def test_prefill_pool_fifo_and_skip_finished():
    from dynamo_tpu.fleet.worker import PrefillPool, _SimRequest

    pool = PrefillPool()
    a = _SimRequest("a", list(range(100)), 4, 1)
    b = _SimRequest("b", list(range(50)), 4, 1)
    pool.enqueue(a)
    pool.enqueue(b)
    pool.step(60)           # FIFO: a gets all 60
    assert not a.pool_done and a.pool_left == 40
    a.finished = True       # crash/abandon: capacity skips it
    pool.step(50)
    assert b.pool_done and pool.depth == 0
    assert pool.completed_total == 1


def test_budgeted_decode_degrades_itl_not_tokens():
    """decode_budget_per_step splits a worker's decode throughput over
    active requests — contention shows up in the ITL histogram."""
    from dynamo_tpu.fleet import SimEngineModel, WorkerProfile
    from dynamo_tpu.fleet.clock import VirtualClock

    clock = VirtualClock()
    model = SimEngineModel(
        "w0", WorkerProfile(slots=4, prefill_steps=1, tokens_per_step=4,
                            decode_budget_per_step=8),
        block_size=8, clock=clock.now, on_lifecycle=lambda *a: None,
        role="decode")
    for i in range(4):
        model.submit(f"r{i}", list(range(16)), max_tokens=8)
    for _ in range(8):
        model.step()
        clock.advance()
    assert model.served_total == 4
    hists = slo.merge_latency_wire([model.latency.to_wire()])["decode"]
    # 4 requests sharing budget 8 -> 2 tokens/req/step -> ITL 0.5s/token,
    # strictly worse than the uncontended 0.25 (1s / 4 tokens)
    assert hists["itl"].count > 0
    assert hists["itl"].quantile(0.99) >= 0.5
    assert model.stats()["role"] == "decode"


def test_scheduler_skips_prefill_role_workers():
    from dynamo_tpu.llm.kv_router.indexer import OverlapScores
    from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics
    from dynamo_tpu.llm.kv_router.scheduler import KvScheduler

    sched = KvScheduler(block_size=16, rng=random.Random(0))
    sched.update_metrics({
        1: ForwardPassMetrics(role="prefill", request_total_slots=8,
                              kv_total_blocks=64),
        2: ForwardPassMetrics(role="decode", request_total_slots=8,
                              kv_total_blocks=64),
    })
    for _ in range(4):
        assert sched.schedule(32, OverlapScores()) == 2
    # a fleet of only prefill workers is unroutable
    sched.update_metrics({
        1: ForwardPassMetrics(role="prefill", request_total_slots=8,
                              kv_total_blocks=64)})
    with pytest.raises(RuntimeError):
        sched.schedule(32, OverlapScores())


def test_fleet_report_percentile_is_the_shared_impl():
    from dynamo_tpu.fleet.report import percentile

    assert percentile is slo.nearest_rank
    assert percentile([3.0, 1.0, 2.0], 50) == 2.0
    assert percentile([], 95) is None


@pytest.mark.slow
def test_pd_rebalance_other_seed(run_async):
    """Slow sweep: the loop closes on a different trace too."""
    from dynamo_tpu.fleet import get_scenario, run_scenario

    report = run_async(run_scenario(get_scenario("pd_rebalance"), seed=2))
    assert report["slo"]["met"], report["phases"]
    assert [a for a in report["actuations"]
            if a["action"].startswith("pd-shift")]
    assert report["dynaslo"]["post_rebalance"]["ttft_met"]
