"""Regression tests for the true-positive races dynarace (PR 8) surfaced.

Each test pins a fixed interleaving bug: stats assembly acting on
instances that departed during the scrape gather, the router's zeroed
fallback metrics clobbering a scrape that landed mid-wait, the prefill
worker's transfer-client cache double-connecting under concurrent
misses, the transfer client's ack demux yanking the shared writer out
from under an in-flight send, and double-release on concurrent stop().
"""

from __future__ import annotations

import asyncio
import os
import sys
from types import SimpleNamespace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics  # noqa: E402
from dynamo_tpu.llm.kv_router.router import KvRouter  # noqa: E402
from dynamo_tpu.runtime import guard  # noqa: E402
from dynamo_tpu.runtime.component import (Client, EndpointAddress,  # noqa: E402
                                          EndpointInstance)


def _instance(wid: int) -> EndpointInstance:
    return EndpointInstance("ns", "c", "e", wid, f"subj-{wid:x}")


# --------------------------------------------- Client.collect_stats assembly


def test_collect_stats_skips_instances_departed_mid_gather(run_async):
    """An instance deleted by the watch loop WHILE the stats gather is in
    flight must not have its breakers resurrected by the assembly loop —
    the pre-await target snapshot is stale by then (DL012 shape)."""

    client = Client.__new__(Client)
    client.address = EndpointAddress("ns", "c", "e")
    client.instances = {7: _instance(7)}
    client.breakers = guard.BreakerBoard("test", guard.BreakerConfig())
    client.retry = guard.RetryPolicy(max_attempts=1, base_s=0.001,
                                     cap_s=0.002)

    async def request(subject, payload, timeout=None):
        # simulate the watch loop's delete landing during the probe
        client.instances.pop(7, None)
        client.breakers.drop("stats", 7)
        client.breakers.drop("request", 7)
        raise RuntimeError("probe raced the delete")

    client.drt = SimpleNamespace(dcp=SimpleNamespace(request=request))

    out = run_async(client.collect_stats(timeout=0.1))
    assert out == {}
    # before the fix, record_failure() re-created a breaker for the dead
    # instance via breakers.get(...) — a ghost gauge row that never dies
    assert ("stats", 7) not in client.breakers.breakers


def test_collect_stats_still_records_for_live_instances(run_async):
    client = Client.__new__(Client)
    client.address = EndpointAddress("ns", "c", "e")
    client.instances = {7: _instance(7)}
    client.breakers = guard.BreakerBoard("test", guard.BreakerConfig())
    client.retry = guard.RetryPolicy(max_attempts=1, base_s=0.001,
                                     cap_s=0.002)

    async def request(subject, payload, timeout=None):
        raise RuntimeError("down")

    client.drt = SimpleNamespace(dcp=SimpleNamespace(request=request))
    run_async(client.collect_stats(timeout=0.1))
    assert client.breakers.get("stats", 7).failures == 1


# ------------------------------------------------ router fallback clobbering


def test_router_fallback_does_not_clobber_fresh_metrics(run_async):
    """schedule() with an empty scheduler waits for instances; if a real
    scrape lands DURING that wait, the zeroed fallback metrics must not
    overwrite it (the router would dogpile the busiest worker)."""

    async def publish(subject, payload):
        return None

    drt = SimpleNamespace(dcp=SimpleNamespace(publish=publish))
    router = KvRouter(drt, "ns", "c", block_size=4, seed=0)
    real = ForwardPassMetrics(request_active_slots=3,
                              request_total_slots=8,
                              kv_active_blocks=5, kv_total_blocks=10)

    class FakeClient:
        async def collect_stats(self, timeout=None):
            return {}            # stats plane empty: forces the fallback

        def instance_ids(self):
            return [1]

        async def wait_for_instances(self, timeout=30.0):
            # a scrape completes while schedule() is parked here
            router.scheduler.update_metrics({1: real})
            return [1]

    router.client = FakeClient()

    async def go():
        return await router.schedule([1, 2, 3, 4, 5])

    wid = run_async(go())
    assert wid == 1
    assert router.scheduler.workers[1].metrics.request_active_slots == 3


# ------------------------------------- prefill-worker transfer-client cache


def test_transfer_client_cache_single_connection_per_engine(run_async):
    """Two concurrent cache misses for the same engine must converge on
    ONE client; the loser's fresh connection is closed, not leaked, and
    the cache is never clobbered (read-lookup-store TOCTOU)."""
    from dynamo_tpu.llm.disagg import prefill_worker as pw_mod
    from dynamo_tpu.llm.disagg.prefill_worker import PrefillWorker
    from dynamo_tpu.llm.disagg.transfer import TransferStats

    made = []

    class StubClient:
        def __init__(self):
            self.closed = False
            made.append(self)

        def close(self):
            self.closed = True

    async def fake_lookup(dcp, namespace, engine_id, stats=None):
        await asyncio.sleep(0)   # force the interleave at the await
        return StubClient()

    pw = PrefillWorker.__new__(PrefillWorker)
    pw._clients = {}
    pw.drt = SimpleNamespace(dcp=None)
    pw.namespace = "ns"
    pw.xfer = TransferStats()

    orig = pw_mod.KvTransferClient.lookup
    pw_mod.KvTransferClient.lookup = staticmethod(fake_lookup)
    try:
        async def go():
            return await asyncio.gather(pw._client(1), pw._client(1))

        a, b = run_async(go())
    finally:
        pw_mod.KvTransferClient.lookup = orig

    assert a is b
    assert len(made) == 2            # both tasks looked up...
    assert sum(1 for c in made if not c.closed) == 1  # ...one survived
    assert pw._clients[1] is a


# --------------------------------------- transfer-client writer demux race


def test_ack_loop_nulls_writer_under_lock_and_fails_pending(run_async):
    """Connection loss in the ack demux must fail every pending send AND
    clear the shared writer under _conn_lock so the next send
    reconnects; senders keep the writer _ensure returned, so the null
    can never yank it out from under an in-flight frame."""
    from dynamo_tpu.llm.disagg.transfer import KvTransferClient

    class StubWriter:
        def __init__(self):
            self.closed = False

        def is_closing(self):
            return self.closed

        def close(self):
            self.closed = True

    class BoomReader:
        async def readexactly(self, n):
            raise ConnectionError("peer died")

    async def go():
        client = KvTransferClient("h", 1)
        w = StubWriter()
        client._writer = w
        # _ensure returns the live writer without reconnecting — the
        # local reference senders must hold across their awaits
        assert await client._ensure() is w
        q = client._register("r1")
        await client._ack_loop(BoomReader(), w)
        assert client._writer is None       # cleared under the lock
        assert w.closed
        err = q.get_nowait()
        assert err["conn_lost"] and not err["ok"]

    run_async(go())


# ----------------------------------------------- stop() double-release races


def test_controller_concurrent_stop_unsubscribes_once(run_async):
    """Two stop() calls racing at the unsubscribe await must release the
    subscription exactly once (claim-before-await)."""
    from dynamo_tpu.fleet.controller import FleetController

    calls = []

    async def unsubscribe(sid):
        calls.append(sid)
        await asyncio.sleep(0)

    ctl = FleetController.__new__(FleetController)
    ctl._sid = 42
    ctl.drt = SimpleNamespace(dcp=SimpleNamespace(unsubscribe=unsubscribe))

    async def go():
        await asyncio.gather(ctl.stop(), ctl.stop())

    run_async(go())
    assert calls == [42]
    assert ctl._sid is None


def test_publisher_concurrent_stop_cancels_once(run_async):
    """KvEventPublisher.stop() claims the task before awaiting the join:
    concurrent stops see None and skip, and a task handle is never
    cancelled twice through the publisher."""
    from dynamo_tpu.llm.kv_router.publisher import KvEventPublisher

    class PM:
        def drain_events(self):
            return []

    pub = KvEventPublisher.__new__(KvEventPublisher)
    pub.engine = SimpleNamespace(pm=PM())
    pub.dcp = SimpleNamespace()
    pub.subject = "s"
    pub.worker_id = 1

    async def go():
        pub._task = asyncio.get_running_loop().create_task(
            asyncio.sleep(30))
        await asyncio.gather(pub.stop(), pub.stop())

    run_async(go())
    assert pub._task is None
