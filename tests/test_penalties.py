"""Sampling penalties (repetition / frequency / presence): semantics of
apply_penalties vs the HF logits processor and OpenAI definitions, and
the serving path end-to-end (penalties must bite inside the fused decode
window, across windows, and on the prefill first token). The reference
serves these via vLLM SamplingParams; SamplingOptions carried the fields
since round 1 but silently ignored them until round 5."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.sampling import SamplingBatch, apply_penalties
from dynamo_tpu.llm.protocols.common import (PreprocessedRequest,
                                             SamplingOptions,
                                             StopConditions)
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.runtime.engine import Context


def test_repetition_penalty_matches_transformers():
    """HF RepetitionPenaltyLogitsProcessor is the oracle: tokens present
    in the context get positive logits divided / negative multiplied."""
    import torch
    from transformers import RepetitionPenaltyLogitsProcessor

    rng = np.random.RandomState(0)
    V = 40
    logits = rng.randn(1, V).astype(np.float32) * 3
    ctx = np.array([[3, 7, 7, 12]])
    proc = RepetitionPenaltyLogitsProcessor(penalty=1.7)
    want = proc(torch.tensor(ctx), torch.tensor(logits)).numpy()

    presence = np.zeros((1, V), np.int8)
    presence[0, ctx[0]] = 1
    got = apply_penalties(jnp.asarray(logits), jnp.zeros((1, V), jnp.int32),
                          jnp.asarray(presence),
                          jnp.asarray([1.7], jnp.float32),
                          jnp.zeros(1), jnp.zeros(1))
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-6)


def test_frequency_presence_penalties_openai_semantics():
    """OpenAI: logits[t] -= freq*count[t] + pres*(count[t]>0), counts
    over GENERATED tokens only."""
    V = 10
    logits = jnp.zeros((1, V), jnp.float32)
    counts = jnp.asarray(np.array([[0, 1, 3, 0, 0, 0, 0, 0, 0, 0]],
                                  np.int32))
    out = apply_penalties(logits, counts, jnp.zeros((1, V), jnp.int8),
                          jnp.ones(1), jnp.asarray([0.5]),
                          jnp.asarray([0.25]))
    out = np.asarray(out)[0]
    assert out[0] == 0.0
    np.testing.assert_allclose(out[1], -0.5 * 1 - 0.25)
    np.testing.assert_allclose(out[2], -0.5 * 3 - 0.25)


def test_sampling_batch_detects_penalties():
    none = SamplingBatch.build([SamplingOptions()], 1)
    assert not none.has_penalties
    assert SamplingBatch.build(
        [SamplingOptions(repetition_penalty=1.3)], 1).has_penalties
    assert SamplingBatch.build(
        [SamplingOptions(frequency_penalty=0.5)], 2).has_penalties
    assert SamplingBatch.build(
        [SamplingOptions(presence_penalty=0.1)], 1).has_penalties


def _run_engine(req_opts, prompt, n, run_async, **ecfg_over):
    from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine

    cfg = ModelConfig.tiny()
    base = dict(page_size=8, num_pages=64, max_batch=4, prefill_chunk=32,
                prefill_buckets=(32,), batch_buckets=(4,),
                page_buckets=(16,), decode_steps=4)
    base.update(ecfg_over)
    eng = JaxEngine(cfg, EngineConfig(**base), seed=0)
    if base.get("warmup_penalties"):
        eng.warmup()  # must pre-compile the penalized window variants

    async def go():
        req = PreprocessedRequest(
            token_ids=list(prompt), sampling=req_opts,
            stop=StopConditions(max_tokens=n, ignore_eos=True),
            eos_token_ids=[])
        toks = []
        async for out in eng.generate(req, Context()):
            toks.extend(out.token_ids)
            if out.finish_reason:
                break
        await eng.stop()
        return toks

    return run_async(go())


def test_engine_repetition_penalty_breaks_greedy_loops(run_async):
    """A strong repetition penalty must change the GREEDY continuation
    (penalties apply before argmax) and strictly reduce repetition vs
    the unpenalized run — across multiple K=4 windows, so the device
    in-window counts AND the host rebuild both participate."""
    prompt = [(i * 11) % 200 + 1 for i in range(12)]
    plain = _run_engine(SamplingOptions(), prompt, 24, run_async)
    pen = _run_engine(SamplingOptions(repetition_penalty=8.0), prompt, 24,
                      run_async)
    assert len(plain) == len(pen) == 24

    def max_count(toks):
        _, c = np.unique(np.asarray(toks), return_counts=True)
        return int(c.max())

    # tiny random models loop hard under greedy; the penalty must break
    # that loop measurably
    assert max_count(pen) < max_count(plain), (plain, pen)
    assert pen != plain


def test_engine_presence_penalty_no_pipelining_correctness(run_async):
    """Presence-penalized batches force the in-flight window to land
    before dispatch (host counts must be accurate); the run completes
    with the requested token count and differs from the plain run."""
    prompt = [5, 9, 2, 6, 5, 3]
    plain = _run_engine(SamplingOptions(), prompt, 16, run_async)
    pen = _run_engine(SamplingOptions(presence_penalty=2.0), prompt, 16,
                      run_async)
    assert len(pen) == 16
    assert pen != plain


def test_no_penalties_path_untouched(run_async):
    """Requests without penalties keep the exact pre-penalty program —
    token-identical to a run before this feature (pins the None path)."""
    prompt = [3, 1, 4, 1, 5]
    a = _run_engine(SamplingOptions(), prompt, 12, run_async)
    b = _run_engine(SamplingOptions(), prompt, 12, run_async)
    assert a == b and len(a) == 12


def test_warmup_penalties_flag(run_async):
    """warmup_penalties=True pre-compiles the penalized window variants;
    a penalty request then serves through the warmed engine."""
    toks = _run_engine(SamplingOptions(repetition_penalty=2.0),
                       [1, 2, 3, 4], 8, run_async,
                       warmup_penalties=True)
    assert len(toks) == 8


def test_logit_bias_forces_and_bans_tokens(run_async):
    """OpenAI logit_bias: +100 effectively forces a token under greedy,
    -100 bans it — end-to-end through the engine (dense bias array rides
    the penalty tuple as a 6th element)."""
    forced = _run_engine(SamplingOptions(logit_bias={7: 100.0}),
                         [1, 2, 3], 6, run_async)
    assert forced == [7] * 6

    plain = _run_engine(SamplingOptions(), [1, 2, 3], 6, run_async)
    banned = _run_engine(
        SamplingOptions(logit_bias={int(plain[0]): -100.0}),
        [1, 2, 3], 6, run_async)
    assert banned[0] != plain[0]


def test_logit_bias_http_mapping():
    """The OpenAI request's {str token id: bias} map reaches
    SamplingOptions as {int: float} (the preprocessor conversion)."""
    from dynamo_tpu.llm.protocols.openai import ChatCompletionRequest

    req = ChatCompletionRequest(
        model="m", messages=[{"role": "user", "content": "x"}],
        logit_bias={"42": -100, "7": 2.5})
    assert req.logit_bias == {"42": -100, "7": 2.5}
    mapped = {int(k): float(v) for k, v in req.logit_bias.items()}
    assert mapped == {42: -100.0, 7: 2.5}
