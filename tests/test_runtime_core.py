"""Runtime-core tests: DCP control plane (KV/lease/watch, pub-sub,
request-reply, queues), two-part codec, and the end-to-end component
request/response path (reference test model: lib/runtime/tests/)."""

import asyncio

import pytest

from dynamo_tpu.runtime import (Annotated, Context, DcpClient, DcpServer,
                                DistributedRuntime, NoRespondersError, pack,
                                unpack)
from dynamo_tpu.runtime.codec import TwoPartMessage, decode_buffer, encode
from dynamo_tpu.runtime.dcp_server import subject_matches


def test_subject_matching():
    assert subject_matches("a.b.c", "a.b.c")
    assert subject_matches("a.*.c", "a.b.c")
    assert not subject_matches("a.*.c", "a.b.d")
    assert subject_matches("a.>", "a.b.c")
    assert subject_matches("a.>", "a.b")
    assert not subject_matches("a.>", "a")
    assert not subject_matches("a.b", "a.b.c")


def test_two_part_codec_roundtrip():
    # a registered frame header, so this also passes under
    # DYN_WIRE_VALIDATE=1 (the codec hook rejects ad-hoc headers there)
    msg = TwoPartMessage({"t": "err", "message": "x", "kind": "E"},
                         b"\x00\x01payload\xff")
    buf = encode(msg)
    decoded, rest = decode_buffer(buf + b"extra")
    assert decoded.header == {"t": "err", "message": "x", "kind": "E"}
    assert decoded.body == b"\x00\x01payload\xff"
    assert rest == b"extra"
    # corruption detected
    bad = bytearray(buf)
    bad[-1] ^= 0xFF
    with pytest.raises(Exception):
        decode_buffer(bytes(bad))


def test_kv_lease_watch(run_async):
    async def main():
        server = await DcpServer.start()
        c1 = await DcpClient.connect(server.address)
        c2 = await DcpClient.connect(server.address)

        # basic KV
        await c1.kv_put("config/a", b"1")
        assert await c2.kv_get("config/a") == b"1"
        assert await c2.kv_get("config/missing") is None
        assert await c1.kv_create("config/a", b"2") is False  # already exists
        assert await c1.kv_create("config/b", b"2") is True

        items = await c2.kv_get_prefix("config/")
        assert [(i.key, i.value) for i in items] == [
            ("config/a", b"1"), ("config/b", b"2")]

        # watch sees put + lease-expiry delete
        items, watch = await c2.kv_watch_prefix("inst/")
        assert items == []
        lease = await c1.lease_grant(ttl=0.5)
        await c1.kv_put("inst/x", b"alive", lease=lease)
        ev = await asyncio.wait_for(watch.__anext__(), 2)
        assert (ev.event, ev.key, ev.value) == ("put", "inst/x", b"alive")
        # no keepalive → expiry → delete event
        ev = await asyncio.wait_for(watch.__anext__(), 3)
        assert (ev.event, ev.key) == ("delete", "inst/x")
        assert await c1.kv_get("inst/x") is None
        await watch.stop()

        # lease revoke deletes attached keys immediately
        lease2 = await c1.lease_grant(ttl=30)
        await c1.kv_put("inst/y", b"v", lease=lease2)
        await c1.lease_revoke(lease2)
        assert await c1.kv_get("inst/y") is None

        await c1.close()
        await c2.close()
        await server.stop()

    run_async(main())


def test_kv_compare_and_swap(run_async):
    """mod_rev-guarded writes (reference etcd.rs transactional guard):
    a concurrent writer makes the stale CAS fail instead of silently
    reverting the other write."""

    async def main():
        server = await DcpServer.start()
        c1 = await DcpClient.connect(server.address)
        c2 = await DcpClient.connect(server.address)

        await c1.kv_put("spec/x", b"v1")
        item = await c1.kv_get_item("spec/x")
        assert item is not None and item.mod_rev > 0

        # concurrent writer bumps the revision
        await c2.kv_put("spec/x", b"v2-concurrent")
        # stale CAS must fail and leave the concurrent write intact
        assert await c1.kv_cas("spec/x", b"v3-stale", item.mod_rev) is False
        assert await c1.kv_get("spec/x") == b"v2-concurrent"
        # fresh CAS succeeds
        item = await c1.kv_get_item("spec/x")
        assert await c1.kv_cas("spec/x", b"v3", item.mod_rev) is True
        assert await c1.kv_get("spec/x") == b"v3"
        # prev_rev=0 = create-if-absent semantics
        assert await c1.kv_cas("spec/x", b"v4", 0) is False
        assert await c1.kv_cas("spec/new", b"v1", 0) is True

        await c1.close()
        await c2.close()
        await server.stop()

    run_async(main())


def test_pubsub_and_request_reply(run_async):
    async def main():
        server = await DcpServer.start()
        pub = await DcpClient.connect(server.address)
        sub1 = await DcpClient.connect(server.address)
        sub2 = await DcpClient.connect(server.address)

        got1, got2 = [], []

        async def h1(msg):
            got1.append(msg.payload)

        async def h2(msg):
            got2.append(msg.payload)

        await sub1.subscribe("events.kv", h1)
        await sub2.subscribe("events.kv", h2)
        await pub.publish("events.kv", b"e1")
        await asyncio.sleep(0.1)
        assert got1 == [b"e1"] and got2 == [b"e1"]  # fan-out to plain subs

        # queue group: exactly one member receives each message
        qgot = []

        async def hq(msg):
            qgot.append(msg.payload)

        await sub1.subscribe("work.items", hq, group="g")
        await sub2.subscribe("work.items", hq, group="g")
        for i in range(4):
            await pub.publish("work.items", bytes([i]))
        await asyncio.sleep(0.1)
        assert sorted(qgot) == [bytes([i]) for i in range(4)]

        # request/reply
        async def echo(msg):
            await msg.respond(b"re:" + msg.payload)

        await sub1.subscribe("svc.echo", echo, group="workers")
        assert await pub.request("svc.echo", b"hi") == b"re:hi"

        with pytest.raises(NoRespondersError):
            await pub.request("svc.nobody", b"hi", timeout=2)

        await pub.close()
        await sub1.close()
        await sub2.close()
        await server.stop()

    run_async(main())


def test_work_queue(run_async):
    async def main():
        server = await DcpServer.start()
        a = await DcpClient.connect(server.address)
        b = await DcpClient.connect(server.address)

        assert await a.queue_pull("q1") is None  # empty, no wait
        await a.queue_put("q1", b"item1")
        assert await a.queue_len("q1") == 1
        assert await b.queue_pull("q1") == b"item1"

        # blocking pull woken by a later put
        async def delayed_put():
            await asyncio.sleep(0.1)
            await a.queue_put("q1", b"item2")

        t = asyncio.ensure_future(delayed_put())
        assert await b.queue_pull("q1", timeout=2) == b"item2"
        await t
        await a.close()
        await b.close()
        await server.stop()

    run_async(main())


def test_component_end_to_end(run_async):
    """Worker serves an endpoint; client discovers it and streams responses
    over the TCP call-home plane (reference runtime hello_world example)."""

    async def main():
        drt = await DistributedRuntime.detached()
        ns = drt.namespace("test")

        async def handler(request, context: Context):
            for i in range(int(request["n"])):
                yield {"i": i, "msg": request["msg"]}

        comp = ns.component("greeter")
        await comp.create_service()
        handle = await comp.endpoint("generate").serve(
            handler, stats_handler=lambda: {"custom": 7})

        client = await ns.component("greeter").endpoint("generate").client()
        ids = await client.wait_for_instances()
        assert ids == [drt.instance_id]

        stream = await client.round_robin({"n": 3, "msg": "hello"})
        out = [env.data async for env in stream]
        assert out == [{"i": 0, "msg": "hello"}, {"i": 1, "msg": "hello"},
                       {"i": 2, "msg": "hello"}]

        # direct routing + stats
        stream = await client.direct({"n": 1, "msg": "d"}, ids[0])
        assert [e.data async for e in stream] == [{"i": 0, "msg": "d"}]
        stats = await client.collect_stats()
        assert stats[ids[0]]["data"] == {"custom": 7}

        # errors propagate with their original type (ValueError survives the
        # wire so frontends can map validation errors to 4xx)
        async def failing(request, context):
            yield {"ok": 1}
            raise ValueError("boom")

        fcomp = ns.component("fail")
        await fcomp.endpoint("generate").serve(failing)
        fclient = await fcomp.endpoint("generate").client()
        await fclient.wait_for_instances()
        stream = await fclient.round_robin({})
        with pytest.raises(ValueError, match="boom"):
            async for _ in stream:
                pass

        # withdrawing the endpoint removes it from discovery
        await handle.stop()
        await asyncio.sleep(0.1)
        assert client.instance_ids() == []
        with pytest.raises(NoRespondersError):
            await client.round_robin({"n": 1, "msg": "x"})

        await client.close()
        await fclient.close()
        await drt.shutdown()

    run_async(main())


def test_annotated_envelope():
    a = Annotated(data={"x": 1})
    assert Annotated.from_dict(a.to_dict()).data == {"x": 1}
    err = Annotated.from_error("bad")
    assert err.is_error and err.error_message() == "bad"
    assert unpack(pack({"a": [1, 2, b"x"]})) == {"a": [1, 2, b"x"]}


def test_blocking_pull_does_not_stall_connection(run_async):
    """Regression: a long q_pull on a connection must not serialize other
    ops on the same connection (lease keepalives would miss)."""

    async def main():
        server = await DcpServer.start()
        c = await DcpClient.connect(server.address)

        async def slow_pull():
            return await c.queue_pull("empty", timeout=3)

        t0 = asyncio.get_event_loop().time()
        pull = asyncio.ensure_future(slow_pull())
        await asyncio.sleep(0.05)
        await c.ping()  # must complete while the pull is still waiting
        assert asyncio.get_event_loop().time() - t0 < 1.0
        pull.cancel()
        await c.close()
        await server.stop()

    run_async(main())


def test_server_stop_with_live_clients(run_async):
    """Regression: stop() must not hang while clients are connected
    (Python 3.12 wait_closed waits for handlers)."""

    async def main():
        server = await DcpServer.start()
        c = await DcpClient.connect(server.address)
        await c.ping()
        await asyncio.wait_for(server.stop(), 8)
        await c.close()

    run_async(main())


def test_responder_death_fails_inflight_request(run_async):
    """Regression: if the responder conn dies mid-request, the requester
    gets an immediate error, not a full timeout."""

    async def main():
        server = await DcpServer.start()
        worker = await DcpClient.connect(server.address)
        requester = await DcpClient.connect(server.address)

        async def never_respond(msg):
            await worker.close()  # die before replying

        await worker.subscribe("svc.dead", never_respond, group="g")
        t0 = asyncio.get_event_loop().time()
        with pytest.raises(Exception) as ei:
            await requester.request("svc.dead", b"x", timeout=10)
        assert asyncio.get_event_loop().time() - t0 < 5.0
        assert "disconnect" in str(ei.value)
        await requester.close()
        await server.stop()

    run_async(main())


def test_plain_subscriber_does_not_steal_requests(run_async):
    """Regression: requests route only to queue-group members; a plain
    observer subscription on the subject must not consume them."""

    async def main():
        server = await DcpServer.start()
        observer = await DcpClient.connect(server.address)
        worker = await DcpClient.connect(server.address)
        requester = await DcpClient.connect(server.address)

        observed = []

        async def observe(msg):
            observed.append(msg.payload)  # never responds

        async def serve(msg):
            await msg.respond(b"served:" + msg.payload)

        await observer.subscribe("svc.x", observe)  # plain, no group
        await worker.subscribe("svc.x", serve, group="workers")
        assert await requester.request("svc.x", b"r1", timeout=5) == b"served:r1"
        for c in (observer, worker, requester):
            await c.close()
        await server.stop()

    run_async(main())


def test_object_pool(run_async):
    """RAII object pool (reference utils/pool.rs): items return on
    release, shared items on last clone, factory growth capped."""
    from dynamo_tpu.utils.pool import Pool

    async def scenario():
        pool = Pool(items=["a", "b"], factory=lambda: "c", max_size=3)
        i1 = await pool.acquire()
        i2 = await pool.acquire()
        i3 = await pool.acquire()  # factory-grown
        assert pool.available == 0 and pool.size == 3
        assert pool.try_acquire() is None  # capped
        with i1 as v:
            assert v == "a"
        assert pool.available == 1  # context exit returned it
        sh = i2.share()
        cl = sh.clone()
        sh.release()
        assert pool.available == 1  # still held by the clone
        cl.release()
        assert pool.available == 2
        cl.release()  # double release is a no-op
        assert pool.available == 2
        i3.release()
        assert pool.available == 3
        got = await pool.acquire()
        got.release()
        return True

    assert run_async(scenario())


def test_lease_survives_event_loop_stall(run_async):
    """The primary lease must outlive synchronous work that blocks the
    event loop for multiples of the TTL (engine warmup, bulk host
    transfers): the keepalive runs on its own thread + connection, so a
    stalled loop cannot starve renewals and vaporize every
    lease-attached record (the disagg 'no KV transfer endpoint' failure
    mode)."""
    import time

    from dynamo_tpu.runtime.runtime import DistributedRuntime

    async def main():
        drt = await DistributedRuntime.attach(
            (await _fresh_server()).address, lease_ttl=0.5)
        await drt.dcp.kv_put("inst/me", b"alive", lease=drt.primary_lease)
        # block the loop for 4x the TTL — the old loop-resident keepalive
        # died here and the key vanished
        time.sleep(2.0)
        await asyncio.sleep(0.3)  # let the reaper tick with IO pending
        assert await drt.dcp.kv_get("inst/me") == b"alive"
        # a fresh keepalive still renews after the stall
        await asyncio.sleep(1.0)
        assert await drt.dcp.kv_get("inst/me") == b"alive"
        await drt.shutdown()

    async def _fresh_server():
        return await DcpServer.start()

    run_async(main())
