"""Engine tests: page manager prefix caching/eviction/events, and the JAX
engine end-to-end — continuous batching, prefix reuse, cancellation,
preemption, and the full HTTP-chain integration."""

import asyncio

import numpy as np
import pytest

from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
from dynamo_tpu.engine.kv_manager import PageManager, chain_hashes, hash_block
from dynamo_tpu.llm.protocols.common import (PreprocessedRequest,
                                             SamplingOptions, StopConditions)
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.runtime import Context


def test_chain_hashes_deterministic_and_chained():
    ids = list(range(32))
    h1 = chain_hashes(ids, 16)
    h2 = chain_hashes(ids, 16)
    assert h1 == h2 and len(h1) == 2
    # chaining: second block hash depends on the first
    other = chain_hashes([1] + ids[1:], 16)
    assert other[0] != h1[0] and other[1] != h1[1]
    assert hash_block(0, ids[:16]) == h1[0]


def test_page_manager_prefix_reuse_and_eviction():
    pm = PageManager(num_pages=8, page_size=4)  # 7 usable pages
    prompt = list(range(12))  # 3 blocks
    alloc = pm.allocate_sequence(prompt)
    assert alloc is not None
    pages, cached = alloc
    assert len(pages) == 3 and cached == 0
    # commit the full blocks (as prefill does)
    hashes = chain_hashes(prompt, 4)
    for i, h in enumerate(hashes):
        pm.commit(pages[i], h, parent_hash=hashes[i - 1] if i else None)
    stored = pm.drain_events()
    assert [e.kind for e in stored] == ["stored"] * 3

    # same prompt again: full prefix reuse (capped to leave the tail block)
    alloc2 = pm.allocate_sequence(prompt)
    pages2, cached2 = alloc2
    assert cached2 == 8  # 2 blocks reused; last block recomputed
    assert pages2[:2] == pages[:2]

    pm.release_sequence(pages)
    pm.release_sequence(pages2)
    # all pages now reusable; allocating 7 fresh pages must evict some and
    # emit removed events
    big = pm.allocate_sequence(list(range(100, 128)))  # 7 blocks
    assert big is not None
    removed = [e for e in pm.drain_events() if e.kind == "removed"]
    assert removed  # evictions happened
    assert pm.available == 0


def test_page_manager_oom_returns_none():
    pm = PageManager(num_pages=4, page_size=4)
    a = pm.allocate_sequence(list(range(12)))  # uses all 3 usable pages
    assert a is not None
    assert pm.allocate_sequence(list(range(100, 104))) is None
    assert pm.allocate_page() is None
    pm.release_sequence(a[0])
    assert pm.allocate_page() is not None


def mk_engine(**eng_kw):
    cfg = ModelConfig.tiny()
    defaults = dict(page_size=8, num_pages=64, max_batch=8, prefill_chunk=32)
    defaults.update(eng_kw)
    return JaxEngine(cfg, EngineConfig(**defaults), seed=0)


def mk_request(tokens, max_tokens=8, **sampling):
    return PreprocessedRequest(
        token_ids=list(tokens),
        sampling=SamplingOptions(**sampling),
        stop=StopConditions(max_tokens=max_tokens),
        eos_token_ids=[258])


async def collect(engine, req, ctx=None):
    ctx = ctx or Context()
    toks, finish = [], None
    async for out in engine.generate(req, ctx):
        toks.extend(out.token_ids)
        if out.finish_reason:
            finish = out.finish_reason
            break
    return toks, finish


def test_engine_generates_deterministically(run_async):
    async def main():
        engine = mk_engine()
        req = mk_request(range(10, 30), max_tokens=6)
        toks1, fin1 = await collect(engine, req)
        assert len(toks1) == 6 and fin1 == "length"
        # greedy → identical rerun (and exercises prefix cache reuse)
        toks2, fin2 = await collect(engine, mk_request(range(10, 30),
                                                       max_tokens=6))
        assert toks2 == toks1
        assert engine.prefix_hit_tokens_total > 0  # second run hit the cache
        stats = engine.stats()
        assert stats["request_active_slots"] == 0
        assert stats["kv_active_blocks"] == 0  # everything released
        await engine.stop()

    run_async(main())


def test_engine_concurrent_requests(run_async):
    """Continuous batching: concurrent requests with different lengths and
    sampling all complete; distinct prompts give distinct outputs."""

    async def main():
        engine = mk_engine()
        reqs = [mk_request(range(i * 7 + 1, i * 7 + 12 + i), max_tokens=4 + i)
                for i in range(5)]
        results = await asyncio.gather(*(collect(engine, r) for r in reqs))
        for i, (toks, fin) in enumerate(results):
            assert len(toks) == 4 + i, f"req {i}: {toks}"
            assert fin == "length"
        await engine.stop()

    run_async(main())


def test_engine_cancellation_frees_pages(run_async):
    async def main():
        engine = mk_engine()
        ctx = Context()
        req = mk_request(range(20), max_tokens=10_000)

        async def consume():
            count = 0
            async for out in engine.generate(req, ctx):
                count += len(out.token_ids)
                if count >= 3:
                    ctx.stop_generating()
                if out.finish_reason:
                    return out.finish_reason
            return None

        fin = await asyncio.wait_for(consume(), 30)
        assert fin == "cancelled"
        await asyncio.sleep(0.05)
        assert engine.stats()["kv_active_blocks"] == 0
        await engine.stop()

    run_async(main())


def test_engine_preemption_under_memory_pressure(run_async):
    """More concurrent work than the page pool can hold: preemption +
    re-admission must still complete every request."""

    async def main():
        # 15 usable pages of 8 tokens; 4 requests × (16-token prompt +
        # 16 generated) ≈ 16 pages → forced preemption
        engine = mk_engine(num_pages=16, max_batch=4, watermark_pages=1)
        reqs = [mk_request(range(i * 16, i * 16 + 16), max_tokens=16)
                for i in range(4)]
        results = await asyncio.wait_for(
            asyncio.gather(*(collect(engine, r) for r in reqs)), 120)
        for toks, fin in results:
            assert len(toks) == 16 and fin == "length"
        assert engine.stats()["kv_active_blocks"] == 0
        await engine.stop()

    run_async(main())


def test_engine_behind_full_llm_chain(run_async):
    """JaxEngine behind Backend + preprocessor + HTTP service: the complete
    aggregated serving slice (SURVEY §7 step 3) on CPU."""

    async def main():
        import aiohttp

        from dynamo_tpu.llm.engines import LocalChatChain
        from dynamo_tpu.llm.http.service import HttpService
        from dynamo_tpu.llm.model_card import ModelDeploymentCard

        engine = mk_engine()
        mdc = ModelDeploymentCard(name="tiny-jax", tokenizer_kind="byte",
                                  context_length=256)
        service = HttpService()
        service.manager.add_chat_model("tiny-jax",
                                       LocalChatChain(mdc, engine))
        await service.start(host="127.0.0.1", port=0)
        async with aiohttp.ClientSession() as http:
            body = {"model": "tiny-jax", "stream": False, "max_tokens": 8,
                    "messages": [{"role": "user", "content": "hello"}]}
            async with http.post(
                    f"http://127.0.0.1:{service.port}/v1/chat/completions",
                    json=body) as r:
                assert r.status == 200, await r.text()
                data = await r.json()
        assert data["choices"][0]["finish_reason"] == "length"
        await service.stop()
        await engine.stop()

    run_async(main())


def test_multi_step_decode_matches_single_step(run_async):
    """The fused K-step decode window must produce exactly the same
    tokens as K single steps (greedy and seeded sampling)."""
    import numpy as np

    from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
    from dynamo_tpu.llm.protocols.common import (PreprocessedRequest,
                                                 SamplingOptions,
                                                 StopConditions)
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.runtime.engine import Context

    cfg = ModelConfig.tiny()
    rng = np.random.RandomState(7)
    prompts = [rng.randint(1, 500, n).tolist() for n in (9, 21)]

    async def gen_all(engine):
        outs = []
        for i, p in enumerate(prompts):
            sampling = (SamplingOptions() if i == 0 else
                        SamplingOptions(temperature=0.8, top_k=20, seed=42))
            req = PreprocessedRequest(
                token_ids=p, sampling=sampling,
                stop=StopConditions(max_tokens=11, ignore_eos=True),
                eos_token_ids=[])
            toks = []
            async for out in engine.generate(req, Context()):
                toks.extend(out.token_ids)
                if out.finish_reason:
                    break
            outs.append(toks)
        await engine.stop()
        return outs

    results = {}
    for k in (1, 4):
        ecfg = EngineConfig(page_size=4, num_pages=64, max_batch=4,
                            prefill_chunk=32, prefill_buckets=(32,),
                            batch_buckets=(4,), page_buckets=(16,),
                            decode_steps=k)
        results[k] = run_async(gen_all(JaxEngine(cfg, ecfg, seed=0)))

    assert results[1] == results[4]
    assert all(len(t) == 11 for t in results[4])


def test_on_device_eos_stops_mid_window(run_async):
    """On-device stop masking: pick a token the greedy run emits mid-window
    and declare it EOS on a second run — generation must stop right after
    emitting it, with no trailing tokens from the rest of the window (the
    device freezes the row; the host discards nothing it shouldn't)."""
    cfg = ModelConfig.tiny()
    ecfg = EngineConfig(page_size=4, num_pages=64, max_batch=4,
                        prefill_chunk=32, prefill_buckets=(32,),
                        batch_buckets=(4,), page_buckets=(16,),
                        decode_steps=4)
    prompt = list(range(40, 60))

    async def gen(engine, eos_ids, n):
        req = PreprocessedRequest(
            token_ids=prompt, sampling=SamplingOptions(),
            stop=StopConditions(max_tokens=n), eos_token_ids=eos_ids)
        toks, fin = [], None
        async for out in engine.generate(req, Context()):
            toks.extend(out.token_ids)
            if out.finish_reason:
                fin = out.finish_reason
                break
        await engine.stop()
        return toks, fin

    free, fin1 = run_async(gen(JaxEngine(cfg, ecfg, seed=0), [], 12))
    assert fin1 == "length" and len(free) == 12
    # make the 6th greedy token (lands mid-window for K=4) the stop token
    eos = free[5]
    cut = free[: free.index(eos) + 1]
    got, fin2 = run_async(gen(JaxEngine(cfg, ecfg, seed=0), [eos], 12))
    assert fin2 == "eos"
    assert got == cut


def test_pipeline_toggle_token_identity(run_async):
    """pipeline_decode=False (dispatch+readback each window) and the
    pipelined default must produce identical tokens — the device carry is
    exact, not speculative."""
    cfg = ModelConfig.tiny()
    rng = np.random.RandomState(3)
    prompts = [rng.randint(1, 500, n).tolist() for n in (7, 18, 33)]

    async def gen_all(engine):
        async def one(p, i):
            req = PreprocessedRequest(
                token_ids=p,
                sampling=SamplingOptions(temperature=0.7, top_k=12,
                                         seed=100 + i),
                stop=StopConditions(max_tokens=9, ignore_eos=True),
                eos_token_ids=[])
            toks = []
            async for out in engine.generate(req, Context()):
                toks.extend(out.token_ids)
                if out.finish_reason:
                    break
            return toks
        outs = await asyncio.gather(*(one(p, i)
                                      for i, p in enumerate(prompts)))
        await engine.stop()
        return outs

    results = {}
    for pipe in (False, True):
        ecfg = EngineConfig(page_size=4, num_pages=64, max_batch=4,
                            prefill_chunk=32, prefill_buckets=(32,),
                            batch_buckets=(4,), page_buckets=(16,),
                            decode_steps=3, pipeline_decode=pipe)
        results[pipe] = run_async(gen_all(JaxEngine(cfg, ecfg, seed=0)))

    assert results[False] == results[True]
    assert all(len(t) == 9 for t in results[True])


def test_prefill_token_budget_mixing(run_async):
    """Budgeted chunked-prefill mixing: tokens identical to pure
    prefill-priority, and decode windows demonstrably dispatch while a
    prompt backlog is still prefilling (the decode-starvation fix)."""
    cfg = ModelConfig.tiny()
    rng = np.random.RandomState(7)
    # a running request first, then a burst of long prompts to create a
    # prefill backlog that pure priority would drain before any decode
    first = rng.randint(1, 500, 9).tolist()
    burst = [rng.randint(1, 500, 60).tolist() for _ in range(4)]

    async def gen_all(engine):
        async def one(p, i, delay=0.0):
            if delay:
                await asyncio.sleep(delay)
            req = PreprocessedRequest(
                token_ids=p,
                sampling=SamplingOptions(temperature=0.6, top_k=8,
                                         seed=200 + i),
                stop=StopConditions(max_tokens=12, ignore_eos=True),
                eos_token_ids=[])
            toks = []
            async for out in engine.generate(req, Context()):
                toks.extend(out.token_ids)
                if out.finish_reason:
                    break
            return toks
        outs = await asyncio.gather(
            one(first, 0),
            *(one(p, i + 1, delay=0.05) for i, p in enumerate(burst)))
        await engine.stop()
        return outs

    results = {}
    mixed = {}
    for budget in (None, 32):
        ecfg = EngineConfig(page_size=4, num_pages=128, max_batch=8,
                            prefill_chunk=32, prefill_buckets=(32,),
                            batch_buckets=(8,), page_buckets=(16,),
                            decode_steps=3, prefill_token_budget=budget)
        eng = JaxEngine(cfg, ecfg, seed=0)
        results[budget] = run_async(gen_all(eng))
        mixed[budget] = eng.mixed_dispatches

    assert results[None] == results[32], "budgeted mixing changed tokens"
    assert mixed[32] > 0, "no decode window overlapped the prefill backlog"
    assert mixed[None] == 0  # pure priority never mixes


def test_admission_clamped_to_warmed_grid(run_async):
    """No mid-serving compile: prompts beyond the largest page bucket are
    rejected at admission, and generation is cut at the grid capacity
    instead of growing the page table past the warmed bucket."""
    cfg = ModelConfig.tiny()
    ecfg = EngineConfig(page_size=4, num_pages=64, max_batch=4,
                        prefill_chunk=32, prefill_buckets=(32,),
                        batch_buckets=(4,), page_buckets=(8,),
                        decode_steps=4)

    async def main():
        engine = JaxEngine(cfg, ecfg, seed=0)
        assert engine.cap_tokens == 32
        # over-capacity prompt → error finish, no pages leaked
        req = PreprocessedRequest(
            token_ids=list(range(1, 41)), sampling=SamplingOptions(),
            stop=StopConditions(max_tokens=4), eos_token_ids=[])
        fin = None
        async for out in engine.generate(req, Context()):
            if out.finish_reason:
                fin = out.finish_reason
                break
        assert fin == "error"
        # near-capacity prompt: generation cut at cap_tokens, not max_tokens
        req2 = PreprocessedRequest(
            token_ids=list(range(1, 29)), sampling=SamplingOptions(),
            stop=StopConditions(max_tokens=50, ignore_eos=True),
            eos_token_ids=[])
        toks, fin2 = [], None
        async for out in engine.generate(req2, Context()):
            toks.extend(out.token_ids)
            if out.finish_reason:
                fin2 = out.finish_reason
                break
        assert fin2 == "length"
        assert len(toks) == 32 - 28
        assert engine.pm.active == 0
        await engine.stop()

    run_async(main())


def test_prefill_pallas_flag_token_identity(run_async, monkeypatch):
    """DYN_PREFILL_PALLAS routes chunked prefill through the flash
    kernel (interpret mode on CPU): served tokens must be identical to
    the default XLA gather path — the kernel-in-engine integration, not
    just the kernel math."""
    prompt = list(range(40, 40 + 21))

    def run(flagged):
        if flagged:
            monkeypatch.setenv("DYN_PREFILL_PALLAS", "1")
            monkeypatch.setenv("DYN_PALLAS_INTERPRET", "1")
        else:
            monkeypatch.delenv("DYN_PREFILL_PALLAS", raising=False)
            monkeypatch.delenv("DYN_PALLAS_INTERPRET", raising=False)
        engine = mk_engine(page_size=4, num_pages=32, prefill_chunk=16)

        async def gen():
            toks, fin = await collect(
                engine, mk_request(prompt, max_tokens=6))
            await engine.stop()
            return toks, fin

        return run_async(gen())

    want = run(False)
    got = run(True)
    assert got == want
