"""Kubernetes deployment renderer (reference deploy/dynamo/operator
controllers expanding DynamoDeployment CRs; here a pure function +
helm-chart-style test, deploy/Kubernetes/test_helm_charts.py analog)."""

import importlib.util
import os

import yaml

_spec = importlib.util.spec_from_file_location(
    "k8s_render", os.path.join(os.path.dirname(__file__), "..",
                               "deploy", "kubernetes", "render.py"))
render_mod = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_spec and render_mod)


def test_render_example_deployment():
    path = os.path.join(os.path.dirname(__file__), "..", "deploy",
                        "kubernetes", "example-deployment.yaml")
    with open(path) as f:
        spec = yaml.safe_load(f)
    objs = render_mod.render(spec)
    kinds = [(o["kind"], o["metadata"]["name"]) for o in objs]
    # control plane + configmap
    assert ("Deployment", "llama-disagg-dcp") in kinds
    assert ("Service", "llama-disagg-dcp") in kinds
    assert ("ConfigMap", "llama-disagg-service-config") in kinds
    # one Deployment per service
    for svc in ("routedfrontend", "routedprocessor", "router",
                "tpuworker", "prefillworker"):
        assert ("Deployment", f"llama-disagg-{svc}") in kinds
    # frontend exposed
    assert ("Service", "llama-disagg-routedfrontend") in kinds

    by_name = {o["metadata"]["name"]: o for o in objs
               if o["kind"] == "Deployment"}
    worker = by_name["llama-disagg-tpuworker"]
    podspec = worker["spec"]["template"]["spec"]
    assert podspec["nodeSelector"][
        "cloud.google.com/gke-tpu-accelerator"] == "tpu-v5-lite-podslice"
    assert podspec["containers"][0]["resources"]["limits"][
        "google.com/tpu"] == "4"
    assert worker["spec"]["replicas"] == 4
    # CPU-pinned control services
    router = by_name["llama-disagg-router"]
    env = {e["name"]: e.get("value")
           for e in router["spec"]["template"]["spec"]["containers"][0]["env"]}
    assert env.get("JAX_PLATFORMS") == "cpu"
    assert "llama-disagg-dcp" in env["DYN_DCP_ADDRESS"]
    # everything round-trips through YAML
    yaml.safe_dump_all(objs)
