"""Kubernetes deployment renderer (reference deploy/dynamo/operator
controllers expanding DynamoDeployment CRs; here a pure function +
helm-chart-style test, deploy/Kubernetes/test_helm_charts.py analog)."""

import importlib.util
import os

import yaml

_spec = importlib.util.spec_from_file_location(
    "k8s_render", os.path.join(os.path.dirname(__file__), "..",
                               "deploy", "kubernetes", "render.py"))
render_mod = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_spec and render_mod)


def test_render_example_deployment():
    path = os.path.join(os.path.dirname(__file__), "..", "deploy",
                        "kubernetes", "example-deployment.yaml")
    with open(path) as f:
        spec = yaml.safe_load(f)
    objs = render_mod.render(spec)
    kinds = [(o["kind"], o["metadata"]["name"]) for o in objs]
    # control plane + configmap
    assert ("Deployment", "llama-disagg-dcp") in kinds
    assert ("Service", "llama-disagg-dcp") in kinds
    assert ("ConfigMap", "llama-disagg-service-config") in kinds
    # one Deployment per service
    for svc in ("routedfrontend", "routedprocessor", "router",
                "tpuworker", "prefillworker"):
        assert ("Deployment", f"llama-disagg-{svc}") in kinds
    # frontend exposed
    assert ("Service", "llama-disagg-routedfrontend") in kinds

    by_name = {o["metadata"]["name"]: o for o in objs
               if o["kind"] == "Deployment"}
    worker = by_name["llama-disagg-tpuworker"]
    podspec = worker["spec"]["template"]["spec"]
    assert podspec["nodeSelector"][
        "cloud.google.com/gke-tpu-accelerator"] == "tpu-v5-lite-podslice"
    assert podspec["containers"][0]["resources"]["limits"][
        "google.com/tpu"] == "4"
    assert worker["spec"]["replicas"] == 4
    # CPU-pinned control services
    router = by_name["llama-disagg-router"]
    env = {e["name"]: e.get("value")
           for e in router["spec"]["template"]["spec"]["containers"][0]["env"]}
    assert env.get("JAX_PLATFORMS") == "cpu"
    assert "llama-disagg-dcp" in env["DYN_DCP_ADDRESS"]
    # everything round-trips through YAML
    yaml.safe_dump_all(objs)


def _frontend_spec(ingress, spec_level=True):
    spec = {
        "metadata": {"name": "demo", "namespace": "prod"},
        "spec": {
            "graph": "g:F",
            "services": {
                "Frontend": {"frontend": True, "port": 8080},
                "Debug": {"frontend": False},
            },
        },
    }
    if spec_level:
        spec["spec"]["ingress"] = ingress
    else:
        spec["spec"]["services"]["Frontend"]["ingress"] = ingress
    return spec


def test_render_ingress():
    """spec.ingress → networking/v1 Ingress for the frontend Service
    (reference operator pkg/dynamo/system/ingress.go: class, host,
    path, annotations, TLS from the network config)."""
    objs = render_mod.render(_frontend_spec({
        "className": "nginx", "hostSuffix": "svc.example.com",
        "tlsSecret": "demo-tls",
        "annotations": {"a": "b"},
    }))
    ings = [o for o in objs if o["kind"] == "Ingress"]
    assert len(ings) == 1
    ing = ings[0]
    assert ing["spec"]["ingressClassName"] == "nginx"
    rule = ing["spec"]["rules"][0]
    assert rule["host"] == "demo.svc.example.com"
    p = rule["http"]["paths"][0]
    assert p["pathType"] == "Prefix" and p["path"] == "/"
    assert p["backend"]["service"] == {"name": "demo-frontend",
                                       "port": {"number": 8080}}
    assert ing["spec"]["tls"] == [{"hosts": ["demo.svc.example.com"],
                                   "secretName": "demo-tls"}]
    assert ing["metadata"]["annotations"]["a"] == "b"
    yaml.safe_dump_all(objs)


def test_render_ingress_per_service_and_disabled():
    # per-service placement works too
    objs = render_mod.render(_frontend_spec({"host": "x.io"},
                                            spec_level=False))
    assert any(o["kind"] == "Ingress" for o in objs)
    # enabled: false renders nothing
    objs = render_mod.render(_frontend_spec({"enabled": False,
                                             "host": "x.io"}))
    assert not any(o["kind"] == "Ingress" for o in objs)
    # no ingress key at all renders nothing (backward compatible)
    spec = _frontend_spec({"host": "x"})
    del spec["spec"]["ingress"]
    assert not any(o["kind"] == "Ingress"
                   for o in render_mod.render(spec))


def test_render_debug_canary_ingress():
    """ingress.debugService → a second canary-by-header Ingress — the
    K8s-native form of the reference's Envoy header-routed
    debug/production split (internal/envoy/envoy.go)."""
    objs = render_mod.render(_frontend_spec({
        "className": "nginx", "host": "demo.io",
        "debugService": "Debug", "debugHeader": "x-dyn-debug",
        "debugHeaderValue": "on",
    }))
    ings = {o["metadata"]["name"]: o for o in objs
            if o["kind"] == "Ingress"}
    assert set(ings) == {"demo-frontend", "demo-frontend-debug"}
    # the debug target gets a backing Service even though it is not a
    # frontend — the canary Ingress must have something to route to
    assert any(o["kind"] == "Service"
               and o["metadata"]["name"] == "demo-debug" for o in objs)
    canary = ings["demo-frontend-debug"]
    ann = canary["metadata"]["annotations"]
    assert ann["nginx.ingress.kubernetes.io/canary"] == "true"
    assert ann["nginx.ingress.kubernetes.io/canary-by-header"] == \
        "x-dyn-debug"
    assert ann["nginx.ingress.kubernetes.io/canary-by-header-value"] == \
        "on"
    assert canary["spec"]["rules"][0]["http"]["paths"][0]["backend"][
        "service"]["name"] == "demo-debug"


def test_render_istio_virtualservice():
    """ingress.istio → VirtualService with the debug-header route first
    (reference dynamonimdeployment_controller.go:1133
    createOrUpdateVirtualService)."""
    objs = render_mod.render(_frontend_spec({
        "istio": True, "host": "demo.io", "debugService": "Debug",
    }))
    assert not any(o["kind"] == "Ingress" for o in objs)
    vss = [o for o in objs if o["kind"] == "VirtualService"]
    assert len(vss) == 1
    http = vss[0]["spec"]["http"]
    assert len(http) == 2
    # header-matched route must come first (Istio evaluates in order)
    assert "headers" in http[0]["match"][0]
    assert http[0]["route"][0]["destination"]["host"].startswith(
        "demo-debug.prod")
    assert http[1]["route"][0]["destination"]["host"].startswith(
        "demo-frontend.prod")


def test_spec_ingress_ambiguous_frontends_rejected():
    """Two frontends + one spec-level ingress would claim the same
    host+path with arbitrary routing — render refuses loudly; an
    explicit ingress.service (or per-service blocks) disambiguates."""
    import pytest

    spec = _frontend_spec({"host": "demo.io"})
    spec["spec"]["services"]["Frontend2"] = {"frontend": True,
                                             "port": 8081}
    with pytest.raises(ValueError, match="ambiguous"):
        render_mod.render(spec)
    spec["spec"]["ingress"]["service"] = "Frontend2"
    objs = render_mod.render(spec)
    ings = [o for o in objs if o["kind"] == "Ingress"]
    assert len(ings) == 1
    assert ings[0]["spec"]["rules"][0]["http"]["paths"][0]["backend"][
        "service"]["name"] == "demo-frontend2"


def test_debug_route_uses_debug_services_port():
    """The canary/Istio debug route must target the DEBUG service's own
    port (its backing Service exposes that), not the frontend's."""
    spec = _frontend_spec({"host": "demo.io", "debugService": "Debug"})
    spec["spec"]["services"]["Debug"]["port"] = 9090
    objs = render_mod.render(spec)
    svc = [o for o in objs if o["kind"] == "Service"
           and o["metadata"]["name"] == "demo-debug"][0]
    assert svc["spec"]["ports"][0]["port"] == 9090
    canary = [o for o in objs if o["kind"] == "Ingress"
              and o["metadata"]["name"].endswith("-debug")][0]
    assert canary["spec"]["rules"][0]["http"]["paths"][0]["backend"][
        "service"]["port"]["number"] == 9090
    # Istio variant too
    spec["spec"]["ingress"]["istio"] = True
    vs = [o for o in render_mod.render(spec)
          if o["kind"] == "VirtualService"][0]
    assert vs["spec"]["http"][0]["route"][0]["destination"]["port"][
        "number"] == 9090
    assert vs["spec"]["http"][1]["route"][0]["destination"]["port"][
        "number"] == 8080


def test_dangling_ingress_references_rejected():
    import pytest

    # ingress.service naming a non-frontend
    spec = _frontend_spec({"host": "x.io", "service": "Debug"})
    with pytest.raises(ValueError, match="not a frontend"):
        render_mod.render(spec)
    # ingress.service typo
    spec = _frontend_spec({"host": "x.io", "service": "frontend"})
    with pytest.raises(ValueError, match="not a frontend"):
        render_mod.render(spec)
    # ingress block on a non-frontend service
    spec = _frontend_spec({"host": "x.io"})
    del spec["spec"]["ingress"]
    spec["spec"]["services"]["Debug"]["ingress"] = {"host": "d.io"}
    with pytest.raises(ValueError, match="not.*frontend"):
        render_mod.render(spec)
    # debugService naming an undefined service
    spec = _frontend_spec({"host": "x.io", "debugService": "Debgu"})
    with pytest.raises(ValueError, match="no defined service"):
        render_mod.render(spec)
