"""Flagship example graphs (reference examples/llm/graphs/*): deploy the
KV-routed aggregated graph inline with the tiny JAX engine and drive it
through the OpenAI HTTP frontend."""

import asyncio
import socket

import pytest

from dynamo_tpu.sdk import ServiceConfig, deploy_inline


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_agg_router_graph_end_to_end(run_async):
    import importlib

    import examples.llm.components as comp

    importlib.reload(comp)  # fresh service objects (tests share a process)
    mod = importlib.import_module("examples.llm.graphs.agg_router")
    importlib.reload(mod)

    port = _free_port()
    cfg = ServiceConfig({
        "RoutedFrontend": {"served_model_name": "tiny", "port": port,
                           "host": "127.0.0.1"},
        "RoutedProcessor": {"served_model_name": "tiny", "kv_block_size": 8},
        "Router": {"kv_block_size": 8, "scrape_interval": 0.2},
        "TpuWorker": {"model": "tiny", "served_model_name": "tiny",
                      "kv_block_size": 8, "num_pages": 128},
    })

    async def scenario():
        import aiohttp

        dep = await deploy_inline(mod.Frontend, config=cfg)
        try:
            async with aiohttp.ClientSession() as s:
                # models endpoint
                async with s.get(f"http://127.0.0.1:{port}/v1/models") as r:
                    models = await r.json()
                # streamed chat completion through the routed path
                payload = {"model": "tiny", "stream": True, "max_tokens": 8,
                           "messages": [{"role": "user",
                                         "content": "hello graph"}]}
                chunks = []
                async with s.post(
                        f"http://127.0.0.1:{port}/v1/chat/completions",
                        json=payload) as r:
                    assert r.status == 200, await r.text()
                    async for line in r.content:
                        line = line.decode().strip()
                        if line.startswith("data:"):
                            chunks.append(line[5:].strip())
                # non-streamed completion
                async with s.post(
                        f"http://127.0.0.1:{port}/v1/completions",
                        json={"model": "tiny", "prompt": "abc",
                              "max_tokens": 4}) as r:
                    comp_resp = (r.status, await r.json())
            # router made at least one decision
            router_svc = next(w for w in dep.workers
                              if w.svc.name == "Router")
            router_stats = router_svc.instance.router.stats()
            return models, chunks, comp_resp, router_stats
        finally:
            await dep.stop()
            await dep.drt.shutdown()

    models, chunks, comp_resp, router_stats = run_async(scenario())
    assert models["data"][0]["id"] == "tiny"
    assert chunks[-1] == "[DONE]"
    assert len(chunks) >= 3  # role chunk + >=1 content + [DONE]
    status, body = comp_resp
    assert status == 200
    assert body["object"] == "text_completion"
    assert router_stats["decisions"] >= 1


def test_graph_shapes():
    """All four graphs resolve and contain the expected service sets."""
    import importlib

    import examples.llm.components as comp

    importlib.reload(comp)
    for name, expect in [
        ("agg", {"Frontend", "Processor", "TpuWorker"}),
        ("agg_router", {"RoutedFrontend", "RoutedProcessor", "Router",
                        "TpuWorker"}),
        ("disagg", {"Frontend", "Processor", "TpuWorker", "PrefillWorker"}),
        ("disagg_router", {"RoutedFrontend", "RoutedProcessor", "Router",
                           "TpuWorker", "PrefillWorker"}),
    ]:
        mod = importlib.import_module(f"examples.llm.graphs.{name}")
        importlib.reload(mod)
        got = {s.name for s in mod.Frontend.graph()}
        assert got == expect, f"{name}: {got}"
        # workers precede processors in deployment order
        order = [s.name for s in mod.Frontend.graph()]
        assert order.index("TpuWorker") < max(
            i for i, n in enumerate(order) if "Processor" in n)


def test_int8_worker_graph_end_to_end(run_async):
    """The quantized flagship path (configs/disagg_router_int8.yaml's
    dtype: int8 worker key) serves through the routed graph: the
    worker's engine holds QuantInt8 weights and completions stream."""
    import importlib

    import examples.llm.components as comp

    importlib.reload(comp)
    mod = importlib.import_module("examples.llm.graphs.agg_router")
    importlib.reload(mod)

    port = _free_port()
    cfg = ServiceConfig({
        "RoutedFrontend": {"served_model_name": "tiny", "port": port,
                           "host": "127.0.0.1"},
        "RoutedProcessor": {"served_model_name": "tiny", "kv_block_size": 8},
        "Router": {"kv_block_size": 8, "scrape_interval": 0.2},
        "TpuWorker": {"model": "tiny", "served_model_name": "tiny",
                      "dtype": "int8", "kv_block_size": 8,
                      "num_pages": 128},
    })

    async def scenario():
        import aiohttp

        dep = await deploy_inline(mod.Frontend, config=cfg)
        try:
            worker = next(w for w in dep.workers
                          if w.svc.name == "TpuWorker")
            from dynamo_tpu.models.quant import QuantInt8
            assert isinstance(worker.instance.engine.params["wq"],
                              QuantInt8)
            async with aiohttp.ClientSession() as s:
                async with s.post(
                        f"http://127.0.0.1:{port}/v1/completions",
                        json={"model": "tiny", "prompt": "abc",
                              "max_tokens": 4}) as r:
                    return r.status, await r.json()
        finally:
            await dep.stop()
            await dep.drt.shutdown()

    status, body = run_async(scenario())
    assert status == 200
    assert body["object"] == "text_completion"
    assert body["choices"][0]["text"]
