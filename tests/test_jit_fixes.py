"""Regression tests for the true positives dynajit (DL015-DL017) found
in the engine — each was FIXED, not baselined (tools/dynalint gate), and
each fix is pinned here:

- the host-tier dtype probe resolved the pool dtype through a device
  round-trip (``np.asarray(jnp.zeros((), dtype))``) — DL017;
- ``extract_pages`` / ``inject_pages`` / ``extract_pages_chunked``
  gathered/scattered with request-length page index arrays — one XLA
  compile per distinct page count, mid-serving, on the disagg path —
  DL015. Now pow2-padded (extract trims host-side; inject pads the
  rows and drops the out-of-range scatter targets), so the compiled
  program set is O(log n) and warmable.
"""

import asyncio

import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
from dynamo_tpu.engine.jit_fence import CompileFence
from dynamo_tpu.models.config import ModelConfig


def mk_engine(**eng_kw):
    cfg = ModelConfig.tiny()
    defaults = dict(page_size=8, num_pages=32, max_batch=4,
                    prefill_chunk=32, decode_steps=1,
                    pipeline_decode=False)
    defaults.update(eng_kw)
    return JaxEngine(cfg, EngineConfig(**defaults), seed=0)


# --------------------------------------------------- host-tier dtype probe


def test_host_pool_dtype_without_device_roundtrip():
    """On the lossless tier (``host_tier_int8=False``) the host pools
    must match the device pool dtype (incl. bf16), resolved WITHOUT a
    device round-trip (jax_engine DL017 fix). With the dynaheat
    int8-default tier the host pools are int8 by design."""
    eng = mk_engine(host_pages=8, num_pages=16, host_tier_int8=False)
    assert eng.host_k is not None
    assert eng.host_k.dtype == np.dtype(eng.kv_k.dtype)
    assert eng.host_v.dtype == np.dtype(eng.kv_v.dtype)
    eng_bf16 = JaxEngine(ModelConfig.tiny(),
                         EngineConfig(page_size=8, num_pages=16,
                                      host_pages=8, host_tier_int8=False),
                         seed=0, dtype=jnp.bfloat16)
    assert eng_bf16.host_k.dtype == np.dtype(jnp.bfloat16)
    # int8 tier default-on: host pools hold quantized pages regardless
    # of the device dtype (halved relay bytes; identity pinned in
    # tests/test_kv_offload.py)
    eng_i8 = mk_engine(host_pages=8, num_pages=16)
    assert eng_i8.ecfg.host_tier_int8 is True
    assert eng_i8.host_k.dtype == np.dtype(np.int8)


# ------------------------------------------------ pow2-padded extract/inject


def _rand_pages(eng, n, seed=0):
    rng = np.random.RandomState(seed)
    k = rng.randn(*(eng.kv_k.shape[0], n, *eng.kv_k.shape[2:])) \
        .astype(np.float32)
    v = rng.randn(*(eng.kv_v.shape[0], n, *eng.kv_v.shape[2:])) \
        .astype(np.float32)
    return k, v


def test_extract_inject_roundtrip_identity(run_async):
    """Padded inject → padded extract round-trips content exactly, and
    neither touches pages outside the given ids."""
    eng = mk_engine()

    async def main():
        pages = [3, 7, 11, 2, 9]                     # 5 → pads to 8
        k, v = _rand_pages(eng, len(pages), seed=1)
        before = np.asarray(eng.kv_k)
        await eng.inject_pages(pages, k, v)
        got_k, got_v = await eng.extract_pages(pages)
        np.testing.assert_array_equal(got_k, k)
        np.testing.assert_array_equal(got_v, v)
        # untouched pages keep their content (the pad scatter dropped)
        after = np.asarray(eng.kv_k)
        others = [p for p in range(eng.ecfg.num_pages)
                  if p not in pages]
        np.testing.assert_array_equal(after[:, others], before[:, others])
        await eng.stop()

    run_async(main())


def test_extract_inject_compile_count_is_pow2_bounded(run_async):
    """Distinct page counts within one pow2 bucket share ONE compiled
    gather/scatter program (the DL015 fix): after the first 5-page
    extract+inject compiles the size-8 programs, 6- and 7-page calls
    compile NOTHING new."""
    eng = mk_engine()
    fence = CompileFence("extract-regression", mode="")

    async def main():
        k, v = _rand_pages(eng, 5, seed=2)
        await eng.inject_pages([1, 2, 3, 4, 5], k, v)
        await eng.extract_pages([1, 2, 3, 4, 5])     # compiles size-8
        fence.arm()
        for ids in ([6, 7, 8, 9, 10, 11], [1, 3, 5, 7, 9, 11, 13]):
            ki, vi = _rand_pages(eng, len(ids), seed=len(ids))
            await eng.inject_pages(ids, ki, vi)
            got_k, got_v = await eng.extract_pages(ids)
            np.testing.assert_array_equal(got_k, ki)
            np.testing.assert_array_equal(got_v, vi)
        assert fence.post_warmup_compiles == 0, (
            "a same-bucket page count recompiled the gather/scatter")
        fence.disarm()
        await eng.stop()

    run_async(main())


def test_extract_chunked_pads_final_slice(run_async):
    """The chunked extract's remainder slice is padded to chunk_pages:
    content identity holds and the remainder compiles no fresh gather
    once the full-chunk program exists."""
    eng = mk_engine()
    fence = CompileFence("chunked-regression", mode="")

    async def main():
        pages = [2, 4, 6, 8, 10, 12]                 # 6 pages, chunks of 4
        k, v = _rand_pages(eng, len(pages), seed=3)
        await eng.inject_pages(pages, k, v)
        parts = []
        first = True
        async for off, kc, vc, _dt in eng.extract_pages_chunked(pages, 4):
            if first:
                # the size-4 gather program now exists; the padded
                # 2-page remainder must reuse it
                fence.arm()
                first = False
            parts.append((off, kc, vc))
        assert fence.post_warmup_compiles == 0, (
            "the remainder slice compiled its own gather")
        fence.disarm()
        got_k = np.concatenate([kc for _, kc, _ in parts], axis=1)
        got_v = np.concatenate([vc for _, _, vc in parts], axis=1)
        assert [off for off, _, _ in parts] == [0, 4]
        np.testing.assert_array_equal(got_k, k)
        np.testing.assert_array_equal(got_v, v)
        await eng.stop()

    run_async(main())


def test_extract_single_page_and_full_pool(run_async):
    """Pow2 padding edge cases: 1 page (no pad) and a count already at a
    pow2 boundary (no pad) stay exact."""
    eng = mk_engine()

    async def main():
        for ids in ([5], [1, 2, 3, 4]):
            k, v = _rand_pages(eng, len(ids), seed=len(ids) + 10)
            await eng.inject_pages(ids, k, v)
            got_k, got_v = await eng.extract_pages(ids)
            np.testing.assert_array_equal(got_k, k)
            np.testing.assert_array_equal(got_v, v)
        await eng.stop()

    run_async(main())
