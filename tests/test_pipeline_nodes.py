"""Pipeline node algebra (reference lib/runtime/src/pipeline/: operator
composition, segment source/sink across the network)."""

from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.pipeline import (FnOperator, RemoteSink,
                                         SegmentSource, chain)
from dynamo_tpu.runtime.runtime import DistributedRuntime


async def _echo_engine(request, context):
    for part in str(request).split():
        yield part


def test_chain_composition(run_async):
    async def scenario():
        async def lower_a(req, ctx):
            return f"A({req})"

        a = FnOperator(lower_a, lambda item, ctx: f"a:{item}")

        async def lower_b(req, ctx):
            return req.upper()

        b = FnOperator(lower_b, lambda item, ctx: f"b:{item}")

        engine = chain(a, b, sink=_echo_engine)
        return [x async for x in engine("hello world", Context())]

    out = run_async(scenario())
    # request path: A(hello world) → upper; response path: b: then a:
    assert out == ["a:b:A(HELLO", "a:b:WORLD)"]


def test_segment_split_over_network(run_async):
    """A pipeline split across two components: frontend half forwards via
    RemoteSink to a served SegmentSource backend half."""

    async def scenario():
        drt = await DistributedRuntime.detached()
        backend = SegmentSource(chain(
            FnOperator(None, lambda item, ctx: f"be:{item}"),
            sink=_echo_engine))
        comp = drt.namespace("p").component("segment")
        await comp.create_service()
        handle = await comp.endpoint("generate").serve(backend)

        client = await comp.endpoint("generate").client()
        await client.wait_for_instances()
        sink = RemoteSink(client)

        def unwrap(env, ctx):
            return f"fe:{env.data}"

        frontend = chain(FnOperator(None, unwrap), sink=sink)
        out = [x async for x in frontend("x y z", Context())]
        await client.close()
        await handle.stop()
        await drt.shutdown()
        return out

    out = run_async(scenario())
    assert out == ["fe:be:x", "fe:be:y", "fe:be:z"]
