"""Tier-1 self-enforcement of the dynalint static-analysis suite.

Three layers:

1. **The gate** — the analyzer runs over ``dynamo_tpu/``, ``bench.py``
   and ``tools/`` and fails on any violation not grandfathered in
   ``tools/dynalint/baseline.txt`` (ratchet-only: the baseline may
   shrink, never grow).
2. **Per-rule fixtures** — every rule demonstrably fires on its bad
   snippet and stays quiet on its good one, plus suppression-comment
   and baseline-ratchet behavior.
3. **Generated artifacts** — ``docs/env_vars.md`` must match the env
   registry, and the optional ruff gate runs when ruff is installed.
"""

from __future__ import annotations

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.dynalint import (analyze_paths, analyze_source,  # noqa: E402
                            apply_baseline, load_baseline)

BASELINE = os.path.join(REPO, "tools", "dynalint", "baseline.txt")
GATE_PATHS = [os.path.join(REPO, "dynamo_tpu"),
              os.path.join(REPO, "bench.py"),
              os.path.join(REPO, "tools")]


def lint(src: str, path: str = "dynamo_tpu/fixture.py"):
    return analyze_source(src, path)


def codes(src: str, path: str = "dynamo_tpu/fixture.py"):
    return [v.code for v in lint(src, path)]


# ------------------------------------------------------------------ the gate


def test_repo_is_dynalint_clean():
    """The analyzer is green on its own repo modulo the baseline."""
    violations = analyze_paths(GATE_PATHS, root=REPO)
    allowed = load_baseline(BASELINE) if os.path.exists(BASELINE) else {}
    fresh, _stale = apply_baseline(violations, allowed)
    assert not fresh, (
        "new dynalint violations (fix them, add an inline "
        "`# dynalint: disable=<rule>` with a justification, or — last "
        "resort — baseline them):\n" +
        "\n".join(v.render() for v in fresh))


def test_baseline_is_not_stale():
    """Fixed violations must leave the baseline (ratchet-only gate)."""
    violations = analyze_paths(GATE_PATHS, root=REPO)
    allowed = load_baseline(BASELINE) if os.path.exists(BASELINE) else {}
    _fresh, stale = apply_baseline(violations, allowed)
    assert not stale, f"stale baseline entries — delete them: {stale}"


def test_cli_entrypoint():
    """`python -m tools.dynalint <paths>` exits 0 on the clean tree."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.dynalint",
         "dynamo_tpu", "bench.py", "tools"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ----------------------------------------------- DL001 blocking-call-in-async


DL001_BAD = """
import time, subprocess, requests
async def handler():
    time.sleep(1)
    subprocess.run(["ls"])
    requests.get("http://x")
    open("/tmp/f")
"""

DL001_GOOD = """
import asyncio, time
def sync_helper():
    time.sleep(1)           # sync context: fine
    open("/tmp/f")
async def handler():
    await asyncio.sleep(1)
    await asyncio.to_thread(time.sleep, 1)   # routed off-loop: fine
    def inner():
        time.sleep(1)       # nested sync def: runs elsewhere
"""


def test_dl001_fires_on_bad():
    assert codes(DL001_BAD).count("DL001") == 4


def test_dl001_quiet_on_good():
    assert "DL001" not in codes(DL001_GOOD)


# -------------------------------------------------- DL002 fire-and-forget-task


DL002_BAD = """
import asyncio
async def start():
    asyncio.create_task(work())          # dropped outright
"""

DL002_BAD_ATTR = """
import asyncio
class Svc:
    async def start(self):
        self._task = asyncio.create_task(self.loop())
    async def stop(self):
        pass                              # no cancel path anywhere
"""

DL002_GOOD = """
import asyncio
from dynamo_tpu.runtime.tasks import cancel_join, spawn_tracked
class Svc:
    async def start(self):
        self._task = asyncio.create_task(self.loop())
        self._other = spawn_tracked(self.loop())   # tracked wrapper
    async def stop(self):
        await cancel_join(self._task)
async def inline():
    t = asyncio.create_task(work())
    await t                                # awaited local
    results = await asyncio.gather(*[asyncio.create_task(w())
                                     for w in fns])
"""


def test_dl002_fires_on_dropped():
    assert "DL002" in codes(DL002_BAD)


def test_dl002_fires_on_never_cancelled_attr():
    assert "DL002" in codes(DL002_BAD_ATTR)


def test_dl002_quiet_on_good():
    assert "DL002" not in codes(DL002_GOOD)


# -------------------------------------------------- DL003 swallowed-loop-error


DL003_BAD = """
async def loop():
    while True:
        try:
            await tick()
        except Exception:
            pass
"""

DL003_GOOD = """
import asyncio, logging
log = logging.getLogger(__name__)
async def loop():
    while True:
        try:
            await tick()
        except Exception:
            log.exception("tick failed")
async def loop2():
    while True:
        try:
            await tick()
        except Exception:
            await asyncio.sleep(1.0)      # backoff counts
async def loop3():
    while True:
        try:
            await tick()
        except Exception:
            break                          # exits the loop: fine
def not_a_loop():
    try:
        tick()
    except Exception:
        pass                               # broad but not spinning
"""


def test_dl003_fires_on_silent_spin():
    assert "DL003" in codes(DL003_BAD)


def test_dl003_quiet_on_good():
    assert "DL003" not in codes(DL003_GOOD)


# ------------------------------------------------- DL004 lock-across-blocking


DL004_BAD = """
import asyncio, time
class S:
    async def send(self):
        async with self._wlock:
            time.sleep(1)
    async def wait_under_lock(self):
        async with self._lock:
            await asyncio.sleep(30)
"""

DL004_GOOD = """
import asyncio, time
class S:
    async def send(self):
        async with self._wlock:
            self.writer.write(b"x")
            await self.writer.drain()      # short await: fine
    async def capped(self):
        async with self._sem:              # semaphore = concurrency cap,
            await asyncio.sleep(30)        # holding it long is the point
    def sync_path(self):
        time.sleep(1)                      # no lock held
"""


def test_dl004_fires_on_blocking_under_lock():
    assert codes(DL004_BAD).count("DL004") == 2


def test_dl004_quiet_on_good():
    assert "DL004" not in codes(DL004_GOOD)


# --------------------------------------------- DL005 jax-host-sync-in-hot-path


DL005_BAD = """
import numpy as np
class JaxEngine:
    def _step(self):
        toks = np.asarray(self.dev_toks)
        jax.block_until_ready(self.kv)
        n = self.counter.item()
"""

DL005_GOOD = """
import numpy as np
import jax.numpy as jnp
class JaxEngine:
    def _step(self):
        x = jnp.asarray(self.rows)         # device-side: fine
    def warmup(self):
        np.asarray(self.kv)                # not a hot-path function
    def _decode_step_spec(self):
        np.asarray(self.kv)                # allowlisted sync arm
"""


def test_dl005_fires_in_engine_hot_path():
    assert codes(DL005_BAD, "dynamo_tpu/engine/fixture.py").count(
        "DL005") == 3


def test_dl005_quiet_on_good_and_allowlist():
    assert "DL005" not in codes(DL005_GOOD, "dynamo_tpu/engine/fixture.py")


def test_dl005_scoped_to_engine_modules():
    assert "DL005" not in codes(DL005_BAD, "dynamo_tpu/llm/fixture.py")


# ---------------------------------------------------- DL006 untracked-env-read


DL006_BAD = """
import os
ADDR = os.environ.get("DYN_DCP_ADDRESS")
TOK = os.environ["DYN_ADMIN_TOKENS"]
LOG = os.getenv("DYN_LOG")
os.environ.setdefault("HF_HUB_OFFLINE", "1")
HAVE = "DYN_LOG" in os.environ
"""

DL006_GOOD = """
import os, subprocess
from dynamo_tpu.runtime.config import env_str
ADDR = env_str("DYN_DCP_ADDRESS")
os.environ["JAX_PLATFORMS"] = "cpu"        # write, not a read
child_env = dict(os.environ)               # whole-env passthrough
subprocess.run(["x"], env={**os.environ})
"""


def test_dl006_fires_on_direct_reads():
    assert codes(DL006_BAD).count("DL006") == 5


def test_dl006_quiet_on_registry_and_writes():
    assert "DL006" not in codes(DL006_GOOD)


def test_dl006_allows_config_module():
    assert "DL006" not in codes(DL006_BAD,
                                "dynamo_tpu/runtime/config.py")


# ------------------------------------------------------- DL007 span-not-closed


DL007_BAD = """
def handler(tracer):
    tracer.start_span("http.request")          # dropped outright
"""

DL007_BAD_ASSIGNED = """
def handler(tracer):
    span = tracer.start_span("http.request")
    span.set_attribute("model", "m")           # used, but never closed
"""

DL007_BAD_ATTR = """
class Svc:
    def begin(self, tracer):
        self._span = tracer.start_span("op")   # no end() anywhere
"""

DL007_GOOD = """
def with_form(tracer):
    with tracer.start_span("http.request") as span:
        span.set_attribute("model", "m")

def explicit_end(tracer):
    span = tracer.start_span("op")
    span.set_attribute("k", 1)
    span.end()

def with_variable(tracer):
    span = tracer.start_span("op")
    with span:
        pass

def escapes(tracer):
    return tracer.start_span("op")             # caller owns closing

class Svc:
    def begin(self, tracer):
        self._span = tracer.start_span("op")
    def finish(self):
        self._span.end()
"""


def test_dl007_fires_on_dropped_span():
    assert "DL007" in codes(DL007_BAD)


def test_dl007_fires_on_unclosed_assignment():
    assert "DL007" in codes(DL007_BAD_ASSIGNED)


def test_dl007_fires_on_unclosed_attr():
    assert "DL007" in codes(DL007_BAD_ATTR)


def test_dl007_quiet_on_good():
    assert "DL007" not in codes(DL007_GOOD)


# ----------------------------------------------------------------- suppression


def test_inline_suppression_same_line():
    src = ("import time\n"
           "async def f():\n"
           "    time.sleep(1)  # dynalint: disable=blocking-call-in-async\n")
    assert "DL001" not in codes(src)


def test_inline_suppression_line_above():
    src = ("import time\n"
           "async def f():\n"
           "    # dynalint: disable=DL001\n"
           "    time.sleep(1)\n")
    assert "DL001" not in codes(src)


def test_suppression_is_rule_scoped():
    src = ("import time\n"
           "async def f():\n"
           "    time.sleep(1)  # dynalint: disable=untracked-env-read\n")
    assert "DL001" in codes(src)  # wrong rule named: still fires


# ------------------------------------------------------------ baseline ratchet


def test_baseline_ratchet(tmp_path):
    violations = lint(DL003_BAD, "dynamo_tpu/somefile.py")
    assert violations, "fixture must produce a violation"
    key = violations[0].baseline_key

    # 1. baselined violation passes
    bl = tmp_path / "baseline.txt"
    bl.write_text(f"# grandfathered\n{key}\n")
    fresh, stale = apply_baseline(violations, load_baseline(str(bl)))
    assert not fresh and not stale

    # 2. a NEW violation (not in the baseline) fails
    more = violations + lint(DL001_BAD, "dynamo_tpu/otherfile.py")
    fresh, _ = apply_baseline(more, load_baseline(str(bl)))
    assert fresh and all(v.code == "DL001" for v in fresh)

    # 3. stale entry (violation fixed) is reported for deletion
    bl.write_text(f"{key}\ndynamo_tpu/gone.py::swallowed-loop-error::f\n")
    fresh, stale = apply_baseline(violations, load_baseline(str(bl)))
    assert not fresh
    assert stale == ["dynamo_tpu/gone.py::swallowed-loop-error::f"]


def test_baseline_count_suffix(tmp_path):
    """path::rule::scope::N grandfathers N instances in one line."""
    two = lint(DL003_BAD, "dynamo_tpu/somefile.py") * 2
    key = two[0].baseline_key
    bl = tmp_path / "baseline.txt"
    bl.write_text(f"{key}::2\n")
    fresh, stale = apply_baseline(two, load_baseline(str(bl)))
    assert not fresh and not stale


# ------------------------------------------------------- generated artifacts


def test_env_docs_in_sync():
    """docs/env_vars.md must match the registry (regenerate with
    `python -m tools.dynalint --write-env-docs docs/env_vars.md`)."""
    from dynamo_tpu.runtime.config import render_env_docs

    path = os.path.join(REPO, "docs", "env_vars.md")
    with open(path, encoding="utf-8") as f:
        on_disk = f.read()
    assert on_disk == render_env_docs(), (
        "docs/env_vars.md is out of date — regenerate it with "
        "`python -m tools.dynalint --write-env-docs docs/env_vars.md`")


def test_env_registry_rejects_unregistered():
    from dynamo_tpu.runtime.config import UnregisteredEnvVar, env_str

    with pytest.raises(UnregisteredEnvVar):
        env_str("DYN_NO_SUCH_KNOB_EVER")


def test_ruff_gate():
    """Second gate: ruff (pyflakes + async + bugbear subset from
    pyproject.toml) when available; skip gracefully when not baked in."""
    try:
        import ruff  # noqa: F401
    except ImportError:
        pytest.skip("ruff not installed in this image")
    proc = subprocess.run([sys.executable, "-m", "ruff", "check", "."],
                          cwd=REPO, capture_output=True, text=True,
                          timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
