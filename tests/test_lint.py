"""Tier-1 self-enforcement of the dynalint static-analysis suite.

Three layers:

1. **The gate** — the analyzer runs over ``dynamo_tpu/``, ``bench.py``
   and ``tools/`` and fails on any violation not grandfathered in
   ``tools/dynalint/baseline.txt`` (ratchet-only: the baseline may
   shrink, never grow).
2. **Per-rule fixtures** — every rule demonstrably fires on its bad
   snippet and stays quiet on its good one, plus suppression-comment
   and baseline-ratchet behavior.
3. **Generated artifacts** — ``docs/env_vars.md`` must match the env
   registry, and the optional ruff gate runs when ruff is installed.
"""

from __future__ import annotations

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.dynalint import (CallGraph, analyze_project,  # noqa: E402
                            analyze_races, analyze_source, analyze_tree,
                            apply_baseline, load_baseline, load_source,
                            load_sources, load_wire_schemas,
                            parse_module)

BASELINE = os.path.join(REPO, "tools", "dynalint", "baseline.txt")
GATE_PATHS = [os.path.join(REPO, "dynamo_tpu"),
              os.path.join(REPO, "bench.py"),
              os.path.join(REPO, "tools")]


def lint(src: str, path: str = "dynamo_tpu/fixture.py"):
    return analyze_source(src, path)


def codes(src: str, path: str = "dynamo_tpu/fixture.py"):
    return [v.code for v in lint(src, path)]


# ------------------------------------------------------------------ the gate


def test_repo_is_dynalint_clean():
    """Per-file AND whole-program (dynaflow) rules are green on their own
    repo modulo the baseline — DL008-DL010 active, baseline EMPTY."""
    violations = analyze_tree(GATE_PATHS, root=REPO)
    allowed = load_baseline(BASELINE) if os.path.exists(BASELINE) else {}
    fresh, _stale = apply_baseline(violations, allowed)
    assert not fresh, (
        "new dynalint violations (fix them, add an inline "
        "`# dynalint: disable=<rule>` with a justification, or — last "
        "resort — baseline them):\n" +
        "\n".join(v.render() for v in fresh))


def test_baseline_is_not_stale():
    """Fixed violations must leave the baseline (ratchet-only gate)."""
    violations = analyze_tree(GATE_PATHS, root=REPO)
    allowed = load_baseline(BASELINE) if os.path.exists(BASELINE) else {}
    _fresh, stale = apply_baseline(violations, allowed)
    assert not stale, f"stale baseline entries — delete them: {stale}"


def test_cli_entrypoint():
    """`python -m tools.dynalint <paths>` exits 0 on the clean tree."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.dynalint",
         "dynamo_tpu", "bench.py", "tools"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ----------------------------------------------- DL001 blocking-call-in-async


DL001_BAD = """
import time, subprocess, requests
async def handler():
    time.sleep(1)
    subprocess.run(["ls"])
    requests.get("http://x")
    open("/tmp/f")
"""

DL001_GOOD = """
import asyncio, time
def sync_helper():
    time.sleep(1)           # sync context: fine
    open("/tmp/f")
async def handler():
    await asyncio.sleep(1)
    await asyncio.to_thread(time.sleep, 1)   # routed off-loop: fine
    def inner():
        time.sleep(1)       # nested sync def: runs elsewhere
"""


def test_dl001_fires_on_bad():
    assert codes(DL001_BAD).count("DL001") == 4


def test_dl001_quiet_on_good():
    assert "DL001" not in codes(DL001_GOOD)


# -------------------------------------------------- DL002 fire-and-forget-task


DL002_BAD = """
import asyncio
async def start():
    asyncio.create_task(work())          # dropped outright
"""

DL002_BAD_ATTR = """
import asyncio
class Svc:
    async def start(self):
        self._task = asyncio.create_task(self.loop())
    async def stop(self):
        pass                              # no cancel path anywhere
"""

DL002_GOOD = """
import asyncio
from dynamo_tpu.runtime.tasks import cancel_join, spawn_tracked
class Svc:
    async def start(self):
        self._task = asyncio.create_task(self.loop())
        self._other = spawn_tracked(self.loop())   # tracked wrapper
    async def stop(self):
        await cancel_join(self._task)
async def inline():
    t = asyncio.create_task(work())
    await t                                # awaited local
    results = await asyncio.gather(*[asyncio.create_task(w())
                                     for w in fns])
"""


def test_dl002_fires_on_dropped():
    assert "DL002" in codes(DL002_BAD)


def test_dl002_fires_on_never_cancelled_attr():
    assert "DL002" in codes(DL002_BAD_ATTR)


def test_dl002_quiet_on_good():
    assert "DL002" not in codes(DL002_GOOD)


# -------------------------------------------------- DL003 swallowed-loop-error


DL003_BAD = """
async def loop():
    while True:
        try:
            await tick()
        except Exception:
            pass
"""

DL003_GOOD = """
import asyncio, logging
log = logging.getLogger(__name__)
async def loop():
    while True:
        try:
            await tick()
        except Exception:
            log.exception("tick failed")
async def loop2():
    while True:
        try:
            await tick()
        except Exception:
            await asyncio.sleep(1.0)      # backoff counts
async def loop3():
    while True:
        try:
            await tick()
        except Exception:
            break                          # exits the loop: fine
def not_a_loop():
    try:
        tick()
    except Exception:
        pass                               # broad but not spinning
"""


def test_dl003_fires_on_silent_spin():
    assert "DL003" in codes(DL003_BAD)


def test_dl003_quiet_on_good():
    assert "DL003" not in codes(DL003_GOOD)


# ------------------------------------------------- DL004 lock-across-blocking


DL004_BAD = """
import asyncio, time
class S:
    async def send(self):
        async with self._wlock:
            time.sleep(1)
    async def wait_under_lock(self):
        async with self._lock:
            await asyncio.sleep(30)
"""

DL004_GOOD = """
import asyncio, time
class S:
    async def send(self):
        async with self._wlock:
            self.writer.write(b"x")
            await self.writer.drain()      # short await: fine
    async def capped(self):
        async with self._sem:              # semaphore = concurrency cap,
            await asyncio.sleep(30)        # holding it long is the point
    def sync_path(self):
        time.sleep(1)                      # no lock held
"""


def test_dl004_fires_on_blocking_under_lock():
    assert codes(DL004_BAD).count("DL004") == 2


def test_dl004_quiet_on_good():
    assert "DL004" not in codes(DL004_GOOD)


# --------------------------------------------- DL005 jax-host-sync-in-hot-path


DL005_BAD = """
import numpy as np
class JaxEngine:
    def _step(self):
        toks = np.asarray(self.dev_toks)
        jax.block_until_ready(self.kv)
        n = self.counter.item()
"""

DL005_GOOD = """
import numpy as np
import jax.numpy as jnp
class JaxEngine:
    def _step(self):
        x = jnp.asarray(self.rows)         # device-side: fine
    def warmup(self):
        np.asarray(self.kv)                # not a hot-path function
    def _decode_step_spec(self):
        np.asarray(self.kv)                # allowlisted sync arm
"""


def test_dl005_fires_in_engine_hot_path():
    assert codes(DL005_BAD, "dynamo_tpu/engine/fixture.py").count(
        "DL005") == 3


def test_dl005_quiet_on_good_and_allowlist():
    assert "DL005" not in codes(DL005_GOOD, "dynamo_tpu/engine/fixture.py")


def test_dl005_scoped_to_engine_modules():
    assert "DL005" not in codes(DL005_BAD, "dynamo_tpu/llm/fixture.py")


# ---------------------------------------------------- DL006 untracked-env-read


DL006_BAD = """
import os
ADDR = os.environ.get("DYN_DCP_ADDRESS")
TOK = os.environ["DYN_ADMIN_TOKENS"]
LOG = os.getenv("DYN_LOG")
os.environ.setdefault("HF_HUB_OFFLINE", "1")
HAVE = "DYN_LOG" in os.environ
"""

DL006_GOOD = """
import os, subprocess
from dynamo_tpu.runtime.config import env_str
ADDR = env_str("DYN_DCP_ADDRESS")
os.environ["JAX_PLATFORMS"] = "cpu"        # write, not a read
child_env = dict(os.environ)               # whole-env passthrough
subprocess.run(["x"], env={**os.environ})
"""


def test_dl006_fires_on_direct_reads():
    assert codes(DL006_BAD).count("DL006") == 5


def test_dl006_quiet_on_registry_and_writes():
    assert "DL006" not in codes(DL006_GOOD)


def test_dl006_allows_config_module():
    assert "DL006" not in codes(DL006_BAD,
                                "dynamo_tpu/runtime/config.py")


# ------------------------------------------------------- DL007 span-not-closed


DL007_BAD = """
def handler(tracer):
    tracer.start_span("http.request")          # dropped outright
"""

DL007_BAD_ASSIGNED = """
def handler(tracer):
    span = tracer.start_span("http.request")
    span.set_attribute("model", "m")           # used, but never closed
"""

DL007_BAD_ATTR = """
class Svc:
    def begin(self, tracer):
        self._span = tracer.start_span("op")   # no end() anywhere
"""

DL007_GOOD = """
def with_form(tracer):
    with tracer.start_span("http.request") as span:
        span.set_attribute("model", "m")

def explicit_end(tracer):
    span = tracer.start_span("op")
    span.set_attribute("k", 1)
    span.end()

def with_variable(tracer):
    span = tracer.start_span("op")
    with span:
        pass

def escapes(tracer):
    return tracer.start_span("op")             # caller owns closing

class Svc:
    def begin(self, tracer):
        self._span = tracer.start_span("op")
    def finish(self):
        self._span.end()
"""


def test_dl007_fires_on_dropped_span():
    assert "DL007" in codes(DL007_BAD)


def test_dl007_fires_on_unclosed_assignment():
    assert "DL007" in codes(DL007_BAD_ASSIGNED)


def test_dl007_fires_on_unclosed_attr():
    assert "DL007" in codes(DL007_BAD_ATTR)


def test_dl007_quiet_on_good():
    assert "DL007" not in codes(DL007_GOOD)


# --------------------------------------------------- DL011 unbounded-await


DL011_BAD = """
import asyncio
async def talk(reader, writer, work_queue):
    r, w = await asyncio.open_connection("h", 1)   # unbounded connect
    data = await reader.readexactly(4)             # unbounded read
    await writer.drain()                           # unbounded drain
    item = await work_queue.get()                  # unbounded queue get
"""

DL011_BAD_CODEC = """
from . import codec
async def loop(reader):
    msg = await codec.decode(reader)               # frame-read primitive
    frame = await read_frame(reader)               # dcp primitive
"""

DL011_GOOD = """
import asyncio
from . import codec, guard
async def talk(reader, writer, work_queue, deadline):
    r, w = await asyncio.wait_for(
        asyncio.open_connection("h", 1), 30.0)      # bounded connect
    data = await asyncio.wait_for(reader.readexactly(4), 5.0)
    await asyncio.wait_for(writer.drain(), 30.0)
    item = await guard.bound(work_queue.get(), deadline=deadline)
    msg = await asyncio.wait_for(codec.decode(reader), 10.0)
async def not_network(seq, d):
    out = await seq.out.get()       # not queue-shaped: engine stream
    val = d.get("k")                # sync dict get: no await
"""

DL011_SUPPRESSED = """
async def server_loop(reader):
    while True:
        # idle server read: lifetime is the connection
        msg = await decode(reader)  # dynalint: disable=unbounded-await
"""


def test_dl011_fires_on_naked_net_awaits():
    assert codes(DL011_BAD).count("DL011") == 4


def test_dl011_fires_on_codec_primitives():
    assert codes(DL011_BAD_CODEC).count("DL011") == 2


def test_dl011_quiet_on_bounded():
    assert "DL011" not in codes(DL011_GOOD)


def test_dl011_suppression():
    assert "DL011" not in codes(DL011_SUPPRESSED)


# --------------------------------------------- DL018 unsampled-profiler-sync


PROFILER_PATH = "dynamo_tpu/engine/fix_profiler.py"

DL018_BAD = """
import time
import jax
import numpy as np
class Prof:
    def end(self, ref):
        t0 = time.perf_counter()
        jax.block_until_ready(ref)        # sync with no sample guard
        host = np.asarray(ref)            # ditto
        return time.perf_counter() - t0
"""

DL018_BAD_ELSE = """
import jax
class Prof:
    def end(self, ref):
        if self.sampling:
            jax.block_until_ready(ref)    # guarded: fine
        else:
            jax.block_until_ready(ref)    # the NOT-sampling branch: fires
"""

DL018_GOOD = """
import time
import jax
import numpy as np
class Prof:
    def end(self, t0, ref):
        if self.sampling and t0 is not None:
            t1 = time.perf_counter()
            jax.block_until_ready(ref)
            host = np.asarray(ref)
        if self.enabled:
            ref.block_until_ready()
    def tick(self):
        self._iter += 1                   # no sync: nothing to guard
"""

DL018_SUPPRESSED = """
import jax
class Prof:
    def flush(self, ref):
        # one-shot teardown drain, not a per-step path
        jax.block_until_ready(ref)  # dynalint: disable=unsampled-profiler-sync
"""


def test_dl018_fires_on_unguarded_profiler_sync():
    assert codes(DL018_BAD, PROFILER_PATH).count("DL018") == 2


def test_dl018_fires_in_else_branch():
    assert codes(DL018_BAD_ELSE, PROFILER_PATH).count("DL018") == 1


def test_dl018_quiet_under_sample_guard():
    assert "DL018" not in codes(DL018_GOOD, PROFILER_PATH)


def test_dl018_only_applies_to_profiler_paths():
    # the same unguarded sync outside profiler modules is DL005/DL017
    # territory, not DL018
    assert "DL018" not in codes(DL018_BAD, "dynamo_tpu/engine/other.py")


def test_dl018_suppression():
    assert "DL018" not in codes(DL018_SUPPRESSED, PROFILER_PATH)


# ------------------------------------------------- dynaflow fixture plumbing


def project(*mods, schemas=None, depth=4):
    """Run the whole-program passes over in-memory fixture modules given
    as (path, src) pairs."""
    sources = [parse_module(src, path) for path, src in mods]
    kwargs = {}
    if schemas is not None:
        kwargs["schemas"] = schemas
    return analyze_project(sources, dl008_depth=depth, **kwargs)


FIXTURE_WIRE = '''
FIX_FRAME = register_frame(
    "fix.frame", version=2, when={"kind": "fix"},
    fields=[
        ("kind", "str", "required", 1, "discriminator"),
        ("request_id", "str", "required", 1, "id"),
        ("extra", "int", "optional", 2, "added in v2"),
    ])
'''


def fixture_schemas():
    schemas, const_map, bad = load_wire_schemas(
        parse_module(FIXTURE_WIRE, "pkg/wire.py"))
    assert not bad and const_map == {"FIX_FRAME": "fix.frame"}
    return schemas


# ----------------------------------------- DL008 transitive-blocking-in-async


DL008_BAD = """
import time
def helper():
    time.sleep(1)
def middle():
    helper()
async def endpoint():
    middle()
"""

DL008_GOOD = """
import asyncio, time
def helper():
    time.sleep(1)
async def endpoint():
    await asyncio.to_thread(helper)       # offloaded: no edge
async def other():
    await peer()                           # async callee: its own root
async def peer():
    await asyncio.sleep(1)
"""

DL008_SUPPRESSED_CALLSITE = """
import time
def helper():
    time.sleep(1)
async def endpoint():
    helper()  # dynalint: disable=transitive-blocking-in-async
"""

DL008_SUPPRESSED_SINK = """
import time
def helper():
    # dynalint: disable=DL008
    time.sleep(1)
async def endpoint():
    helper()
"""

DL008_DEEP = """
import time
def f5():
    time.sleep(1)
def f4():
    f5()
def f3():
    f4()
def f2():
    f3()
def f1():
    f2()
async def endpoint():
    f1()
"""


def test_dl008_fires_through_sync_chain():
    vs = [v for v in project(("pkg/m.py", DL008_BAD)) if v.code == "DL008"]
    assert len(vs) == 1
    assert vs[0].scope == "endpoint"
    assert "time.sleep" in vs[0].message


def test_dl008_quiet_on_offload_and_async_callees():
    assert not [v for v in project(("pkg/m.py", DL008_GOOD))
                if v.code == "DL008"]


def test_dl008_suppression_at_callsite_and_sink():
    for src in (DL008_SUPPRESSED_CALLSITE, DL008_SUPPRESSED_SINK):
        assert not [v for v in project(("pkg/m.py", src))
                    if v.code == "DL008"]


def test_dl008_depth_limit():
    """The 5-frame chain is past the default depth of 4 but within 6."""
    assert not [v for v in project(("pkg/m.py", DL008_DEEP), depth=4)
                if v.code == "DL008"]
    assert [v for v in project(("pkg/m.py", DL008_DEEP), depth=6)
            if v.code == "DL008"]


def test_dl008_cross_module_alias():
    """from pkg.a import helper as h; the async caller lives elsewhere."""
    mod_a = """
import time
def helper():
    time.sleep(1)
"""
    mod_b = """
from pkg.a import helper as h
async def endpoint():
    h()
"""
    vs = [v for v in project(("pkg/a.py", mod_a), ("pkg/b.py", mod_b))
          if v.code == "DL008"]
    assert len(vs) == 1 and vs[0].path == "pkg/b.py"


def test_dl008_method_attribution_and_inheritance():
    src = """
import time
class Base:
    def _io(self):
        time.sleep(1)
class Svc(Base):
    async def handle(self):
        self._io()
"""
    vs = [v for v in project(("pkg/m.py", src)) if v.code == "DL008"]
    assert len(vs) == 1 and vs[0].scope == "Svc.handle"


# -------------------------------------------------- call-graph unit behavior


def test_callgraph_async_and_alias_resolution():
    mod_a = """
def plain():
    pass
async def aplain():
    pass
"""
    mod_b = """
import pkg.a as alias
from pkg.a import plain as renamed
async def caller():
    alias.plain()
    renamed()
"""
    g = CallGraph.build([parse_module(mod_a, "pkg/a.py"),
                         parse_module(mod_b, "pkg/b.py")])
    assert g.functions["pkg.a:plain"].is_async is False
    assert g.functions["pkg.a:aplain"].is_async is True
    caller = g.functions["pkg.b:caller"]
    assert caller.is_async is True
    targets = {cs.target for cs in caller.calls}
    assert targets == {"pkg.a:plain"}  # both routes resolve to one function


def test_callgraph_method_resolution():
    src = """
class Svc:
    def start(self):
        self.step()
    def step(self):
        pass
def outer():
    Svc()
    """
    g = CallGraph.build([parse_module(src, "pkg/m.py")])
    start = g.functions["pkg.m:Svc.start"]
    assert [cs.target for cs in start.calls] == ["pkg.m:Svc.step"]


# --------------------------------------------------- DL009 wire-field-drift


def test_dl009_write_side_drift():
    """A dict-literal key at an encode anchor that the schema lacks."""
    src = """
from dynamo_tpu.runtime import wire
def send():
    return wire.checked(wire.FIX_FRAME, {
        "kind": "fix", "request_id": "r", "zstd_level": 3})
"""
    vs = [v for v in project(("pkg/m.py", src), schemas=fixture_schemas())
          if v.code == "DL009"]
    assert len(vs) == 1 and "zstd_level" in vs[0].message


def test_dl009_write_side_drift_via_late_store():
    """Keys added with var[...] = ... after the anchor are still checked."""
    src = """
from dynamo_tpu.runtime import wire
def send():
    h = wire.checked(wire.FIX_FRAME, {"kind": "fix", "request_id": "r"})
    h["sneaky"] = 1
    return h
"""
    vs = [v for v in project(("pkg/m.py", src), schemas=fixture_schemas())
          if v.code == "DL009"]
    assert len(vs) == 1 and "sneaky" in vs[0].message


def test_dl009_read_side_drift():
    """A .get()/[] read through a decode anchor of an undeclared key."""
    src = """
from dynamo_tpu.runtime import wire
def recv(header):
    h = wire.decoded(wire.FIX_FRAME, header)
    _ = h["kind"], h["request_id"]
    return h.get("legacy_field")
"""
    vs = [v for v in project(("pkg/m.py", src), schemas=fixture_schemas())
          if v.code == "DL009"]
    assert len(vs) == 1 and "legacy_field" in vs[0].message


def test_dl009_required_never_read():
    """A required field no decoder reads is flagged at the registration."""
    src = """
from dynamo_tpu.runtime import wire
def recv(header):
    h = wire.decoded(wire.FIX_FRAME, header)
    return h["kind"]
"""
    vs = [v for v in project(("pkg/m.py", src), schemas=fixture_schemas())
          if v.code == "DL009"]
    assert len(vs) == 1
    assert "request_id" in vs[0].message and vs[0].scope == "fix.frame"


def test_dl009_clean_roundtrip():
    src = """
from dynamo_tpu.runtime import wire
def send():
    return wire.checked(wire.FIX_FRAME, {
        "kind": "fix", "request_id": "r", "extra": 2})
def recv(header):
    h = wire.decoded(wire.FIX_FRAME, header)
    return h["kind"], h["request_id"], h.get("extra")
"""
    assert not [v for v in project(("pkg/m.py", src),
                                   schemas=fixture_schemas())
                if v.code == "DL009"]


def test_dl009_drifted_pair_write_and_read():
    """The deliberately-drifted pair: encoder grew a field by hand, the
    decoder still reads a long-deleted one — both sides fire."""
    encoder = """
from dynamo_tpu.runtime import wire
def send():
    return wire.checked(wire.FIX_FRAME, {
        "kind": "fix", "request_id": "r", "grew_by_hand": 1})
"""
    decoder = """
from dynamo_tpu.runtime import wire
def recv(header):
    h = wire.decoded(wire.FIX_FRAME, header)
    return h["kind"], h["request_id"], h.get("deleted_long_ago")
"""
    vs = [v for v in project(("pkg/enc.py", encoder),
                             ("pkg/dec.py", decoder),
                             schemas=fixture_schemas())
          if v.code == "DL009"]
    assert {v.path for v in vs} == {"pkg/enc.py", "pkg/dec.py"}
    msgs = " ".join(v.message for v in vs)
    assert "grew_by_hand" in msgs and "deleted_long_ago" in msgs


# ------------------------------------------------ DL010 undeclared-wire-frame


def test_dl010_fires_on_unanchored_literal():
    src = """
from dynamo_tpu.runtime import codec
def send(writer):
    writer.writelines(codec.encode_parts({"mystery": 1, "blob": 2}))
"""
    vs = [v for v in project(("pkg/m.py", src), schemas=fixture_schemas())
          if v.code == "DL010"]
    assert len(vs) == 1 and "mystery" in vs[0].message


def test_dl010_quiet_on_anchored_and_matching():
    src = """
from dynamo_tpu.runtime import codec, wire
def send(writer):
    writer.writelines(codec.encode_parts(
        wire.checked(wire.FIX_FRAME, {"kind": "fix", "request_id": "r"})))
    h = wire.checked(wire.FIX_FRAME, {"kind": "fix", "request_id": "r"})
    writer.writelines(codec.encode_parts(h))
    writer.writelines(codec.encode_parts(
        {"kind": "fix", "request_id": "r"}))   # literal matches the schema
def opaque(writer, header):
    writer.writelines(codec.encode_parts(header))  # unknown: never guess
"""
    assert not [v for v in project(("pkg/m.py", src),
                                   schemas=fixture_schemas())
                if v.code == "DL010"]


def test_wire_registry_declarations_are_literal():
    """Non-literal register_frame args would drop the frame from the
    static pass — the loader flags them."""
    bad = """
V = 2
F = register_frame("f.f", version=V, fields=[])
"""
    _schemas, _cmap, violations = load_wire_schemas(
        parse_module(bad, "pkg/wire.py"))
    assert violations and violations[0].code == "DL009"


# ----------------------------------------------------------------- suppression


def test_inline_suppression_same_line():
    src = ("import time\n"
           "async def f():\n"
           "    time.sleep(1)  # dynalint: disable=blocking-call-in-async\n")
    assert "DL001" not in codes(src)


def test_inline_suppression_line_above():
    src = ("import time\n"
           "async def f():\n"
           "    # dynalint: disable=DL001\n"
           "    time.sleep(1)\n")
    assert "DL001" not in codes(src)


def test_suppression_is_rule_scoped():
    src = ("import time\n"
           "async def f():\n"
           "    time.sleep(1)  # dynalint: disable=untracked-env-read\n")
    assert "DL001" in codes(src)  # wrong rule named: still fires


# ------------------------------------------------------------ baseline ratchet


def test_baseline_ratchet(tmp_path):
    violations = lint(DL003_BAD, "dynamo_tpu/somefile.py")
    assert violations, "fixture must produce a violation"
    key = violations[0].baseline_key

    # 1. baselined violation passes
    bl = tmp_path / "baseline.txt"
    bl.write_text(f"# grandfathered\n{key}\n")
    fresh, stale = apply_baseline(violations, load_baseline(str(bl)))
    assert not fresh and not stale

    # 2. a NEW violation (not in the baseline) fails
    more = violations + lint(DL001_BAD, "dynamo_tpu/otherfile.py")
    fresh, _ = apply_baseline(more, load_baseline(str(bl)))
    assert fresh and all(v.code == "DL001" for v in fresh)

    # 3. stale entry (violation fixed) is reported for deletion
    bl.write_text(f"{key}\ndynamo_tpu/gone.py::swallowed-loop-error::f\n")
    fresh, stale = apply_baseline(violations, load_baseline(str(bl)))
    assert not fresh
    assert stale == ["dynamo_tpu/gone.py::swallowed-loop-error::f"]


def test_baseline_count_suffix(tmp_path):
    """path::rule::scope::N grandfathers N instances in one line."""
    two = lint(DL003_BAD, "dynamo_tpu/somefile.py") * 2
    key = two[0].baseline_key
    bl = tmp_path / "baseline.txt"
    bl.write_text(f"{key}::2\n")
    fresh, stale = apply_baseline(two, load_baseline(str(bl)))
    assert not fresh and not stale


# ----------------------------------------------- dynarace fixture plumbing


def race(*mods):
    """Run the dynarace passes (DL012-DL014 + interprocedural DL005)
    over in-memory fixture modules given as (path, src) pairs."""
    return analyze_races([parse_module(src, path) for path, src in mods])


def race_codes(src, path="pkg/m.py"):
    return [v.code for v in race((path, src))]


# --------------------------------------------- DL012 atomicity-across-await


DL012_BAD = """
import asyncio
from dynamo_tpu.runtime.tasks import spawn_tracked

class Svc:
    async def start(self):
        spawn_tracked(self.loop_a())
        spawn_tracked(self.loop_b())

    async def loop_a(self):
        while True:
            n = self.counter
            await asyncio.sleep(1)
            self.counter = n + 1        # lost update across the await

    async def loop_b(self):
        self.counter = 0
"""

DL012_BAD_STALE_CHECK = """
import asyncio

class Svc:
    async def ensure(self):
        if self._conn is None:          # stale check...
            await asyncio.sleep(1)
            self._conn = object()       # ...acted on after the await

    async def drop(self):
        self._conn = None
"""

DL012_GOOD_LOCK = """
import asyncio

class Svc:
    async def ensure(self):
        async with self._lock:          # one lock across the whole
            if self._conn is None:      # read-check-act sequence
                await asyncio.sleep(1)
                self._conn = object()

    async def drop(self):
        async with self._lock:
            self._conn = None
"""

DL012_GOOD_RECHECK = """
import asyncio

class Svc:
    async def ensure(self):
        if self._conn is None:
            await asyncio.sleep(1)
            if self._conn is None:      # double-checked: re-validated
                self._conn = object()   # after the await

    async def drop(self):
        self._conn = None
"""

DL012_GOOD_ATOMIC = """
import asyncio

class Svc:
    async def bump(self):
        self.counter += 1               # single statement: atomic
        await asyncio.sleep(1)
        self.counter += 1

    async def other(self):
        self.counter = 0

    def sync_path(self):
        n = self.counter                # sync frame: cannot interleave
        self.counter = n + 1
"""

DL012_SUPPRESSED_WRITE = """
import asyncio

class Svc:
    async def ensure(self):
        if self._conn is None:
            await asyncio.sleep(1)
            # single caller by construction (start() runs once)
            self._conn = object()  # dynalint: disable=atomicity-across-await

    async def drop(self):
        self._conn = None
"""

DL012_SUPPRESSED_READ = """
import asyncio

class Svc:
    async def ensure(self):
        if self._conn is None:  # dynalint: disable=DL012
            await asyncio.sleep(1)
            self._conn = object()

    async def drop(self):
        self._conn = None
"""


def test_dl012_fires_on_lost_update():
    vs = [v for v in race(("pkg/m.py", DL012_BAD)) if v.code == "DL012"]
    assert len(vs) == 1
    assert vs[0].scope == "Svc.loop_a" and "counter" in vs[0].message


def test_dl012_fires_on_stale_check():
    vs = [v for v in race(("pkg/m.py", DL012_BAD_STALE_CHECK))
          if v.code == "DL012"]
    assert len(vs) == 1 and "_conn" in vs[0].message


def test_dl012_quiet_on_lock_held_both_ends():
    assert "DL012" not in race_codes(DL012_GOOD_LOCK)


def test_dl012_quiet_on_recheck_after_await():
    assert "DL012" not in race_codes(DL012_GOOD_RECHECK)


def test_dl012_quiet_on_atomic_and_sync():
    assert "DL012" not in race_codes(DL012_GOOD_ATOMIC)


def test_dl012_suppression_both_ends():
    for src in (DL012_SUPPRESSED_WRITE, DL012_SUPPRESSED_READ):
        assert "DL012" not in race_codes(src)


# ---------------------------------------- DL013 unguarded-concurrent-mutation


DL013_BAD_GUARDED = """
import asyncio

class Svc:
    def __init__(self):
        self._lock = asyncio.Lock()
        self._conn = None  # guarded-by: self._lock

    async def touch(self):
        self._conn = object()           # async frame, lock not held
"""

DL013_GOOD_GUARDED = """
import asyncio

class Svc:
    def __init__(self):
        self._lock = asyncio.Lock()
        self._conn = None  # guarded-by: self._lock

    async def touch(self):
        async with self._lock:
            self._conn = object()

    def sync_touch(self):
        self._conn = None               # sync frame: event-loop atomic
"""

DL013_BAD_UNKNOWN_LOCK = """
class Svc:
    def __init__(self):
        self._conn = None  # guarded-by: self._nope_lock
"""

DL013_BAD_INCONSISTENT = """
import asyncio
from dynamo_tpu.runtime.tasks import spawn_tracked

class Svc:
    async def start(self):
        spawn_tracked(self.locked())
        spawn_tracked(self.unlocked())

    async def locked(self):
        async with self._wlock:
            self.table[1] = 1           # mutation under the lock...

    async def unlocked(self):
        self.table[2] = 2               # ...and without it elsewhere
"""

DL013_SUPPRESSED = """
import asyncio

class Svc:
    def __init__(self):
        self._lock = asyncio.Lock()
        self._conn = None  # guarded-by: self._lock

    async def touch(self):
        # teardown path: the loop is already stopped here
        self._conn = None  # dynalint: disable=unguarded-concurrent-mutation
"""


def test_dl013_fires_on_guarded_access_without_lock():
    vs = [v for v in race(("pkg/m.py", DL013_BAD_GUARDED))
          if v.code == "DL013"]
    assert len(vs) == 1
    assert vs[0].scope == "Svc.touch" and "guarded-by" in vs[0].message


def test_dl013_quiet_on_lock_held_and_sync_frames():
    assert "DL013" not in race_codes(DL013_GOOD_GUARDED)


def test_dl013_fires_on_unknown_lock():
    vs = [v for v in race(("pkg/m.py", DL013_BAD_UNKNOWN_LOCK))
          if v.code == "DL013"]
    assert len(vs) == 1 and "never" in vs[0].message


def test_dl013_fires_on_inconsistent_discipline():
    vs = [v for v in race(("pkg/m.py", DL013_BAD_INCONSISTENT))
          if v.code == "DL013"]
    assert len(vs) == 1
    assert vs[0].scope == "Svc.unlocked" and "_wlock" in vs[0].message


def test_dl013_suppression():
    assert "DL013" not in race_codes(DL013_SUPPRESSED)


# -------------------------------------------- DL014 lock-order-inversion


DL014_BAD = """
import asyncio

class Svc:
    async def fwd(self):
        async with self.a_lock:
            async with self.b_lock:
                pass

    async def rev(self):
        async with self.b_lock:
            async with self.a_lock:
                pass
"""

DL014_GOOD = """
import asyncio

class Svc:
    async def one(self):
        async with self.a_lock:
            async with self.b_lock:
                pass

    async def two(self):
        async with self.a_lock:         # same order everywhere: fine
            async with self.b_lock:
                pass
"""

DL014_INTERPROCEDURAL = """
import asyncio

class Svc:
    async def outer(self):
        async with self.a_lock:
            await self.inner()          # acquires b under a...

    async def inner(self):
        async with self.b_lock:
            pass

    async def other(self):
        async with self.b_lock:
            async with self.a_lock:     # ...opposite order here
                pass
"""

DL014_SUPPRESSED = """
import asyncio

class Svc:
    async def fwd(self):
        async with self.a_lock:
            # startup-only path, never concurrent with rev()
            async with self.b_lock:  # dynalint: disable=DL014
                pass

    async def rev(self):
        async with self.b_lock:
            async with self.a_lock:  # dynalint: disable=lock-order-inversion
                pass
"""


def test_dl014_fires_on_inverted_pair():
    vs = [v for v in race(("pkg/m.py", DL014_BAD)) if v.code == "DL014"]
    assert len(vs) == 2                      # one per direction
    assert {v.scope for v in vs} == {"Svc.fwd", "Svc.rev"}


def test_dl014_quiet_on_consistent_order():
    assert "DL014" not in race_codes(DL014_GOOD)


def test_dl014_fires_through_call_under_lock():
    vs = [v for v in race(("pkg/m.py", DL014_INTERPROCEDURAL))
          if v.code == "DL014"]
    assert vs and any(v.scope == "Svc.other" for v in vs)


def test_dl014_suppression():
    assert "DL014" not in race_codes(DL014_SUPPRESSED)


# ------------------------------------------- DL005 interprocedural (dynarace)


DL005_TRANSITIVE = """
import numpy as np

class JaxEngine:
    def _step(self):
        self._helper()

    def _helper(self):
        np.asarray(self.kv)
"""

DL005_TRANSITIVE_ALLOWLISTED = """
import numpy as np

class JaxEngine:
    def _step(self):
        self._decode_step_single()      # allowlisted sync arm

    def _decode_step_single(self):
        np.asarray(self.kv)
"""

DL005_TRANSITIVE_SUPPRESSED = """
import numpy as np

class JaxEngine:
    def _step(self):
        self._helper()  # dynalint: disable=jax-host-sync-in-hot-path

    def _helper(self):
        np.asarray(self.kv)
"""


def test_dl005_interprocedural_fires_at_hot_call_site():
    vs = [v for v in race(("dynamo_tpu/engine/fixture.py", DL005_TRANSITIVE))
          if v.code == "DL005"]
    assert len(vs) == 1
    assert vs[0].scope == "JaxEngine._step" and "_helper" in vs[0].message


def test_dl005_interprocedural_scoped_to_engine():
    assert "DL005" not in [
        v.code for v in race(("dynamo_tpu/llm/fixture.py", DL005_TRANSITIVE))]


def test_dl005_interprocedural_respects_allowlist():
    assert "DL005" not in [
        v.code for v in race(("dynamo_tpu/engine/fixture.py",
                              DL005_TRANSITIVE_ALLOWLISTED))]


def test_dl005_interprocedural_suppression_at_call_site():
    assert "DL005" not in [
        v.code for v in race(("dynamo_tpu/engine/fixture.py",
                              DL005_TRANSITIVE_SUPPRESSED))]


# ----------------------------------------------------- dynarace determinism


def test_dynarace_deterministic_output():
    """Two runs over the same fixture set produce byte-identical findings
    in identical order (the gate diffs against a baseline, so ordering
    churn would thrash it)."""
    mods = (("pkg/a.py", DL012_BAD), ("pkg/b.py", DL013_BAD_INCONSISTENT),
            ("pkg/c.py", DL014_BAD),
            ("dynamo_tpu/engine/fixture.py", DL005_TRANSITIVE))
    first = [v.render() for v in race(*mods)]
    second = [v.render() for v in race(*mods)]
    assert first and first == second


# --------------------------------------------------- dynajit (DL015-DL017)


def jit_pass(*mods):
    """Run the dynajit passes (DL015-DL017 + warmup coverage) over
    in-memory fixture modules given as (path, src) pairs."""
    from tools.dynalint import analyze_jit

    return analyze_jit([parse_module(src, path) for path, src in mods])


def jit_codes(src, path="dynamo_tpu/engine/fixture.py"):
    return [v.code for v in jit_pass((path, src))]


DL015_BAD_SHAPE = """
import jax
import jax.numpy as jnp
import numpy as np
from functools import partial

@partial(jax.jit, static_argnames=("k",))
def fwd(x, *, k=1):
    return x

class Eng:
    def _step(self, batch):
        toks = np.zeros((len(batch), 8), np.int32)   # raw batch dim
        fwd(jnp.asarray(toks))
"""

DL015_BAD_STATIC = """
import jax
import jax.numpy as jnp
from functools import partial

@partial(jax.jit, static_argnames=("k",))
def fwd(x, *, k=1):
    return x

class Eng:
    def _step(self, batch):
        fwd(jnp.zeros((4, 8)), k=len(batch))   # per-value recompile
"""

DL015_GOOD_BUCKETED = """
import jax
import jax.numpy as jnp
import numpy as np
from functools import partial

@partial(jax.jit, static_argnames=("k",))
def fwd(x, *, k=1):
    return x

class Eng:
    def _step(self, batch):
        B = self.ecfg.bucket_batch(len(batch))   # laundered
        toks = np.zeros((B, 8), np.int32)
        fwd(jnp.asarray(toks), k=self.ecfg.decode_steps)
"""

DL015_BAD_GATHER = """
import jax.numpy as jnp
import numpy as np
from typing import List

class Eng:
    def extract(self, page_ids: List[int]):
        idx = jnp.asarray(page_ids, jnp.int32)
        return np.asarray(self.kv_k[:, idx])
"""

DL015_GOOD_GATHER = """
import jax.numpy as jnp
import numpy as np
from typing import List

def _pad_pow2(lst, fill):
    return lst

class Eng:
    def extract(self, page_ids: List[int]):
        idx = jnp.asarray(_pad_pow2(list(page_ids), 0), jnp.int32)
        k = np.asarray(self.kv_k[:, idx])  # dynalint: disable=implicit-host-transfer
        return k[:, :len(page_ids)]
"""

DL015_UNWARMED_ENTRY = """
import jax
import jax.numpy as jnp

@jax.jit
def fwd(x):
    return x

@jax.jit
def other(x):
    return x

class Eng:
    def warmup(self):
        fwd(jnp.zeros((4,)))
    def _step(self):
        fwd(jnp.zeros((4,)))
        other(jnp.zeros((4,)))   # dispatched at serving time, never warmed
"""

DL015_SUPPRESSED = """
import jax
import jax.numpy as jnp
import numpy as np
from functools import partial

@partial(jax.jit, static_argnames=("k",))
def fwd(x, *, k=1):
    return x

class Eng:
    def _step(self, batch):
        toks = np.zeros((len(batch), 8), np.int32)
        # one-shot admin path, documented
        fwd(jnp.asarray(toks))  # dynalint: disable=recompile-hazard
"""


def test_dl015_fires_on_raw_shape():
    vs = [v for v in jit_pass(("dynamo_tpu/engine/fixture.py",
                               DL015_BAD_SHAPE)) if v.code == "DL015"]
    assert len(vs) == 1 and "request-varying shape" in vs[0].message
    assert vs[0].scope == "Eng._step"


def test_dl015_fires_on_raw_static_value():
    vs = [v for v in jit_pass(("dynamo_tpu/engine/fixture.py",
                               DL015_BAD_STATIC)) if v.code == "DL015"]
    assert len(vs) == 1 and "static arg" in vs[0].message


def test_dl015_quiet_on_bucketed():
    assert "DL015" not in jit_codes(DL015_GOOD_BUCKETED)


def test_dl015_fires_on_raw_device_gather():
    vs = [v for v in jit_pass(("dynamo_tpu/engine/fixture.py",
                               DL015_BAD_GATHER)) if v.code == "DL015"]
    assert len(vs) == 1 and "device gather" in vs[0].message
    # the same fixture's np.asarray over the gather is the DL017 shape
    assert "DL017" in jit_codes(DL015_BAD_GATHER)


def test_dl015_quiet_on_padded_gather():
    codes = jit_codes(DL015_GOOD_GATHER)
    assert "DL015" not in codes and "DL017" not in codes


def test_dl026_subsumes_dl015_warmup_coverage():
    """The unwarmed-entry coverage check moved to dynaform wholesale:
    DL015 keeps its shape rules and must NOT report coverage anymore,
    and DL026 reports the unwarmed entry exactly once (no
    double-reporting across the two passes)."""
    assert "DL015" not in jit_codes(DL015_UNWARMED_ENTRY)
    vs = [v for v in form_pass(("dynamo_tpu/engine/fixture.py",
                                DL015_UNWARMED_ENTRY))
          if v.code == "DL026"]
    assert len(vs) == 1
    assert "`other`" in vs[0].message and "warmup" in vs[0].message
    assert vs[0].scope == "other"


def test_dl015_suppression():
    assert "DL015" not in jit_codes(DL015_SUPPRESSED)


def test_dl015_scoped_to_engine_modules():
    # same source under llm/ produces nothing: the serving-layer scope
    assert jit_codes(DL015_BAD_SHAPE, path="dynamo_tpu/llm/fixture.py") \
        == []


# ------------------------------------------------ DL016 donation-discipline


DL016_BAD_USE_AFTER = """
import jax
import jax.numpy as jnp
from functools import partial

@partial(jax.jit, donate_argnames=("pool",))
def upd(pool, x):
    return pool.at[0].set(x)

class Eng:
    def _step(self):
        out = upd(self.pool_arr, 1)
        return self.pool_arr.sum()      # donated buffer used afterwards
"""

DL016_GOOD_REBIND = """
import jax
import jax.numpy as jnp
from functools import partial

@partial(jax.jit, donate_argnames=("pool",))
def upd(pool, x):
    return pool.at[0].set(x)

class Eng:
    def _step(self):
        self.pool_arr = upd(self.pool_arr, 1)
        return self.pool_arr.sum()      # rebound first: fine
"""

DL016_BAD_CONVENTION = """
import jax.numpy as jnp

class Eng:
    def _step(self):
        logits = self.decode_fn(self.kv_k, jnp.zeros((4,)))
        return self.kv_k.sum()          # pool donated by convention
"""

DL016_GOOD_CONVENTION = """
import jax.numpy as jnp

class Eng:
    def _step(self):
        logits, self.kv_k, self.kv_v = self.decode_fn(
            self.kv_k, self.kv_v, jnp.zeros((4,)))
        return self.kv_k.sum()
"""

DL016_BAD_UNDONATED_WRITE = """
import jax

@jax.jit
def scatter(pool, rows):
    return pool.at[:4].set(rows)    # written + returned, not donated
"""

DL016_GOOD_DONATED_WRITE = """
import jax
from functools import partial

@partial(jax.jit, donate_argnames=("pool",))
def scatter(pool, rows):
    return pool.at[:4].set(rows)
"""

DL016_SUPPRESSED = """
import jax.numpy as jnp

class Eng:
    def _step(self):
        logits = self.decode_fn(self.kv_k, jnp.zeros((4,)))
        # double-buffered pools: the read targets the standby copy
        return self.kv_k.sum()  # dynalint: disable=donation-discipline
"""


def test_dl016_fires_on_donated_use_after():
    vs = [v for v in jit_pass(("dynamo_tpu/engine/fixture.py",
                               DL016_BAD_USE_AFTER))
          if v.code == "DL016"]
    assert len(vs) == 1 and "self.pool_arr" in vs[0].message


def test_dl016_quiet_on_rebind():
    assert "DL016" not in jit_codes(DL016_GOOD_REBIND)


def test_dl016_pool_convention():
    assert "DL016" in jit_codes(DL016_BAD_CONVENTION)
    assert "DL016" not in jit_codes(DL016_GOOD_CONVENTION)


def test_dl016_fires_on_undonated_inplace_write():
    vs = [v for v in jit_pass(("dynamo_tpu/engine/fixture.py",
                               DL016_BAD_UNDONATED_WRITE))
          if v.code == "DL016"]
    assert len(vs) == 1 and "without donating" in vs[0].message
    assert "DL016" not in jit_codes(DL016_GOOD_DONATED_WRITE)


def test_dl016_suppression():
    assert "DL016" not in jit_codes(DL016_SUPPRESSED)


# --------------------------------------------- DL017 implicit-host-transfer


DL017_BAD_FLOW = """
import jax.numpy as jnp
import numpy as np

class Eng:
    def report(self):
        acc = jnp.zeros((4,)) + 1       # device value through a variable
        vals = acc.tolist()             # sink 1
        n = int(jnp.sum(acc))           # sink 2
        return vals, n
"""

DL017_GOOD_HOST = """
import numpy as np

class Eng:
    def _helper(self):
        xs = [1, 2, 3]
        return np.asarray(xs)    # host list: NOT a device sync (DL005's
                                 # callsite pattern cannot tell these apart)
"""

DL017_CHAIN_MODELS = """
import jax.numpy as jnp
import numpy as np

def land(x):
    t = jnp.zeros((4,))
    return np.asarray(t)        # device sink in a models module
"""

DL017_CHAIN_ENGINE = """
from dynamo_tpu.models.fixmod import land

class Eng:
    def _step(self):
        land(1)
"""

DL017_SUPPRESSED = """
import jax.numpy as jnp
import numpy as np

class Eng:
    def report(self):
        acc = jnp.zeros((4,)) + 1
        # the export IS the D2H, documented
        return np.asarray(acc)  # dynalint: disable=implicit-host-transfer
"""


def test_dl017_fires_on_device_value_flow():
    vs = [v for v in jit_pass(("dynamo_tpu/engine/fixture.py",
                               DL017_BAD_FLOW)) if v.code == "DL017"]
    assert len(vs) == 2
    assert any(".tolist()" in v.message for v in vs)
    assert any("`int()`" in v.message for v in vs)


def test_dl017_quiet_on_host_asarray():
    assert "DL017" not in jit_codes(DL017_GOOD_HOST)


def test_dl017_chain_reports_at_hot_call_site():
    vs = [v for v in jit_pass(
        ("dynamo_tpu/models/fixmod.py", DL017_CHAIN_MODELS),
        ("dynamo_tpu/engine/fixture.py", DL017_CHAIN_ENGINE))
        if v.code == "DL017"]
    assert len(vs) == 1
    assert vs[0].path == "dynamo_tpu/engine/fixture.py"
    assert vs[0].scope == "Eng._step" and "land" in vs[0].message


def test_dl017_suppression():
    assert "DL017" not in jit_codes(DL017_SUPPRESSED)


def test_dynajit_deterministic_output():
    mods = (("dynamo_tpu/engine/a.py", DL015_BAD_SHAPE),
            ("dynamo_tpu/engine/b.py", DL016_BAD_USE_AFTER),
            ("dynamo_tpu/models/fixmod.py", DL017_CHAIN_MODELS),
            ("dynamo_tpu/engine/c.py", DL017_CHAIN_ENGINE))
    first = [v.render() for v in jit_pass(*mods)]
    second = [v.render() for v in jit_pass(*mods)]
    assert first and first == second


# ------------------------------------------------------- generated artifacts


def test_env_docs_in_sync():
    """docs/env_vars.md must match the registry (regenerate with
    `python -m tools.dynalint --write-env-docs docs/env_vars.md`)."""
    from dynamo_tpu.runtime.config import render_env_docs

    path = os.path.join(REPO, "docs", "env_vars.md")
    with open(path, encoding="utf-8") as f:
        on_disk = f.read()
    assert on_disk == render_env_docs(), (
        "docs/env_vars.md is out of date — regenerate it with "
        "`python -m tools.dynalint --write-env-docs docs/env_vars.md`")


def test_wire_docs_in_sync():
    """docs/wire_schemas.md must match the registry (regenerate with
    `python -m tools.dynalint --wire-schemas docs/wire_schemas.md`)."""
    from dynamo_tpu.runtime.wire import render_wire_docs

    path = os.path.join(REPO, "docs", "wire_schemas.md")
    with open(path, encoding="utf-8") as f:
        on_disk = f.read()
    assert on_disk == render_wire_docs(), (
        "docs/wire_schemas.md is out of date — regenerate it with "
        "`python -m tools.dynalint --wire-schemas docs/wire_schemas.md`")


def test_disagg_frame_tables_in_sync():
    """The frame tables embedded in docs/disagg_serving.md are generated
    from the registry and must match it."""
    from dynamo_tpu.runtime.wire import render_frame_tables

    path = os.path.join(REPO, "docs", "disagg_serving.md")
    with open(path, encoding="utf-8") as f:
        doc = f.read()
    begin = "<!-- BEGIN wire-frames (generated from dynamo_tpu/runtime/wire.py) -->\n"
    end = "<!-- END wire-frames -->"
    assert begin in doc and end in doc
    embedded = doc.split(begin, 1)[1].split(end, 1)[0]
    assert embedded == render_frame_tables(("kv_transfer.", "prefill.")), (
        "docs/disagg_serving.md wire-frame tables are out of date — "
        "re-embed render_frame_tables(('kv_transfer.', 'prefill.'))")


def test_wire_schema_matches_static_parse():
    """The statically-parsed schemas (what the lint pass enforces) agree
    with the imported runtime registry (what DYN_WIRE_VALIDATE enforces)
    — one source of truth, two consumers."""
    from dynamo_tpu.runtime import wire as rt

    schemas, const_map, bad = load_wire_schemas(load_source(
        os.path.join(REPO, "dynamo_tpu", "runtime", "wire.py"),
        "dynamo_tpu/runtime/wire.py"))
    assert not bad
    assert set(schemas) == set(rt.FRAMES)
    for name, schema in schemas.items():
        frame = rt.FRAMES[name]
        assert schema.required == frame.required_names
        assert schema.fields == frame.field_names
        assert schema.version == frame.version
        assert dict(schema.when) == frame.when
        assert getattr(rt, schema.const) == name


def test_source_cache_parses_once():
    """The per-run AST cache: two loads of one unchanged file return the
    identical ModuleSource (the per-pass re-parse bug)."""
    path = os.path.join(REPO, "dynamo_tpu", "runtime", "wire.py")
    a = load_source(path, "dynamo_tpu/runtime/wire.py")
    b = load_source(path, "dynamo_tpu/runtime/wire.py")
    assert a is b


def test_cli_json_reports_wall_time():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.dynalint", "--json",
         os.path.join(REPO, "tools", "dynalint", "baseline.py")],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    import json

    out = json.loads(proc.stdout)
    assert "wall_seconds" in out and out["wall_seconds"] >= 0


def test_cli_all_entry():
    """`python -m tools.dynalint --all` runs per-file + dynaflow +
    dynarace off one shared parse cache; --json carries per-rule counts
    and per-pass wall seconds (the dynarace pass timed separately)."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.dynalint", "--all", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    import json

    out = json.loads(proc.stdout)
    assert out["violations"] == []
    assert "rule_counts" in out
    for p in ("per_file", "dynaflow", "dynarace", "dynajit", "dynahot",
              "dynaform"):
        assert out["passes"][p] >= 0


def test_lint_suite_wall_budget():
    """The whole in-process suite (per-file + dynaflow + dynarace, one
    shared parse) must stay within a pinned CPU-seconds ceiling so
    tier-1 does not bloat as the tree grows."""
    import time

    t0 = time.process_time()
    analyze_tree(GATE_PATHS, root=REPO)
    cpu = time.process_time() - t0
    assert cpu < 30.0, f"lint suite took {cpu:.1f} CPU-seconds (budget 30)"


def test_cli_callgraph_dot(tmp_path):
    dot = tmp_path / "graph.dot"
    proc = subprocess.run(
        [sys.executable, "-m", "tools.dynalint",
         "--callgraph-dot", str(dot),
         os.path.join(REPO, "dynamo_tpu", "llm", "disagg")],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    text = dot.read_text()
    assert text.startswith("digraph dynaflow")
    # async transfer-plane entrypoints are annotated
    assert "KvTransferServer._ingest_worker" in text
    # dynarace concurrency coloring: roots bold orange, shared-state
    # touchers double-bordered
    assert "#e06c00" in text
    assert "peripheries=2" in text


def test_env_registry_rejects_unregistered():
    from dynamo_tpu.runtime.config import UnregisteredEnvVar, env_str

    with pytest.raises(UnregisteredEnvVar):
        env_str("DYN_NO_SUCH_KNOB_EVER")


def test_ruff_gate():
    """Second gate: ruff (pyflakes + async + bugbear subset from
    pyproject.toml) when available; skip gracefully when not baked in."""
    try:
        import ruff  # noqa: F401
    except ImportError:
        pytest.skip("ruff not installed in this image")
    proc = subprocess.run([sys.executable, "-m", "ruff", "check", "."],
                          cwd=REPO, capture_output=True, text=True,
                          timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# =================================================== dynaproto (DL019-DL021)


from tools.dynalint.dynaproto import (PROTO_MODULE_REL,  # noqa: E402
                                      analyze_protocols, collect_anchors,
                                      load_protocols)
from tools.dynalint.modelcheck import (check_models,  # noqa: E402
                                       check_protocol_models)

PROTO_REG_TOY = """
TOY = register_protocol(
    "toy",
    states=("a", "b", "c"), initial="a", terminal=("c",),
    lock="loop",
    owners=(("runtime/toysvc.py", "state"),),
    edges=(
        {"from": "a", "to": "b", "name": "go"},
        {"from": "b", "to": "c", "name": "stop"},
    ),
)
"""

TOY_OK = """
class ToySvc:
    def __init__(self):
        self.state = "a"
    def go(self):
        self.state = "b"  # proto: toy a->b
    def stop(self):
        self.state = "c"  # proto: toy b->c
"""


def proto_pass(*mods, registry=PROTO_REG_TOY, race_model=None):
    sources = [parse_module(src, path) for path, src in mods]
    sources.append(parse_module(registry, PROTO_MODULE_REL))
    return analyze_protocols(sources, race_model=race_model)


def proto_codes(*mods, **kw):
    return [v.code for v in proto_pass(*mods, **kw)]


def test_dl019_quiet_on_anchored_good():
    assert proto_codes(("dynamo_tpu/runtime/toysvc.py", TOY_OK)) == []


DL019_BAD_ANCHORS = """
class ToySvc:
    def __init__(self):
        self.state = "a"
    def go(self):
        self.state = "b"  # proto: toy a->z
    def weird(self):
        pass  # proto: nosuchmachine a->b
    def skip(self):
        pass  # proto: toy a->c
"""


def test_dl019_fires_on_unknown_state_machine_and_edge():
    vs = [v for v in proto_pass(
        ("dynamo_tpu/runtime/toysvc.py", DL019_BAD_ANCHORS))
        if v.code == "DL019"]
    msgs = "\n".join(v.message for v in vs)
    assert "unknown state" in msgs           # a->z
    assert "unknown machine" in msgs         # nosuchmachine
    assert "not a declared edge" in msgs     # a->c undeclared
    assert len(vs) == 3


DL019_UNANCHORED_STORE = """
class ToySvc:
    def __init__(self):
        self.state = "a"          # __init__ = initial state, exempt
    def go(self):
        self.state = "b"          # protocol-state store, no anchor
"""

DL019_SUPPRESSED_STORE = """
class ToySvc:
    def go(self):
        # justification: migration shim
        self.state = "b"  # dynalint: disable=undeclared-transition
"""


def test_dl019_fires_on_unanchored_owner_store():
    vs = [v for v in proto_pass(
        ("dynamo_tpu/runtime/toysvc.py", DL019_UNANCHORED_STORE))
        if v.code == "DL019"]
    assert len(vs) == 1
    assert "carries no anchor" in vs[0].message
    assert vs[0].scope == "ToySvc.go"


def test_dl019_suppression():
    assert "DL019" not in proto_codes(
        ("dynamo_tpu/runtime/toysvc.py", DL019_SUPPRESSED_STORE))


def test_dl019_call_anchor_form():
    src = """
from dynamo_tpu.runtime import proto

class ToySvc:
    def go(self):
        proto.step("toy", "a", "b")
        self.state = "b"
    def stop(self):
        proto.step("toy", ("a", "b"), "c")   # a->c is NOT declared
        self.state = "c"
"""
    vs = [v for v in proto_pass(("dynamo_tpu/runtime/toysvc.py", src))
          if v.code == "DL019"]
    assert len(vs) == 1 and "`a`->`c`" in vs[0].message


def test_dl019_docstring_examples_are_not_anchors():
    src = '''
class ToySvc:
    def go(self):
        """Grammar example: # proto: toy a->z (not an anchor)."""
        self.state = "b"  # proto: toy a->b
    def stop(self):
        self.state = "c"  # proto: toy b->c
'''
    # the a->z docstring example would be a DL019 if comments were
    # matched textually; tokenize-based scanning keeps it inert
    assert proto_codes(("dynamo_tpu/runtime/toysvc.py", src)) == []


# ---------------------------------------------------- DL020 coverage/locks


def test_dl020_fires_on_uncovered_edge():
    # only the a->b edge is anchored: b->c has drifted
    src = """
class ToySvc:
    def go(self):
        self.state = "b"  # proto: toy a->b
"""
    vs = [v for v in proto_pass(("dynamo_tpu/runtime/toysvc.py", src))
          if v.code == "DL020"]
    assert len(vs) == 1
    assert "`stop`" in vs[0].message and vs[0].path == PROTO_MODULE_REL


def test_dl020_fires_on_edge_out_of_terminal():
    reg = """
BAD = register_protocol(
    "toy",
    states=("a", "c"), initial="a", terminal=("c",),
    edges=({"from": "c", "to": "a", "name": "undead"},),
)
"""
    vs = [v for v in proto_pass(registry=reg) if v.code == "DL020"]
    assert any("leaves terminal state" in v.message for v in vs)


def test_dl020_loop_machine_rejects_await_straddling_mutation():
    src = """
class ToySvc:
    async def go(self):
        # proto: toy a->b
        self.state = await self._fetch()
    def stop(self):
        self.state = "c"  # proto: toy b->c
"""
    vs = [v for v in proto_pass(("dynamo_tpu/runtime/toysvc.py", src))
          if v.code == "DL020"]
    assert len(vs) == 1 and "straddles an await" in vs[0].message


def test_dl020_attr_lock_discipline():
    reg = """
TOY = register_protocol(
    "toy",
    states=("a", "b", "c"), initial="a", terminal=("c",),
    lock="self._state_lock",
    owners=(("runtime/toysvc.py", "state"),),
    edges=(
        {"from": "a", "to": "b", "name": "go"},
        {"from": "b", "to": "c", "name": "stop"},
    ),
)
"""
    good = """
class ToySvc:
    def __init__(self):
        self._state_lock = Lock()
    async def go(self):
        async with self._state_lock:
            self.state = "b"  # proto: toy a->b
    async def stop(self):
        async with self._state_lock:
            self.state = "c"  # proto: toy b->c
"""
    bad = """
class ToySvc:
    async def go(self):
        self.state = "b"  # proto: toy a->b
    async def stop(self):
        self.state = "c"  # proto: toy b->c
"""
    assert "DL020" not in proto_codes(
        ("dynamo_tpu/runtime/toysvc.py", good), registry=reg)
    vs = [v for v in proto_pass(("dynamo_tpu/runtime/toysvc.py", bad),
                                registry=reg) if v.code == "DL020"]
    assert len(vs) == 2
    assert all("does not hold it" in v.message for v in vs)


def test_dl020_concurrent_roots_require_declared_lock():
    reg = """
TOY = register_protocol(
    "toy2",
    states=("a", "b"), initial="a",
    owners=(("runtime/toysvc.py", "state"),),
    edges=({"from": "a", "to": "b", "name": "go"},),
)
"""
    src = """
import asyncio

class ToySvc:
    def __init__(self):
        self.state = "a"
    async def worker(self):
        self.state = "b"  # proto: toy2 a->b
    def start(self):
        for _ in range(2):
            asyncio.create_task(self.worker())
"""
    from tools.dynalint.dynarace import build_race_model, scan_modules

    sources = [parse_module(src, "dynamo_tpu/runtime/toysvc.py"),
               parse_module(reg, PROTO_MODULE_REL)]
    graph = CallGraph.build(sources)
    model = build_race_model(graph, scan_modules(sources))
    vs = [v for v in analyze_protocols(sources, graph=graph,
                                       race_model=model)
          if v.code == "DL020"]
    assert len(vs) == 1 and "declares no lock" in vs[0].message


# ------------------------------------------------------------ model checker


def test_modelcheck_catches_nack_before_delete():
    """The drain-ordering bug class begin_drain had: the nack edge is
    enabled while the discovery record is still present."""
    reg = """
X = register_protocol(
    "drain2",
    states=("live", "draining", "stopped"), initial="live",
    terminal=("stopped",), lock="loop",
    vars={"discovery": ("present", "deleted")},
    init={"discovery": "present"},
    edges=(
        {"from": "live", "to": "draining", "name": "enter_draining"},
        {"from": "draining", "to": "draining", "name": "withdraw",
         "set": {"discovery": "deleted"}},
        {"from": "draining", "to": "draining", "name": "nack"},
        {"from": "draining", "to": "stopped", "name": "stop"},
    ),
    invariants=(
        {"name": "delete-before-nack",
         "never_fire": {"edges": ("nack",),
                        "when": {"discovery": "present"}}},
    ))
"""
    schemas, bad = load_protocols(parse_module(reg, PROTO_MODULE_REL))
    assert not bad
    vs = check_models(schemas)
    assert len(vs) == 1
    assert "delete-before-nack" in vs[0].message
    assert "enter_draining" in vs[0].message  # counterexample trace


def test_modelcheck_catches_missing_kill_guard_on_resume():
    reg = """
Y = register_protocol(
    "req2",
    states=("decode", "resumed", "cancelled"), initial="decode",
    terminal=("cancelled",), lock="loop",
    vars={"killed": (False, True)},
    init={"killed": False},
    edges=(
        {"from": "decode", "to": "resumed", "name": "revive"},
        {"from": "resumed", "to": "decode", "name": "redispatch"},
        {"from": "decode", "to": "cancelled", "name": "cancel",
         "when": {"killed": True}},
    ),
    env=(
        {"name": "client_kill", "when": {"killed": False},
         "set": {"killed": True}},
    ),
    invariants=(
        {"name": "no-resume-after-kill",
         "never_fire": {"edges": ("revive", "redispatch"),
                        "when": {"killed": True}}},
    ))
"""
    schemas, _ = load_protocols(parse_module(reg, PROTO_MODULE_REL))
    vs = check_models(schemas)
    assert len(vs) == 1 and "no-resume-after-kill" in vs[0].message


def test_modelcheck_never_stable_leak():
    """A terminal request whose entry has no close path quiesces open —
    the journal-leak shape."""
    reg = """
Z = register_protocol(
    "jrn2",
    states=("open", "closed"), initial="open", terminal=("closed",),
    vars={"request": ("streaming", "finished")},
    init={"request": "streaming"},
    edges=(
        {"from": "open", "to": "open", "name": "record"},
    ),
    env=(
        {"name": "finish", "when": {"request": "streaming"},
         "set": {"request": "finished"}},
    ),
    invariants=(
        {"name": "closed-after-finish",
         "never_stable": {"request": "finished", "state": "open"}},
    ))
"""
    schemas, _ = load_protocols(parse_module(reg, PROTO_MODULE_REL))
    # `record` is a self-loop: every open state has an enabled protocol
    # edge, so nothing is quiescent and the leak would hide — drop it
    vs = check_models(schemas)
    assert not vs  # self-loop masks quiescence: documents the semantics
    reg2 = reg.replace(
        '{"from": "open", "to": "open", "name": "record"},', "")
    schemas2, _ = load_protocols(parse_module(reg2, PROTO_MODULE_REL))
    vs2 = check_models(schemas2)
    assert len(vs2) == 1 and "closed-after-finish" in vs2[0].message
    assert "quiescent" in vs2[0].message


def test_modelcheck_depth_bound_reported():
    reg = """
W = register_protocol(
    "deep",
    states=("a", "b"), initial="a", depth=2,
    vars={"n": (0, 1, 2, 3, 4, 5, 6, 7)},
    init={"n": 0},
    edges=(
        {"from": "a", "to": "b", "name": "go", "set": {"n": "+1"}},
        {"from": "b", "to": "a", "name": "back", "set": {"n": "+1"}},
    ))
"""
    schemas, _ = load_protocols(parse_module(reg, PROTO_MODULE_REL))
    vs = check_models(schemas)
    assert len(vs) == 1 and "not exhausted" in vs[0].message


def test_modelcheck_deterministic_over_real_registry():
    schemas, bad = load_protocols(load_source(
        os.path.join(REPO, "dynamo_tpu", "runtime", "proto.py"),
        PROTO_MODULE_REL))
    assert not bad
    r1, r2 = {}, {}
    v1 = [v.render() for v in check_models(schemas, report_out=r1)]
    v2 = [v.render() for v in check_models(schemas, report_out=r2)]
    assert v1 == v2 and r1 == r2
    assert v1 == []   # the declared protocols hold their invariants
    assert len(r1) >= 5
    for name, rep in r1.items():
        assert rep["exhausted"], f"{name} not exhaustively explored"
        assert rep["model_states"] > 0


# ------------------------------------------------------------------- DL021


DL021_BAD = """
class ServeHandle:
    async def _on_request(self, msg):
        try:
            await msg.respond({"ok": True})
        except Exception:
            return None
"""

DL021_GOOD_RERAISE = """
class ServeHandle:
    async def _on_request(self, msg):
        try:
            await msg.respond({"ok": True})
        except Exception:
            raise
"""

DL021_GOOD_TYPED_FIRST = """
class ServeHandle:
    async def _on_request(self, msg):
        try:
            await msg.respond({"ok": True})
        except DeadlineExceeded:
            return None
        except Exception:
            return None
"""

DL021_GOOD_MAPS_INLINE = """
class ServeHandle:
    async def _on_request(self, msg):
        try:
            await msg.respond({"ok": True})
        except Exception as e:
            if isinstance(e, NoCapacity):
                return 503
            return 500
"""

DL021_SUPPRESSED = """
class ServeHandle:
    async def _on_request(self, msg):
        try:
            await msg.respond({"ok": True})
        # teardown sweep, no client response rides on it
        except Exception:  # dynalint: disable=typed-error-swallow
            return None
"""


def test_dl021_fires_on_swallowing_broad_except():
    vs = [v for v in proto_pass(
        ("dynamo_tpu/runtime/component.py", DL021_BAD))
        if v.code == "DL021"]
    assert len(vs) == 1 and vs[0].scope == "ServeHandle._on_request"


def test_dl021_quiet_on_reraise_typed_first_and_inline_map():
    for src in (DL021_GOOD_RERAISE, DL021_GOOD_TYPED_FIRST,
                DL021_GOOD_MAPS_INLINE):
        assert "DL021" not in proto_codes(
            ("dynamo_tpu/runtime/component.py", src)), src


def test_dl021_suppression():
    assert "DL021" not in proto_codes(
        ("dynamo_tpu/runtime/component.py", DL021_SUPPRESSED))


def test_dl021_scoped_to_http_and_servehandle_plane():
    # the same broad except in an unreachable helper module is quiet
    src = DL021_BAD.replace("ServeHandle", "Helper")
    assert "DL021" not in proto_codes(
        ("dynamo_tpu/llm/helper.py", src))


# --------------------------------------------------- dynaproto sync gates


def test_proto_registry_matches_static_parse():
    """The statically-parsed machines (what the lint pass + model
    checker enforce) agree with the imported runtime registry (what
    DYN_PROTO_VALIDATE enforces) — one source of truth, two consumers."""
    from dynamo_tpu.runtime import proto as rt

    schemas, bad = load_protocols(load_source(
        os.path.join(REPO, "dynamo_tpu", "runtime", "proto.py"),
        PROTO_MODULE_REL))
    assert not bad
    assert set(schemas) == set(rt.PROTOCOLS)
    for name, schema in schemas.items():
        m = rt.PROTOCOLS[name]
        assert tuple(schema.states) == m.states
        assert schema.initial == m.initial
        assert tuple(schema.terminal) == m.terminal
        assert schema.lock == m.lock
        assert schema.owners == m.owners
        assert len(schema.edges) == len(m.edges)
        for se, re_ in zip(schema.edges, m.edges):
            assert (se["from"], se["to"], se["name"]) == \
                (re_.frm, re_.to, re_.name)
        assert len(schema.invariants) == len(m.invariants)
        assert getattr(rt, schema.const) == name


def test_model_and_code_cannot_drift():
    """THE sync gate: every declared edge of every machine is anchored
    by a real code site in the tree, every anchor names a declared
    edge, and the model checker exhaustively explores >=5 machines with
    every declared invariant holding."""
    sources = load_sources(GATE_PATHS, root=REPO)
    proto_ms = next(m for m in sources if m.path == PROTO_MODULE_REL)
    schemas, bad = load_protocols(proto_ms)
    assert not bad and len(schemas) >= 5
    anchors, stores, abad = collect_anchors(sources, schemas)
    assert not abad
    covered = set()
    for a in anchors:
        assert a.machine in schemas, f"anchor names unknown {a.machine}"
        for pair in a.transitions:
            assert pair in schemas[a.machine].edge_pairs, \
                f"anchor {a.path}:{a.line} names undeclared {pair}"
            covered.add((a.machine,) + pair)
    for schema in schemas.values():
        for e in schema.edges:
            assert (schema.name, e["from"], e["to"]) in covered, \
                f"edge {schema.name}.{e['name']} has no code anchor"
    report: dict = {}
    assert check_models(schemas, report_out=report) == []
    exhausted = [n for n, r in report.items() if r["exhausted"]]
    assert len(exhausted) >= 5


def test_proto_docs_tables_in_sync():
    """The machine tables embedded in docs/static_analysis.md are
    generated from the registry and must match it."""
    from dynamo_tpu.runtime.proto import render_proto_tables

    path = os.path.join(REPO, "docs", "static_analysis.md")
    with open(path, encoding="utf-8") as f:
        doc = f.read()
    begin = ("<!-- BEGIN proto-machines (generated from "
             "dynamo_tpu/runtime/proto.py) -->\n")
    end = "<!-- END proto-machines -->"
    assert begin in doc and end in doc
    embedded = doc.split(begin, 1)[1].split(end, 1)[0]
    assert embedded == render_proto_tables(), (
        "docs/static_analysis.md proto-machine tables are out of date — "
        "re-embed dynamo_tpu.runtime.proto.render_proto_tables()")


def test_rule_table_in_sync_with_registry():
    """docs/static_analysis.md's rule table and --list-rules both carry
    every registered rule DL001-DL021 (the table was hand-maintained
    and drifted; now it is gated)."""
    from tools.dynalint.analyzer import RULES as _RULES

    path = os.path.join(REPO, "docs", "static_analysis.md")
    with open(path, encoding="utf-8") as f:
        doc = f.read()
    import re as _re

    rows = dict(_re.findall(r"^\| (DL\d+) \| `([a-z0-9\-]+)` \|", doc,
                            flags=_re.M))
    assert set(rows) == set(_RULES), (
        f"rule-table drift: missing {sorted(set(_RULES) - set(rows))}, "
        f"extra {sorted(set(rows) - set(_RULES))}")
    for code, name in rows.items():
        assert name == _RULES[code][0], f"{code} row names `{name}`"
    proc = subprocess.run(
        [sys.executable, "-m", "tools.dynalint", "--list-rules"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0
    listed = set(_re.findall(r"^(DL\d+)", proc.stdout, flags=_re.M))
    assert listed == set(_RULES)


def test_cli_all_reports_protocols_block():
    """--all --json carries the dynaproto/modelcheck pass timings and
    the per-machine state-space counts."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.dynalint", "--all", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    import json

    out = json.loads(proc.stdout)
    assert out["violations"] == []
    for p in ("dynaproto", "modelcheck"):
        assert out["passes"][p] >= 0
    protos = out["protocols"]
    assert len(protos) >= 5
    for name, rep in protos.items():
        assert rep["exhausted"], name
        assert rep["model_states"] > 0
        assert rep["edges"] > 0


def test_cli_proto_dot(tmp_path):
    dot = tmp_path / "machines.dot"
    proc = subprocess.run(
        [sys.executable, "-m", "tools.dynalint",
         "--proto-dot", str(dot)],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    text = dot.read_text()
    assert text.startswith("digraph dynaproto")
    assert "breaker" in text and "serve_handle.drain" in text
    # every edge in the real tree is anchored: nothing renders red
    assert "color=red" not in text
    assert "forestgreen" in text


def test_proto_dot_colors_drifted_edges(tmp_path):
    from tools.dynalint.dynaproto import protocols_to_dot

    schemas, _ = load_protocols(parse_module(PROTO_REG_TOY,
                                             PROTO_MODULE_REL))
    src = """
class ToySvc:
    def go(self):
        self.state = "b"  # proto: toy a->b
"""
    anchors, _stores, _bad = collect_anchors(
        [parse_module(src, "dynamo_tpu/runtime/toysvc.py")], schemas)
    text = protocols_to_dot(schemas, anchors)
    assert "color=forestgreen" in text   # anchored a->b
    assert "color=red" in text           # drifted b->c


def test_dynaproto_deterministic_output():
    mods = (("dynamo_tpu/runtime/toysvc.py", DL019_BAD_ANCHORS),
            ("dynamo_tpu/runtime/component.py", DL021_BAD))
    first = [v.render() for v in proto_pass(*mods)]
    second = [v.render() for v in proto_pass(*mods)]
    assert first and first == second


# ---------------------------------------------- dynahot (DL022-DL024)

from tools.dynalint import (HOT_FRAME_RE, HOT_ROOTS,  # noqa: E402
                            analyze_hot, hot_regions)


def hot_pass(*mods):
    """Run the dynahot pass over fixture modules (path, src)."""
    sources = [parse_module(src, path) for path, src in mods]
    return analyze_hot(sources)


def hot_codes(*mods):
    return [v.code for v in hot_pass(*mods)]


# engine-path module with a name-grammar hot root (`_step`): the
# legacy DL005 grammar seeds dynahot scheduler-kind regions too
DL022_BAD_DEFAULT = """
class Eng:
    def _step(self, reqs):
        for r in reqs:
            if r.tok in (self.cfg.stop.ids or []):
                self.kill(r)
"""

DL022_GOOD_HOISTED = """
class Eng:
    def _step(self, reqs):
        stop_ids = self.cfg.stop.ids
        if not stop_ids:
            return
        for r in reqs:
            if r.tok in stop_ids:
                self.kill(r)
"""

DL022_BAD_COMPILE = """
import re

class Eng:
    def _step(self, lines):
        for ln in lines:
            if re.compile("tok=(\\\\d+)").search(ln):
                self.hit(ln)
"""

DL022_BAD_LOOP_PROBE = """
import asyncio

class Eng:
    def _step(self, outs):
        for o in outs:
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                loop = None
            self.put(loop, o)
"""


def test_dl022_fires_on_invariant_default_rebuild():
    assert "DL022" in hot_codes(
        ("dynamo_tpu/engine/toyeng.py", DL022_BAD_DEFAULT))


def test_dl022_quiet_on_hoisted():
    assert "DL022" not in hot_codes(
        ("dynamo_tpu/engine/toyeng.py", DL022_GOOD_HOISTED))


def test_dl022_fires_on_compile_in_loop():
    assert "DL022" in hot_codes(
        ("dynamo_tpu/engine/toyeng.py", DL022_BAD_COMPILE))


def test_dl022_fires_on_exception_probe_loop():
    out = hot_pass(("dynamo_tpu/engine/toyeng.py", DL022_BAD_LOOP_PROBE))
    assert ["DL022"] == [v.code for v in out]
    assert "get_running_loop" in out[0].message


def test_dl022_quiet_off_hot_path():
    # same body, module outside engine/ and no declared root: no region
    assert hot_codes(("dynamo_tpu/util/toy.py", DL022_BAD_DEFAULT)) == []


def test_dl022_suppression():
    src = DL022_BAD_DEFAULT.replace(
        "            if r.tok in",
        "            # dynalint: disable=hot-loop-invariant-work\n"
        "            if r.tok in")
    assert "DL022" not in hot_codes(("dynamo_tpu/engine/toyeng.py", src))


DL023_BAD_FSTRING = """
class Eng:
    def _step(self, reqs):
        for r in reqs:
            self.logger.debug(f"dispatch {r.id} pages={r.pages}")
"""

DL023_GOOD_LAZY = """
class Eng:
    def _step(self, reqs):
        for r in reqs:
            self.logger.debug("dispatch %s pages=%s", r.id, r.pages)
"""

DL023_GOOD_GUARDED = """
import logging

class Eng:
    def _step(self, reqs):
        for r in reqs:
            if self.logger.isEnabledFor(logging.DEBUG):
                self.logger.debug(f"dispatch {r.id} pages={r.pages}")
"""


def test_dl023_fires_on_eager_fstring_log():
    out = hot_pass(("dynamo_tpu/engine/toyeng.py", DL023_BAD_FSTRING))
    assert "DL023" in [v.code for v in out]


def test_dl023_quiet_on_lazy_args_and_level_guard():
    assert "DL023" not in hot_codes(
        ("dynamo_tpu/engine/toyeng.py", DL023_GOOD_LAZY))
    assert "DL023" not in hot_codes(
        ("dynamo_tpu/engine/toyeng.py", DL023_GOOD_GUARDED))


def test_dl023_suppression():
    src = DL023_BAD_FSTRING.replace(
        "self.logger.debug(",
        "self.logger.debug(  # dynalint: disable=hot-eager-format\n"
        "                ")
    assert "DL023" not in hot_codes(("dynamo_tpu/engine/toyeng.py", src))


DL024_BAD_APPEND = """
class Eng:
    def __init__(self):
        self.history = []

    def _step(self, reqs):
        for r in reqs:
            self.history.append(r.id)
"""

DL024_GOOD_RING = """
from collections import deque

class Eng:
    def __init__(self):
        self.history = deque(maxlen=256)

    def _step(self, reqs):
        for r in reqs:
            self.history.append(r.id)
"""

DL024_GOOD_EVICTED = """
class Eng:
    def __init__(self):
        self.history = []

    def _step(self, reqs):
        for r in reqs:
            self.history.append(r.id)

    def reap(self):
        while len(self.history) > 256:
            self.history.pop()
"""


def test_dl024_fires_on_unbounded_request_path_growth():
    out = hot_pass(("dynamo_tpu/engine/toyeng.py", DL024_BAD_APPEND))
    assert "DL024" in [v.code for v in out]
    assert "history" in out[0].message


def test_dl024_quiet_on_ring_and_eviction():
    assert "DL024" not in hot_codes(
        ("dynamo_tpu/engine/toyeng.py", DL024_GOOD_RING))
    assert "DL024" not in hot_codes(
        ("dynamo_tpu/engine/toyeng.py", DL024_GOOD_EVICTED))


def test_dl024_bounded_by_comment():
    src = DL024_BAD_APPEND.replace(
        "self.history.append(r.id)",
        "# bounded-by: reqs is capped by max_batch upstream\n"
        "            self.history.append(r.id)")
    assert "DL024" not in hot_codes(("dynamo_tpu/engine/toyeng.py", src))


def test_dl024_suppression():
    src = DL024_BAD_APPEND.replace(
        "self.history.append(r.id)",
        "self.history.append(r.id)  # dynalint: disable=unbounded-growth")
    assert "DL024" not in hot_codes(("dynamo_tpu/engine/toyeng.py", src))


def test_dl024_quiet_off_request_path():
    # growth in a frame no hot root reaches: not DL024's business
    src = DL024_BAD_APPEND.replace("def _step", "def admin_dump")
    assert hot_codes(("dynamo_tpu/engine/toyeng.py", src)) == []


# ------------------------------------------- dynahot region machinery


def test_hot_frame_re_matches_legacy_hot_re():
    """DL005 behavior pin: the registry-derived frame-name pattern is
    EXACTLY the legacy analyzer HOT_RE grammar for ["step"]."""
    import re as _re

    legacy = _re.compile(r"(^|_)step($|_)")
    corpus = ["_step", "step", "decode_step_fn", "stepper", "misstep",
              "_stepper", "my_step", "step_once", "restep", "_loop",
              "process_window", "generate", "schedule", "steps"]
    for name in corpus:
        assert bool(HOT_FRAME_RE.search(name)) == \
            bool(legacy.search(name)), name


def test_hot_regions_reach_declared_roots_with_loop_depth():
    """Declared per_token roots seed regions; callees reached through a
    loop accumulate depth."""
    src = """
class Backend:
    def generate(self, req):
        self.prep(req)
        for tok in req:
            self.relay(tok)

    def prep(self, req):
        pass

    def relay(self, tok):
        pass
"""
    sources = [parse_module(src, "dynamo_tpu/llm/backend.py")]
    regions = hot_regions(CallGraph.build(sources), sources)
    gen = regions["dynamo_tpu.llm.backend:Backend.generate"]
    assert gen.kind == "per_token" and gen.depth == 0
    assert regions["dynamo_tpu.llm.backend:Backend.prep"].depth == 0
    assert regions["dynamo_tpu.llm.backend:Backend.relay"].depth == 1


def test_hot_roots_registry_is_pure_literal():
    """The registry must stay a declared literal (tooling and docs parse
    it); every declared root names module:Class.method."""
    for kind in ("scheduler", "per_token"):
        for entry in HOT_ROOTS[kind]:
            mod, qual = entry.split(":")
            assert mod and "." in mod and "." in qual, entry
    assert HOT_ROOTS["frame_name_segments"] == ["step"]


def test_dynahot_deterministic_output():
    mods = (("dynamo_tpu/engine/toyeng.py", DL022_BAD_DEFAULT),
            ("dynamo_tpu/engine/toyeng2.py", DL024_BAD_APPEND),
            ("dynamo_tpu/engine/toyeng3.py", DL023_BAD_FSTRING))
    first = [v.render() for v in hot_pass(*mods)]
    second = [v.render() for v in hot_pass(*mods)]
    assert first and first == second


def test_source_cache_keys_on_content_hash(tmp_path):
    """Same mtime + same size but different bytes must MISS the parse
    cache (the staleness bug the sha1 key fixes)."""
    f = tmp_path / "mod.py"
    f.write_text("x = 1\n")
    st = os.stat(f)
    a = load_source(str(f), "dynamo_tpu/fixture_cache.py")
    f.write_text("y = 2\n")  # same byte length
    os.utime(f, (st.st_atime, st.st_mtime))  # force identical mtime
    b = load_source(str(f), "dynamo_tpu/fixture_cache.py")
    assert a is not b
    assert "y" in [n.targets[0].id for n in b.tree.body]


# --------------------------------------------- dynaform (DL025-DL027)

from tools.dynalint import analyze_form  # noqa: E402


def form_pass(*mods):
    """Run the dynaform passes (DL025-DL027) over fixture modules."""
    return analyze_form([parse_module(src, path) for path, src in mods])


def form_codes(*mods):
    return [v.code for v in form_pass(*mods)]


ENG = "dynamo_tpu/engine/fixture.py"

DL025_BAD_WIDEN = """
import jax.numpy as jnp

class Eng:
    def _step(self):
        bias = jnp.zeros((4,))            # fp32 default
        return self.kv_k * 2 + bias       # bf16 (+) fp32 widens
"""

DL025_BAD_INT8 = """
import jax.numpy as jnp

class Eng:
    def _step(self):
        q = jnp.zeros((4, 8), jnp.int8)
        return q * 0.5                    # int8 (+) python float -> fp32
"""

DL025_GOOD_WEAK = """
class Eng:
    def _step(self):
        return self.kv_k * 0.5            # weak python float stays bf16
"""

DL025_PROMOTE_OK = """
import jax.numpy as jnp

class Eng:
    def _step(self):
        acc = jnp.zeros((4,))
        # promote-ok: softmax accumulation in fp32 by design
        return acc + self.kv_k
"""


def test_dl025_fires_on_fp32_widen():
    vs = [v for v in form_pass((ENG, DL025_BAD_WIDEN))
          if v.code == "DL025"]
    assert len(vs) == 1
    assert "promotes a bf16 device value to fp32" in vs[0].message
    assert vs[0].scope == "Eng._step"


def test_dl025_fires_on_int8_float_mix():
    vs = [v for v in form_pass((ENG, DL025_BAD_INT8))
          if v.code == "DL025"]
    assert len(vs) == 1 and "4x" in vs[0].message


def test_dl025_quiet_on_weak_scalar():
    # bf16 (+) python float is the weak-type FAST path, not a widening
    assert "DL025" not in form_codes((ENG, DL025_GOOD_WEAK))


def test_dl025_promote_ok_comment():
    assert "DL025" not in form_codes((ENG, DL025_PROMOTE_OK))


def test_dl025_suppression():
    src = DL025_BAD_WIDEN.replace(
        "        return self.kv_k * 2 + bias",
        "        # dynalint: disable=silent-dtype-promotion\n"
        "        return self.kv_k * 2 + bias")
    assert "DL025" not in form_codes((ENG, src))


def test_dl025_quiet_off_hot_path():
    # same widening in a frame no hot root reaches: not DL025's business
    src = DL025_BAD_WIDEN.replace("def _step", "def admin_dump")
    assert form_codes((ENG, src)) == []


# the three historical fence findings, re-derived statically on seeds

DL026_HIST_KWARGS = """
import jax
import jax.numpy as jnp
from functools import partial

@partial(jax.jit, static_argnames=("penalties",))
def decode(x, *, penalties=None):
    return x

class Eng:
    def warmup(self):
        decode(jnp.zeros((4, 8), jnp.bfloat16))
    def _step(self):
        decode(jnp.zeros((4, 8), jnp.bfloat16), penalties=None)
"""

DL026_HIST_CARRY = """
import jax
import jax.numpy as jnp

@jax.jit
def window(tok, kv):
    return tok, kv

class Eng:
    def warmup(self):
        tok = jnp.zeros((4,), jnp.int32)      # host-built: uncommitted
        window(tok, self.kv_k)
    def _step(self):
        tok, self.kv_k = window(self.prev_tok, self.kv_k)
        window(tok, self.kv_k)                # jit result: committed
"""

DL026_HIST_LISTY = """
import jax.numpy as jnp

def _pad_pow2(lst, fill):
    return lst

class Eng:
    def warmup(self):
        self.decode_fn(jnp.zeros((4,), jnp.int32))
    def _drain(self, page_ids):
        idx = jnp.asarray(_pad_pow2(list(page_ids), 0), jnp.int32)
        return idx
"""


def test_dl026_historical_explicit_vs_defaulted_kwargs():
    """PR-9 fence finding: `penalties=None` passed explicitly keys a
    DIFFERENT jit cache entry than the warmed defaulted form."""
    vs = [v for v in form_pass((ENG, DL026_HIST_KWARGS))
          if v.code == "DL026"]
    assert len(vs) == 1
    assert "no warmup form has this arity/kwarg set" in vs[0].message
    assert "penalties={None}" in vs[0].message   # the serving form render


def test_dl026_historical_committed_vs_uncommitted_carry():
    """PR-12 fence finding: refeeding a jit-result (committed) carry
    where warmup passed a host-built (uncommitted) one recompiles under
    a mesh."""
    vs = [v for v in form_pass((ENG, DL026_HIST_CARRY))
          if v.code == "DL026"]
    assert len(vs) == 1
    assert "different jit cache entries under a mesh" in vs[0].message


def test_dl026_historical_listy_convert():
    """PR-17 fence finding: `jnp.asarray(<python list>)` on the serving
    drain lowers one tiny program per distinct pow2 padded length."""
    vs = [v for v in form_pass((ENG, DL026_HIST_LISTY))
          if v.code == "DL026"]
    assert len(vs) == 1
    assert "python list" in vs[0].message
    assert "list-convert" in vs[0].message


def test_dl026_quiet_when_warmup_covers_listy():
    src = DL026_HIST_LISTY.replace(
        "        self.decode_fn(jnp.zeros((4,), jnp.int32))",
        "        self.decode_fn(jnp.zeros((4,), jnp.int32))\n"
        "        jnp.asarray(_pad_pow2([0], 0), jnp.int32)")
    assert "DL026" not in form_codes((ENG, src))


DL026_BAD_STATIC_VALUE = """
import jax
import jax.numpy as jnp
from functools import partial

@partial(jax.jit, static_argnames=("topn",))
def win(x, *, topn=0):
    return x

class Eng:
    def warmup(self):
        win(jnp.zeros((4,), jnp.bfloat16), topn=0)
    def _step(self, wants):
        t = self.ecfg.max_top_logprobs if wants else 0
        win(jnp.zeros((4,), jnp.bfloat16), topn=t)
"""


def test_dl026_static_kwarg_value_set_fires():
    """static argnames key the cache per VALUE: a serving value set not
    covered by warmup is a first-request compile (the fleet finding this
    PR fixed: logprobs_topn flipping 0 -> max_top_logprobs)."""
    vs = [v for v in form_pass((ENG, DL026_BAD_STATIC_VALUE))
          if v.code == "DL026"]
    assert len(vs) == 1
    assert "never warmed" in vs[0].message
    assert "cfg:max_top_logprobs" in vs[0].message


def test_dl026_static_value_set_covered_by_warmup_loop():
    src = DL026_BAD_STATIC_VALUE.replace(
        "        win(jnp.zeros((4,), jnp.bfloat16), topn=0)",
        "        variants = [0]\n"
        "        variants.append(self.ecfg.max_top_logprobs)\n"
        "        for t in variants:\n"
        "            win(jnp.zeros((4,), jnp.bfloat16), topn=t)")
    assert "DL026" not in form_codes((ENG, src))


def test_dl026_quiet_on_matching_forms():
    src = DL026_HIST_KWARGS.replace(
        "        decode(jnp.zeros((4, 8), jnp.bfloat16))\n",
        "        decode(jnp.zeros((4, 8), jnp.bfloat16), penalties=None)\n")
    assert "DL026" not in form_codes((ENG, src))


def test_dl026_suppression():
    src = DL026_BAD_STATIC_VALUE.replace(
        "        win(jnp.zeros((4,), jnp.bfloat16), topn=t)",
        "        # dynalint: disable=warmup-form-drift\n"
        "        win(jnp.zeros((4,), jnp.bfloat16), topn=t)")
    assert "DL026" not in form_codes((ENG, src))


DL027_BAD_NO_SCALE = """
from dynamo_tpu.engine.kv_compress import dequantize_pages

class Eng:
    def _drain(self):
        return dequantize_pages(self.staged)      # missing scale tensor
"""

DL027_BAD_DROPPED_SCALE = """
from dynamo_tpu.engine.kv_compress import quantize_pages

class Eng:
    def _drain(self, g):
        q, s = quantize_pages(g)
        self.stash(q)                             # s never used
"""

DL027_GOOD_PAIR = """
from dynamo_tpu.engine.kv_compress import (dequantize_pages,
                                           quantize_pages)

class Eng:
    def _drain(self, g):
        q, s = quantize_pages(g)
        return dequantize_pages(q, s)
"""

DL027_BAD_RAW_PAGES = """
import jax.numpy as jnp

class Eng:
    def _restore(self, idx):
        if self.ecfg.host_tier_int8:
            pages = self.host_k[idx]
            self.kv_k = self.decode_fn(jnp.asarray(pages))  # raw codes
"""

DL027_BAD_FP16_MIX = """
class Eng:
    def _restore(self, idx):
        if self.ecfg.host_tier_int8:
            pass
        else:
            return self.host_k_s[idx]   # fp16 branch reads a scale pool
"""


def test_dl027_missing_scale_arg():
    vs = [v for v in form_pass((ENG, DL027_BAD_NO_SCALE))
          if v.code == "DL027"]
    assert len(vs) == 1 and "without its scale tensor" in vs[0].message


def test_dl027_dropped_scale():
    vs = [v for v in form_pass((ENG, DL027_BAD_DROPPED_SCALE))
          if v.code == "DL027"]
    assert len(vs) == 1 and "`s`" in vs[0].message
    assert "never used" in vs[0].message


def test_dl027_quiet_on_paired_quant_dequant():
    assert form_codes((ENG, DL027_GOOD_PAIR)) == []


def test_dl027_raw_int8_pages_into_jit():
    vs = [v for v in form_pass((ENG, DL027_BAD_RAW_PAGES))
          if v.code == "DL027"]
    assert len(vs) == 1
    assert "without dequantize_pages" in vs[0].message


def test_dl027_fp16_branch_touches_scale_pool():
    vs = [v for v in form_pass((ENG, DL027_BAD_FP16_MIX))
          if v.code == "DL027"]
    assert len(vs) == 1 and "never mix" in vs[0].message


def test_dl027_suppression():
    src = DL027_BAD_NO_SCALE.replace(
        "        return dequantize_pages(self.staged)",
        "        # dynalint: disable=tier-dtype-contract\n"
        "        return dequantize_pages(self.staged)")
    assert "DL027" not in form_codes((ENG, src))


def test_dl027_scoped_to_engine_modules():
    # the host-side *_np pair in llm/ transfer code is out of scope
    assert form_codes(("dynamo_tpu/llm/fixture.py",
                       DL027_BAD_NO_SCALE)) == []


def test_dynaform_deterministic_output():
    mods = ((ENG, DL025_BAD_WIDEN),
            ("dynamo_tpu/engine/fixture2.py", DL027_BAD_DROPPED_SCALE),
            ("dynamo_tpu/engine/fixture3.py", DL026_BAD_STATIC_VALUE))
    first = [v.render() for v in form_pass(*mods)]
    second = [v.render() for v in form_pass(*mods)]
    assert first and first == second
