"""Ring attention / sequence-parallel long prefill (SURVEY §5 long-context:
absent in the reference; first-class here). Runs on the 8-device virtual
CPU mesh from conftest.py."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.llama import init_params, reference_forward
from dynamo_tpu.parallel.mesh import MeshSpec, shard_params
from dynamo_tpu.parallel.ring_attention import (make_long_prefill_fn,
                                                ring_attention,
                                                scatter_prefill_kv)


def _full_attention(q, k, v, positions, scale):
    """Dense causal GQA reference."""
    B, T, H, hd = q.shape
    KV = k.shape[2]
    qg = q.reshape(B, T, KV, H // KV, hd)
    scores = jnp.einsum("btkgh,bskh->bkgts", qg.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    valid = (positions[:, None, :] >= 0) & \
            (positions[:, None, :] <= positions[:, :, None])
    scores = jnp.where(valid[:, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgts,bskh->btkgh", probs, v.astype(jnp.float32))
    return out.reshape(B, T, H, hd)


@pytest.mark.parametrize("spec", [MeshSpec(seq=8), MeshSpec(seq=4, model=2)])
def test_ring_matches_dense(spec):
    mesh = spec.build()
    rng = np.random.RandomState(0)
    B, T, H, KV, hd = 2, 32, 4, 2, 16
    q = jnp.asarray(rng.randn(B, T, H, hd), jnp.float32)
    k = jnp.asarray(rng.randn(B, T, KV, hd), jnp.float32)
    v = jnp.asarray(rng.randn(B, T, KV, hd), jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
    with jax.set_mesh(mesh):
        out = ring_attention(q, k, v, positions, mesh, scale=0.25)
    ref = _full_attention(q, k, v, positions, 0.25)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_ring_padding_rows():
    """Padding (-1 positions) must not contaminate valid rows, and fully
    padded query rows must come out finite."""
    mesh = MeshSpec(seq=8).build()
    rng = np.random.RandomState(1)
    B, T, H, KV, hd = 1, 16, 2, 2, 8
    q = jnp.asarray(rng.randn(B, T, H, hd), jnp.float32)
    k = jnp.asarray(rng.randn(B, T, KV, hd), jnp.float32)
    v = jnp.asarray(rng.randn(B, T, KV, hd), jnp.float32)
    n_valid = 10
    positions = jnp.where(jnp.arange(T) < n_valid, jnp.arange(T), -1)[None]
    with jax.set_mesh(mesh):
        out = ring_attention(q, k, v, positions, mesh, scale=0.3)
    ref = _full_attention(q[:, :n_valid], k[:, :n_valid], v[:, :n_valid],
                          positions[:, :n_valid], 0.3)
    np.testing.assert_allclose(np.asarray(out[:, :n_valid]),
                               np.asarray(ref), rtol=2e-4, atol=2e-5)
    assert np.isfinite(np.asarray(out)).all()


def test_long_prefill_matches_reference_forward():
    """Sequence-parallel prefill over the whole stack == dense forward."""
    cfg = ModelConfig.tiny()
    mesh = MeshSpec(seq=4, model=2).build()
    params = init_params(cfg, jax.random.PRNGKey(0))
    params = shard_params(params, cfg, mesh)
    B, T = 2, 32
    tokens = jnp.asarray(
        np.random.RandomState(2).randint(1, 500, (B, T)), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    fn = make_long_prefill_fn(cfg, mesh)
    with jax.set_mesh(mesh):
        logits, k_all, v_all = fn(params, tokens, positions)
    ref = reference_forward(params, cfg, tokens)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref[:, -1]),
                               rtol=5e-4, atol=5e-4)
    assert k_all.shape == (cfg.num_layers, B, T, cfg.num_kv_heads,
                           cfg.head_dim_)


def _gemma2_tiny():
    return ModelConfig.tiny(num_heads=4, num_kv_heads=2, head_dim=16,
                            hidden_size=64, vocab_size=256,
                            model_type="gemma2", sandwich_norms=True,
                            embed_scale=True, norm_unit_offset=True,
                            hidden_act="gelu_tanh",
                            attn_logit_softcap=20.0,
                            final_logit_softcap=30.0, sliding_window=6,
                            query_pre_attn_scalar=16.0)


def test_ring_kernel_sliding_window_and_softcap():
    """The ring kernel with Gemma-2 knobs == dense attention with the
    same mask/softcap — including a window SMALLER than a ring block
    (window is a position predicate, not a block-local one) and one
    larger than a block."""
    mesh = MeshSpec(seq=4).build()
    rng = np.random.RandomState(3)
    B, T, H, KV, hd = 2, 32, 4, 2, 16
    q = jnp.asarray(rng.randn(B, T, H, hd), jnp.float32)
    k = jnp.asarray(rng.randn(B, T, KV, hd), jnp.float32)
    v = jnp.asarray(rng.randn(B, T, KV, hd), jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
    for window in (5, 13):  # block = T/4 = 8: below and above
        for softcap in (None, 20.0):
            with jax.set_mesh(mesh):
                out = ring_attention(q, k, v, positions, mesh, scale=0.25,
                                     softcap=softcap, window=window,
                                     is_sliding=True)
            qg = q.reshape(B, T, KV, H // KV, hd)
            scores = jnp.einsum("btkgh,bskh->bkgts",
                                qg.astype(jnp.float32), k) * 0.25
            if softcap:
                scores = softcap * jnp.tanh(scores / softcap)
            j, t = positions[:, None, :], positions[:, :, None]
            vis = (j <= t) & (j > t - window)
            scores = jnp.where(vis[:, None, None], scores, -1e30)
            ref = jnp.einsum("bkgts,bskh->btkgh",
                             jax.nn.softmax(scores, axis=-1),
                             v).reshape(B, T, H, hd)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       rtol=2e-4, atol=2e-5)
            # global layers must ignore the window even when one is set
            with jax.set_mesh(mesh):
                out_g = ring_attention(q, k, v, positions, mesh,
                                       scale=0.25, softcap=softcap,
                                       window=window, is_sliding=False)
            scores_g = jnp.einsum("btkgh,bskh->bkgts",
                                  qg.astype(jnp.float32), k) * 0.25
            if softcap:
                scores_g = softcap * jnp.tanh(scores_g / softcap)
            scores_g = jnp.where((j <= t)[:, None, None], scores_g, -1e30)
            ref_g = jnp.einsum("bkgts,bskh->btkgh",
                               jax.nn.softmax(scores_g, axis=-1),
                               v).reshape(B, T, H, hd)
            np.testing.assert_allclose(np.asarray(out_g),
                                       np.asarray(ref_g),
                                       rtol=2e-4, atol=2e-5)


def test_long_prefill_gemma2_matches_reference_forward():
    """Gemma-2 semantics (sliding window on even layers, score + final
    softcaps, sandwich norms, embed scale) through the sequence-parallel
    ring prefill == the dense reference forward (VERDICT r4 task 7 —
    this was a hard ValueError for two rounds)."""
    cfg = _gemma2_tiny()
    mesh = MeshSpec(seq=4, model=2).build()
    params = init_params(cfg, jax.random.PRNGKey(4))
    sharded = shard_params(params, cfg, mesh)
    B, T = 2, 32
    tokens = jnp.asarray(
        np.random.RandomState(5).randint(1, 250, (B, T)), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    fn = make_long_prefill_fn(cfg, mesh)
    with jax.set_mesh(mesh):
        logits, k_all, v_all = fn(sharded, tokens, positions)
    ref = reference_forward(params, cfg, tokens)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref[:, -1]),
                               rtol=5e-4, atol=5e-4)


def test_engine_accepts_gemma2_long_prefill():
    """JaxEngine no longer refuses Gemma-2 + long_prefill_threshold."""
    from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine

    cfg = _gemma2_tiny()
    mesh = MeshSpec(seq=4, model=2).build()
    eng = JaxEngine(cfg, EngineConfig(page_size=8, num_pages=32,
                                      max_batch=2, prefill_chunk=16,
                                      prefill_buckets=(16,),
                                      batch_buckets=(1, 2),
                                      page_buckets=(8,),
                                      long_prefill_threshold=16),
                    mesh=mesh)
    assert eng.long_prefill_fn is not None


def test_scatter_prefill_kv_roundtrip():
    """K/V from long prefill lands in the paged pool where the paged
    decode path expects it."""
    cfg = ModelConfig.tiny()
    from dynamo_tpu.models.llama import KVCacheSpec, init_kv_cache
    ps = 8
    kv_k, kv_v = init_kv_cache(cfg, KVCacheSpec(num_pages=8, page_size=ps))
    B, T = 1, 16
    rng = np.random.RandomState(3)
    k_all = jnp.asarray(rng.randn(cfg.num_layers, B, T, cfg.num_kv_heads,
                                  cfg.head_dim_), jnp.float32)
    v_all = jnp.asarray(rng.randn(*k_all.shape), jnp.float32)
    pages = [2, 5]
    flat = jnp.asarray([[pages[t // ps] * ps + t % ps for t in range(T)]],
                       jnp.int32)
    kv_k, kv_v = scatter_prefill_kv(kv_k, kv_v, k_all, v_all, flat)
    got = np.asarray(kv_k[:, 2]).transpose(0, 2, 1, 3)  # [L, ps, KV, hd]
    np.testing.assert_allclose(got, np.asarray(k_all[:, 0, :ps]), rtol=1e-6)
    got5 = np.asarray(kv_v[:, 5]).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(got5, np.asarray(v_all[:, 0, ps:]), rtol=1e-6)
