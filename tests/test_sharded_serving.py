"""dynashard: mesh-sharded serving with data-parallel replicas (ISSUE 12).

Covers the tentpole's four planes:

- submesh planning: DevicePool assignment/release/re-partitioning and
  mesh-shape parsing (pure units, no jax);
- the sharded engine serving path: a mesh>1 JaxEngine serves
  token-identical to the unsharded control with the compile fence at
  zero — the committed-carry warmup variants must hold (in-process,
  riding conftest's forced-8-device CPU host);
- the REAL stack end-to-end in a SUBPROCESS (XLA's device-count flag is
  read once at backend init, so the suite's own backend can't be
  trusted): HTTP → Processor → KvRouter → 2 sharded replicas, asserting
  token identity vs the unsharded control, post_warmup_compiles == 0
  per replica, the KV-router overlap hit landing on the replica that
  committed the prefix, and per-replica `replica="rN"` gauge rows;
- the dynafleet `sharded` scenario: the planner scales sharded replicas,
  joins/drains re-partition the modeled device pool, the SLO report
  shows recovery.
"""

import asyncio
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from dynamo_tpu.parallel.serving import (DevicePool,  # noqa: E402
                                         NoFreeDevices, devices_per_replica,
                                         mesh_shape_str, parse_mesh_shape,
                                         plan_replicas)


# ------------------------------------------------------------- pure units


def test_parse_mesh_shape():
    assert parse_mesh_shape(None) == {}
    assert parse_mesh_shape("") == {}
    assert parse_mesh_shape("model=2") == {"model": 2}
    assert parse_mesh_shape("data=2, model=4") == {"data": 2, "model": 4}
    assert mesh_shape_str({"model": 2, "data": 2}) == "data=2,model=2"
    assert mesh_shape_str({}) == "single"
    assert mesh_shape_str({"model": 1}) == "single"
    assert devices_per_replica({"data": 2, "model": 4}) == 8
    with pytest.raises(ValueError):
        parse_mesh_shape("model2")
    with pytest.raises(ValueError):
        parse_mesh_shape("warp=2")
    with pytest.raises(ValueError):
        parse_mesh_shape("model=0")


def test_device_pool_assign_release_repartition():
    pool = DevicePool(list(range(8)))
    assert pool.acquire("r0", 2) == [0, 1]
    assert pool.acquire("r1", 2) == [2, 3]
    assert pool.free == [4, 5, 6, 7]
    # drain r0 → its devices return; the next join re-partitions onto
    # the LOWEST free indices (the freed submesh first)
    assert pool.release("r0") == [0, 1]
    assert pool.acquire("r2", 4) == [0, 1, 4, 5]
    assert pool.assignment() == {"r1": [2, 3], "r2": [0, 1, 4, 5]}
    # exhaustion is a typed error, never a silent unsharded fallback
    with pytest.raises(NoFreeDevices):
        pool.acquire("r3", 4)
    # double-acquire under one name is a bug, not a replacement
    with pytest.raises(ValueError):
        pool.acquire("r1", 1)


def test_plan_replicas():
    specs = plan_replicas({"model": 2}, 3, list(range(8)))
    assert [s.name for s in specs] == ["r0", "r1", "r2"]
    assert [s.devices for s in specs] == [[0, 1], [2, 3], [4, 5]]
    assert specs[0].mesh_shape == "model=2"
    with pytest.raises(NoFreeDevices):
        plan_replicas({"model": 4}, 3, list(range(8)))


# ------------------------------------------ per-replica metric identity


def test_aggregator_replica_labels():
    """N replicas in one process must render DISTINCT per-worker gauge
    rows keyed by the stable `replica` label (the ISSUE 12 metric-
    identity satellite), plus the submesh-size gauge."""
    from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics
    from dynamo_tpu.metrics.component import MetricsAggregator

    agg = MetricsAggregator.__new__(MetricsAggregator)
    agg.namespace = "shardtest"
    agg.worker_metrics = {
        0x10: ForwardPassMetrics(worker_label="r0", mesh_shape="model=2",
                                 mesh_devices=2, request_active_slots=1),
        0x11: ForwardPassMetrics(worker_label="r1", mesh_shape="model=2",
                                 mesh_devices=2, request_active_slots=2),
        0x12: ForwardPassMetrics(),  # unlabeled legacy worker
    }
    agg.hit_rate_isl_blocks = agg.hit_rate_overlap_blocks = 0
    agg.hit_rate_events = 0
    agg.scrape_failures_total = agg.consecutive_scrape_failures = 0
    agg._client = None
    text = agg.render_prometheus()
    assert ('dyn_worker_request_active_slots{namespace="shardtest",'
            'worker="10",replica="r0"} 1') in text
    assert ('dyn_worker_request_active_slots{namespace="shardtest",'
            'worker="11",replica="r1"} 2') in text
    # unlabeled workers keep the legacy label set (no empty replica="")
    assert ('dyn_worker_request_active_slots{namespace="shardtest",'
            'worker="12"} 0') in text
    assert ('dyn_engine_mesh_devices{namespace="shardtest",worker="10",'
            'replica="r0"} 2') in text
    # the labeled families carry the replica label too
    assert 'worker="10",replica="r0",quantile="p99"' in text


# ------------------------------- sharded engine serving path, in-process


def _tiny_ecfg(**over):
    from dynamo_tpu.engine.jax_engine import EngineConfig

    base = dict(page_size=4, num_pages=64, max_batch=4, prefill_chunk=32,
                prefill_buckets=(32,), batch_buckets=(4,),
                page_buckets=(16,))
    base.update(over)
    return EngineConfig(**base)


async def _collect(engine, prompt, n=8, rid=None):
    from dynamo_tpu.llm.protocols.common import (PreprocessedRequest,
                                                 SamplingOptions,
                                                 StopConditions)
    from dynamo_tpu.runtime.engine import Context

    req = PreprocessedRequest(
        token_ids=list(prompt), sampling=SamplingOptions(),
        stop=StopConditions(max_tokens=n, ignore_eos=True),
        eos_token_ids=[])
    toks = []
    ctx = Context(rid) if rid else Context()
    async for out in engine.generate(req, ctx):
        toks.extend(out.token_ids)
        if out.finish_reason:
            break
    return toks


def test_sharded_engine_token_identity_and_fence(run_async):
    """A model=2 submesh engine (2 of the conftest-forced 8 CPU devices)
    serves mixed concurrent traffic token-identical to the unsharded
    control with post_warmup_compiles == 0 — the committed-carry warmup
    variants (the sharding-specific compile-fence fix) under load."""
    import jax
    import numpy as np

    from dynamo_tpu.engine.jax_engine import JaxEngine
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.parallel.mesh import MeshSpec

    if len(jax.devices()) < 4:
        pytest.skip("needs the forced multi-device CPU host")
    cfg = ModelConfig.tiny()
    rng = np.random.RandomState(7)
    prompts = [rng.randint(1, 400, int(n)).tolist()
               for n in rng.randint(8, 30, size=5)]

    async def serve(engine):
        outs = await asyncio.gather(
            *(_collect(engine, p, n=6) for p in prompts))
        # second wave: chained windows + prefix hits on a warm engine
        outs += await asyncio.gather(
            *(_collect(engine, p, n=6) for p in prompts[:2]))
        await engine.stop()
        return outs

    control = JaxEngine(cfg, _tiny_ecfg(), seed=3)
    control.warmup()
    want = run_async(serve(control))

    mesh = MeshSpec(model=2).build(jax.devices()[2:4])
    sharded = JaxEngine(cfg, _tiny_ecfg(), seed=3, mesh=mesh,
                        worker_label="r0")
    sharded.warmup()
    got = run_async(serve(sharded))
    assert got == want
    assert sharded.fence.post_warmup_compiles == 0, \
        "compile fence broke under sharding"
    st = sharded.stats()
    assert st["worker_label"] == "r0"
    assert st["mesh_shape"] == "model=2"
    assert st["mesh_devices"] == 2


def test_replica_identity_in_cost_block(run_async):
    """The PR 10 per-request cost block names the replica/submesh that
    served the request (the /v1/traces/{rid} surface)."""
    import jax

    from dynamo_tpu.engine.jax_engine import JaxEngine
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.parallel.mesh import MeshSpec
    from dynamo_tpu.runtime import profiling

    if len(jax.devices()) < 2:
        pytest.skip("needs the forced multi-device CPU host")
    mesh = MeshSpec(model=2).build(jax.devices()[:2])
    engine = JaxEngine(ModelConfig.tiny(), _tiny_ecfg(), seed=0,
                       mesh=mesh, worker_label="r7")
    engine.warmup()

    async def main():
        await _collect(engine, list(range(1, 13)), n=4, rid="shard-rid-1")
        await engine.stop()

    run_async(main())
    cost = profiling.request_attribution("shard-rid-1")
    assert cost is not None
    assert cost["replica"] == "r7"
    assert cost["mesh_shape"] == "model=2"


def test_backend_harvests_remote_cost_after_length_cap(run_async):
    """When the Backend's own token cap fires before the engine's finish
    chunk, the cost block riding that chunk (replica, prefix split —
    everything /v1/traces/{rid} and router calibration need in a
    MULTI-PROCESS deployment) must still be drained and registered.
    Found live by the dynashard cross-process verify: the Backend
    returned at the cap and the remote cost never landed."""
    from dynamo_tpu.llm.backend import Backend
    from dynamo_tpu.llm.protocols.common import (EngineOutput,
                                                 PreprocessedRequest,
                                                 StopConditions)
    from dynamo_tpu.llm.tokenizer import ByteTokenizer
    from dynamo_tpu.runtime import profiling
    from dynamo_tpu.runtime.engine import Context

    cost_block = {"replica": "r1", "mesh_shape": "model=2",
                  "device_hit_blocks": 3, "prompt_blocks": 5}

    class RemoteLikeEngine:
        """Token chunks first, the cost-bearing finish in a SEPARATE
        later chunk — the remote worker wire shape."""

        async def generate(self, request, context):
            tok = ByteTokenizer()
            yield EngineOutput(token_ids=tok.encode("abcd", False))
            await asyncio.sleep(0.01)
            yield EngineOutput(token_ids=[], finish_reason="length",
                               cost=dict(cost_block)).to_dict()

    async def main():
        backend = Backend(RemoteLikeEngine(), ByteTokenizer())
        req = PreprocessedRequest(token_ids=[1],
                                  stop=StopConditions(max_tokens=4,
                                                      ignore_eos=True),
                                  eos_token_ids=[])
        ctx = Context("harvest-rid-1")
        outs = [o async for o in backend.generate(req, ctx)]
        return outs

    outs = run_async(main())
    assert outs[-1].finish_reason == "length"
    # the finish chunk the CLIENT sees carries the harvested cost...
    assert outs[-1].cost == cost_block
    # ...and the frontend-process attribution ring has it too
    assert profiling.request_attribution("harvest-rid-1") == cost_block


def test_backend_skips_harvest_on_stop_string(run_async):
    """A stop-STRING match is host-side only — the engine will not
    finish within the bound, so the Backend must not stall the final
    chunk waiting for a cost block that is not coming."""
    import time

    from dynamo_tpu.llm.backend import Backend
    from dynamo_tpu.llm.protocols.common import (EngineOutput,
                                                 PreprocessedRequest,
                                                 StopConditions)
    from dynamo_tpu.llm.tokenizer import ByteTokenizer
    from dynamo_tpu.runtime.engine import Context

    class NeverFinishingEngine:
        async def generate(self, request, context):
            tok = ByteTokenizer()
            yield EngineOutput(token_ids=tok.encode("abcSTOP", False))
            while not context.stopped:
                await asyncio.sleep(0.05)

    async def main():
        backend = Backend(NeverFinishingEngine(), ByteTokenizer())
        req = PreprocessedRequest(token_ids=[1],
                                  stop=StopConditions(stop=["STOP"],
                                                      ignore_eos=True),
                                  eos_token_ids=[])
        t0 = time.monotonic()
        outs = [o async for o in backend.generate(req, Context())]
        return outs, time.monotonic() - t0

    outs, dt = run_async(main())
    assert outs[-1].finish_reason == "stop"
    assert outs[-1].cost is None
    assert dt < Backend.COST_HARVEST_BOUND_S, \
        f"stop-string finish stalled {dt:.3f}s waiting for a cost block"


# ------------------------------------------------- fleet sharded scenario


def test_sharded_fleet_scenario(run_async):
    """The planner scales SHARDED replicas: the burst forces a scale-up
    (fresh submeshes partitioned), the post-burst drain releases devices,
    the late join re-partitions onto them — with the SLO met and
    recovery measured (ISSUE 12 tentpole part c)."""
    from dynamo_tpu.fleet.harness import run_scenario
    from dynamo_tpu.fleet.scenarios import get_scenario

    report = run_async(run_scenario(get_scenario("sharded"), seed=0))
    assert report["slo"]["met"], report["phases"]
    assert report["slo"]["time_to_recover_s"] is not None
    ups = [a for a in report["actuations"] if a["action"] == "scale-up"]
    assert ups, "planner never scaled the sharded pool up"
    assert report["workers"]["peak_live"] > 2

    sh = report["sharding"]
    assert sh["devices_per_replica"] == 2
    assert sh["max_devices_in_use"] <= sh["device_pool_size"]
    # replay the timeline: no device may be assigned to two live
    # replicas at once, and every assignment is exactly 2 devices
    live = {}
    reused_released = False
    released_pool = set()
    for ev in sh["timeline"]:
        if ev["event"] == "assign":
            assert len(ev["devices"]) == 2
            for d in ev["devices"]:
                owners = [w for w, devs in live.items() if d in devs]
                assert not owners, \
                    f"device {d} double-assigned: {owners} + {ev}"
            if released_pool & set(ev["devices"]):
                reused_released = True
            live[ev["worker"]] = set(ev["devices"])
        elif ev["event"] == "release":
            released_pool |= set(ev["devices"])
            live.pop(ev["worker"], None)
    releases = [e for e in sh["timeline"] if e["event"] == "release"]
    assert releases, "scale-down never released a submesh"
    assert reused_released, \
        "join never re-partitioned onto released devices"
    # per-replica identity rode the stats plane into the fleet report
    assert report["engine_gauges"]["workers_scraped"] >= 2


# ------------------------------------- the REAL stack e2e (subprocess)

E2E_WORKER = r'''
import asyncio, json, sys

import jax

assert len(jax.devices()) == 8, jax.devices()

import aiohttp
import numpy as np

from dynamo_tpu.engine.jax_engine import EngineConfig, JaxEngine
from dynamo_tpu.llm.http.service import HttpService
from dynamo_tpu.llm.kv_router.router import KvRouter
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.processor import Processor
from dynamo_tpu.llm.worker import serve_token_model
from dynamo_tpu.metrics.component import MetricsAggregator
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.parallel.serving import ShardedReplicaSet
from dynamo_tpu.runtime.runtime import DistributedRuntime

CFG = ModelConfig.tiny()
PS = 4


def ecfg():
    # max_batch 8 > the 6-wide concurrent wave: the router's optimistic
    # slot accounting (reset only at scrapes) must never see the single
    # control worker as saturated. 160 pages hold the whole wave plus
    # the warm prefix WITHOUT evictions (the overlap assertion needs the
    # warm request's committed blocks still resident), and the 32-page
    # bucket gives a 128-token grid capacity so the 65-token
    # prefix-extending request is admissible.
    return EngineConfig(page_size=PS, num_pages=160, max_batch=8,
                        prefill_chunk=32, prefill_buckets=(32,),
                        batch_buckets=(4,), page_buckets=(32,))


WORDS = ("alpha bravo charlie delta echo foxtrot golf hotel india "
         "juliet kilo lima mike oscar papa romeo").split()


def words(rng, n):
    out, ln = [], 0
    while ln < n:
        w = WORDS[rng.randint(0, len(WORDS) - 1)]
        out.append(w)
        ln += len(w) + 1
    return " ".join(out)[:n]


async def drive(http, port, reqs, osl=8):
    texts = {}

    async def one(rid, prompt):
        async with http.post(
                f"http://127.0.0.1:{port}/v1/completions",
                json={"model": "m", "prompt": prompt, "stream": True,
                      "max_tokens": osl, "temperature": 0.0},
                headers={"X-Request-Id": rid}) as resp:
            assert resp.status == 200, (rid, resp.status)
            pieces = []
            async for raw in resp.content:
                line = raw.strip()
                if line == b"data: [DONE]":
                    break
                if not line.startswith(b"data: "):
                    continue
                chunk = json.loads(line[len(b"data: "):])
                for c in chunk.get("choices", []):
                    pieces.append(c.get("text") or "")
            texts[rid] = "".join(pieces)

    await asyncio.gather(*(one(rid, p) for rid, p in reqs))
    return texts


async def trace_cost(http, port, rid):
    async with http.get(f"http://127.0.0.1:{port}/v1/traces/{rid}") as r:
        assert r.status == 200, (rid, r.status)
        return (await r.json()).get("cost") or {}


async def main():
    rng = np.random.RandomState(0)
    base = words(rng, 48)
    reqs = [(f"q-{i:02d}", words(rng, 40 + 4 * (i % 3)))
            for i in range(6)]
    out = {"devices": len(jax.devices())}

    # ---- leg A: unsharded control through the same stack
    drt = await DistributedRuntime.detached()
    mdc = ModelDeploymentCard(name="m", tokenizer_kind="byte",
                              kv_block_size=PS, model_type="completions")
    control = JaxEngine(CFG, ecfg(), seed=0)
    await asyncio.to_thread(control.warmup)
    handle, publisher = await serve_token_model(
        drt, mdc, control, namespace="ns", component="ctrl")
    kvr = KvRouter(drt, "ns", "ctrl", block_size=PS, seed=0)
    await kvr.start(run_loop=False)
    await kvr.scrape_once()
    client = await drt.namespace("ns").component("ctrl") \
        .endpoint("generate_tokens").client()
    service = HttpService()
    service.manager.add_completions_model(
        "m", Processor(mdc, client, kvr).completion)
    await service.start(host="127.0.0.1", port=0)
    async with aiohttp.ClientSession() as http:
        ctrl_texts = await drive(http, service.port, reqs)
    await service.stop()
    await kvr.stop()
    await client.close()
    await publisher.stop()
    await handle.stop()
    await control.stop()
    out["control_compiles"] = control.fence.post_warmup_compiles
    await drt.shutdown()

    # ---- leg B: 2 data-parallel model=2 replicas behind the KV router
    drt = await DistributedRuntime.detached()
    rs = ShardedReplicaSet(CFG, ecfg(), mesh_axes={"model": 2},
                           replicas=2, namespace="ns", component="shard",
                           mdc=mdc, dcp_address=drt.dcp.address, seed=0)
    await rs.start()
    kvr = KvRouter(drt, "ns", "shard", block_size=PS, seed=0)
    await kvr.start(run_loop=False)
    await kvr.scrape_once()
    client = await drt.namespace("ns").component("shard") \
        .endpoint("generate_tokens").client()
    service = HttpService()
    service.manager.add_completions_model(
        "m", Processor(mdc, client, kvr).completion)
    await service.start(host="127.0.0.1", port=0)
    agg = MetricsAggregator(drt, "ns", "shard")
    await agg.start(run_loop=False)

    async with aiohttp.ClientSession() as http:
        shard_texts = await drive(http, service.port, reqs)
        # overlap phase: warm one replica with `base`, settle the event
        # plane, then a base-prefixed request must land on THAT replica
        # and realize a device prefix hit
        warm_texts = await drive(http, service.port, [("warm-0", base)])
        await rs.flush_kv_events()
        await asyncio.sleep(0.05)
        await kvr.scrape_once()
        hit_texts = await drive(
            http, service.port,
            [("hit-0", base + " " + words(rng, 16))])
        warm_cost = await trace_cost(http, service.port, "warm-0")
        hit_cost = await trace_cost(http, service.port, "hit-0")
    await agg.scrape_once()
    render = agg.render_prometheus()

    out["texts_identical"] = (shard_texts == ctrl_texts)
    out["overlap_nonempty"] = bool(warm_texts.get("warm-0")
                                   and hit_texts.get("hit-0"))
    out["n_texts"] = len(shard_texts)
    out["nonempty"] = all(len(t) > 0 for t in shard_texts.values())
    out["per_replica_compiles"] = rs.post_warmup_compiles()
    out["per_replica_served"] = {
        r.name: r.engine.prompt_tokens_total for r in rs.replicas}
    out["mesh_shape"] = rs.mesh_shape
    out["assignment"] = rs.assignment()
    out["warm_replica"] = warm_cost.get("replica")
    out["hit_replica"] = hit_cost.get("replica")
    out["hit_device_hit_blocks"] = hit_cost.get("device_hit_blocks")
    out["hit_router_overlap_blocks"] = hit_cost.get(
        "router_overlap_blocks")
    out["hit_mesh_shape"] = hit_cost.get("mesh_shape")
    out["render_has_r0"] = ',replica="r0"}' in render \
        or ',replica="r0",' in render
    out["render_has_r1"] = ',replica="r1"}' in render \
        or ',replica="r1",' in render
    out["render_mesh_rows"] = render.count("dyn_engine_mesh_devices{")

    await service.stop()
    await agg.stop()
    await kvr.stop()
    await client.close()
    await rs.stop()
    await drt.shutdown()
    print("RESULT " + json.dumps(out))


asyncio.run(main())
'''


@pytest.mark.slow  # heavyweight e2e: tier-1 wall budget (cheaper siblings stay in the gate)
def test_sharded_serving_e2e_subprocess(device_subprocess):
    """The acceptance scenario, subprocess-isolated on a forced-8-device
    CPU host: concurrent HTTP requests through processor + KV router to
    2 mesh-sharded replicas are token-identical to the unsharded
    control, every replica's compile fence reads zero, the overlap hit
    lands on the replica that committed the prefix, and the aggregator
    renders per-replica gauge rows."""
    proc = device_subprocess(E2E_WORKER, devices=8, timeout=600)
    assert proc.returncode == 0, f"e2e worker failed:\n{proc.stdout[-6000:]}"
    line = [ln for ln in proc.stdout.splitlines()
            if ln.startswith("RESULT ")][-1]
    out = json.loads(line[len("RESULT "):])

    assert out["devices"] == 8
    assert out["n_texts"] == 6 and out["nonempty"]
    assert out["texts_identical"], \
        "sharded replicas are not token-identical to the control"
    assert out["control_compiles"] == 0
    assert out["per_replica_compiles"] == {"r0": 0, "r1": 0}, \
        f"compile fence broke under sharding: {out['per_replica_compiles']}"
    # both replicas actually served traffic (router load spreading)
    assert all(v > 0 for v in out["per_replica_served"].values()), \
        out["per_replica_served"]
    assert out["mesh_shape"] == "model=2"
    assert out["assignment"] == {"r0": [0, 1], "r1": [2, 3]}
    # overlap routing: the prefix-extending request landed on the SAME
    # replica that committed the prefix, predicted AND realized
    assert out["overlap_nonempty"], "overlap-phase request error-finished"
    assert out["warm_replica"] in ("r0", "r1")
    assert out["hit_replica"] == out["warm_replica"], \
        (out["warm_replica"], out["hit_replica"])
    assert out["hit_router_overlap_blocks"] > 0
    assert out["hit_device_hit_blocks"] > 0
    assert out["hit_mesh_shape"] == "model=2"
    # per-replica metric identity on the aggregator exposition
    assert out["render_has_r0"] and out["render_has_r1"]
    assert out["render_mesh_rows"] >= 2
