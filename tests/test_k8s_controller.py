"""Reconcile controller vs a fake cluster (reference
deploy/dynamo/operator internal/controller/dynamodeployment_controller.go
+ its envtest suite): CR converges into children, drift heals, scale
changes propagate, orphans are deleted, status reflects readiness, and
foreign objects are never touched."""

import copy
import os

import yaml

from dynamo_tpu.k8s.controller import MANAGED_BY, Reconciler


class FakeKube:
    """In-memory KubeClient: (kind, ns, name) -> object."""

    def __init__(self):
        self.store = {}
        self.deleted = []

    def _sel_match(self, obj, sel):
        if not sel:
            return True
        labels = obj.get("metadata", {}).get("labels", {})
        for part in sel.split(","):
            k, v = part.split("=", 1)
            if labels.get(k) != v:
                return False
        return True

    def list(self, kind, namespace, label_selector=None):
        return [copy.deepcopy(o) for (k, ns, _), o in self.store.items()
                if k == kind and ns == namespace
                and self._sel_match(o, label_selector)]

    def get(self, kind, namespace, name):
        o = self.store.get((kind, namespace, name))
        return copy.deepcopy(o) if o else None

    def create(self, kind, namespace, obj):
        obj = copy.deepcopy(obj)
        obj.setdefault("metadata", {})["resourceVersion"] = "1"
        self.store[(kind, namespace, obj["metadata"]["name"])] = obj
        return obj

    def replace(self, kind, namespace, name, obj):
        cur = self.store[(kind, namespace, name)]
        obj = copy.deepcopy(obj)
        obj["metadata"]["resourceVersion"] = str(
            int(cur["metadata"].get("resourceVersion", "0")) + 1)
        self.store[(kind, namespace, name)] = obj
        return obj

    def delete(self, kind, namespace, name):
        self.store.pop((kind, namespace, name), None)
        self.deleted.append((kind, namespace, name))

    def update_status(self, kind, namespace, name, status):
        if (kind, namespace, name) in self.store:
            self.store[(kind, namespace, name)]["status"] = status


def example_cr():
    path = os.path.join(os.path.dirname(__file__), "..", "deploy",
                        "kubernetes", "example-deployment.yaml")
    with open(path) as f:
        cr = yaml.safe_load(f)
    cr["metadata"]["uid"] = "uid-123"
    return cr


def test_cr_converges_end_to_end():
    kube = FakeKube()
    ns = "serving"
    kube.create("DynamoDeployment", ns, example_cr())
    rec = Reconciler(kube)
    rec.reconcile_all(ns)

    deps = kube.list("Deployment", ns)
    names = sorted(d["metadata"]["name"] for d in deps)
    assert "llama-disagg-dcp" in names
    assert "llama-disagg-tpuworker" in names
    assert len(names) == 6  # dcp + 5 services
    # children carry ownerReferences + managed-by labels
    for d in deps:
        assert d["metadata"]["ownerReferences"][0]["name"] == "llama-disagg"
        assert d["metadata"]["ownerReferences"][0]["uid"] == "uid-123"
        assert (d["metadata"]["labels"]["app.kubernetes.io/managed-by"]
                == MANAGED_BY)
    assert kube.get("ConfigMap", ns, "llama-disagg-service-config")
    assert kube.get("Service", ns, "llama-disagg-routedfrontend")

    # no deployment reports ready yet → Progressing
    cr = kube.get("DynamoDeployment", ns, "llama-disagg")
    assert cr["status"]["phase"] == "Progressing"

    # mark every child ready → Ready with full count
    for (k, n, name), obj in list(kube.store.items()):
        if k == "Deployment":
            obj["status"] = {
                "readyReplicas": obj["spec"].get("replicas", 1)}
    rec.reconcile_all(ns)
    cr = kube.get("DynamoDeployment", ns, "llama-disagg")
    assert cr["status"] == {"phase": "Ready", "readyServices": 6}


def test_scale_change_and_orphan_deletion():
    kube = FakeKube()
    ns = "serving"
    kube.create("DynamoDeployment", ns, example_cr())
    rec = Reconciler(kube)
    rec.reconcile_all(ns)
    assert kube.get("Deployment", ns,
                    "llama-disagg-tpuworker")["spec"]["replicas"] == 4

    cr = kube.get("DynamoDeployment", ns, "llama-disagg")
    cr["spec"]["services"]["TpuWorker"]["replicas"] = 8
    del cr["spec"]["services"]["PrefillWorker"]
    kube.store[("DynamoDeployment", ns, "llama-disagg")] = cr
    rec.reconcile_all(ns)

    assert kube.get("Deployment", ns,
                    "llama-disagg-tpuworker")["spec"]["replicas"] == 8
    assert kube.get("Deployment", ns, "llama-disagg-prefillworker") is None
    assert ("Deployment", ns, "llama-disagg-prefillworker") in kube.deleted


def test_drift_heals_and_foreign_objects_untouched():
    kube = FakeKube()
    ns = "serving"
    kube.create("DynamoDeployment", ns, example_cr())
    # a foreign deployment that must never be touched
    kube.create("Deployment", ns, {
        "kind": "Deployment",
        "metadata": {"name": "unrelated", "labels": {"app": "x"}},
        "spec": {"replicas": 3}})
    rec = Reconciler(kube)
    rec.reconcile_all(ns)

    # manual drift WITHOUT touching the annotation (kubectl scale):
    # field-level diff must heal it
    d = kube.store[("Deployment", ns, "llama-disagg-router")]
    d["spec"]["replicas"] = 99
    rec.reconcile_all(ns)
    assert kube.get("Deployment", ns,
                    "llama-disagg-router")["spec"]["replicas"] == 1

    # annotation tamper also heals
    d = kube.store[("Deployment", ns, "llama-disagg-router")]
    d["metadata"]["annotations"]["dynamo-tpu.dev/spec-hash"] = "tampered"
    rec.reconcile_all(ns)
    assert (kube.get("Deployment", ns, "llama-disagg-router")
            ["metadata"]["annotations"]["dynamo-tpu.dev/spec-hash"]
            != "tampered")

    # server-added defaulted fields are NOT drift (no churn)
    d = kube.store[("Deployment", ns, "llama-disagg-router")]
    rv_before = d["metadata"]["resourceVersion"]
    d["spec"]["strategy"] = {"type": "RollingUpdate"}  # server default
    d["status"] = {"observedGeneration": 1}
    # ...including defaults added INSIDE list elements, where a real
    # apiserver does most of its defaulting (containers[], ports[])
    for c in d["spec"]["template"]["spec"]["containers"]:
        c["imagePullPolicy"] = "IfNotPresent"
        c["terminationMessagePath"] = "/dev/termination-log"
        for p in c.get("ports", []):
            p["protocol"] = "TCP"
    rec.reconcile_all(ns)
    assert (kube.get("Deployment", ns, "llama-disagg-router")
            ["metadata"]["resourceVersion"] == rv_before)
    # but a real in-list edit (image override) IS drift and heals
    d = kube.store[("Deployment", ns, "llama-disagg-router")]
    orig_image = d["spec"]["template"]["spec"]["containers"][0]["image"]
    d["spec"]["template"]["spec"]["containers"][0]["image"] = "evil:latest"
    rec.reconcile_all(ns)
    assert (kube.get("Deployment", ns, "llama-disagg-router")
            ["spec"]["template"]["spec"]["containers"][0]["image"]
            == orig_image)

    assert kube.get("Deployment", ns, "unrelated")["spec"]["replicas"] == 3
    assert ("Deployment", ns, "unrelated") not in kube.deleted


def test_webhook_injected_sidecar_tolerated():
    """A mutating webhook PREPENDING a container (vault-agent style) is a
    server addition, not drift — named-element matching keeps the
    positional comparison from misaligning and replace-fighting it."""
    kube = FakeKube()
    ns = "serving"
    kube.create("DynamoDeployment", ns, example_cr())
    rec = Reconciler(kube)
    rec.reconcile_all(ns)

    d = kube.store[("Deployment", ns, "llama-disagg-router")]
    rv_before = d["metadata"]["resourceVersion"]
    d["spec"]["template"]["spec"]["containers"].insert(0, {
        "name": "istio-proxy", "image": "istio/proxyv2:1.20"})
    rec.reconcile_all(ns)
    after = kube.get("Deployment", ns, "llama-disagg-router")
    assert after["metadata"]["resourceVersion"] == rv_before
    assert after["spec"]["template"]["spec"]["containers"][0]["name"] \
        == "istio-proxy"

    # but appending to an OWNED scalar list (a rendered command flag) is
    # an edit to heal — the extra-element tolerance is only for
    # named-element lists
    d = kube.store[("Deployment", ns, "llama-disagg-router")]
    dyn_c = [c for c in d["spec"]["template"]["spec"]["containers"]
             if c["name"] != "istio-proxy"][0]
    n_cmd = len(dyn_c["command"])
    dyn_c["command"].append("--insecure")
    rec.reconcile_all(ns)
    healed = kube.get("Deployment", ns, "llama-disagg-router")
    healed_c = [c for c in healed["spec"]["template"]["spec"]["containers"]
                if c["name"] != "istio-proxy"][0]
    assert len(healed_c["command"]) == n_cmd


def test_service_replace_preserves_cluster_ip():
    """A real apiserver 422-rejects a Service PUT that drops the
    server-allocated spec.clusterIP; the controller must carry the
    immutable fields over when healing drift."""
    kube = FakeKube()
    ns = "serving"
    kube.create("DynamoDeployment", ns, example_cr())
    rec = Reconciler(kube)
    rec.reconcile_all(ns)

    s = kube.store[("Service", ns, "llama-disagg-routedfrontend")]
    s["spec"]["clusterIP"] = "10.0.0.42"           # server-allocated
    s["spec"]["clusterIPs"] = ["10.0.0.42"]
    s["metadata"]["annotations"]["dynamo-tpu.dev/spec-hash"] = "tampered"
    rec.reconcile_all(ns)
    healed = kube.get("Service", ns, "llama-disagg-routedfrontend")
    assert (healed["metadata"]["annotations"]["dynamo-tpu.dev/spec-hash"]
            != "tampered")
    assert healed["spec"]["clusterIP"] == "10.0.0.42"
    assert healed["spec"]["clusterIPs"] == ["10.0.0.42"]


def test_cr_error_does_not_wedge_other_crs():
    kube = FakeKube()
    ns = "serving"
    bad = {"apiVersion": "dynamo-tpu.dev/v1alpha1",
           "kind": "DynamoDeployment",
           "metadata": {"name": "broken", "namespace": ns},
           "spec": {}}  # missing required graph → render raises
    kube.create("DynamoDeployment", ns, bad)
    kube.create("DynamoDeployment", ns, example_cr())
    Reconciler(kube).reconcile_all(ns)
    assert kube.get("Deployment", ns, "llama-disagg-dcp") is not None


def test_helm_chart_structure():
    """Platform chart sanity (reference deploy/Kubernetes/
    test_helm_charts.py analog): chart metadata + CRD parse, templates
    reference only defined values, RBAC covers every kind the controller
    touches."""
    base = os.path.join(os.path.dirname(__file__), "..", "deploy", "helm",
                        "dynamo-platform")
    with open(os.path.join(base, "Chart.yaml")) as f:
        chart = yaml.safe_load(f)
    assert chart["name"] == "dynamo-tpu-platform"
    with open(os.path.join(base, "values.yaml")) as f:
        values = yaml.safe_load(f)
    assert "operator" in values and "image" in values["operator"]
    with open(os.path.join(base, "crds",
                           "dynamodeployment-crd.yaml")) as f:
        crd = yaml.safe_load(f)
    assert crd["kind"] == "CustomResourceDefinition"
    assert crd["spec"]["names"]["kind"] == "DynamoDeployment"
    # every service field render() reads must survive structural-schema
    # pruning on a real apiserver
    svc_schema = (crd["spec"]["versions"][0]["schema"]["openAPIV3Schema"]
                  ["properties"]["spec"]["properties"]["services"]
                  ["additionalProperties"]["properties"])
    for field in ("replicas", "tpuAccelerator", "tpuTopology", "tpuChips",
                  "frontend", "port", "serviceType", "resources"):
        assert field in svc_schema, f"CRD schema missing {field}"
    # the chart CRD and the kubectl-apply CRD are the same file content
    # (two install paths, one schema — drift here means two clusters
    # enforce different APIs)
    with open(os.path.join(os.path.dirname(__file__), "..", "deploy",
                           "kubernetes", "crd.yaml")) as f:
        assert yaml.safe_load(f) == crd
    # templates: every .Values.x.y reference resolves in values.yaml
    import re
    for tpl in ("operator.yaml", "rbac.yaml"):
        with open(os.path.join(base, "templates", tpl)) as f:
            text = f.read()
        for ref in re.findall(r"\.Values\.([A-Za-z0-9_.]+)", text):
            node = values
            for part in ref.split("."):
                assert isinstance(node, dict) and part in node, \
                    f"{tpl}: .Values.{ref} undefined in values.yaml"
                node = node[part]
    with open(os.path.join(base, "templates", "rbac.yaml")) as f:
        rbac_text = f.read()
    for resource in ("dynamodeployments", "deployments", "services",
                     "configmaps", "dynamodeployments/status"):
        assert resource in rbac_text


def test_mixed_and_unnamed_port_lists_tolerate_server_additions():
    """ADVICE r3: Service port lists where `name` is optional must not
    re-read a webhook/server-appended element as drift on every tick
    (that hot-loops replaces against the apiserver)."""
    from dynamo_tpu.k8s.controller import _owned_fields_drifted

    # single unnamed wanted port; server appended a named metrics port
    want = {"ports": [{"port": 80, "targetPort": 8080}]}
    have = {"ports": [{"port": 80, "targetPort": 8080, "protocol": "TCP"},
                      {"name": "metrics", "port": 9090}]}
    assert not _owned_fields_drifted(want, have)

    # mixed list: named element matched by name regardless of order
    want = {"ports": [{"name": "http", "port": 80},
                      {"port": 7070}]}
    have = {"ports": [{"port": 7070, "protocol": "TCP"},
                      {"name": "http", "port": 80},
                      {"name": "injected", "port": 1}]}
    assert not _owned_fields_drifted(want, have)

    # a real edit to the unnamed element IS drift
    have_edited = {"ports": [{"port": 7171, "protocol": "TCP"},
                             {"name": "http", "port": 80}]}
    assert _owned_fields_drifted(want, have_edited)

    # a missing named element IS drift
    assert _owned_fields_drifted(
        want, {"ports": [{"port": 7070}]})

    # scalar lists stay strict: an appended arg is drift to heal
    assert _owned_fields_drifted({"args": ["-a"]}, {"args": ["-a", "-b"]})


def test_ingress_converges_and_drift_heals():
    """A CR with spec.ingress converges an Ingress child (ownerRefs,
    managed-by labels), heals class drift, and deletes it when the spec
    drops ingress — the reference operator's networking plane
    (pkg/dynamo/system/ingress.go) under the same convergence rules as
    Deployments."""
    kube = FakeKube()
    cr = example_cr()
    cr["spec"]["ingress"] = {"className": "nginx", "host": "llama.io"}
    kube.create("DynamoDeployment", "serving", cr)
    rec = Reconciler(kube)
    rec.reconcile_all("serving")

    ing = kube.get("Ingress", "serving", "llama-disagg-routedfrontend")
    assert ing is not None
    assert ing["metadata"]["labels"][
        "app.kubernetes.io/managed-by"] == MANAGED_BY
    assert ing["metadata"]["ownerReferences"][0]["name"] == "llama-disagg"
    assert ing["spec"]["ingressClassName"] == "nginx"

    # kubectl-edit drift on an owned field heals
    broken = kube.get("Ingress", "serving", "llama-disagg-routedfrontend")
    broken["spec"]["ingressClassName"] = "other"
    kube.store[("Ingress", "serving",
                "llama-disagg-routedfrontend")] = broken
    rec.reconcile_all("serving")
    assert kube.get("Ingress", "serving", "llama-disagg-routedfrontend")[
        "spec"]["ingressClassName"] == "nginx"

    # dropping ingress from the spec orphan-deletes the child
    cr2 = kube.get("DynamoDeployment", "serving", "llama-disagg")
    del cr2["spec"]["ingress"]
    kube.store[("DynamoDeployment", "serving", "llama-disagg")] = cr2
    rec.reconcile_all("serving")
    assert kube.get("Ingress", "serving",
                    "llama-disagg-routedfrontend") is None


def test_istio_route_absent_cluster_tolerated():
    """On a cluster without the Istio CRDs the VirtualService list 404s;
    reconcile must treat that as 'none exist', not fail — and still
    converge everything else."""

    class NoIstioKube(FakeKube):
        def list(self, kind, namespace, label_selector=None):
            if kind == "VirtualService":
                raise RuntimeError("404 the server could not find the "
                                   "requested resource")
            return super().list(kind, namespace, label_selector)

    kube = NoIstioKube()
    kube.create("DynamoDeployment", "serving", example_cr())
    Reconciler(kube).reconcile_all("serving")
    assert kube.get("Deployment", "serving", "llama-disagg-dcp")


def test_istio_route_non_404_error_raises():
    """Only NOT-FOUND demotes to 'no VirtualServices'; a 403/timeout on
    the optional kind must surface (otherwise a transient apiserver
    error is indistinguishable from 'Istio not installed')."""

    class ForbiddenKube(FakeKube):
        def list(self, kind, namespace, label_selector=None):
            if kind == "VirtualService":
                raise RuntimeError("403 forbidden")
            return super().list(kind, namespace, label_selector)

    kube = ForbiddenKube()
    kube.create("DynamoDeployment", "serving", example_cr())
    import pytest
    with pytest.raises(RuntimeError, match="403"):
        Reconciler(kube)._observe("serving", "llama-disagg",
                                  "DynamoDeployment")


def model_request_cr(**spec_over):
    spec = {"modelId": "org/model-8b", "storage": "40Gi"}
    spec.update(spec_over)
    return {
        "apiVersion": "dynamo-tpu.dev/v1alpha1",
        "kind": "DynamoModelRequest",
        "metadata": {"name": "llama8b", "namespace": "serving",
                     "uid": "uid-mr"},
        "spec": spec,
    }


def test_model_request_converges_pvc_and_job():
    """DynamoModelRequest → PVC + seeding Job with ownerRefs; status
    tracks the Job (Seeding → Ready) — the reference's DynamoNimRequest
    ModelsSeeding/ModelsExists conditions, TPU-shaped (checkpoint onto a
    claim instead of a model-baked image)."""
    kube = FakeKube()
    kube.create("DynamoModelRequest", "serving", model_request_cr())
    rec = Reconciler(kube)
    rec.reconcile_all("serving")

    pvc = kube.get("PersistentVolumeClaim", "serving", "llama8b-models")
    assert pvc is not None
    assert pvc["spec"]["resources"]["requests"]["storage"] == "40Gi"
    assert pvc["metadata"]["ownerReferences"][0]["kind"] == \
        "DynamoModelRequest"
    job = kube.get("Job", "serving", "llama8b-seed")
    assert job is not None
    cmd = job["spec"]["template"]["spec"]["containers"][0]["command"]
    assert cmd[:4] == ["python", "-m", "dynamo_tpu", "fetch-model"]
    assert "org/model-8b" in cmd
    cr = kube.get("DynamoModelRequest", "serving", "llama8b")
    assert cr["status"]["phase"] == "Seeding"
    assert cr["status"]["claim"] == "llama8b-models"

    # job completes → Ready
    job["status"] = {"succeeded": 1}
    kube.store[("Job", "serving", "llama8b-seed")] = job
    rec.reconcile_all("serving")
    assert kube.get("DynamoModelRequest", "serving",
                    "llama8b")["status"]["phase"] == "Ready"


def test_model_request_pvc_create_only_job_recreates():
    """PVC spec is immutable: drift is left alone. Job template is
    immutable: a changed render (new modelId) applies by delete +
    recreate."""
    kube = FakeKube()
    kube.create("DynamoModelRequest", "serving", model_request_cr())
    rec = Reconciler(kube)
    rec.reconcile_all("serving")

    # hand-shrink the PVC (drift) — reconcile must NOT touch it
    pvc = kube.get("PersistentVolumeClaim", "serving", "llama8b-models")
    pvc["spec"]["resources"]["requests"]["storage"] = "1Gi"
    kube.store[("PersistentVolumeClaim", "serving",
                "llama8b-models")] = pvc
    rec.reconcile_all("serving")
    assert kube.get("PersistentVolumeClaim", "serving", "llama8b-models")[
        "spec"]["resources"]["requests"]["storage"] == "1Gi"

    # change the model → the Job is deleted and recreated, not replaced
    cr = kube.get("DynamoModelRequest", "serving", "llama8b")
    cr["spec"]["modelId"] = "org/other-model"
    kube.store[("DynamoModelRequest", "serving", "llama8b")] = cr
    rec.reconcile_all("serving")
    assert ("Job", "serving", "llama8b-seed") in kube.deleted
    job = kube.get("Job", "serving", "llama8b-seed")
    assert "org/other-model" in \
        job["spec"]["template"]["spec"]["containers"][0]["command"]


def test_model_request_existing_claim_and_token():
    from dynamo_tpu.k8s.render import render_model_request

    objs = render_model_request(model_request_cr(
        existingClaim="shared-models", hfTokenSecret="hf-tok"))
    kinds = [o["kind"] for o in objs]
    assert "PersistentVolumeClaim" not in kinds  # reuse, don't create
    job = [o for o in objs if o["kind"] == "Job"][0]
    vol = job["spec"]["template"]["spec"]["volumes"][0]
    assert vol["persistentVolumeClaim"]["claimName"] == "shared-models"
    env = job["spec"]["template"]["spec"]["containers"][0]["env"]
    assert env[0]["valueFrom"]["secretKeyRef"]["name"] == "hf-tok"


def test_same_name_deployment_and_model_request_coexist():
    """A DynamoDeployment and a DynamoModelRequest named identically (the
    natural pairing) must never orphan-delete each other's children —
    observed state partitions by owning CR KIND, not just instance."""
    kube = FakeKube()
    kube.create("DynamoDeployment", "serving",
                {**example_cr(),
                 "metadata": {"name": "llama8b", "namespace": "serving",
                              "uid": "u1"}})
    kube.create("DynamoModelRequest", "serving", model_request_cr())
    rec = Reconciler(kube)
    rec.reconcile_all("serving")
    rec.reconcile_all("serving")  # second pass: would orphan-delete

    assert kube.get("PersistentVolumeClaim", "serving", "llama8b-models")
    assert kube.get("Job", "serving", "llama8b-seed")
    assert kube.get("Deployment", "serving", "llama8b-dcp")
    assert ("PersistentVolumeClaim", "serving",
            "llama8b-models") not in kube.deleted
    assert ("Deployment", "serving", "llama8b-dcp") not in kube.deleted


def test_model_request_failed_via_job_condition():
    """Under restartPolicy OnFailure the failed counter never increments
    — phase must come from the Job's Failed CONDITION."""
    kube = FakeKube()
    kube.create("DynamoModelRequest", "serving", model_request_cr())
    rec = Reconciler(kube)
    rec.reconcile_all("serving")
    job = kube.get("Job", "serving", "llama8b-seed")
    job["status"] = {"failed": 0, "conditions": [
        {"type": "Failed", "status": "True",
         "reason": "BackoffLimitExceeded"}]}
    kube.store[("Job", "serving", "llama8b-seed")] = job
    rec.reconcile_all("serving")
    assert kube.get("DynamoModelRequest", "serving",
                    "llama8b")["status"]["phase"] == "Failed"


def test_model_request_existing_claim_status():
    kube = FakeKube()
    kube.create("DynamoModelRequest", "serving",
                model_request_cr(existingClaim="shared-models"))
    Reconciler(kube).reconcile_all("serving")
    cr = kube.get("DynamoModelRequest", "serving", "llama8b")
    assert cr["status"]["claim"] == "shared-models"
    assert kube.get("PersistentVolumeClaim", "serving",
                    "llama8b-models") is None


def test_seed_job_without_token_has_no_env_key():
    """env: [] would be dropped by a real apiserver on read-back and
    re-read as drift → permanent Job recreate hot loop; the renderer
    must omit the key entirely."""
    from dynamo_tpu.k8s.render import render_model_request

    job = [o for o in render_model_request(model_request_cr())
           if o["kind"] == "Job"][0]
    assert "env" not in job["spec"]["template"]["spec"]["containers"][0]
