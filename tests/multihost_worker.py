"""Worker for tests/test_multihost.py: joins a 2-process SPMD group via
``initialize_multihost`` (the Ray-bootstrap replacement — reference
lib/llm/src/engines/vllm/ray.rs), builds the GLOBAL 2x2 data×model mesh
from both processes' CPU devices, runs one TP+DP-sharded forward, and
checks its addressable output shards against a process-local oracle.

Run as: python multihost_worker.py <coordinator> <num_procs> <pid>
(env must set JAX_PLATFORMS=cpu and a 2-device virtual CPU host).
"""

import sys

import jax

jax.config.update("jax_platforms", "cpu")  # before backend init (conftest
# trick: the ambient TPU plugin would otherwise grab the backend)

import numpy as np  # noqa: E402


def main(coordinator: str, num_processes: int, process_id: int) -> None:
    from dynamo_tpu.parallel.mesh import initialize_multihost, param_pspecs

    initialize_multihost(coordinator, num_processes, process_id)
    assert jax.process_count() == num_processes, jax.process_count()

    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import ModelConfig

    devs = jax.devices()
    assert len(devs) == 2 * num_processes, devs  # 2 virtual CPUs per proc
    mesh = Mesh(np.array(devs).reshape(num_processes, 2),
                ("data", "model"))

    cfg = ModelConfig.tiny(num_heads=4, num_kv_heads=2, head_dim=8,
                           hidden_size=32, vocab_size=128)
    # identical on every process (deterministic PRNG) — the multi-host
    # contract jax.distributed requires for jit'd programs
    params_host = jax.tree.map(
        np.asarray, llama.init_params(cfg, jax.random.PRNGKey(0),
                                      dtype=jnp.float32))
    specs = param_pspecs(cfg)

    def gput(spec, a):
        s = NamedSharding(mesh, spec)
        return jax.make_array_from_callback(a.shape, s, lambda idx: a[idx])

    gparams = {k: gput(specs.get(k, P(*([None] * v.ndim))), v)
               for k, v in params_host.items()}
    B, T = 2 * num_processes, 6
    tokens = (np.arange(B * T, dtype=np.int32).reshape(B, T) * 7) % 120
    gtokens = gput(P("data", None), tokens)

    fwd = jax.jit(lambda p, t: llama.reference_forward(p, cfg, t))
    logits = fwd(gparams, gtokens)
    jax.block_until_ready(logits)

    # oracle: same forward, process-local single device, full inputs
    ref = np.asarray(fwd(jax.device_put(params_host),
                         jax.device_put(tokens)))
    for shard in logits.addressable_shards:
        got = np.asarray(shard.data)
        want = ref[shard.index]
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)
    print(f"MULTIHOST-OK pid={process_id} procs={jax.process_count()} "
          f"global_devices={len(devs)} mesh={mesh.shape}")


if __name__ == "__main__":
    main(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]))
