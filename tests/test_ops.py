"""Pallas kernels vs their XLA reference paths (interpret mode on CPU —
SURVEY §4 TPU test plan: sharding/kernels CI-testable without hardware)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.models.llama import _paged_attention
from dynamo_tpu.ops.paged_attention import paged_attention_decode


def _random_pages(key, num_pages, ps, KV, hd, dtype=jnp.float32):
    k1, k2 = jax.random.split(key)
    shape = (num_pages, KV, ps, hd)  # kv-head-major pool layout
    return (jax.random.normal(k1, shape, dtype),
            jax.random.normal(k2, shape, dtype))


@pytest.mark.parametrize("group,hd,ps", [(4, 64, 8), (1, 32, 16)])
def test_decode_kernel_matches_gather(group, hd, ps):
    KV = 2
    H = KV * group
    B, P, num_pages = 5, 4, 32
    key = jax.random.PRNGKey(0)
    kq, kp, kt = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, H, hd), jnp.float32)
    k_pages, v_pages = _random_pages(kp, num_pages, ps, KV, hd)

    # distinct random page tables + varied lengths (incl. exact page fill)
    rng = np.random.RandomState(3)
    table = np.zeros((B, P), np.int32)
    lengths = np.array([1, ps, ps + 3, 2 * ps, P * ps], np.int32)
    for b in range(B):
        npages = -(-int(lengths[b]) // ps)
        table[b, :npages] = rng.choice(
            np.arange(1, num_pages), npages, replace=False)

    scale = hd ** -0.5
    got = paged_attention_decode(q, k_pages, v_pages, jnp.asarray(table),
                                 jnp.asarray(lengths), scale=scale,
                                 interpret=True)

    # XLA gather path: q positions are length-1 (the just-written token)
    positions = jnp.asarray(lengths - 1)[:, None]
    want = _paged_attention(q[:, None], k_pages, v_pages, jnp.asarray(table),
                            positions, scale)[:, 0]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_decode_kernel_padding_rows_zero():
    """length-0 rows (batch padding) must come out as zeros, not NaN."""
    B, H, KV, hd, ps, P = 3, 4, 2, 32, 8, 2
    q = jnp.ones((B, H, hd), jnp.float32)
    k_pages, v_pages = _random_pages(jax.random.PRNGKey(1), 8, ps, KV, hd)
    table = jnp.zeros((B, P), jnp.int32)
    lengths = jnp.asarray([0, 5, 0], jnp.int32)
    out = paged_attention_decode(q, k_pages, v_pages, table, lengths,
                                 interpret=True)
    out = np.asarray(out)
    assert np.isfinite(out).all()
    np.testing.assert_array_equal(out[0], 0.0)
    np.testing.assert_array_equal(out[2], 0.0)
    assert np.abs(out[1]).sum() > 0


def test_decode_kernel_bf16():
    B, H, KV, hd, ps, P = 2, 8, 4, 64, 8, 2
    q = jax.random.normal(jax.random.PRNGKey(2), (B, H, hd), jnp.bfloat16)
    k_pages, v_pages = _random_pages(jax.random.PRNGKey(3), 8, ps, KV, hd,
                                     jnp.bfloat16)
    table = jnp.asarray([[1, 2], [3, 0]], jnp.int32)
    lengths = jnp.asarray([11, 8], jnp.int32)
    got = paged_attention_decode(q, k_pages, v_pages, table, lengths,
                                 interpret=True)
    assert got.dtype == jnp.bfloat16
    positions = (lengths - 1)[:, None]
    want = _paged_attention(q[:, None], k_pages, v_pages, table, positions,
                            hd ** -0.5)[:, 0]
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=0.05, atol=0.05)


def test_pool_window_merge_matches_xla():
    """The fused-window pool attention (Pallas kernel w/ stats + online-
    softmax merge against the in-flight window buffer) must match the XLA
    concat path — including rows with an empty pool (start=0) and padding
    rows (start=-1). This is the only exercise the stats/merge path gets
    off-TPU (interpret mode)."""
    from dynamo_tpu.models.llama import (_pool_window_attention,
                                         _pool_window_attention_pallas)

    B, H, KV, hd, ps, P, L, K = 4, 8, 4, 64, 8, 3, 2, 4
    key = jax.random.PRNGKey(7)
    ks = jax.random.split(key, 5)
    k_pools = jax.random.normal(ks[0], (L, 16, KV, ps, hd), jnp.float32)
    v_pools = jax.random.normal(ks[1], (L, 16, KV, ps, hd), jnp.float32)
    q = jax.random.normal(ks[2], (B, 1, H, hd), jnp.float32)
    wk = jax.random.normal(ks[3], (B, K, KV, hd), jnp.float32)
    wv = jax.random.normal(ks[4], (B, K, KV, hd), jnp.float32)
    table = jnp.asarray([[1, 2, 3], [4, 5, 6], [7, 8, 9], [1, 0, 0]],
                        jnp.int32)
    # row 0: mid-pool; row 1: page-boundary; row 2: empty pool (start=0);
    # row 3: padding (start=-1)
    start = jnp.asarray([13, 16, 0, -1], jnp.int32)
    scale = hd ** -0.5
    for i in (0, K - 1):
        for l in range(L):
            got = _pool_window_attention_pallas(
                q, k_pools, v_pools, jnp.int32(l), table, start, wk, wv,
                i, scale, interpret=True)
            want = _pool_window_attention(
                q, k_pools[l], v_pools[l], table, start, wk, wv, i, scale)
            np.testing.assert_allclose(np.asarray(got)[:3],
                                       np.asarray(want)[:3],
                                       rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("group,hd,T", [(2, 16, 8), (4, 32, 16)])
def test_prefill_kernel_matches_gather(group, hd, T):
    """Flash prefill over pages == the XLA gather path: chunk starting
    mid-sequence (prefix already cached), per-row distinct positions,
    padding rows, trailing invalid pages."""
    import numpy as np

    from dynamo_tpu.models.llama import _paged_attention
    from dynamo_tpu.ops.paged_attention import paged_attention_prefill

    rng = np.random.RandomState(0)
    B, KV, ps, N, P = 3, 2, 4, 32, 6
    H = KV * group
    q = jnp.asarray(rng.randn(B, T, H, hd), jnp.float32)
    k_pages = jnp.asarray(rng.randn(N, KV, ps, hd), jnp.float32)
    v_pages = jnp.asarray(rng.randn(N, KV, ps, hd), jnp.float32)
    table = np.zeros((B, P), np.int32)
    table[0, :4] = [3, 7, 2, 9]          # 2 prefix pages + chunk pages
    table[1, :2] = [11, 4]
    # row 2: padding row (all positions -1)
    positions = np.full((B, T), -1, np.int32)
    positions[0] = np.arange(8, 8 + T)   # chunk starts at position 8
    positions[1] = np.arange(T)
    q_pos = jnp.asarray(positions)

    want = _paged_attention(q, k_pages, v_pages, jnp.asarray(table),
                            q_pos, 0.3)
    got = paged_attention_prefill(q, k_pages, v_pages, jnp.asarray(table),
                                  q_pos, scale=0.3, interpret=True)
    # padding rows: XLA path masks everything -> softmax over -inf gives
    # uniform garbage; the kernel returns zeros. Compare live rows only,
    # and assert the kernel's padding rows are exactly zero.
    np.testing.assert_allclose(np.asarray(got[:2]), np.asarray(want[:2]),
                               rtol=2e-5, atol=2e-5)
    assert np.all(np.asarray(got[2]) == 0.0)


def test_prefill_kernel_bf16():
    import numpy as np

    from dynamo_tpu.models.llama import _paged_attention
    from dynamo_tpu.ops.paged_attention import paged_attention_prefill

    rng = np.random.RandomState(1)
    B, KV, group, ps, hd, N, P, T = 2, 2, 2, 4, 16, 16, 4, 8
    H = KV * group
    q = jnp.asarray(rng.randn(B, T, H, hd), jnp.bfloat16)
    k_pages = jnp.asarray(rng.randn(N, KV, ps, hd), jnp.bfloat16)
    v_pages = jnp.asarray(rng.randn(N, KV, ps, hd), jnp.bfloat16)
    table = np.zeros((B, P), np.int32)
    table[0, :3] = [1, 5, 9]
    table[1, :2] = [2, 8]
    positions = np.stack([np.arange(4, 4 + T), np.arange(T)])
    want = _paged_attention(q, k_pages, v_pages, jnp.asarray(table),
                            jnp.asarray(positions), 0.25)
    got = paged_attention_prefill(q, k_pages, v_pages, jnp.asarray(table),
                                  jnp.asarray(positions), scale=0.25,
                                  interpret=True)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=2e-2, atol=2e-2)


def test_decode_kernel_softcap_and_window_match_gather():
    """Gemma-2 semantics in the decode kernel: tanh score softcap and a
    per-row lower bound (sliding window) match the XLA path — including
    the degenerate all-masked-page case the valid-mask guards."""
    from dynamo_tpu.models.llama import _paged_attention

    KV, group, hd, ps = 2, 2, 32, 8
    H = KV * group
    B, P, num_pages = 4, 4, 32
    key = jax.random.PRNGKey(7)
    kq, kp = jax.random.split(key)
    q = jax.random.normal(kq, (B, H, hd), jnp.float32)
    k_pages, v_pages = _random_pages(kp, num_pages, ps, KV, hd)

    rng = np.random.RandomState(7)
    table = np.zeros((B, P), np.int32)
    lengths = np.array([ps + 3, 2 * ps, P * ps, 5], np.int32)
    for b in range(B):
        npages = -(-int(lengths[b]) // ps)
        table[b, :npages] = rng.choice(
            np.arange(1, num_pages), npages, replace=False)

    scale = hd ** -0.5
    window, softcap = 6, 15.0
    eff = np.full(B, window, np.int32)
    lower = np.clip(lengths - eff, 0, np.maximum(lengths - 1, 0))
    got = paged_attention_decode(
        q, k_pages, v_pages, jnp.asarray(table), jnp.asarray(lengths),
        scale=scale, interpret=True, softcap=softcap,
        lower=jnp.asarray(lower))

    positions = jnp.asarray(lengths - 1)[:, None]
    want = _paged_attention(q[:, None], k_pages, v_pages,
                            jnp.asarray(table), positions, scale,
                            softcap=softcap, window=window,
                            is_sliding=True)[:, 0]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_prefill_kernel_softcap_and_window_match_gather():
    """Gemma-2 semantics in the flash prefill kernel: softcap + per-row
    effective window (with page skipping below the window) match the XLA
    gather path over a chunk longer than the window."""
    from dynamo_tpu.models.llama import _paged_attention
    from dynamo_tpu.ops.paged_attention import paged_attention_prefill

    KV, group, hd, ps, T = 2, 2, 32, 8, 24
    H = KV * group
    B, P, num_pages = 2, 4, 32
    key = jax.random.PRNGKey(8)
    kq, kp = jax.random.split(key)
    q = jax.random.normal(kq, (B, T, H, hd), jnp.float32)
    k_pages, v_pages = _random_pages(kp, num_pages, ps, KV, hd)

    rng = np.random.RandomState(8)
    table = np.zeros((B, P), np.int32)
    for b in range(B):
        table[b] = rng.choice(np.arange(1, num_pages), P, replace=False)
    positions = np.broadcast_to(np.arange(T, dtype=np.int32), (B, T))

    scale = hd ** -0.5
    window, softcap = 7, 12.0
    got = paged_attention_prefill(
        q, k_pages, v_pages, jnp.asarray(table), jnp.asarray(positions),
        scale=scale, interpret=True, softcap=softcap,
        eff_win=jnp.full((B,), window, jnp.int32))
    want = _paged_attention(q, k_pages, v_pages, jnp.asarray(table),
                            jnp.asarray(positions), scale,
                            softcap=softcap, window=window,
                            is_sliding=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_prefill_kernel_window_second_chunk_page_skip():
    """Chunked prefill whose second chunk starts past the window: pages
    wholly below the window's reach are skipped by the lower-bound guard
    yet the output still matches the XLA path."""
    from dynamo_tpu.models.llama import _paged_attention
    from dynamo_tpu.ops.paged_attention import paged_attention_prefill

    KV, group, hd, ps, T = 1, 2, 32, 4, 8
    H = KV * group
    B, P, num_pages = 1, 8, 32
    key = jax.random.PRNGKey(9)
    kq, kp = jax.random.split(key)
    q = jax.random.normal(kq, (B, T, H, hd), jnp.float32)
    k_pages, v_pages = _random_pages(kp, num_pages, ps, KV, hd)
    table = np.arange(1, P + 1, dtype=np.int32)[None]
    # chunk covers positions 20..27; window 6 → nothing below pos 15 is
    # visible, so pages 0..2 (positions 0..11) are skippable
    positions = (20 + np.arange(T, dtype=np.int32))[None]

    scale = hd ** -0.5
    window = 6
    got = paged_attention_prefill(
        q, k_pages, v_pages, jnp.asarray(table), jnp.asarray(positions),
        scale=scale, interpret=True,
        eff_win=jnp.full((B,), window, jnp.int32))
    want = _paged_attention(q, k_pages, v_pages, jnp.asarray(table),
                            jnp.asarray(positions), scale,
                            window=window, is_sliding=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
