"""Model tests: paged attention correctness vs full attention, chunked
prefill continuation, decode parity, sampling semantics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.sampling import SamplingBatch, sample_tokens
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.llama import (DROP_SLOT, init_kv_cache, init_params,
                                     make_step_fns, reference_forward,
                                     KVCacheSpec)

PAGE = 8  # small page size for tests


def build(cfg=None, num_pages=64):
    cfg = cfg or ModelConfig.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    spec = KVCacheSpec(num_pages=num_pages, page_size=PAGE)
    kv_k, kv_v = init_kv_cache(cfg, spec)
    prefill, decode = make_step_fns(cfg)
    return cfg, params, kv_k, kv_v, prefill, decode


def page_plan(seq_positions, page_table_rows, page_size=PAGE):
    """flat slot index for each (row, position): page*page_size + offset."""
    out = np.full(seq_positions.shape, DROP_SLOT, np.int32)
    for b in range(seq_positions.shape[0]):
        for t in range(seq_positions.shape[1]):
            pos = seq_positions[b, t]
            if pos < 0:
                continue
            page = page_table_rows[b][pos // page_size]
            out[b, t] = page * page_size + pos % page_size
    return out


def test_paged_prefill_matches_full_attention():
    cfg, params, kv_k, kv_v, prefill, _ = build()
    T = 20
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, T), 0, 500)
    ref_logits = reference_forward(params, cfg, tokens)  # [B, T, V]

    pages = [[1, 2, 3], [4, 5, 6]]  # non-contiguous, per-row page tables
    positions = np.broadcast_to(np.arange(T), (2, T)).copy()
    table = np.array([r + [0] * (8 - len(r)) for r in pages], np.int32)
    slots = page_plan(positions, pages)
    logits, kv_k, kv_v = prefill(
        params, tokens, jnp.asarray(positions), kv_k, kv_v,
        jnp.asarray(table), jnp.asarray(slots),
        jnp.full((2,), T - 1, jnp.int32))
    np.testing.assert_allclose(np.asarray(logits),
                               np.asarray(ref_logits[:, -1]), rtol=2e-4,
                               atol=2e-4)


def test_decode_matches_full_attention():
    """Prefill T tokens, then decode the next one; logits must match the
    full-attention forward over T+1 tokens."""
    cfg, params, kv_k, kv_v, prefill, decode = build()
    T = 11
    key = jax.random.PRNGKey(2)
    tokens = jax.random.randint(key, (1, T + 1), 0, 500)
    ref = reference_forward(params, cfg, tokens)  # [1, T+1, V]

    pages = [[7, 3]]
    positions = np.arange(T)[None, :]
    table = np.array([pages[0] + [0] * 6], np.int32)
    slots = page_plan(positions, pages)
    _, kv_k, kv_v = prefill(
        params, tokens[:, :T], jnp.asarray(positions), kv_k, kv_v,
        jnp.asarray(table), jnp.asarray(slots),
        jnp.full((1,), T - 1, jnp.int32))

    dec_pos = np.array([T], np.int32)
    dec_slots = page_plan(dec_pos[None, :].copy(), pages)
    logits, kv_k, kv_v = decode(
        params, tokens[:, T], jnp.asarray(dec_pos), kv_k, kv_v,
        jnp.asarray(table), jnp.asarray(dec_slots[:, 0]))
    np.testing.assert_allclose(np.asarray(logits)[0],
                               np.asarray(ref[0, T]), rtol=2e-4, atol=2e-4)


def test_chunked_prefill_continuation():
    """Prefill in two chunks (the long-context/disagg path); final logits
    must match single-shot prefill."""
    cfg, params, kv_k, kv_v, prefill, _ = build()
    T = 16
    tokens = jax.random.randint(jax.random.PRNGKey(3), (1, T), 0, 500)
    pages = [[9, 4]]
    table = np.array([pages[0] + [0] * 6], np.int32)

    # single shot
    positions = np.arange(T)[None, :]
    slots = page_plan(positions, pages)
    kv_k1, kv_v1 = init_kv_cache(cfg, KVCacheSpec(64, PAGE))
    ref_logits, _, _ = prefill(params, tokens, jnp.asarray(positions),
                               kv_k1, kv_v1, jnp.asarray(table),
                               jnp.asarray(slots),
                               jnp.full((1,), T - 1, jnp.int32))

    # two chunks of 8
    half = T // 2
    pos_a = np.arange(half)[None, :]
    slots_a = page_plan(pos_a, pages)
    _, kv_k, kv_v = prefill(params, tokens[:, :half], jnp.asarray(pos_a),
                            kv_k, kv_v, jnp.asarray(table),
                            jnp.asarray(slots_a),
                            jnp.full((1,), half - 1, jnp.int32))
    pos_b = np.arange(half, T)[None, :]
    slots_b = page_plan(pos_b, pages)
    logits, _, _ = prefill(params, tokens[:, half:], jnp.asarray(pos_b),
                           kv_k, kv_v, jnp.asarray(table),
                           jnp.asarray(slots_b),
                           jnp.full((1,), half - 1, jnp.int32))
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits),
                               rtol=2e-4, atol=2e-4)


def test_padding_rows_do_not_corrupt_cache():
    """Padded batch rows (positions=-1, slots=-1) must not write pages."""
    cfg, params, kv_k, kv_v, prefill, _ = build()
    tokens = np.zeros((2, 4), np.int64)
    tokens[0] = [5, 6, 7, 8]
    positions = np.array([[0, 1, 2, 3], [-1, -1, -1, -1]], np.int32)
    table = np.zeros((2, 8), np.int32)
    table[0, 0] = 2
    slots = np.array([[16, 17, 18, 19]] + [[DROP_SLOT] * 4], np.int32)
    before = np.asarray(kv_k)
    _, kv_k, kv_v = prefill(params, jnp.asarray(tokens),
                            jnp.asarray(positions), kv_k, kv_v,
                            jnp.asarray(table), jnp.asarray(slots),
                            jnp.array([3, 0], jnp.int32))
    after = np.asarray(kv_k)
    # only page 2 rows (slots 16..19) changed
    changed = np.any(before != after, axis=(0, 3, 4))  # [pages, page_size]
    assert changed[2, :4].all()
    changed[2, :4] = False
    assert not changed.any()


def test_moe_forward_runs():
    cfg = ModelConfig.tiny(num_experts=4, num_experts_per_tok=2,
                           model_type="mixtral")
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 6), 0, 500)
    logits = reference_forward(params, cfg, tokens)
    assert logits.shape == (1, 6, cfg.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()


def test_sampling_greedy_and_seeded():
    logits = jnp.asarray(np.random.RandomState(0).randn(4, 512) * 3)

    class S:
        temperature = None
        top_k = None
        top_p = None
        seed = None

    greedy_batch = SamplingBatch.build([S()] * 4, 4)
    toks = sample_tokens(logits, jnp.asarray(greedy_batch.temperature),
                         jnp.asarray(greedy_batch.top_k),
                         jnp.asarray(greedy_batch.top_p),
                         jnp.asarray(greedy_batch.seeds), jnp.int32(0))
    np.testing.assert_array_equal(np.asarray(toks),
                                  np.asarray(jnp.argmax(logits, -1)))

    class S2:
        temperature = 0.8
        top_k = 40
        top_p = 0.9
        seed = 1234

    b = SamplingBatch.build([S2()] * 4, 4)
    t1 = sample_tokens(logits, jnp.asarray(b.temperature),
                       jnp.asarray(b.top_k), jnp.asarray(b.top_p),
                       jnp.asarray(b.seeds), jnp.int32(7))
    t2 = sample_tokens(logits, jnp.asarray(b.temperature),
                       jnp.asarray(b.top_k), jnp.asarray(b.top_p),
                       jnp.asarray(b.seeds), jnp.int32(7))
    np.testing.assert_array_equal(np.asarray(t1), np.asarray(t2))  # same seed+step
    t3 = sample_tokens(logits, jnp.asarray(b.temperature),
                       jnp.asarray(b.top_k), jnp.asarray(b.top_p),
                       jnp.asarray(b.seeds), jnp.int32(8))
    assert not np.array_equal(np.asarray(t1), np.asarray(t3))  # step advances

    # top-k=1 equals greedy even with temperature
    class S3:
        temperature = 1.0
        top_k = 1
        top_p = 1.0
        seed = 5

    b3 = SamplingBatch.build([S3()] * 4, 4)
    t4 = sample_tokens(logits, jnp.asarray(b3.temperature),
                       jnp.asarray(b3.top_k), jnp.asarray(b3.top_p),
                       jnp.asarray(b3.seeds), jnp.int32(0))
    np.testing.assert_array_equal(np.asarray(t4),
                                  np.asarray(jnp.argmax(logits, -1)))


def test_softcap_and_attn_scale_knobs():
    """The Gemma-2-forward-looking knobs are exercised directly (no HF
    checkpoint can set them yet — gemma2 loading is refused — but the
    logit softcap and attention-scale override must not bit-rot in the
    hot logit path)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.models.llama import init_params, reference_forward

    cap = 5.0
    cfg = ModelConfig.tiny(final_logit_softcap=cap,
                           query_pre_attn_scalar=64.0)
    assert abs(cfg.attn_scale - 0.125) < 1e-9  # 1/sqrt(64), not head_dim
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.asarray(np.arange(1, 9)[None, :])
    logits = np.asarray(reference_forward(params, cfg, tokens))
    assert np.all(np.abs(logits) < cap)  # tanh-capped
    # and the cap actually changes values vs the uncapped config
    cfg0 = ModelConfig.tiny(query_pre_attn_scalar=64.0)
    base = np.asarray(reference_forward(params, cfg0, tokens))
    expect = cap * np.tanh(base / cap)
    np.testing.assert_allclose(logits, expect, rtol=1e-5, atol=1e-5)
