"""Metrics aggregator + mock worker (reference components/metrics with
mock_worker.rs: the metrics plane is testable with no engine)."""

import asyncio

from dynamo_tpu.metrics import MetricsAggregator, MockWorker
from dynamo_tpu.runtime.runtime import DistributedRuntime


def test_aggregator_scrapes_mock_workers(run_async):
    async def scenario():
        drt = await DistributedRuntime.detached()
        w1 = MockWorker(drt, component="mockw", seed=1,
                        hit_rate_interval=0.05)
        w2_drt = drt  # same process, same bus
        w2 = MockWorker(w2_drt, component="mockw", seed=2,
                        hit_rate_interval=0.05)
        await w1.start()
        await w2.start()

        agg = MetricsAggregator(drt, "dynamo", "mockw", interval=0.1)
        await agg.start()
        await asyncio.sleep(0.5)
        await agg.scrape_once()
        text = agg.render_prometheus()
        await agg.stop()
        await w1.stop()
        await w2.stop()
        await drt.shutdown()
        return agg, text

    agg, text = run_async(scenario())
    # both workers share a lease id? no — same drt => same worker id; the
    # stats plane keys by instance id, so one entry is expected here
    assert agg.worker_metrics, "no worker metrics scraped"
    assert "dyn_worker_cache_usage_perc" in text
    assert 'namespace="dynamo"' in text
    assert agg.hit_rate_events > 0
    assert "dyn_kv_hit_rate_overlap_blocks" in text
